"""FusedMultiTransformer (reference fused_transformer.py:1071): prefill vs
decode-with-cache parity, gradients, rmsnorm/layernorm variants."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import FusedMultiTransformer

B, S, E, H, FF, L = 2, 8, 32, 4, 64, 2


def _model(norm="layernorm", act="gelu"):
    paddle.seed(0)
    return FusedMultiTransformer(
        E, H, FF, num_layers=L, norm_type=norm, activation=act
    )


def test_forward_shapes_and_grads():
    m = _model()
    x = paddle.to_tensor(np.random.default_rng(0).normal(size=(B, S, E)).astype(np.float32))
    out = m(x)
    assert list(out.shape) == [B, S, E]
    out.sum().backward()
    grads = [p.grad for p in m.parameters() if not p.stop_gradient]
    assert all(g is not None for g in grads)
    assert sum(float(g.abs().sum()) for g in grads) > 0


@pytest.mark.parametrize("norm", ["layernorm", "rmsnorm"])
def test_prefill_then_decode_matches_full_forward(norm):
    m = _model(norm=norm)
    rng = np.random.default_rng(1)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))

    import jax.numpy as jnp

    # full forward over S tokens
    full = m(x).numpy()

    # prefill S-1 tokens (time_step signals use_cache -> fresh K/V returned)
    prefix = paddle.to_tensor(np.asarray(x.numpy())[:, : S - 1])
    res = m.forward(prefix, time_step=paddle.to_tensor(S - 1))
    assert isinstance(res, tuple)
    hid, kv_list = res
    # pad the prefill K/V to S and decode the last token
    pads = [
        (
            paddle.to_tensor(jnp.pad(k._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
            paddle.to_tensor(jnp.pad(v._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
        )
        for k, v in kv_list
    ]
    last = paddle.to_tensor(np.asarray(x.numpy())[:, S - 1 : S])
    step_out, _ = m(last, caches=pads, time_step=paddle.to_tensor(S - 1))
    np.testing.assert_allclose(
        np.asarray(step_out.numpy())[:, 0], full[:, -1], rtol=2e-4, atol=2e-5
    )


def test_post_layernorm_rejected():
    with pytest.raises(NotImplementedError):
        FusedMultiTransformer(E, H, FF, normalize_before=False)


def test_rotary_embs_prefill_decode_parity():
    """rotary_embs (cos, sin) are applied in both prefill and cached decode;
    the cached step must match the full rotated forward."""
    import jax.numpy as jnp

    m = _model(norm="rmsnorm")
    rng = np.random.default_rng(2)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    hd = E // H
    inv = 1.0 / (10000 ** (np.arange(0, hd, 2) / hd))
    t = np.arange(32)[:, None] * inv[None, :]
    cos = paddle.to_tensor(np.concatenate([np.cos(t), np.cos(t)], -1).astype(np.float32))
    sin = paddle.to_tensor(np.concatenate([np.sin(t), np.sin(t)], -1).astype(np.float32))

    full = m(x, rotary_embs=(cos, sin)).numpy()

    prefix = paddle.to_tensor(np.asarray(x.numpy())[:, : S - 1])
    hid, kv_list = m.forward(prefix, rotary_embs=(cos, sin), time_step=paddle.to_tensor(S - 1))
    pads = [
        (
            paddle.to_tensor(jnp.pad(k._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
            paddle.to_tensor(jnp.pad(v._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
        )
        for k, v in kv_list
    ]
    last = paddle.to_tensor(np.asarray(x.numpy())[:, S - 1 : S])
    step_out, _ = m(last, caches=pads, time_step=paddle.to_tensor(S - 1), rotary_embs=(cos, sin))
    np.testing.assert_allclose(
        np.asarray(step_out.numpy())[:, 0], full[:, -1], rtol=2e-4, atol=2e-5
    )
