"""FusedMultiTransformer (reference fused_transformer.py:1071): prefill vs
decode-with-cache parity, gradients, rmsnorm/layernorm variants."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import FusedMultiTransformer

B, S, E, H, FF, L = 2, 8, 32, 4, 64, 2


def _model(norm="layernorm", act="gelu"):
    paddle.seed(0)
    return FusedMultiTransformer(
        E, H, FF, num_layers=L, norm_type=norm, activation=act
    )


def test_forward_shapes_and_grads():
    m = _model()
    x = paddle.to_tensor(np.random.default_rng(0).normal(size=(B, S, E)).astype(np.float32))
    out = m(x)
    assert list(out.shape) == [B, S, E]
    out.sum().backward()
    grads = [p.grad for p in m.parameters() if not p.stop_gradient]
    assert all(g is not None for g in grads)
    assert sum(float(g.abs().sum()) for g in grads) > 0


@pytest.mark.parametrize("norm", ["layernorm", "rmsnorm"])
def test_prefill_then_decode_matches_full_forward(norm):
    m = _model(norm=norm)
    rng = np.random.default_rng(1)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))

    import jax.numpy as jnp

    # full forward over S tokens
    full = m(x).numpy()

    # prefill S-1 tokens (time_step signals use_cache -> fresh K/V returned)
    prefix = paddle.to_tensor(np.asarray(x.numpy())[:, : S - 1])
    res = m.forward(prefix, time_step=paddle.to_tensor(S - 1))
    assert isinstance(res, tuple)
    hid, kv_list = res
    # pad the prefill K/V to S and decode the last token
    pads = [
        (
            paddle.to_tensor(jnp.pad(k._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
            paddle.to_tensor(jnp.pad(v._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
        )
        for k, v in kv_list
    ]
    last = paddle.to_tensor(np.asarray(x.numpy())[:, S - 1 : S])
    step_out, _ = m(last, caches=pads, time_step=paddle.to_tensor(S - 1))
    np.testing.assert_allclose(
        np.asarray(step_out.numpy())[:, 0], full[:, -1], rtol=2e-4, atol=2e-5
    )


def test_post_layernorm_rejected():
    with pytest.raises(NotImplementedError):
        FusedMultiTransformer(E, H, FF, normalize_before=False)


def test_rotary_embs_prefill_decode_parity():
    """rotary_embs (cos, sin) are applied in both prefill and cached decode;
    the cached step must match the full rotated forward."""
    import jax.numpy as jnp

    m = _model(norm="rmsnorm")
    rng = np.random.default_rng(2)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    hd = E // H
    inv = 1.0 / (10000 ** (np.arange(0, hd, 2) / hd))
    t = np.arange(32)[:, None] * inv[None, :]
    cos = paddle.to_tensor(np.concatenate([np.cos(t), np.cos(t)], -1).astype(np.float32))
    sin = paddle.to_tensor(np.concatenate([np.sin(t), np.sin(t)], -1).astype(np.float32))

    full = m(x, rotary_embs=(cos, sin)).numpy()

    prefix = paddle.to_tensor(np.asarray(x.numpy())[:, : S - 1])
    hid, kv_list = m.forward(prefix, rotary_embs=(cos, sin), time_step=paddle.to_tensor(S - 1))
    pads = [
        (
            paddle.to_tensor(jnp.pad(k._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
            paddle.to_tensor(jnp.pad(v._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
        )
        for k, v in kv_list
    ]
    last = paddle.to_tensor(np.asarray(x.numpy())[:, S - 1 : S])
    step_out, _ = m(last, caches=pads, time_step=paddle.to_tensor(S - 1), rotary_embs=(cos, sin))
    np.testing.assert_allclose(
        np.asarray(step_out.numpy())[:, 0], full[:, -1], rtol=2e-4, atol=2e-5
    )

def test_attn_mask_causal_matches_default():
    """A pure-causal additive attn_mask must reproduce the no-mask (causal
    flash) path — proves the mask is actually applied with the right
    convention, not ignored (ADVICE r4 medium)."""
    m = _model()
    rng = np.random.default_rng(3)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    neg = np.finfo(np.float32).min
    causal = np.where(np.tril(np.ones((S, S), bool)), 0.0, neg).astype(np.float32)
    mask = paddle.to_tensor(np.broadcast_to(causal, (B, 1, S, S)).copy())
    np.testing.assert_allclose(
        m(x, attn_mask=mask).numpy(), m(x).numpy(), rtol=2e-4, atol=2e-5
    )


def test_attn_mask_padding_changes_output():
    """Masking out the first key column must change outputs for positions that
    could previously attend to it — silently ignoring the mask would not.
    Uses a per-sample [B, S, S] mask (3-D broadcast path) and leaves row 0
    fully masked: the clamp must keep the output finite, not NaN."""
    m = _model()
    rng = np.random.default_rng(4)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    neg = np.finfo(np.float32).min
    causal = np.where(np.tril(np.ones((S, S), bool)), 0.0, neg).astype(np.float32)
    padded = np.broadcast_to(causal, (B, S, S)).copy()
    padded[:, :, 0] = neg  # no one may attend to key 0 (row 0 fully masked)
    out_causal = m(x, attn_mask=paddle.to_tensor(np.broadcast_to(causal, (B, S, S)).copy())).numpy()
    out_padded = m(x, attn_mask=paddle.to_tensor(padded)).numpy()
    assert np.isfinite(out_padded).all(), "fully-masked query row produced NaN"
    assert np.abs(out_causal[:, 1:] - out_padded[:, 1:]).max() > 1e-4


def test_attn_mask_bool_accepted():
    m = _model()
    rng = np.random.default_rng(5)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    mask = paddle.to_tensor(np.tril(np.ones((S, S), bool)))
    np.testing.assert_allclose(
        m(x, attn_mask=mask).numpy(), m(x).numpy(), rtol=2e-4, atol=2e-5
    )


def test_attn_mask_rejected_in_decode():
    import jax.numpy as jnp

    m = _model()
    rng = np.random.default_rng(6)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    _, kv_list = m.forward(x, time_step=paddle.to_tensor(S))
    pads = [
        (
            paddle.to_tensor(jnp.pad(k._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
            paddle.to_tensor(jnp.pad(v._data, ((0, 0), (0, 1), (0, 0), (0, 0)))),
        )
        for k, v in kv_list
    ]
    last = paddle.to_tensor(rng.normal(size=(B, 1, E)).astype(np.float32))
    with pytest.raises(NotImplementedError):
        m(last, attn_mask=paddle.to_tensor(np.zeros((1, 1), np.float32)),
          caches=pads, time_step=paddle.to_tensor(S))


def test_swiglu_is_gated_split():
    """swiglu allocates ffn1 at 2*ff and computes silu(a)*b (ADVICE r4: the
    old path did x*sigmoid(x) over width ff — wrong math AND wrong layout)."""
    m = _model(act="swiglu")
    assert list(m.ffn1_weights[0].shape) == [E, 2 * FF]
    rng = np.random.default_rng(7)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    out = m(x)
    assert list(out.shape) == [B, S, E]
    # manual recomputation through the public weights, on a 1-layer model
    import jax
    import jax.numpy as jnp

    paddle.seed(0)
    m1 = FusedMultiTransformer(E, H, FF, num_layers=1, activation="swiglu")
    out1 = m1(x).numpy()
    # recompute for m1's weights
    ln = m1._norm(x, m1.ln_scales[0], m1.ln_biases[0])
    attn, _ = m1._attn(0, ln, None, None, None, False)
    h1 = x.numpy() + (attn @ m1.linear_weights[0] + m1.linear_biases[0]).numpy()
    ln2 = m1._norm(paddle.to_tensor(h1), m1.ffn_ln_scales[0], m1.ffn_ln_biases[0]).numpy()
    z = ln2 @ m1.ffn1_weights[0].numpy() + m1.ffn1_biases[0].numpy()
    a, b = z[..., :FF], z[..., FF:]
    gated = np.asarray(jax.nn.silu(jnp.asarray(a))) * b
    expect = h1 + gated @ m1.ffn2_weights[0].numpy() + m1.ffn2_biases[0].numpy()
    np.testing.assert_allclose(out1, expect, rtol=2e-4, atol=2e-5)
