"""Fleet observability: per-replica metric attribution, cluster aggregation,
the SLO burn-rate monitor, and coordinated incident snapshots.

The acceptance surface of ``observability/metrics.py`` (MetricScope),
``observability/slo.py``, ``observability/aggregate.py`` and the
router/cluster wiring:

- replica-scoped metric cells roll up into the process-global families with
  a ``replica=`` label; the metrics-off path stays a no-op;
- per-replica flight rings tee into the global black box;
- the cluster churn property test: after EVERY op (submit/pump/kill/revive/
  drain), each fleet-aggregated counter equals the sum over its
  replica-scoped series AND reconciles with engine truth, and the cluster
  ``/healthz`` replica states match the cluster state exactly;
- the burn-rate monitor's multi-window hysteresis (a fast-window blip must
  not page; a sustained violation must);
- kill-mid-storm: one correlated incident directory containing every
  replica's ring, rendered by the dump CLI as a single cross-replica
  timeline with the failed-over request's spans from BOTH replicas in one
  tree (exit 2 on missing/corrupt incident dirs, never vacuous);
- fleet ``/metrics`` + ``/healthz`` endpoints, and format agreement with
  ``start_metrics_server``.

Everything runs on CPU with the tiny Llama config, same as test_router.py.
"""

import http.client
import json
import os
import shutil
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability.slo import OK, PAGE, WARN, BurnRateMonitor, SLOConfig
from paddle_tpu.serving import (
    ReplicaCluster,
    ReplicaRouter,
    RouterConfig,
    ServingConfig,
    ServingFrontend,
    start_serving_server,
    stop_serving_server,
)
from paddle_tpu.testing import faults


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _cluster(seed=0, n=3, max_queue=8, **engine_kw):
    m, cfg = _model(seed)
    engine_kw.setdefault("max_slots", 2)
    engine_kw.setdefault("block_size", 4)
    engine_kw.setdefault("prompt_bucket", 16)

    def factory(name):
        eng = ContinuousBatchingEngine(m, **engine_kw)
        return ServingFrontend(eng, ServingConfig(max_queue=max_queue))

    cluster = ReplicaCluster(factory, [f"r{i}" for i in range(n)])
    router = ReplicaRouter(cluster, RouterConfig())
    return router, cluster, cfg


def _prompt(rng, cfg, n=5):
    return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)


@pytest.fixture
def metrics_on():
    prior = paddle.get_flags(["FLAGS_enable_metrics"])
    paddle.set_flags({"FLAGS_enable_metrics": True})
    obs.GLOBAL_METRICS.reset()
    try:
        yield
    finally:
        paddle.set_flags(prior)


# -- metric scoping -----------------------------------------------------------

class TestMetricScope:
    def test_scoped_cells_roll_up_with_replica_label(self, metrics_on):
        reg = obs.MetricsRegistry()
        c = reg.counter("ms_demo_total", "h", labelnames=("reason",))
        scoped = reg.scope(replica="rA").bind(c)
        c.labels(reason="x").inc(2)
        scoped.labels(reason="x").inc(3)
        text = reg.render_prometheus()
        assert 'ms_demo_total{reason="x"} 2' in text
        assert 'ms_demo_total{replica="rA",reason="x"} 3' in text
        # reads are scope-local; family reads are unscoped
        assert scoped.value(reason="x") == 3
        assert c.value(reason="x") == 2
        assert c.scope_total(("rA",)) == 3

    def test_gauge_and_histogram_scoping(self, metrics_on):
        reg = obs.MetricsRegistry()
        sc = reg.scope(replica="rB")
        g = sc.bind(reg.gauge("ms_demo_gauge"))
        h = sc.bind(reg.histogram("ms_demo_seconds"))
        g.set(7)
        h.observe(0.5)
        assert g.value() == 7
        assert h.count() == 1 and h.sum() == 0.5
        assert h.quantile(0.5) > 0
        text = reg.render_prometheus()
        assert 'ms_demo_gauge{replica="rB"} 7' in text
        assert 'ms_demo_seconds_count{replica="rB"} 1' in text

    def test_conflicting_scope_labelnames_raise(self, metrics_on):
        reg = obs.MetricsRegistry()
        c = reg.counter("ms_conflict_total")
        reg.scope(replica="r0").bind(c)
        with pytest.raises(ValueError):
            reg.scope(shard="s0").bind(c)

    def test_metrics_off_path_records_nothing(self):
        prior = paddle.get_flags(["FLAGS_enable_metrics"])
        paddle.set_flags({"FLAGS_enable_metrics": False})
        try:
            reg = obs.MetricsRegistry()
            scoped = reg.scope(replica="r0").bind(reg.counter("ms_off_total"))
            scoped.inc(5)
            assert scoped.total() == 0.0
            assert "ms_off_total" not in reg.render_prometheus()
        finally:
            paddle.set_flags(prior)

    def test_family_strict_read(self, metrics_on):
        reg = obs.MetricsRegistry()
        c = reg.counter("ms_family_total")
        assert reg.family("ms_family_total") is c
        with pytest.raises(KeyError):
            reg.family("ms_family_typo_total")

    def test_reset_clears_scoped_cells(self, metrics_on):
        reg = obs.MetricsRegistry()
        scoped = reg.scope(replica="r0").bind(reg.counter("ms_reset_total"))
        scoped.inc(4)
        reg.reset()
        assert scoped.total() == 0.0
        scoped.inc(1)  # handles survive a reset
        assert scoped.total() == 1.0


# -- flight child rings -------------------------------------------------------

class TestFlightChildRings:
    def test_child_ring_tees_tagged_into_parent(self):
        parent = obs.FlightRecorder(capacity=16)
        child = parent.child(replica="r9")
        child.record("admit", req_id=1)
        own = child.snapshot()
        assert len(own) == 1 and own[0]["replica"] == "r9"
        up = parent.snapshot()
        assert len(up) == 1 and up[0]["replica"] == "r9" and up[0]["kind"] == "admit"

    def test_explicit_field_wins_over_scope_tag(self):
        parent = obs.FlightRecorder(capacity=16)
        child = parent.child(replica="r9")
        child.record("replica_state", replica="other")
        assert child.snapshot()[0]["replica"] == "other"

    def test_child_dump_carries_scope(self, tmp_path):
        parent = obs.FlightRecorder(capacity=16)
        child = parent.child(replica="r3")
        child.record("evict", req_id=2)
        path = child.dump("test", path=str(tmp_path / "ring.json"))
        payload = json.loads(open(path).read())
        assert payload["scope"] == {"replica": "r3"}
        assert payload["events"][0]["replica"] == "r3"


# -- burn-rate monitor --------------------------------------------------------

def _sample(term, ok, in_slo, disp, re, p99=0.01):
    return {
        "terminals": float(term), "ok": float(ok), "ok_in_slo": float(in_slo),
        "dispatches": float(disp), "redispatches": float(re),
        "ttft_p99_s": float(p99),
    }


class TestBurnRateMonitor:
    CFG = dict(
        ttft_p99_target_s=1.0, goodput_target=0.9, shed_budget=0.1,
        failover_budget=0.1, fast_window_s=1.0, slow_window_s=4.0,
        min_terminals=4, warn_burn=1.0, page_burn=4.0,
    )

    def test_fast_blip_alone_does_not_escalate(self):
        m = BurnRateMonitor(SLOConfig(**self.CFG))
        t = 0.0
        # 4s of healthy traffic fills the slow window
        for i in range(1, 9):
            t += 0.5
            m.observe(t, _sample(50 * i, 50 * i, 50 * i, 50 * i, 0))
        # a one-tick blip: 10 sheds inside the fast window, but the slow
        # window's fraction stays far under budget -> min(fast, slow) low
        t += 0.5
        state = m.observe(t, _sample(410, 400, 400, 410, 0))
        assert state == OK, m.last
        assert m.last["fast"]["shed"] > 1.0  # the fast window DID see it
        assert m.last["effective"]["shed"] < 1.0

    def test_sustained_violation_escalates_and_recovers_with_hysteresis(self):
        m = BurnRateMonitor(SLOConfig(**self.CFG))
        t = 0.0
        for i in range(1, 9):
            t += 0.5
            m.observe(t, _sample(50 * i, 50 * i, 50 * i, 50 * i, 0))
        base = 400
        state = OK
        for i in range(1, 17):  # 8s of 50% sheds: both windows saturate
            t += 0.5
            state = m.observe(
                t, _sample(base + 10 * i, base + 5 * i, base + 5 * i,
                           base + 10 * i, 0)
            )
        assert state == PAGE, m.last
        assert [e["to"] for e in m.timeline] == ["warn", "page"]
        # recovery: healthy traffic drains both windows; hysteresis releases
        for i in range(1, 30):
            t += 0.5
            last = m._samples[-1][1]
            state = m.observe(t, _sample(
                last["terminals"] + 20, last["ok"] + 20,
                last["ok_in_slo"] + 20, last["dispatches"] + 20,
                last["redispatches"],
            ))
        assert state == OK
        times = m.time_in_states(t)
        assert times["page"] > 0 and times["warn"] > 0

    def test_ttft_signal_pages_without_terminal_volume(self):
        m = BurnRateMonitor(SLOConfig(**self.CFG))
        t = 0.0
        state = OK
        for i in range(1, 14):  # p99 5x target, sustained past the slow window
            t += 0.5
            state = m.observe(t, _sample(i, i, i, i, 0, p99=5.0))
        assert state == PAGE
        assert m.timeline[0]["signal"] == "ttft"

    def test_low_traffic_total_outage_still_pages_via_slow_window(self):
        """An under-populated fast window must DEFER to the slow window,
        not zero the min(): ~1 terminal/s with 100% sheds never fills the
        fast window past min_terminals, but the sustained slow-window burn
        is the outage the monitor exists to page on."""
        m = BurnRateMonitor(SLOConfig(**{**self.CFG, "fast_window_s": 1.0,
                                         "slow_window_s": 8.0,
                                         "min_terminals": 4}))
        t = 0.0
        state = OK
        for i in range(1, 25):  # 1 terminal/s, all shed, for 24s
            t += 1.0
            state = m.observe(t, _sample(i, 0, 0, i, 0))
        # fast window holds ~1 terminal < min_terminals every tick...
        assert m.last["fast"]["shed"] == 0.0
        # ...but the slow window saw the sustained 100% shed rate
        assert state == PAGE, m.last

    def test_observe_is_rate_bounded(self):
        m = BurnRateMonitor(SLOConfig(**self.CFG))  # fast 1.0 -> ~15.6ms min
        for i in range(10_000):  # a tight inline pump: ~microsecond spacing
            m.observe(1.0 + i * 1e-6, _sample(i, i, i, i, 0))
        assert len(m._samples) <= 3, len(m._samples)

    def test_min_terminals_guards_empty_windows(self):
        m = BurnRateMonitor(SLOConfig(**self.CFG))
        # 2 terminals, both shed: far below min_terminals -> burn 0
        state = m.observe(1.0, _sample(2, 0, 0, 2, 0))
        assert state == OK
        assert m.last["effective"]["shed"] == 0.0
        # min_terminals < 1 would divide by a zero-terminal window delta
        with pytest.raises(ValueError):
            SLOConfig(**{**self.CFG, "min_terminals": 0})

    def test_ttft_needs_sustained_elevation_not_one_sample(self):
        """The ttft windows are disjoint (fast vs slow-minus-fast): a single
        elevated p99 sample inside the fast window must not latch a state
        by itself — sustained elevation must."""
        m = BurnRateMonitor(SLOConfig(**self.CFG))  # target 1.0, fast 1.0
        t = 0.0
        for i in range(1, 9):  # healthy history fills the sustained half
            t += 0.5
            m.observe(t, _sample(10 * i, 10 * i, 10 * i, 10 * i, 0, p99=0.1))
        t += 0.5
        state = m.observe(t, _sample(90, 90, 90, 90, 0, p99=20.0))
        assert state == OK  # one blip: sustained half still reads 0.1
        assert m.last["effective"]["ttft"] < 1.0

    def test_transitions_emit_counters_and_flight_events(self, metrics_on):
        obs.GLOBAL_FLIGHT_RECORDER.clear()
        m = BurnRateMonitor(SLOConfig(**self.CFG))
        t = 0.0
        for i in range(1, 20):
            t += 0.5
            m.observe(t, _sample(10 * i, 5 * i, 5 * i, 10 * i, 0))
        fam = obs.GLOBAL_METRICS.family("slo_state_transitions_total")
        # a violation this hard may jump OK -> PAGE in one tick; what must
        # hold is that PAGE was entered and counted
        assert fam.value(to="page") >= 1
        kinds = [e for e in obs.GLOBAL_FLIGHT_RECORDER.snapshot()
                 if e["kind"] == "slo_state"]
        assert any(e["to"] == "page" for e in kinds)


# -- cluster churn property test ----------------------------------------------

class TestClusterChurnProperty:
    def _truth(self, cluster, carry, stat_key):
        out = {}
        for name, r in cluster.replicas.items():
            out[name] = carry.get(name, {}).get(stat_key, 0) + (
                r.frontend.engine.stats[stat_key]
            )
        return out

    def _check(self, observer, router, cluster, carry):
        fc = observer.fleet_counters()
        # (1) every fleet-aggregated counter equals the sum over its
        # replica-scoped series
        for name, entry in fc.items():
            if entry.get("unregistered"):
                continue
            assert entry["fleet"] == pytest.approx(
                sum(entry["per_replica"].values())
            ), name
        # (2) replica-attributed series reconcile exactly with engine truth
        admitted = self._truth(cluster, carry, "admitted")
        per = fc["engine_requests_admitted_total"]["per_replica"]
        for name, want in admitted.items():
            assert per.get(name, 0.0) == pytest.approx(want), (name, per, admitted)
        prefill = self._truth(cluster, carry, "prompt_tokens_computed")
        per = fc["engine_prefill_tokens_computed_total"]["per_replica"]
        for name, want in prefill.items():
            assert per.get(name, 0.0) == pytest.approx(want), (name, per)
        # (3) the cluster /healthz replica states match cluster truth exactly
        hz = observer.healthz()
        for name, r in cluster.replicas.items():
            assert hz["replicas"][name]["state"] == r.state
            assert hz["cluster"]["replicas"][name]["state"] == r.state

    def test_churn_reconciles_after_every_op(self, metrics_on):
        router, cluster, cfg = _cluster(n=3)
        observer = obs.ClusterObserver(
            router, slo_config=SLOConfig(fast_window_s=0.5, slow_window_s=2.0),
            incident_dir=tempfile.mkdtemp(prefix="churn_inc_"),
            incident_cooldown_s=1e9,  # churn is not an incident storm test
        )
        rng = np.random.default_rng(42)
        # replica-scoped engine.stats reset on revive: carry the old
        # generation's truth forward
        carry = {name: {"admitted": 0, "prompt_tokens_computed": 0}
                 for name in cluster.names()}
        handles = []
        ops = 0
        for step in range(70):
            op = rng.choice(["submit", "pump", "pump", "kill", "revive", "drain"])
            try:
                if op == "submit":
                    h = router.submit(_prompt(rng, cfg), max_new_tokens=3)
                    handles.append(h)
                elif op == "pump":
                    router.pump()
                elif op == "kill":
                    up = [r for r in cluster if r.state == "up"]
                    # keep at least one replica alive so the storm drains
                    if len(up) >= 2:
                        up[0].kill("churn kill")
                        router.pump()  # probe observes the death
                elif op == "revive":
                    dead = [r for r in cluster if r.state == "dead"]
                    if dead:
                        name = dead[0].name
                        st = dead[0].frontend.engine.stats
                        carry[name]["admitted"] += st["admitted"]
                        carry[name]["prompt_tokens_computed"] += (
                            st["prompt_tokens_computed"]
                        )
                        router.revive(name)
                elif op == "drain":
                    up = [r for r in cluster if r.state == "up"]
                    if len(up) >= 2:
                        router.drain(up[-1].name)
                        router.pump()
                        router.resume(up[-1].name)
            except Exception as exc:
                if type(exc).__name__ not in ("Overloaded",):
                    raise
            ops += 1
            self._check(observer, router, cluster, carry)
        # drain everything still live so the test leaves no dangling work
        for _ in range(400):
            router.pump()
            if all(h.finished for h in handles):
                break
        self._check(observer, router, cluster, carry)
        assert ops == 70


# -- kill-mid-storm incident + dump CLI ---------------------------------------

class _CliResult:
    def __init__(self, returncode, stdout, stderr):
        self.returncode, self.stdout, self.stderr = returncode, stdout, stderr


def _run_dump_cli(path):
    """Drive the dump CLI in-process (same main() the `python -m` entry
    runs — a fresh interpreter per invocation would re-import jax and cost
    seconds of tier-1 wall per call; the end-to-end subprocess form is
    covered by the verify drive script)."""
    import contextlib
    import io

    from paddle_tpu.observability.dump import main

    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = main([path])
    return _CliResult(rc, out.getvalue(), err.getvalue())


class TestIncidentKillMidStorm:
    def test_incident_contains_every_replica_ring_and_cli_renders(self, metrics_on):
        prior = paddle.get_flags(["FLAGS_trace_sample_rate"])
        paddle.set_flags({"FLAGS_trace_sample_rate": 1.0})
        base = tempfile.mkdtemp(prefix="storm_inc_")
        try:
            router, cluster, cfg = _cluster(n=3)
            observer = obs.ClusterObserver(
                router,
                slo_config=SLOConfig(
                    ttft_p99_target_s=30.0,  # isolate: only real failures alert
                    fast_window_s=0.3, slow_window_s=1.0, min_terminals=2,
                    failover_budget=0.05, shed_budget=0.05,
                ),
                incident_dir=base, incident_cooldown_s=0.0,
            )
            rng = np.random.default_rng(7)
            handles = []
            for i in range(10):
                handles.append(
                    router.submit(_prompt(rng, cfg), max_new_tokens=6)
                )
                router.pump()
                if i == 5:
                    faults.install_plan(
                        faults.FaultPlan.single("replica.kill", 0)
                    )
            for _ in range(600):
                router.pump()
                if all(h.finished for h in handles):
                    break
            faults.install_plan(None)
            assert all(h.finished for h in handles)
            dead = [r.name for r in cluster if r.state == "dead"]
            assert len(dead) == 1
            # a WARN/PAGE transition was recorded by the burn-rate monitor
            assert any(
                e["to"] in ("warn", "page") for e in observer.monitor.timeline
            ), observer.monitor.last
            # ONE correlated incident directory, with every replica's ring
            assert observer.incidents, "no incident written"
            inc = observer.incidents[0]
            files = set(os.listdir(inc))
            for name in cluster.names():
                assert f"flight_{name}.json" in files, files
            assert {"incident.json", "flight_global.json", "routing.json"} <= files
            manifest = json.load(open(os.path.join(inc, "incident.json")))
            assert manifest["schema"] == obs.INCIDENT_SCHEMA
            assert set(manifest["replicas"]) == set(cluster.names())
            # the dump CLI renders the dir as one cross-replica timeline
            r = _run_dump_cli(inc)
            assert r.returncode == 0, r.stderr
            assert "cross-replica timeline" in r.stdout
            for name in cluster.names():
                assert name in r.stdout
            # a failed-over request: spans from BOTH replicas in ONE tree.
            # The death-time incident fired before the failover finished, so
            # write a post-storm snapshot (same writer, full span buffer).
            failed_over = [
                h for h in handles
                if any(kind == "failover" for kind, _ in h.routes)
                and h.outcome == "ok"
            ]
            assert failed_over, "storm produced no successful failover"
            post = observer.write_incident("postmortem")
            assert post is not None
            r2 = _run_dump_cli(post)
            assert r2.returncode == 0, r2.stderr
            assert "[replicas: " in r2.stdout  # a multi-replica trace exists
            assert "router.failover" in r2.stdout
            # the bridge span names both endpoints of the failover
            assert any(
                "@" in line and "->" in line
                for line in r2.stdout.splitlines()
                if "router.failover" in line
            ), r2.stdout
        finally:
            faults.install_plan(None)
            paddle.set_flags(prior)
            shutil.rmtree(base, ignore_errors=True)

    def test_dump_cli_exit_2_on_missing_and_corrupt_incident(self, tmp_path):
        # missing dir (as a file path) -> 2
        r = _run_dump_cli(str(tmp_path / "nope"))
        assert r.returncode == 2
        # empty dir: no manifest -> 2
        empty = tmp_path / "incident_empty"
        empty.mkdir()
        r = _run_dump_cli(str(empty))
        assert r.returncode == 2
        assert "incident.json" in r.stderr
        # corrupt manifest -> 2
        bad = tmp_path / "incident_bad"
        bad.mkdir()
        (bad / "incident.json").write_text("{not json")
        r = _run_dump_cli(str(bad))
        assert r.returncode == 2
        # schema-correct manifest referencing a missing ring -> 2
        torn = tmp_path / "incident_torn"
        torn.mkdir()
        (torn / "incident.json").write_text(json.dumps({
            "schema": obs.INCIDENT_SCHEMA, "reason": "t", "replicas": ["r0"],
            "files": {"flight": ["flight_r0.json"], "spans": None,
                      "routing": "routing.json"},
        }))
        r = _run_dump_cli(str(torn))
        assert r.returncode == 2
        assert "missing ring" in r.stderr
        # a manifest-referenced routing file that is gone is equally torn
        torn2 = tmp_path / "incident_torn2"
        torn2.mkdir()
        (torn2 / "incident.json").write_text(json.dumps({
            "schema": obs.INCIDENT_SCHEMA, "reason": "t", "replicas": [],
            "files": {"flight": [], "spans": None, "routing": "routing.json"},
        }))
        r = _run_dump_cli(str(torn2))
        assert r.returncode == 2
        assert "routing" in r.stderr

    def test_failed_incident_write_cleans_staging_and_retries(self, tmp_path, metrics_on):
        router, cluster, cfg = _cluster(n=2)
        observer = obs.ClusterObserver(
            router, slo_config=SLOConfig(), incident_dir=str(tmp_path),
            incident_cooldown_s=60.0,
        )
        # break the span export so the write fails mid-way
        real = obs.GLOBAL_TRACER.export_jsonl
        obs.GLOBAL_TRACER.export_jsonl = lambda path: (_ for _ in ()).throw(
            OSError("disk full")
        )
        try:
            assert observer.write_incident("broken") is None
            # no torn .tmp staging dir left beside real incidents
            assert all(".tmp." not in n for n in os.listdir(tmp_path)), (
                os.listdir(tmp_path)
            )
        finally:
            obs.GLOBAL_TRACER.export_jsonl = real
        # and a later attempt (the cooldown never stamped) succeeds cleanly
        path = observer.write_incident("broken")
        assert path is not None and os.path.isdir(path)


# -- fleet endpoints ----------------------------------------------------------

class TestFleetEndpoints:
    def _get(self, port, path):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read().decode()
        finally:
            conn.close()

    def test_cluster_healthz_and_fleet_metrics(self, metrics_on):
        router, cluster, cfg = _cluster(n=2)
        observer = obs.ClusterObserver(
            router, slo_config=SLOConfig(), incident_cooldown_s=1e9,
        )
        rng = np.random.default_rng(3)
        hs = [router.submit(_prompt(rng, cfg), max_new_tokens=3)
              for _ in range(3)]
        for _ in range(200):
            router.pump()
            if all(h.finished for h in hs):
                break
        srv = start_serving_server(router, port=0)
        try:
            port = srv.server_address[1]
            status, body = self._get(port, "/healthz")
            assert status == 200
            hz = json.loads(body)
            # router state + per-replica lifecycle/capability + slo block
            assert set(hz) == {"cluster", "replicas", "slo"}
            for name, r in cluster.replicas.items():
                entry = hz["replicas"][name]
                assert entry["state"] == r.state
                assert entry["tp_degree"] == 1
                assert "kv_tier" in entry and "spec_decode" in entry
            assert hz["slo"]["state"] in ("ok", "warn", "page")
            status, text = self._get(port, "/metrics")
            assert status == 200
            assert 'engine_requests_admitted_total{replica="r0"}' in text
        finally:
            stop_serving_server(router)

    def test_metrics_server_serves_same_replica_labeled_exposition(self, metrics_on):
        router, cluster, cfg = _cluster(n=2)
        rng = np.random.default_rng(4)
        hs = [router.submit(_prompt(rng, cfg), max_new_tokens=3)
              for _ in range(3)]
        for _ in range(200):
            router.pump()
            if all(h.finished for h in hs):
                break
        serving_srv = start_serving_server(router, port=0)
        metrics_srv = obs.start_metrics_server(port=0)
        try:
            _, fleet = self._get(serving_srv.server_address[1], "/metrics")
            _, process = self._get(metrics_srv.server_address[1], "/metrics")
            # one renderer, two ports: identical exposition when quiesced
            # (no traffic between the two scrapes)
            fleet_lines = {
                l for l in fleet.splitlines()
                if l.startswith("engine_requests_admitted_total")
            }
            process_lines = {
                l for l in process.splitlines()
                if l.startswith("engine_requests_admitted_total")
            }
            assert fleet_lines and fleet_lines == process_lines
            assert any('replica="' in l for l in fleet_lines)
        finally:
            stop_serving_server(router)
            obs.stop_metrics_server()

    def test_metrics_off_cluster_records_nothing(self):
        prior = paddle.get_flags(["FLAGS_enable_metrics"])
        paddle.set_flags({"FLAGS_enable_metrics": False})
        obs.GLOBAL_METRICS.reset()
        try:
            router, cluster, cfg = _cluster(n=2)
            observer = obs.ClusterObserver(
                router, slo_config=SLOConfig(), incident_cooldown_s=1e9,
            )
            rng = np.random.default_rng(5)
            hs = [router.submit(_prompt(rng, cfg), max_new_tokens=3)
                  for _ in range(2)]
            for _ in range(200):
                router.pump()
                if all(h.finished for h in hs):
                    break
            assert all(h.outcome == "ok" for h in hs)
            fc = observer.fleet_counters()
            entry = fc["engine_requests_admitted_total"]
            assert entry["fleet"] == 0.0  # off = no cells, not stale values
            # ...but cluster truth (healthz) is metrics-independent
            hz = observer.healthz()
            assert all(
                e["state"] == cluster.replicas[n].state
                for n, e in hz["replicas"].items()
            )
        finally:
            paddle.set_flags(prior)
