"""Distributed-config auto-tuner (reference ``auto_tuner/tuner.py:21``):
candidate generation, divisibility + memory pruning, trial loop, best pick."""

import numpy as np
import pytest

from paddle_tpu.distributed.auto_tuner import (
    AutoTuner,
    default_candidates,
    divisor,
    prune_by_memory,
)

MODEL = {
    "num_layers": 8,
    "hidden_size": 1024,
    "num_attention_heads": 16,
    "vocab_size": 32000,
    "intermediate_size": 4096,
    "seq_length": 1024,
}


def _cfg(**kw):
    base = {"num_gpus": 8, "global_batch_size": 16, "model_cfg": MODEL, "hbm_bytes": 64e9}
    base.update(kw)
    return base


def test_divisor():
    assert divisor(12) == [1, 2, 3, 4, 6, 12]
    assert divisor(8, reverse=True) == [8, 4, 2, 1]


def test_default_candidates_respect_model_divisibility():
    cand = default_candidates(_cfg())
    assert all(MODEL["num_attention_heads"] % mp == 0 for mp in cand["mp_degree"])
    assert all(MODEL["num_layers"] % pp == 0 for pp in cand["pp_degree"])
    # vocab 32000 % 3 != 0 so 3 isn't there anyway; mp=16 > 8 gpus excluded later
    assert 1 in cand["mp_degree"] and 2 in cand["mp_degree"]


def test_queue_only_valid_factorizations():
    t = AutoTuner(_cfg())
    seen = set()
    while True:
        c = t.search_once()
        if c is None:
            break
        assert c["dp_degree"] * c["mp_degree"] * c["pp_degree"] == 8
        assert c["dp_degree"] % c["sharding_degree"] == 0
        per_dp = 16 // c["dp_degree"]
        assert per_dp % c["micro_batch_size"] == 0
        assert c["acc_steps"] == per_dp // c["micro_batch_size"]
        if c["sharding_degree"] == 1:
            assert c["sharding_stage"] == 1
        key = tuple(sorted((k, v) for k, v in c.items()))
        assert key not in seen
        seen.add(key)
    assert len(seen) > 10


def test_memory_prune_rejects_oversized():
    # tiny HBM: everything but the most parallel configs must be pruned
    small = _cfg(hbm_bytes=1e6)
    assert prune_by_memory(
        {"mp_degree": 1, "pp_degree": 1, "sharding_degree": 1, "sharding_stage": 1,
         "micro_batch_size": 4, "use_recompute": False},
        small,
    )
    big = _cfg(hbm_bytes=1e15)
    assert not prune_by_memory(
        {"mp_degree": 1, "pp_degree": 1, "sharding_degree": 1, "sharding_stage": 1,
         "micro_batch_size": 4, "use_recompute": False},
        big,
    )
    # recompute reduces the activation term
    mid = dict(mp_degree=1, pp_degree=1, sharding_degree=1, sharding_stage=1,
               micro_batch_size=16, use_recompute=False)
    tight = _cfg(hbm_bytes=5e9)  # static state ~3.6e9; act 2.1e9 w/o recompute
    assert prune_by_memory(mid, tight)
    mid_rc = dict(mid, use_recompute=True)
    assert not prune_by_memory(mid_rc, tight)


def test_task_limit():
    t = AutoTuner(_cfg(task_limit=3))
    got = [t.search_once() for _ in range(5)]
    assert sum(c is not None for c in got) == 3


def test_run_picks_best_and_tolerates_failures():
    t = AutoTuner(_cfg(task_limit=50))

    def trial(cfg):
        # synthetic: mp=2 pp=1 shines; some configs "OOM"
        if cfg["micro_batch_size"] == 1:
            raise MemoryError("oom")
        return 1000.0 * cfg["mp_degree"] - 100.0 * cfg["pp_degree"] + cfg["micro_batch_size"]

    best = t.run(trial)
    assert best is not None and best["status"] == "ok"
    ok = [c for c in t.history_cfgs if c["metric"] is not None]
    assert best["metric"] == max(c["metric"] for c in ok)
    failed = [c for c in t.history_cfgs if c["metric"] is None]
    assert failed and all(c["status"].startswith("failed") for c in failed)


def test_min_mode_picks_smallest():
    t = AutoTuner(_cfg(mode="min", task_limit=10))
    best = t.run(lambda cfg: float(cfg["mp_degree"]))
    assert best["mp_degree"] == min(
        c["mp_degree"] for c in t.history_cfgs if c["metric"] is not None
    )


class TestSubprocessIsolation:
    """isolation='subprocess' (VERDICT r5 #5): a hard-crashing or hung trial
    kills one child, not the sweep."""

    def test_survives_hard_process_death(self):
        t = AutoTuner(_cfg(task_limit=6))
        doomed = [dict(t._queue[0]), dict(t._queue[2])]  # two hard crashes

        def trial(cfg):
            if any(all(cfg[k] == v for k, v in d.items()) for d in doomed):
                import os
                os._exit(137)  # simulates an XLA OOM / Mosaic abort killing the process
            return 100.0 * cfg["mp_degree"] + cfg["micro_batch_size"]

        best = t.run(trial, isolation="subprocess")
        assert best is not None and best["status"] == "ok"
        died = [c for c in t.history_cfgs if "died" in str(c["status"])]
        assert len(died) == 2 and all(c["metric"] is None for c in died)
        ok = [c for c in t.history_cfgs if c["metric"] is not None]
        assert len(ok) == 4 and best["metric"] == max(c["metric"] for c in ok)

    def test_python_exception_reported(self):
        t = AutoTuner(_cfg(task_limit=8))

        def trial(cfg):
            if cfg["use_recompute"]:
                raise MemoryError("RESOURCE_EXHAUSTED: out of memory")
            return float(cfg["micro_batch_size"])

        best = t.run(trial, isolation="subprocess")
        failed = [c for c in t.history_cfgs if c["metric"] is None]
        assert failed and all("MemoryError" in c["status"] for c in failed)
        assert best is not None and not best["use_recompute"]

    def test_hung_trial_times_out(self):
        t = AutoTuner(_cfg(task_limit=4))
        first = dict(t._queue[0])  # poison exactly the first trial

        def trial(cfg):
            if all(cfg[k] == v for k, v in first.items()):
                import time
                time.sleep(300)
            return float(cfg["mp_degree"])

        best = t.run(trial, isolation="subprocess", trial_timeout=3.0)
        hung = [c for c in t.history_cfgs if "timed out" in str(c["status"])]
        assert len(hung) == 1 and hung[0]["metric"] is None
        assert best is not None and best["status"] == "ok"

    def test_rejects_unknown_isolation(self):
        t = AutoTuner(_cfg())
        with pytest.raises(ValueError, match="isolation"):
            t.run(lambda cfg: 1.0, isolation="thread")
