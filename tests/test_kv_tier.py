"""Hierarchical KV: the host-RAM spill tier under the prefix cache.

The acceptance surface of ``inference/kv_tier.py`` + the engine's
spill/prefetch integration:

- LRU-evicted zero-ref chain blocks spill D2H into the bounded host pool
  instead of dying; a prefix match against a spilled chain prefetches its
  blocks H2D into freshly reserved pool slots, overlapped with the mixed
  ragged step (the per-slot gate), and every full cached block before the
  first divergent block maps regardless of which tier holds it — including
  the divergent block's partial via prefetch-on-write;
- byte-exact greedy parity of a multi-turn workload with the tier on vs off,
  through ONE compiled step signature either way;
- ``kv_tier.spill`` / ``kv_tier.prefetch`` fault sites: spill failure drops
  the chain (pre-tier behavior), prefetch failure degrades to recompute —
  both zero-cost when no plan is installed;
- recovery drops the in-flight prefetch set and rebuilds from host truth
  (the tier survives the lost device pools);
- budget discipline: host bytes never exceed ``FLAGS_kv_host_tier_bytes``,
  drops cascade to unreachable descendants, pinned entries never drop.

Everything runs on CPU with the tiny Llama config, same as test_engine.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.inference import ContinuousBatchingEngine, HostKVTier
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.testing import faults

from conftest import assert_engine_pool_exact, assert_kv_tier_exact


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _engine(m, tier_bytes, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 32)
    kw.setdefault("max_model_len", 48)
    return ContinuousBatchingEngine(m, kv_host_tier_bytes=tier_bytes, **kw)


def _kv(seed, shape=(2, 2, 2, 4, 16)):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(shape).astype(np.float32)


class TestHostKVTierUnit:
    BLOCK_NBYTES = 2 * 2 * 2 * 4 * 16 * 4  # tiny-llama f32 block

    def _tier(self, blocks=4):
        return HostKVTier(blocks * self.BLOCK_NBYTES, self.BLOCK_NBYTES)

    def test_put_lookup_roundtrip_and_budget_gauge(self):
        tier = self._tier(2)
        kv = _kv(0)
        assert tier.put(b"root", b"d1", b"tok1", kv)
        assert tier.bytes_used == self.BLOCK_NBYTES
        node = tier.lookup_pin(b"root", b"tok1")
        assert node is not None and np.array_equal(node.kv, kv)
        assert tier.lookup_pin(b"root", b"tok2") is None
        tier.unpin([node])

    def test_lru_evicts_oldest_when_over_budget(self):
        tier = self._tier(2)
        assert tier.put(b"r", b"d1", b"t1", _kv(1))
        assert tier.put(b"r", b"d2", b"t2", _kv(2))
        assert tier.put(b"r", b"d3", b"t3", _kv(3))  # evicts t1
        assert (b"r", b"t1") not in tier
        assert (b"r", b"t2") in tier and (b"r", b"t3") in tier
        s = tier.stats_snapshot()
        assert s["host_bytes"] <= s["budget_bytes"]
        assert s["dropped_blocks"] == 1 and s["spilled_blocks"] == 3

    def test_lookup_touches_lru_order(self):
        tier = self._tier(2)
        tier.put(b"r", b"d1", b"t1", _kv(1))
        tier.put(b"r", b"d2", b"t2", _kv(2))
        node = tier.lookup_pin(b"r", b"t1")  # t1 becomes MRU
        tier.unpin([node])
        tier.put(b"r", b"d3", b"t3", _kv(3))  # evicts t2, not t1
        assert (b"r", b"t1") in tier and (b"r", b"t2") not in tier

    def test_pinned_entries_never_drop(self):
        tier = self._tier(1)
        tier.put(b"r", b"d1", b"t1", _kv(1))
        node = tier.lookup_pin(b"r", b"t1")
        # over budget but everything pinned: the new spill is refused
        assert not tier.put(b"r", b"d2", b"t2", _kv(2))
        assert tier.stats_snapshot()["refused_spills"] == 1
        tier.unpin([node])
        assert tier.put(b"r", b"d2", b"t2", _kv(2))  # now t1 can go

    def test_dropping_a_parent_cascades_unreachable_descendants(self):
        tier = self._tier(8)
        tier.put(b"root", b"dA", b"tA", _kv(1))
        tier.put(b"dA", b"dB", b"tB", _kv(2))
        tier.put(b"dB", b"dC", b"tC", _kv(3))
        # make the PARENT the LRU head (children spilled later are newer
        # anyway), then force one drop: the whole subtree must leave — a
        # child whose parent digest left the tier is unreachable by any walk
        assert tier.drop_lru(1) == 3
        assert len(tier) == 0
        assert tier.stats_snapshot()["dropped_blocks"] == 3

    def test_put_same_key_is_idempotent_touch(self):
        tier = self._tier(2)
        kv = _kv(1)
        assert tier.put(b"r", b"d1", b"t1", kv)
        assert tier.put(b"r", b"d1", b"t1", _kv(9))  # same digest == same bytes
        node = tier.lookup_pin(b"r", b"t1")
        assert np.array_equal(node.kv, kv)  # first copy retained
        assert len(tier) == 1
        tier.unpin([node])

    def test_best_partial_prefers_longest_common_run(self):
        tier = self._tier(4)
        t_a = np.asarray([1, 2, 3, 4], np.int32)
        t_b = np.asarray([1, 2, 9, 9], np.int32)
        tier.put(b"r", b"dA", t_a.tobytes(), _kv(1))
        tier.put(b"r", b"dB", t_b.tobytes(), _kv(2))
        got = tier.best_partial(b"r", np.asarray([1, 2, 3, 9], np.int32))
        assert got is not None
        node, k = got
        assert node.token_bytes == t_a.tobytes() and k == 3
        tier.unpin([node])
        assert tier.best_partial(b"r", np.asarray([7, 7], np.int32)) is None


class TestSpillPrefetchCycle:
    def test_evicted_chain_spills_and_a_later_match_prefetches(self):
        m, cfg = _model(seed=60)
        rng = np.random.default_rng(60)
        eng = _engine(m, 1 << 20, num_blocks=64)
        x = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
        r1 = eng.add_request(x, max_new_tokens=2)
        out_cold = eng.run()
        eng._cache.evict_blocks(16)  # whole dead chain -> host tier
        assert eng._cache.node_count == 0
        assert eng.kv_tier_stats()["spilled_blocks"] >= 3
        r2 = eng.add_request(x, max_new_tokens=2)
        out_warm = eng.run()
        # 16-token prompt: 3 full blocks prefetched (12) + 3-token partial
        # of the spilled block 3 via prefetch-on-write
        assert out_warm[r2].cached_tokens == 15
        assert eng.kv_tier_stats()["prefetched_blocks"] == 4
        np.testing.assert_array_equal(
            out_cold[r1].tokens(), out_warm[r2].tokens()
        )
        assert_engine_pool_exact(eng)
        assert_kv_tier_exact(eng)

    def test_multi_turn_workload_byte_identical_tier_on_vs_off(self):
        """The acceptance parity run: interleaved multi-turn conversations
        over a pool too small to retain the working set — tier-on must
        spill, prefetch, AND emit byte-identical greedy tokens, through ONE
        compiled signature, same as tier-off."""
        m, cfg = _model(seed=61)

        def drive(tier_bytes):
            rng = np.random.default_rng(61)
            eng = _engine(m, tier_bytes, num_blocks=12, max_model_len=64,
                          prompt_bucket=48)
            streams = {}
            outs = []
            for op in range(10):
                conv = int(rng.integers(0, 3))
                tail = rng.integers(0, cfg.vocab_size,
                                    (int(rng.integers(3, 8)),)).astype(np.int32)
                prev = streams.get(conv)
                prompt = tail if prev is None else np.concatenate([prev, tail])
                if prompt.size > 40:
                    prompt = tail
                rid = eng.add_request(prompt, max_new_tokens=3)
                done = eng.run()
                streams[conv] = done[rid].tokens()
                outs.append(streams[conv])
                assert_engine_pool_exact(eng)
                assert_kv_tier_exact(eng)
            # final round: force every resident chain out (spilling when the
            # tier is on), then each conversation takes one more turn — with
            # the tier on, its history comes back by prefetch; off, by
            # recompute. Same tokens either way.
            eng._cache.evict_blocks(64)
            for conv in sorted(streams):
                tail = rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)
                prompt = np.concatenate([streams[conv], tail])[-40:]
                rid = eng.add_request(prompt, max_new_tokens=3)
                done = eng.run()
                outs.append(done[rid].tokens())
                assert_engine_pool_exact(eng)
                assert_kv_tier_exact(eng)
            return eng, outs

        eng_on, outs_on = drive(1 << 20)
        eng_off, outs_off = drive(0)
        assert len(outs_on) == len(outs_off)
        for a, b in zip(outs_on, outs_off):
            np.testing.assert_array_equal(a, b)
        t = eng_on.kv_tier_stats()
        assert t["spilled_blocks"] > 0 and t["prefetched_blocks"] > 0
        assert eng_off.kv_tier_stats() == {"enabled": False}
        # ONE compiled step signature with the tier on or off
        assert eng_on.stats["step_traces"] == 1
        assert eng_off.stats["step_traces"] == 1

    def test_prefetch_gate_blocks_slot_until_copies_land(self):
        """A slot admitted against a spilled chain is gated: its rows stay
        out of the mixed step while the H2D copies are in flight, and the
        gate clears (poll or forced wait) before its suffix computes."""
        m, cfg = _model(seed=62)
        rng = np.random.default_rng(62)
        eng = _engine(m, 1 << 20, num_blocks=64)
        x = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
        eng.add_request(x, max_new_tokens=2)
        eng.run()
        eng._cache.evict_blocks(16)
        req = eng.make_request(x, max_new_tokens=2)
        eng.enqueue(req)
        eng._admit_waiting([])  # prefetch issued here
        slot = next(i for i, r in enumerate(eng._slot_req) if r is req)
        assert eng._prefetch_wait[slot] is not None  # gate armed at admit
        marker, n_blocks, tokens = eng._prefetch_wait[slot]
        assert n_blocks == 4 and tokens == 15
        out = eng.run()  # polls/waits the gate, then computes the suffix
        assert eng._prefetch_wait[slot] is None
        assert out[req.req_id].finished
        assert_engine_pool_exact(eng)

    def test_tier_under_tensor_parallel_mesh_byte_identical(self):
        """The tier under a CPU tp=2 mesh: spill gathers the head shards
        D2H (the tier always holds the full-head view), the prefetch fold's
        ``out_shardings`` pin keeps the committed pool partition (a drifted
        sharding would compile a SECOND step executable), and tokens stay
        byte-identical to the tp=1 engine."""
        m, cfg = _model(seed=72)

        def drive(tp):
            rng = np.random.default_rng(72)
            eng = _engine(m, 1 << 20, num_blocks=64, tp=tp)
            x = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
            r1 = eng.add_request(x, max_new_tokens=3)
            o1 = eng.run()
            eng._cache.evict_blocks(16)
            r2 = eng.add_request(x, max_new_tokens=3)
            o2 = eng.run()
            return eng, o1[r1].tokens(), o2[r2].tokens(), o2[r2].cached_tokens

        eng2, cold2, warm2, cached2 = drive(2)
        eng1, cold1, warm1, cached1 = drive(1)
        assert cached2 == cached1 == 15
        assert eng2.kv_tier_stats()["prefetched_blocks"] == 4
        np.testing.assert_array_equal(cold1, cold2)
        np.testing.assert_array_equal(warm1, warm2)
        np.testing.assert_array_equal(cold2, warm2)
        assert eng2.stats["step_traces"] == 1  # out_shardings held the line
        assert_engine_pool_exact(eng2)
        assert_kv_tier_exact(eng2)

    def test_tier_requires_prefix_cache(self):
        m, _cfg = _model(seed=63)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, prompt_bucket=16,
            enable_prefix_cache=False, kv_host_tier_bytes=1 << 20,
        )
        assert eng.kv_tier_stats() == {"enabled": False}

    def test_host_budget_pressure_drops_lru_and_stays_within_budget(self):
        m, cfg = _model(seed=64)
        rng = np.random.default_rng(64)
        # budget of exactly 2 blocks: heavy eviction churn must drop
        bpb = 2 * cfg.num_hidden_layers * cfg.num_key_value_heads * \
            (cfg.hidden_size // cfg.num_attention_heads) * 4 * 4  # f32, bs=4
        eng = _engine(m, 2 * bpb, num_blocks=10, max_model_len=32,
                      prompt_bucket=16)
        for _ in range(6):
            p = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
            eng.add_request(p, max_new_tokens=2)
            eng.run()
            assert_kv_tier_exact(eng)
        t = eng.kv_tier_stats()
        assert t["host_bytes"] <= t["budget_bytes"] == 2 * bpb
        assert t["dropped_blocks"] > 0


class TestFaultSites:
    def test_sites_are_pinned_in_known_sites(self):
        assert "kv_tier.spill" in faults.KNOWN_SITES
        assert "kv_tier.prefetch" in faults.KNOWN_SITES

    def test_spill_fault_drops_the_chain_old_behavior(self):
        m, cfg = _model(seed=65)
        rng = np.random.default_rng(65)
        eng = _engine(m, 1 << 20, num_blocks=64)
        x = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
        r1 = eng.add_request(x, max_new_tokens=2)
        out1 = eng.run()
        with faults.inject(faults.FaultPlan.single("kv_tier.spill", 0)):
            eng._cache.evict_blocks(1)
        assert len(eng._host_tier) == 0  # nothing half-stored
        eng._cache.evict_blocks(16)  # later spills work again
        assert len(eng._host_tier) > 0
        # the dropped block is recomputed, byte-identically
        r2 = eng.add_request(x, max_new_tokens=2)
        out2 = eng.run()
        np.testing.assert_array_equal(out1[r1].tokens(), out2[r2].tokens())
        assert_engine_pool_exact(eng)
        assert_kv_tier_exact(eng)

    def test_prefetch_fault_degrades_request_to_recompute(self):
        m, cfg = _model(seed=66)
        rng = np.random.default_rng(66)
        eng = _engine(m, 1 << 20, num_blocks=64)
        x = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
        r1 = eng.add_request(x, max_new_tokens=3)
        out1 = eng.run()
        eng._cache.evict_blocks(16)
        with faults.inject(faults.FaultPlan.single("kv_tier.prefetch", 0)):
            r2 = eng.add_request(x, max_new_tokens=3)
            out2 = eng.run()
        assert out2[r2].cached_tokens == 0  # host match abandoned, recompute
        assert eng.kv_tier_stats()["prefetched_blocks"] == 0
        np.testing.assert_array_equal(out1[r1].tokens(), out2[r2].tokens())
        # the spilled chain is still intact for the NEXT match
        r3 = eng.add_request(x, max_new_tokens=3)
        out3 = eng.run()
        assert out3[r3].cached_tokens > 0
        np.testing.assert_array_equal(out1[r1].tokens(), out3[r3].tokens())
        assert_engine_pool_exact(eng)
        assert_kv_tier_exact(eng)

    def test_sites_are_zero_cost_when_no_plan_installed(self):
        m, cfg = _model(seed=67)
        rng = np.random.default_rng(67)
        eng = _engine(m, 1 << 20, num_blocks=64)
        x = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
        eng.add_request(x, max_new_tokens=2)
        eng.run()
        eng._cache.evict_blocks(16)
        eng.add_request(x, max_new_tokens=2)
        eng.run()
        assert eng.kv_tier_stats()["spilled_blocks"] > 0
        assert eng.kv_tier_stats()["prefetched_blocks"] > 0
        # with no plan, the sites do not even count their calls
        assert faults.site_call_count("kv_tier.spill") == 0
        assert faults.site_call_count("kv_tier.prefetch") == 0


class TestRecovery:
    def test_recovery_drops_in_flight_set_and_rebuilds_from_host_truth(self):
        """A dispatch fault mid-workload: recovery rebuilds device pools,
        the host tier SURVIVES (its spilled counter does not reset), the
        in-flight prefetch gates are dropped, and the replayed stream is
        byte-identical to a fault-free run."""
        m, cfg = _model(seed=68)

        def drive(plan):
            rng = np.random.default_rng(68)
            eng = _engine(m, 1 << 20, num_blocks=64)
            x = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
            eng.add_request(x, max_new_tokens=2)
            eng.run()
            eng._cache.evict_blocks(16)
            spilled = eng.kv_tier_stats()["spilled_blocks"]
            assert spilled > 0
            rid = eng.add_request(x, max_new_tokens=6)
            done = {}
            if plan is not None:
                with faults.inject(plan):
                    while eng.has_work():
                        for q in eng.step():
                            done[q.req_id] = q
            else:
                while eng.has_work():
                    for q in eng.step():
                        done[q.req_id] = q
            return eng, done[rid], spilled

        eng_f, req_f, spilled = drive(
            faults.FaultPlan.single("engine.decode", 1)
        )
        assert eng_f.stats["recoveries"] == 1
        assert all(w is None for w in eng_f._prefetch_wait)
        assert eng_f.kv_tier_stats()["spilled_blocks"] >= spilled
        eng_c, req_c, _ = drive(None)
        np.testing.assert_array_equal(req_f.tokens(), req_c.tokens())
        assert_engine_pool_exact(eng_f)
        assert_kv_tier_exact(eng_f)


class TestObservability:
    def test_tier_metrics_and_labeled_hit_split(self):
        m, cfg = _model(seed=69)
        rng = np.random.default_rng(69)
        prior = paddle.get_flags(["FLAGS_enable_metrics"])
        try:
            paddle.set_flags({"FLAGS_enable_metrics": True})
            obs.GLOBAL_METRICS.reset()
            eng = _engine(m, 1 << 20, num_blocks=64)
            x = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
            eng.add_request(x, max_new_tokens=2)
            eng.run()  # cold: miss
            eng.add_request(x, max_new_tokens=2)
            eng.run()  # resident hit -> tier="hbm"
            eng._cache.evict_blocks(32)
            eng.add_request(x, max_new_tokens=2)
            eng.run()  # spilled hit -> tier="host"
            reg = obs.GLOBAL_METRICS
            hits = reg.get("prefix_cache_hits_total")
            assert hits.value(tier="hbm") == 1.0
            assert hits.value(tier="host") == 1.0
            assert reg.get("kv_tier_spilled_blocks_total").value() > 0
            assert reg.get("kv_tier_prefetched_blocks_total").value() == 4.0
            assert (
                reg.get("kv_tier_host_bytes").value()
                == eng.kv_tier_stats()["host_bytes"]
            )
            stats = eng._cache.stats_snapshot()
            assert stats["host_hits"] == 1 and stats["hits"] == 2
        finally:
            paddle.set_flags(prior)
            obs.GLOBAL_METRICS.reset()

    def test_flight_events_for_spill_and_prefetch(self):
        from paddle_tpu.observability import flight_recorder as flight

        m, cfg = _model(seed=70)
        rng = np.random.default_rng(70)
        eng = _engine(m, 1 << 20, num_blocks=64)
        x = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
        eng.add_request(x, max_new_tokens=2)
        eng.run()
        eng._cache.evict_blocks(16)
        eng.add_request(x, max_new_tokens=2)
        eng.run()
        kinds = [e["kind"] for e in flight.GLOBAL_FLIGHT_RECORDER.snapshot()]
        assert "kv_spill" in kinds and "kv_prefetch" in kinds

    def test_healthz_kv_tier_block(self):
        from paddle_tpu.serving import ServingConfig, ServingFrontend

        m, _cfg = _model(seed=71)
        eng = _engine(m, 1 << 20, num_blocks=64)
        fe = ServingFrontend(eng, ServingConfig(max_queue=4))
        snap = fe.snapshot()
        assert snap["kv_tier"]["enabled"] is True
        assert snap["kv_tier"]["budget_bytes"] == 1 << 20
        for k in ("host_bytes", "spilled_blocks", "prefetched_blocks",
                  "dropped_blocks"):
            assert k in snap["kv_tier"]


def test_bench_smoke_kv_tier_multi_turn_ttft():
    """The guarded bench secondary runs end to end on CPU and reports the
    sweep, counters and the 1-compile honesty field."""
    import bench

    rec = bench._bench_kv_tier_multi_turn(paddle, "cpu")
    assert "error" not in rec, rec
    assert rec["metric"] == "kv_tier_multi_turn_ttft"
    assert rec["compiled_signatures_per_engine"] == 1
    sweep = rec["sweep"]
    assert sweep[0]["kv_host_tier_bytes"] == 0
    assert len(sweep) >= 3
    on = sweep[-1]
    assert on["spilled_blocks"] > 0 and on["prefetched_blocks"] > 0
    assert on["host_hit_rate"] > 0
    for pt in sweep:
        assert "warm_ttft_ms" in pt and "p50" in pt["warm_ttft_ms"]
