"""Continuous-batching engine: the no-retrace invariant (exactly ONE
compiled signature over a mixed prefill/decode workload — chunked prefill),
token-for-token parity with per-sequence ``generate_paged``, and exact
refcounted block-pool accounting under adversarial admit/evict orders.

Everything here runs on CPU and fast — this file IS the tier-1 guard that
turns an engine retrace regression into a CI failure instead of a silent
TPU-only compile storm.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


from conftest import assert_engine_pool_exact as _assert_pool_exact


def _assert_drained(eng):
    """No live work: every block free or warm in the cache — never leaked."""
    _assert_pool_exact(eng)
    s = eng.pool_stats()
    assert s["free"] + s["cached_blocks"] == s["total"], s


def _reference(m, prompt, max_new, block_size, eos=None):
    """Per-sequence generate_paged oracle, truncated at eos like the engine."""
    out = np.asarray(
        m.generate_paged(
            paddle.to_tensor(prompt[None]), max_new_tokens=max_new,
            block_size=block_size, eos_token_id=eos,
        ).numpy()
    )[0]
    if eos is not None:
        gen = out[len(prompt):]
        hits = np.where(gen == eos)[0]
        if hits.size:
            out = out[: len(prompt) + hits[0] + 1]
    return out


class TestNoRetraceInvariant:
    def test_mixed_workload_exactly_one_compile_and_token_parity(self):
        """The acceptance test: staggered admits (7 requests through 3
        slots), early finishes (varied budgets), varied prompt lengths —
        exactly ONE unified step trace (chunked prefill rides the decode
        dispatch), outputs equal to running each sequence alone through
        generate_paged."""
        m, cfg = _model()
        rng = np.random.default_rng(0)
        eng = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, prompt_bucket=16
        )
        specs = [(5, 6), (7, 4), (3, 9), (6, 2), (2, 7), (8, 5), (4, 3)]
        prompts = [
            rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
            for n, _ in specs
        ]
        rids = [
            eng.add_request(p, max_new_tokens=t)
            for p, (_, t) in zip(prompts, specs)
        ]
        out = eng.run()

        assert eng.stats["step_traces"] == 1, eng.stats
        if hasattr(eng._step_fn, "_cache_size"):  # jit-level confirmation
            assert eng._step_fn._cache_size() == 1

        for rid, p, (_, t) in zip(rids, prompts, specs):
            ref = _reference(m, p, t, block_size=4)
            np.testing.assert_array_equal(out[rid].tokens(), ref)

    def test_late_submits_mid_flight_no_retrace(self):
        """Requests added AFTER decoding started enter freed slots without a
        new compile — admits/evictions are data, not shapes."""
        m, cfg = _model(seed=1)
        rng = np.random.default_rng(1)
        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=16)
        first = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
        r0 = eng.add_request(first, max_new_tokens=3)
        eng.step()
        late = rng.integers(0, cfg.vocab_size, (7,)).astype(np.int32)
        r1 = eng.add_request(late, max_new_tokens=5)
        out = eng.run()
        assert eng.stats["step_traces"] == 1
        np.testing.assert_array_equal(
            out[r0].tokens(), _reference(m, first, 3, block_size=4)
        )
        np.testing.assert_array_equal(
            out[r1].tokens(), _reference(m, late, 5, block_size=4)
        )

    def test_eos_finishes_early_frees_slot(self):
        m, cfg = _model(seed=2)
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)
        # pick an eos greedy decoding actually emits mid-stream
        probe = _reference(m, prompt, 6, block_size=4)
        eos = int(probe[len(prompt) + 2])
        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=8)
        rid = eng.add_request(prompt, max_new_tokens=6, eos_token_id=eos)
        out = eng.run()
        req = out[rid]
        assert req.finish_reason == "stop"
        assert req.generated[-1] == eos
        np.testing.assert_array_equal(
            req.tokens(), _reference(m, prompt, 6, block_size=4, eos=eos)
        )
        _assert_drained(eng)  # everything reclaimed or warm in the cache


class TestBlockPoolAccounting:
    def test_exact_after_every_step(self):
        """allocated + free == pool size after EVERY admit/evict boundary."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(3)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, num_blocks=12, prompt_bucket=8,
            max_model_len=16,
        )
        for n, t in [(5, 4), (3, 6), (7, 3), (2, 5), (6, 2)]:
            eng.add_request(
                rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32),
                max_new_tokens=t,
            )
        _assert_pool_exact(eng)
        while eng.has_work():
            eng.step()
            _assert_pool_exact(eng)
        _assert_drained(eng)

    def test_adversarial_evict_then_admit_larger_prompt(self):
        """A large request must WAIT until a finishing sequence's blocks are
        reclaimed, then admit into them — accounting exact throughout."""
        m, cfg = _model(seed=4)
        rng = np.random.default_rng(4)
        # pool of 4 blocks x 4 tokens: A (prompt 5, +4 -> 2 blocks) leaves
        # only 2 unreserved; B (prompt 9, +4 -> 3 blocks) cannot coexist
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, num_blocks=4, prompt_bucket=12,
            max_model_len=16,
        )
        a = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
        b = rng.integers(0, cfg.vocab_size, (9,)).astype(np.int32)
        ra = eng.add_request(a, max_new_tokens=4)
        rb = eng.add_request(b, max_new_tokens=4)
        saw_b_waiting = False
        out = {}
        while eng.has_work():
            for req in eng.step():
                out[req.req_id] = req
            _assert_pool_exact(eng)
            if any(r is not None and r.req_id == ra for r in eng._slot_req):
                # while A lives, B must not have been admitted (3 > 4 - 2)
                assert all(
                    r is None or r.req_id != rb for r in eng._slot_req
                )
                saw_b_waiting = True
        assert saw_b_waiting
        np.testing.assert_array_equal(
            out[ra].tokens(), _reference(m, a, 4, block_size=4)
        )
        np.testing.assert_array_equal(
            out[rb].tokens(), _reference(m, b, 4, block_size=4)
        )
        _assert_drained(eng)

    def test_failed_decode_step_rolls_back_allocator(self):
        """A transient device failure mid-step must leave the allocator in
        lockstep with the engine (mgr lengths == _ntok), so retried steps
        neither leak blocks nor break the reservation invariant."""
        m, cfg = _model(seed=8)
        rng = np.random.default_rng(8)
        prompt = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=8)
        rid = eng.add_request(prompt, max_new_tokens=4)
        real, calls = eng._step_fn, []

        def flaky(*a, **k):
            if not calls:
                calls.append(1)
                raise RuntimeError("transient device failure")
            return real(*a, **k)

        eng._step_fn = flaky
        with pytest.raises(RuntimeError, match="transient"):
            eng.step()
        _assert_pool_exact(eng)
        # rolled back, not drifted: block capacity is in lockstep with _ntok
        assert len(eng._blocks[0]) * eng.block_size >= eng._ntok[0]
        out = eng.run()  # retrying serves identical tokens
        np.testing.assert_array_equal(
            out[rid].tokens(), _reference(m, prompt, 4, block_size=4)
        )
        _assert_drained(eng)

    def test_donated_buffer_loss_marks_engine_broken(self):
        """When a failed step consumed donated cache buffers (TPU), the
        engine must refuse further use instead of serving garbage KV."""
        m, cfg = _model(seed=9)
        rng = np.random.default_rng(9)
        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=8)
        eng.add_request(
            rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32), max_new_tokens=4
        )
        eng._buffers_lost = lambda: True  # what a donating backend reports

        def doomed(*a, **k):
            raise RuntimeError("device died mid-step")

        eng._step_fn = doomed
        with pytest.raises(RuntimeError, match="device died"):
            eng.step()
        with pytest.raises(RuntimeError, match="build a new"):
            eng.step()
        with pytest.raises(RuntimeError, match="build a new"):
            eng.add_request(np.zeros((2,), np.int32))

    def test_reservation_prevents_mid_flight_exhaustion(self):
        """Worst-case reservation at admit means step() can never raise the
        allocator's out-of-blocks MemoryError mid-decode."""
        m, cfg = _model(seed=5)
        rng = np.random.default_rng(5)
        eng = ContinuousBatchingEngine(
            m, max_slots=4, block_size=4, num_blocks=6, prompt_bucket=8,
            max_model_len=16,
        )
        for _ in range(6):
            eng.add_request(
                rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32),
                max_new_tokens=7,
            )
        while eng.has_work():
            eng.step()  # MemoryError here would fail the test
            _assert_pool_exact(eng)


class TestIntakeValidation:
    def test_rejects_prompt_over_bucket(self):
        m, cfg = _model(seed=6)
        eng = ContinuousBatchingEngine(m, max_slots=1, block_size=4, prompt_bucket=8)
        with pytest.raises(ValueError, match="prompt_bucket"):
            eng.add_request(np.zeros((9,), np.int32))

    def test_rejects_over_model_len(self):
        m, cfg = _model(seed=6)
        eng = ContinuousBatchingEngine(
            m, max_slots=1, block_size=4, prompt_bucket=8, max_model_len=12
        )
        with pytest.raises(ValueError, match="max_model_len"):
            eng.add_request(np.zeros((8,), np.int32), max_new_tokens=5)

    def test_rejects_request_larger_than_whole_pool(self):
        """A request no eviction can make room for must fail at intake, not
        sit at the FIFO head busy-looping run() forever."""
        m, cfg = _model(seed=6)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, num_blocks=2, prompt_bucket=8,
            max_model_len=16,
        )
        with pytest.raises(ValueError, match="KV blocks"):
            eng.add_request(np.zeros((8,), np.int32), max_new_tokens=8)

    def test_rejects_empty_and_zero_budget(self):
        m, cfg = _model(seed=6)
        eng = ContinuousBatchingEngine(m, max_slots=1, block_size=4, prompt_bucket=8)
        with pytest.raises(ValueError, match="empty"):
            eng.add_request(np.zeros((0,), np.int32))
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.add_request(np.zeros((2,), np.int32), max_new_tokens=0)


class TestEngineMetrics:
    """The observability acceptance test: metrics report exactly 2 compiles
    for a staggered mixed workload, TTFT/decode histograms are populated,
    pool gauges match ``pool_stats()`` exactly after every admit/evict, and
    recording is a no-op with metrics disabled."""

    def _flag(self):
        return paddle.get_flags(["FLAGS_enable_metrics"])["FLAGS_enable_metrics"]

    def _assert_gauges_match(self, reg, eng):
        s = eng.pool_stats()
        assert reg.get("engine_kv_blocks_allocated").value() == s["allocated"]
        assert reg.get("engine_kv_blocks_free").value() == s["free"]
        # utilization measures LIVE load: warm-but-reclaimable cached blocks
        # are headroom, not pressure
        assert reg.get("engine_kv_pool_utilization").value() == pytest.approx(
            (s["allocated"] - s["cached_reusable"]) / s["total"]
        )
        assert reg.get("engine_queue_depth").value() == len(eng._waiting)
        assert reg.get("engine_active_slots").value() == sum(
            r is not None for r in eng._slot_req
        )

    def test_staggered_workload_metrics_and_watchdog(self):
        from paddle_tpu import observability as obs

        prior = self._flag()
        obs.GLOBAL_METRICS.reset()
        obs.GLOBAL_WATCHDOG.reset()
        paddle.set_flags({"FLAGS_enable_metrics": True})
        try:
            m, cfg = _model(seed=11)
            rng = np.random.default_rng(11)
            eng = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=16
            )
            reg = obs.GLOBAL_METRICS
            # staggered: 5 requests through 2 slots, budgets 2..6 so some
            # finish early and free their slot mid-flight
            specs = [(5, 4), (7, 2), (3, 6), (6, 3), (2, 5)]
            for n, t in specs:
                eng.add_request(
                    rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32),
                    max_new_tokens=t,
                )
            assert reg.get("engine_queue_depth").value() == 5
            done = []
            while eng.has_work():
                done += eng.step()
                self._assert_gauges_match(reg, eng)  # exact after every boundary
            assert len(done) == 5

            # histograms populated: one TTFT per admit, one latency per step
            assert reg.get("engine_ttft_seconds").count() == 5
            assert reg.get("engine_ttft_seconds").sum() > 0
            assert (
                reg.get("engine_decode_step_seconds").count()
                == eng.stats["steps"]
                > 0
            )
            assert reg.get("engine_requests_admitted_total").value() == 5
            assert reg.get("engine_requests_finished_total").total() == 5
            assert reg.get("engine_requests_finished_total").value(reason="length") == 5
            assert reg.get("engine_slots_evicted_total").value() == 5
            assert reg.get("engine_kv_pool_utilization").high_water() > 0
            s = eng.pool_stats()
            assert s["free"] + s["cached_blocks"] == eng.num_blocks

            # the watchdog saw exactly the engine's ONE compiled signature
            # (chunked prefill rides the decode dispatch)
            rep = {
                k: v
                for k, v in obs.GLOBAL_WATCHDOG.report().items()
                if k.startswith("ContinuousBatchingEngine.")
            }
            assert set(rep) == {"ContinuousBatchingEngine.step"}
            assert all(r["count"] == 1 for r in rep.values())
            assert rep["ContinuousBatchingEngine.step"]["signatures"] == ["toks[2,4]"]
            assert all(r["causes"] == {"first_call": 1} for r in rep.values())
            # ... and the gated metric counter agrees: exactly 1 compile
            c = reg.get("jit_compiles_total")
            assert c.value(fn="ContinuousBatchingEngine.step", cause="first_call") == 1
            assert c.total() == 1
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": prior})

    def test_disabled_recording_is_noop(self):
        from paddle_tpu import observability as obs

        prior = self._flag()
        paddle.set_flags({"FLAGS_enable_metrics": False})
        obs.GLOBAL_METRICS.reset()
        obs.GLOBAL_WATCHDOG.reset()
        try:
            m, cfg = _model(seed=12)
            rng = np.random.default_rng(12)
            eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=8)
            eng.add_request(
                rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
                max_new_tokens=3,
            )
            eng.run()
            # nothing recorded anywhere in the registry
            assert obs.GLOBAL_METRICS.snapshot() == {}
            # the watchdog's own ledger stays honest even with metrics off —
            # compile counting is not hot-path recording
            assert obs.GLOBAL_WATCHDOG.counts() == {
                "ContinuousBatchingEngine.step": 1,
            }
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": prior})


def test_step_returns_finished_exactly_once():
    """Finished requests are handed back only by the step() (or run()) call
    during which they finish — the engine retains no reference, so a
    step()-driven server's host memory stays bounded and a later run()
    never re-delivers stale results."""
    m, cfg = _model(seed=10)
    rng = np.random.default_rng(10)
    eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=8)
    rid = eng.add_request(
        rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32), max_new_tokens=2
    )
    done = []
    while eng.has_work():
        done += eng.step()
    assert [r.req_id for r in done] == [rid]
    assert eng.run() == {}  # nothing retained, nothing re-delivered


def test_engine_smoke():
    """Fast tier-1 smoke: two tiny requests end-to-end, ONE compile, pool
    drained — the minimal canary for retrace/accounting regressions."""
    m, cfg = _model(seed=7)
    rng = np.random.default_rng(7)
    eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=8)
    rids = [
        eng.add_request(
            rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32),
            max_new_tokens=3,
        )
        for n in (3, 5)
    ]
    out = eng.run()
    assert set(out) == set(rids)
    assert all(len(r.generated) == 3 for r in out.values())
    assert eng.stats["step_traces"] == 1
    _assert_drained(eng)
