"""Quantization framework (reference ``python/paddle/quantization``):
QAT fake-quant with STE gradients, PTQ observers + convert, int8 inference."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, quantization as Q

RNG = np.random.default_rng(0)


def _model():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _x(b=16):
    return paddle.to_tensor(RNG.normal(size=(b, 8)).astype(np.float32))


def test_quantize_dequantize_roundtrip():
    w = paddle.to_tensor(RNG.normal(size=(8, 4)).astype(np.float32))
    scales = paddle.to_tensor(
        (np.abs(np.asarray(w.numpy())).max(0) / 127.0).astype(np.float32)
    )
    q = Q.quantize_linear(w, scales, axis=1)
    assert str(q.dtype) == "int8"
    back = Q.dequantize_linear(q, scales, axis=1)
    err = np.abs(np.asarray(back.numpy()) - np.asarray(w.numpy())).max()
    assert err <= float(np.asarray(scales.numpy()).max()) * 0.51  # half-ulp rounding


def test_ptq_calibrate_and_convert_accuracy():
    model = _model()
    model.eval()
    x = _x(64)
    ref = model(x).numpy()

    ptq = Q.PTQ(Q.QuantConfig())
    observed = ptq.quantize(model)
    for _ in range(4):
        observed(x)  # calibration
    # observers saw data
    obs = [l for l in observed.sublayers() if isinstance(l, Q.AbsmaxObserver)]
    assert obs and all(o._absmax is not None for o in obs)
    converted = ptq.convert(observed)
    # int8 weights inside
    qlayers = [l for l in converted.sublayers() if isinstance(l, Q.QuantedLinear)]
    assert len(qlayers) == 2
    assert all(str(l.qweight.dtype) == "int8" for l in qlayers)
    got = converted(x).numpy()
    rel = np.abs(np.asarray(got) - np.asarray(ref)).max() / (np.abs(np.asarray(ref)).max() + 1e-9)
    assert rel < 0.05, f"int8 PTQ error too large: {rel}"
    # original model untouched (inplace=False)
    assert not [l for l in model.sublayers() if isinstance(l, Q.QuantedLinear)]


def test_qat_fake_quant_ste_gradients():
    model = _model()
    qat = Q.QAT(Q.QuantConfig())
    qmodel = qat.quantize(model)
    x = _x(8)
    out = qmodel(x)
    out.sum().backward()
    # STE: gradients reach the underlying fp weights through the fake-quant
    grads = [p.grad for p in qmodel.parameters() if not p.stop_gradient]
    assert all(g is not None for g in grads)
    assert any(float(g.abs().sum()) > 0 for g in grads)


def test_qat_trains_then_converts():
    paddle.seed(1)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    qat = Q.QAT(Q.QuantConfig())
    qmodel = qat.quantize(model)
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=qmodel.parameters())
    x = paddle.to_tensor(RNG.normal(size=(32, 4)).astype(np.float32))
    target = paddle.to_tensor((np.asarray(x.numpy()).sum(1, keepdims=True)).astype(np.float32))
    losses = []
    for _ in range(40):
        loss = ((qmodel(x) - target) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, "QAT did not train through fake-quant"
    converted = qat.convert(qmodel)
    q_out = converted(x).numpy()
    f_out = qmodel(x).numpy()
    rel = np.abs(np.asarray(q_out) - np.asarray(f_out)).max() / (
        np.abs(np.asarray(f_out)).max() + 1e-9
    )
    assert rel < 0.1


def test_config_type_and_layer_selection():
    model = _model()
    cfg = Q.QuantConfig()
    cfg.add_layer_config([model[0]])  # only the first Linear
    qat = Q.QAT(cfg)
    qmodel = qat.quantize(model, inplace=True)
    from paddle_tpu.quantization import _QATLinear

    wrapped = [l for l in qmodel.sublayers() if isinstance(l, _QATLinear)]
    assert len(wrapped) == 1


def test_ptq_calibration_actually_feeds_conversion():
    """r4 review: the observer's activation scale must reach the converted
    layer (static input quantization), and configured bit-widths must be
    honored end to end."""
    model = _model()
    model.eval()
    ptq = Q.PTQ(Q.QuantConfig())
    observed = ptq.quantize(model)
    x = _x(32)
    observed(x)
    converted = ptq.convert(observed)
    q = [l for l in converted.sublayers() if isinstance(l, Q.QuantedLinear)]
    assert all(l.act_scale is not None for l in q), "calibration scales dropped"
    # uncalibrated convert has no act scales (weight-only fallback)
    cold = ptq.convert(ptq.quantize(_model()))
    qc = [l for l in cold.sublayers() if isinstance(l, Q.QuantedLinear)]
    assert all(l.act_scale is None for l in qc)


def test_config_bits_honored():
    cfg = Q.QuantConfig(
        activation=Q.FakeQuanterWithAbsMax(quant_bits=4),
        weight=Q.FakeQuanterWithAbsMax(quant_bits=4),
    )
    qat = Q.QAT(cfg)
    from paddle_tpu.quantization import _QATLinear

    qmodel = qat.quantize(_model())
    wrapped = [l for l in qmodel.sublayers() if isinstance(l, _QATLinear)]
    assert all(l.weight_quanter.quant_bits == 4 for l in wrapped)
    assert all(l.act_quanter.quant_bits == 4 for l in wrapped)
    # 4-bit fake quant really uses a 4-bit grid: at most 16 distinct levels
    x = _x(8)
    out = wrapped[0].weight_quanter(wrapped[0].inner.weight)
    per_col = np.asarray(out.numpy())
    col = per_col[:, 0]
    assert len(np.unique(np.round(col / (np.abs(col).max() / 7 + 1e-12)))) <= 16


class TestLlmInt8Kernel:
    def test_quanted_linear_llm_int8_parity(self):
        import paddle_tpu.quantization as q

        paddle.seed(0)
        lin = paddle.nn.Linear(32, 16)
        x = paddle.to_tensor(np.random.default_rng(0).normal(size=(4, 32)).astype(np.float32))
        ref = lin(x).numpy()
        wol = q.QuantedLinear(lin, kernel="weight_only")(x).numpy()
        i8 = q.QuantedLinear(lin, kernel="llm.int8")(x).numpy()
        scale = np.abs(ref).max()
        assert np.abs(wol - ref).max() / scale < 0.02
        assert np.abs(i8 - ref).max() / scale < 0.03

    def test_kernel_plumbs_through_ptq_convert(self):
        import paddle_tpu.quantization as q

        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(16, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 4))
        ptq = q.PTQ(q.QuantConfig())
        observed = ptq.quantize(net)
        x = paddle.to_tensor(np.random.default_rng(1).normal(size=(8, 16)).astype(np.float32))
        observed(x)  # calibrate
        converted = ptq.convert(observed, kernel="llm.int8")
        quanted = [l for _, l in converted.named_sublayers() if isinstance(l, q.QuantedLinear)]
        assert len(quanted) == 2 and all(l.kernel == "llm.int8" for l in quanted)
        ref = net(x).numpy()
        out = converted(x).numpy()
        assert np.abs(out - ref).max() / np.abs(ref).max() < 0.06

    def test_rejects_bad_kernel(self):
        import paddle_tpu.quantization as q

        with pytest.raises(ValueError, match="kernel"):
            q.QuantedLinear(paddle.nn.Linear(4, 4), kernel="int4")
