"""Memory observability tests (reference ``paddle/phi/core/memory/stats.h:126``
DeviceMemoryStat peak/current + ``paddle.device.cuda.max_memory_allocated``)
and the ZeRO sharded-state memory-saving proof VERDICT r2 asked for.
"""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.core import memory as M


class TestMemoryStats:
    def test_allocated_tracks_live_arrays(self):
        base = M.memory_allocated()
        big = jnp.ones((512, 1024), jnp.float32)  # 2 MiB
        big.block_until_ready()
        cur = M.memory_allocated()
        assert cur >= base + big.nbytes
        peak = M.max_memory_allocated()
        assert peak >= cur
        del big
        assert M.max_memory_allocated() >= peak  # peak survives the free

    def test_reset_peak(self):
        big = jnp.ones((256, 1024), jnp.float32)
        big.block_until_ready()
        M.max_memory_allocated()
        del big
        M.reset_max_memory_allocated()
        after = M.max_memory_allocated()
        small = jnp.ones((8,), jnp.float32)
        small.block_until_ready()
        assert M.max_memory_allocated() < after + 10_000_000

    def test_device_namespace_parity(self):
        # paddle.device.cuda.* script-compat surface
        assert paddle.device.memory_allocated() >= 0
        assert paddle.device.cuda.max_memory_allocated() >= 0
        paddle.device.cuda.reset_max_memory_allocated()
        assert paddle.device.max_memory_allocated() >= 0

    def test_compiled_memory_stats(self):
        f = jax.jit(lambda x: jnp.tanh(x @ x).sum())
        c = f.lower(jnp.ones((128, 128))).compile()
        stats = M.compiled_memory_stats(c)
        assert stats["argument_size_in_bytes"] >= 128 * 128 * 4
        assert stats["peak_memory_in_bytes"] > 0

    def test_profiler_records_peak(self):
        import paddle_tpu.profiler as prof

        p = prof.Profiler()
        p.start()
        x = jnp.ones((256, 256), jnp.float32)
        x.block_until_ready()
        p.stop()
        assert p.peak_memory_allocated >= x.nbytes
        del x


class TestZeroShardingMemory:
    """VERDICT r2 weak #8: prove the ZeRO memory claim with numbers."""

    def _model_and_data(self):
        import paddle_tpu.nn as nn

        paddle.seed(0)
        model = nn.Sequential(nn.Linear(256, 256), nn.Linear(256, 256))
        x = paddle.randn([16, 256])
        y = paddle.randn([16, 256])
        return model, x, y

    def test_sharded_optimizer_states_are_1_over_n(self):
        from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer.dygraph_sharding_optimizer import (
            DygraphShardingOptimizer,
        )

        mesh = dist.ProcessMesh(shape=[8], dim_names=["sharding"])
        dist.set_mesh(mesh)
        model, x, y = self._model_and_data()
        inner = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
        opt = DygraphShardingOptimizer(inner, mesh=mesh)

        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()

        # every moment buffer: per-device shard bytes == total/8
        n_checked = 0
        for state in inner._accumulators.values():
            for t in state.values():
                arr = t._data if hasattr(t, "_data") else t
                if arr.ndim == 0:
                    continue
                shard = arr.addressable_shards[0].data
                if shard.size < arr.size:
                    assert shard.size * 8 == arr.size
                    n_checked += 1
        assert n_checked > 0, "no sharded optimizer state found"

    def test_compiled_step_peak_smaller_with_sharded_states(self):
        """Per-device HBM of one compiled train step: ZeRO-sharded optimizer
        states must need less argument memory than replicated states."""
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("sharding",))
        h = 512
        w = jnp.ones((h, h), jnp.float32)
        g = jnp.ones((h, h), jnp.float32)

        def adam_step(w, g, m, v):
            m2 = 0.9 * m + 0.1 * g
            v2 = 0.999 * v + 0.001 * g * g
            return w - 1e-3 * m2 / (jnp.sqrt(v2) + 1e-8), m2, v2

        repl = NamedSharding(mesh, P())
        shard = NamedSharding(mesh, P("sharding"))

        def compile_with(state_sharding):
            m = jax.device_put(jnp.zeros((h, h)), state_sharding)
            v = jax.device_put(jnp.zeros((h, h)), state_sharding)
            return (
                jax.jit(adam_step, donate_argnums=(0, 2, 3))
                .lower(jax.device_put(w, repl), jax.device_put(g, repl), m, v)
                .compile()
            )

        size_repl = M.compiled_memory_stats(compile_with(repl))["argument_size_in_bytes"]
        size_shard = M.compiled_memory_stats(compile_with(shard))["argument_size_in_bytes"]
        # m+v replicated cost 2*h*h*4 per device; sharded cost 1/8 of that
        saved = size_repl - size_shard
        expect_saved = 2 * h * h * 4 * (1 - 1 / 8)
        assert saved >= 0.9 * expect_saved, (size_repl, size_shard, expect_saved)
