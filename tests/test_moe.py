"""MoE/EP tests: gate dispatch correctness, capacity, aux loss, MoELayer
forward/backward, expert-parallel sharding, training convergence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.incubate.distributed.models.moe import (
    Experts,
    GShardGate,
    MoELayer,
    NaiveGate,
    SwitchGate,
)
from paddle_tpu.incubate.distributed.models.moe.gate import _topk_dispatch


class TestTopkDispatch:
    def test_top1_routing(self):
        logits = jnp.asarray(
            [[5.0, 0.0, 0.0], [0.0, 5.0, 0.0], [0.0, 0.0, 5.0], [5.0, 0.0, 0.0]]
        )
        combine, dispatch, gates, top1 = _topk_dispatch(logits, 1, capacity=2)
        # token 0 → expert 0 slot 0; token 3 → expert 0 slot 1
        assert bool(dispatch[0, 0, 0]) and bool(dispatch[3, 0, 1])
        assert bool(dispatch[1, 1, 0]) and bool(dispatch[2, 2, 0])
        # combine weights are the (renormalized) top-1 gate prob ≈ softmax max
        assert float(combine[0, 0, 0]) > 0.9

    def test_capacity_overflow_drops_tokens(self):
        logits = jnp.tile(jnp.asarray([[9.0, 0.0]]), (5, 1))  # all → expert 0
        combine, dispatch, _, _ = _topk_dispatch(logits, 1, capacity=2)
        kept = np.asarray(dispatch.sum(axis=(1, 2)))
        np.testing.assert_array_equal(kept, [1, 1, 0, 0, 0])

    def test_top2_renormalized(self):
        logits = jnp.asarray([[2.0, 1.0, -5.0]])
        combine, dispatch, _, _ = _topk_dispatch(logits, 2, capacity=2)
        total = float(combine.sum())
        np.testing.assert_allclose(total, 1.0, rtol=1e-5)
        assert int(dispatch.sum()) == 2


class TestGates:
    @pytest.mark.parametrize("cls,k", [(NaiveGate, 2), (GShardGate, 2), (SwitchGate, 1)])
    def test_gate_shapes_and_loss(self, cls, k):
        paddle.seed(0)
        gate = cls(d_model=16, num_expert=4)
        x = paddle.randn([24, 16])
        combine, dispatch, cap = gate(x, 1.5)
        assert tuple(combine.shape) == (24, 4, cap)
        assert tuple(dispatch.shape) == (24, 4, cap)
        loss = gate.get_loss()
        if cls is NaiveGate:
            assert float(loss) == 0.0
        else:
            assert float(loss) > 0.0  # load-balance loss


class TestMoELayer:
    def test_forward_shape_and_grad(self):
        paddle.seed(1)
        experts = Experts(num_experts=4, d_model=16, d_hidden=32)
        moe = MoELayer(d_model=16, experts=experts, gate={"type": "gshard", "top_k": 2})
        x = paddle.randn([2, 8, 16])
        x.stop_gradient = False
        y = moe(x)
        assert tuple(y.shape) == (2, 8, 16)
        (y**2).mean().backward()
        assert experts.w1.grad is not None
        assert moe.gate.wg.weight.grad is not None

    def test_expert_list_compat(self):
        paddle.seed(2)
        experts = [nn.Linear(16, 16) for _ in range(4)]
        moe = MoELayer(d_model=16, experts=experts, gate="switch")
        y = moe(paddle.randn([2, 8, 16]))
        assert tuple(y.shape) == (2, 8, 16)

    def test_ep_sharding(self):
        mesh = dist.ProcessMesh(shape=[4, 2], dim_names=["ep", "dp"])
        dist.set_mesh(mesh)
        paddle.seed(3)
        experts = Experts(num_experts=8, d_model=16, d_hidden=32)
        moe = MoELayer(d_model=16, experts=experts, gate="gshard")
        from paddle_tpu.distributed.placements import Shard

        assert isinstance(experts.w1.placements[0], Shard)
        assert len(experts.w1._data.sharding.device_set) == 8
        y = moe(paddle.randn([2, 16, 16]))
        assert np.isfinite(y.numpy()).all()

    def test_moe_trains(self):
        from paddle_tpu.distributed.mesh import set_mesh

        set_mesh(None)
        paddle.seed(4)
        experts = Experts(num_experts=4, d_model=8, d_hidden=16)
        moe = MoELayer(d_model=8, experts=experts, gate={"type": "gshard", "top_k": 2},
                       capacity_factor=2.0)
        head = nn.Linear(8, 4)
        params = moe.parameters() + head.parameters()
        opt = paddle.optimizer.AdamW(learning_rate=5e-3, parameters=params)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(32, 8)).astype(np.float32))
        w = rng.normal(size=(8, 4)).astype(np.float32)
        y = paddle.to_tensor((rng.normal(size=(32, 8)).astype(np.float32) @ w))
        losses = []
        for _ in range(40):
            out = head(moe(x))
            loss = ((out - y) ** 2).mean()
            aux = moe.get_aux_loss()
            total = loss + 0.01 * aux
            total.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    def test_late_mesh_binding(self):
        # model built BEFORE the mesh exists: EP activates on first forward
        paddle.seed(6)
        experts = Experts(num_experts=8, d_model=16, d_hidden=16)
        moe = MoELayer(d_model=16, experts=experts, gate="gshard")
        assert moe._mesh is None
        mesh = dist.ProcessMesh(shape=[8], dim_names=["ep"])
        dist.set_mesh(mesh)
        y = moe(paddle.randn([4, 16]))
        assert moe._mesh is not None
        from paddle_tpu.distributed.placements import Shard

        assert isinstance(experts.w1.placements[0], Shard)
        assert np.isfinite(y.numpy()).all()

    def test_eval_capacity_larger(self):
        gate = GShardGate(d_model=8, num_expert=2, capacity=(1.0, 2.0))
        x = paddle.randn([8, 8])
        gate.train()
        _, _, cap_train = gate(x)
        gate.eval()
        _, _, cap_eval = gate(x)
        assert cap_eval > cap_train

    def test_switch_jitter_training_only(self):
        paddle.seed(7)
        gate = SwitchGate(d_model=8, num_expert=2, switch_eps=0.3)
        x = paddle.randn([16, 8])
        gate.eval()
        c1, _, _ = gate(x)
        c2, _, _ = gate(x)
        np.testing.assert_allclose(c1.numpy(), c2.numpy())  # deterministic in eval

    def test_capacity_ceils(self):
        from paddle_tpu.incubate.distributed.models.moe.gate import _capacity

        # 10 tokens / 4 experts at factor 1.0 → ceil(2.5) = 3, not floor 2
        assert _capacity(10, 4, 1.0, 1) == 3

    def test_global_scatter_rejects_uneven(self):
        from paddle_tpu.distributed.utils import global_scatter

        with pytest.raises(NotImplementedError):
            global_scatter(paddle.randn([4, 8]), np.asarray([1, 3]), np.asarray([2, 2]))

    def test_aux_loss_cleared(self):
        paddle.seed(5)
        experts = Experts(num_experts=2, d_model=8, d_hidden=8)
        moe = MoELayer(d_model=8, experts=experts, gate="gshard")
        moe(paddle.randn([4, 8]))
        assert moe.get_aux_loss() is not None
        assert moe.get_aux_loss() is None  # cleared by the read


class TestFusedMoe:
    """Dropless fused MoE over lax.ragged_dot (reference fused_moe_kernel.cu)."""

    def _ref(self, x, gw, w1, w2, k, act, norm):
        # dense reference: route every token through its top-k experts
        logits = x @ gw
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        order = np.argsort(-p, axis=-1)[:, :k]
        y = np.zeros_like(x)
        for t in range(x.shape[0]):
            ws = p[t, order[t]]
            if norm:
                ws = ws / ws.sum()
            for j, e in enumerate(order[t]):
                h = x[t] @ w1[e]
                if act == "swiglu":
                    half = h.shape[-1] // 2
                    h = (h[:half] / (1 + np.exp(-h[:half]))) * h[half:]
                elif act == "gelu":
                    import math

                    h = 0.5 * h * (1 + np.vectorize(math.erf)(h / np.sqrt(2.0)))
                else:
                    h = np.maximum(h, 0)
                y[t] += ws[j] * (h @ w2[e])
        return y

    def test_matches_dense_routing(self):
        from paddle_tpu.incubate.nn.functional import fused_moe

        rng = np.random.default_rng(0)
        T, M, E, H, K = 12, 8, 4, 16, 2
        x = rng.normal(size=(T, M)).astype(np.float32)
        gw = rng.normal(size=(M, E)).astype(np.float32)
        w1 = (rng.normal(size=(E, M, 2 * H)) / np.sqrt(M)).astype(np.float32)
        w2 = (rng.normal(size=(E, H, M)) / np.sqrt(H)).astype(np.float32)
        out = fused_moe(
            paddle.to_tensor(x), paddle.to_tensor(gw), paddle.to_tensor(w1),
            paddle.to_tensor(w2), moe_topk=K,
        )
        ref = self._ref(x, gw, w1, w2, K, "swiglu", True)
        np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=2e-4, atol=2e-5)

    def test_relu_and_3d_input(self):
        from paddle_tpu.incubate.nn.functional import fused_moe

        rng = np.random.default_rng(1)
        B, S, M, E, H = 2, 5, 8, 3, 8
        x = rng.normal(size=(B, S, M)).astype(np.float32)
        gw = rng.normal(size=(M, E)).astype(np.float32)
        w1 = (rng.normal(size=(E, M, H)) / np.sqrt(M)).astype(np.float32)
        w2 = (rng.normal(size=(E, H, M)) / np.sqrt(H)).astype(np.float32)
        out = fused_moe(
            paddle.to_tensor(x), paddle.to_tensor(gw), paddle.to_tensor(w1),
            paddle.to_tensor(w2), moe_topk=1, activation="relu",
        )
        assert list(out.shape) == [B, S, M]
        ref = self._ref(x.reshape(-1, M), gw, w1, w2, 1, "relu", True).reshape(B, S, M)
        np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=2e-4, atol=2e-5)

    def test_gradients_flow_to_experts_and_gate(self):
        from paddle_tpu.incubate.nn.functional import fused_moe

        rng = np.random.default_rng(2)
        T, M, E, H = 8, 8, 3, 8
        x = paddle.to_tensor(rng.normal(size=(T, M)).astype(np.float32))
        x.stop_gradient = False
        gw = paddle.to_tensor(rng.normal(size=(M, E)).astype(np.float32))
        gw.stop_gradient = False
        w1 = paddle.to_tensor((rng.normal(size=(E, M, H)) / 3).astype(np.float32))
        w1.stop_gradient = False
        w2 = paddle.to_tensor((rng.normal(size=(E, H, M)) / 3).astype(np.float32))
        w2.stop_gradient = False
        out = fused_moe(x, gw, w1, w2, moe_topk=2, activation="relu")
        out.sum().backward()
        for t in (x, gw, w1, w2):
            assert t.grad is not None
            assert np.isfinite(np.asarray(t.grad.numpy())).all()
        # every expert that received tokens gets weight grads
        g1 = np.asarray(w1.grad.numpy())
        assert (np.abs(g1).sum(axis=(1, 2)) > 0).any()

    def test_gelu_activation(self):
        from paddle_tpu.incubate.nn.functional import fused_moe

        rng = np.random.default_rng(3)
        T, M, E, H = 8, 8, 3, 8
        x = rng.normal(size=(T, M)).astype(np.float32)
        gw = rng.normal(size=(M, E)).astype(np.float32)
        w1 = (rng.normal(size=(E, M, H)) / np.sqrt(M)).astype(np.float32)
        w2 = (rng.normal(size=(E, H, M)) / np.sqrt(H)).astype(np.float32)
        out = fused_moe(
            paddle.to_tensor(x), paddle.to_tensor(gw), paddle.to_tensor(w1),
            paddle.to_tensor(w2), moe_topk=2, activation="gelu",
        )
        ref = self._ref(x, gw, w1, w2, 2, "gelu", True)
        np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=2e-4, atol=2e-5)
