"""nn: Layer mechanics, layers forward shapes/numerics, losses, attention."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _t(a, **kw):
    return paddle.to_tensor(np.asarray(a), **kw)


class TestLayerBase:
    def test_parameter_registration(self):
        layer = nn.Linear(4, 3)
        names = [n for n, _ in layer.named_parameters()]
        assert set(names) == {"weight", "bias"}
        assert layer.weight.shape == [4, 3]
        assert not layer.weight.stop_gradient

    def test_sublayer_tree_and_state_dict(self):
        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = model.state_dict()
        assert "0.weight" in sd and "2.bias" in sd
        params = model.parameters()
        assert len(params) == 4

    def test_set_state_dict_roundtrip(self):
        m1 = nn.Linear(3, 3)
        m2 = nn.Linear(3, 3)
        m2.set_state_dict({k: v.numpy() for k, v in m1.state_dict().items()})
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy())

    def test_train_eval_recursive(self):
        model = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        model.eval()
        assert not model[1].training
        model.train()
        assert model[1].training

    def test_forward_hooks(self):
        layer = nn.Linear(2, 2)
        calls = []
        h = layer.register_forward_post_hook(lambda l, i, o: calls.append(1))
        layer(_t(np.ones((1, 2), np.float32)))
        assert calls == [1]
        h.remove()
        layer(_t(np.ones((1, 2), np.float32)))
        assert calls == [1]

    def test_to_dtype(self):
        layer = nn.Linear(2, 2)
        layer.to(dtype="bfloat16")
        assert layer.weight.dtype == paddle.bfloat16


class TestLayers:
    def test_linear_numerics(self):
        layer = nn.Linear(4, 3)
        x = np.random.rand(5, 4).astype(np.float32)
        out = layer(_t(x))
        expected = x @ layer.weight.numpy() + layer.bias.numpy()
        np.testing.assert_allclose(out.numpy(), expected, rtol=1e-5)

    def test_embedding(self):
        emb = nn.Embedding(10, 6)
        idx = _t(np.array([[1, 2], [3, 4]]), dtype="int32")
        out = emb(idx)
        assert out.shape == [2, 2, 6]
        np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1], rtol=1e-6)

    def test_conv2d_shapes(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = _t(np.random.rand(2, 3, 16, 16).astype(np.float32))
        out = conv(x)
        assert out.shape == [2, 8, 8, 8]

    def test_conv2d_vs_manual(self):
        conv = nn.Conv2D(1, 1, 2, bias_attr=False)
        x = np.random.rand(1, 1, 3, 3).astype(np.float32)
        out = conv(_t(x)).numpy()
        w = conv.weight.numpy()[0, 0]
        expected = np.zeros((2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                expected[i, j] = (x[0, 0, i : i + 2, j : j + 2] * w).sum()
        np.testing.assert_allclose(out[0, 0], expected, rtol=1e-4)

    def test_pools(self):
        x = _t(np.random.rand(1, 2, 8, 8).astype(np.float32))
        assert nn.MaxPool2D(2, 2)(x).shape == [1, 2, 4, 4]
        assert nn.AvgPool2D(2, 2)(x).shape == [1, 2, 4, 4]
        assert nn.AdaptiveAvgPool2D(1)(x).shape == [1, 2, 1, 1]
        np.testing.assert_allclose(
            nn.AdaptiveAvgPool2D(1)(x).numpy()[..., 0, 0], x.numpy().mean((2, 3)), rtol=1e-5
        )

    def test_layer_norm(self):
        ln = nn.LayerNorm(6)
        x = np.random.rand(2, 3, 6).astype(np.float32)
        out = ln(_t(x)).numpy()
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        np.testing.assert_allclose(out, (x - mean) / np.sqrt(var + 1e-5), rtol=1e-4, atol=1e-5)

    def test_rms_norm(self):
        rms = nn.RMSNorm(8)
        x = np.random.rand(4, 8).astype(np.float32)
        out = rms(_t(x)).numpy()
        expected = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out, expected, rtol=1e-4)

    def test_batch_norm_updates_stats(self):
        bn = nn.BatchNorm2D(3)
        x = _t(np.random.rand(4, 3, 5, 5).astype(np.float32) + 2.0)
        bn.train()
        bn(x)
        assert float(np.abs(bn._mean.numpy()).sum()) > 0
        bn.eval()
        out = bn(x)
        assert out.shape == [4, 3, 5, 5]

    def test_dropout_train_eval(self):
        drop = nn.Dropout(0.5)
        x = _t(np.ones((100, 100), np.float32))
        drop.train()
        y = drop(x)
        frac_zero = float((y.numpy() == 0).mean())
        assert 0.3 < frac_zero < 0.7
        drop.eval()
        np.testing.assert_allclose(drop(x).numpy(), x.numpy())

    def test_sequential_and_layerlist(self):
        model = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
        assert len(model) == 2
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4
        assert len(nn.Sequential(*ll).parameters()) == 8


class TestLosses:
    def test_cross_entropy_matches_numpy(self):
        logits = np.random.rand(8, 5).astype(np.float32)
        labels = np.random.randint(0, 5, (8,))
        loss = F.cross_entropy(_t(logits), _t(labels, dtype="int32"))
        p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        expected = -np.log(p[np.arange(8), labels]).mean()
        np.testing.assert_allclose(loss.numpy(), expected, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.rand(4, 3).astype(np.float32)
        labels = np.array([0, -100, 2, -100])
        loss = F.cross_entropy(_t(logits), _t(labels, dtype="int32"), ignore_index=-100)
        p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        expected = -np.log(p[[0, 2], [0, 2]]).mean()
        np.testing.assert_allclose(loss.numpy(), expected, rtol=1e-5)

    def test_cross_entropy_soft_label(self):
        logits = np.random.rand(4, 3).astype(np.float32)
        soft = np.random.dirichlet(np.ones(3), 4).astype(np.float32)
        loss = F.cross_entropy(_t(logits), _t(soft), soft_label=True)
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        np.testing.assert_allclose(loss.numpy(), -(soft * logp).sum(-1).mean(), rtol=1e-5)

    def test_mse_bce(self):
        a = np.random.rand(6).astype(np.float32)
        b = np.random.rand(6).astype(np.float32)
        np.testing.assert_allclose(F.mse_loss(_t(a), _t(b)).numpy(), ((a - b) ** 2).mean(), rtol=1e-5)
        p = np.clip(np.random.rand(6).astype(np.float32), 0.01, 0.99)
        y = (np.random.rand(6) > 0.5).astype(np.float32)
        expected = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        np.testing.assert_allclose(F.binary_cross_entropy(_t(p), _t(y)).numpy(), expected, rtol=1e-4)

    def test_kl_div(self):
        x = np.log(np.random.dirichlet(np.ones(4), 3)).astype(np.float32)
        y = np.random.dirichlet(np.ones(4), 3).astype(np.float32)
        expected = (y * (np.log(y) - x)).sum(-1).mean() / 4 * 4
        got = F.kl_div(_t(x), _t(y), reduction="mean").numpy()
        np.testing.assert_allclose(got, (y * (np.log(y) - x)).mean(), rtol=1e-4)

    def test_loss_layers(self):
        ce = nn.CrossEntropyLoss()
        out = ce(_t(np.random.rand(4, 3).astype(np.float32)), _t(np.array([0, 1, 2, 0]), dtype="int32"))
        assert out.shape == []


class TestAttention:
    def test_sdpa_matches_reference(self):
        b, s, h, d = 2, 8, 2, 16
        q = np.random.rand(b, s, h, d).astype(np.float32)
        k = np.random.rand(b, s, h, d).astype(np.float32)
        v = np.random.rand(b, s, h, d).astype(np.float32)
        out = F.scaled_dot_product_attention(_t(q), _t(k), _t(v))
        # numpy reference
        qh = q.transpose(0, 2, 1, 3)
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        logits = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(d)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        expected = (p @ vh).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out.numpy(), expected, rtol=1e-4, atol=1e-5)

    def test_causal_flash_attention(self):
        b, s, h, d = 1, 6, 1, 8
        q = np.random.rand(b, s, h, d).astype(np.float32)
        k = np.random.rand(b, s, h, d).astype(np.float32)
        v = np.random.rand(b, s, h, d).astype(np.float32)
        out, _ = F.flash_attention(_t(q), _t(k), _t(v), causal=True)
        # position 0 attends only to position 0
        np.testing.assert_allclose(out.numpy()[0, 0, 0], v[0, 0, 0], rtol=1e-5)

    def test_flashmask_matches_dense_causal(self):
        """FlashMask with start=S (nothing masked below causal) == causal attention."""
        b, s, h, d = 1, 8, 1, 4
        q = np.random.rand(b, s, h, d).astype(np.float32)
        k = np.random.rand(b, s, h, d).astype(np.float32)
        v = np.random.rand(b, s, h, d).astype(np.float32)
        idx = np.full((b, 1, s, 1), s, np.int32)  # no extra masking
        out_mask = F.flashmask_attention(_t(q), _t(k), _t(v), _t(idx), causal=True)
        out_causal, _ = F.flash_attention(_t(q), _t(k), _t(v), causal=True)
        np.testing.assert_allclose(out_mask.numpy(), out_causal.numpy(), rtol=1e-5, atol=1e-6)

    def test_flashmask_document_mask(self):
        """Two documents packed: tokens must not attend across the boundary."""
        b, s, h, d = 1, 8, 1, 4
        boundary = 4
        q = np.random.rand(b, s, h, d).astype(np.float32)
        k = np.random.rand(b, s, h, d).astype(np.float32)
        v = np.random.rand(b, s, h, d).astype(np.float32)
        # causal doc mask: for key j in doc0 (j<4), mask rows >= 4
        idx = np.zeros((b, 1, s, 1), np.int32)
        idx[:, :, :boundary, 0] = boundary  # keys in doc0: masked for rows >= 4
        idx[:, :, boundary:, 0] = s
        out = F.flashmask_attention(_t(q), _t(k), _t(v), _t(idx), causal=True).numpy()
        # row 4 (first token of doc1) attends only to key 4 ⇒ output == v[4]
        np.testing.assert_allclose(out[0, boundary, 0], v[0, boundary, 0], rtol=1e-5)

    def test_multi_head_attention_layer(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = _t(np.random.rand(2, 5, 16).astype(np.float32))
        out = mha(x)
        assert out.shape == [2, 5, 16]

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(d_model=16, nhead=2, dim_feedforward=32)
        enc = nn.TransformerEncoder(layer, num_layers=2)
        x = _t(np.random.rand(2, 5, 16).astype(np.float32))
        out = enc(x)
        assert out.shape == [2, 5, 16]


class TestActivations:
    def test_activations_numerics(self):
        x = np.linspace(-3, 3, 13).astype(np.float32)
        t = _t(x)
        np.testing.assert_allclose(F.relu(t).numpy(), np.maximum(x, 0))
        np.testing.assert_allclose(F.sigmoid(t).numpy(), 1 / (1 + np.exp(-x)), rtol=1e-5)
        np.testing.assert_allclose(
            F.softmax(t).numpy(), np.exp(x) / np.exp(x).sum(), rtol=1e-5
        )
        np.testing.assert_allclose(F.leaky_relu(t, 0.1).numpy(), np.where(x > 0, x, 0.1 * x), rtol=1e-6)
        np.testing.assert_allclose(F.silu(t).numpy(), x / (1 + np.exp(-x)), rtol=1e-5)

    def test_swiglu(self):
        x = np.random.rand(4, 8).astype(np.float32)
        y = np.random.rand(4, 8).astype(np.float32)
        out = F.swiglu(_t(x), _t(y)).numpy()
        np.testing.assert_allclose(out, x / (1 + np.exp(-x)) * y, rtol=1e-5)


class TestInitializers:
    def test_constant_and_assign(self):
        from paddle_tpu.nn import initializer as I

        p = paddle.create_parameter([3, 3], default_initializer=I.Constant(2.0))
        assert (p.numpy() == 2).all()

    def test_xavier_stats(self):
        from paddle_tpu.nn import initializer as I

        p = paddle.create_parameter([256, 256], default_initializer=I.XavierNormal())
        std = p.numpy().std()
        assert 0.05 < std < 0.08  # sqrt(2/512) ≈ 0.0625

    def test_orthogonal(self):
        from paddle_tpu.nn import initializer as I

        p = paddle.create_parameter([16, 16], default_initializer=I.Orthogonal())
        eye = p.numpy() @ p.numpy().T
        np.testing.assert_allclose(eye, np.eye(16), atol=1e-4)


def test_adaptive_pool_upsampling_no_nan():
    """Adaptive pooling with output > input duplicates values (window
    [floor(i*in/out), ceil((i+1)*in/out)) is never empty) — regression for
    NaN via empty-window division."""
    import paddle_tpu.nn.functional as F

    x = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    out = F.adaptive_avg_pool2d(x, (6, 6)).numpy()
    assert np.isfinite(out).all()
    # corner windows replicate the corner input values
    assert out[0, 0, 0, 0] == 0.0 and out[0, 0, 5, 5] == 3.0
    mx = F.adaptive_max_pool2d(x, (3, 3)).numpy()
    assert np.isfinite(mx).all() and mx[0, 0, 2, 2] == 3.0
