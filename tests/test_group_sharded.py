"""ZeRO sharding tests: stage 1/2/3 numerics vs unsharded baseline, state
sharding verification, group_sharded_parallel API.

Mirrors the reference's dygraph_group_sharded_stage{2,3}.py loss-parity
pattern (SURVEY §4), in-process on the 8-device CPU mesh.
"""

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.meta_optimizers import DygraphShardingOptimizer
from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer.dygraph_sharding_optimizer import (
    sharded_placements,
)
from paddle_tpu.distributed.placements import Replicate, Shard
from paddle_tpu.distributed.sharding import group_sharded_parallel


def _mlp(seed=11):
    paddle.seed(seed)
    return nn.Sequential(
        nn.Linear(16, 32),
        nn.GELU(),
        nn.Linear(32, 16),
    )


def _train(model, opt, steps=5, seed=0):
    rng = np.random.default_rng(seed)
    xs = [rng.normal(size=(8, 16)).astype(np.float32) for _ in range(steps)]
    losses = []
    for x in xs:
        t = paddle.to_tensor(x)
        loss = (model(t) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


class TestShardedPlacements:
    def test_picks_divisible_dim(self):
        mesh = dist.ProcessMesh(shape=[4, 2], dim_names=["sharding", "mp"])
        dist.set_mesh(mesh)
        p = paddle.randn([8, 6])
        plc = sharded_placements(p, mesh, "sharding")
        assert plc is not None and isinstance(plc[0], Shard) and plc[0].get_dim() == 0

    def test_respects_existing_mp_shard(self):
        mesh = dist.ProcessMesh(shape=[2, 2], dim_names=["sharding", "mp"])
        dist.set_mesh(mesh)
        p = paddle.randn([8, 6])
        p.process_mesh = mesh
        p.placements = [Replicate(), Shard(0)]  # mp already shards dim 0
        plc = sharded_placements(p, mesh, "sharding")
        # sharding axis must pick a different dim — dim 1 (6 % 2 == 0)
        assert isinstance(plc[0], Shard) and plc[0].get_dim() == 1
        assert isinstance(plc[1], Shard) and plc[1].get_dim() == 0

    def test_none_for_indivisible(self):
        mesh = dist.ProcessMesh(shape=[8], dim_names=["sharding"])
        dist.set_mesh(mesh)
        p = paddle.randn([3, 5])
        assert sharded_placements(p, mesh, "sharding") is None


class TestDygraphShardingOptimizer:
    def test_matches_unsharded_adamw(self):
        mesh = dist.ProcessMesh(shape=[4], dim_names=["sharding"])
        dist.set_mesh(mesh)

        m1 = _mlp()
        o1 = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m1.parameters())
        base_losses = _train(m1, o1)

        m2 = _mlp()  # same seed → same init
        o2 = DygraphShardingOptimizer(
            paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m2.parameters()),
            mesh=mesh,
        )
        shard_losses = _train(m2, o2)
        np.testing.assert_allclose(base_losses, shard_losses, rtol=2e-5, atol=1e-7)
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=2e-5, atol=1e-7)

    def test_optimizer_state_is_sharded(self):
        mesh = dist.ProcessMesh(shape=[4], dim_names=["sharding"])
        dist.set_mesh(mesh)
        m = _mlp()
        inner = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
        opt = DygraphShardingOptimizer(inner, mesh=mesh)
        _train(m, opt, steps=1)
        w = m[0].weight  # [16, 32]: shardable
        st = inner._accumulators[id(w)]
        m1 = st["moment1"]
        # moment sharded over 4 devices: each shard holds 1/4 of the rows
        assert len(m1.sharding.device_set) == 4
        shard_shape = m1.addressable_shards[0].data.shape
        assert shard_shape[0] * 4 == m1.shape[0] or shard_shape[1] * 4 == m1.shape[1]

    def test_params_restored_to_original_placement(self):
        mesh = dist.ProcessMesh(shape=[4], dim_names=["sharding"])
        dist.set_mesh(mesh)
        m = _mlp()
        opt = DygraphShardingOptimizer(
            paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters()),
            mesh=mesh,
        )
        _train(m, opt, steps=1)
        for p in m.parameters():
            assert all(isinstance(pl, Replicate) for pl in p.placements)


class TestGroupShardedParallel:
    @pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
    def test_levels_match_baseline(self, level):
        mesh = dist.ProcessMesh(shape=[4, 2], dim_names=["sharding", "dp"])
        dist.set_mesh(mesh)
        m1 = _mlp(seed=21)
        o1 = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m1.parameters())
        base = _train(m1, o1)

        m2 = _mlp(seed=21)
        o2 = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m2.parameters())
        m2, o2, _ = group_sharded_parallel(m2, o2, level)
        got = _train(m2, o2)
        np.testing.assert_allclose(base, got, rtol=2e-5, atol=1e-7)

    def test_stage3_params_stay_sharded(self):
        mesh = dist.ProcessMesh(shape=[4], dim_names=["sharding"])
        dist.set_mesh(mesh)
        m = _mlp(seed=31)
        o = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
        m, o, _ = group_sharded_parallel(m, o, "p_g_os")
        w = m[0].weight
        assert isinstance(w.placements[0], Shard)
        _train(m, o, steps=2)
        # stage 3: params remain sharded after the step (no gather-back)
        assert isinstance(m[0].weight.placements[0], Shard)
        assert len(m[0].weight._data.sharding.device_set) == 4

    def test_bad_level_raises(self):
        mesh = dist.ProcessMesh(shape=[2], dim_names=["sharding"])
        dist.set_mesh(mesh)
        m = _mlp()
        o = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
        with pytest.raises(ValueError):
            group_sharded_parallel(m, o, "bogus")


class TestStage2GradSharding:
    def test_grads_sharded_at_backward_time(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            DygraphShardingOptimizerV2,
        )

        mesh = dist.ProcessMesh(shape=[4], dim_names=["sharding"])
        dist.set_mesh(mesh)
        m = _mlp(seed=51)
        opt = DygraphShardingOptimizerV2(
            paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters()),
            mesh=mesh,
        )
        x = paddle.randn([8, 16])
        (m(x) ** 2).mean().backward()
        # before step(): the hook has already reduce-scattered the grad
        w = m[0].weight
        g = w.grad._data
        shard_rows = g.addressable_shards[0].data.shape
        assert shard_rows[0] * 4 == g.shape[0] or shard_rows[1] * 4 == g.shape[1]
        opt.step()
        opt.clear_grad()

    def test_v2_matches_baseline(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            DygraphShardingOptimizerV2,
        )

        mesh = dist.ProcessMesh(shape=[4], dim_names=["sharding"])
        dist.set_mesh(mesh)
        m1 = _mlp(seed=52)
        o1 = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m1.parameters())
        base = _train(m1, o1)
        m2 = _mlp(seed=52)
        o2 = DygraphShardingOptimizerV2(
            paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m2.parameters()),
            mesh=mesh,
        )
        got = _train(m2, o2)
        np.testing.assert_allclose(base, got, rtol=2e-5, atol=1e-7)


class TestFleetShardingIntegration:
    def test_distributed_optimizer_wraps_sharding(self):
        """The hybrid [dp=2, sharding=4] wrap must produce the SAME training
        trajectory as the unsharded optimizer — numeric parity against the
        plain-AdamW baseline (the assertion every other class in this file
        uses; a raw loss-decrease check over 3 steps of per-step-random
        inputs is noise, not a correctness signal — the baseline itself
        fails it) plus the params landing byte-comparable after training."""
        import paddle_tpu.distributed.fleet as fleet

        m1 = _mlp(seed=41)
        o1 = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m1.parameters())
        base = _train(m1, o1, steps=3)

        strat = fleet.DistributedStrategy()
        strat.hybrid_configs = {
            "dp_degree": 2,
            "pp_degree": 1,
            "sharding_degree": 4,
            "mp_degree": 1,
        }
        fleet.init(is_collective=True, strategy=strat)
        m = _mlp(seed=41)
        o = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
        )
        from paddle_tpu.distributed.fleet.meta_optimizers import HybridParallelOptimizer

        assert isinstance(o, HybridParallelOptimizer)
        assert o._sharding  # the ZeRO wrap actually engaged
        losses = _train(m, o, steps=3)
        np.testing.assert_allclose(base, losses, rtol=2e-5, atol=1e-7)
        for p1, p2 in zip(m1.parameters(), m.parameters()):
            np.testing.assert_allclose(
                p1.numpy(), p2.numpy(), rtol=2e-5, atol=1e-7
            )
