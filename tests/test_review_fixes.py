"""Regression tests for review findings: jit RNG threading, train/eval retrace,
scaler double-unscale guard, param-group lr, group-local broadcast, p2p perms,
need_clip norm exclusion."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_dropout_fresh_masks_under_jit():
    drop = nn.Dropout(0.5)
    drop.train()

    @paddle.jit.to_static
    def f(x):
        return drop(x)

    x = paddle.ones([64, 64])
    m1 = f(x).numpy()
    m2 = f(x).numpy()
    assert not np.allclose(m1, m2), "compiled dropout must draw a fresh mask per call"


def test_train_eval_retraces_free_function():
    model = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.9))

    @paddle.jit.to_static
    def f(model, x):
        return model(x)

    x = paddle.ones([16, 8])
    model.train()
    out_train = f(model, x).numpy()
    model.eval()
    out_eval = f(model, x).numpy()
    # eval: dropout disabled → deterministic pass-through of linear
    expected = x.numpy() @ model[0].weight.numpy() + model[0].bias.numpy()
    np.testing.assert_allclose(out_eval, expected, rtol=1e-4)
    assert (out_train == 0).mean() > 0.5  # train mode really dropped


def test_scaler_manual_unscale_then_step():
    scaler = paddle.amp.GradScaler(enable=True, init_loss_scaling=2.0**10)
    w = paddle.Parameter(np.ones(2, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[w])
    loss = (w * paddle.to_tensor(np.array([1.0, 2.0], np.float32))).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)  # manual unscale for clipping
    g_after_manual = w.grad.numpy().copy()
    scaler.step(opt)  # must NOT unscale a second time
    np.testing.assert_allclose(g_after_manual, [1.0, 2.0], rtol=1e-6)
    np.testing.assert_allclose(w.numpy(), [0.0, -1.0], rtol=1e-5)


def test_param_group_learning_rates():
    w1 = paddle.Parameter(np.zeros(1, np.float32), name="slow")
    w2 = paddle.Parameter(np.zeros(1, np.float32), name="fast")
    opt = paddle.optimizer.SGD(
        learning_rate=1.0,
        parameters=[
            {"params": [w1], "learning_rate": 0.1},
            {"params": [w2], "learning_rate": 10.0},
        ],
    )
    (w1 * 1.0 + w2 * 1.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(w1.numpy(), [-0.1], rtol=1e-6)
    np.testing.assert_allclose(w2.numpy(), [-10.0], rtol=1e-6)


def test_adamw_apply_decay_param_fun():
    w_decay = paddle.Parameter(np.full(1, 10.0, np.float32), name="linear_w")
    w_nodecay = paddle.Parameter(np.full(1, 10.0, np.float32), name="norm_w")
    opt = paddle.optimizer.AdamW(
        learning_rate=0.1,
        weight_decay=0.5,
        parameters=[w_decay, w_nodecay],
        apply_decay_param_fun=lambda n: "norm" not in n,
    )
    (w_decay * 0.0 + w_nodecay * 0.0).sum().backward()
    opt.step()
    assert w_decay.numpy()[0] < 10.0  # decayed
    np.testing.assert_allclose(w_nodecay.numpy(), [10.0], rtol=1e-6)  # untouched


def test_need_clip_excluded_from_global_norm():
    clip = nn.ClipGradByGlobalNorm(1.0)
    p1 = paddle.Parameter(np.ones(1, np.float32))
    p2 = paddle.Parameter(np.ones(1, np.float32))
    p2.need_clip = False
    from paddle_tpu.core.tensor import Tensor

    g1 = Tensor(np.array([0.5], np.float32))
    g2 = Tensor(np.array([100.0], np.float32))  # huge but excluded
    out = clip([(p1, g1), (p2, g2)])
    # p1's grad norm (0.5) is under the threshold → unchanged
    np.testing.assert_allclose(out[0][1].numpy(), [0.5], rtol=1e-6)
    np.testing.assert_allclose(out[1][1].numpy(), [100.0], rtol=1e-6)


def test_broadcast_subgroup_uses_local_rank():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec
    from jax.experimental.shard_map import shard_map

    import paddle_tpu.distributed as dist

    devices = np.asarray(jax.devices()[:4])
    mesh = Mesh(devices, ("g",))
    group = dist.new_group(ranks=[4, 5, 6, 7], axis_name="g")

    def body(x):
        return dist.broadcast(x, src=6, group=group)

    x = jnp.arange(4, dtype=jnp.float32).reshape(4, 1)
    out = jax.jit(
        shard_map(body, mesh=mesh, in_specs=PartitionSpec("g"), out_specs=PartitionSpec("g"))
    )(x)
    # member at local index 2 (global rank 6) holds value 2.0
    np.testing.assert_allclose(np.asarray(out).reshape(-1), [2, 2, 2, 2])


def test_ppermute_shift():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec
    from jax.experimental.shard_map import shard_map

    import paddle_tpu.distributed as dist

    devices = np.asarray(jax.devices()[:4])
    mesh = Mesh(devices, ("pp",))
    group = dist.new_group(ranks=[0, 1, 2, 3], axis_name="pp")
    perm = [(i, (i + 1) % 4) for i in range(4)]

    def body(x):
        return dist.ppermute(x, perm, group)

    x = jnp.arange(4, dtype=jnp.float32).reshape(4, 1)
    out = jax.jit(
        shard_map(body, mesh=mesh, in_specs=PartitionSpec("pp"), out_specs=PartitionSpec("pp"))
    )(x)
    np.testing.assert_allclose(np.asarray(out).reshape(-1), [3, 0, 1, 2])
