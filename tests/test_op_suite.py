"""Op tests on the OpTest harness (reference test/legacy_test/test_*_op.py
pattern): numpy references, analytic-vs-numeric grads, eager/jit parity."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import OpTest


def _rand(*shape, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=shape) * scale).astype(np.float32)


class TestMatmulOp(OpTest):
    op = staticmethod(paddle.matmul)
    inputs = {"x": _rand(3, 4, seed=1), "y": _rand(4, 5, seed=2)}
    expected = staticmethod(lambda x, y: x @ y)

    def test(self):
        self.check_output()
        self.check_grad(["x", "y"])


class TestMatmulTransposeOp(OpTest):
    op = staticmethod(paddle.matmul)
    inputs = {"x": _rand(3, 4, seed=3), "y": _rand(5, 4, seed=4)}
    attrs = {"transpose_y": True}
    expected = staticmethod(lambda x, y: x @ y.T)

    def test(self):
        self.check_output()
        self.check_grad(["x", "y"])


class TestSoftmaxOp(OpTest):
    op = staticmethod(F.softmax)
    inputs = {"x": _rand(4, 8, seed=5)}

    @staticmethod
    def expected(x):
        e = np.exp(x - x.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestTanhOp(OpTest):
    op = staticmethod(paddle.tanh)
    inputs = {"x": _rand(3, 7, seed=6)}
    expected = staticmethod(np.tanh)

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestSigmoidOp(OpTest):
    op = staticmethod(F.sigmoid)
    inputs = {"x": _rand(2, 9, seed=7)}
    expected = staticmethod(lambda x: 1 / (1 + np.exp(-x)))

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestGeluOp(OpTest):
    op = staticmethod(F.gelu)
    inputs = {"x": _rand(3, 5, seed=8)}

    @staticmethod
    def expected(x):
        from scipy.special import erf  # type: ignore

        return 0.5 * x * (1 + erf(x / np.sqrt(2)))

    def test(self):
        try:
            import scipy  # noqa: F401
        except ImportError:
            pytest.skip("scipy not available")
        self.check_output()
        self.check_grad(["x"])


class TestReduceSumOp(OpTest):
    op = staticmethod(paddle.sum)
    inputs = {"x": _rand(3, 4, 5, seed=9)}
    attrs = {"axis": 1}
    expected = staticmethod(lambda x: x.sum(1))

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestMeanOp(OpTest):
    op = staticmethod(paddle.mean)
    inputs = {"x": _rand(6, 3, seed=10)}
    expected = staticmethod(lambda x: x.mean())

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestLayerNormOp(OpTest):
    op = staticmethod(F.layer_norm)
    inputs = {
        "x": _rand(4, 16, seed=11),
        "w": np.ones(16, np.float32) + _rand(16, seed=12, scale=0.1),
        "b": _rand(16, seed=13, scale=0.1),
    }
    attrs = {"normalized_shape": 16}

    @staticmethod
    def op_wrapper(x, w, b, normalized_shape):
        return F.layer_norm(x, normalized_shape, weight=w, bias=b)

    op = staticmethod(op_wrapper.__func__)

    @staticmethod
    def expected(x, w, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * w + b

    def test(self):
        self.check_output()
        self.check_grad(["x", "w", "b"], max_relative_error=1e-2)


class TestLogSoftmaxOp(OpTest):
    op = staticmethod(F.log_softmax)
    inputs = {"x": _rand(3, 6, seed=14)}

    @staticmethod
    def expected(x):
        m = x.max(-1, keepdims=True)
        return x - m - np.log(np.exp(x - m).sum(-1, keepdims=True))

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestExpOp(OpTest):
    op = staticmethod(paddle.exp)
    inputs = {"x": _rand(4, 4, seed=15, scale=0.5)}
    expected = staticmethod(np.exp)

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestBF16Output(OpTest):
    """dtype-aware tolerance path (reference bf16 op tests)."""

    op = staticmethod(paddle.matmul)
    inputs = {
        "x": _rand(4, 8, seed=16).astype("float32"),
        "y": _rand(8, 4, seed=17).astype("float32"),
    }

    def test(self):
        import jax.numpy as jnp

        x = paddle.to_tensor(self.inputs["x"]).astype("bfloat16")
        y = paddle.to_tensor(self.inputs["y"]).astype("bfloat16")
        out = paddle.matmul(x, y)
        assert str(out.dtype).endswith("bfloat16")
        ref = self.inputs["x"] @ self.inputs["y"]
        np.testing.assert_allclose(
            out.astype("float32").numpy(), ref, rtol=3e-2, atol=3e-2
        )
