"""Launch CLI tests: env wiring, multi-proc spawn, failure teardown, logs."""

import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.distributed.launch.main import _child_env, _parse_args, launch


class TestArgsAndEnv:
    def test_parse(self):
        args = _parse_args(
            ["--master", "10.0.0.1:8090", "--nnodes", "4", "--rank", "2", "train.py", "--lr", "0.1"]
        )
        assert args.master == "10.0.0.1:8090"
        assert args.nnodes == 4 and args.rank == 2
        assert args.training_script == "train.py"
        assert args.training_script_args == ["--lr", "0.1"]

    def test_child_env(self):
        args = _parse_args(["--master", "h:1234", "--nnodes", "2", "--rank", "1",
                            "--nproc_per_node", "2", "t.py"])
        env = _child_env(args, local_rank=1)
        assert env["PADDLE_TRAINER_ID"] == "3"  # 1*2+1
        assert env["PADDLE_TRAINERS_NUM"] == "4"
        assert env["PADDLE_MASTER"] == "h:1234"
        assert env["MASTER_PORT"] == "1234"


class TestLaunchRun:
    def _script(self, tmp_path, body):
        f = tmp_path / "worker.py"
        f.write_text(textwrap.dedent(body))
        return str(f)

    def test_spawns_and_collects(self, tmp_path):
        script = self._script(
            tmp_path,
            """
            import os
            print("rank", os.environ["PADDLE_TRAINER_ID"], "of", os.environ["PADDLE_TRAINERS_NUM"])
            """,
        )
        log_dir = str(tmp_path / "logs")
        rc = launch(["--nproc_per_node", "2", "--log_dir", log_dir, script])
        assert rc == 0
        logs = sorted(os.listdir(log_dir))
        assert logs == ["workerlog.0", "workerlog.1"]
        out0 = open(os.path.join(log_dir, "workerlog.0")).read()
        assert "rank 0 of 2" in out0

    def test_failure_propagates(self, tmp_path):
        script = self._script(
            tmp_path,
            """
            import os, sys, time
            if os.environ["PADDLE_TRAINER_ID"] == "1":
                sys.exit(3)
            time.sleep(30)  # must be torn down by the watcher
            """,
        )
        import time

        t0 = time.time()
        rc = launch(["--nproc_per_node", "2", script])
        assert rc == 3
        assert time.time() - t0 < 25  # watcher killed the sleeper


class TestTwoNodeHandshake:
    """End-to-end jax.distributed coordination on localhost (VERDICT r5 #9):
    two `launch` node-processes, one worker each, real coordinator handshake
    through PADDLE_MASTER -> init_parallel_env -> cross-process allgather.

    The allgather runs over the coordination-service KV store
    (``dist.all_gather_object``), not an XLA computation — cross-process XLA
    collectives are unavailable on the CPU backend, and the store path is
    exactly what bootstrap/coordination code must use there."""

    def test_two_node_localhost_coordination(self, tmp_path):
        import socket
        import time

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent("""
            import os
            import jax
            jax.config.update("jax_platforms", "cpu")
            import paddle_tpu.distributed as dist

            dist.init_parallel_env()  # wires jax.distributed from PADDLE_* env
            assert jax.process_count() == 2, jax.process_count()
            rank = jax.process_index()
            assert rank == int(os.environ["PADDLE_TRAINER_ID"])

            # cross-process object allgather through the coordination store
            got = []
            dist.all_gather_object(got, {"rank": rank, "value": rank + 1})
            assert [g["rank"] for g in got] == [0, 1], got
            assert sum(g["value"] for g in got) == 3, got  # 1 + 2
            print("HANDSHAKE_OK", rank, flush=True)
        """))

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # no virtual 8-device split in workers
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        logs = [str(tmp_path / f"node{r}") for r in range(2)]
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--master", f"127.0.0.1:{port}", "--nnodes", "2", "--rank", str(r),
                 "--nproc_per_node", "1", "--log_dir", logs[r], str(worker)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for r in range(2)
        ]
        deadline = time.time() + 180
        for p in procs:
            p.wait(timeout=max(5.0, deadline - time.time()))
        outs = [open(os.path.join(logs[r], "workerlog.0")).read() for r in range(2)]
        assert procs[0].returncode == 0 and procs[1].returncode == 0, outs
        assert "HANDSHAKE_OK 0" in outs[0] and "HANDSHAKE_OK 1" in outs[1], outs
