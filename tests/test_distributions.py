"""Distribution families (reference ``python/paddle/distribution``): log_prob
parity against torch.distributions oracles, sample-moment sanity, KL pairs."""

import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _t(x):
    return torch.as_tensor(np.asarray(x, np.float64))


ORACLES = [
    # (ours, torch ctor, params, test values)
    (lambda: D.Beta(2.0, 3.0), lambda: torch.distributions.Beta(_t(2.0), _t(3.0)),
     [0.1, 0.5, 0.9]),
    (lambda: D.Gumbel(1.0, 2.0), lambda: torch.distributions.Gumbel(_t(1.0), _t(2.0)),
     [-1.0, 0.5, 4.0]),
    (lambda: D.LogNormal(0.5, 0.7), lambda: torch.distributions.LogNormal(_t(0.5), _t(0.7)),
     [0.2, 1.0, 3.0]),
    (lambda: D.Poisson(3.5), lambda: torch.distributions.Poisson(_t(3.5)),
     [0.0, 2.0, 7.0]),
    (lambda: D.Geometric(0.3), lambda: torch.distributions.Geometric(_t(0.3)),
     [0.0, 1.0, 5.0]),
    (lambda: D.Cauchy(0.0, 1.5), lambda: torch.distributions.Cauchy(_t(0.0), _t(1.5)),
     [-2.0, 0.0, 3.0]),
]


@pytest.mark.parametrize("ours,theirs,values", ORACLES,
                         ids=["beta", "gumbel", "lognormal", "poisson", "geometric", "cauchy"])
def test_log_prob_matches_torch(ours, theirs, values):
    d = ours()
    ref = theirs()
    for v in values:
        got = float(d.log_prob(v).numpy())
        want = float(ref.log_prob(_t(v)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_dirichlet_log_prob_and_mean():
    alpha = np.array([2.0, 3.0, 5.0], np.float32)
    d = D.Dirichlet(alpha)
    ref = torch.distributions.Dirichlet(_t(alpha))
    v = np.array([0.2, 0.3, 0.5], np.float32)
    np.testing.assert_allclose(
        float(d.log_prob(v).numpy()), float(ref.log_prob(_t(v))), rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(d.mean.numpy()), alpha / alpha.sum(), rtol=1e-5
    )
    s = d.sample([100])
    np.testing.assert_allclose(np.asarray(s.numpy()).sum(-1), 1.0, rtol=1e-4)


def test_multinomial_log_prob_and_counts():
    probs = np.array([0.2, 0.3, 0.5], np.float32)
    d = D.Multinomial(10, probs)
    ref = torch.distributions.Multinomial(10, probs=_t(probs))
    v = np.array([2.0, 3.0, 5.0], np.float32)
    np.testing.assert_allclose(
        float(d.log_prob(v).numpy()), float(ref.log_prob(_t(v))), rtol=1e-4
    )
    paddle.seed(0)
    s = np.asarray(d.sample([40]).numpy())
    assert s.shape == (40, 3)
    np.testing.assert_array_equal(s.sum(-1), np.full(40, 10.0))


def test_entropy_matches_torch():
    pairs = [
        (D.Beta(2.0, 3.0), torch.distributions.Beta(_t(2.0), _t(3.0))),
        (D.Gumbel(1.0, 2.0), torch.distributions.Gumbel(_t(1.0), _t(2.0))),
        (D.Dirichlet(np.array([2.0, 3.0, 5.0], np.float32)),
         torch.distributions.Dirichlet(_t([2.0, 3.0, 5.0]))),
        (D.Cauchy(0.0, 1.5), torch.distributions.Cauchy(_t(0.0), _t(1.5))),
    ]
    for ours, ref in pairs:
        np.testing.assert_allclose(
            float(ours.entropy().numpy()), float(ref.entropy()), rtol=1e-4,
            err_msg=type(ours).__name__,
        )


def test_sample_moments():
    paddle.seed(1)
    checks = [
        (D.Beta(2.0, 3.0), 2 / 5),
        (D.LogNormal(0.0, 0.5), np.exp(0.125)),
        (D.Poisson(4.0), 4.0),
        (D.Geometric(0.4), 1.5),
        (D.Gumbel(0.0, 1.0), 0.5772),
    ]
    for d, want_mean in checks:
        s = np.asarray(d.sample([20000]).numpy())
        np.testing.assert_allclose(s.mean(), want_mean, rtol=0.1,
                                   err_msg=type(d).__name__)


def test_kl_gamma_and_beta_match_torch():
    p = D.Gamma(2.0, 1.5)
    q = D.Gamma(3.0, 0.5)
    tp = torch.distributions.Gamma(_t(2.0), _t(1.5))
    tq = torch.distributions.Gamma(_t(3.0), _t(0.5))
    np.testing.assert_allclose(
        float(D.kl_divergence(p, q).numpy()),
        float(torch.distributions.kl_divergence(tp, tq)), rtol=1e-4,
    )
    pb = D.Beta(2.0, 3.0)
    qb = D.Beta(4.0, 1.0)
    tpb = torch.distributions.Beta(_t(2.0), _t(3.0))
    tqb = torch.distributions.Beta(_t(4.0), _t(1.0))
    np.testing.assert_allclose(
        float(D.kl_divergence(pb, qb).numpy()),
        float(torch.distributions.kl_divergence(tpb, tqb)), rtol=1e-4,
    )


def test_unregistered_kl_raises():
    with pytest.raises(NotImplementedError):
        D.kl_divergence(D.Beta(1.0, 1.0), D.Gamma(1.0, 1.0))


def test_multinomial_zero_prob_category_finite():
    """r4 review: a zero count against a zero-probability category must
    contribute 0 to log_prob, not NaN."""
    d = D.Multinomial(5, np.array([0.5, 0.5, 0.0], np.float32))
    lp = float(d.log_prob(np.array([3.0, 2.0, 0.0], np.float32)).numpy())
    ref = torch.distributions.Multinomial(
        5, probs=_t([0.5, 0.5, 0.0])
    ).log_prob(_t([3.0, 2.0, 0.0]))
    assert np.isfinite(lp)
    np.testing.assert_allclose(lp, float(ref), rtol=1e-4)
