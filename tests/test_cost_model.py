"""Cost model v1 (reference auto_parallel/static/cost/): analytic step-time
estimates, auto_tuner ordering, Engine sanity surface, and a ranking-
correlation check against measured CPU-mesh trial times."""

import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel.cost_model import (
    estimate_step_time,
    rank_configs,
    validate_ranking,
)

MODEL = {
    "num_layers": 8,
    "hidden_size": 1024,
    "num_attention_heads": 16,
    "vocab_size": 32000,
    "intermediate_size": 4096,
    "seq_length": 1024,
}
TCFG = {"model_cfg": MODEL, "global_batch_size": 16, "num_gpus": 8}


def _cfg(**kw):
    base = {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": 1, "sharding_degree": 1,
        "sharding_stage": 1, "micro_batch_size": 2, "use_recompute": False,
        "acc_steps": 1,
    }
    base.update(kw)
    return base


class TestAnalyticProperties:
    def test_recompute_costs_more_compute(self):
        a = estimate_step_time(_cfg(), TCFG)
        b = estimate_step_time(_cfg(use_recompute=True), TCFG)
        assert b["compute_s"] > a["compute_s"]
        assert b["compute_s"] / a["compute_s"] == pytest.approx(8 / 6, rel=1e-6)

    def test_mp_adds_comm_and_divides_compute(self):
        a = estimate_step_time(_cfg(), TCFG)
        b = estimate_step_time(_cfg(mp_degree=4), TCFG)
        assert b["comm_s"] > a["comm_s"]
        assert b["compute_s"] == pytest.approx(a["compute_s"] / 4, rel=1e-6)

    def test_pp_bubble(self):
        a = estimate_step_time(_cfg(acc_steps=4), TCFG)
        b = estimate_step_time(_cfg(pp_degree=4, acc_steps=4), TCFG)
        assert a["bubble_factor"] == 1.0
        assert b["bubble_factor"] == pytest.approx((4 + 3) / 4)

    def test_dp_grad_sync_scales_with_params_not_batch(self):
        small = dict(TCFG, global_batch_size=8)
        a = estimate_step_time(_cfg(dp_degree=2), small)
        big = dict(TCFG, global_batch_size=64)
        b = estimate_step_time(_cfg(dp_degree=2), big)
        assert a["comm_s"] == pytest.approx(b["comm_s"], rel=1e-6)

    def test_dispatch_scales_with_microbatches(self):
        a = estimate_step_time(_cfg(acc_steps=1), TCFG)
        b = estimate_step_time(_cfg(acc_steps=8), TCFG)
        assert b["dispatch_s"] == pytest.approx(8 * a["dispatch_s"], rel=1e-6)


class TestRanking:
    def test_rank_configs_sorted(self):
        cfgs = [
            _cfg(use_recompute=True, acc_steps=8),
            _cfg(),
            _cfg(mp_degree=8),
        ]
        ranked = rank_configs(cfgs, TCFG)
        est = [c["cost_estimate"] for c in ranked]
        assert est == sorted(est)

    def test_auto_tuner_cost_order(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner

        t = AutoTuner(dict(TCFG, hbm_bytes=64e9, order="cost"))
        est = [c["cost_estimate"] for c in t._queue]
        assert len(est) > 4 and est == sorted(est)

    def test_engine_cost_surface(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.auto_parallel import Engine, Strategy

        paddle.seed(0)
        lin = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
        eng = Engine(lin, loss=lambda o, l: o.sum(), optimizer=opt,
                     strategy=Strategy({"recompute": {"enable": True}}))
        mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"], process_ids=list(range(8)))
        eng.prepare(mesh=mesh)
        cost = eng.cost(MODEL, global_batch_size=16)
        assert cost["step_time_s"] > 0 and cost["comm_s"] > 0
        # recompute reflected
        eng2 = Engine(lin, loss=lambda o, l: o.sum(), optimizer=opt)
        eng2.prepare(mesh=mesh)
        assert eng2.cost(MODEL, 16)["compute_s"] < cost["compute_s"]


class TestRankingCorrelation:
    def test_predicted_ranking_matches_measured_cpu_trials(self):
        """Spearman(predicted, measured) on a tiny GPT over configs differing
        in recompute and micro-batching — the two axes whose relative cost
        survives on the CPU backend (VERDICT r5 #8's 'done' bar)."""
        import paddle_tpu.nn.functional as F
        from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

        VOCAB, SEQ, GBS = 64, 32, 8
        model_cfg = {
            "num_layers": 4, "hidden_size": 64, "num_attention_heads": 4,
            "vocab_size": VOCAB, "intermediate_size": 256, "seq_length": SEQ,
        }
        trial_cfgs = [
            _cfg(micro_batch_size=8, acc_steps=1),
            _cfg(micro_batch_size=8, acc_steps=1, use_recompute=True),
            _cfg(micro_batch_size=2, acc_steps=4),
            _cfg(micro_batch_size=2, acc_steps=4, use_recompute=True),
        ]
        # CPU-calibrated knobs: tiny peak so compute is visible vs overhead
        tcfg = {
            "model_cfg": model_cfg, "global_batch_size": GBS,
            "peak_flops": 2e10, "mfu": 1.0, "step_overhead": 2e-3,
        }
        predicted = [estimate_step_time(c, tcfg)["step_time_s"] for c in trial_cfgs]

        def build_timer(cfg):
            paddle.seed(0)
            gcfg = GPTConfig(
                vocab_size=VOCAB, hidden_size=64, num_layers=4, num_heads=4,
                max_position=SEQ,
            )
            m = GPTForPretraining(gcfg)
            opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
            mbs, acc = cfg["micro_batch_size"], cfg["acc_steps"]
            use_rc = cfg["use_recompute"]

            @paddle.jit.to_static
            def micro(m, opt, ids, labels):
                if use_rc:
                    from paddle_tpu.distributed.fleet import recompute

                    logits = recompute(m, ids)
                else:
                    logits = m(ids)
                loss = F.cross_entropy(
                    logits.reshape([-1, VOCAB]).astype("float32"), labels.reshape([-1])
                )
                (loss / acc).backward()
                opt.step()
                opt.clear_grad()
                return loss

            rng = np.random.default_rng(0)
            ids = paddle.to_tensor(rng.integers(0, VOCAB, (mbs, SEQ)).astype(np.int32))
            for _ in range(2 * acc):  # warmup/compile
                micro(m, opt, ids, ids)
            steps = 12 // acc  # equal dispatch count per timed block for every cfg

            def timed_step() -> float:
                t0 = time.perf_counter()
                for _ in range(steps):
                    for _ in range(acc):  # one dispatched program per microbatch
                        loss = micro(m, opt, ids, ids)
                float(loss)
                return (time.perf_counter() - t0) / steps

            return timed_step

        # Compile everything first, then time round-robin with min-over-passes:
        # sequential per-config timing lets runtime drift (allocator/thread-pool
        # warmup, a transient load spike on a shared 2-core box) land entirely
        # on one config and invert the ranking the assertion checks.
        timers = [build_timer(c) for c in trial_cfgs]
        measured = [float("inf")] * len(timers)
        for _ in range(3):
            for i, timed_step in enumerate(timers):
                measured[i] = min(measured[i], timed_step())
        rho = validate_ranking(predicted, measured)
        assert rho >= 0.5, (
            f"cost-model ranking does not track measurements: rho={rho} "
            f"predicted={predicted} measured={measured}"
        )
