"""Fused linear + cross-entropy loss head: CPU-pinned numerics (scan
reference AND interpret-mode Pallas) vs the unfused ``lm_head +
F.cross_entropy`` composition, reduction/ignore_index semantics, the
``(loss, None)`` model contract, the ``FLAGS_use_fused_loss`` env seed, and
the compiled-peak-memory regression the no-materialization claim rests on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import memory as M
from paddle_tpu.flags import GLOBAL_FLAGS, FlagRegistry
from paddle_tpu.kernels.fused_loss import fused_linear_cross_entropy
from paddle_tpu.nn.functional.loss import cross_entropy

IGN = -100


def _data(n=48, h=64, v=1000, dtype=jnp.float32, seed=0, n_ignored=4):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(n, h)), dtype)
    w = jnp.asarray(rng.normal(size=(h, v)) * 0.05, dtype)
    lab = rng.integers(0, v, (n,)).astype(np.int32)
    if n_ignored:
        lab[rng.choice(n, n_ignored, replace=False)] = IGN
    return x, w, jnp.asarray(lab)


def _unfused(x, w, lab, reduction="mean"):
    return cross_entropy.raw_fn(x @ w, lab, ignore_index=IGN, reduction=reduction)


def _grads(fn, *args):
    return jax.value_and_grad(fn, argnums=(0, 1))(*args)


class TestReferenceParity:
    """The lax.scan custom-VJP reference (the CPU/tier-1 path) vs unfused."""

    @pytest.mark.parametrize("v", [1000, 512, 130])  # incl. ragged vocab tails
    def test_loss_and_grads_fp32(self, v):
        x, w, lab = _data(v=v)
        lu, gu = _grads(_unfused, x, w, lab)
        lf, gf = _grads(lambda x, w: fused_linear_cross_entropy(x, w, lab), x, w)
        np.testing.assert_allclose(float(lf), float(lu), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gu[0]), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gu[1]), rtol=1e-4, atol=1e-5)

    def test_bf16_inputs(self):
        x, w, lab = _data(h=128, v=512, dtype=jnp.bfloat16)
        lu, gu = _grads(_unfused, x, w, lab)
        lf, gf = _grads(lambda x, w: fused_linear_cross_entropy(x, w, lab), x, w)
        assert lf.dtype == jnp.float32  # fp32 online logsumexp, fp32 loss
        np.testing.assert_allclose(float(lf), float(lu), rtol=1e-3, atol=1e-3)
        for got, ref in zip(gf, gu):
            assert got.dtype == ref.dtype  # grads land back in bf16
            np.testing.assert_allclose(
                np.asarray(got, np.float32), np.asarray(ref, np.float32),
                rtol=1e-2, atol=1e-2,
            )

    def test_tied_vocab_major_layout(self):
        x, w, lab = _data()
        lu, gu = _grads(_unfused, x, w, lab)
        lt, gt = _grads(
            lambda x, wv: fused_linear_cross_entropy(x, wv, lab, vocab_major=True),
            x, w.T,
        )
        np.testing.assert_allclose(float(lt), float(lu), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(gt[0]), np.asarray(gu[0]), rtol=1e-4, atol=1e-5)
        # dW comes back in the embedding's [V, H] layout
        np.testing.assert_allclose(np.asarray(gt[1]), np.asarray(gu[1].T), rtol=1e-4, atol=1e-5)

    def test_all_rows_ignored(self):
        x, w, _ = _data()
        lab = jnp.full((x.shape[0],), IGN, jnp.int32)
        lf, gf = _grads(lambda x, w: fused_linear_cross_entropy(x, w, lab), x, w)
        assert float(lf) == 0.0  # mean denominator clamps at 1, like F.cross_entropy
        assert float(_unfused(x, w, lab)) == 0.0
        assert float(jnp.abs(gf[0]).max()) == 0.0
        assert float(jnp.abs(gf[1]).max()) == 0.0

    def test_mean_denominator_counts_only_valid(self):
        x, w, lab = _data(n_ignored=0)
        lab = lab.at[:30].set(IGN)  # 18 of 48 rows contribute
        ls = fused_linear_cross_entropy(x, w, lab, reduction="sum")
        lm = fused_linear_cross_entropy(x, w, lab, reduction="mean")
        np.testing.assert_allclose(float(lm), float(ls) / 18.0, rtol=1e-5)
        np.testing.assert_allclose(float(lm), float(_unfused(x, w, lab)), rtol=1e-3)

    def test_reduction_none_shape_and_values(self):
        x, w, lab = _data()
        per = fused_linear_cross_entropy(
            x.reshape(4, 12, -1), w, lab.reshape(4, 12), reduction="none"
        )
        assert per.shape == (4, 12)
        ref = _unfused(x, w, lab, reduction="none")
        np.testing.assert_allclose(np.asarray(per).ravel(), np.asarray(ref), rtol=1e-4, atol=1e-5)


class TestPallasInterpretParity:
    """The Pallas kernels (fwd + dX + dW), interpret mode on CPU."""

    @pytest.mark.parametrize("vocab_major", [False, True])
    @pytest.mark.parametrize("v", [1000, 256])  # 1000 % 128 != 0: ragged tail
    def test_loss_and_grads(self, vocab_major, v):
        x, w, lab = _data(h=128, v=v)
        wl = w.T if vocab_major else w
        lu, gu = _grads(_unfused, x, w, lab)
        lp, gp = _grads(
            lambda x, wl: fused_linear_cross_entropy(
                x, wl, lab, vocab_major=vocab_major, interpret=True, block=(16, 128)
            ),
            x, wl,
        )
        np.testing.assert_allclose(float(lp), float(lu), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(gu[0]), rtol=1e-4, atol=1e-5)
        dw = gp[1].T if vocab_major else gp[1]
        np.testing.assert_allclose(np.asarray(dw), np.asarray(gu[1]), rtol=1e-4, atol=1e-5)

    def test_bf16_and_row_padding(self):
        # 40 rows with a 16-row block: the kernel pads rows 40→48 with
        # ignore_index labels; padded rows must contribute nothing
        x, w, lab = _data(n=40, h=128, v=256, dtype=jnp.bfloat16)
        lu, gu = _grads(_unfused, x, w, lab)
        lp, gp = _grads(
            lambda x, w: fused_linear_cross_entropy(
                x, w, lab, interpret=True, block=(16, 128)
            ),
            x, w,
        )
        np.testing.assert_allclose(float(lp), float(lu), rtol=1e-3, atol=1e-3)
        for got, ref in zip(gp, gu):
            np.testing.assert_allclose(
                np.asarray(got, np.float32), np.asarray(ref, np.float32),
                rtol=1e-2, atol=1e-2,
            )

    def test_all_ignored_interpret(self):
        x, w, _ = _data(h=128, v=256)
        lab = jnp.full((x.shape[0],), IGN, jnp.int32)
        lp, gp = _grads(
            lambda x, w: fused_linear_cross_entropy(
                x, w, lab, interpret=True, block=(16, 128)
            ),
            x, w,
        )
        assert float(lp) == 0.0
        assert float(jnp.abs(gp[0]).max()) == 0.0 and float(jnp.abs(gp[1]).max()) == 0.0


class TestModelContract:
    """Models return (loss, None) on the fused path, (loss, logits) off it."""

    def _llama(self, tie):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        cfg.tie_word_embeddings = tie
        model = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(3)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32))
        return model, ids

    @pytest.mark.parametrize("tie", [False, True])
    def test_llama_fused_vs_unfused(self, tie):
        model, ids = self._llama(tie)
        prior = paddle.get_flags(["FLAGS_use_fused_loss"])
        try:
            paddle.set_flags({"FLAGS_use_fused_loss": True})
            loss_f, second = model(ids, labels=ids)
            assert second is None  # the contract: no [B, S, V] buffer to return
            loss_f.backward()
            head = model.lm_head.weight if not tie else model.llama.embed_tokens.weight
            assert head.grad is not None and float(head.grad.abs().sum()) > 0
            model.clear_gradients()
            paddle.set_flags({"FLAGS_use_fused_loss": False})
            loss_u, logits = model(ids, labels=ids)
            assert logits is not None
            np.testing.assert_allclose(float(loss_f), float(loss_u), rtol=1e-3, atol=1e-3)
        finally:
            paddle.set_flags(prior)

    def test_gpt_and_ernie_fused_paths(self):
        from paddle_tpu.models.ernie import ErnieConfig, ErnieModel
        from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

        prior = paddle.get_flags(["FLAGS_use_fused_loss"])
        rng = np.random.default_rng(4)
        ids = paddle.to_tensor(rng.integers(0, 128, (2, 16)).astype(np.int32))
        try:
            paddle.set_flags({"FLAGS_use_fused_loss": True})
            paddle.seed(0)
            gpt = GPTForPretraining(GPTConfig.tiny())
            loss, second = gpt(ids, labels=ids)
            assert second is None
            loss.backward()
            assert float(gpt.gpt.embeddings.word_embeddings.weight.grad.abs().sum()) > 0
            paddle.seed(0)
            ernie = ErnieModel(ErnieConfig.tiny())
            mlm = np.full((2, 16), IGN, np.int64)
            mlm[0, 3], mlm[1, 5] = 7, 9
            loss_f, pooled = ernie(ids, labels=paddle.to_tensor(mlm))
            assert tuple(pooled.shape) == (2, 64)
            paddle.set_flags({"FLAGS_use_fused_loss": False})
            loss_u, _ = ernie(ids, labels=paddle.to_tensor(mlm))
            np.testing.assert_allclose(float(loss_f), float(loss_u), rtol=1e-3, atol=1e-3)
        finally:
            paddle.set_flags(prior)


class TestAutotuneEntry:
    def test_entry_consults_tuner_for_blocks(self, monkeypatch):
        """When ``block`` isn't pinned, the entry asks the autotuner for the
        (row_block, vocab_block) pair (the flash_attention test pattern)."""
        from paddle_tpu.kernels import autotune as at

        seen = {}

        def fake_autotune(kernel, key, candidates, build, default, repeats=3):
            seen["kernel"], seen["key"] = kernel, key
            return (16, 128)

        monkeypatch.setattr(at, "autotune", fake_autotune)
        x, w, lab = _data(h=128, v=256)
        loss = fused_linear_cross_entropy(x, w, lab, interpret=True)
        assert np.isfinite(float(loss))
        assert seen["kernel"] == "fused_linear_xent"
        assert seen["key"][1] == 256  # vocab size in the cache key


class TestFallbackCounter:
    def test_warn_fallback_counts_per_kernel(self):
        """A Pallas failure degrading to the XLA path is scrapeable, not just
        a one-time log line."""
        from paddle_tpu.kernels import select

        prior = paddle.get_flags(["FLAGS_enable_metrics"])
        paddle.set_flags({"FLAGS_enable_metrics": True})
        try:
            before = select._fallbacks_total.value(kernel="flxent_probe")
            select.warn_fallback("flxent_probe", RuntimeError("boom"))
            select.warn_fallback("flxent_probe", RuntimeError("boom again"))
            assert select._fallbacks_total.value(kernel="flxent_probe") == before + 2
        finally:
            paddle.set_flags(prior)


class TestFlagEnvSeeding:
    """FLAGS_use_fused_loss seeds from the environment at first read
    (the test_observability.py pattern)."""

    def test_env_seeds_fresh_registry(self, monkeypatch):
        reg = FlagRegistry()
        reg.define("use_fused_loss", bool, True, "")
        monkeypatch.setenv("FLAGS_use_fused_loss", "false")
        assert reg.get("use_fused_loss") is False

    def test_flag_registered_with_default_on(self):
        assert isinstance(GLOBAL_FLAGS.get("use_fused_loss"), bool)


class TestCompiledMemoryRegression:
    """The no-materialization claim, enforced: the jitted fused train loss
    must peak strictly below the unfused composition (core/memory.py
    compiled stats, the test_memory.py methodology)."""

    def test_fused_peak_below_unfused(self):
        n, h, v = 512, 128, 4096
        x = jnp.zeros((n, h), jnp.bfloat16)
        w = jnp.zeros((h, v), jnp.bfloat16)
        lab = jnp.zeros((n,), jnp.int32)

        def unfused(x, w, lab):
            return _unfused(x, w, lab)

        def fused(x, w, lab):
            return fused_linear_cross_entropy(x, w, lab)

        def peak(fn):
            c = jax.jit(jax.value_and_grad(fn, argnums=(0, 1))).lower(x, w, lab).compile()
            return M.compiled_memory_stats(c)["peak_memory_in_bytes"]

        p_unfused = peak(unfused)
        p_fused = peak(fused)
        # the unfused composition holds [N, V] logits (+ fp32 log_softmax
        # copies) live across backward; the fused path's largest loss-head
        # temp is one [N, block] chunk
        assert p_fused < p_unfused, (p_fused, p_unfused)
        # and not marginally: at this shape the gap is several [N, V] buffers
        assert p_unfused - p_fused > n * v * 2, (p_fused, p_unfused)
