"""Sparse tensor family (reference ``paddle/phi/core/sparse_coo_tensor.h`` +
``python/paddle/sparse``, sparse_ops.yaml): OpTest-style parity vs dense."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse

RNG = np.random.default_rng(0)


def _rand_sparse_np(shape=(6, 8), density=0.3):
    dense = RNG.normal(size=shape).astype(np.float32)
    mask = RNG.random(shape) < density
    return dense * mask


class TestSparseCoo:
    def test_roundtrip_dense_coo_dense(self):
        d = _rand_sparse_np()
        s = paddle.to_tensor(d).to_sparse_coo(2)
        assert s.is_sparse() and s.is_sparse_coo()
        assert s.shape == [6, 8]
        assert s.nnz == int((d != 0).sum())
        np.testing.assert_array_equal(s.to_dense().numpy(), d)

    def test_construct_from_indices_values(self):
        indices = [[0, 1, 2], [1, 2, 0]]
        values = [1.0, 2.0, 3.0]
        s = sparse.sparse_coo_tensor(indices, values, shape=[3, 3])
        dense = np.zeros((3, 3), np.float32)
        dense[0, 1], dense[1, 2], dense[2, 0] = 1, 2, 3
        np.testing.assert_array_equal(s.to_dense().numpy(), dense)
        # indices()/values() come back in paddle layout
        assert list(s.indices().shape) == [2, 3]
        assert list(s.values().shape) == [3]

    def test_coalesce_sums_duplicates(self):
        s = sparse.sparse_coo_tensor([[0, 0], [1, 1]], [2.0, 3.0], shape=[2, 2])
        c = s.coalesce()
        assert c.nnz == 1
        assert float(c.to_dense().numpy()[0, 1]) == 5.0

    def test_unary_ops_match_dense(self):
        d = np.clip(np.abs(_rand_sparse_np()), 0.0, 0.9)  # in-domain for sqrt/asin
        s = paddle.to_tensor(d).to_sparse_coo(2)
        for name in ["relu", "abs", "sin", "sinh", "tan", "tanh", "asin",
                     "asinh", "atan", "sqrt", "square", "log1p", "expm1", "neg"]:
            fn = getattr(sparse, name)
            got = fn(s).to_dense().numpy()
            ref_fn = {
                "relu": lambda x: np.maximum(x, 0), "neg": np.negative,
                "asin": np.arcsin, "asinh": np.arcsinh, "atan": np.arctan,
            }.get(name, getattr(np, name, None))
            ref = np.where(d != 0, ref_fn(d), 0.0).astype(np.float32)
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6, err_msg=name)

    def test_pow_and_cast(self):
        d = np.abs(_rand_sparse_np())
        s = paddle.to_tensor(d).to_sparse_coo(2)
        np.testing.assert_allclose(
            sparse.pow(s, 2.0).to_dense().numpy(), d * d, rtol=1e-5
        )
        assert str(sparse.cast(s, value_dtype="float64").dtype) in ("float64", "float32")

    def test_add_subtract_union_patterns(self):
        a = _rand_sparse_np()
        b = _rand_sparse_np()
        sa = paddle.to_tensor(a).to_sparse_coo(2)
        sb = paddle.to_tensor(b).to_sparse_coo(2)
        np.testing.assert_allclose((sa + sb).to_dense().numpy(), a + b, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose((sa - sb).to_dense().numpy(), a - b, rtol=1e-5, atol=1e-6)

    def test_multiply_dense_masks(self):
        a = _rand_sparse_np()
        y = RNG.normal(size=a.shape).astype(np.float32)
        s = paddle.to_tensor(a).to_sparse_coo(2)
        np.testing.assert_allclose(
            sparse.multiply(s, paddle.to_tensor(y)).to_dense().numpy(),
            a * y, rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            sparse.multiply(s, 2.5).to_dense().numpy(), a * 2.5, rtol=1e-5
        )

    def test_matmul_sparse_dense(self):
        a = _rand_sparse_np((5, 7))
        y = RNG.normal(size=(7, 3)).astype(np.float32)
        s = paddle.to_tensor(a).to_sparse_coo(2)
        out = sparse.matmul(s, paddle.to_tensor(y))
        np.testing.assert_allclose(np.asarray(out.numpy()), a @ y, rtol=1e-4, atol=1e-5)
        # dense @ sparse
        x = RNG.normal(size=(4, 5)).astype(np.float32)
        out2 = sparse.matmul(paddle.to_tensor(x), s)
        np.testing.assert_allclose(np.asarray(out2.numpy()), x @ a, rtol=1e-4, atol=1e-5)

    def test_masked_matmul(self):
        x = RNG.normal(size=(5, 4)).astype(np.float32)
        y = RNG.normal(size=(4, 6)).astype(np.float32)
        mask_np = (_rand_sparse_np((5, 6)) != 0).astype(np.float32)
        mask = paddle.to_tensor(mask_np).to_sparse_coo(2)
        out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y), mask)
        np.testing.assert_allclose(
            out.to_dense().numpy(), (x @ y) * mask_np, rtol=1e-4, atol=1e-5
        )

    def test_transpose_and_sum(self):
        a = _rand_sparse_np((4, 6))
        s = paddle.to_tensor(a).to_sparse_coo(2)
        np.testing.assert_allclose(
            sparse.transpose(s, [1, 0]).to_dense().numpy(), a.T, rtol=1e-6
        )
        np.testing.assert_allclose(float(sparse.sum(s).numpy()), a.sum(), rtol=1e-5)
        np.testing.assert_allclose(
            sparse.sum(s, axis=0).to_dense().numpy(), a.sum(0), rtol=1e-5, atol=1e-6
        )

    def test_is_same_shape(self):
        a = paddle.to_tensor(_rand_sparse_np()).to_sparse_coo(2)
        b = paddle.to_tensor(_rand_sparse_np()).to_sparse_coo(2)
        assert sparse.is_same_shape(a, b)


class TestSparseCsr:
    def test_coo_csr_roundtrip(self):
        d = _rand_sparse_np((5, 9))
        csr = paddle.to_tensor(d).to_sparse_csr()
        assert csr.is_sparse_csr()
        np.testing.assert_array_equal(csr.to_dense().numpy(), d)
        back = csr.to_sparse_coo()
        np.testing.assert_array_equal(back.to_dense().numpy(), d)

    def test_construct_csr(self):
        # [[0, 1, 0], [2, 0, 3]]
        csr = sparse.sparse_csr_tensor([0, 1, 3], [1, 0, 2], [1.0, 2.0, 3.0], [2, 3])
        ref = np.array([[0, 1, 0], [2, 0, 3]], np.float32)
        np.testing.assert_array_equal(csr.to_dense().numpy(), ref)
        assert csr.nnz == 3

    def test_csr_matmul_via_coo(self):
        d = _rand_sparse_np((4, 5))
        y = RNG.normal(size=(5, 2)).astype(np.float32)
        csr = paddle.to_tensor(d).to_sparse_csr()
        out = sparse.matmul(csr, paddle.to_tensor(y))
        np.testing.assert_allclose(np.asarray(out.numpy()), d @ y, rtol=1e-4, atol=1e-5)


class TestDenseTrailingDims:
    """r4 review: COO with sparse_dim < ndim (dense trailing dims)."""

    def test_sum_over_dense_axis(self):
        arr = np.zeros((4, 3, 2), np.float32)
        arr[0, 1] = [1.0, 2.0]
        arr[2, 0] = [3.0, 4.0]
        s = paddle.to_tensor(arr).to_sparse_coo(2)  # indices have 2 cols
        out = sparse.sum(s, axis=2)
        np.testing.assert_allclose(out.to_dense().numpy(), arr.sum(2), rtol=1e-6)

    def test_sum_over_sparse_axis_keeps_dense_part(self):
        arr = np.zeros((4, 3, 2), np.float32)
        arr[0, 1] = [1.0, 2.0]
        arr[2, 1] = [3.0, 4.0]
        s = paddle.to_tensor(arr).to_sparse_coo(2)
        out = sparse.sum(s, axis=0)
        np.testing.assert_allclose(out.to_dense().numpy(), arr.sum(0), rtol=1e-6)

    def test_transpose_dense_dims_rejected(self):
        arr = np.zeros((4, 3, 2), np.float32)
        arr[0, 1] = [1.0, 2.0]
        s = paddle.to_tensor(arr).to_sparse_coo(2)
        with pytest.raises(NotImplementedError):
            sparse.transpose(s, [2, 1, 0])
        out = sparse.transpose(s, [1, 0, 2])  # sparse-dims-only perm is fine
        np.testing.assert_allclose(out.to_dense().numpy(), arr.transpose(1, 0, 2))
