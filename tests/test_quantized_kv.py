"""Quantized serving plane: ``FLAGS_kv_cache_dtype=int8`` KV pool +
``FLAGS_weight_only_int8`` projections.

The contract under test (engine ``kv_cache_dtype=`` / ``weight_only_int8=``
+ ``kernels/quant.py`` + the scale-threaded block-attention dispatchers):

- the bf16 DEFAULT is byte-identical to the pre-quantization engine: 2-tuple
  caches, no scale planes, the same ONE compiled step signature;
- the int8 pool is 4-tuples ``(kc, vc, ks, vs)`` with fp32 scale planes
  ``[NB, KVH, BS]`` addressed by the SAME block ids — the scales ride every
  lifecycle seam (refcounts, CoW, rewind, spill/prefetch, recovery, tp) the
  200-op churn property exercises, still under ONE compiled signature;
- quality is MEASURED, not assumed: greedy token-match vs the bf16 engine
  ≥ 0.99 and a hard max-logit-error tolerance (the same numbers bench
  records), with KV bytes/token reduced ≥ 1.5x;
- ``quant.dequant`` is a fault SITE that degrades one dispatch to the XLA
  gather fallback (counted) — never the engine's recovery path;
- the weight-only int8 kernel (interpret mode) stays in numeric lockstep
  with its canonical XLA composition, and tied/shared weights are never
  quantized.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.kernels.quant import (
    int8_weight_matmul,
    quantize_module_weights,
    quantize_weight_int8,
)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.testing import faults

from conftest import assert_engine_pool_exact as _assert_pool_exact
from conftest import assert_kv_tier_exact


def _model(seed=0, **cfg_over):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    for k, v in cfg_over.items():
        setattr(cfg, k, v)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _workload(cfg, seed=0):
    rng = np.random.default_rng(seed)
    specs = [(5, 6), (7, 4), (3, 8), (6, 2), (2, 7)]
    return [
        (rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32), t)
        for n, t in specs
    ]


def _run(m, work, **kw):
    eng = ContinuousBatchingEngine(
        m, max_slots=3, block_size=4, prompt_bucket=16, **kw
    )
    rids = [eng.add_request(p, max_new_tokens=t) for p, t in work]
    out = eng.run()
    return eng, [out[r].tokens() for r in rids]


def _assert_scale_planes(eng):
    """The quantized-pool structural invariant: every layer entry is a
    4-tuple, the scale planes are fp32 ``[NB, KVH, BS]`` over the SAME block
    ids as the int8 KV arrays (entry exists iff the pool has the block), and
    every scale is finite and strictly positive — the quantize-on-write rule
    (``absmax/127`` or the 1.0 identity) can produce nothing else, so a
    zero/NaN scale is a leak from an uninitialized or torn write."""
    nb, kvh, bs, _hd = eng._cache_shape
    assert eng._quant_kv
    for entry in eng._caches:
        assert len(entry) == 4
        kc, vc, ks, vs = entry
        assert kc.dtype == jnp.int8 and vc.dtype == jnp.int8
        for sc in (ks, vs):
            assert sc.shape == (nb, kvh, bs)
            assert sc.dtype == jnp.float32
            a = np.asarray(sc)
            assert np.isfinite(a).all()
            assert (a > 0).all()


class TestBf16DefaultUnchanged:
    def test_default_engine_has_no_scale_planes(self):
        m, cfg = _model(seed=1)
        eng, toks = _run(m, _workload(cfg, 1))
        assert eng.kv_cache_dtype == "bf16"
        assert not eng._quant_kv
        for entry in eng._caches:
            assert len(entry) == 2
        s = eng.pool_stats()
        assert s["kv_cache_dtype"] == "bf16"
        assert s["bytes_per_token"] > 0
        assert eng.stats["step_traces"] == 1
        assert all(len(t) > 0 for t in toks)

    def test_invalid_dtype_rejected(self):
        m, _cfg = _model(seed=1)
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, kv_cache_dtype="fp4"
            )


class TestQuantizedPoolStructure:
    def test_int8_pool_scale_planes_and_one_signature(self):
        m, cfg = _model(seed=2)
        eng, toks = _run(m, _workload(cfg, 2), kv_cache_dtype="int8")
        assert eng.kv_cache_dtype == "int8"
        _assert_scale_planes(eng)
        _assert_pool_exact(eng)
        assert eng.pool_stats()["kv_cache_dtype"] == "int8"
        # the whole mixed prefill/decode workload through ONE compiled step
        assert eng.stats["step_traces"] == 1
        assert all(len(t) > 0 for t in toks)

    def test_bytes_per_token_reduction(self):
        """The tentpole's accounting claim: int8 bytes/token = 2·L·KVH·(D+4)
        (one scale fp32 per token-row per head riding along) — ≥ 1.5x under
        the bf16/f32 pool's 2·L·KVH·D·itemsize."""
        m, cfg = _model(seed=3)
        hd = cfg.hidden_size // cfg.num_attention_heads
        base = ContinuousBatchingEngine(m, max_slots=2, block_size=4)
        quant = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, kv_cache_dtype="int8"
        )
        bpt_b = base.pool_stats()["bytes_per_token"]
        bpt_q = quant.pool_stats()["bytes_per_token"]
        expect_q = 2 * cfg.num_hidden_layers * cfg.num_key_value_heads * (hd + 4)
        assert bpt_q == expect_q
        assert bpt_b / bpt_q >= 1.5


class TestQuantizedChurnProperty:
    def test_200_op_seeded_churn_quantized_pool(self):
        """The prefix-cache churn property test on the INT8 pool: seeded
        admit/decode/cancel/evict churn with heavy prefix sharing — pool
        refcounts exact AND the scale-plane invariant after EVERY op, every
        request delivered exactly once, one compiled signature. Then the
        leak probe: a fresh request through the churned pool must emit the
        same tokens as on a pristine engine — a scale row leaking across
        free/CoW/rewind would corrupt it."""
        m, cfg = _model(seed=40)
        rng = np.random.default_rng(40)
        eng = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, num_blocks=24, prompt_bucket=16,
            max_model_len=32, kv_cache_dtype="int8",
        )
        families = [
            rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
            for n in (9, 6, 12)
        ]

        def make_prompt():
            fam = families[int(rng.integers(0, len(families)))]
            tail_n = int(rng.integers(0, 4))
            tail = rng.integers(0, cfg.vocab_size, (tail_n,)).astype(np.int32)
            return np.concatenate([fam, tail])[:16]

        submitted, done = {}, {}
        cancelled = 0
        for _op in range(200):
            r = rng.random()
            if r < 0.40 and len(eng._waiting) < 6:
                rid = eng.add_request(
                    make_prompt(), max_new_tokens=int(rng.integers(1, 6))
                )
                submitted[rid] = True
            elif r < 0.85:
                if eng.has_work():
                    for req in eng.step():
                        assert req.req_id not in done, "delivered twice"
                        done[req.req_id] = req
            elif r < 0.93:
                live = [q.req_id for q in eng.live_requests()] + [
                    q.req_id for q in eng._waiting
                ]
                if live:
                    rid = int(rng.choice(live))
                    req = eng.cancel_request(rid)
                    assert req is not None and req.finished
                    done[rid] = req
                    cancelled += 1
            else:
                if eng._cache is not None:
                    eng._cache.evict_blocks(1)  # external pressure
            _assert_pool_exact(eng)
            _assert_scale_planes(eng)
        while eng.has_work():
            for req in eng.step():
                assert req.req_id not in done
                done[req.req_id] = req
            _assert_pool_exact(eng)
            _assert_scale_planes(eng)
        assert set(done) == set(submitted)
        assert cancelled > 0
        assert eng.stats["step_traces"] == 1

        # scale-leak probe: fresh prompt through the churned pool vs a
        # pristine engine with the same seeded weights — byte-identical
        probe = rng.integers(0, cfg.vocab_size, (7,)).astype(np.int32)
        r_churn = eng.add_request(probe, max_new_tokens=5)
        out_churn = eng.run()
        m2, _ = _model(seed=40)
        fresh = ContinuousBatchingEngine(
            m2, max_slots=3, block_size=4, num_blocks=24, prompt_bucket=16,
            max_model_len=32, kv_cache_dtype="int8",
        )
        r_fresh = fresh.add_request(probe, max_new_tokens=5)
        out_fresh = fresh.run()
        np.testing.assert_array_equal(
            out_churn[r_churn].tokens(), out_fresh[r_fresh].tokens()
        )

    def test_200_op_churn_quantized_host_tier_spill_prefetch(self):
        """The hierarchical-KV churn extended to the int8 pool: the host
        tier stores the PACKED block representation (int8 KV + the scale
        planes viewed as 4 trailing bytes), so ``block_nbytes`` is the
        packed size — and the dual-residency equality in
        ``assert_kv_tier_exact`` checks the packed capture byte-for-byte
        through spill AND prefetch after every op."""
        m, cfg = _model(seed=52)
        rng = np.random.default_rng(52)
        hd = cfg.hidden_size // cfg.num_attention_heads
        # packed int8 block: [L, 2, KVH, BS, D+4] x 1 byte
        bpb = cfg.num_hidden_layers * 2 * cfg.num_key_value_heads * 4 * (hd + 4)
        eng = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, num_blocks=20, prompt_bucket=24,
            max_model_len=40, kv_host_tier_bytes=6 * bpb,
            kv_cache_dtype="int8",
        )
        assert eng._host_tier.block_nbytes == bpb
        families = [
            rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
            for n in (9, 12)
        ]
        finished_streams = []

        def make_prompt():
            if finished_streams and rng.random() < 0.5:
                base = finished_streams[int(rng.integers(0, len(finished_streams)))]
            else:
                base = families[int(rng.integers(0, len(families)))]
            tail_n = int(rng.integers(0, 4))
            tail = rng.integers(0, cfg.vocab_size, (tail_n,)).astype(np.int32)
            return np.concatenate([base, tail])[:20]

        submitted, done = {}, {}
        for _op in range(200):
            r = rng.random()
            if r < 0.35 and len(eng._waiting) < 6:
                rid = eng.add_request(
                    make_prompt(), max_new_tokens=int(rng.integers(1, 6))
                )
                submitted[rid] = True
            elif r < 0.80:
                if eng.has_work():
                    for req in eng.step():
                        assert req.req_id not in done, "delivered twice"
                        done[req.req_id] = req
                        if len(finished_streams) < 6:
                            finished_streams.append(req.tokens())
            elif r < 0.88:
                live = [q.req_id for q in eng.live_requests()] + [
                    q.req_id for q in eng._waiting
                ]
                if live:
                    rid = int(rng.choice(live))
                    req = eng.cancel_request(rid)
                    assert req is not None and req.finished
                    done[rid] = req
            elif r < 0.96:
                eng._cache.evict_blocks(1)  # device pressure -> SPILL
            else:
                eng._host_tier.drop_lru(1)
            _assert_pool_exact(eng)
            _assert_scale_planes(eng)
            assert_kv_tier_exact(eng)
        while eng.has_work():
            for req in eng.step():
                assert req.req_id not in done
                done[req.req_id] = req
            _assert_pool_exact(eng)
            assert_kv_tier_exact(eng)
        assert set(done) == set(submitted)
        s = eng._host_tier.stats_snapshot()
        assert s["spilled_blocks"] > 0  # the churn actually spilled
        assert s["prefetched_blocks"] > 0  # ... and came back
        # the byte counters advertise the PACKED (halved) traffic
        assert s["spilled_bytes"] == s["spilled_blocks"] * bpb
        assert s["prefetched_bytes"] == s["prefetched_blocks"] * bpb
        assert eng.stats["step_traces"] == 1


class TestQualityGate:
    def test_greedy_token_match_and_logit_error_within_tolerance(self):
        """The measured quality numbers bench records, asserted as a HARD
        tier-1 gate: greedy token-match ≥ 0.99 on the seeded workload,
        weight-only max logit error bounded, KV bytes/token ≥ 1.5x down."""
        from paddle_tpu.inference.quality import quality_delta

        # the EXACT seeded CPU workload bench.py's quantized record runs —
        # the gate asserts on the number the bench reports, not a cousin
        rng = np.random.default_rng(11)
        cfg = LlamaConfig.tiny()
        prompts = [
            rng.integers(
                0, cfg.vocab_size, (int(rng.integers(8, 17)),)
            ).astype(np.int32)
            for _ in range(4)
        ]
        q = quality_delta(
            lambda: _model(seed=0)[0],
            prompts,
            max_new_tokens=8,
            engine_kwargs=dict(max_slots=2, block_size=4, prompt_bucket=16),
            kv_cache_dtype="int8",
            weight_only_int8=True,
        )
        assert q["tokens_compared"] >= 20
        assert q["token_match_rate"] >= 0.99, q
        assert q["max_logit_error"] <= 0.25, q
        assert q["kv_bytes_reduction"] >= 1.5, q


class TestRecoveryReplayParity:
    def test_decode_fault_replays_quantized_pool_to_parity(self):
        """A decode-step fault on the int8 engine: ONE recovery, replay
        re-prefills through the same quantize-on-write path, and the final
        streams equal the un-faulted quantized run exactly — quantization is
        deterministic per token row, so replay parity is byte parity."""
        m, cfg = _model(seed=20)
        work = _workload(cfg, 20)
        eng_a, toks_a = _run(m, work, kv_cache_dtype="int8")
        assert eng_a.stats["recoveries"] == 0

        m2, _ = _model(seed=20)
        eng_b = ContinuousBatchingEngine(
            m2, max_slots=3, block_size=4, prompt_bucket=16,
            kv_cache_dtype="int8",
        )
        rids = [eng_b.add_request(p, max_new_tokens=t) for p, t in work]
        with faults.inject(faults.FaultPlan.single("engine.decode", 3)):
            out_b = eng_b.run()
        assert eng_b.stats["recoveries"] == 1
        for ta, rb in zip(toks_a, rids):
            np.testing.assert_array_equal(ta, out_b[rb].tokens())
        # the recovered pool kept the quantized structure (and one program)
        _assert_scale_planes(eng_b)
        assert eng_b.stats["step_traces"] == 1


@pytest.mark.skipif(len(jax.devices()) < 2, reason="tp tests need >= 2 devices")
class TestTpScaleConsistency:
    def test_tp2_scale_planes_head_sharded_and_byte_consistent(self):
        """``tp=2`` over the int8 pool: the scale planes shard over the SAME
        head axis as the KV arrays (each device holds KVH/tp full scale
        rows), outputs stay byte-identical to ``tp=1``, and the GLOBAL scale
        planes are byte-identical too — head-sharding must not change a
        single quantization decision."""
        m1, cfg = _model(seed=30)
        eng1, toks1 = _run(m1, _workload(cfg, 30), kv_cache_dtype="int8")
        m2, _ = _model(seed=30)
        eng2, toks2 = _run(m2, _workload(cfg, 30), kv_cache_dtype="int8", tp=2)
        for ta, tb in zip(toks1, toks2):
            np.testing.assert_array_equal(ta, tb)
        nb, kvh, bs, hd = eng2._cache_shape
        for (kc1, vc1, ks1, vs1), (kc2, vc2, ks2, vs2) in zip(
            eng1._caches, eng2._caches
        ):
            for arr in (kc2, vc2):
                shards = {
                    s.device.id: s.data.shape for s in arr.addressable_shards
                }
                assert len(shards) == 2, shards
                for shape in shards.values():
                    assert tuple(shape) == (nb, kvh // 2, bs, hd), shards
            for sc in (ks2, vs2):
                # every device holds its head slice of the global plane,
                # BYTE-identical — sharding must never reshuffle or
                # re-derive a single scale
                g = np.asarray(sc)
                shards = list(sc.addressable_shards)
                assert len(shards) == 2, shards
                for s in shards:
                    assert tuple(s.data.shape) == (nb, kvh // 2, bs)
                    h0 = s.index[1].start or 0
                    np.testing.assert_array_equal(
                        np.asarray(s.data), g[:, h0 : h0 + kvh // 2, :]
                    )
            # across topologies the floats agree to reduction-order noise
            # (the tokens above are BYTE-identical): same quantization
            # decisions, ULP-level scale differences only
            np.testing.assert_allclose(
                np.asarray(ks1), np.asarray(ks2), rtol=1e-5, atol=1e-8
            )
            np.testing.assert_allclose(
                np.asarray(vs1), np.asarray(vs2), rtol=1e-5, atol=1e-8
            )
            # dequantized KV differs by at most one quantization step
            dk = np.abs(
                np.asarray(kc1, np.float32) * np.asarray(ks1)[..., None]
                - np.asarray(kc2, np.float32) * np.asarray(ks2)[..., None]
            )
            assert (dk <= np.asarray(ks1)[..., None] * 1.001).all()
        _assert_scale_planes(eng2)
        assert eng2.stats["step_traces"] == 1


class TestQuantDequantFaultSite:
    """``quant.dequant``: a counted degradation site INSIDE the Pallas try —
    an injected dequant failure falls back to the XLA gather for that one
    dispatch (warn_fallback-counted), and is never a recovery trigger."""

    def _setup(self, seed=60):
        from paddle_tpu.incubate.nn.functional import (
            block_multihead_chunk_attention,
        )

        rng = np.random.default_rng(seed)
        nb, hkv, bs, d, b, hq = 8, 2, 4, 16, 2, 4
        q = jnp.asarray(rng.normal(size=(b, 1, hq, d)), jnp.float32)
        k1 = jnp.asarray(rng.normal(size=(b, 1, hkv, d)), jnp.float32)
        v1 = jnp.asarray(rng.normal(size=(b, 1, hkv, d)), jnp.float32)
        kc = jnp.asarray(
            rng.integers(-127, 128, (nb, hkv, bs, d)), jnp.int8
        )
        vc = jnp.asarray(
            rng.integers(-127, 128, (nb, hkv, bs, d)), jnp.int8
        )
        ks = jnp.asarray(rng.uniform(0.5, 1.5, (nb, hkv, bs)), jnp.float32)
        vs = jnp.asarray(rng.uniform(0.5, 1.5, (nb, hkv, bs)), jnp.float32)
        tables = jnp.asarray([[2, 3], [4, 5]], jnp.int32)
        lens = jnp.asarray([5, 3], jnp.int32)
        q_lens = jnp.asarray([1, 1], jnp.int32)

        def call():
            return block_multihead_chunk_attention(
                q, k1, v1, kc, vc, tables, lens, q_lens,
                key_scale=ks, value_scale=vs,
            )

        return call

    def test_site_is_known_and_zero_cost_without_plan(self):
        assert "quant.dequant" in faults.KNOWN_SITES
        call = self._setup()
        call()  # no plan installed: one cached-bool read per dispatch
        assert faults.site_call_count("quant.dequant") == 0

    def test_injected_fault_degrades_to_xla_fallback_not_recovery(
        self, monkeypatch
    ):
        import paddle_tpu.kernels.paged_attention as pa
        import paddle_tpu.kernels.select as sel

        call = self._setup(seed=61)
        out_xla = np.asarray(call()[0])  # CPU backend: the gather fallback

        monkeypatch.setattr(sel, "pallas_enabled", lambda flag: True)
        real = pa.paged_flash_chunk
        monkeypatch.setattr(
            pa, "paged_flash_chunk",
            lambda *a, **kw: real(*a, interpret=True, **kw),
        )
        # never-firing plan proves the Pallas try actually engages (the
        # site is only declared inside it) — and the kernel stays lockstep
        with faults.inject(faults.FaultPlan.single("quant.dequant", 99)):
            out_k = np.asarray(call()[0])
            assert faults.site_call_count("quant.dequant") == 1
        np.testing.assert_allclose(out_k, out_xla, rtol=2e-5, atol=2e-5)

        prior = paddle.get_flags(["FLAGS_enable_metrics"])["FLAGS_enable_metrics"]
        paddle.set_flags({"FLAGS_enable_metrics": True})
        try:
            before = sel._fallbacks_total.value(kernel="paged_flash_chunk")
            with faults.inject(faults.FaultPlan.single("quant.dequant", 0)):
                out_f = np.asarray(call()[0])  # no exception escapes
            after = sel._fallbacks_total.value(kernel="paged_flash_chunk")
            assert after == before + 1  # the degradation is counted
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": prior})
        # the degraded dispatch IS the XLA fallback, byte for byte
        np.testing.assert_array_equal(out_f, out_xla)

    def test_engine_completes_with_zero_recoveries_under_plan(self):
        m, cfg = _model(seed=62)
        work = _workload(cfg, 62)[:3]
        eng = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, prompt_bucket=16,
            kv_cache_dtype="int8",
        )
        rids = [eng.add_request(p, max_new_tokens=t) for p, t in work]
        with faults.inject(faults.FaultPlan.single("quant.dequant", 0)):
            out = eng.run()
        assert set(out) == set(rids)
        assert eng.stats["recoveries"] == 0  # degradation, never recovery


class TestWeightOnlyInt8:
    def test_quantize_roundtrip_error_bound(self):
        rng = np.random.default_rng(70)
        w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
        w8, scale = quantize_weight_int8(w)
        assert w8.dtype == jnp.int8 and scale.shape == (32,)
        assert (np.asarray(scale) > 0).all()
        err = np.abs(np.asarray(w) - np.asarray(w8, np.float32) * np.asarray(scale)[None, :])
        # symmetric rounding: at most half an LSB per column
        assert (err <= np.asarray(scale)[None, :] * 0.5 + 1e-7).all()

    def test_int8_matmul_interpret_lockstep_with_xla(self):
        rng = np.random.default_rng(71)
        x = jnp.asarray(rng.normal(size=(8, 64)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
        w8, scale = quantize_weight_int8(w)
        out_xla = np.asarray(int8_weight_matmul(x, w8, scale))  # CPU: XLA path
        out_pal = np.asarray(int8_weight_matmul(x, w8, scale, interpret=True))
        np.testing.assert_allclose(out_pal, out_xla, rtol=1e-5, atol=1e-5)
        ref = (
            np.asarray(x) @ np.asarray(w8, np.float32)
        ) * np.asarray(scale)[None, :]
        np.testing.assert_allclose(out_xla, ref, rtol=1e-5, atol=1e-5)

    def test_quantize_module_targets_projections_only(self):
        m, cfg = _model(seed=72)
        quantized = quantize_module_weights(m)
        # 3 MLP projections per layer + the untied lm-head
        assert len(quantized) == 3 * cfg.num_hidden_layers + 1
        for layer in m.llama.layers:
            for name in ("gate_proj", "up_proj", "down_proj"):
                w = getattr(layer.mlp, name).weight
                assert w._data.dtype == jnp.int8
                assert w._quant_scale is not None
            for name in ("q_proj", "k_proj", "v_proj", "o_proj"):
                w = getattr(layer.self_attn, name).weight
                assert jnp.issubdtype(w._data.dtype, jnp.floating)
                assert getattr(w, "_quant_scale", None) is None
        assert m.lm_head.weight._data.dtype == jnp.int8
        emb = m.llama.embed_tokens.weight
        assert jnp.issubdtype(emb._data.dtype, jnp.floating)
        # idempotent: a second pass finds nothing left to quantize
        assert quantize_module_weights(m) == []

    def test_tied_and_shared_weights_never_quantized(self):
        from paddle_tpu import nn

        # llama with tied embeddings: no lm_head Parameter exists at all,
        # and the embedding weight (which feeds the token gather) stays full
        # precision
        m, cfg = _model(seed=73, tie_word_embeddings=True)
        quantized = quantize_module_weights(m)
        assert len(quantized) == 3 * cfg.num_hidden_layers  # MLP only
        emb = m.llama.embed_tokens.weight
        assert jnp.issubdtype(emb._data.dtype, jnp.floating)
        assert getattr(emb, "_quant_scale", None) is None

        # a Parameter SHARED between an lm_head and a non-target layer must
        # be skipped — the other consumer needs the full-precision array
        class _Tied(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lm_head = nn.Linear(8, 16, bias_attr=False)
                self.proj = nn.Linear(8, 16, bias_attr=False)
                self.proj.weight = self.lm_head.weight

        t = _Tied()
        assert quantize_module_weights(t) == []
        assert jnp.issubdtype(t.lm_head.weight._data.dtype, jnp.floating)

    def test_weight_only_engine_one_signature(self):
        m, cfg = _model(seed=74)
        eng, toks = _run(m, _workload(cfg, 74), weight_only_int8=True)
        assert eng._wq_params  # the engine actually quantized projections
        assert eng.stats["step_traces"] == 1
        assert all(len(t) > 0 for t in toks)

    def test_quantized_fused_loss_interpret_matches_reference(self):
        """Quantized lm-head fused loss: the interpret-mode Pallas chunk
        walk, the scan fallback (the CPU default), and a dense dequantized
        cross-entropy all agree."""
        from paddle_tpu.kernels.fused_loss import fused_linear_cross_entropy

        rng = np.random.default_rng(75)
        x = jnp.asarray(rng.normal(size=(6, 32)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(32, 64)), jnp.float32)
        w8, scale = quantize_weight_int8(w)
        labels_np = rng.integers(0, 64, (6,)).astype(np.int32)
        labels_np[2] = -100
        labels = jnp.asarray(labels_np)

        loss_scan = fused_linear_cross_entropy(
            x, w8, labels, weight_scale=scale
        )
        loss_interp = fused_linear_cross_entropy(
            x, w8, labels, weight_scale=scale, interpret=True
        )
        dense_w = w8.astype(jnp.float32) * scale[None, :]
        loss_dense = fused_linear_cross_entropy(x, dense_w, labels)
        np.testing.assert_allclose(
            np.asarray(loss_scan), np.asarray(loss_dense), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(loss_interp), np.asarray(loss_dense), rtol=1e-5, atol=1e-6
        )


class TestQuantObservability:
    def test_quant_metrics_and_pool_stats_surface(self):
        """``kv_pool_bytes_per_token`` gauge tracks the pool's accounting,
        ``kv_quant_dequant_total`` counts quantize-on-write tokens and
        dequant dispatches, and ``pool_stats``/healthz carry the dtype."""
        prior = paddle.get_flags(["FLAGS_enable_metrics"])["FLAGS_enable_metrics"]
        paddle.set_flags({"FLAGS_enable_metrics": True})
        try:
            m, cfg = _model(seed=80)
            eng = ContinuousBatchingEngine(
                m, max_slots=3, block_size=4, prompt_bucket=16,
                kv_cache_dtype="int8",
            )
            q_before = eng._metrics["kv_quant"].value(op="quant")
            d_before = eng._metrics["kv_quant"].value(op="dequant")
            for p, t in _workload(cfg, 80)[:3]:
                eng.add_request(p, max_new_tokens=t)
            eng.run()
            s = eng.pool_stats()
            assert s["kv_cache_dtype"] == "int8"
            # every prompt + generated token was quantized on write exactly
            # once; every dispatched step dequantized
            assert eng._metrics["kv_quant"].value(op="quant") > q_before
            assert eng._metrics["kv_quant"].value(op="dequant") > d_before
            assert eng._metrics["kv_bytes_per_token"].value() == s["bytes_per_token"]
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": prior})
