"""Paged (blocked) KV-cache attention (reference ``block_multihead_attention_``
fused_ops.yaml:45 / block_multi_head_attention_kernel.cu): allocator reuse,
prefill + decode parity vs dense attention, jit/donation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.incubate.nn.functional import (
    BlockKVCache,
    block_cache_prefill,
    block_multihead_attention,
)

B, HQ, HKV, D = 2, 4, 2, 8
BS = 4  # block size


def _dense_attention(q, ks, vs, lens):
    """Reference: full attention of one query over each sequence's prefix."""
    b, hq, d = q.shape[0], q.shape[2], q.shape[3]
    rep = hq // ks.shape[2]
    k = np.repeat(ks, rep, axis=2).astype(np.float32)
    v = np.repeat(vs, rep, axis=2).astype(np.float32)
    out = np.zeros((b, 1, hq, d), np.float32)
    for i in range(b):
        L = lens[i]
        qi = q[i, 0].astype(np.float32) / np.sqrt(d)  # [H, D]
        scores = np.einsum("hd,lhd->hl", qi, k[i, :L])
        probs = np.exp(scores - scores.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        out[i, 0] = np.einsum("hl,lhd->hd", probs, v[i, :L])
    return out


class TestAllocator:
    def test_alloc_grow_free_reuse(self):
        cache = BlockKVCache(num_blocks=8, block_size=BS, num_heads=HKV, head_dim=D,
                             max_blocks_per_seq=4)
        cache.allocate(seq_id=0, num_tokens=5)  # needs 2 blocks
        cache.allocate(seq_id=1, num_tokens=3)  # 1 block
        assert cache.free_blocks == 8 - 3
        assert cache.seq_len(0) == 5 and cache.seq_len(1) == 3
        cache.allocate(0, 4)  # 9 tokens -> 3 blocks
        assert cache.free_blocks == 8 - 4
        t = cache.block_table([0, 1])
        assert t.shape == (2, 4)
        # block ids are disjoint between live sequences
        used0 = set(np.asarray(t[0][:3]).tolist())
        used1 = {int(t[1][0])}
        assert used0.isdisjoint(used1)
        cache.free(0)
        assert cache.free_blocks == 8 - 1
        # freed blocks get reused
        cache.allocate(2, 12)
        assert cache.free_blocks == 8 - 4

    def test_pool_exhaustion_raises(self):
        cache = BlockKVCache(2, BS, HKV, D, max_blocks_per_seq=4)
        cache.allocate(0, 2 * BS)
        with pytest.raises(MemoryError):
            cache.allocate(1, 1)


class TestPagedAttention:
    def _setup(self, prompt_lens):
        rng = np.random.default_rng(3)
        S = max(prompt_lens)
        ks = rng.normal(size=(B, S + 8, HKV, D)).astype(np.float32)
        vs = rng.normal(size=(B, S + 8, HKV, D)).astype(np.float32)
        cache = BlockKVCache(num_blocks=16, block_size=BS, num_heads=HKV, head_dim=D,
                             max_blocks_per_seq=4, dtype=jnp.float32)
        for i, L in enumerate(prompt_lens):
            cache.allocate(i, L)
        tables = cache.block_table(range(B))
        kc, vc = block_cache_prefill(
            cache.key_cache, cache.value_cache,
            jnp.asarray(ks[:, :S]), jnp.asarray(vs[:, :S]),
            tables, jnp.asarray(prompt_lens, jnp.int32),
        )
        return rng, ks, vs, cache, tables, kc, vc

    def test_prefill_then_decode_matches_dense(self):
        prompt_lens = [5, 7]
        rng, ks, vs, cache, tables, kc, vc = self._setup(prompt_lens)
        # one decode step per sequence: new token at position prompt_len
        q = rng.normal(size=(B, 1, HQ, D)).astype(np.float32)
        new_k = np.stack([ks[i, prompt_lens[i]] for i in range(B)])[:, None]
        new_v = np.stack([vs[i, prompt_lens[i]] for i in range(B)])[:, None]
        out, kc, vc = block_multihead_attention(
            jnp.asarray(q), jnp.asarray(new_k), jnp.asarray(new_v),
            kc, vc, tables, jnp.asarray(prompt_lens, jnp.int32),
        )
        ref = _dense_attention(q, ks, vs, [l + 1 for l in prompt_lens])
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_multi_step_decode_crosses_block_boundary(self):
        prompt_lens = [3, 2]  # appending will cross the BS=4 boundary
        rng, ks, vs, cache, tables, kc, vc = self._setup(prompt_lens)
        lens = list(prompt_lens)
        for step in range(6):  # positions 3..8 / 2..7 -> into blocks 1 and 2
            for i in range(B):
                cache.allocate(i, 1)
            tables = cache.block_table(range(B))
            q = rng.normal(size=(B, 1, HQ, D)).astype(np.float32)
            new_k = np.stack([ks[i, lens[i]] for i in range(B)])[:, None]
            new_v = np.stack([vs[i, lens[i]] for i in range(B)])[:, None]
            out, kc, vc = block_multihead_attention(
                jnp.asarray(q), jnp.asarray(new_k), jnp.asarray(new_v),
                kc, vc, tables, jnp.asarray(lens, jnp.int32),
            )
            lens = [l + 1 for l in lens]
            ref = _dense_attention(q, ks, vs, lens)
            np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5,
                                       err_msg=f"step {step}")

    def test_jit_compiles_once_with_donation(self):
        prompt_lens = [4, 4]
        rng, ks, vs, cache, tables, kc, vc = self._setup(prompt_lens)
        step = jax.jit(block_multihead_attention, donate_argnums=(3, 4))
        lens = list(prompt_lens)
        for _ in range(3):
            for i in range(B):
                cache.allocate(i, 1)
            q = rng.normal(size=(B, 1, HQ, D)).astype(np.float32)
            new_k = np.stack([ks[i, lens[i]] for i in range(B)])[:, None]
            new_v = np.stack([vs[i, lens[i]] for i in range(B)])[:, None]
            out, kc, vc = step(
                jnp.asarray(q), jnp.asarray(new_k), jnp.asarray(new_v),
                kc, vc, cache.block_table(range(B)), jnp.asarray(lens, jnp.int32),
            )
            lens = [l + 1 for l in lens]
        ref = _dense_attention(q, ks, vs, lens)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_nonshared_blocks_isolated(self):
        """Writing sequence 0's tokens never touches sequence 1's blocks."""
        prompt_lens = [4, 4]
        _, ks, vs, cache, tables, kc, vc = self._setup(prompt_lens)
        before = np.asarray(kc[np.asarray(tables[1][:1])])
        cache.allocate(0, 1)
        t2 = cache.block_table(range(B))
        new_k = jnp.ones((B, 1, HKV, D), jnp.float32)
        _, kc2, _ = block_multihead_attention(
            jnp.zeros((B, 1, HQ, D), jnp.float32), new_k, new_k,
            kc, vc, t2, jnp.asarray([4, 3], jnp.int32),
        )
        # seq 1 wrote into its own block at pos 3; seq 0 into a new block.
        # positions 0..2 of seq 1's first block are untouched
        # (cache layout [NB, H, BS, D]: token positions are axis 2)
        after = np.asarray(kc2[np.asarray(t2[1][:1])])
        np.testing.assert_array_equal(before[0, :, :3], after[0, :, :3])


class TestSlotMask:
    """Ragged-batch contract for the continuous-batching engine: masked-off
    slots append nothing, attend over nothing, return zeros — XLA fallback in
    lockstep with the Pallas kernel."""

    def _setup(self, seed=9):
        rng = np.random.default_rng(seed)
        nb, mbs = 8, 2
        q = jnp.asarray(rng.normal(size=(B, 1, HQ, D)), jnp.float32)
        k1 = jnp.asarray(rng.normal(size=(B, 1, HKV, D)), jnp.float32)
        v1 = jnp.asarray(rng.normal(size=(B, 1, HKV, D)), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(nb, HKV, BS, D)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(nb, HKV, BS, D)), jnp.float32)
        # slot 1's table row deliberately ALIASES slot 0's blocks (an evicted
        # slot's zeroed row points at block ids a live sequence may own)
        tables = jnp.asarray([[2, 3], [2, 3]], jnp.int32)
        lens = jnp.asarray([5, 3], jnp.int32)
        return q, k1, v1, kc, vc, tables, lens

    def test_masked_slot_writes_nothing_returns_zeros(self):
        q, k1, v1, kc, vc, tables, lens = self._setup()
        mask = jnp.asarray([True, False])
        out, kc2, vc2 = block_multihead_attention(
            q, k1, v1, kc, vc, tables, lens, slot_mask=mask
        )
        # slot 1 returned zeros
        assert (np.asarray(out)[1] == 0.0).all()
        assert np.abs(np.asarray(out)[0]).sum() > 0
        # slot 1's append was dropped: only slot 0's position changed
        ref_kc = np.array(kc)
        ref_kc[np.asarray(tables)[0, 5 // BS], :, 5 % BS] = np.asarray(k1)[0, 0]
        np.testing.assert_array_equal(np.asarray(kc2), ref_kc)

    def test_active_mask_all_true_matches_unmasked(self):
        q, k1, v1, kc, vc, tables, lens = self._setup(seed=10)
        tables = jnp.asarray([[2, 3], [4, 5]], jnp.int32)  # disjoint this time
        out_m, kc_m, vc_m = block_multihead_attention(
            q, k1, v1, kc, vc, tables, lens, slot_mask=jnp.asarray([True, True])
        )
        out_u, kc_u, vc_u = block_multihead_attention(
            q, k1, v1, kc, vc, tables, lens
        )
        np.testing.assert_array_equal(np.asarray(out_m), np.asarray(out_u))
        np.testing.assert_array_equal(np.asarray(kc_m), np.asarray(kc_u))

    def test_kernel_lockstep_with_xla_fallback(self, monkeypatch):
        """Same inputs + slot_mask through the Pallas kernel (interpret) and
        the XLA gather path: identical zeros for the masked slot, matching
        outputs for the live one."""
        import paddle_tpu.kernels.paged_attention as pa
        import paddle_tpu.kernels.select as sel

        q, k1, v1, kc, vc, tables, lens = self._setup(seed=11)
        mask = jnp.asarray([False, True])
        out_xla, _, _ = block_multihead_attention(
            q, k1, v1, kc, vc, tables, lens, slot_mask=mask
        )
        monkeypatch.setattr(sel, "pallas_enabled", lambda flag: True)
        real = pa.paged_flash_decode
        monkeypatch.setattr(
            pa, "paged_flash_decode",
            lambda *a, **kw: real(*a, interpret=True, **kw),
        )
        out_k, _, _ = block_multihead_attention(
            q, k1, v1, kc, vc, tables, lens, slot_mask=mask
        )
        assert (np.asarray(out_k)[0] == 0.0).all()
        np.testing.assert_allclose(
            np.asarray(out_k), np.asarray(out_xla), rtol=2e-5, atol=2e-5
        )


class TestFusedDecodeWrapper:
    """``block_multihead_attention_fused``: the rope-fused counterpart of the
    decode wrapper. On a backend without the kernel, fused on/off must
    execute the SAME op composition (byte-identical outputs); with the
    kernel forced on (interpret mode), numerics stay in lockstep with the
    XLA fallback."""

    def _setup(self, seed=13):
        rng = np.random.default_rng(seed)
        nb, mbs = 8, 2
        q = jnp.asarray(rng.normal(size=(B, 1, HQ, D)), jnp.float32)
        k1 = jnp.asarray(rng.normal(size=(B, 1, HKV, D)), jnp.float32)
        v1 = jnp.asarray(rng.normal(size=(B, 1, HKV, D)), jnp.float32)
        cos = jnp.asarray(np.cos(rng.normal(size=(B, 1, 1, D))), jnp.float32)
        sin = jnp.asarray(np.sin(rng.normal(size=(B, 1, 1, D))), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(nb, HKV, BS, D)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(nb, HKV, BS, D)), jnp.float32)
        tables = jnp.asarray([[2, 3], [4, 5]], jnp.int32)
        lens = jnp.asarray([5, 3], jnp.int32)
        return q, k1, v1, cos, sin, kc, vc, tables, lens

    def test_fallback_byte_identical_to_unfused_composition(self):
        from paddle_tpu.incubate.nn.functional import (
            _rope_apply_xla,
            block_multihead_attention_fused,
        )

        q, k1, v1, cos, sin, kc, vc, tables, lens = self._setup()
        out_f, kc_f, vc_f = block_multihead_attention_fused(
            q, k1, v1, cos, sin, kc, vc, tables, lens
        )
        q_r = _rope_apply_xla(q, sin, cos, True)
        k_r = _rope_apply_xla(k1, sin, cos, True)
        out_u, kc_u, vc_u = block_multihead_attention(
            q_r, k_r, v1, kc, vc, tables, lens
        )
        np.testing.assert_array_equal(np.asarray(out_f), np.asarray(out_u))
        np.testing.assert_array_equal(np.asarray(kc_f), np.asarray(kc_u))
        np.testing.assert_array_equal(np.asarray(vc_f), np.asarray(vc_u))

    def test_kernel_lockstep_with_xla_fallback(self, monkeypatch):
        import paddle_tpu.kernels.paged_attention as pa
        import paddle_tpu.kernels.select as sel
        from paddle_tpu.incubate.nn.functional import (
            block_multihead_attention_fused,
        )

        q, k1, v1, cos, sin, kc, vc, tables, lens = self._setup(seed=14)
        mask = jnp.asarray([False, True])
        out_xla, _, _ = block_multihead_attention_fused(
            q, k1, v1, cos, sin, kc, vc, tables, lens, slot_mask=mask
        )
        monkeypatch.setattr(sel, "pallas_enabled", lambda flag: True)
        real = pa.paged_flash_decode_fused
        monkeypatch.setattr(
            pa, "paged_flash_decode_fused",
            lambda *a, **kw: real(*a, interpret=True, **kw),
        )
        out_k, _, _ = block_multihead_attention_fused(
            q, k1, v1, cos, sin, kc, vc, tables, lens, slot_mask=mask
        )
        assert (np.asarray(out_k)[0] == 0.0).all()
        assert np.abs(np.asarray(out_k)[1]).sum() > 0
        np.testing.assert_allclose(
            np.asarray(out_k), np.asarray(out_xla), rtol=2e-5, atol=2e-5
        )
