"""Op-parity audit (VERDICT r4 #3): a checked-in diff of the framework's op
surface against the reference's ``paddle/phi/ops/yaml/ops.yaml`` manifest
(466 entries, frozen in ``tests/data/ops_yaml_manifest.txt``).

Every manifest entry must be accounted for exactly one way:
  1. the op registry or a public module surface (auto-resolved),
  2. ALIASES — implemented under a different (jax-idiomatic or layered) name,
  3. DELEGATED — absorbed by the XLA/PJRT execution model with rationale
     (streams, memcpy, IR-internal creation variants, multi-tensor fusion),
  4. SKIP — a justified scope decision; the list must stay below 50 entries.

The audit fails on any unaccounted op AND on any stale entry (an alias that
stops resolving, or a skip for an op that has since been implemented).
"""

import os

import pytest

import paddle_tpu as paddle

DATA = os.path.join(os.path.dirname(__file__), "data")

# name -> dotted path under paddle_tpu (resolved and checked callable)
ALIASES = {
    # optimizers: the *_ kernel ops are the apply-step of the optimizer class
    "adadelta_": "optimizer.Adadelta", "adagrad_": "optimizer.Adagrad",
    "adam_": "optimizer.Adam", "adamax_": "optimizer.Adamax",
    "adamw_": "optimizer.AdamW", "asgd_": "optimizer.ASGD",
    "lamb_": "optimizer.Lamb", "momentum_": "optimizer.Momentum",
    "nadam_": "optimizer.NAdam", "radam_": "optimizer.RAdam",
    "rmsprop_": "optimizer.RMSProp", "rprop_": "optimizer.Rprop",
    "sgd_": "optimizer.SGD", "ftrl": "optimizer.Ftrl",
    "decayed_adagrad": "optimizer.DecayedAdagrad", "dpsgd": "optimizer.Dpsgd",
    # losses / activations named differently
    "bce_loss": "nn.functional.binary_cross_entropy",
    "kldiv_loss": "nn.functional.kl_div",
    "logsigmoid": "nn.functional.log_sigmoid",
    "tanh_shrink": "nn.functional.tanhshrink",
    "cross_entropy_with_softmax": "nn.functional.softmax_with_cross_entropy",
    # interpolation family -> one functional
    "bicubic_interp": "nn.functional.interpolate",
    "bilinear_interp": "nn.functional.interpolate",
    "linear_interp": "nn.functional.interpolate",
    "nearest_interp": "nn.functional.interpolate",
    "trilinear_interp": "nn.functional.interpolate",
    # legacy c_* collectives -> the collective API (XLA collectives underneath)
    "c_allgather": "distributed.all_gather",
    "c_allreduce_max": "distributed.all_reduce",
    "c_allreduce_min": "distributed.all_reduce",
    "c_allreduce_prod": "distributed.all_reduce",
    "c_allreduce_sum": "distributed.all_reduce",
    "c_broadcast": "distributed.broadcast",
    "c_concat": "distributed.all_gather",
    "c_reduce_sum": "distributed.reduce",
    "c_scatter": "distributed.scatter",
    # conv / pool variants are parameterizations of the base functionals
    "conv2d_transpose_bias": "nn.functional.conv2d_transpose",
    "depthwise_conv2d": "nn.functional.conv2d",
    "depthwise_conv2d_transpose": "nn.functional.conv2d_transpose",
    "max_pool2d_with_index": "nn.functional.max_pool2d",
    "max_pool3d_with_index": "nn.functional.max_pool3d",
    "pool2d": "nn.functional.avg_pool2d",
    "pool3d": "nn.functional.avg_pool3d",
    "pad3d": "pad",
    "fractional_max_pool2d": None,  # in SKIP
    # fft kernel triple -> the fft module
    "fft_c2c": "fft.fft", "fft_c2r": "fft.irfft", "fft_r2c": "fft.rfft",
    # attention kernels
    "flash_attn": "nn.functional.flash_attention",
    "memory_efficient_attention": "nn.functional.flash_attention",
    "sparse_attention": "nn.functional.flashmask_attention",
    # rnn family
    "gru": "nn.GRU", "lstm": "nn.LSTM", "cudnn_lstm": "nn.LSTM",
    "rnn": "nn.SimpleRNN", "gru_unit": "nn.GRUCell",
    "sync_batch_norm_": "nn.SyncBatchNorm",
    # misc renames / layered surfaces
    "auc": "metric.Auc",
    "accuracy_check": "allclose",
    "check_numerics": "amp.debugging.check_numerics",
    "enable_check_model_nan_inf": "amp.debugging.TensorCheckerConfig",
    "disable_check_model_nan_inf": "amp.debugging.TensorCheckerConfig",
    "check_finite_and_unscale_": "amp.GradScaler",
    "update_loss_scaling_": "amp.GradScaler",
    "matrix_rank_tol": "linalg.matrix_rank",
    "mean_all": "mean",
    "repeat_interleave_with_tensor_index": "repeat_interleave",
    "index_select_strided": "index_select",
    "split_with_num": "split",
    "shuffle_channel": "nn.functional.channel_shuffle",
    "assign_out_": "assign", "assign_value_": "assign",
    "fused_multi_transformer": "incubate.nn.FusedMultiTransformer",
    "beam_search": "generation.GenerationMixin.generate_beam",
    "moe": "incubate.nn.functional.fused_moe",
    # quantization kernel family -> the quantization module
    "fake_quantize_abs_max": "quantization.FakeQuanterWithAbsMax",
    "fake_quantize_dequantize_abs_max": "quantization.FakeQuanterWithAbsMax",
    "fake_quantize_moving_average_abs_max": "quantization.FakeQuanterWithAbsMax",
    "fake_quantize_dequantize_moving_average_abs_max": "quantization.FakeQuanterWithAbsMax",
    "fake_quantize_range_abs_max": "quantization.FakeQuanterWithAbsMax",
    "fake_channel_wise_quantize_abs_max": "quantization.FakeQuanterWithAbsMax",
    "fake_channel_wise_quantize_dequantize_abs_max": "quantization.FakeQuanterWithAbsMax",
    "fake_channel_wise_dequantize_max_abs": "quantization.dequantize_linear",
    "fake_dequantize_max_abs": "quantization.dequantize_linear",
    "dequantize_abs_max": "quantization.weight_dequantize",
}
ALIASES = {k: v for k, v in ALIASES.items() if v is not None}

# name -> rationale: absorbed by the XLA/PJRT execution model (the VERDICT's
# "yes (delegated)" category — there is nothing to call because the compiler
# or runtime owns the concern)
DELEGATED = {
    "data": "program inputs are jit arguments (no feed-var op in a traced program)",
    "depend": "XLA dataflow ordering; no explicit dependency edges needed",
    "copy_to": "jax.device_put via Tensor.to/cuda/cpu surfaces; PJRT owns placement",
    "share_data": "jax arrays are immutable aliases; sharing is the default",
    "npu_identity": "device-specific identity; XLA DCEs identities",
    "memcpy_d2h": "PJRT transfer engine (Tensor.numpy/device_get)",
    "memcpy_h2d": "PJRT transfer engine (to_tensor/device_put)",
    "trans_layout": "XLA chooses layouts; no user-visible layout transform",
    "c_identity": "identity collective for graph partitioning; GSPMD inserts its own",
    "c_sync_calc_stream": "no user-visible streams on TPU; XLA serializes per-core",
    "c_sync_comm_stream": "collective scheduling is XLA's latency-hiding pass",
    "sync_calc_stream": "same as c_sync_calc_stream",
    "merge_selected_rows": "SelectedRows grads are dense on TPU (embedding grads are scatter-adds XLA fuses)",
    "set_value_with_tensor": "Tensor.__setitem__ lowers to at[].set",
    "full_batch_size_like": "IR-internal creation variant of full",
    "full_int_array": "IR-internal constant op (jnp literal)",
    "full_with_tensor": "IR-internal creation variant of full",
    "uniform_random_batch_size_like": "IR-internal creation variant of uniform",
    "uniform_inplace": "Tensor.uniform_ method (functional rng underneath)",
    "gaussian_inplace": "Tensor.normal_ method (functional rng underneath)",
    "fused_batch_norm_act": "XLA fuses batch_norm+activation automatically",
    "fused_bn_add_activation": "XLA fuses batch_norm+add+activation automatically",
    "coalesce_tensor": "multi-tensor buffer fusion is XLA's (and donation's) job",
    "merged_adam_": "multi-tensor optimizer apply: the whole step is one XLA program",
    "merged_momentum_": "multi-tensor optimizer apply: one XLA program",
    "assign_pos": "capacity-free dropless MoE (lax.ragged_dot) needs no position bookkeeping",
    "number_count": "dropless MoE: expert counts fall out of the gather",
    "limit_by_capacity": "dropless MoE has no capacity limit",
    "prune_gate_by_capacity": "dropless MoE has no capacity pruning",
    "random_routing": "gshard MoELayer gate implements routing in-layer",
    "dequantize_log": "log-scale embedding-table quantization unused; linear dequant covers serving",
}

# name -> justification: deliberate scope decisions, kept under 50
SKIP = {
    # detection model zoo ops (anchor-era CV pipelines; the framework targets
    # the reference's training/serving core — nms/box_coder/roi_align/
    # roi_pool/matrix_nms/prior_box/box_clip ARE implemented)
    "bipartite_match": "greedy bipartite box matching (SSD-era matcher)",
    "collect_fpn_proposals": "FPN proposal collection pipeline op",
    "detection_map": "detection mAP eval op (host-side metric in practice)",
    "generate_proposals": "RPN proposal generation pipeline op",
    "multiclass_nms3": "multiclass NMS variant with per-class loops",
    "psroi_pool": "position-sensitive ROI pooling (R-FCN only)",
    "yolo_box": "YOLO decode head", "yolo_box_head": "YOLO decode head",
    "yolo_box_post": "YOLO postprocess", "yolo_loss": "YOLO training loss",
    "deformable_conv": "deformable sampling conv (irregular gather per tap)",
    "correlation": "optical-flow correlation volume (FlowNet)",
    # pre-transformer NLP / recommender legacy
    "attention_lstm": "fused legacy attention-LSTM cell",
    "batch_fc": "per-batch FC for old recommenders",
    "chunk_eval": "IOB chunking eval op",
    "crf_decoding": "linear-chain CRF decode (viterbi_decode IS implemented)",
    "ctc_align": "CTC alignment postprocess",
    "cvm": "continuous-value-model recommender op",
    "im2sequence": "OCR image-to-sequence slicing",
    "match_matrix_tensor": "text-matching bilinear op",
    "partial_concat": "recommender partial concat",
    "partial_sum": "recommender partial sum",
    "pyramid_hash": "hash-embedding for sparse recommenders",
    "rank_attention": "ranking attention for recommenders",
    "sequence_conv": "LoD-sequence conv (LoD tensors out of scope)",
    "sequence_pool": "LoD-sequence pooling (LoD tensors out of scope)",
    "shuffle_batch": "in-batch negative sampling shuffle",
    "tdm_child": "tree-based deep match traversal",
    "tdm_sampler": "tree-based deep match sampler",
    "warpctc": "CTC loss via warp-ctc (no TPU kernel; XLA CTC not ported)",
    "warprnnt": "RNN-T loss via warp-rnnt (same)",
    # host-side graph sampling (data-dependent shapes, belongs in the loader)
    "graph_khop_sampler": "k-hop neighbor sampling is host-side data prep",
    "graph_sample_neighbors": "neighbor sampling is host-side data prep",
    "reindex_graph": "graph reindexing is host-side data prep",
    "weighted_sample_neighbors": "weighted sampling is host-side data prep",
    # misc
    "calc_reduced_attn_scores": "speculative-decoding helper for a specific CUDA kernel",
    "class_center_sample": "PLSC face-recognition class sampling",
    "margin_cross_entropy": "PLSC margin softmax (model-parallel face rec)",
    "hsigmoid_loss": "hierarchical sigmoid (pre-sampled-softmax era)",
    "fractional_max_pool2d": "randomized fractional pooling (research op)",
    "fractional_max_pool3d": "randomized fractional pooling (research op)",
    "read_file": "raw file read belongs in paddle.io/vision datasets",
    "decode_jpeg": "JPEG decode belongs in the input pipeline (PIL/npy loaders)",
    "lookup_table_dequant": "quantized PS embedding table (PS is out of scope)",
    "dgc": "deep gradient compression targets slow interconnects; ICI makes it moot",
    "dgc_clip_by_norm": "dgc family (see dgc; clip_by_norm IS implemented)",
    "dgc_momentum": "dgc family (see dgc)",
    "average_accumulates_": "ModelAverage EMA swap (EMA available via optax-style user code)",
}


def _resolve(path):
    obj = paddle
    for part in path.split("."):
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


def _auto_surfaces():
    import paddle_tpu.distributed as dist
    import paddle_tpu.fft
    import paddle_tpu.incubate.nn.functional as IF
    import paddle_tpu.linalg
    import paddle_tpu.metric
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer
    import paddle_tpu.quantization
    import paddle_tpu.signal
    import paddle_tpu.sparse
    from paddle_tpu.core.tensor import Tensor

    return [paddle, F, paddle_tpu.fft, paddle_tpu.signal, paddle_tpu.sparse,
            paddle_tpu.linalg, dist, IF, Tensor, paddle_tpu.metric,
            paddle_tpu.optimizer, paddle_tpu.quantization]


def test_ops_yaml_fully_accounted():
    from paddle_tpu.ops.registry import REGISTRY

    manifest = [l.strip() for l in open(os.path.join(DATA, "ops_yaml_manifest.txt")) if l.strip()]
    assert len(manifest) == 466, "manifest must mirror ops.yaml"
    surfaces = _auto_surfaces()
    unaccounted, stale_alias, overlap = [], [], []
    for name in manifest:
        in_reg = name in REGISTRY
        auto = in_reg or any(
            callable(getattr(s, c, None)) for s in surfaces for c in {name, name.rstrip("_")}
        )
        in_alias, in_del, in_skip = name in ALIASES, name in DELEGATED, name in SKIP
        if in_alias and _resolve(ALIASES[name]) is None:
            stale_alias.append((name, ALIASES[name]))
        if in_skip and (auto or in_alias):
            overlap.append(name)  # stale skip: it exists now
        if not (auto or in_alias or in_del or in_skip):
            unaccounted.append(name)
    assert not unaccounted, f"{len(unaccounted)} ops unaccounted: {unaccounted}"
    assert not stale_alias, f"aliases no longer resolve: {stale_alias}"
    assert not overlap, f"SKIP entries that now exist (remove them): {overlap}"


def test_skip_list_bounded():
    assert len(SKIP) < 50, f"skip list has {len(SKIP)} entries; justify or implement"


def test_tensor_method_parity():
    """Methods the reference exposes on Tensor must exist as methods here,
    not only as module functions (VERDICT r4 Weak #7)."""
    from paddle_tpu.core.tensor import Tensor

    required = [
        "unique", "unique_consecutive", "nonzero", "median", "kthvalue",
        "mode", "bincount", "isin", "cumsum", "flatten", "roll",
        "index_fill", "index_fill_", "fill_diagonal", "unfold", "gammaln",
        "as_complex", "diag_embed", "reduce_as", "is_empty", "fill_",
    ]
    missing = [n for n in required if not hasattr(Tensor, n)]
    assert not missing, f"Tensor methods missing: {missing}"


def test_alias_targets_are_callable():
    bad = [(k, v) for k, v in ALIASES.items() if not callable(_resolve(v))]
    assert not bad, f"alias targets not callable: {bad}"


def test_no_double_classification():
    dup = (set(ALIASES) & set(DELEGATED)) | (set(ALIASES) & set(SKIP)) | (
        set(DELEGATED) & set(SKIP)
    )
    assert not dup, f"ops classified twice: {dup}"


SPARSE_SKIP = {
    "batch_norm_": "sparse batchnorm trains dense stats on sparse activations (3-D conv stack only)",
    "sync_batch_norm_": "see batch_norm_",
    "conv3d": "sparse 3-D submanifold conv (point-cloud stack; no TPU sparse conv kernel)",
    "conv3d_implicit_gemm": "see conv3d",
    "maxpool": "sparse 3-D maxpool (point-cloud stack)",
    "fused_attention": "sparse attention covered by dense FlashMask path",
}


def test_sparse_ops_yaml_accounted():
    import paddle_tpu.sparse as sp

    manifest = [l.strip() for l in open(os.path.join(DATA, "sparse_ops_yaml_manifest.txt")) if l.strip()]
    assert len(manifest) == 51
    methods = set(dir(sp.SparseCooTensor)) | set(dir(sp.SparseCsrTensor))
    unaccounted = []
    for name in manifest:
        ok = (
            callable(getattr(sp, name, None))
            or name in methods
            or name.rstrip("_") in methods
            or name in SPARSE_SKIP
        )
        if not ok:
            unaccounted.append(name)
    assert not unaccounted, f"sparse ops unaccounted: {unaccounted}"
    assert len(SPARSE_SKIP) < 10
    stale = [n for n in SPARSE_SKIP if callable(getattr(sp, n, None))]
    assert not stale, f"sparse skips that now exist: {stale}"
