"""Distributed checkpoint tests: sharded save, reshard-on-load across
different meshes, optimizer state round-trip, plain numpy entries.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict
from paddle_tpu.distributed.placements import Replicate, Shard


def _sharded_tensor(shape, mesh, placements, seed=0):
    paddle.seed(seed)
    t = paddle.randn(shape)
    return dist.shard_tensor(t, mesh, placements)


class TestSaveLoadRoundTrip:
    def test_replicated_round_trip(self, tmp_path):
        mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"])
        dist.set_mesh(mesh)
        t = _sharded_tensor([16, 8], mesh, [Replicate()])
        save_state_dict({"w": t}, str(tmp_path))
        target = dist.shard_tensor(paddle.zeros([16, 8]), mesh, [Replicate()])
        load_state_dict({"w": target}, str(tmp_path))
        np.testing.assert_allclose(target.numpy(), t.numpy())

    def test_sharded_round_trip(self, tmp_path):
        mesh = dist.ProcessMesh(shape=[4, 2], dim_names=["mp", "dp"])
        dist.set_mesh(mesh)
        t = _sharded_tensor([16, 8], mesh, [Shard(0), Replicate()], seed=1)
        save_state_dict({"w": t}, str(tmp_path))
        target = dist.shard_tensor(paddle.zeros([16, 8]), mesh, [Shard(0), Replicate()])
        load_state_dict({"w": target}, str(tmp_path))
        np.testing.assert_allclose(target.numpy(), t.numpy())

    def test_reshard_on_load_cross_mesh(self, tmp_path):
        # save sharded over mp=4 on dim 0, load sharded over mp=2 on dim 1
        mesh_a = dist.ProcessMesh(shape=[4, 2], dim_names=["mp", "dp"])
        t = _sharded_tensor([16, 8], mesh_a, [Shard(0), Replicate()], seed=2)
        save_state_dict({"w": t}, str(tmp_path))

        mesh_b = dist.ProcessMesh(shape=[2, 4], dim_names=["mp", "dp"])
        target = dist.shard_tensor(paddle.zeros([16, 8]), mesh_b, [Shard(1), Replicate()])
        load_state_dict({"w": target}, str(tmp_path))
        np.testing.assert_allclose(target.numpy(), t.numpy())
        # target keeps ITS sharding (dim 1 over 2 devices)
        shard_shape = target._data.addressable_shards[0].data.shape
        assert shard_shape == (16, 4)

    def test_model_and_optimizer_state(self, tmp_path):
        mesh = dist.ProcessMesh(shape=[4], dim_names=["sharding"])
        dist.set_mesh(mesh)
        paddle.seed(3)
        m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 8))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
        x = paddle.randn([4, 8])
        (m(x) ** 2).mean().backward()
        opt.step()
        opt.clear_grad()

        sd = {**m.state_dict(), **{f"opt.{k}": v for k, v in opt.state_dict().items() if hasattr(v, "_data")}}
        save_state_dict(sd, str(tmp_path))

        paddle.seed(99)
        m2 = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 8))
        sd2 = m2.state_dict()
        load_state_dict(sd2, str(tmp_path))
        for (k1, v1), (k2, v2) in zip(sorted(m.state_dict().items()), sorted(sd2.items())):
            np.testing.assert_allclose(v1.numpy(), v2.numpy(), err_msg=k1)

    def test_plain_numpy_entries(self, tmp_path):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        save_state_dict({"a": arr}, str(tmp_path))
        out = {"a": None}
        load_state_dict(out, str(tmp_path))
        np.testing.assert_array_equal(out["a"], arr)

    def test_scalar_round_trip(self, tmp_path):
        save_state_dict({"step": np.float32(7.5), "m": np.zeros((2, 2), np.float32)}, str(tmp_path))
        out = {"step": None}
        load_state_dict(out, str(tmp_path))
        assert float(out["step"]) == 7.5

    def test_resave_fewer_ranks_no_stale_mix(self, tmp_path):
        # first save leaves files; a second save into the same dir must not
        # mix with them
        save_state_dict({"a": np.ones((4, 4), np.float32)}, str(tmp_path))
        save_state_dict({"a": np.full((4, 4), 2.0, np.float32)}, str(tmp_path))
        out = {"a": None}
        load_state_dict(out, str(tmp_path))
        np.testing.assert_array_equal(out["a"], np.full((4, 4), 2.0, np.float32))

    def test_missing_tensor_raises(self, tmp_path):
        save_state_dict({"a": np.zeros(3, np.float32)}, str(tmp_path))
        with pytest.raises(KeyError):
            load_state_dict({"b": paddle.zeros([3])}, str(tmp_path))

    def test_shape_mismatch_raises(self, tmp_path):
        save_state_dict({"a": np.zeros((3, 3), np.float32)}, str(tmp_path))
        with pytest.raises(ValueError):
            load_state_dict({"a": paddle.zeros([4, 4])}, str(tmp_path))
