"""Observability layer tests: metrics registry (golden Prometheus rendering,
log-scale histogram bucket math), flag gating (disabled recording is a no-op,
env-var seeding at first read), exporters (HTTP endpoint, JSONL snapshots),
and the recompile watchdog (cause attribution through jit, budget warning).
"""

import json
import urllib.request

import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.flags import GLOBAL_FLAGS, FlagRegistry
from paddle_tpu.observability.metrics import MetricsRegistry


@pytest.fixture
def metrics_on():
    """Enable metrics for one test, reset shared state, restore after."""
    prior = paddle.get_flags(["FLAGS_enable_metrics"])["FLAGS_enable_metrics"]
    obs.GLOBAL_METRICS.reset()
    obs.GLOBAL_WATCHDOG.reset()
    paddle.set_flags({"FLAGS_enable_metrics": True})
    yield
    paddle.set_flags({"FLAGS_enable_metrics": prior})


@pytest.fixture
def metrics_off():
    prior = paddle.get_flags(["FLAGS_enable_metrics"])["FLAGS_enable_metrics"]
    paddle.set_flags({"FLAGS_enable_metrics": False})
    yield
    paddle.set_flags({"FLAGS_enable_metrics": prior})


class TestHistogramBuckets:
    def test_log_scale_bounds(self, metrics_on):
        reg = MetricsRegistry()
        h = reg.histogram("h", start=1e-3, factor=4.0, count=5)
        assert h.bounds == (1e-3, 4e-3, 16e-3, 64e-3, 256e-3)

    def test_cumulative_counts_sum_count(self, metrics_on):
        reg = MetricsRegistry()
        h = reg.histogram("h", start=1.0, factor=2.0, count=4)  # le 1,2,4,8
        for v in (0.5, 1.0, 3.0, 10.0):
            h.observe(v)
        # raw per-bucket (le semantics: 1.0 lands in the le=1 bucket)
        assert h.bucket_counts() == [2, 0, 1, 0, 1]
        assert h.count() == 4
        assert h.sum() == pytest.approx(14.5)

    def test_quantile_interpolation(self, metrics_on):
        reg = MetricsRegistry()
        h = reg.histogram("h", start=1.0, factor=2.0, count=4)
        for v in (0.5, 1.0, 3.0, 10.0):
            h.observe(v)
        # q=0.5 -> target 2 falls exactly at the le=1 bucket's upper edge
        assert h.quantile(0.5) == pytest.approx(1.0)
        # q=0.75 -> target 3: bucket (2,4], one obs -> upper edge
        assert h.quantile(0.75) == pytest.approx(4.0)
        # overflow mass resolves to the largest finite bound
        assert h.quantile(1.0) == pytest.approx(8.0)
        assert reg.histogram("empty").quantile(0.9) == 0.0

    def test_get_or_create_rejects_kind_mismatch(self, metrics_on):
        reg = MetricsRegistry()
        reg.counter("m")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("m")


class TestPrometheusGolden:
    def test_text_exposition_format(self, metrics_on):
        reg = MetricsRegistry()
        c = reg.counter("requests_total", "Total requests.", labelnames=("code",))
        c.labels(code="200").inc(3)
        c.labels(code="500").inc()
        g = reg.gauge("queue_depth", "Queue depth.")
        g.set(7)
        h = reg.histogram("latency_seconds", "Latency.", start=1.0, factor=2.0, count=4)
        for v in (0.5, 1.0, 3.0, 10.0):
            h.observe(v)
        expected = (
            "# HELP latency_seconds Latency.\n"
            "# TYPE latency_seconds histogram\n"
            'latency_seconds_bucket{le="1"} 2\n'
            'latency_seconds_bucket{le="2"} 2\n'
            'latency_seconds_bucket{le="4"} 3\n'
            'latency_seconds_bucket{le="8"} 3\n'
            'latency_seconds_bucket{le="+Inf"} 4\n'
            "latency_seconds_sum 14.5\n"
            "latency_seconds_count 4\n"
            "# HELP queue_depth Queue depth.\n"
            "# TYPE queue_depth gauge\n"
            "queue_depth 7\n"
            "# HELP requests_total Total requests.\n"
            "# TYPE requests_total counter\n"
            'requests_total{code="200"} 3\n'
            'requests_total{code="500"} 1\n'
        )
        assert reg.render_prometheus() == expected

    def test_label_escaping(self, metrics_on):
        reg = MetricsRegistry()
        reg.counter("c", labelnames=("p",)).labels(p='a"b\\c').inc()
        assert r'c{p="a\"b\\c"} 1' in reg.render_prometheus()

    def test_gauge_high_water(self, metrics_on):
        reg = MetricsRegistry()
        g = reg.gauge("util")
        for v in (0.25, 0.875, 0.5):
            g.set(v)
        assert g.value() == 0.5
        assert g.high_water() == 0.875


class TestFlagGating:
    def test_disabled_recording_is_noop(self, metrics_off):
        reg = MetricsRegistry()
        c = reg.counter("c")
        g = reg.gauge("g")
        h = reg.histogram("h")
        c.inc(5)
        g.set(3)
        h.observe(1.0)
        assert not obs.metrics_enabled()
        assert c.value() == 0.0 and g.value() == 0.0 and h.count() == 0
        assert reg.snapshot() == {}
        assert reg.render_prometheus() == ""

    def test_toggle_updates_cached_gate(self, metrics_off):
        reg = MetricsRegistry()
        c = reg.counter("c")
        paddle.set_flags({"FLAGS_enable_metrics": True})
        assert obs.metrics_enabled()
        c.inc()
        paddle.set_flags({"FLAGS_enable_metrics": False})
        c.inc()
        assert c.value() == 1.0

    def test_new_flags_are_defined(self):
        flags = paddle.get_flags(
            ["FLAGS_enable_metrics", "FLAGS_metrics_port", "FLAGS_max_compiles_per_fn"]
        )
        assert isinstance(flags["FLAGS_enable_metrics"], bool)
        assert flags["FLAGS_metrics_port"] == 0
        assert flags["FLAGS_max_compiles_per_fn"] == 16


class TestEnvSeeding:
    """FLAGS_<name> env vars seed flag values at FIRST read."""

    def test_env_seeds_global_registry_flag(self, monkeypatch):
        name = "obs_env_seed_probe"
        monkeypatch.setenv(f"FLAGS_{name}", "17")
        GLOBAL_FLAGS.define(name, int, 3, "env-seeding test probe")
        try:
            assert GLOBAL_FLAGS.get(name) == 17
        finally:
            GLOBAL_FLAGS._flags.pop(name, None)

    def test_env_seeds_each_new_flag_type(self, monkeypatch):
        reg = FlagRegistry()
        reg.define("enable_metrics", bool, False, "")
        reg.define("metrics_port", int, 0, "")
        reg.define("max_compiles_per_fn", int, 16, "")
        monkeypatch.setenv("FLAGS_enable_metrics", "true")
        monkeypatch.setenv("FLAGS_metrics_port", "9090")
        monkeypatch.setenv("FLAGS_max_compiles_per_fn", "4")
        assert reg.get("enable_metrics") is True
        assert reg.get("metrics_port") == 9090
        assert reg.get("max_compiles_per_fn") == 4

    def test_explicit_set_beats_env(self, monkeypatch):
        reg = FlagRegistry()
        reg.define("metrics_port", int, 0, "")
        reg.set("metrics_port", 7070)
        monkeypatch.setenv("FLAGS_metrics_port", "9090")
        assert reg.get("metrics_port") == 7070  # env only applies at FIRST read

    def test_on_change_fires_for_set_and_env_seed(self, monkeypatch):
        reg = FlagRegistry()
        reg.define("a", int, 0, "")
        reg.define("b", int, 0, "")
        seen = []
        reg.on_change("a", seen.append)
        reg.on_change("b", seen.append)
        reg.set("a", 5)
        monkeypatch.setenv("FLAGS_b", "7")
        reg.get("b")
        assert seen == [5, 7]


class TestExporters:
    def test_http_endpoint_serves_prometheus_text(self, metrics_on):
        obs.GLOBAL_METRICS.counter("http_probe_total", "probe").inc(2)
        srv = obs.start_metrics_server(port=0)  # ephemeral port
        try:
            port = srv.server_address[1]
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                assert r.status == 200
                assert "text/plain" in r.headers["Content-Type"]
                body = r.read().decode()
            assert "http_probe_total 2" in body
            assert "# TYPE http_probe_total counter" in body
            # only /metrics is the exposition endpoint
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=5)
            assert ei.value.code == 404
        finally:
            obs.stop_metrics_server()

    def test_server_disabled_when_flag_unset(self):
        prior = paddle.get_flags(["FLAGS_metrics_port"])["FLAGS_metrics_port"]
        paddle.set_flags({"FLAGS_metrics_port": 0})
        try:
            assert obs.start_metrics_server() is None
        finally:
            paddle.set_flags({"FLAGS_metrics_port": prior})

    def test_jsonl_snapshots_and_trace_link_events(self, metrics_on, tmp_path):
        obs.drain_trace_events()  # clear leftovers from other tests
        obs.GLOBAL_METRICS.counter("snap_probe_total").inc(3)
        path = str(tmp_path / "metrics.jsonl")
        rec1 = obs.write_snapshot_jsonl(path)
        obs.GLOBAL_METRICS.counter("snap_probe_total").inc()
        rec2 = obs.write_snapshot_jsonl(path)
        lines = open(path).read().splitlines()
        assert len(lines) == 2
        parsed = [json.loads(l) for l in lines]
        assert parsed[0]["seq"] == rec1["seq"]
        assert parsed[1]["seq"] == rec2["seq"] == rec1["seq"] + 1
        vals = [p["metrics"]["snap_probe_total"]["values"][0]["value"] for p in parsed]
        assert vals == [3.0, 4.0]
        events = obs.drain_trace_events()
        assert [e["name"] for e in events] == ["metrics_snapshot"] * 2
        assert events[0]["ph"] == "i"
        assert events[0]["args"] == {"path": path, "seq": rec1["seq"]}
        assert obs.drain_trace_events() == []  # drained exactly once


class TestRecompileWatchdog:
    def test_ledger_and_budget_warning(self, metrics_on):
        wd = obs.RecompileWatchdog(registry=MetricsRegistry())
        prior = paddle.get_flags(["FLAGS_max_compiles_per_fn"])["FLAGS_max_compiles_per_fn"]
        paddle.set_flags({"FLAGS_max_compiles_per_fn": 2})
        try:
            wd.record_compile("f", signature="[2,4]", cause=obs.CAUSE_FIRST_CALL)
            wd.record_compile("f", signature="[3,4]", cause=obs.CAUSE_NEW_SHAPE_DTYPE)
            wd.record_compile("f", signature="[5,4]", cause=obs.CAUSE_NEW_SHAPE_DTYPE)
            # budget counts RE-compiles: 2 so far, within budget 2
            with pytest.warns(obs.RecompileBudgetWarning, match="'f' recompiled 3 times"):
                wd.record_compile("f", signature="[7,4]", cause=obs.CAUSE_NEW_SHAPE_DTYPE)
            rep = wd.report()["f"]
            assert rep["count"] == 4
            assert rep["causes"] == {"first_call": 1, "new_shape_dtype": 3}
            assert rep["signatures"] == ["[2,4]", "[3,4]", "[5,4]", "[7,4]"]
            assert wd.total() == 4
        finally:
            paddle.set_flags({"FLAGS_max_compiles_per_fn": prior})

    def test_first_call_compiles_never_trip_budget(self, metrics_on):
        """Many engine/Layer instances share one fn name; their expected
        once-per-instance first traces must not fire the retrace warning."""
        import warnings

        wd = obs.RecompileWatchdog(registry=MetricsRegistry())
        prior = paddle.get_flags(["FLAGS_max_compiles_per_fn"])["FLAGS_max_compiles_per_fn"]
        paddle.set_flags({"FLAGS_max_compiles_per_fn": 2})
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("error", obs.RecompileBudgetWarning)
                for _ in range(20):
                    wd.record_compile("Engine.prefill", cause=obs.CAUSE_FIRST_CALL)
            assert wd.counts()["Engine.prefill"] == 20
        finally:
            paddle.set_flags({"FLAGS_max_compiles_per_fn": prior})

    def test_budget_zero_disables_warning(self, metrics_on):
        wd = obs.RecompileWatchdog(registry=MetricsRegistry())
        prior = paddle.get_flags(["FLAGS_max_compiles_per_fn"])["FLAGS_max_compiles_per_fn"]
        paddle.set_flags({"FLAGS_max_compiles_per_fn": 0})
        try:
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("error", obs.RecompileBudgetWarning)
                for i in range(50):
                    wd.record_compile("f", cause=obs.CAUSE_NEW_SHAPE_DTYPE)
        finally:
            paddle.set_flags({"FLAGS_max_compiles_per_fn": prior})

    def test_jit_cause_attribution(self, metrics_on):
        """StaticFunction cache misses feed the watchdog with the right
        causes: first trace, a new input-shape bucket, a train/eval flip."""
        import paddle_tpu.nn as nn

        paddle.seed(0)
        model = nn.Linear(4, 4)

        @paddle.jit.to_static
        def f(model, x):
            return model(x)

        model.train()
        f(model, paddle.randn([2, 4]))  # first_call
        f(model, paddle.randn([3, 4]))  # new_shape_dtype
        model.eval()
        f(model, paddle.randn([3, 4]))  # mode_flip
        f(model, paddle.randn([3, 4]))  # cache hit: no new compile
        rep = obs.GLOBAL_WATCHDOG.report()
        key = [k for k in rep if k.endswith(".f") or k == "f"]
        assert len(key) == 1, rep
        rec = rep[key[0]]
        assert rec["count"] == 3
        assert rec["causes"] == {
            "first_call": 1,
            "new_shape_dtype": 1,
            "mode_flip": 1,
        }
        # the gated metric counter saw the same three compiles
        c = obs.GLOBAL_METRICS.get("jit_compiles_total")
        assert c.value(fn=key[0], cause="mode_flip") == 1
        assert sum(
            v["value"]
            for v in c._snapshot_values()
            if v["labels"]["fn"] == key[0]
        ) == 3

    def test_graph_break_is_not_counted_as_compile(self, metrics_on):
        """A full_graph=False trace that graph-breaks to eager never produced
        a compiled program — the watchdog must not count it."""

        @paddle.jit.to_static(full_graph=False)
        def g(x):
            if float(x.sum()) > 0:  # concretization -> graph break
                return x + 1
            return x - 1

        with pytest.warns(UserWarning, match="graph break"):
            g(paddle.ones([2]))
        g(paddle.ones([2]))  # guard-cache hit: eager again
        assert not any(k == "g" or k.endswith(".g") for k in obs.GLOBAL_WATCHDOG.counts())


class TestCollectiveCounters:
    def test_single_process_collectives_counted(self, metrics_on):
        import paddle_tpu.distributed as dist

        t = paddle.ones([4])
        dist.all_reduce(t)
        dist.all_reduce(t)
        dist.broadcast(t, src=0)
        calls = obs.GLOBAL_METRICS.get("collective_calls_total")
        assert calls.value(op="all_reduce") == 2
        assert calls.value(op="broadcast") == 1
        secs = obs.GLOBAL_METRICS.get("collective_seconds_total")
        assert secs.value(op="all_reduce") >= 0.0

    def test_disabled_collectives_not_counted(self, metrics_off):
        import paddle_tpu.distributed as dist

        obs.GLOBAL_METRICS.reset()
        t = paddle.ones([4])
        dist.all_reduce(t)
        calls = obs.GLOBAL_METRICS.get("collective_calls_total")
        assert calls.value(op="all_reduce") == 0
