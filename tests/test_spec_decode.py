"""Speculative decoding on the one-signature engine: greedy outputs
byte-identical with speculation on vs off, exactly ONE compiled signature
with drafts riding the mixed ragged step, exact refcounted pool accounting
across accept/rewind churn, fault-degraded verification, recovery
mid-speculation, and the PR-10 follow-on — generated-token blocks
registered into the prefix cache at request finish.

Everything here runs on CPU and fast — this file is the tier-1 guard that
turns a speculation regression (token drift, rewind leak, retrace) into a
CI failure instead of a silent correctness/perf bug on TPU.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.inference.spec_decode import NGramDrafter, count_accepted
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.testing.faults import FaultPlan, inject


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


from conftest import assert_engine_pool_exact


def _assert_pool_exact(eng):
    """The shared churn invariant, plus the speculation-specific bound:
    a rewound table is never shorter than the committed tokens."""
    assert_engine_pool_exact(eng)
    for slot, req in enumerate(eng._slot_req):
        if req is not None:
            assert len(eng._blocks[slot]) * eng.block_size >= eng._ntok[slot]


def _assert_drained(eng):
    _assert_pool_exact(eng)
    s = eng.pool_stats()
    assert s["free"] + s["cached_blocks"] == s["total"], s


def _repetitive_prompts(rng, cfg, n, length=16):
    """Templated prompts (boilerplate + fill, repeated) — the drafter's
    home turf, guaranteeing the spec path actually packs drafts."""
    out = []
    template = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
    for _ in range(n):
        fill = rng.integers(0, cfg.vocab_size, (2,)).astype(np.int32)
        out.append(np.concatenate([template, fill, template, fill])[:length])
    return out


class TestDrafter:
    def test_cyclic_context_full_draft(self):
        d = NGramDrafter(3)
        ctx = np.tile(np.array([7, 11], np.int32), 20)
        draft = d.propose(ctx, 6)
        # the cycle continues: [7, 11, 7, 11, ...] after a trailing 11
        np.testing.assert_array_equal(draft, [7, 11, 7, 11, 7, 11])

    def test_no_recurrence_no_draft(self):
        d = NGramDrafter(3)
        ctx = np.arange(32, dtype=np.int32)  # every token unique
        assert d.propose(ctx, 4).size == 0

    def test_longest_ngram_wins_over_recency(self):
        d = NGramDrafter(3)
        # trailing 3-gram [1,2,3] occurs early (continues with 9);
        # the bare 1-gram [3] also occurs later (continues with 5)
        ctx = np.array([1, 2, 3, 9, 0, 3, 5, 0, 1, 2, 3], np.int32)
        np.testing.assert_array_equal(d.propose(ctx, 1), [9])

    def test_full_continuation_preferred_over_truncated(self):
        d = NGramDrafter(1)
        # the trailing 5 recurs at index 0 (full 3-token continuation) and
        # index 5 (only 2 tokens after it) — the full draft wins over the
        # more recent truncated one
        ctx = np.array([5, 1, 2, 3, 4, 5, 9, 5], np.int32)
        np.testing.assert_array_equal(d.propose(ctx, 3), [1, 2, 3])

    def test_short_context_and_zero_budget(self):
        d = NGramDrafter(3)
        assert d.propose(np.array([3], np.int32), 4).size == 0
        assert d.propose(np.array([3, 3, 3], np.int32), 0).size == 0

    def test_count_accepted(self):
        row = np.array([4, 5, 6, 7], np.int32)
        assert count_accepted(row, np.array([4, 5, 6], np.int32)) == 3
        assert count_accepted(row, np.array([4, 9, 6], np.int32)) == 1
        assert count_accepted(row, np.array([9], np.int32)) == 0
        assert count_accepted(row, np.empty((0,), np.int32)) == 0


class TestSpecParity:
    def test_greedy_byte_identical_on_vs_off(self):
        """The acceptance test: a mixed workload (repetitive + random
        prompts, staggered budgets, more requests than slots) produces the
        SAME greedy stream with speculation on and off, through exactly ONE
        compiled signature each, with the pool drained at the end."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(9)
        prompts = _repetitive_prompts(rng, cfg, 3) + [
            rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
            for n in (5, 9)
        ]
        budgets = [24, 18, 21, 8, 12]

        def run(spec):
            eng = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=32,
                prefill_chunk=8, max_model_len=128, spec_decode=spec,
            )
            rids = [
                eng.add_request(p, max_new_tokens=t)
                for p, t in zip(prompts, budgets)
            ]
            out = eng.run()
            return eng, [out[r].tokens() for r in rids]

        eng_off, toks_off = run(False)
        eng_on, toks_on = run(True)
        for a, b in zip(toks_off, toks_on):
            np.testing.assert_array_equal(a, b)
        # the workload genuinely speculated (drafts packed and some
        # accepted), and both engines compiled exactly once
        assert eng_on.stats["spec_drafted"] > 0
        assert eng_on.stats["spec_accepted"] > 0
        assert eng_on.stats["steps"] < eng_off.stats["steps"]
        assert eng_off.stats["step_traces"] == 1
        assert eng_on.stats["step_traces"] == 1
        if hasattr(eng_on._step_fn, "_cache_size"):
            assert eng_on._step_fn._cache_size() == 1
        _assert_drained(eng_off)
        _assert_drained(eng_on)

    def test_eos_respected_across_speculative_commits(self):
        """An eos that greedy decode emits mid-stream truncates identically
        with speculation on — even when the eos lands inside an accepted
        draft's bulk commit."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(9)
        prompt = _repetitive_prompts(rng, cfg, 1)[0]

        def run(spec, eos=None):
            eng = ContinuousBatchingEngine(
                m, max_slots=1, block_size=4, prompt_bucket=32,
                prefill_chunk=8, max_model_len=128, spec_decode=spec,
            )
            rid = eng.add_request(prompt, max_new_tokens=24, eos_token_id=eos)
            out = eng.run()
            _assert_drained(eng)
            return out[rid]

        probe = run(False)
        # pick an eos the stream actually emits past the first few tokens,
        # so with speculation it can fall inside a committed draft run
        eos = int(probe.generated[len(probe.generated) // 2])
        ref = run(False, eos=eos)
        spec = run(True, eos=eos)
        assert ref.finish_reason == spec.finish_reason
        np.testing.assert_array_equal(ref.tokens(), spec.tokens())
        assert spec.generated[-1] == eos or spec.finish_reason == "length"

    def test_churn_refcounts_exact_across_rewinds(self):
        """Seeded churn property test: shared-prefix prompts (cache hits +
        CoW forks) mixed with repetitive tails (drafts + rewinds) and
        mid-stream eos finishes — pool refcounts equal slot mappings + CoW
        pins + chain ownership after EVERY step."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(17)
        shared = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
        eng = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, prompt_bucket=32, num_blocks=48,
            prefill_chunk=8, max_model_len=64, spec_decode=True,
        )
        reps = _repetitive_prompts(rng, cfg, 4)
        for j in range(8):
            if j % 2 == 0:
                tail = rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)
                prompt = np.concatenate([shared, tail])
            else:
                prompt = reps[j // 2]
            eng.add_request(
                prompt,
                max_new_tokens=int(rng.integers(6, 20)),
                eos_token_id=int(rng.integers(0, cfg.vocab_size))
                if j % 3 == 0
                else None,
            )
        _assert_pool_exact(eng)
        while eng.has_work():
            eng.step()
            _assert_pool_exact(eng)
        # the run exercised the paths under test: drafts, rejections
        # (rewinds), and prefix-cache sharing
        assert eng.stats["spec_drafted"] > 0
        assert eng.stats["spec_rejected"] > 0
        assert eng.stats["prompt_tokens_reused"] > 0
        _assert_drained(eng)

    def test_speculation_respects_worst_case_reservation(self):
        """Drafts are capped at the remaining token budget, so a slot's KV
        can never transiently outgrow its worst-case reservation — a
        pool-exhaustion MemoryError mid-step would fail this test."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(9)
        # pool sized to the exact worst case of the admitted requests
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, num_blocks=12, prompt_bucket=16,
            prefill_chunk=8, max_model_len=24, spec_decode=True,
        )
        for p in _repetitive_prompts(rng, cfg, 4, length=8):
            eng.add_request(p, max_new_tokens=16)
        while eng.has_work():
            eng.step()  # MemoryError here would fail the test
            _assert_pool_exact(eng)
            for slot, req in enumerate(eng._slot_req):
                if req is not None:
                    worst = req.prompt.size + req.max_new_tokens - 1
                    assert int(eng._ntok[slot]) <= worst
        _assert_drained(eng)


class TestSpecFaults:
    def test_verify_fault_degrades_to_plain_decode(self):
        """An injected ``spec.verify`` fault must degrade that slot to
        plain decode for the step — same greedy stream, no lost tokens, no
        rewind corruption, engine fully usable after."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(9)
        prompts = _repetitive_prompts(rng, cfg, 2)

        def run(spec, plan=None):
            eng = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=32,
                prefill_chunk=8, max_model_len=128, spec_decode=spec,
            )
            rids = [eng.add_request(p, max_new_tokens=20) for p in prompts]
            if plan is not None:
                with inject(plan):
                    out = eng.run()
            else:
                out = eng.run()
            _assert_drained(eng)
            return eng, [out[r].tokens() for r in rids]

        _, ref = run(False)
        plan = FaultPlan(
            [t for i in (0, 1, 2) for t in FaultPlan.single("spec.verify", i).triggers]
        )
        eng, faulted = run(True, plan=plan)
        for a, b in zip(ref, faulted):
            np.testing.assert_array_equal(a, b)
        # the degraded steps counted their whole draft as rejected, and the
        # engine never took the recovery path (degrade is not a failure)
        assert eng.stats["spec_drafted"] > 0
        assert eng.stats["recoveries"] == 0
        assert not eng.broken

    def test_recovery_mid_speculation_replays_to_same_tokens(self):
        """A buffers-lost dispatch failure in the middle of a speculative
        workload recovers by replaying committed host truth — the final
        streams equal the unfaulted (and unspeculated) run."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(9)
        prompts = _repetitive_prompts(rng, cfg, 2)

        def run(spec, plan=None):
            eng = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=32,
                prefill_chunk=8, max_model_len=128, spec_decode=spec,
            )
            rids = [eng.add_request(p, max_new_tokens=20) for p in prompts]
            if plan is not None:
                with inject(plan):
                    out = eng.run()
            else:
                out = eng.run()
            _assert_drained(eng)
            return eng, [out[r].tokens() for r in rids]

        _, ref = run(False)
        # call 6 lands mid-decode (prompts prefill in 2 chunk steps each);
        # an InjectedFault at the dispatch site models donated-buffer loss
        eng, replayed = run(True, plan=FaultPlan.single("engine.decode", 6))
        for a, b in zip(ref, replayed):
            np.testing.assert_array_equal(a, b)
        assert eng.stats["recoveries"] == 1
        assert eng.stats["step_traces"] == 1  # recovery reused the program
        assert not eng.broken


class TestGeneratedBlockRegistration:
    def test_second_turn_maps_first_turns_generated_kv(self):
        """PR-10 follow-on: a finished request's full blocks of GENERATED
        tokens enter the prefix cache, so a multi-turn conversation's second
        turn (prompt = first prompt + reply + new text) maps the first
        turn's KV instead of recomputing it."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(5)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, prompt_bucket=64,
            prefill_chunk=8, max_model_len=128,
        )
        turn1 = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
        r1 = eng.add_request(turn1, max_new_tokens=9)
        out1 = eng.run()
        assert eng.stats["gen_blocks_registered"] > 0
        # turn 2 replays the whole first exchange plus new user text
        turn2 = np.concatenate(
            [out1[r1].tokens(), rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)]
        )
        computed_before = eng.stats["prompt_tokens_computed"]
        r2 = eng.add_request(turn2, max_new_tokens=4)
        out2 = eng.run()
        req2 = out2[r2]
        # turn 1 stored prompt(8) + 8 appended generated tokens = 4 full
        # blocks, all of which the second turn's prompt must map
        assert req2.cached_tokens >= 16
        computed = eng.stats["prompt_tokens_computed"] - computed_before
        assert computed <= turn2.size - 16 + eng.block_size
        _assert_drained(eng)

    def test_registration_matches_speculated_stream(self):
        """With speculation on, finish-time registration hashes only
        COMMITTED tokens (rewinds happened at commit time), so a second
        turn over a speculated first turn maps byte-correct KV — greedy
        outputs still identical to the unspeculated engine."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(9)
        prompt = _repetitive_prompts(rng, cfg, 1)[0]
        tail = rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)

        def two_turns(spec):
            eng = ContinuousBatchingEngine(
                m, max_slots=1, block_size=4, prompt_bucket=64,
                prefill_chunk=8, max_model_len=128, spec_decode=spec,
            )
            r1 = eng.add_request(prompt, max_new_tokens=13)
            out1 = eng.run()
            turn2 = np.concatenate([out1[r1].tokens(), tail])
            r2 = eng.add_request(turn2, max_new_tokens=6)
            out2 = eng.run()
            _assert_drained(eng)
            return out1[r1], out2[r2]

        a1, a2 = two_turns(False)
        b1, b2 = two_turns(True)
        np.testing.assert_array_equal(a1.tokens(), b1.tokens())
        np.testing.assert_array_equal(a2.tokens(), b2.tokens())
        assert b2.cached_tokens > 0


class TestSpecObservability:
    def test_metrics_counters_and_acceptance_histogram(self):
        from paddle_tpu import observability as obs

        prior = paddle.get_flags(["FLAGS_enable_metrics"])
        obs.GLOBAL_METRICS.reset()
        paddle.set_flags({"FLAGS_enable_metrics": True})
        try:
            m, cfg = _model(seed=3)
            rng = np.random.default_rng(9)
            eng = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=32,
                prefill_chunk=8, max_model_len=128, spec_decode=True,
            )
            for p in _repetitive_prompts(rng, cfg, 3):
                eng.add_request(p, max_new_tokens=16)
            eng.run()
            reg = obs.GLOBAL_METRICS
            s = eng.spec_decode_stats()
            assert s["drafted_tokens"] > 0
            assert (
                reg.get("spec_decode_drafted_tokens_total").value()
                == s["drafted_tokens"]
            )
            assert (
                reg.get("spec_decode_accepted_tokens_total").value()
                == s["accepted_tokens"]
            )
            assert (
                reg.get("spec_decode_rejected_tokens_total").value()
                == s["rejected_tokens"]
            )
            h = reg.get("spec_decode_acceptance_rate")
            assert h.count() == s["speculative_steps"] > 0
            assert s["accepted_tokens"] + s["rejected_tokens"] == s["drafted_tokens"]
            assert 0.0 <= s["acceptance_rate"] <= 1.0
        finally:
            paddle.set_flags(prior)

    def test_healthz_snapshot_surfaces_acceptance(self):
        from paddle_tpu.serving import ServingFrontend

        m, cfg = _model(seed=3)
        rng = np.random.default_rng(9)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, prompt_bucket=32,
            prefill_chunk=8, max_model_len=128, spec_decode=True,
        )
        fe = ServingFrontend(eng)
        handle = fe.submit(_repetitive_prompts(rng, cfg, 1)[0], max_new_tokens=12)
        while not handle.finished:
            fe.pump()
        snap = fe.snapshot()
        assert snap["spec_decode"]["enabled"] is True
        assert snap["spec_decode"]["drafted_tokens"] > 0
        assert 0.0 <= snap["spec_decode"]["acceptance_rate"] <= 1.0

    def test_spec_rewind_flight_events(self):
        from paddle_tpu.observability import flight_recorder as flight

        m, cfg = _model(seed=3)
        rng = np.random.default_rng(9)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, prompt_bucket=32,
            prefill_chunk=8, max_model_len=128, spec_decode=True,
        )
        for p in _repetitive_prompts(rng, cfg, 2):
            eng.add_request(p, max_new_tokens=16)
        eng.run()
        assert eng.stats["spec_rejected"] > 0
        events = [
            e
            for e in flight.get_flight_recorder().snapshot()
            if e["kind"] == "spec_rewind"
        ]
        assert events, "rejections must leave spec_rewind events in the black box"
        e = events[-1]
        assert e["drafted"] == e["accepted"] + e["rejected"]


def test_bench_spec_decode_cpu_smoke():
    """Tier-1 smoke of the guarded bench: machinery runs, honesty fields
    present (byte-identical greedy, 1 compile per engine), acceptance rate
    reported. The >= 2x speedup itself is asserted loosely (> 1.2x) to stay
    robust to CI-machine noise; the full number lands in the bench record."""
    import bench

    rec = bench._bench_spec_decode(paddle, "cpu")
    assert "error" not in rec, rec
    assert rec["greedy_identical_on_vs_off"] is True
    assert rec["compiled_signatures_per_engine"] == {"off": 1, "on": 1}
    assert 0.0 <= rec["acceptance_rate"] <= 1.0
    assert rec["steps_on"] < rec["steps_off"]
    assert rec["speedup_vs_off"] > 1.2
