"""Op-layer parity tests vs numpy references (OpTest methodology, SURVEY §4)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def _t(arr, **kw):
    return paddle.to_tensor(np.asarray(arr), **kw)


class TestMathOps:
    def test_unary_vs_numpy(self):
        x = np.random.rand(3, 4).astype(np.float32) + 0.5
        t = _t(x)
        np.testing.assert_allclose(paddle.exp(t).numpy(), np.exp(x), rtol=1e-6)
        np.testing.assert_allclose(paddle.log(t).numpy(), np.log(x), rtol=1e-6)
        np.testing.assert_allclose(paddle.sqrt(t).numpy(), np.sqrt(x), rtol=1e-6)
        np.testing.assert_allclose(paddle.rsqrt(t).numpy(), 1 / np.sqrt(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.tanh(t).numpy(), np.tanh(x), rtol=1e-6)
        np.testing.assert_allclose(paddle.floor(t).numpy(), np.floor(x))
        np.testing.assert_allclose(paddle.abs(_t(-x)).numpy(), x)

    def test_binary_broadcast(self):
        a = np.random.rand(3, 1, 4).astype(np.float32)
        b = np.random.rand(2, 4).astype(np.float32)
        np.testing.assert_allclose(
            paddle.add(_t(a), _t(b)).numpy(), a + b, rtol=1e-6
        )
        np.testing.assert_allclose(
            paddle.maximum(_t(a), _t(b)).numpy(), np.maximum(a, b)
        )

    def test_scale_clip(self):
        x = np.linspace(-2, 2, 10).astype(np.float32)
        np.testing.assert_allclose(
            paddle.scale(_t(x), scale=3.0, bias=1.0).numpy(), 3 * x + 1, rtol=1e-6
        )
        np.testing.assert_allclose(paddle.clip(_t(x), -1, 1).numpy(), np.clip(x, -1, 1))

    def test_cumsum_cumprod(self):
        x = np.random.rand(4, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.cumsum(_t(x), axis=1).numpy(), np.cumsum(x, 1), rtol=1e-5)
        np.testing.assert_allclose(paddle.cumprod(_t(x), dim=0).numpy(), np.cumprod(x, 0), rtol=1e-5)

    def test_add_n(self):
        xs = [np.random.rand(2, 2).astype(np.float32) for _ in range(3)]
        np.testing.assert_allclose(
            paddle.add_n([_t(x) for x in xs]).numpy(), sum(xs), rtol=1e-6
        )


class TestReduction:
    def test_reductions(self):
        x = np.random.rand(3, 4, 5).astype(np.float32)
        t = _t(x)
        np.testing.assert_allclose(paddle.sum(t).numpy(), x.sum(), rtol=1e-5)
        np.testing.assert_allclose(paddle.sum(t, axis=1).numpy(), x.sum(1), rtol=1e-5)
        np.testing.assert_allclose(paddle.mean(t, axis=[0, 2]).numpy(), x.mean((0, 2)), rtol=1e-5)
        np.testing.assert_allclose(paddle.max(t, axis=0, keepdim=True).numpy(), x.max(0, keepdims=True))
        np.testing.assert_allclose(paddle.prod(t, axis=2).numpy(), x.prod(2), rtol=1e-5)
        np.testing.assert_allclose(paddle.std(t).numpy(), x.std(ddof=1), rtol=1e-4)
        np.testing.assert_allclose(paddle.var(t, unbiased=False).numpy(), x.var(), rtol=1e-4)
        np.testing.assert_allclose(paddle.logsumexp(t, axis=-1).numpy(),
                                   np.log(np.exp(x).sum(-1)), rtol=1e-5)

    def test_tensor_methods(self):
        x = _t(np.arange(6, dtype=np.float32).reshape(2, 3))
        assert x.sum().item() == 15
        assert x.mean().item() == 2.5
        assert x.max().item() == 5


class TestManipulation:
    def test_reshape_family(self):
        x = _t(np.arange(24, dtype=np.float32))
        assert paddle.reshape(x, [2, 3, 4]).shape == [2, 3, 4]
        assert x.reshape([4, 6]).shape == [4, 6]
        y = x.reshape([2, 3, 4])
        assert paddle.flatten(y, 1, 2).shape == [2, 12]
        assert paddle.squeeze(y.reshape([2, 1, 12]), axis=1).shape == [2, 12]
        assert paddle.unsqueeze(x, [0, 2]).shape == [1, 24, 1]

    def test_concat_split_stack(self):
        a = _t(np.ones((2, 3), np.float32))
        b = _t(np.zeros((2, 3), np.float32))
        c = paddle.concat([a, b], axis=0)
        assert c.shape == [4, 3]
        s = paddle.stack([a, b], axis=1)
        assert s.shape == [2, 2, 3]
        parts = paddle.split(c, 2, axis=0)
        assert len(parts) == 2 and parts[0].shape == [2, 3]
        parts = paddle.split(c, [1, 3], axis=0)
        assert parts[1].shape == [3, 3]
        parts = paddle.split(c, [1, -1], axis=0)
        assert parts[1].shape == [3, 3]

    def test_gather_scatter(self):
        x = _t(np.arange(12, dtype=np.float32).reshape(4, 3))
        idx = _t(np.array([0, 2]), dtype="int32")
        g = paddle.gather(x, idx, axis=0)
        np.testing.assert_allclose(g.numpy(), x.numpy()[[0, 2]])
        upd = _t(np.full((2, 3), -1, np.float32))
        s = paddle.scatter(x, idx, upd)
        assert (s.numpy()[[0, 2]] == -1).all()

    def test_take_put_along_axis(self):
        x = _t(np.random.rand(3, 4).astype(np.float32))
        idx = _t(np.array([[0, 1, 2, 3], [3, 2, 1, 0], [0, 0, 0, 0]]), dtype="int32")
        taken = paddle.take_along_axis(x, idx, axis=1)
        np.testing.assert_allclose(taken.numpy(), np.take_along_axis(x.numpy(), idx.numpy(), 1))

    def test_tile_expand_flip_roll(self):
        x = _t(np.array([[1.0, 2.0]], np.float32))
        assert paddle.tile(x, [2, 3]).shape == [2, 6]
        assert paddle.expand(x, [4, 2]).shape == [4, 2]
        np.testing.assert_allclose(paddle.flip(x, axis=1).numpy(), [[2, 1]])
        np.testing.assert_allclose(paddle.roll(x, 1, axis=1).numpy(), [[2, 1]])

    def test_pad(self):
        x = _t(np.ones((1, 1, 2, 2), np.float32))
        p = paddle.ops.manipulation.pad(x, [1, 1, 1, 1])
        assert p.shape == [1, 1, 4, 4]
        assert p.numpy()[0, 0, 0, 0] == 0

    def test_unique_eager(self):
        x = _t(np.array([3, 1, 2, 1, 3]))
        u = paddle.ops.manipulation.unique(x)
        assert u.numpy().tolist() == [1, 2, 3]


class TestSearchSort:
    def test_argmax_topk_sort(self):
        x = np.random.rand(4, 6).astype(np.float32)
        t = _t(x)
        np.testing.assert_allclose(paddle.argmax(t, axis=1).numpy(), x.argmax(1))
        v, i = paddle.topk(t, k=3, axis=1)
        np.testing.assert_allclose(v.numpy(), -np.sort(-x, axis=1)[:, :3], rtol=1e-6)
        np.testing.assert_allclose(paddle.sort(t, axis=1).numpy(), np.sort(x, 1), rtol=1e-6)
        np.testing.assert_allclose(
            paddle.argsort(t, axis=1, descending=True).numpy(), np.argsort(-x, 1)
        )

    def test_where_nonzero(self):
        x = _t(np.array([1.0, -1.0, 2.0]))
        y = paddle.where(x > 0, x, paddle.zeros_like(x))
        np.testing.assert_allclose(y.numpy(), [1, 0, 2])
        nz = paddle.ops.search.nonzero(x > 0)
        assert nz.numpy().tolist() == [[0], [2]]


class TestLinalg:
    def test_matmul_transpose_flags(self):
        a = np.random.rand(3, 4).astype(np.float32)
        b = np.random.rand(3, 5).astype(np.float32)
        out = paddle.matmul(_t(a), _t(b), transpose_x=True)
        np.testing.assert_allclose(out.numpy(), a.T @ b, rtol=1e-5)

    def test_einsum(self):
        a = np.random.rand(2, 3).astype(np.float32)
        b = np.random.rand(3, 4).astype(np.float32)
        np.testing.assert_allclose(
            paddle.einsum("ij,jk->ik", _t(a), _t(b)).numpy(), a @ b, rtol=1e-5
        )

    def test_norm(self):
        x = np.random.rand(3, 4).astype(np.float32)
        np.testing.assert_allclose(paddle.norm(_t(x)).numpy(), np.linalg.norm(x), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.norm(_t(x), p=1, axis=1).numpy(), np.abs(x).sum(1), rtol=1e-5
        )

    def test_solve_inv_det(self):
        a = np.random.rand(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
        b = np.random.rand(3, 2).astype(np.float32)
        np.testing.assert_allclose(paddle.linalg.solve(_t(a), _t(b)).numpy(), np.linalg.solve(a, b), rtol=1e-4)
        np.testing.assert_allclose(paddle.linalg.inv(_t(a)).numpy(), np.linalg.inv(a), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(paddle.linalg.det(_t(a)).numpy(), np.linalg.det(a), rtol=1e-4)

    def test_svd_qr_cholesky(self):
        a = np.random.rand(4, 3).astype(np.float32)
        u, s, v = paddle.linalg.svd(_t(a))
        np.testing.assert_allclose((u.numpy() * s.numpy()) @ v.numpy().T, a, atol=1e-5)
        q, r = paddle.linalg.qr(_t(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-5)
        spd = a.T @ a + np.eye(3, dtype=np.float32)
        l = paddle.linalg.cholesky(_t(spd))
        np.testing.assert_allclose(l.numpy() @ l.numpy().T, spd, atol=1e-5)


class TestRandom:
    def test_seed_determinism(self):
        paddle.seed(7)
        a = paddle.randn([4, 4])
        paddle.seed(7)
        b = paddle.randn([4, 4])
        np.testing.assert_allclose(a.numpy(), b.numpy())

    def test_uniform_range(self):
        x = paddle.uniform([1000], min=2.0, max=3.0)
        assert float(x.min()) >= 2.0 and float(x.max()) <= 3.0

    def test_randperm(self):
        p = paddle.randperm(10)
        assert sorted(p.numpy().tolist()) == list(range(10))

    def test_bernoulli_multinomial(self):
        probs = paddle.full([1000], 0.3)
        draws = paddle.bernoulli(probs)
        assert 0.15 < float(draws.mean()) < 0.45
        m = paddle.multinomial(paddle.to_tensor([0.1, 0.0, 0.9]), num_samples=50, replacement=True)
        assert 1 not in m.numpy().tolist()


class TestInferMeta:
    def test_abstract_eval(self):
        from paddle_tpu.ops.registry import infer_meta

        out = infer_meta("matmul", paddle.ones([7, 3]), paddle.ones([3, 9]))
        assert tuple(out.shape) == (7, 9)


class TestR4CoverageOps:
    """r4 additions: take/renorm/tensordot/vander/trace/signbit/isin/..."""

    def test_trace_diagonal(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        np.testing.assert_allclose(paddle.trace(x).numpy(), 4.0)

    def test_take_modes(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        idx = paddle.to_tensor(np.array([[0, 5], [7, -1]], np.int32))
        np.testing.assert_allclose(
            paddle.take(x, idx, mode="wrap").numpy(), [[0, 5], [1, 5]]
        )
        np.testing.assert_allclose(
            paddle.take(x, idx, mode="clip").numpy(), [[0, 5], [5, 0]]
        )
        # default: negatives wrap once, then clamp (paddle semantics)
        np.testing.assert_allclose(paddle.take(x, idx).numpy(), [[0, 5], [5, 5]])

    def test_tensordot(self):
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        x = paddle.to_tensor(a)
        np.testing.assert_allclose(
            paddle.tensordot(x, x, axes=[[1], [1]]).numpy(), a @ a.T, rtol=1e-6
        )

    def test_renorm_caps_slices(self):
        a = np.array([[3.0, 4.0], [0.3, 0.4]], np.float32)
        out = paddle.renorm(paddle.to_tensor(a), 2.0, 0, 1.0).numpy()
        np.testing.assert_allclose(np.linalg.norm(np.asarray(out)[0]), 1.0, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(out)[1], a[1], rtol=1e-6)  # under the cap: untouched

    def test_vander_signbit_isin_negative(self):
        v = paddle.vander(paddle.to_tensor(np.array([1.0, 2.0], np.float32)), n=3)
        np.testing.assert_allclose(v.numpy(), np.vander([1.0, 2.0], 3), rtol=1e-6)
        s = paddle.signbit(paddle.to_tensor(np.array([-1.0, 2.0], np.float32)))
        np.testing.assert_array_equal(s.numpy(), [True, False])
        np.testing.assert_array_equal(
            paddle.isin(
                paddle.to_tensor(np.array([1.0, 3.0], np.float32)),
                paddle.to_tensor(np.array([3.0], np.float32)),
            ).numpy(),
            [False, True],
        )
        np.testing.assert_allclose(
            paddle.negative(paddle.to_tensor(np.array([1.0], np.float32))).numpy(), [-1.0]
        )

    def test_take_and_renorm_gradients(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        x.stop_gradient = False
        paddle.take(x, paddle.to_tensor(np.array([0, 5], np.int32))).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1, 0, 0], [0, 0, 1]])

    def test_lstsq(self):
        import paddle_tpu.linalg as L

        rng = np.random.default_rng(0)
        a = rng.normal(size=(6, 3)).astype(np.float32)
        b = rng.normal(size=(6, 2)).astype(np.float32)
        sol, res, rank, sv = L.lstsq(paddle.to_tensor(a), paddle.to_tensor(b))
        ref_sol, _res, ref_rank, ref_sv = np.linalg.lstsq(a, b, rcond=None)
        np.testing.assert_allclose(np.asarray(sol.numpy()), ref_sol, rtol=1e-3, atol=1e-4)
        assert int(rank.numpy()) == ref_rank
        np.testing.assert_allclose(np.asarray(sv.numpy()), ref_sv, rtol=1e-4)

    def test_lstsq_underdetermined_empty_residuals(self):
        import paddle_tpu.linalg as L

        rng = np.random.default_rng(1)
        a = rng.normal(size=(2, 4)).astype(np.float32)
        b = rng.normal(size=(2, 1)).astype(np.float32)
        _sol, res, _rank, _sv = L.lstsq(paddle.to_tensor(a), paddle.to_tensor(b))
        assert list(res.shape) == [0]  # numpy/reference semantics
        with pytest.raises(ValueError, match="driver"):
            L.lstsq(paddle.to_tensor(a), paddle.to_tensor(b), driver="bogus")
