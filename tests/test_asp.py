"""ASP n:m structured sparsity (reference python/paddle/incubate/asp/):
mask generation, sparsity checks, prune_model, sparsity-preserving optimizer."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate import asp

rng = np.random.default_rng(0)


class TestMasks:
    def test_mask_1d_is_2_of_4(self):
        w = rng.normal(size=(8, 16)).astype(np.float32)
        mask = asp.get_mask_1d(w, 2, 4)
        assert asp.check_mask_1d(mask, 2, 4)
        assert mask.sum() == w.size // 2  # exactly 2 of every 4 kept
        # the kept entries are the largest-|w| of each group
        groups = np.abs(w.reshape(-1, 4))
        kept = mask.reshape(-1, 4)
        for g, k in zip(groups, kept):
            assert set(np.where(k > 0)[0]) == set(np.argsort(-g, kind="stable")[:2])

    def test_mask_2d_greedy_row_and_col_budget(self):
        w = rng.normal(size=(8, 8)).astype(np.float32)
        mask = asp.get_mask_2d_greedy(w, 2, 4)
        assert asp.check_mask_2d(mask, 2, 4)
        assert not asp.check_mask_2d(np.ones((8, 8)), 2, 4)

    def test_check_rejects_dense(self):
        assert not asp.check_mask_1d(np.ones(8), 2, 4)
        assert asp.check_mask_1d(np.array([1, 1, 0, 0, 0, 1, 0, 1]), 2, 4)

    def test_density(self):
        assert asp.calculate_density(np.array([1.0, 0, 0, 2])) == 0.5


class TestPruneModel:
    def _model(self):
        paddle.seed(0)
        return paddle.nn.Sequential(
            paddle.nn.Linear(16, 32), paddle.nn.ReLU(), paddle.nn.Linear(32, 8)
        )

    def test_prunes_weights_not_biases(self):
        m = self._model()
        masks = asp.prune_model(m, 2, 4)
        named = dict(m.named_parameters())
        weight_names = [n for n in named if n.endswith("weight")]
        assert set(masks) == set(weight_names)
        for n in weight_names:
            assert asp.check_sparsity(named[n], "check_mask_1d", 2, 4)
            assert abs(asp.calculate_density(named[n]) - 0.5) < 0.01
        for n, p in named.items():
            if n.endswith("bias"):
                assert asp.calculate_density(p) >= 0.0  # untouched (no mask)
                assert n not in masks

    def test_excluded_layers(self):
        m = self._model()
        names = [n for n, _ in m.named_parameters() if n.endswith("weight")]
        asp.set_excluded_layers([names[0]])
        try:
            masks = asp.prune_model(m, 2, 4)
            assert names[0] not in masks and len(masks) == 1
        finally:
            asp.reset_excluded_layers()

    def test_sparsity_survives_training(self):
        import paddle_tpu.nn.functional as F

        m = self._model()
        opt = asp.prune_and_decorate(
            m, paddle.optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
        )
        x = paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
        y = paddle.to_tensor(rng.normal(size=(8, 8)).astype(np.float32))
        losses = []
        for _ in range(6):
            loss = F.mse_loss(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], "decorated optimizer failed to train"
        for n, p in m.named_parameters():
            if n.endswith("weight"):
                assert asp.check_sparsity(p, "check_mask_1d", 2, 4), n
                assert abs(asp.calculate_density(p) - 0.5) < 0.01

    def test_undecorated_training_breaks_sparsity(self):
        """Negative control: without the decorated optimizer the masks decay
        (Adam moments resurrect pruned weights)."""
        import paddle_tpu.nn.functional as F

        m = self._model()
        asp.prune_model(m, 2, 4)
        opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
        x = paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
        y = paddle.to_tensor(rng.normal(size=(8, 8)).astype(np.float32))
        for _ in range(3):
            loss = F.mse_loss(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        dens = [asp.calculate_density(p) for n, p in m.named_parameters() if n.endswith("weight")]
        assert any(d > 0.6 for d in dens)


def test_reference_call_order_decorate_then_prune():
    """The reference allows decorate() BEFORE prune_model(); masks must
    still be re-applied via the registry."""
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    m = paddle.nn.Sequential(paddle.nn.Linear(16, 16), paddle.nn.ReLU(),
                             paddle.nn.Linear(16, 4))
    opt = asp.decorate(paddle.optimizer.Adam(learning_rate=1e-2, parameters=m.parameters()))
    asp.prune_model(m, 2, 4)
    x = paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
    y = paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32))
    for _ in range(4):
        loss = F.mse_loss(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    for n, p in m.named_parameters():
        if n.endswith("weight"):
            assert asp.check_sparsity(p, "check_mask_1d", 2, 4), n


class TestMaskLifetime:
    """Masks live ON their Parameters (no id(param)-keyed module registry):
    a dead model's masks can never be applied to a fresh weight whose object
    id happens to collide (CPython reuses ids after GC)."""

    def _model(self):
        paddle.seed(0)
        return paddle.nn.Sequential(
            paddle.nn.Linear(16, 32), paddle.nn.ReLU(), paddle.nn.Linear(32, 8)
        )

    def test_no_module_level_registry(self):
        assert not hasattr(asp, "_MASK_REGISTRY")
        m = self._model()
        asp.prune_model(m, 2, 4)
        pruned = [p for n, p in m.named_parameters() if n.endswith("weight")]
        assert all(getattr(p, "_asp_mask", None) is not None for p in pruned)

    def test_fresh_model_after_dead_pruned_model_stays_dense(self):
        """Prune a model, drop it, GC; a NEW model's decorated optimizer must
        not sparsify anything — deterministically, whatever ids CPython
        hands out."""
        import gc

        dead = self._model()
        asp.prune_model(dead, 2, 4)
        del dead
        gc.collect()

        fresh = self._model()
        opt = asp.decorate(
            paddle.optimizer.SGD(learning_rate=0.0, parameters=fresh.parameters())
        )
        x = paddle.to_tensor(rng.normal(size=(4, 16)).astype(np.float32))
        loss = paddle.nn.functional.mse_loss(
            fresh(x), paddle.to_tensor(np.zeros((4, 8), np.float32))
        )
        loss.backward()
        opt.step()
        for n, p in fresh.named_parameters():
            if n.endswith("weight"):
                assert asp.calculate_density(p) > 0.9, n  # still dense

    def test_explicit_attach_masks_beats_later_prune_model(self):
        """attach_masks is a per-optimizer override: a prune_model that runs
        AFTERWARDS must not clobber it for this optimizer."""
        m = self._model()
        opt = asp.decorate(
            paddle.optimizer.SGD(learning_rate=0.0, parameters=m.parameters())
        )
        name = "0.weight"
        p = dict(m.named_parameters())[name]
        custom = np.zeros(tuple(p.shape), np.float32)  # adversarial: all-zero
        opt.attach_masks(m, {name: custom})
        asp.prune_model(m, 2, 4)  # later prune must not displace the override
        x = paddle.to_tensor(rng.normal(size=(4, 16)).astype(np.float32))
        loss = paddle.nn.functional.mse_loss(
            m(x), paddle.to_tensor(np.zeros((4, 8), np.float32))
        )
        loss.backward()
        opt.step()
        assert float(np.abs(p.numpy()).sum()) == 0.0  # custom mask applied

    def test_decorate_then_prune_order_still_works(self):
        m = self._model()
        opt = asp.decorate(
            paddle.optimizer.SGD(learning_rate=1e-2, parameters=m.parameters())
        )
        asp.prune_model(m, 2, 4)  # AFTER decorate — reference-allowed order
        x = paddle.to_tensor(rng.normal(size=(4, 16)).astype(np.float32))
        loss = paddle.nn.functional.mse_loss(
            m(x), paddle.to_tensor(np.zeros((4, 8), np.float32))
        )
        loss.backward()
        opt.step()
        for n, p in m.named_parameters():
            if n.endswith("weight"):
                assert asp.check_sparsity(p, "check_mask_1d", 2, 4), n
