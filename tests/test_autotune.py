"""Kernel autotune cache (reference ``phi/kernels/autotune/auto_tune_base.h:48``
+ ``cache.h:97``): benchmark-driven per-shape block-size selection."""

import json

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.kernels import autotune as at


@pytest.fixture(autouse=True)
def _clean_cache():
    at.cache.clear()
    paddle.set_flags({"FLAGS_use_kernel_autotune": False, "FLAGS_kernel_autotune_cache": ""})
    yield
    at.cache.clear()
    paddle.set_flags({"FLAGS_use_kernel_autotune": False, "FLAGS_kernel_autotune_cache": ""})


def test_disabled_returns_default():
    calls = []

    def build(cfg):
        calls.append(cfg)
        return lambda: jax.numpy.zeros(())

    got = at.autotune("k", (1, 2), [(128, 128), (256, 128)], build, default=(64, 64))
    assert got == (64, 64)
    assert calls == []  # nothing timed when disabled


def test_tuning_picks_and_caches(monkeypatch):
    paddle.set_flags({"FLAGS_use_kernel_autotune": True})
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    built = []

    def build(cfg):
        built.append(cfg)
        if cfg == "bad":
            return None  # inapplicable config is skipped

        def run():
            # make 'slow' measurably slower via a bigger computation
            n = 400 if cfg == "slow" else 8
            return jax.numpy.linalg.norm(jax.numpy.ones((n, n)) @ jax.numpy.ones((n, n)))

        return run

    got = at.autotune("flash", (2, 128), ["slow", "fast", "bad"], build, default="d")
    assert got == "fast"
    assert built == ["slow", "fast", "bad"]
    # second call: cache hit, no rebuilds
    built.clear()
    again = at.autotune("flash", (2, 128), ["slow", "fast", "bad"], build, default="d")
    assert again == "fast" and built == []
    # different key re-tunes
    at.autotune("flash", (4, 256), ["fast"], build, default="d")
    assert built == ["fast"]


def test_all_candidates_fail_falls_back(monkeypatch):
    paddle.set_flags({"FLAGS_use_kernel_autotune": True})
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def build(cfg):
        def run():
            raise RuntimeError("no lowering")

        return run

    assert at.autotune("k", (1,), ["a", "b"], build, default="dflt") == "dflt"
    # the failure is cached too (no repeated lowering attempts)
    assert at.cache.get("k", (1,)) == "dflt"


def test_json_persistence(tmp_path, monkeypatch):
    path = str(tmp_path / "tune.json")
    paddle.set_flags({"FLAGS_use_kernel_autotune": True, "FLAGS_kernel_autotune_cache": path})
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def build(cfg):
        return lambda: jax.numpy.zeros(())

    got = at.autotune("flash", (8, 64), [(128, 128)], build, default=(64, 64))
    assert got == (128, 128)
    stored = json.load(open(path))
    assert stored  # persisted
    # fresh process simulation: new cache object reads the file, skips timing
    at.cache.clear()
    built = []

    def build2(cfg):
        built.append(cfg)
        return lambda: jax.numpy.zeros(())

    again = at.autotune("flash", (8, 64), [(128, 128), (256, 256)], build2, default=(64, 64))
    assert again == (128, 128)
    assert built == []


def test_flash_attention_entry_uses_tuner(monkeypatch):
    """The public entry consults the tuner when blocks aren't pinned."""
    from paddle_tpu.kernels import flash_attention as fa

    paddle.set_flags({"FLAGS_use_kernel_autotune": True})
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    seen = {}

    def fake_autotune(kernel, key, candidates, build, default, repeats=3):
        seen["kernel"] = kernel
        seen["key"] = key
        return (256, 128)

    monkeypatch.setattr(at, "autotune", fake_autotune)
    q = jax.numpy.zeros((1, 256, 2, 64), jax.numpy.float32)
    out = fa.flash_attention_pallas(q, q, q, causal=True, interpret=True)
    assert out.shape == q.shape
    assert seen["kernel"] == "flash_attention"
    assert seen["key"][3] == 256  # sq in the cache key
