"""Tensor basics: creation, dtype, indexing, conversion, operators."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_roundtrip():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    assert str(np.dtype(x.dtype)) == "float32"
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_dtypes_and_cast():
    x = paddle.to_tensor([1, 2, 3], dtype="int64")
    y = x.astype("float32")
    assert str(np.dtype(y.dtype)) == "float32"
    z = y.astype(paddle.bfloat16)
    assert z.dtype == paddle.bfloat16


def test_arith_operators():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a**2).numpy(), [1, 4])
    np.testing.assert_allclose((2 * a).numpy(), [2, 4])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])
    np.testing.assert_allclose((1 - a).numpy(), [0, -1])


def test_comparison_returns_tensor():
    a = paddle.to_tensor([1.0, 5.0])
    b = paddle.to_tensor([3.0, 3.0])
    assert (a < b).numpy().tolist() == [True, False]
    assert (a == a).numpy().tolist() == [True, True]


def test_indexing_and_setitem():
    x = paddle.arange(12, dtype="float32").reshape([3, 4])
    row = x[1]
    np.testing.assert_allclose(row.numpy(), [4, 5, 6, 7])
    sub = x[0:2, 1:3]
    np.testing.assert_allclose(sub.numpy(), [[1, 2], [5, 6]])
    x[0, 0] = 100.0
    assert x.numpy()[0, 0] == 100.0


def test_item_and_scalar_conversion():
    x = paddle.to_tensor(3.5)
    assert x.item() == pytest.approx(3.5)
    assert float(x) == pytest.approx(3.5)
    with pytest.raises(Exception):
        bool(paddle.to_tensor([1.0, 2.0]))


def test_matmul_operator():
    a = paddle.ones([2, 3])
    b = paddle.ones([3, 4])
    c = a @ b
    assert c.shape == [2, 4]
    np.testing.assert_allclose(c.numpy(), np.full((2, 4), 3.0))


def test_clone_detach():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    d = x.detach()
    assert d.stop_gradient
    c = x.clone()
    assert not c.stop_gradient  # clone participates in autograd


def test_set_value_shape_check():
    x = paddle.zeros([2, 2])
    with pytest.raises(Exception):
        x.set_value(np.zeros((3, 3), dtype=np.float32))


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([4]).numpy().sum() == 4
    assert paddle.full([2], 7.0).numpy().tolist() == [7, 7]
    assert paddle.arange(5).numpy().tolist() == [0, 1, 2, 3, 4]
    assert paddle.eye(3).numpy().trace() == 3
    t = paddle.tril(paddle.ones([3, 3]))
    assert t.numpy()[0, 2] == 0


class TestAuxTensorTypes:
    """TensorArray (reference tensor_array.h) + SelectedRows
    (selected_rows.h:27)."""

    def test_tensor_array_write_read_stack(self):
        from paddle_tpu.framework import array_length, array_read, array_write, create_array

        arr = create_array()
        for i in range(3):
            array_write(paddle.to_tensor(np.full((2,), float(i), np.float32)), i, arr)
        assert array_length(arr) == 3
        np.testing.assert_allclose(array_read(arr, 1).numpy(), [1.0, 1.0])
        array_write(paddle.to_tensor(np.full((2,), 9.0, np.float32)), 1, arr)  # overwrite
        np.testing.assert_allclose(arr.stack().numpy(), [[0, 0], [9, 9], [2, 2]])
        with pytest.raises(IndexError):
            arr.write(7, paddle.to_tensor(np.zeros((2,), np.float32)))

    def test_selected_rows_to_dense_and_merge(self):
        from paddle_tpu import SelectedRows

        sr = SelectedRows(
            rows=np.array([1, 3, 1], np.int32),
            value=np.array([[1.0, 1.0], [2.0, 2.0], [5.0, 5.0]], np.float32),
            height=5,
        )
        assert sr.shape == [5, 2]
        dense = sr.to_dense().numpy()
        np.testing.assert_allclose(
            dense, [[0, 0], [6, 6], [0, 0], [2, 2], [0, 0]]
        )
        merged = sr.merge_rows()
        assert int(merged.rows.numpy().shape[0]) == 2
        np.testing.assert_allclose(merged.to_dense().numpy(), dense)

    def test_string_tensor(self):
        from paddle_tpu import StringTensor

        st = StringTensor([["ab", "cd"], ["e", "f"]])
        assert st.shape == [2, 2]
        assert st[0, 1] == "cd"
        row = st[0]
        assert row.shape == [2] and len(row) == 2
        assert st.numpy().dtype == object
