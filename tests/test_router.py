"""Cluster-scale serving: the prefix-affinity replica router.

The acceptance surface of ``serving/router.py`` + ``serving/cluster.py``:

- rendezvous hashing's minimal-remap property and the prefix-chain routing
  key's equality with the prefix cache's rolling digest;
- prefix affinity as a measurable property — a shared-prefix workload
  computes fewer prompt tokens and sees faster warm TTFT through affinity
  routing than through round-robin, and the affinity/spill/failover
  counters reconcile with the routing log exactly;
- replica death as a routing event: salvage, bounded deadline-aware
  re-dispatch, explicit terminals, terminal-exactly-once across failovers
  (the seeded churn property test and the kill-mid-storm acceptance test);
- drain semantics, health-probe fault degradation, flight-recorder state
  transitions, the ``router.failover`` trace span, and the all-replicas-dead
  black-box dump;
- the ``cluster_goodput_tokens_per_sec`` bench record (CPU smoke).

Everything runs on CPU with the tiny Llama config, same as test_serving.py.
Replicas share one model object (read-only at inference): identical weights
are what makes failover re-generation deterministic.
"""

import http.client
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.inference.prefix_cache import PrefixCache, chain_digest
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (
    Overloaded,
    Priority,
    ReplicaCluster,
    ReplicaRouter,
    RouterConfig,
    ServingConfig,
    ServingFrontend,
    start_serving_server,
    stop_serving_server,
)
from paddle_tpu.serving.cluster import (
    REPLICA_DEAD,
    REPLICA_DEGRADED,
    REPLICA_DRAINING,
    REPLICA_UP,
)
from paddle_tpu.serving.loadgen import (
    TrafficClass,
    measure_sustainable_rate,
    poisson_arrivals,
    run_cluster_open_loop,
)
from paddle_tpu.serving.router import rendezvous_rank
from paddle_tpu.testing import faults


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _cluster(seed=0, n=3, max_queue=8, router_cfg=None, **engine_kw):
    m, cfg = _model(seed)
    engine_kw.setdefault("max_slots", 2)
    engine_kw.setdefault("block_size", 4)
    engine_kw.setdefault("prompt_bucket", 16)

    def factory(name):
        eng = ContinuousBatchingEngine(m, **engine_kw)
        return ServingFrontend(eng, ServingConfig(max_queue=max_queue))

    cluster = ReplicaCluster(factory, [f"r{i}" for i in range(n)])
    router = ReplicaRouter(cluster, router_cfg or RouterConfig())
    return router, cluster, cfg


def _prompt(rng, cfg, n=6):
    return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)


def _drain_router(router, handles, max_iters=800):
    done = []
    for _ in range(max_iters):
        done += router.pump()
        if all(h.finished for h in handles):
            return done
    raise AssertionError(
        "requests did not reach a terminal state: "
        f"{[(h.id, h.outcome, h.replica) for h in handles]} {router.snapshot()}"
    )


# -- routing key + rendezvous hashing -----------------------------------------

class TestRoutingKey:
    def test_rendezvous_minimal_remap_on_loss(self):
        names = ["a", "b", "c", "d"]
        keys = [bytes([i, i + 1]) for i in range(64)]
        owner = {k: rendezvous_rank(k, names)[0] for k in keys}
        survivors = [n for n in names if n != "b"]
        for k in keys:
            new = rendezvous_rank(k, survivors)[0]
            if owner[k] != "b":
                # only the dead replica's share remaps — the survivors'
                # keys (and so their warm caches) are untouched
                assert new == owner[k]
            else:
                assert new in survivors

    def test_rendezvous_add_only_steals(self):
        names = ["a", "b", "c"]
        keys = [bytes([i]) for i in range(64)]
        owner = {k: rendezvous_rank(k, names)[0] for k in keys}
        grown = names + ["d"]
        stolen = 0
        for k in keys:
            new = rendezvous_rank(k, grown)[0]
            if new != owner[k]:
                assert new == "d"  # a new replica only ever takes, never shuffles
                stolen += 1
        assert 0 < stolen < len(keys)

    def test_prefix_chain_hash_matches_cache_digest_recurrence(self):
        m, cfg = _model(seed=1)
        eng = ContinuousBatchingEngine(m, max_slots=1, block_size=4, prompt_bucket=16)
        prompt = np.arange(10, dtype=np.int32)
        # the engine's routing key walks the same H(parent, tokens) chain
        # the prefix cache keys nodes by
        d = b"prefix-cache-root"
        for i in range(2):  # two full blocks of 4
            d = PrefixCache._digest(d, prompt[i * 4 : (i + 1) * 4].tobytes())
        assert eng.prefix_chain_hash(prompt) == d.hex()
        # capping at one block matches the one-block walk
        d1 = PrefixCache._digest(b"prefix-cache-root", prompt[:4].tobytes())
        assert eng.prefix_chain_hash(prompt, max_blocks=1) == d1.hex()

    def test_shared_prefix_same_key_divergent_tails(self):
        shared = np.arange(8, dtype=np.int32)
        a = np.concatenate([shared, np.asarray([90, 91, 92], np.int32)])
        b = np.concatenate([shared, np.asarray([70, 71], np.int32)])
        ka = chain_digest(a, 4, max_blocks=2)
        kb = chain_digest(b, 4, max_blocks=2)
        assert ka == kb  # tails beyond the affinity window do not scatter
        # ... but different prefixes do spread
        c = np.concatenate([shared + 1, np.asarray([90], np.int32)])
        assert chain_digest(c, 4, max_blocks=2) != ka
        # short prompts hash raw tokens (still spread, never collide to root)
        assert chain_digest(np.asarray([1, 2], np.int32), 4) != chain_digest(
            np.asarray([3], np.int32), 4
        )


# -- affinity routing ----------------------------------------------------------

class TestAffinityRouting:
    def test_shared_prefix_lands_on_one_replica_and_counters_reconcile(self):
        router, cluster, cfg = _cluster(seed=2)
        rng = np.random.default_rng(2)
        shared = _prompt(rng, cfg, 8)
        handles = []
        for _ in range(5):
            tail = _prompt(rng, cfg, 3)
            handles.append(
                router.submit(np.concatenate([shared, tail]), max_new_tokens=2)
            )
        owners = {h.replica for h in handles}
        assert len(owners) == 1  # one family, one replica
        _drain_router(router, handles)
        assert all(h.outcome == "ok" for h in handles)
        counters = router.routing_counters()
        assert counters["affinity"] == 5
        # reconciliation: every routing decision is one count + one log entry
        # (the log is a bounded window; the monotonic dispatch count is the
        # reconciliation surface)
        assert sum(counters.values()) == router.dispatch_count() == 5
        assert len(router.routing_log()) == 5

    def test_affinity_beats_round_robin_on_shared_prefix_workload(self):
        """ISSUE acceptance: prefix affinity is measurable. The same
        3-family shared-prefix workload through affinity routing vs
        round-robin: affinity computes fewer prompt tokens (each family's
        prefix computed once cluster-wide vs once per replica), shows a
        higher prefix-cache hit rate, and its warm requests see faster
        TTFT. Requests run one at a time so TTFT is step-count, not
        batching noise."""
        results = {}
        for policy in ("affinity", "round_robin"):
            router, cluster, cfg = _cluster(
                seed=3, router_cfg=RouterConfig(policy=policy)
            )
            rng = np.random.default_rng(3)  # same workload both ways
            families = [_prompt(rng, cfg, 8) for _ in range(3)]
            warm_ttfts = []
            seen_family = set()
            for i in range(18):
                # seeded family choice (NOT i % n_replicas: that would
                # accidentally align round-robin's rotation with the
                # families and hand it perfect affinity)
                fam = int(rng.integers(0, 3))
                prompt = np.concatenate(
                    [families[fam], _prompt(rng, cfg, 3)]
                )
                h = router.submit(prompt, max_new_tokens=2)
                _drain_router(router, [h])
                assert h.outcome == "ok"
                if fam in seen_family:
                    warm_ttfts.append(h.first_token_time - h.submit_time)
                seen_family.add(fam)
            computed = sum(
                r.frontend.engine.stats["prompt_tokens_computed"]
                for r in cluster
            )
            reused = sum(
                r.frontend.engine.stats["prompt_tokens_reused"]
                for r in cluster
            )
            results[policy] = {
                "computed": computed,
                "reused": reused,
                "warm_ttft_mean": sum(warm_ttfts) / len(warm_ttfts),
                "routes": router.routing_counters(),
                "log": len(router.routing_log()),
            }
        aff, rr = results["affinity"], results["round_robin"]
        # every routing decision accounted, both policies
        assert sum(aff["routes"].values()) == aff["log"] == 18
        assert rr["routes"]["round_robin"] == 18
        # the prefix is computed once per family under affinity; round-robin
        # recomputes it once per (family, replica) pair
        assert aff["computed"] < rr["computed"]
        assert aff["reused"] > rr["reused"]
        # ... which is visible as wall-clock warm-TTFT speedup
        assert aff["warm_ttft_mean"] < rr["warm_ttft_mean"], results

    def test_spill_when_affinity_target_is_shedding(self):
        # drive one replica's controller to SHEDDING through real queue
        # depth, then submit a request whose affinity key targets it
        cfg_s = ServingConfig(
            max_queue=4,
            degrade_queue_frac=(0.25, 0.1),
            shed_queue_frac=(0.5, 0.25),
        )
        m, cfg = _model(seed=4)

        def factory(name):
            eng = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=16
            )
            return ServingFrontend(eng, cfg_s)

        cluster = ReplicaCluster(factory, ["r0", "r1", "r2"])
        router = ReplicaRouter(cluster, RouterConfig())
        rng = np.random.default_rng(4)
        probe = router.submit(_prompt(rng, cfg, 8), max_new_tokens=2)
        target = cluster.replicas[probe.replica]
        # back the affinity target up until its controller latches SHEDDING
        fill = []
        while target.frontend.controller.level < 2:
            fill.append(
                target.frontend.submit(_prompt(rng, cfg, 4), max_new_tokens=6)
            )
            target.frontend.pump()
        h = router.submit(
            np.concatenate([probe.prompt[:8], _prompt(rng, cfg, 2)]),
            max_new_tokens=2,
        )
        # same affinity key, but the target is shedding: spilled elsewhere
        assert h.replica != probe.replica
        assert h.routes[0][0] == "spill"
        assert router.routing_counters()["spill"] == 1
        # router pump drives every frontend, so the direct backlog drains too
        _drain_router(router, [probe, h])
        for _ in range(500):
            if all(f.finished for f in fill):
                break
            router.pump()
        assert all(f.finished for f in fill)


# -- death as a routing event --------------------------------------------------

class TestFailover:
    def test_kill_redispatches_and_finishes_with_identical_tokens(self):
        router, cluster, cfg = _cluster(seed=5)
        rng = np.random.default_rng(5)
        prompt = _prompt(rng, cfg, 8)
        # oracle: the same prompt on a healthy cluster
        oracle = router.submit(prompt, max_new_tokens=6)
        _drain_router(router, [oracle])
        victim = router.submit(prompt, max_new_tokens=6)
        router.pump()  # dispatched, some tokens may be out
        owner = victim.replica
        cluster.replicas[owner].kill()
        _drain_router(router, [victim])
        assert victim.outcome == "ok"
        assert victim.redispatches >= 1
        assert victim.redispatches <= router.config.max_redispatch
        # failover is visible in the routes and the replica is DEAD
        assert victim.routes[-1][0] in ("failover", "affinity")
        assert cluster.replicas[owner].state == REPLICA_DEAD
        # deterministic re-generation: the client saw the same stream the
        # healthy cluster would have produced, exactly once
        assert victim.tokens() == oracle.tokens()
        assert len(victim.tokens()) == 6

    def test_salvage_delivers_results_the_dead_engine_already_finished(self):
        router, cluster, cfg = _cluster(seed=6)
        rng = np.random.default_rng(6)
        h = router.submit(_prompt(rng, cfg, 4), max_new_tokens=2)
        replica = cluster.replicas[h.replica]
        # the replica finishes the request entirely on its own pump (the
        # router has not ticked): then it dies before the router ever
        # forwards the result
        for _ in range(50):
            replica.frontend.pump()
            if h.inner.finished:
                break
        assert h.inner.outcome == "ok" and not h.finished
        replica.kill()
        _drain_router(router, [h])
        assert h.outcome == "ok" and len(h.tokens()) == 2
        assert h.redispatches == 0  # delivered, not re-dispatched
        assert router.salvaged_count() == 1

    def test_redispatch_budget_exhaustion_sheds_replica_failure(self):
        router, cluster, cfg = _cluster(
            seed=7, n=2, router_cfg=RouterConfig(max_redispatch=0)
        )
        rng = np.random.default_rng(7)
        h = router.submit(_prompt(rng, cfg, 6), max_new_tokens=8)
        router.pump()
        cluster.replicas[h.replica].kill()
        _drain_router(router, [h])
        # zero budget: the death sheds explicitly, never silently
        assert h.outcome == "replica_failure"
        assert router.shed_counters()["replica_failure"] == 1

    def test_redispatched_request_keeps_original_deadline(self):
        router, cluster, cfg = _cluster(seed=8, n=2)
        rng = np.random.default_rng(8)
        h = router.submit(_prompt(rng, cfg, 6), max_new_tokens=4, ttl_s=3600.0)
        router.pump()
        orig_deadline = h.deadline
        cluster.replicas[h.replica].kill()
        _drain_router(router, [h])
        assert h.outcome == "ok"
        assert h.deadline == orig_deadline  # failover never extends the SLO
        # the replica that finished it saw only the REMAINING budget
        assert h.result(timeout=5.0).deadline <= orig_deadline

    def test_unmakeable_deadline_sheds_at_failover(self):
        router, cluster, cfg = _cluster(
            seed=9, n=2,
            router_cfg=RouterConfig(max_redispatch=3, redispatch_backoff_s=10.0),
        )
        rng = np.random.default_rng(9)
        h = router.submit(_prompt(rng, cfg, 6), max_new_tokens=8, ttl_s=1.0)
        router.pump()
        cluster.replicas[h.replica].kill()
        # the 10s backoff lands past the 1s deadline: deadline-aware shed,
        # no healthy replica's prefill is burned on a request that cannot land
        _drain_router(router, [h])
        assert h.outcome == "deadline_failover"
        assert router.shed_counters()["deadline_failover"] == 1

    def test_revive_rejoins_the_ring_with_fresh_generation(self):
        router, cluster, cfg = _cluster(seed=10)
        rng = np.random.default_rng(10)
        h = router.submit(_prompt(rng, cfg, 6), max_new_tokens=2)
        name = h.replica
        _drain_router(router, [h])
        cluster.replicas[name].kill()
        router.pump()
        assert cluster.replicas[name].state == REPLICA_DEAD
        replica = router.revive(name)
        assert replica.state == REPLICA_UP and replica.generation == 1
        # the revived replica reclaims exactly its old rendezvous share
        h2 = router.submit(h.prompt, max_new_tokens=2)
        assert h2.replica == name
        _drain_router(router, [h2])
        assert h2.outcome == "ok"


# -- drain ---------------------------------------------------------------------

class TestDrain:
    def test_drain_stops_intake_finishes_live_then_resume(self):
        router, cluster, cfg = _cluster(seed=11)
        rng = np.random.default_rng(11)
        obs.GLOBAL_FLIGHT_RECORDER.clear()
        h = router.submit(_prompt(rng, cfg, 8), max_new_tokens=4)
        owner = h.replica
        router.drain(owner)
        assert cluster.replicas[owner].state == REPLICA_DRAINING
        # live work on the draining replica finishes normally — no shed
        _drain_router(router, [h])
        assert h.outcome == "ok" and len(h.tokens()) == 4
        # its ring share remapped: the same key routes elsewhere now
        h2 = router.submit(h.prompt, max_new_tokens=2)
        assert h2.replica != owner
        _drain_router(router, [h2])
        events = [e["kind"] for e in obs.GLOBAL_FLIGHT_RECORDER.snapshot()]
        assert "replica_drained" in events
        router.resume(owner)
        assert cluster.replicas[owner].state == REPLICA_UP
        h3 = router.submit(h.prompt, max_new_tokens=2)
        assert h3.replica == owner  # share reclaimed
        _drain_router(router, [h3])

    def test_all_replicas_draining_rejects_with_no_replicas(self):
        router, cluster, cfg = _cluster(seed=12, n=2)
        rng = np.random.default_rng(12)
        router.drain("r0")
        router.drain("r1")
        with pytest.raises(Overloaded) as ei:
            router.submit(_prompt(rng, cfg, 4), max_new_tokens=2)
        assert ei.value.reason == "no_replicas"
        assert router.shed_counters()["no_replicas"] == 1


# -- health probing + fault sites ----------------------------------------------

class TestHealthAndFaults:
    def test_sites_are_registered_for_campaigns(self):
        assert "router.dispatch" in faults.KNOWN_SITES
        assert "router.health_probe" in faults.KNOWN_SITES
        assert "replica.kill" in faults.KNOWN_SITES
        plan = faults.FaultPlan.sample(faults.KNOWN_SITES, 4, seed=9)
        assert faults.FaultPlan.parse(plan.spec()) == plan

    def test_dispatch_site_fires_before_any_state_change(self):
        router, cluster, cfg = _cluster(seed=13, n=2)
        rng = np.random.default_rng(13)
        with faults.inject(faults.FaultPlan.single("router.dispatch", 0)):
            with pytest.raises(faults.InjectedFault):
                router.submit(_prompt(rng, cfg, 4), max_new_tokens=2)
        assert router.live_requests() == []
        assert sum(router.routing_counters().values()) == 0
        # still open for business
        h = router.submit(_prompt(rng, cfg, 4), max_new_tokens=2)
        _drain_router(router, [h])
        assert h.outcome == "ok"

    def test_health_probe_fault_degrades_then_recovers(self):
        router, cluster, cfg = _cluster(seed=14, n=2)
        rng = np.random.default_rng(14)
        with faults.inject(faults.FaultPlan.single("router.health_probe", 0)):
            router.pump()
        # one failing probe suspects (DEGRADED), never kills — and the
        # replica stays routable throughout
        degraded = [r for r in cluster if r.state == REPLICA_DEGRADED]
        assert len(degraded) == 1 and degraded[0].routable
        router.pump()  # next clean probe restores UP
        assert all(r.state == REPLICA_UP for r in cluster)
        h = router.submit(_prompt(rng, cfg, 4), max_new_tokens=2)
        _drain_router(router, [h])
        assert h.outcome == "ok"

    def test_replica_kill_site_flips_frontend_to_permanent_failure(self):
        router, cluster, cfg = _cluster(seed=15, n=2)
        rng = np.random.default_rng(15)
        handles = [
            router.submit(_prompt(rng, cfg, 6), max_new_tokens=4)
            for _ in range(3)
        ]
        router.pump()
        # call_index 0: the first replica probed on the next pump dies
        with faults.inject(faults.FaultPlan.single("replica.kill", 0)):
            router.pump()
        dead = [r for r in cluster if r.state == REPLICA_DEAD]
        assert len(dead) == 1
        assert dead[0].frontend.engine.broken  # permanent, not transient
        _drain_router(router, handles)
        # death-as-routing-event end to end: every request reached an
        # explicit terminal, none silently lost
        assert all(h.outcome is not None for h in handles)
        assert all(
            h.outcome == "ok" or h.outcome in ("replica_failure",)
            for h in handles
        )


# -- observability -------------------------------------------------------------

class TestClusterObservability:
    def test_replica_state_transitions_are_flight_events(self):
        router, cluster, cfg = _cluster(seed=16, n=2)
        obs.GLOBAL_FLIGHT_RECORDER.clear()
        cluster.replicas["r0"].kill()
        router.pump()
        transitions = [
            e for e in obs.GLOBAL_FLIGHT_RECORDER.snapshot()
            if e["kind"] == "replica_state"
        ]
        assert any(
            e["replica"] == "r0" and e["to"] == REPLICA_DEAD for e in transitions
        )

    def test_all_replicas_dead_dumps_the_black_box(self, tmp_path):
        prior = paddle.get_flags(["FLAGS_flight_recorder_dir"])
        paddle.set_flags({"FLAGS_flight_recorder_dir": str(tmp_path)})
        try:
            router, cluster, cfg = _cluster(seed=17, n=2)
            for r in cluster:
                r.kill()
            router.pump()
            assert all(r.state == REPLICA_DEAD for r in cluster)
            dumps = [
                f for f in os.listdir(tmp_path)
                if "router_all_replicas_dead" in f
            ]
            assert len(dumps) == 1
            payload = json.loads((tmp_path / dumps[0]).read_text())
            kinds = [e["kind"] for e in payload["events"]]
            assert "all_replicas_dead" in kinds
        finally:
            paddle.set_flags(prior)

    def test_failover_span_shows_both_replicas_in_one_trace(self):
        prior = paddle.get_flags(["FLAGS_trace_sample_rate", "FLAGS_trace_seed"])
        paddle.set_flags(
            {"FLAGS_trace_sample_rate": 1.0, "FLAGS_trace_seed": 77}
        )
        obs.GLOBAL_TRACER.clear()
        try:
            router, cluster, cfg = _cluster(seed=18, n=2)
            rng = np.random.default_rng(18)
            h = router.submit(_prompt(rng, cfg, 6), max_new_tokens=4)
            router.pump()
            first_owner = h.replica
            cluster.replicas[first_owner].kill()
            _drain_router(router, [h])
            assert h.outcome == "ok" and h.replica != first_owner
            spans = obs.GLOBAL_TRACER.spans(trace_id=h.trace_ctx.trace_id)
            names = [s["name"] for s in spans]
            # both replicas' request trees + the failover bridge + the root,
            # all in ONE trace
            assert names.count("request") == 2
            assert "router.failover" in names
            assert "router.request" in names
            failover = next(s for s in spans if s["name"] == "router.failover")
            assert failover["attrs"]["from_replica"] == first_owner
            assert failover["attrs"]["to_replica"] == h.replica
            # the failover span and the request spans parent into the root
            root = next(s for s in spans if s["name"] == "router.request")
            assert failover["parent_id"] == root["span_id"]
            assert root["attrs"]["redispatches"] == h.redispatches
        finally:
            obs.GLOBAL_TRACER.clear()
            paddle.set_flags(prior)


# -- the seeded churn property test -------------------------------------------

class TestRouterChurnProperty:
    def test_churn_over_submit_kill_revive_drain_pump(self):
        """ISSUE satellite: N ops over submit/kill/revive/drain/pump —
        after EVERY op: each live request is owned by exactly one replica
        (and that replica's frontend agrees), terminal-exactly-once across
        failovers, re-dispatch count <= budget, and the routing counters
        account every routing decision exactly."""
        router, cluster, cfg = _cluster(
            seed=19, max_queue=6,
            router_cfg=RouterConfig(max_redispatch=2, redispatch_backoff_s=0.001),
        )
        rng = np.random.default_rng(19)
        families = [_prompt(rng, cfg, 8) for _ in range(3)]
        accepted = {}
        terminal = {}
        rejected = 0

        def note_done(handles):
            for h in handles:
                assert h.id not in terminal, "delivered twice"
                terminal[h.id] = h.outcome

        def check_invariants():
            # counters reconcile with the monotonic dispatch count after
            # every op (and with the log, which retains everything at this
            # scale)
            counters = router.routing_counters()
            assert sum(counters.values()) == router.dispatch_count()
            assert router.dispatch_count() == len(router.routing_log())
            live = router.live_requests()
            for rr in live:
                assert not rr.finished
                # owned by exactly one replica (or None only while no
                # routable failover target exists)
                if rr.replica is not None:
                    assert rr.replica in cluster.replicas
                assert rr.redispatches <= router.config.max_redispatch
                if rr.inner is not None:
                    # exactly the owner's frontend holds this inner handle
                    # (identity check: inner ids are per-engine counters and
                    # may collide numerically across replicas)
                    holders = [
                        r.name for r in cluster
                        if r.frontend._live.get(rr.inner.id) is rr.inner
                    ]
                    assert holders in ([rr.replica], []), (holders, rr.replica)
            # every terminal is explicit
            assert all(out is not None for out in terminal.values())

        for step in range(140):
            op = rng.random()
            if op < 0.45:
                fam = families[int(rng.integers(0, 3))]
                prompt = np.concatenate([fam, _prompt(rng, cfg, int(rng.integers(1, 4)))])
                ttl = None if rng.random() < 0.7 else float(rng.choice([1e-5, 3600.0]))
                try:
                    h = router.submit(
                        prompt,
                        max_new_tokens=int(rng.integers(2, 6)),
                        priority=int(rng.integers(0, 3)),
                        tenant=str(rng.choice(["a", "b"])),
                        ttl_s=ttl,
                    )
                    accepted[h.id] = h
                except Overloaded:
                    rejected += 1
            elif op < 0.75:
                note_done(router.pump())
            elif op < 0.83:
                alive = [r for r in cluster if r.alive]
                if len(alive) >= 2:
                    victim = alive[int(rng.integers(0, len(alive)))]
                    victim.kill()
            elif op < 0.90:
                dead = [r for r in cluster if r.state == REPLICA_DEAD]
                if dead:
                    router.revive(dead[int(rng.integers(0, len(dead)))].name)
            elif op < 0.95:
                routable = [r for r in cluster if r.routable]
                if len(routable) >= 2:
                    router.drain(routable[int(rng.integers(0, len(routable)))].name)
            else:
                draining = [r for r in cluster if r.state == REPLICA_DRAINING]
                if draining:
                    router.resume(draining[0].name)
            check_invariants()

        # park the cluster healthy and drain everything to terminal
        for r in cluster:
            if r.state == REPLICA_DEAD:
                router.revive(r.name)
        for r in cluster:
            if r.state == REPLICA_DRAINING:
                router.resume(r.name)
        for _ in range(1000):
            note_done(router.pump())
            check_invariants()
            if all(h.finished for h in accepted.values()):
                break
        # terminal-exactly-once, cluster-wide, nobody lost
        assert set(terminal) == set(accepted)
        outcomes = set(terminal.values())
        assert "ok" in outcomes
        # churn deep enough to exercise the failover path
        assert any(h.redispatches > 0 for h in accepted.values()) or (
            "replica_failure" in outcomes
        )
        # router sheds reconcile with router-originated terminals
        router_shed_outcomes = ("replica_failure", "deadline_failover")
        sheds = router.shed_counters()
        for reason in router_shed_outcomes:
            assert sheds.get(reason, 0) == sum(
                1 for o in terminal.values() if o == reason
            )


# -- the kill-mid-storm acceptance test ---------------------------------------

class TestKillMidStormAcceptance:
    def test_kill_mid_storm_loses_zero_requests_silently(self):
        """ISSUE acceptance: 3 replicas under calibrated 2x overload, one
        replica killed mid-storm via the fault site. Every in-flight
        request on the dead replica is either delivered (salvaged /
        re-dispatched and finished) or shed with an explicit terminal;
        terminal-exactly-once holds cluster-wide; the recompile watchdog
        still reports exactly 1 compiled signature per surviving engine."""
        obs.GLOBAL_WATCHDOG.reset()
        router, cluster, cfg = _cluster(seed=20, max_queue=6)
        # calibrate on one replica, warm the rest so the storm adds nothing
        rate = measure_sustainable_rate(
            cluster.replicas["r0"].frontend, 6, seed=20,
            prompt_len=(3, 7), max_new_tokens=(3, 8),
            vocab_size=cfg.vocab_size,
        )
        rng = np.random.default_rng(20)
        for name in ("r1", "r2"):
            fe = cluster.replicas[name].frontend
            h = fe.submit(_prompt(rng, cfg, 4), max_new_tokens=2)
            while not h.finished:
                fe.pump()
        mix = [
            TrafficClass("chat", Priority.INTERACTIVE, 1.0, (3, 7), (3, 8), 2.0),
            TrafficClass("batch", Priority.BEST_EFFORT, 1.0, (3, 7), (3, 8), 2.0),
        ]
        arrivals = poisson_arrivals(
            2.0 * 3 * rate, 36, mix, seed=21, vocab_size=cfg.vocab_size
        )
        kill_at = arrivals[len(arrivals) // 3].t
        state = {"killed": False}

        def mid_storm(router_, now):
            if not state["killed"] and now >= kill_at:
                state["killed"] = True
                faults.install_plan(faults.FaultPlan.single("replica.kill", 0))

        try:
            report = run_cluster_open_loop(
                router, arrivals, max_wall_s=90.0, on_iteration=mid_storm
            )
        finally:
            faults.install_plan(None)
        assert state["killed"]
        assert report["undelivered_arrivals"] == 0, report
        dead = [r for r in cluster if r.state == REPLICA_DEAD]
        assert len(dead) == 1  # the kill landed, exactly one replica died
        # ZERO silent losses: everything accepted reached exactly one
        # explicit terminal (accepted == in-SLO + late + explicit sheds)
        for key, pc in report["per_class"].items():
            assert (
                pc["accepted"]
                == pc["finished_in_slo"] + pc["finished_late"] + pc["shed_after_accept"]
            ), (key, pc)
        # the death was handled as a routing event: salvage or failover ran
        assert report["failovers"] + report["salvaged"] >= 1, report
        # router-originated sheds are explicit terminals, never silence
        for reason in report["router_sheds"]:
            assert reason in ("replica_failure", "deadline_failover", "no_replicas")
        # counters account every routing decision exactly
        assert sum(report["routes"].values()) == report["dispatches"]
        # 1 compiled signature per engine (3 built), zero added by the storm
        assert report["compiled_signatures_total"] == 3, report
        assert sum(report["compiles_during_run"].values()) == 0, report


# -- multi-replica HTTP mode ---------------------------------------------------

class TestClusterHTTP:
    def test_router_behind_the_http_endpoint(self):
        router, cluster, cfg = _cluster(seed=21, n=2)
        srv = start_serving_server(router, port=0)
        port = srv.server_address[1]
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request(
                "POST", "/v1/generate",
                json.dumps({"prompt": [1, 2, 3, 4], "max_new_tokens": 3}),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            body = resp.read().decode()
            conn.close()
            assert resp.status == 200
            lines = [json.loads(l) for l in body.strip().splitlines()]
            assert lines[-1]["done"] is True and lines[-1]["outcome"] == "ok"
            assert lines[-1]["tokens"] == 3
            # /healthz is the cluster view: per-replica states + counters
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            conn.request("GET", "/healthz")
            snap = json.loads(conn.getresponse().read().decode())
            conn.close()
            assert set(snap["replicas"]) == {"r0", "r1"}
            assert snap["routable_replicas"] == 2
            assert sum(snap["routes"].values()) >= 1
        finally:
            stop_serving_server(router)


# -- bench smoke ---------------------------------------------------------------

def test_bench_cluster_goodput_cpu_smoke():
    """The guarded cluster bench runs on CPU with a tiny budget and carries
    the fields reruns are compared on (ISSUE: CPU-smoked in tier-1)."""
    import bench

    rec = bench._bench_cluster_goodput(paddle, "cpu")
    assert "error" not in rec, rec
    assert rec["metric"] == "cluster_goodput_tokens_per_sec"
    assert rec["value"] >= 0
    assert rec["replicas"] == 3
    assert rec["killed_replica"] in ("r0", "r1", "r2")
    assert rec["compiled_signatures"] == 3, rec
    assert rec["compiles_during_storm"] == 0, rec
    assert set(rec["slo_attainment"]) == {
        "chat/interactive", "app/standard", "batch/best_effort"
    }
    assert set(rec["affinity_hit_rate"]) == {"before_kill", "after_kill", "overall"}
    assert rec["failovers"] + rec["salvaged"] >= 1
    assert rec["offered_rate_rps"] == pytest.approx(
        2 * 3 * rec["sustainable_rate_per_replica_rps"], rel=0.02
    )
    # fleet observability rides the storm: the monitor's state timeline is
    # part of the record, and the whole layer adds zero compiled signatures
    assert rec["one_compile_per_engine"] is True
    mon = rec["slo_monitor"]
    assert mon["final_state"] in ("ok", "warn", "page")
    assert {"time_in_warn_s", "time_in_page_s", "transitions"} <= set(mon)
    # the kill produces failovers/sheds: the monitor must have left OK at
    # some point during the storm
    assert any(e["to"] in ("warn", "page") for e in mon["transitions"]), mon
    assert rec["incidents_written"] >= 1
