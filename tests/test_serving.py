"""SLO-aware serving frontend: weighted fair admission, deadlines at every
lifecycle stage, hysteresis load shedding, the streaming HTTP endpoint, and
the overload acceptance test — arrivals at 2x the sustainable rate must be
absorbed by explicit shedding (429 / typed ``Overloaded``), never by
unbounded queue growth or recompilation.

Everything runs on CPU with the tiny Llama config, same as test_engine.py.
"""

import http.client
import json
import socket
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.inference.engine import InferenceRequest
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (
    Hysteresis,
    Overloaded,
    Priority,
    ServingConfig,
    ServingFrontend,
    WeightedFairPolicy,
    start_serving_server,
    stop_serving_server,
)
from paddle_tpu.serving.frontend import DEGRADED, NORMAL, SHEDDING
from paddle_tpu.serving.loadgen import (
    TrafficClass,
    measure_sustainable_rate,
    poisson_arrivals,
    run_open_loop,
)
from paddle_tpu.testing import faults


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _frontend(seed=0, max_queue=8, config=None, **engine_kw):
    m, cfg = _model(seed)
    engine_kw.setdefault("max_slots", 2)
    engine_kw.setdefault("block_size", 4)
    engine_kw.setdefault("prompt_bucket", 8)
    eng = ContinuousBatchingEngine(m, **engine_kw)
    fe = ServingFrontend(eng, config or ServingConfig(max_queue=max_queue))
    return fe, eng, cfg


def _drained(eng):
    """With no live work, every block is either free or retained (warm,
    reclaimable) by the prefix cache — anything else is a leak."""
    s = eng.pool_stats()
    return s["free"] + s["cached_blocks"] == s["total"]


def _prompt(rng, cfg, n=4):
    return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)


def _drain(fe, handles, max_iters=500):
    done = []
    for _ in range(max_iters):
        done += fe.pump()
        if all(h.finished for h in handles):
            return done
    raise AssertionError("requests did not reach a terminal state")


@pytest.fixture
def metrics_on():
    prior = paddle.get_flags(["FLAGS_enable_metrics"])["FLAGS_enable_metrics"]
    paddle.set_flags({"FLAGS_enable_metrics": True})
    obs.GLOBAL_METRICS.reset()
    obs.GLOBAL_WATCHDOG.reset()
    yield obs.GLOBAL_METRICS
    paddle.set_flags({"FLAGS_enable_metrics": prior})


# -- hysteresis + controller -------------------------------------------------

class TestHysteresis:
    def test_latched_thresholds(self):
        g = Hysteresis(high=0.8, low=0.4)
        assert g.update(0.7) is False  # below start: stays off
        assert g.update(0.85) is True  # crossed start
        assert g.update(0.5) is True  # between stop and start: LATCHED on
        assert g.update(0.79) is True  # still latched below start
        assert g.update(0.3) is False  # below stop: released
        assert g.update(0.5) is False  # must cross start again

    def test_start_stop_must_be_ordered(self):
        with pytest.raises(ValueError, match="low"):
            Hysteresis(high=0.4, low=0.8)

    def test_controller_levels_escalate_and_release(self):
        cfg = ServingConfig(
            max_queue=10,
            degrade_queue_frac=(0.5, 0.2),
            shed_queue_frac=(0.8, 0.4),
            degrade_util=(2.0, 2.0),  # effectively disabled
            shed_util=(2.0, 2.0),
        )
        from paddle_tpu.serving.frontend import OverloadController

        c = OverloadController(cfg)
        assert c.update(0.1, 0.0, 0.0) == NORMAL
        assert c.update(0.6, 0.0, 0.0) == DEGRADED
        assert c.update(0.9, 0.0, 0.0) == SHEDDING
        assert c.update(0.6, 0.0, 0.0) == SHEDDING  # latched: 0.6 > shed stop 0.4
        assert c.update(0.3, 0.0, 0.0) == DEGRADED  # shed released, degrade latched
        assert c.update(0.1, 0.0, 0.0) == NORMAL


# -- weighted fair scheduling ------------------------------------------------

class TestWeightedFairPolicy:
    def _reqs(self, specs):
        return [
            InferenceRequest(i, np.zeros(4, np.int32), 4, None, priority=p, tenant=t)
            for i, (p, t) in enumerate(specs)
        ]

    def test_stride_shares_converge_to_weights(self):
        pol = WeightedFairPolicy({0: 2.0, 2: 1.0})
        waiting = self._reqs([(0, "a")] * 30 + [(2, "b")] * 30)
        picks = []
        for _ in range(18):
            req = pol.select(waiting, lambda r: True)
            picks.append(req.priority)
            waiting.remove(req)
        # a sustained backlog splits admissions 2:1 between the classes
        assert picks.count(0) == 12 and picks.count(2) == 6
        # ... and best-effort is never starved outright
        assert 2 in picks[:3]

    def test_tenant_round_robin_within_class(self):
        pol = WeightedFairPolicy()
        waiting = self._reqs(
            [(1, "a"), (1, "a"), (1, "a"), (1, "b"), (1, "c")]
        )
        order = []
        while waiting:
            req = pol.select(waiting, lambda r: True)
            order.append(req.tenant)
            waiting.remove(req)
        # tenants alternate before any tenant gets a second turn
        assert order[:3] in (["a", "b", "c"], ["b", "c", "a"], ["c", "a", "b"],
                             ["a", "c", "b"], ["b", "a", "c"], ["c", "b", "a"])
        assert order.count("a") == 3

    def test_no_capacity_skipping(self):
        # the fair-share winner doesn't fit -> nothing is admitted (no
        # starvation of large requests by small ones behind them)
        pol = WeightedFairPolicy()
        waiting = self._reqs([(0, "a"), (1, "b")])
        assert pol.select(waiting, lambda r: r.priority == 1) is None

    def test_positive_weights_enforced(self):
        with pytest.raises(ValueError, match="weight"):
            WeightedFairPolicy({0: 0.0})

    def test_rejoining_class_cannot_burst_through_missed_turns(self):
        # best-effort served once early, then idle while interactive builds
        # 20 turns of pass; on rejoin it must NOT win 20 consecutive turns
        pol = WeightedFairPolicy({0: 4.0, 2: 1.0})
        be = self._reqs([(2, "b")])
        assert pol.select(be, lambda r: True).priority == 2  # early turn
        inter = self._reqs([(0, "a")] * 20)
        for _ in range(20):
            req = pol.select(inter, lambda r: True)
            assert req.priority == 0
            inter.remove(req)
        mixed = self._reqs([(0, "a")] * 12 + [(2, "b")] * 12)
        picks = []
        for _ in range(10):
            req = pol.select(mixed, lambda r: True)
            picks.append(req.priority)
            mixed.remove(req)
        # rejoin is clamped to the incumbent's pass: the 4:1 share resumes
        # immediately instead of best-effort draining its stale credit
        assert picks.count(2) <= 3, picks
        assert picks[0] == 0 or picks[1] == 0, picks


# -- intake: typed errors + bounds + degradation ------------------------------

class TestIntake:
    def test_typed_intake_errors(self):
        from paddle_tpu.inference import (
            EmptyPromptError,
            IntakeError,
            InvalidTokenBudgetError,
            PromptTooLongError,
            RequestTooLongError,
            RequestUnservableError,
        )

        m, cfg = _model(seed=6)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, num_blocks=2, prompt_bucket=8,
            max_model_len=16,
        )
        with pytest.raises(EmptyPromptError):
            eng.add_request(np.zeros((0,), np.int32))
        with pytest.raises(InvalidTokenBudgetError):
            eng.add_request(np.zeros((2,), np.int32), max_new_tokens=0)
        with pytest.raises(PromptTooLongError):
            eng.add_request(np.zeros((9,), np.int32))
        with pytest.raises(RequestTooLongError):
            eng.add_request(np.zeros((8,), np.int32), max_new_tokens=12)
        with pytest.raises(RequestUnservableError):
            eng.add_request(np.zeros((8,), np.int32), max_new_tokens=8)
        # every subclass is still a ValueError: pre-existing callers hold
        for exc in (EmptyPromptError, InvalidTokenBudgetError, PromptTooLongError,
                    RequestTooLongError, RequestUnservableError):
            assert issubclass(exc, IntakeError) and issubclass(exc, ValueError)

    def test_bounded_queue_rejects_with_retry_after(self, metrics_on):
        fe, eng, cfg = _frontend(seed=1, max_queue=2)
        rng = np.random.default_rng(1)
        fe.submit(_prompt(rng, cfg), max_new_tokens=3)
        fe.submit(_prompt(rng, cfg), max_new_tokens=3)
        with pytest.raises(Overloaded) as ei:
            fe.submit(_prompt(rng, cfg), max_new_tokens=3)
        assert ei.value.reason == "queue_full"
        assert ei.value.retry_after > 0
        assert metrics_on.get("serving_shed_total").value(reason="queue_full") == 1

    def test_shedding_rejects_best_effort_clamps_standard(self, metrics_on):
        # drive the controller to SHEDDING through real queue depth (the
        # gauge signal), then check all three per-class intake behaviors
        cfg_s = ServingConfig(
            max_queue=4,
            degrade_queue_frac=(0.25, 0.1),
            shed_queue_frac=(0.5, 0.25),
            degrade_max_new_tokens=2,
        )
        fe, eng, cfg = _frontend(seed=2, config=cfg_s)
        rng = np.random.default_rng(2)
        for _ in range(3):
            fe.submit(_prompt(rng, cfg), max_new_tokens=6)
        fe.pump()  # controller sees queue_frac >= 0.5 -> SHEDDING
        assert fe.controller.level == SHEDDING
        with pytest.raises(Overloaded) as ei:
            fe.submit(_prompt(rng, cfg), priority=Priority.BEST_EFFORT)
        assert ei.value.reason == "overload"
        assert metrics_on.get("serving_shed_total").value(reason="overload") == 1
        h_std = fe.submit(_prompt(rng, cfg), max_new_tokens=6,
                          priority=Priority.STANDARD)
        assert h_std.inner.max_new_tokens == 2 and h_std.degraded
        h_int = fe.submit(_prompt(rng, cfg), max_new_tokens=6,
                          priority=Priority.INTERACTIVE)
        assert h_int.inner.max_new_tokens == 6 and not h_int.degraded
        assert (
            metrics_on.get("serving_degraded_total").value(
                action="clamp_max_new_tokens"
            )
            == 1
        )
        _drain(fe, [h_std, h_int])

    def test_degraded_clamps_only_best_effort(self):
        cfg_s = ServingConfig(
            max_queue=8,
            degrade_queue_frac=(0.25, 0.1),
            shed_queue_frac=(0.9, 0.5),
            degrade_max_new_tokens=2,
        )
        fe, eng, cfg = _frontend(seed=3, config=cfg_s)
        rng = np.random.default_rng(3)
        for _ in range(3):
            fe.submit(_prompt(rng, cfg), max_new_tokens=6)
        fe.pump()
        assert fe.controller.level == DEGRADED
        h_be = fe.submit(_prompt(rng, cfg), max_new_tokens=6,
                         priority=Priority.BEST_EFFORT)
        h_std = fe.submit(_prompt(rng, cfg), max_new_tokens=6,
                          priority=Priority.STANDARD)
        assert h_be.inner.max_new_tokens == 2 and h_be.degraded
        assert h_std.inner.max_new_tokens == 6 and not h_std.degraded
        _drain(fe, [h_be, h_std])


# -- deadlines at every lifecycle stage ---------------------------------------

class TestDeadlines:
    def test_queued_expiry_sheds_before_prefill(self, metrics_on):
        fe, eng, cfg = _frontend(seed=4, max_queue=16)
        rng = np.random.default_rng(4)
        # one long request occupies both slots' worth of admissions slowly;
        # the TTL'd ones behind it expire while queued
        keeper = fe.submit(_prompt(rng, cfg), max_new_tokens=8)
        doomed = [
            fe.submit(_prompt(rng, cfg), max_new_tokens=4, ttl_s=1e-4)
            for _ in range(2)
        ]
        time.sleep(0.01)  # both TTLs are long gone
        prefills_before = eng.stats["admitted"]
        _drain(fe, [keeper] + doomed)
        for h in doomed:
            assert h.outcome == "deadline_queued"
            assert h.inner.admit_time is None  # never prefilled
            assert h.tokens() == []
        assert keeper.outcome == "ok"
        # no prefill was spent on the expired ones
        assert eng.stats["admitted"] == prefills_before + 1
        assert metrics_on.get("serving_deadline_miss_total").value(stage="queued") == 2
        assert metrics_on.get("serving_shed_total").value(reason="deadline_queued") == 2
        assert _drained(eng)

    def test_mid_decode_expiry_evicts_and_reclaims(self, metrics_on):
        fe, eng, cfg = _frontend(seed=5, max_queue=4)
        rng = np.random.default_rng(5)
        h = fe.submit(_prompt(rng, cfg), max_new_tokens=64, ttl_s=3600.0)
        fe.pump()  # admitted, first token out
        assert h.inner.admit_time is not None
        assert len(h.inner.generated) >= 1
        # force the expiry deterministically (no sleep-timing in CI)
        h.inner.deadline = time.perf_counter() - 1.0
        done = []
        while not h.finished:
            done += fe.pump()
        assert h.outcome == "deadline_decode"
        assert [d.id for d in done] == [h.id]
        assert 1 <= len(h.inner.generated) < 64  # evicted mid-generation
        assert metrics_on.get("serving_deadline_miss_total").value(stage="decode") == 1
        assert metrics_on.get("serving_shed_total").value(reason="deadline_decode") == 1
        assert _drained(eng)  # blocks reclaimed (cache retention is not a leak)

    def test_engine_level_deadline_without_frontend(self):
        # the engine enforces deadlines for direct users too
        m, cfg = _model(seed=6)
        eng = ContinuousBatchingEngine(m, max_slots=1, block_size=4, prompt_bucket=8)
        rng = np.random.default_rng(6)
        live = eng.add_request(_prompt(rng, cfg), max_new_tokens=2)
        dead = eng.add_request(
            _prompt(rng, cfg), max_new_tokens=2,
            deadline=time.perf_counter() - 1.0,
        )
        out = {}
        while eng.has_work():
            for r in eng.step():
                out[r.req_id] = r
        assert out[dead].finish_reason == "deadline" and out[dead].generated == []
        assert out[live].finish_reason == "length"

    def test_cancel_reclaims_mid_decode(self, metrics_on):
        fe, eng, cfg = _frontend(seed=7, max_queue=4)
        rng = np.random.default_rng(7)
        h = fe.submit(_prompt(rng, cfg), max_new_tokens=64)
        fe.pump()
        assert fe.cancel(h.id, reason="client_disconnect") is True
        assert h.outcome == "client_disconnect" and h.finished
        assert _drained(eng)
        assert metrics_on.get("serving_shed_total").value(reason="client_disconnect") == 1
        assert fe.cancel(h.id) is False  # already terminal: exactly once

    def test_cancel_never_touches_requests_the_frontend_does_not_own(self):
        # a direct engine user's request must survive a frontend id mix-up
        fe, eng, cfg = _frontend(seed=18, max_queue=4)
        rng = np.random.default_rng(18)
        direct = eng.add_request(_prompt(rng, cfg), max_new_tokens=3)
        assert fe.cancel(direct) is False
        # the direct request is untouched and still completes normally
        out = {}
        while eng.has_work():
            for r in eng.step():
                out[r.req_id] = r
        assert out[direct].finish_reason == "length"

    def test_tenant_metric_label_cardinality_is_bounded(self, metrics_on):
        cfg_s = ServingConfig(max_queue=64, max_tenant_labels=3)
        fe, eng, cfg = _frontend(seed=19, config=cfg_s)
        rng = np.random.default_rng(19)
        handles = [
            fe.submit(_prompt(rng, cfg), max_new_tokens=2, tenant=f"t{i}")
            for i in range(6)
        ]
        cells = metrics_on.get("serving_requests_total")._snapshot_values()
        tenants = {c["labels"]["tenant"] for c in cells}
        assert tenants == {"t0", "t1", "t2", "overflow"}
        overflow = [c for c in cells if c["labels"]["tenant"] == "overflow"]
        assert sum(c["value"] for c in overflow) == 3
        _drain(fe, handles)


# -- streaming + pump thread --------------------------------------------------

class TestStreaming:
    def test_stream_yields_all_tokens_in_order(self):
        fe, eng, cfg = _frontend(seed=8, max_queue=4)
        rng = np.random.default_rng(8)
        h = fe.submit(_prompt(rng, cfg, 5), max_new_tokens=6)
        fe.start()
        try:
            streamed = list(h.stream(timeout=30.0))
        finally:
            fe.stop()
        assert h.outcome == "ok"
        assert streamed == h.tokens() and len(streamed) == 6

    def test_transient_step_failure_does_not_brick_the_frontend(self):
        # engine.step()'s caller-retryable contract: a dispatch failure with
        # buffers intact rolls back and re-raises with the engine USABLE —
        # the pump thread must retry, not fail every live stream
        fe, eng, cfg = _frontend(seed=20, max_queue=4)
        rng = np.random.default_rng(20)
        real, tripped = eng._step_fn, []

        def flaky(*a, **k):
            if not tripped:
                tripped.append(1)
                raise RuntimeError("transient device failure")
            return real(*a, **k)

        eng._step_fn = flaky
        h = fe.submit(_prompt(rng, cfg), max_new_tokens=4)
        fe.start()
        try:
            inner = h.result(timeout=30.0)
        finally:
            fe.stop()
        assert tripped and h.outcome == "ok"
        assert len(inner.generated) == 4
        fe.submit(_prompt(rng, cfg), max_new_tokens=2)  # still open for business

    def test_engine_permanent_failure_fails_streams_explicitly(self):
        fe, eng, cfg = _frontend(seed=9, max_queue=4, max_recoveries=0)
        rng = np.random.default_rng(9)
        h = fe.submit(_prompt(rng, cfg), max_new_tokens=8)
        plan = faults.FaultPlan.single("engine.decode", call_index=1)
        fe.start()
        try:
            with faults.inject(plan):
                inner = h.result(timeout=30.0)
        finally:
            fe.stop()
        assert h.outcome == "engine_failure"
        assert inner is h.inner
        # the frontend is now closed for business, loudly
        with pytest.raises(RuntimeError, match="build a new"):
            fe.submit(_prompt(rng, cfg))


# -- fault-injection sites ----------------------------------------------------

class TestServingFaultSites:
    def test_intake_site_fires_and_is_counted(self, metrics_on):
        fe, eng, cfg = _frontend(seed=10, max_queue=4)
        rng = np.random.default_rng(10)
        plan = faults.FaultPlan.single("serving.intake", call_index=1)
        with faults.inject(plan):
            fe.submit(_prompt(rng, cfg), max_new_tokens=2)  # call 0: clean
            with pytest.raises(faults.InjectedFault):
                fe.submit(_prompt(rng, cfg), max_new_tokens=2)  # call 1: boom
            assert faults.site_call_count("serving.intake") == 2
        assert metrics_on.get("faults_injected_total").value(site="serving.intake") == 1
        # the fault fired BEFORE any state change: nothing was queued for it
        assert eng.queue_depth() == 1

    def test_sites_are_zero_cost_when_no_plan_installed(self):
        # the cached-bool gate must be OFF and no counters accumulate when
        # no plan is installed — serving traffic pays one list read per site
        from paddle_tpu.testing.faults import _ACTIVE

        assert not _ACTIVE[0]
        fe, eng, cfg = _frontend(seed=11, max_queue=4)
        rng = np.random.default_rng(11)
        h = fe.submit(_prompt(rng, cfg), max_new_tokens=2)
        _drain(fe, [h])
        assert h.outcome == "ok"
        # no plan: sites do not even count calls
        assert faults.site_call_count("serving.intake") == 0
        assert faults.site_call_count("serving.respond") == 0

    def test_serving_sites_are_registered_for_campaigns(self):
        assert "serving.intake" in faults.KNOWN_SITES
        assert "serving.respond" in faults.KNOWN_SITES
        plan = faults.FaultPlan.sample(faults.KNOWN_SITES, 3, seed=5)
        assert faults.FaultPlan.parse(plan.spec()) == plan  # round-trips


# -- HTTP endpoint ------------------------------------------------------------

@pytest.fixture
def http_frontend():
    fe, eng, cfg = _frontend(seed=12, max_queue=4)
    srv = start_serving_server(fe, port=0)
    port = srv.server_address[1]
    yield fe, eng, cfg, port
    stop_serving_server(fe)


def _post(port, payload, timeout=30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(
        "POST", "/v1/generate", json.dumps(payload),
        {"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    body = resp.read().decode()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, body, headers


class TestServingHTTP:
    def test_streaming_generate(self, http_frontend):
        fe, eng, cfg, port = http_frontend
        status, body, _ = _post(
            port, {"prompt": [1, 2, 3, 4], "max_new_tokens": 3,
                   "priority": "interactive", "tenant": "acme"}
        )
        assert status == 200
        lines = [json.loads(l) for l in body.strip().splitlines()]
        assert [set(l) for l in lines[:-1]] == [{"token"}] * 3
        assert lines[-1] == {"done": True, "outcome": "ok", "tokens": 3}

    def test_non_streaming_generate(self, http_frontend):
        fe, eng, cfg, port = http_frontend
        status, body, _ = _post(
            port, {"prompt": [5, 6, 7], "max_new_tokens": 2, "stream": False}
        )
        assert status == 200
        rec = json.loads(body)
        assert rec["outcome"] == "ok" and rec["finish_reason"] == "length"
        assert len(rec["tokens"]) == 2

    def test_intake_validation_maps_to_400(self, http_frontend):
        fe, eng, cfg, port = http_frontend
        status, body, _ = _post(port, {"prompt": list(range(99))})
        assert status == 400
        assert json.loads(body)["type"] == "PromptTooLongError"
        status, body, _ = _post(port, {"prompt": "not-a-list"})
        assert status == 400
        status, body, _ = _post(port, {"prompt": [1], "priority": "vip"})
        assert status == 400 and "priority" in json.loads(body)["error"]
        status, body, _ = _post(port, {"prompt": [1], "max_new_tokens": 0})
        assert status == 400
        assert json.loads(body)["type"] == "InvalidTokenBudgetError"

    def test_unknown_route_is_404(self, http_frontend):
        fe, eng, cfg, port = http_frontend
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/nope")
        assert conn.getresponse().status == 404
        conn.close()
        status, _, _ = _post(port, {"prompt": [1]}, timeout=10)
        assert status == 200  # sanity: the real route still works

    def test_healthz(self, http_frontend):
        fe, eng, cfg, port = http_frontend
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        snap = json.loads(resp.read().decode())
        conn.close()
        assert resp.status == 200
        assert snap["level"] in ("normal", "degraded", "shedding")
        assert snap["max_queue"] == 4

    def test_queue_full_maps_to_429_with_retry_after(self, http_frontend, metrics_on):
        fe, eng, cfg, port = http_frontend
        fe.stop()  # freeze the pump so the queue cannot drain
        rng = np.random.default_rng(12)
        for _ in range(4):
            fe.submit(_prompt(rng, cfg), max_new_tokens=2)
        status, body, headers = _post(port, {"prompt": [1, 2]})
        assert status == 429
        rec = json.loads(body)
        assert rec["reason"] == "queue_full" and rec["retry_after_s"] > 0
        assert float(headers["Retry-After"]) > 0
        assert metrics_on.get("serving_http_responses_total").value(code="429") == 1
        fe.start()  # let the fixture teardown drain cleanly

    def test_injected_respond_fault_evicts_the_request(self, http_frontend, metrics_on):
        # serving.respond with the DEFAULT InjectedFault (what a sampled
        # KNOWN_SITES campaign fires) modelling a torn client connection:
        # the handler must cancel the request so its slot + blocks return
        # to the pool, same as a real disconnect
        fe, eng, cfg, port = http_frontend
        plan = faults.FaultPlan.single("serving.respond", call_index=0)
        with faults.inject(plan):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request(
                "POST", "/v1/generate",
                json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 32}),
            )
            resp = conn.getresponse()
            resp.read()  # connection closes early; body is truncated
            conn.close()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if (
                metrics_on.get("serving_shed_total").value(reason="client_disconnect")
                == 1
                and _drained(eng)
            ):
                break
            time.sleep(0.02)
        assert metrics_on.get("serving_shed_total").value(reason="client_disconnect") == 1
        assert _drained(eng)

    def test_real_client_disconnect_never_leaks_pool_blocks(self, http_frontend):
        fe, eng, cfg, port = http_frontend
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        body = json.dumps({"prompt": [1, 2, 3, 4], "max_new_tokens": 64}).encode()
        s.sendall(
            b"POST /v1/generate HTTP/1.1\r\nHost: x\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        s.recv(256)  # read a little of the stream, then vanish
        s.close()
        # whether the request finished or was cancelled mid-stream, the pool
        # must drain back to full — a gone client cannot leak KV capacity
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            with fe._lock:
                if (
                    _drained(eng)
                    and not eng.has_work()
                ):
                    break
            time.sleep(0.02)
        assert _drained(eng)


# -- sustained-overload engine invariants (property-style churn) --------------

class TestOverloadChurnInvariants:
    def test_admit_evict_shed_churn_holds_invariants(self, metrics_on):
        """Seeded churn across every lifecycle transition — submit (mixed
        priorities/tenants, some with already-expired TTLs), pump, random
        cancels — asserting after EVERY operation: reservations never exceed
        the pool, the gauges equal engine truth, and at the end every
        accepted request reached a terminal state exactly once."""
        fe, eng, cfg = _frontend(
            seed=13, max_queue=6, max_slots=2, block_size=4,
            num_blocks=10, prompt_bucket=8, max_model_len=16,
        )
        rng = np.random.default_rng(13)
        reg = metrics_on
        accepted = {}
        terminal = {}
        rejected_at_intake = 0

        def check_invariants():
            s = eng.pool_stats()
            assert s["allocated"] + s["free"] == s["total"]
            assert int(eng._reserved.sum()) <= eng.num_blocks
            assert reg.get("engine_queue_depth").value() == eng.queue_depth()
            assert reg.get("engine_kv_blocks_allocated").value() == s["allocated"]
            assert reg.get("engine_kv_blocks_free").value() == s["free"]
            assert reg.get("serving_queue_depth").value() == eng.queue_depth()

        def note_done(handles):
            for h in handles:
                assert h.id not in terminal, "delivered twice"
                terminal[h.id] = h.outcome

        for step in range(120):
            op = rng.random()
            if op < 0.5:
                ttl = None if rng.random() < 0.6 else float(rng.choice([1e-5, 3600.0]))
                try:
                    h = fe.submit(
                        _prompt(rng, cfg, int(rng.integers(2, 7))),
                        max_new_tokens=int(rng.integers(2, 8)),
                        priority=int(rng.integers(0, 3)),
                        tenant=str(rng.choice(["a", "b", "c"])),
                        ttl_s=ttl,
                    )
                    accepted[h.id] = h
                except Overloaded:
                    rejected_at_intake += 1
            elif op < 0.85:
                note_done(fe.pump())
            else:
                live_ids = [i for i in accepted if i not in terminal]
                if live_ids:
                    rid = int(rng.choice(live_ids))
                    if fe.cancel(rid, reason="cancelled"):
                        assert accepted[rid].finished
                        terminal[rid] = accepted[rid].outcome
            check_invariants()

        while any(i not in terminal for i in accepted):
            note_done(fe.pump())
            check_invariants()

        # finished exactly once, at every lifecycle stage something was shed
        assert set(terminal) == set(accepted)
        outcomes = set(terminal.values())
        assert "ok" in outcomes
        assert "deadline_queued" in outcomes  # shed while queued
        assert "cancelled" in outcomes  # targeted eviction
        # the shed counter accounts every refusal AND every non-ok terminal
        shed_total = sum(
            v["value"]
            for v in reg.get("serving_shed_total")._snapshot_values()
        )
        non_ok = sum(1 for o in terminal.values() if o != "ok")
        assert shed_total == non_ok + rejected_at_intake
        assert _drained(eng)


# -- the overload acceptance test ---------------------------------------------

class TestOverloadAcceptance:
    def test_2x_overload_sheds_explicitly_and_keeps_one_compile(self, metrics_on):
        """ISSUE acceptance: arrivals at 2x the calibrated sustainable rate.
        The frontend must shed (Overloaded/429 paths) rather than grow the
        queue unboundedly, high-priority SLO attainment must not fall below
        best-effort's, every shed request must be accounted in
        ``serving_shed_total{reason}``, and the recompile watchdog must still
        report exactly 2 compiles for the engine."""
        fe, eng, cfg = _frontend(seed=14, max_queue=6)
        rng_seed = 14
        rate = measure_sustainable_rate(
            fe, 8, seed=rng_seed, prompt_len=(3, 7), max_new_tokens=(4, 10),
            vocab_size=cfg.vocab_size,
        )
        obs.GLOBAL_METRICS.reset()  # overload window accounting only
        mix = [
            TrafficClass("chat", Priority.INTERACTIVE, 1.0, (3, 7), (4, 10), 2.0),
            TrafficClass("batch", Priority.BEST_EFFORT, 1.0, (3, 7), (4, 10), 2.0),
        ]
        arrivals = poisson_arrivals(
            2.0 * rate, 48, mix, seed=rng_seed + 1, vocab_size=cfg.vocab_size
        )
        max_depth_seen = 0

        def bounded_queue(frontend):
            nonlocal max_depth_seen
            max_depth_seen = max(max_depth_seen, frontend.engine.queue_depth())
            assert frontend.engine.queue_depth() <= frontend.config.max_queue

        report = run_open_loop(fe, arrivals, max_wall_s=90.0, on_iteration=bounded_queue)
        assert report["undelivered_arrivals"] == 0, report

        inter = report["per_class"]["chat/interactive"]
        best = report["per_class"]["batch/best_effort"]
        total_refused = sum(
            c["rejected_at_intake"] + c["shed_after_accept"]
            for c in report["per_class"].values()
        )
        # 2x overload MUST shed: roughly half the offered work cannot finish
        assert total_refused > 0, report
        # ... explicitly, not by queue growth
        assert max_depth_seen <= fe.config.max_queue
        # priority classes actually mean something under load
        assert inter["slo_attainment"] >= best["slo_attainment"], report
        # every shed request is accounted in serving_shed_total{reason}
        shed_cells = {
            v["labels"]["reason"]: int(v["value"])
            for v in metrics_on.get("serving_shed_total")._snapshot_values()
        }
        assert sum(shed_cells.values()) == total_refused, (shed_cells, report)
        assert all(reason for reason in shed_cells)
        # the 2-compile honesty check: overload adds no compiles
        assert report["compiled_signatures_total"] == 1, report
        assert sum(report["compiles_during_run"].values()) == 0


# -- engine-level admission policy hook ---------------------------------------

class TestEngineAdmissionPolicy:
    def test_custom_policy_overrides_fifo_order(self):
        from paddle_tpu.inference import AdmissionPolicy

        class LIFO(AdmissionPolicy):
            def select(self, waiting, can_fit):
                for req in reversed(waiting):
                    if can_fit(req):
                        return req
                return None

        m, cfg = _model(seed=15)
        eng = ContinuousBatchingEngine(
            m, max_slots=1, block_size=4, prompt_bucket=8,
            admission_policy=LIFO(),
        )
        rng = np.random.default_rng(15)
        first = eng.add_request(_prompt(rng, cfg), max_new_tokens=2)
        last = eng.add_request(_prompt(rng, cfg), max_new_tokens=2)
        done = eng.step()  # one slot: LIFO admits the LAST submitted
        admitted_first = done[0].req_id if done else eng._slot_req[0].req_id
        assert admitted_first == last
        out = eng.run()
        assert set(list(out) + [d.req_id for d in done]) == {first, last}

    def test_buggy_policy_fails_loudly(self):
        from paddle_tpu.inference import AdmissionPolicy

        class Foreign(AdmissionPolicy):
            def select(self, waiting, can_fit):
                return InferenceRequest(999, np.zeros(2, np.int32), 2, None)

        m, cfg = _model(seed=16)
        eng = ContinuousBatchingEngine(
            m, max_slots=1, block_size=4, prompt_bucket=8,
            admission_policy=Foreign(),
        )
        rng = np.random.default_rng(16)
        eng.add_request(_prompt(rng, cfg), max_new_tokens=2)
        with pytest.raises(RuntimeError, match="not in the waiting queue"):
            eng.step()

    def test_cancel_request_queued_and_mid_decode(self):
        m, cfg = _model(seed=17)
        eng = ContinuousBatchingEngine(m, max_slots=1, block_size=4, prompt_bucket=8)
        rng = np.random.default_rng(17)
        running = eng.add_request(_prompt(rng, cfg), max_new_tokens=32)
        queued = eng.add_request(_prompt(rng, cfg), max_new_tokens=32)
        eng.step()
        got = eng.cancel_request(queued, reason="shed")
        assert got.req_id == queued and got.finish_reason == "shed"
        assert got.generated == []  # never admitted: no prefill spent
        got2 = eng.cancel_request(running, reason="shed")
        assert got2.req_id == running and len(got2.generated) >= 1
        assert _drained(eng)  # blocks reclaimed (cache retention is not a leak)
        assert eng.cancel_request(running) is None  # exactly once
        assert not eng.has_work()
        assert eng.run() == {}  # cancelled requests are NOT re-delivered


# -- bench smoke --------------------------------------------------------------

def test_bench_serving_goodput_cpu_smoke():
    """The guarded bench record runs on CPU with a tiny budget and carries
    the fields reruns are compared on."""
    import bench

    rec = bench._bench_serving_goodput(paddle, "cpu")
    assert "error" not in rec, rec
    assert rec["metric"] == "serving_goodput_tokens_per_sec"
    assert rec["value"] >= 0
    assert rec["compiled_signatures"] == 1, rec
    assert rec["compiles_during_overload"] == 0, rec
    assert set(rec["slo_attainment"]) == {
        "chat/interactive", "app/standard", "batch/best_effort"
    }
    assert isinstance(rec["shed_total_by_reason"], dict)
    assert rec["offered_rate_rps"] == pytest.approx(2 * rec["sustainable_rate_rps"], rel=0.02)
