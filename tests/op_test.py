"""OpTest harness — the reference's op-testing methodology
(``test/legacy_test/op_test.py:418``): each op test supplies numpy inputs and
expected outputs; the harness checks eager output, dygraph/jit parity
(``check_output_with_place:2124`` old-IR/PIR parity analog), and analytic
gradients against numeric central differences (``check_grad_with_place:3140``)
with dtype-aware tolerances.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor

__all__ = ["OpTest"]

_DTYPE_TOL = {
    "float32": (1e-5, 1e-6),
    "float64": (1e-7, 1e-8),
    "bfloat16": (2e-2, 2e-2),
    "float16": (1e-3, 1e-3),
}


class OpTest:
    """Subclass and set ``op`` (callable), ``inputs`` (dict name→numpy),
    ``attrs`` (kwargs), ``expected`` (numpy or callable(numpy inputs)->numpy).
    """

    op: Optional[Callable] = None
    inputs: Dict[str, np.ndarray] = {}
    attrs: Dict[str, Any] = {}
    expected: Any = None

    # -- helpers -----------------------------------------------------------
    def _tensors(self) -> Dict[str, Tensor]:
        return {k: paddle.to_tensor(v) for k, v in self.inputs.items()}

    def _run_op(self, tensors: Dict[str, Tensor]) -> Tensor:
        out = type(self).op(*tensors.values(), **self.attrs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out

    def _expected_np(self) -> np.ndarray:
        if callable(self.expected):
            return np.asarray(self.expected(*self.inputs.values()))
        return np.asarray(self.expected)

    # -- checks (reference parity) ----------------------------------------
    def check_output(self, rtol: Optional[float] = None, atol: Optional[float] = None) -> None:
        """Eager output vs the numpy reference, plus eager↔jit parity (the
        dygraph/static parity axis of the reference harness)."""
        dtype = str(next(iter(self.inputs.values())).dtype) if self.inputs else "float32"
        d_rtol, d_atol = _DTYPE_TOL.get(dtype, (1e-5, 1e-6))
        rtol = rtol if rtol is not None else d_rtol
        atol = atol if atol is not None else d_atol

        tensors = self._tensors()
        eager_out = self._run_op(tensors)
        np.testing.assert_allclose(
            eager_out.numpy(), self._expected_np(), rtol=rtol, atol=atol,
            err_msg=f"{type(self).__name__}: eager output mismatch",
        )

        # jit parity: the same op traced+compiled must agree with eager
        op = type(self).op
        attrs = self.attrs

        @paddle.jit.to_static
        def jit_fn(*ts: Tensor) -> Tensor:
            out = op(*ts, **attrs)
            return out[0] if isinstance(out, (tuple, list)) else out

        jit_out = jit_fn(*self._tensors().values())
        np.testing.assert_allclose(
            jit_out.numpy(), eager_out.numpy(), rtol=rtol, atol=atol,
            err_msg=f"{type(self).__name__}: eager vs jit mismatch",
        )

    def check_grad(
        self,
        inputs_to_check: Sequence[str],
        max_relative_error: float = 5e-3,
        eps: float = 1e-3,
        loss_weights: Optional[np.ndarray] = None,
    ) -> None:
        """Analytic grads (autograd tape) vs numeric central differences
        (reference ``check_grad_with_place`` / ``get_numeric_gradient``)."""
        # analytic
        tensors = self._tensors()
        for name in inputs_to_check:
            tensors[name].stop_gradient = False
        out = self._run_op(tensors)
        if loss_weights is None:
            # random cotangent: a plain sum-loss has zero gradient through
            # ops with constant row sums (softmax) — the reference supplies
            # user_defined_grad_outputs for the same reason
            loss_weights = (
                np.random.default_rng(1234).normal(size=tuple(out.shape)).astype(np.float32)
            )
        w = paddle.to_tensor(loss_weights).astype(out.dtype)
        (out * w).sum().backward()
        analytic = {n: tensors[n].grad.numpy().copy() for n in inputs_to_check}

        # numeric central differences on the numpy function
        wn = np.asarray(w.numpy(), np.float64)
        for name in inputs_to_check:
            base = self.inputs[name].astype(np.float64)
            num = np.zeros_like(base)
            flat = base.reshape(-1)
            numf = num.reshape(-1)
            for i in range(flat.size):
                for sign in (+1, -1):
                    pert = dict(self.inputs)
                    fb = base.copy().reshape(-1)
                    fb[i] += sign * eps
                    pert[name] = fb.reshape(base.shape).astype(self.inputs[name].dtype)
                    ts = {k: paddle.to_tensor(v) for k, v in pert.items()}
                    val = float(
                        (self._run_op(ts).numpy().astype(np.float64) * wn).sum()
                    )
                    numf[i] += sign * val
                numf[i] /= 2 * eps
            a = analytic[name].astype(np.float64)
            denom = max(np.abs(num).max(), np.abs(a).max(), 1e-8)
            max_err = np.abs(a - num).max() / denom
            assert max_err <= max_relative_error, (
                f"{type(self).__name__}: grad wrt {name}: max relative error "
                f"{max_err:.2e} > {max_relative_error:.2e}"
            )
