"""Aux subsystem tests: hapi Model fit/evaluate/predict, amp.debugging
(tensor checker + operator stats), distributions."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestHapiModel:
    def _data(self, n=64):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(n, 8)).astype(np.float32)
        w = rng.normal(size=(8, 1)).astype(np.float32)
        y = x @ w + 0.01 * rng.normal(size=(n, 1)).astype(np.float32)
        return x, y

    def test_fit_reduces_loss(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters()),
            loss=nn.MSELoss(),
        )
        x, y = self._data()
        hist = model.fit((x, y), batch_size=16, epochs=15, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0] * 0.5

    def test_evaluate_and_predict(self):
        paddle.seed(1)
        net = nn.Linear(8, 1)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
            loss=nn.MSELoss(),
        )
        x, y = self._data(32)
        logs = model.evaluate((x, y), batch_size=16)
        assert "eval_loss" in logs and np.isfinite(logs["eval_loss"])
        preds = model.predict(x, batch_size=16)
        assert sum(p.shape[0] for p in preds) == 32

    def test_save_load_roundtrip(self, tmp_path):
        paddle.seed(2)
        net = nn.Linear(8, 1)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters()),
            loss=nn.MSELoss(),
        )
        x, y = self._data(32)
        model.fit((x, y), batch_size=16, epochs=1, verbose=0)
        path = str(tmp_path / "ckpt")
        model.save(path)

        paddle.seed(99)
        net2 = nn.Linear(8, 1)
        model2 = paddle.Model(net2)
        model2.prepare(
            optimizer=paddle.optimizer.Adam(learning_rate=1e-2, parameters=net2.parameters()),
            loss=nn.MSELoss(),
        )
        model2.load(path)
        np.testing.assert_allclose(net.weight.numpy(), net2.weight.numpy())

    def test_evaluate_with_metrics(self):
        paddle.seed(5)
        net = nn.Sequential(nn.Linear(8, 2))
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss(),
            metrics=[paddle.metric.Accuracy(), paddle.metric.Precision()],
        )
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = rng.integers(0, 2, (32, 1)).astype(np.int64)
        logs = model.evaluate((x, y), batch_size=16)
        assert "eval_acc" in logs or any("acc" in k for k in logs)
        assert any("precision" in k for k in logs)

    def test_early_stopping(self):
        from paddle_tpu.hapi.callbacks import EarlyStopping

        paddle.seed(3)
        net = nn.Linear(8, 1)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.0, parameters=net.parameters()),
            loss=nn.MSELoss(),
        )
        x, y = self._data(32)
        es = EarlyStopping(monitor="eval_loss", patience=1, mode="min")
        hist = model.fit((x, y), eval_data=(x, y), batch_size=16, epochs=10,
                         verbose=0, callbacks=[es])
        # lr=0 → no improvement → stops well before 10 epochs
        assert len(hist["loss"]) <= 4

    def test_summary(self):
        net = nn.Linear(8, 4)
        info = paddle.Model(net).summary()
        assert info["total_params"] == 8 * 4 + 4


class TestAmpDebugging:
    def test_tensor_checker_catches_nan(self):
        from paddle_tpu.amp.debugging import (
            TensorCheckerConfig,
            disable_tensor_checker,
            enable_tensor_checker,
        )

        enable_tensor_checker(TensorCheckerConfig(enable=True))
        try:
            bad = paddle.to_tensor(np.array([1.0, np.nan], np.float32))
            with pytest.raises(Exception):
                _ = bad + 1.0
        finally:
            disable_tensor_checker()

    def test_check_numerics(self):
        from paddle_tpu.amp.debugging import DebugMode, check_numerics

        t = paddle.to_tensor(np.array([1.0, np.inf, np.nan], np.float32))
        n_nan, n_inf = check_numerics(t, "op", "t", DebugMode.CHECK_NAN_INF)
        assert (n_nan, n_inf) == (1, 1)
        with pytest.raises(FloatingPointError):
            check_numerics(t, "op", "t", DebugMode.CHECK_NAN_INF_AND_ABORT)

    def test_operator_stats(self, capsys):
        from paddle_tpu.amp.debugging import collect_operator_stats

        with collect_operator_stats():
            a = paddle.randn([4, 4])
            _ = paddle.matmul(a, a)
            _ = a + a
        out = capsys.readouterr().out
        assert "float32" in out


class TestDistributions:
    def test_normal(self):
        from paddle_tpu.distribution import Normal

        paddle.seed(0)
        d = Normal(loc=1.0, scale=2.0)
        s = d.sample([20000])
        assert abs(float(s.numpy().mean()) - 1.0) < 0.1
        assert abs(float(s.numpy().std()) - 2.0) < 0.1
        lp = d.log_prob(1.0)
        expect = -np.log(2.0) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(float(lp.numpy()), expect, rtol=1e-5)

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical

        paddle.seed(1)
        d = Categorical(logits=np.log(np.array([0.7, 0.2, 0.1], np.float32)))
        s = d.sample([10000]).numpy()
        freq = np.bincount(s, minlength=3) / 10000
        np.testing.assert_allclose(freq, [0.7, 0.2, 0.1], atol=0.03)
        np.testing.assert_allclose(float(d.log_prob(0).numpy()), np.log(0.7), rtol=1e-4)

    def test_kl_normal(self):
        from paddle_tpu.distribution import Normal, kl_divergence

        p = Normal(0.0, 1.0)
        q = Normal(1.0, 2.0)
        kl = float(kl_divergence(p, q).numpy())
        expect = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(kl, expect, rtol=1e-5)

    def test_bernoulli_uniform_exponential(self):
        from paddle_tpu.distribution import Bernoulli, Exponential, Uniform

        paddle.seed(2)
        b = Bernoulli(0.3)
        assert abs(float(b.sample([10000]).numpy().mean()) - 0.3) < 0.03
        u = Uniform(0.0, 4.0)
        assert abs(float(u.sample([10000]).numpy().mean()) - 2.0) < 0.1
        e = Exponential(2.0)
        assert abs(float(e.sample([10000]).numpy().mean()) - 0.5) < 0.05
        assert float(u.entropy().numpy()) == pytest.approx(np.log(4.0))

    def test_gamma_laplace_logprob(self):
        from paddle_tpu.distribution import Gamma, Laplace

        g = Gamma(2.0, 3.0)
        # log p(x) = a log b + (a-1) log x - b x - lgamma(a), at x=1
        expect = 2 * np.log(3.0) + 0.0 - 3.0 - 0.0
        np.testing.assert_allclose(float(g.log_prob(1.0).numpy()), expect, rtol=1e-5)
        l = Laplace(0.0, 1.0)
        np.testing.assert_allclose(float(l.log_prob(0.0).numpy()), -np.log(2.0), rtol=1e-5)
