"""Failure detection + elastic: comm watchdog hang dumps, TCPStore-lease
membership, and launcher relaunch-on-failure with checkpoint resume.

Reference parity: ``paddle/phi/core/distributed/comm_task_manager.h:37``
(watchdog), ``fleet/elastic/manager.py:128-251`` (membership + relaunch).
"""

import os
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed.watchdog import CommWatchdog
from paddle_tpu_native.loader import load_native
from paddle_tpu_native.store import TCPStore

native_available = load_native() is not None


class TestCommWatchdog:
    def test_fast_section_no_fire(self):
        fired = []
        wd = CommWatchdog(timeout=5.0, on_timeout=fired.append)
        with wd.section("quick"):
            pass
        time.sleep(0.1)
        assert not fired
        assert wd.completed[-1]["section"] == "quick" and wd.completed[-1]["ok"]

    def test_hang_detected_with_dump(self):
        fired = []
        wd = CommWatchdog(timeout=0.3, on_timeout=fired.append)
        with wd.section("hung_allreduce"):
            time.sleep(0.8)  # simulated stuck collective
        assert len(fired) == 1
        dump = fired[0]
        assert dump["section"] == "hung_allreduce"
        assert dump["elapsed_s"] >= 0.3
        assert dump["thread_stacks"]  # stacks captured for the hang dump
        # the stuck frame (this sleep) is visible in some thread's stack
        assert any("time.sleep" in "".join(st) or "test_hang_detected" in "".join(st)
                   for st in dump["thread_stacks"].values())

    def test_watch_wraps_callable(self):
        wd = CommWatchdog(timeout=5.0)
        assert wd.watch(lambda a, b: a + b, 2, 3) == 5
        assert wd.completed[-1]["section"] == "<lambda>"

    def test_history_records_failures(self):
        wd = CommWatchdog(timeout=5.0)
        with pytest.raises(RuntimeError):
            with wd.section("boom"):
                raise RuntimeError("x")
        assert wd.completed[-1]["ok"] is False


@pytest.mark.skipif(not native_available, reason="native lib not built")
class TestElasticMembership:
    def test_dead_worker_detected_after_kill(self, tmp_path):
        """The VERDICT scenario: kill one local process, observe detection.
        Worker 1 is a real subprocess heartbeating through the store; killing
        it lets its lease expire while worker 0 stays alive."""
        from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus

        master = TCPStore("127.0.0.1", 0, is_master=True, world_size=2, timeout=3)
        worker_code = textwrap.dedent(
            f"""
            import sys, time
            sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
            from paddle_tpu_native.store import TCPStore
            from paddle_tpu.distributed.fleet.elastic import ElasticManager
            store = TCPStore("127.0.0.1", {master.port}, is_master=False, timeout=3)
            em = ElasticManager(store, rank=1, world_size=2, ttl=1.0)
            em.register()
            print("registered", flush=True)
            time.sleep(60)
            """
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", worker_code],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            line = proc.stdout.readline().decode()
            assert "registered" in line, line

            mgr = ElasticManager(master, rank=0, world_size=2, ttl=1.0)
            mgr.register()
            deadline = time.time() + 10
            while time.time() < deadline:
                if mgr.watch() == ElasticStatus.HOLD:
                    break
                time.sleep(0.2)
            assert mgr.watch() == ElasticStatus.HOLD
            assert mgr.alive_workers() == [0, 1]

            proc.kill()
            proc.wait(timeout=10)
            deadline = time.time() + 10
            status = ElasticStatus.HOLD
            while time.time() < deadline:
                status = mgr.watch()
                if status == ElasticStatus.RESTART:
                    break
                time.sleep(0.2)
            assert status == ElasticStatus.RESTART
            assert mgr.dead_workers() == [1]
            mgr.stop()
        finally:
            if proc.poll() is None:
                proc.kill()


class TestLaunchRelaunch:
    def test_failed_worker_relaunched_and_resumes(self, tmp_path):
        """Launcher-level fault tolerance: the worker crashes on its first
        life, is relaunched with PADDLE_RESTART_COUNT=1, restores its
        'checkpoint' and succeeds."""
        from paddle_tpu.distributed.launch.main import launch

        ckpt = tmp_path / "ckpt.txt"
        script = tmp_path / "train.py"
        script.write_text(
            textwrap.dedent(
                f"""
                import os, sys
                ckpt = {str(ckpt)!r}
                restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
                if restart == 0:
                    with open(ckpt, "w") as f:
                        f.write("step=3")
                    sys.exit(1)  # simulated crash mid-training
                assert os.path.exists(ckpt), "checkpoint lost across relaunch"
                state = open(ckpt).read()
                assert state == "step=3"
                print(f"resumed from {{state}} on restart {{restart}}")
                """
            )
        )
        rc = launch(["--max_restarts", "1", "--nproc_per_node", "1", str(script)])
        assert rc == 0

    def test_no_restarts_fails_job(self, tmp_path):
        from paddle_tpu.distributed.launch.main import launch

        script = tmp_path / "always_fail.py"
        script.write_text("import sys; sys.exit(7)\n")
        rc = launch(["--max_restarts", "0", "--nproc_per_node", "1", str(script)])
        assert rc == 7

    def test_group_restart_relaunches_all_local_workers(self, tmp_path):
        """When one rank dies, the WHOLE local group restarts — surviving
        ranks are stuck in collectives and a lone fresh process could never
        rejoin (reference elastic manager restarts all local trainers)."""
        from paddle_tpu.distributed.launch.main import launch

        marker = tmp_path / "lives.txt"
        script = tmp_path / "train.py"
        script.write_text(
            textwrap.dedent(
                f"""
                import os, sys, time
                rank = int(os.environ["PADDLE_TRAINER_ID"])
                restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
                with open({str(marker)!r}, "a") as f:
                    f.write(f"rank{{rank}}-life{{restart}}\\n")
                if restart == 0:
                    if rank == 0:
                        sys.exit(3)      # rank 0 crashes
                    time.sleep(60)       # rank 1 'hangs in a collective'
                """
            )
        )
        rc = launch(["--max_restarts", "1", "--nproc_per_node", "2", str(script)])
        assert rc == 0
        lives = set(marker.read_text().split())
        # both ranks ran life 0 AND both were relaunched for life 1
        assert {"rank0-life0", "rank1-life0", "rank0-life1", "rank1-life1"} <= lives


class TestElasticScaling:
    """r4: scale-in/out envelope, endpoint rebuild, watchdog fault wiring
    (reference elastic manager.py:128-251 + CommTaskManager integration)."""

    def _mgr(self, world="4", rank=0, ttl=5.0):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=3)
        return ElasticManager(store, rank=rank, world_size=world, ttl=ttl), store

    def test_np_range_parsing(self):
        mgr, _ = self._mgr(world="2:4")
        assert mgr.min_np == 2 and mgr.max_np == 4 and mgr.world_size == 4

    def test_scale_decisions(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticStatus

        mgr, store = self._mgr(world="2:4", ttl=30.0)
        now = str(time.time()).encode()
        # 4 alive -> HOLD
        for r in range(4):
            store.set(f"elastic/0/beat/{r}", now)
        assert mgr.watch() == ElasticStatus.HOLD
        # 3 alive (within [2,4)) -> RESTART (scale-in)
        store.set("elastic/0/beat/3", b"0.0")
        assert mgr.watch() == ElasticStatus.RESTART
        # 1 alive (< min_np) -> ERROR
        for r in (1, 2):
            store.set(f"elastic/0/beat/{r}", b"0.0")
        assert mgr.watch() == ElasticStatus.ERROR

    def test_rebuild_endpoints_dense_ranks_and_generation(self):
        mgr, store = self._mgr(world="2:4", ttl=30.0)
        now = str(time.time()).encode()
        for r in (0, 1, 3):  # rank 2 died
            store.set(f"elastic/0/beat/{r}", now)
        topo = mgr.rebuild_endpoints()
        assert topo["world_size"] == 3
        assert topo["rank_map"] == {0: 0, 1: 1, 3: 2}
        assert topo["my_rank"] == 0
        assert topo["generation"] == 1
        # workers read the published membership after relaunch
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        loaded = ElasticManager.load_topology(store)
        assert loaded == {"generation": 1, "world_size": 3, "members": [0, 1, 3]}
        # a second rebuild bumps the generation
        assert mgr.rebuild_endpoints()["generation"] == 2

    def test_watchdog_fault_marks_worker_dead(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus

        mgr, store = self._mgr(world="1:2", ttl=30.0)
        now = str(time.time()).encode()
        store.set("elastic/0/beat/0", now)
        store.set("elastic/0/beat/1", now)
        assert mgr.watch() == ElasticStatus.HOLD
        # rank 1's watchdog fires: heartbeat still fresh, but faulted
        peer = ElasticManager(store, rank=1, world_size="1:2", ttl=30.0)
        peer.watchdog_hook()({"section": "train_step"})
        assert mgr.alive_workers() == [0]
        assert mgr.watch() == ElasticStatus.RESTART

    def test_generation_bump_invalidates_stale_state(self):
        """r4 review: rebuild must not let old-topology beats/faults poison
        the new one, and a re-registered worker sheds its fault mark."""
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        mgr, store = self._mgr(world="2:4", ttl=30.0)
        now = str(time.time()).encode()
        for r in (0, 1, 3):
            store.set(f"elastic/0/beat/{r}", now)
        topo = mgr.rebuild_endpoints()
        assert topo["generation"] == 1
        # old gen-0 beats are invisible now: nobody alive until re-register
        assert mgr.alive_workers() == []
        # survivors re-register under the new generation
        w0 = ElasticManager(store, rank=0, world_size="2:4", ttl=30.0)
        w0.register()
        assert mgr.alive_workers() == [0]
        w0.stop()
        # a previously-faulted worker that re-registers is healthy again
        w1 = ElasticManager(store, rank=1, world_size="2:4", ttl=30.0)
        w1.register()
        w1.report_fault("hang")
        assert mgr.alive_workers() == [0]
        w1.register()  # relaunch: clean fault state
        assert sorted(mgr.alive_workers()) == [0, 1]
        w1.stop()
