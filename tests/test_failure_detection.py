"""Failure detection + recovery: comm watchdog hang dumps, deterministic
fault injection, engine step recovery with request replay, crash-consistent
checkpoints, the resilient train loop, TCPStore-lease membership, and
launcher relaunch-on-failure with checkpoint resume.

Reference parity: ``paddle/phi/core/distributed/comm_task_manager.h:37``
(watchdog detect→dump→abort), ``fleet/elastic/manager.py:128-251``
(membership + relaunch); the recovery layer is PR 6's fault-tolerance
tentpole (see README "Fault tolerance").
"""

import glob
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu.distributed.watchdog import CommWatchdog, WatchdogTimeout
from paddle_tpu.testing import faults
from paddle_tpu_native.loader import load_native
from paddle_tpu_native.store import TCPStore

native_available = load_native() is not None


class TestCommWatchdog:
    def test_fast_section_no_fire(self):
        fired = []
        wd = CommWatchdog(timeout=5.0, on_timeout=fired.append)
        with wd.section("quick"):
            pass
        time.sleep(0.1)
        assert not fired
        assert wd.completed[-1]["section"] == "quick" and wd.completed[-1]["ok"]

    def test_hang_detected_with_dump(self):
        fired = []
        wd = CommWatchdog(timeout=0.3, on_timeout=fired.append)
        with wd.section("hung_allreduce"):
            time.sleep(0.8)  # simulated stuck collective
        assert len(fired) == 1
        dump = fired[0]
        assert dump["section"] == "hung_allreduce"
        assert dump["elapsed_s"] >= 0.3
        assert dump["thread_stacks"]  # stacks captured for the hang dump
        # the stuck frame (this sleep) is visible in some thread's stack
        assert any("time.sleep" in "".join(st) or "test_hang_detected" in "".join(st)
                   for st in dump["thread_stacks"].values())

    def test_watch_wraps_callable(self):
        wd = CommWatchdog(timeout=5.0)
        assert wd.watch(lambda a, b: a + b, 2, 3) == 5
        assert wd.completed[-1]["section"] == "<lambda>"

    def test_history_records_failures(self):
        wd = CommWatchdog(timeout=5.0)
        with pytest.raises(RuntimeError):
            with wd.section("boom"):
                raise RuntimeError("x")
        assert wd.completed[-1]["ok"] is False

    def test_history_records_exception_type(self):
        """WHAT failed, not just that it did — resilient_train_loop and
        tests assert on the type without racing stderr."""
        wd = CommWatchdog(timeout=5.0)
        with pytest.raises(WatchdogTimeout):
            with wd.section("hung"):
                raise WatchdogTimeout("simulated")
        assert wd.completed[-1]["exc_type"] == "WatchdogTimeout"
        with wd.section("fine"):
            pass
        assert wd.completed[-1]["exc_type"] is None

    def test_last_dump_exposed(self):
        wd = CommWatchdog(timeout=0.2, on_timeout=lambda d: None)
        assert wd.last_dump is None
        with wd.section("slow"):
            time.sleep(0.5)
        assert wd.last_dump is not None
        assert wd.last_dump["section"] == "slow"
        assert wd.last_dump["thread_stacks"]

    def test_buggy_on_timeout_handler_cannot_suppress_diagnostics(self, capfd):
        """A handler that raises must not swallow the dump: the default
        stderr diagnostics still run (the abort path's evidence) and
        last_dump is still recorded."""

        def bad_handler(dump):
            raise ValueError("buggy handler")

        wd = CommWatchdog(timeout=0.2, on_timeout=bad_handler)
        with wd.section("slow"):
            time.sleep(0.5)
        time.sleep(0.1)  # let the watchdog thread finish its dump
        assert wd.last_dump is not None and wd.last_dump["section"] == "slow"
        err = capfd.readouterr().err
        assert "buggy handler" in err  # the handler's own failure is visible
        assert "[CommWatchdog] section 'slow'" in err  # ... and so is the dump


@pytest.mark.skipif(not native_available, reason="native lib not built")
class TestElasticMembership:
    def test_dead_worker_detected_after_kill(self, tmp_path):
        """The VERDICT scenario: kill one local process, observe detection.
        Worker 1 is a real subprocess heartbeating through the store; killing
        it lets its lease expire while worker 0 stays alive."""
        from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus

        master = TCPStore("127.0.0.1", 0, is_master=True, world_size=2, timeout=3)
        worker_code = textwrap.dedent(
            f"""
            import sys, time
            sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
            from paddle_tpu_native.store import TCPStore
            from paddle_tpu.distributed.fleet.elastic import ElasticManager
            store = TCPStore("127.0.0.1", {master.port}, is_master=False, timeout=3)
            em = ElasticManager(store, rank=1, world_size=2, ttl=1.0)
            em.register()
            print("registered", flush=True)
            time.sleep(60)
            """
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", worker_code],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            line = proc.stdout.readline().decode()
            assert "registered" in line, line

            mgr = ElasticManager(master, rank=0, world_size=2, ttl=1.0)
            mgr.register()
            deadline = time.time() + 10
            while time.time() < deadline:
                if mgr.watch() == ElasticStatus.HOLD:
                    break
                time.sleep(0.2)
            assert mgr.watch() == ElasticStatus.HOLD
            assert mgr.alive_workers() == [0, 1]

            proc.kill()
            proc.wait(timeout=10)
            deadline = time.time() + 10
            status = ElasticStatus.HOLD
            while time.time() < deadline:
                status = mgr.watch()
                if status == ElasticStatus.RESTART:
                    break
                time.sleep(0.2)
            assert status == ElasticStatus.RESTART
            assert mgr.dead_workers() == [1]
            mgr.stop()
        finally:
            if proc.poll() is None:
                proc.kill()


class TestLaunchRelaunch:
    def test_failed_worker_relaunched_and_resumes(self, tmp_path):
        """Launcher-level fault tolerance: the worker crashes on its first
        life, is relaunched with PADDLE_RESTART_COUNT=1, restores its
        'checkpoint' and succeeds."""
        from paddle_tpu.distributed.launch.main import launch

        ckpt = tmp_path / "ckpt.txt"
        script = tmp_path / "train.py"
        script.write_text(
            textwrap.dedent(
                f"""
                import os, sys
                ckpt = {str(ckpt)!r}
                restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
                if restart == 0:
                    with open(ckpt, "w") as f:
                        f.write("step=3")
                    sys.exit(1)  # simulated crash mid-training
                assert os.path.exists(ckpt), "checkpoint lost across relaunch"
                state = open(ckpt).read()
                assert state == "step=3"
                print(f"resumed from {{state}} on restart {{restart}}")
                """
            )
        )
        rc = launch(["--max_restarts", "1", "--nproc_per_node", "1", str(script)])
        assert rc == 0

    def test_no_restarts_fails_job(self, tmp_path):
        from paddle_tpu.distributed.launch.main import launch

        script = tmp_path / "always_fail.py"
        script.write_text("import sys; sys.exit(7)\n")
        rc = launch(["--max_restarts", "0", "--nproc_per_node", "1", str(script)])
        assert rc == 7

    def test_group_restart_relaunches_all_local_workers(self, tmp_path):
        """When one rank dies, the WHOLE local group restarts — surviving
        ranks are stuck in collectives and a lone fresh process could never
        rejoin (reference elastic manager restarts all local trainers)."""
        from paddle_tpu.distributed.launch.main import launch

        marker = tmp_path / "lives.txt"
        script = tmp_path / "train.py"
        script.write_text(
            textwrap.dedent(
                f"""
                import os, sys, time
                rank = int(os.environ["PADDLE_TRAINER_ID"])
                restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
                with open({str(marker)!r}, "a") as f:
                    f.write(f"rank{{rank}}-life{{restart}}\\n")
                if restart == 0:
                    if rank == 0:
                        sys.exit(3)      # rank 0 crashes
                    time.sleep(60)       # rank 1 'hangs in a collective'
                """
            )
        )
        rc = launch(["--max_restarts", "1", "--nproc_per_node", "2", str(script)])
        assert rc == 0
        lives = set(marker.read_text().split())
        # both ranks ran life 0 AND both were relaunched for life 1
        assert {"rank0-life0", "rank1-life0", "rank0-life1", "rank1-life1"} <= lives


class TestElasticScaling:
    """r4: scale-in/out envelope, endpoint rebuild, watchdog fault wiring
    (reference elastic manager.py:128-251 + CommTaskManager integration)."""

    def _mgr(self, world="4", rank=0, ttl=5.0):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=3)
        return ElasticManager(store, rank=rank, world_size=world, ttl=ttl), store

    def test_np_range_parsing(self):
        mgr, _ = self._mgr(world="2:4")
        assert mgr.min_np == 2 and mgr.max_np == 4 and mgr.world_size == 4

    def test_scale_decisions(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticStatus

        mgr, store = self._mgr(world="2:4", ttl=30.0)
        now = str(time.time()).encode()
        # 4 alive -> HOLD
        for r in range(4):
            store.set(f"elastic/0/beat/{r}", now)
        assert mgr.watch() == ElasticStatus.HOLD
        # 3 alive (within [2,4)) -> RESTART (scale-in)
        store.set("elastic/0/beat/3", b"0.0")
        assert mgr.watch() == ElasticStatus.RESTART
        # 1 alive (< min_np) -> ERROR
        for r in (1, 2):
            store.set(f"elastic/0/beat/{r}", b"0.0")
        assert mgr.watch() == ElasticStatus.ERROR

    def test_rebuild_endpoints_dense_ranks_and_generation(self):
        mgr, store = self._mgr(world="2:4", ttl=30.0)
        now = str(time.time()).encode()
        for r in (0, 1, 3):  # rank 2 died
            store.set(f"elastic/0/beat/{r}", now)
        topo = mgr.rebuild_endpoints()
        assert topo["world_size"] == 3
        assert topo["rank_map"] == {0: 0, 1: 1, 3: 2}
        assert topo["my_rank"] == 0
        assert topo["generation"] == 1
        # workers read the published membership after relaunch
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        loaded = ElasticManager.load_topology(store)
        assert loaded == {"generation": 1, "world_size": 3, "members": [0, 1, 3]}
        # a second rebuild bumps the generation
        assert mgr.rebuild_endpoints()["generation"] == 2

    def test_watchdog_fault_marks_worker_dead(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus

        mgr, store = self._mgr(world="1:2", ttl=30.0)
        now = str(time.time()).encode()
        store.set("elastic/0/beat/0", now)
        store.set("elastic/0/beat/1", now)
        assert mgr.watch() == ElasticStatus.HOLD
        # rank 1's watchdog fires: heartbeat still fresh, but faulted
        peer = ElasticManager(store, rank=1, world_size="1:2", ttl=30.0)
        peer.watchdog_hook()({"section": "train_step"})
        assert mgr.alive_workers() == [0]
        assert mgr.watch() == ElasticStatus.RESTART

    def test_generation_bump_invalidates_stale_state(self):
        """r4 review: rebuild must not let old-topology beats/faults poison
        the new one, and a re-registered worker sheds its fault mark."""
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        mgr, store = self._mgr(world="2:4", ttl=30.0)
        now = str(time.time()).encode()
        for r in (0, 1, 3):
            store.set(f"elastic/0/beat/{r}", now)
        topo = mgr.rebuild_endpoints()
        assert topo["generation"] == 1
        # old gen-0 beats are invisible now: nobody alive until re-register
        assert mgr.alive_workers() == []
        # survivors re-register under the new generation
        w0 = ElasticManager(store, rank=0, world_size="2:4", ttl=30.0)
        w0.register()
        assert mgr.alive_workers() == [0]
        w0.stop()
        # a previously-faulted worker that re-registers is healthy again
        w1 = ElasticManager(store, rank=1, world_size="2:4", ttl=30.0)
        w1.register()
        w1.report_fault("hang")
        assert mgr.alive_workers() == [0]
        w1.register()  # relaunch: clean fault state
        assert sorted(mgr.alive_workers()) == [0, 1]
        w1.stop()

    def test_rebuild_garbage_collects_old_generation_keys(self):
        """CM1003 sweep fix: the generation bump namespaces beat/fault keys
        but used to strand the old generation's keys in the store forever —
        2*max_np keys leaked per restart for the life of the job. Rebuild
        must delete the superseded family."""
        mgr, store = self._mgr(world="2:4", ttl=30.0)
        now = str(time.time()).encode()
        for r in (0, 1, 3):
            store.set(f"elastic/0/beat/{r}", now)
        store.set("elastic/0/fault/2", b"1.0|hang")
        assert store.check("elastic/0/beat/0")
        mgr.rebuild_endpoints()
        # every gen-0 beat/fault key is gone, not merely ignored
        for r in range(4):
            assert not store.check(f"elastic/0/beat/{r}"), r
            assert not store.check(f"elastic/0/fault/{r}"), r
        # the published topology survives the GC
        assert store.check("elastic/generation")
        assert store.check("elastic/world")

    def test_rebuild_tolerates_store_without_delete(self):
        """Duck-typed stores without ``delete`` (older deployments) keep the
        pre-GC behavior: rebuild succeeds, keys merely leak."""
        mgr, store = self._mgr(world="2:4", ttl=30.0)

        class NoDelete:
            def __init__(self, inner):
                self._inner = inner

            def set(self, k, v):
                return self._inner.set(k, v)

            def get(self, k):
                return self._inner.get(k)

            def check(self, k):
                return self._inner.check(k)

        mgr._store = NoDelete(store)
        now = str(time.time()).encode()
        for r in (0, 1):
            store.set(f"elastic/0/beat/{r}", now)
        topo = mgr.rebuild_endpoints()
        assert topo["generation"] == 1 and topo["world_size"] == 2
        assert store.check("elastic/0/beat/0")  # leaked, by design


# -- PR 6 fault-tolerance layer ----------------------------------------------

class TestFaultInjector:
    """Deterministic, site-based injection (testing/faults.py)."""

    def test_parse_spec_round_trip(self):
        spec = "engine.decode:3:InjectedFault;collective.all_reduce:0:RuntimeError"
        plan = faults.FaultPlan.parse(spec)
        assert plan.spec() == spec
        assert plan.triggers[0].exception is faults.InjectedFault
        assert plan.triggers[1].exception is RuntimeError

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError, match="fault-plan entry"):
            faults.FaultPlan.parse("no-colons-here")
        with pytest.raises(ValueError, match="unknown exception"):
            faults.FaultPlan.parse("site:0:NotAnException")

    def test_seeded_sample_is_deterministic(self):
        a = faults.FaultPlan.sample(["s1", "s2", "s3"], n_faults=4, seed=7)
        b = faults.FaultPlan.sample(["s1", "s2", "s3"], n_faults=4, seed=7)
        assert a == b  # same seed -> same plan, replayable from the seed alone
        c = faults.FaultPlan.sample(["s1", "s2", "s3"], n_faults=4, seed=8)
        assert a != c

    def test_same_plan_same_trigger(self):
        """Same plan over the same deterministic workload fires at the SAME
        call — the property every recovery test in this file leans on."""

        def workload():
            fired_at = None
            for i in range(10):
                try:
                    faults.fault_point("det.site")
                except faults.InjectedFault:
                    fired_at = i
            return fired_at

        plan = faults.FaultPlan.single("det.site", 6)
        with faults.inject(plan):
            first = workload()
        with faults.inject(plan):
            second = workload()
        assert first == second == 6

    def test_trigger_fires_at_most_once(self):
        plan = faults.FaultPlan.single("once.site", 0)
        with faults.inject(plan):
            with pytest.raises(faults.InjectedFault):
                faults.fault_point("once.site")
            for _ in range(5):
                faults.fault_point("once.site")  # consumed: no re-fire

    def test_inactive_is_noop_and_counts_reset_on_install(self):
        faults.fault_point("never.registered")  # no plan: must be free & silent
        plan = faults.FaultPlan.single("cnt.site", 99)
        with faults.inject(plan):
            faults.fault_point("cnt.site")
            faults.fault_point("cnt.site")
            assert faults.site_call_count("cnt.site") == 2
        # plan uninstalled: counting stopped, state cleared
        faults.fault_point("cnt.site")
        with faults.inject(plan):
            assert faults.site_call_count("cnt.site") == 0  # fresh install

    def test_flag_activation_and_clear(self):
        import paddle_tpu as paddle

        try:
            paddle.set_flags(
                {"FLAGS_fault_inject_plan": "flag.site:1:MemoryError"}
            )
            faults.fault_point("flag.site")  # call 0: no trigger
            with pytest.raises(MemoryError):
                faults.fault_point("flag.site")  # call 1: boom
        finally:
            paddle.set_flags({"FLAGS_fault_inject_plan": ""})
        faults.fault_point("flag.site")  # cleared: inert again

    def test_injected_faults_counted(self):
        import paddle_tpu as paddle
        from paddle_tpu import observability as obs

        prior = paddle.get_flags(["FLAGS_enable_metrics"])
        paddle.set_flags({"FLAGS_enable_metrics": True})
        obs.GLOBAL_METRICS.reset()
        try:
            with faults.inject(faults.FaultPlan.single("counted.site", 0)):
                with pytest.raises(faults.InjectedFault):
                    faults.fault_point("counted.site")
            c = obs.GLOBAL_METRICS.get("faults_injected_total")
            assert c.value(site="counted.site") == 1
        finally:
            paddle.set_flags(prior)


class TestCollectiveSiteInjection:
    """All 13 collective entry points are fault sites through the same
    instrumented wrapper that feeds their metrics."""

    def test_injection_raises_through_instrumented_wrapper(self):
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist

        t = paddle.to_tensor(np.ones((2,), np.float32))
        with faults.inject(faults.FaultPlan.single("collective.all_reduce", 0)):
            with pytest.raises(faults.InjectedFault, match="collective.all_reduce"):
                dist.all_reduce(t)
        # consumed + uninstalled: the same call now goes through
        dist.all_reduce(t)

    def test_every_entry_point_is_a_site(self):
        """The wrapper computes its site name from the wrapped fn — pin the
        full 13-op surface so a new collective can't silently miss it."""
        import paddle_tpu.distributed.collective as coll

        expected = [
            "all_reduce", "all_gather", "reduce", "reduce_scatter",
            "broadcast", "scatter", "alltoall", "alltoall_single",
            "ppermute", "send", "recv", "batch_isend_irecv", "barrier",
        ]
        for op in expected:
            fn = getattr(coll, op)
            assert hasattr(fn, "__wrapped__"), f"{op} is not instrumented"

    def test_barrier_site_fires(self):
        import paddle_tpu.distributed as dist

        with faults.inject(faults.FaultPlan.single("collective.barrier", 0, RuntimeError)):
            with pytest.raises(RuntimeError, match="collective.barrier"):
                dist.barrier()


def _tiny_engine(seed=0, **kw):
    import paddle_tpu as paddle
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 16)
    return m, cfg, ContinuousBatchingEngine(m, **kw)


class TestEngineRecovery:
    """The tentpole acceptance: a mid-workload decode fault is survived with
    byte-identical tokens, exactly-once finished delivery, and the 2-compile
    invariant intact."""

    def _workload(self, cfg, rng, n=5):
        specs = [(5, 6), (7, 4), (3, 9), (6, 2), (2, 7)][:n]
        return [
            (rng.integers(0, cfg.vocab_size, (p,)).astype(np.int32), t)
            for p, t in specs
        ]

    def test_recovery_tokens_byte_identical_one_compile(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.inference import ContinuousBatchingEngine

        m, cfg, eng_a = _tiny_engine(seed=20, max_slots=3)
        rng = np.random.default_rng(20)
        work = self._workload(cfg, rng)
        rids_a = [eng_a.add_request(p, max_new_tokens=t) for p, t in work]
        out_a = eng_a.run()
        assert eng_a.stats["recoveries"] == 0

        obs.GLOBAL_WATCHDOG.reset()
        eng_b = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, prompt_bucket=16
        )
        rids_b = [eng_b.add_request(p, max_new_tokens=t) for p, t in work]
        with faults.inject(faults.FaultPlan.single("engine.decode", 3)):
            out_b = eng_b.run()

        assert eng_b.stats["recoveries"] == 1
        for ra, rb in zip(rids_a, rids_b):
            np.testing.assert_array_equal(
                out_a[ra].tokens(), out_b[rb].tokens()
            )
            assert out_a[ra].finish_reason == out_b[rb].finish_reason
        # the 1-compile invariant holds ACROSS a recovery: replay reuses
        # the one compiled program (recompile watchdog is the honesty source)
        rep = {
            k: v["count"]
            for k, v in obs.GLOBAL_WATCHDOG.report().items()
            if k.startswith("ContinuousBatchingEngine.")
        }
        assert rep == {"ContinuousBatchingEngine.step": 1}
        assert eng_b.stats["step_traces"] == 1
        s = eng_b.pool_stats()
        assert s["free"] + s["cached_blocks"] == eng_b.num_blocks

    def test_prefill_fault_recovers_too(self):
        m, cfg, eng = _tiny_engine(seed=21)
        rng = np.random.default_rng(21)
        rids = [
            eng.add_request(
                rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
                max_new_tokens=3,
            )
            for _ in range(3)
        ]
        # second prefill dispatch dies "consuming buffers": the first
        # admitted request must be replayed and all three finish
        with faults.inject(faults.FaultPlan.single("engine.prefill", 1)):
            out = eng.run()
        assert set(out) == set(rids)
        assert eng.stats["recoveries"] == 1
        assert all(len(r.generated) == 3 for r in out.values())

    def test_finished_exactly_once_across_recovery(self):
        m, cfg, eng = _tiny_engine(seed=22, max_slots=2)
        rng = np.random.default_rng(22)
        # mixed budgets so some requests finish before/around the fault
        rids = [
            eng.add_request(
                rng.integers(0, cfg.vocab_size, (int(n),)).astype(np.int32),
                max_new_tokens=int(t),
            )
            for n, t in [(4, 2), (6, 5), (3, 3), (5, 4), (2, 6)]
        ]
        delivered = []
        with faults.inject(faults.FaultPlan.single("engine.decode", 2)):
            while eng.has_work():
                delivered += [r.req_id for r in eng.step()]
        assert sorted(delivered) == sorted(rids)  # everyone, exactly once
        assert len(set(delivered)) == len(delivered)
        assert eng.run() == {}  # nothing retained, nothing re-delivered

    def test_retries_exhausted_is_permanent_failure(self):
        m, cfg, eng = _tiny_engine(seed=23, max_recoveries=1)
        rng = np.random.default_rng(23)
        eng.add_request(
            rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
            max_new_tokens=4,
        )
        # faults on the original dispatch AND every retry: recovery exhausts
        plan = faults.FaultPlan(
            [faults.FaultTrigger("engine.decode", i) for i in range(8)]
        )
        with faults.inject(plan):
            with pytest.raises(faults.InjectedFault):
                eng.run()
        # permanently failed: the hard RuntimeError contract
        with pytest.raises(RuntimeError, match="build a new"):
            eng.step()
        with pytest.raises(RuntimeError, match="build a new"):
            eng.add_request(np.zeros((2,), np.int32))

    def test_intake_during_recovery_enqueues(self):
        """Recovery is an engine-internal condition, not a caller error:
        add_request mid-recovery queues the request instead of raising."""
        m, cfg, eng = _tiny_engine(seed=24)
        rng = np.random.default_rng(24)
        r0 = eng.add_request(
            rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
            max_new_tokens=4,
        )
        late_prompt = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
        orig_recover = eng.recover
        late = []

        def recover_with_intake():
            late.append(eng.add_request(late_prompt, max_new_tokens=2))
            orig_recover()

        eng.recover = recover_with_intake
        with faults.inject(faults.FaultPlan.single("engine.decode", 1)):
            out = eng.run()
        assert late and set(out) == {r0, late[0]}
        assert len(out[late[0]].generated) == 2


class TestCrashConsistentCheckpoints:
    """Atomic writes + content-hash manifests + managed retention."""

    def _state(self, paddle, fill=1.0):
        return {
            "w": paddle.to_tensor(np.full((3, 2), fill, np.float32)),
            "sched": {"last_epoch": 4},
        }

    def test_manifest_carries_hashes_and_load_verifies(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.checkpoint import (
            load_state_dict,
            save_state_dict,
        )
        from paddle_tpu.distributed.checkpoint.load_state_dict import _read_metadata

        path = str(tmp_path / "ckpt")
        save_state_dict({"w": paddle.to_tensor(np.ones((4,), np.float32))}, path)
        (meta,) = _read_metadata(path)
        assert meta.file_hashes  # every payload hashed
        # corrupt one byte -> load refuses instead of serving garbage
        npz = glob.glob(os.path.join(path, "*.distcp.npz"))[0]
        data = bytearray(open(npz, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(npz, "wb").write(bytes(data))
        with pytest.raises(ValueError, match="content hash"):
            load_state_dict(
                {"w": paddle.to_tensor(np.zeros((4,), np.float32))}, path
            )

    def test_latest_valid_skips_torn_payload(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu import observability as obs
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        prior = paddle.get_flags(["FLAGS_enable_metrics"])
        paddle.set_flags({"FLAGS_enable_metrics": True})
        obs.GLOBAL_METRICS.reset()
        try:
            mgr = CheckpointManager(str(tmp_path), keep=3)
            mgr.save(self._state(paddle, 1.0), 0)
            mgr.save(self._state(paddle, 2.0), 1)
            npz = glob.glob(os.path.join(mgr._dir(1), "*.distcp.npz"))[0]
            with open(npz, "r+b") as f:
                f.truncate(os.path.getsize(npz) // 2)  # torn write
            rec = mgr.latest_valid()
            assert rec is not None and rec.step == 0
            skipped = obs.GLOBAL_METRICS.get("checkpoints_skipped_torn_total")
            assert skipped.value() == 1
            # restoring from it serves step 0's values
            target = self._state(paddle, 0.0)
            info = mgr.restore(target, step=rec.step)
            assert info["step"] == 0
            np.testing.assert_array_equal(
                np.asarray(target["w"].numpy()), np.full((3, 2), 1.0, np.float32)
            )
        finally:
            paddle.set_flags(prior)

    def test_mid_save_fault_leaves_previous_checkpoint(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path), keep=3)
        mgr.save(self._state(paddle, 1.0), 0)
        with faults.inject(faults.FaultPlan.single("checkpoint.write", 0, OSError)):
            with pytest.raises(OSError):
                mgr.save(self._state(paddle, 2.0), 1)
        # the aborted save committed nothing: no step-1 dir, no staging litter
        assert mgr.steps() == [0]
        assert not glob.glob(os.path.join(str(tmp_path), ".staging*"))
        rec = mgr.latest_valid()
        assert rec is not None and rec.step == 0

    def test_retention_keeps_last_k(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path), keep=2)
        for s in range(4):
            mgr.save(self._state(paddle, float(s)), s)
        assert mgr.steps() == [2, 3]

    def test_missing_manifest_is_invalid(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path), keep=3)
        mgr.save(self._state(paddle, 1.0), 0)
        for f in glob.glob(os.path.join(mgr._dir(0), "*.metadata")):
            os.remove(f)
        assert mgr.latest_valid() is None


class TestResilientTrainLoop:
    """CommWatchdog + checkpoint-resume composition: a WatchdogTimeout /
    backend error resumes from the last good step instead of dying."""

    def _build(self, paddle):
        paddle.seed(0)
        w = paddle.to_tensor(np.ones((4,), np.float32))
        w.stop_gradient = False
        # a stable name, as real Layer parameters have: the optimizer's
        # accumulator checkpoint keys are name-derived, and resume across
        # process lives needs them to match
        w.name = "resilient_w"
        opt = paddle.optimizer.AdamW(learning_rate=0.1, parameters=[w])

        def step_fn_factory(fail_at=None):
            tripped = []

            def step_fn(step):
                if fail_at is not None and step == fail_at and not tripped:
                    tripped.append(step)
                    raise WatchdogTimeout(f"simulated hang at step {step}")
                loss = (w * w).sum()
                loss.backward()
                opt.step()
                opt.clear_grad()

            return step_fn

        return w, opt, step_fn_factory

    def test_resumes_from_last_good_step_bit_exact(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed import resilient_train_loop
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        # fault-free reference
        w0, opt0, mk0 = self._build(paddle)
        m0 = CheckpointManager(str(tmp_path / "a"), keep=3)
        s0 = resilient_train_loop(mk0(), {"w": w0}, 6, m0, optimizer=opt0)
        assert s0["failures"] == 0
        ref = np.asarray(w0.numpy()).copy()

        # watchdog-wrapped run that "hangs" once at step 3
        w1, opt1, mk1 = self._build(paddle)
        m1 = CheckpointManager(str(tmp_path / "b"), keep=3)
        wd = CommWatchdog(timeout=30.0)
        s1 = resilient_train_loop(
            mk1(fail_at=3), {"w": w1}, 6, m1, optimizer=opt1, watchdog=wd
        )
        assert s1["failures"] == 1
        assert s1["resumes"][0]["failed_step"] == 3
        assert s1["resumes"][0]["resumed_from"] == 2
        np.testing.assert_array_equal(np.asarray(w1.numpy()), ref)
        # the watchdog history names WHAT fired — no stderr scraping
        bad = [e for e in wd.completed if e["exc_type"] == "WatchdogTimeout"]
        assert bad and bad[0]["section"] == "train_step_3"

    def test_resumes_across_process_lives(self, tmp_path):
        """A second loop over the same manager (the relaunch scenario)
        starts after the last checkpointed step, not from zero."""
        import paddle_tpu as paddle
        from paddle_tpu.distributed import resilient_train_loop
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        w0, opt0, mk0 = self._build(paddle)
        mgr = CheckpointManager(str(tmp_path), keep=3)
        resilient_train_loop(mk0(), {"w": w0}, 3, mgr, optimizer=opt0)

        w1, opt1, mk1 = self._build(paddle)  # fresh objects = fresh process
        summary = resilient_train_loop(mk1(), {"w": w1}, 6, mgr, optimizer=opt1)
        assert summary["start_step"] == 3  # resumed, not restarted

        # equals a straight 6-step run
        w2, opt2, mk2 = self._build(paddle)
        m2 = CheckpointManager(str(tmp_path / "ref"), keep=3)
        resilient_train_loop(mk2(), {"w": w2}, 6, m2, optimizer=opt2)
        np.testing.assert_array_equal(np.asarray(w1.numpy()), np.asarray(w2.numpy()))

    def test_persistent_fault_escalates(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed import resilient_train_loop
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        w, opt, _ = self._build(paddle)

        def always_fails(step):
            raise RuntimeError("backend down")

        mgr = CheckpointManager(str(tmp_path), keep=3)
        with pytest.raises(RuntimeError, match="backend down"):
            resilient_train_loop(
                always_fails, {"w": w}, 4, mgr, optimizer=opt, max_failures=2
            )


class TestReviewHardening:
    """Review fixes: interrupt transparency, salvage of undelivered results,
    save failures inside the recovery budget, re-save atomicity."""

    def test_keyboard_interrupt_is_never_a_recovery_trigger(self):
        m, cfg, eng = _tiny_engine(seed=30)
        rng = np.random.default_rng(30)
        eng.add_request(
            rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
            max_new_tokens=4,
        )
        eng._buffers_lost = lambda: True  # even with donated buffers gone

        def interrupted(*a, **k):
            raise KeyboardInterrupt()

        eng._step_fn = interrupted
        with pytest.raises(KeyboardInterrupt):
            eng.step()
        # propagated directly: no recovery attempt consumed the interrupt,
        # and the engine is not marked permanently failed by it
        assert eng.stats["recoveries"] == 0
        assert not eng._broken

    def test_drain_finished_salvages_after_permanent_failure(self):
        m, cfg, eng = _tiny_engine(seed=31, max_recoveries=0)
        rng = np.random.default_rng(31)
        eng.add_request(
            rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
            max_new_tokens=4,
        )
        # shed into the pending-delivery buffer (deadline already expired)
        # during the same step whose dispatch then permanently fails
        done_rid = eng.add_request(
            rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32),
            max_new_tokens=4, deadline=time.perf_counter() - 1.0,
        )
        with faults.inject(
            faults.FaultPlan([faults.FaultTrigger("engine.decode", i) for i in range(4)])
        ):
            with pytest.raises(faults.InjectedFault):
                eng.run()
        with pytest.raises(RuntimeError, match="build a new"):
            eng.step()
        salvaged = eng.drain_finished()  # works even on a broken engine
        assert [r.req_id for r in salvaged] == [done_rid]
        assert salvaged[0].finished
        assert eng.drain_finished() == []  # exactly-once: drained

    def test_resilient_loop_survives_transient_save_failure(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed import resilient_train_loop
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        paddle.seed(0)
        w = paddle.to_tensor(np.ones((4,), np.float32))
        w.stop_gradient = False
        w.name = "resilient_w"
        opt = paddle.optimizer.AdamW(learning_rate=0.1, parameters=[w])

        def step_fn(step):
            loss = (w * w).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()

        mgr = CheckpointManager(str(tmp_path), keep=3)
        # one checkpoint.write fault: the save of some step dies mid-write;
        # the loop must count it against the budget and resume, not die
        with faults.inject(
            faults.FaultPlan.single("checkpoint.write", 5, OSError)
        ):
            summary = resilient_train_loop(
                step_fn, {"w": w}, 5, mgr, optimizer=opt, max_failures=2
            )
        assert summary["failures"] == 1
        assert mgr.latest_valid() is not None

    def test_resave_same_step_survives_aborted_commit(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path), keep=3)
        state = {"w": paddle.to_tensor(np.ones((2,), np.float32))}
        mgr.save(state, 0)
        # redoing the SAME step dies mid-write: the previously committed
        # step-0 checkpoint must still be there and valid
        with faults.inject(faults.FaultPlan.single("checkpoint.write", 0, OSError)):
            with pytest.raises(OSError):
                mgr.save(state, 0)
        rec = mgr.latest_valid()
        assert rec is not None and rec.step == 0
        # ... and a successful redo replaces it cleanly
        mgr.save(state, 0)
        assert mgr.latest_valid().step == 0
        assert not glob.glob(os.path.join(str(tmp_path), ".trash*"))
        assert not glob.glob(os.path.join(str(tmp_path), ".staging*"))
