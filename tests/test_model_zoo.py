"""Model-zoo breadth (VERDICT r5 #10): vision models (vgg/mobilenet v1-v3/
lenet/alexnet/squeezenet/shufflenetv2), paddle.audio features, paddle.text
surface — parity smoke tests with shape/grad checks."""

import io
import os
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M

rng = np.random.default_rng(0)


@pytest.mark.slow
class TestVisionModels:
    @pytest.mark.parametrize(
        "build",
        [M.vgg11, M.mobilenet_v1, M.mobilenet_v2, M.mobilenet_v3_small,
         M.mobilenet_v3_large, M.squeezenet1_0, M.squeezenet1_1,
         M.shufflenet_v2_x1_0, M.alexnet],
        ids=lambda f: f.__name__,
    )
    def test_forward_shape(self, build):
        paddle.seed(0)
        m = build(num_classes=5)
        m.eval()
        x = paddle.to_tensor(rng.normal(size=(2, 3, 64, 64)).astype(np.float32))
        out = m(x)
        assert list(out.shape) == [2, 5]
        assert np.isfinite(out.numpy()).all()

    def test_vgg_batch_norm_variant(self):
        paddle.seed(0)
        m = M.vgg11(batch_norm=True, num_classes=3)
        bns = [l for _, l in m.named_sublayers() if isinstance(l, paddle.nn.BatchNorm2D)]
        assert len(bns) == 8

    def test_lenet_trains(self):
        import paddle_tpu.nn.functional as F

        paddle.seed(0)
        m = M.LeNet(num_classes=10)
        opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
        x = paddle.to_tensor(rng.normal(size=(8, 1, 28, 28)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 10, (8,)).astype(np.int64))
        losses = []
        for _ in range(5):
            loss = F.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_mobilenet_v2_grads_flow(self):
        paddle.seed(0)
        m = M.mobilenet_v2(scale=0.35, num_classes=4)
        x = paddle.to_tensor(rng.normal(size=(1, 3, 32, 32)).astype(np.float32))
        m(x).sum().backward()
        grads = [p.grad for p in m.parameters() if not p.stop_gradient]
        assert all(g is not None for g in grads)

    def test_scale_variants(self):
        m = M.mobilenet_v1(scale=0.5, num_classes=2)
        assert m.fc.weight.shape[0] == 512  # 1024 * 0.5


class TestAudio:
    def _wav(self, t=2000, sr=8000):
        x = np.sin(2 * np.pi * 440 * np.arange(t) / sr).astype(np.float32)
        return paddle.to_tensor(x[None])

    def test_windows_match_scipy(self):
        import scipy.signal as ss

        import paddle_tpu.audio.functional as AF

        for name in ["hamming", "hann", "blackman", "bartlett", "nuttall",
                     "cosine", "bohman", "triang"]:
            ours = AF.get_window(name, 64, fftbins=True).numpy()
            ref = ss.get_window(name, 64, fftbins=True)
            np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6, err_msg=name)
        np.testing.assert_allclose(
            AF.get_window(("kaiser", 8.0), 33).numpy(),
            ss.get_window(("kaiser", 8.0), 33), rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            AF.get_window(("gaussian", 5.0), 32).numpy(),
            ss.get_window(("gaussian", 5.0), 32), rtol=1e-5, atol=1e-6,
        )

    def test_mel_filterbank_matches_librosa_formula(self):
        import paddle_tpu.audio.functional as AF

        fb = AF.compute_fbank_matrix(sr=8000, n_fft=256, n_mels=20).numpy()
        assert fb.shape == (20, 129)
        assert (fb >= 0).all() and fb.sum() > 0
        # slaney normalization: filters integrate to ~2/bandwidth
        assert fb.max() < 1.0

    def test_spectrogram_peak_at_tone(self):
        import paddle_tpu.audio as A

        sr, f0 = 8000, 440.0
        spec = A.Spectrogram(n_fft=512, hop_length=256)(self._wav(sr=sr)).numpy()
        freqs = np.linspace(0, sr / 2, 257)
        peak = freqs[spec[0].mean(-1).argmax()]
        assert abs(peak - f0) < 20

    def test_melspectrogram_and_mfcc_shapes(self):
        import paddle_tpu.audio as A

        wav = self._wav()
        mel = A.MelSpectrogram(sr=8000, n_fft=256, n_mels=32)(wav)
        assert list(mel.shape)[:2] == [1, 32]
        logmel = A.LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32, top_db=80.0)(wav)
        assert np.isfinite(logmel.numpy()).all()
        mfcc = A.MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)(wav)
        assert list(mfcc.shape)[:2] == [1, 13]

    def test_power_to_db_topdb_floor(self):
        import paddle_tpu.audio.functional as AF

        x = paddle.to_tensor(np.array([1.0, 1e-12], np.float32))
        db = AF.power_to_db(x, top_db=30.0).numpy()
        assert db[0] == pytest.approx(0.0) and db[1] == pytest.approx(-30.0)


class TestText:
    def test_viterbi_decoder_layer(self):
        import paddle_tpu.text as T

        N = 3
        trans = rng.normal(size=(N + 2, N + 2)).astype(np.float32)
        dec = T.ViterbiDecoder(paddle.to_tensor(trans))
        pot = paddle.to_tensor(rng.normal(size=(2, 5, N)).astype(np.float32))
        lens = paddle.to_tensor(np.array([3, 5], np.int32))
        scores, paths = dec(pot, lens)
        assert list(paths.shape) == [2, 5]
        assert np.isfinite(scores.numpy()).all()

    def test_uci_housing_parses_and_normalizes(self, tmp_path):
        import paddle_tpu.text as T

        data = rng.normal(size=(50, 14)).astype(np.float32)
        f = tmp_path / "housing.data"
        np.savetxt(f, data)
        train = T.UCIHousing(data_file=str(f), mode="train")
        test = T.UCIHousing(data_file=str(f), mode="test")
        assert len(train) == 40 and len(test) == 10
        feat, target = train[0]
        assert feat.shape == (13,) and target.shape == (1,)

    def test_imdb_from_tar(self, tmp_path):
        import paddle_tpu.text as T

        tar_path = tmp_path / "aclImdb.tar.gz"
        with tarfile.open(tar_path, "w:gz") as tf:
            for i, (split, pol, text) in enumerate([
                ("train", "pos", b"a great great movie truly great"),
                ("train", "neg", b"a bad bad film truly bad"),
                ("train", "pos", b"great film"),
                ("train", "neg", b"bad movie"),
            ]):
                info = tarfile.TarInfo(f"aclImdb/train/{pol}/{i}.txt")
                info.size = len(text)
                tf.addfile(info, io.BytesIO(text))
        ds = T.Imdb(data_file=str(tar_path), mode="train", cutoff=2)
        assert len(ds) == 4
        ids, label = ds[0]
        assert ids.dtype == np.int64 and label in (0, 1)
        assert b"great" in ds.word_idx and b"bad" in ds.word_idx

    def test_imikolov_ngrams(self, tmp_path):
        import paddle_tpu.text as T

        f = tmp_path / "ptb.train.txt"
        f.write_text("the cat sat on the mat\nthe dog sat on the rug\n")
        ds = T.Imikolov(data_file=str(f), window_size=3, min_word_freq=2)
        assert len(ds) > 0
        assert all(g.shape == (3,) for g in (ds[i] for i in range(len(ds))))

    def test_missing_file_raises_clearly(self):
        import paddle_tpu.text as T

        with pytest.raises(FileNotFoundError, match="data_file"):
            T.UCIHousing(data_file=None)


@pytest.mark.slow
class TestVisionModelsRound2:
    @pytest.mark.parametrize(
        "build,size",
        [(M.densenet121, 64), (M.inception_v3, 128)],
        ids=["densenet121", "inception_v3"],
    )
    def test_forward_shape(self, build, size):
        paddle.seed(0)
        m = build(num_classes=4)
        m.eval()
        x = paddle.to_tensor(rng.normal(size=(1, 3, size, size)).astype(np.float32))
        out = m(x)
        assert list(out.shape) == [1, 4]
        assert np.isfinite(out.numpy()).all()

    def test_googlenet_returns_main_and_aux(self):
        paddle.seed(0)
        m = M.googlenet(num_classes=4)
        m.eval()
        x = paddle.to_tensor(rng.normal(size=(1, 3, 96, 96)).astype(np.float32))
        out, aux1, aux2 = m(x)
        for o in (out, aux1, aux2):
            assert list(o.shape) == [1, 4]
            assert np.isfinite(o.numpy()).all()

    def test_densenet_variants_channel_math(self):
        # densenet161: init 96, growth 48 -> final features 2208
        m = M.densenet161(num_classes=3)
        assert m.classifier.weight.shape[0] == 2208


class TestAudioBackend:
    def test_wav_roundtrip(self, tmp_path):
        import paddle_tpu.audio as A

        sr = 8000
        wav = np.sin(2 * np.pi * 440 * np.arange(1600) / sr).astype(np.float32)
        path = str(tmp_path / "tone.wav")
        A.save(path, paddle.to_tensor(wav[None]), sr)
        meta = A.info(path)
        assert meta.sample_rate == sr and meta.num_frames == 1600
        assert meta.num_channels == 1 and meta.bits_per_sample == 16
        loaded, sr2 = A.load(path)
        assert sr2 == sr and list(loaded.shape) == [1, 1600]
        np.testing.assert_allclose(loaded.numpy()[0], wav, atol=1e-3)

    def test_wav_offset_and_channels_last(self, tmp_path):
        import paddle_tpu.audio as A

        sr = 4000
        stereo = np.stack([np.ones(100, np.float32) * 0.5,
                           -np.ones(100, np.float32) * 0.5])
        path = str(tmp_path / "st.wav")
        A.save(path, paddle.to_tensor(stereo), sr)
        out, _ = A.load(path, frame_offset=10, num_frames=20, channels_first=False)
        assert list(out.shape) == [20, 2]
        assert abs(float(out.numpy()[0, 0]) - 0.5) < 1e-3

    def test_save_rejects_unsupported_encoding(self, tmp_path):
        import paddle_tpu.audio as A

        with pytest.raises(NotImplementedError, match="PCM_16"):
            A.save(str(tmp_path / "x.wav"), np.zeros((1, 10), np.float32), 8000,
                   encoding="PCM_32", bits_per_sample=32)

    def test_save_int32_rescales(self, tmp_path):
        import paddle_tpu.audio as A

        full = np.full((1, 16), 2**30, np.int32)  # half of int32 full scale
        p = str(tmp_path / "i32.wav")
        A.save(p, full, 8000)
        out, _ = A.load(p)
        assert abs(float(out.numpy()[0, 0]) - 0.5) < 1e-3

    def test_train_test_vocab_shared(self, tmp_path):
        import paddle_tpu.text as T

        tar_path = tmp_path / "aclImdb.tar.gz"
        with tarfile.open(tar_path, "w:gz") as tf:
            for split, pol, i, text in [
                ("train", "pos", 0, b"great great great movie"),
                ("train", "neg", 1, b"bad bad bad film"),
                ("test", "pos", 2, b"great film"),
                ("test", "neg", 3, b"bad movie"),
            ]:
                info = tarfile.TarInfo(f"aclImdb/{split}/{pol}/{i}.txt")
                info.size = len(text)
                tf.addfile(info, io.BytesIO(text))
        tr = T.Imdb(data_file=str(tar_path), mode="train", cutoff=2)
        te = T.Imdb(data_file=str(tar_path), mode="test", cutoff=2)
        assert tr.word_idx == te.word_idx  # shared (train-derived) ids


class TestAudioDatasets:
    def _make_esc50(self, tmp_path):
        import paddle_tpu.audio as A

        root = tmp_path / "ESC-50-master"
        (root / "meta").mkdir(parents=True)
        (root / "audio").mkdir()
        rows = ["filename,fold,target,category,esc10,src_file,take"]
        for i in range(6):
            name = f"clip{i}.wav"
            wav = np.sin(np.arange(400) * (0.1 + 0.01 * i)).astype(np.float32)
            A.save(str(root / "audio" / name), wav[None], 8000)
            rows.append(f"{name},{i % 3 + 1},{i % 2},x,False,s,1")
        (root / "meta" / "esc50.csv").write_text("\n".join(rows))
        return str(root)

    def test_esc50_folds_and_features(self, tmp_path):
        from paddle_tpu.audio.datasets import ESC50

        root = self._make_esc50(tmp_path)
        train = ESC50(data_dir=root, mode="train", split_fold=1)
        dev = ESC50(data_dir=root, mode="dev", split_fold=1)
        assert len(train) == 4 and len(dev) == 2  # folds 2,3 vs fold 1
        wav, label = train[0]
        assert wav.shape[-1] == 400 and label in (0, 1)
        mfcc_ds = ESC50(data_dir=root, mode="dev", split_fold=1,
                        feat_type="mfcc", n_mfcc=13, n_fft=128, n_mels=20)
        feat, _ = mfcc_ds[0]
        assert list(feat.shape)[:2] == [1, 13]

    def test_tess_emotions_from_filenames(self, tmp_path):
        import paddle_tpu.audio as A
        from paddle_tpu.audio.datasets import TESS

        root = tmp_path / "TESS"
        root.mkdir()
        for i, emo in enumerate(["angry", "happy", "sad", "neutral", "fear"]):
            wav = np.zeros(100, np.float32)
            A.save(str(root / f"OAF_word{i}_{emo}.wav"), wav[None], 8000)
        ds = TESS(data_dir=str(root), mode="train", n_folds=5, split_fold=5)
        assert len(ds) == 4  # one file held out to fold 5
        labels = {ds[i][1] for i in range(len(ds))}
        assert labels <= set(range(7))

    def test_bad_feat_type_rejected(self, tmp_path):
        from paddle_tpu.audio.datasets import AudioClassificationDataset

        with pytest.raises(ValueError, match="feat_type"):
            AudioClassificationDataset([], [], feat_type="chromagram")


def test_esc50_spectrogram_feat_type(tmp_path):
    """feat_type='spectrogram' takes no sr kwarg — regression for the
    extractor-construction crash."""
    import paddle_tpu.audio as A
    from paddle_tpu.audio.datasets import ESC50

    root = tmp_path / "ESC-50-master"
    (root / "meta").mkdir(parents=True)
    (root / "audio").mkdir()
    wav = np.sin(np.arange(600) * 0.1).astype(np.float32)
    A.save(str(root / "audio" / "a.wav"), wav[None], 8000)
    (root / "meta" / "esc50.csv").write_text(
        "filename,fold,target,category,esc10,src_file,take\na.wav,1,0,x,False,s,1"
    )
    ds = ESC50(data_dir=str(root), mode="dev", split_fold=1,
               feat_type="spectrogram", n_fft=128)
    feat, label = ds[0]
    assert feat.shape[-2] == 65 and label == 0  # n_fft//2+1 freq bins
