"""RNN/LSTM/GRU layer tests (reference test model:
``test/dygraph_to_static`` + ``test/rnn/test_rnn_nets.py`` — numeric parity
against torch CPU as the oracle, matching weight layouts)."""

import numpy as np
import pytest
import torch

import paddle_tpu as paddle


RNG = np.random.default_rng(7)
B, T, I, H = 3, 7, 5, 4


def _copy_cell_to_torch(cell, tmod, layer, suffix=""):
    with torch.no_grad():
        getattr(tmod, f"weight_ih_l{layer}{suffix}").copy_(torch.tensor(cell.weight_ih.numpy()))
        getattr(tmod, f"weight_hh_l{layer}{suffix}").copy_(torch.tensor(cell.weight_hh.numpy()))
        getattr(tmod, f"bias_ih_l{layer}{suffix}").copy_(torch.tensor(cell.bias_ih.numpy()))
        getattr(tmod, f"bias_hh_l{layer}{suffix}").copy_(torch.tensor(cell.bias_hh.numpy()))


def _layer_cell(rnn_layer, direction=0):
    if hasattr(rnn_layer, "cell"):
        return rnn_layer.cell
    return rnn_layer.cell_fw if direction == 0 else rnn_layer.cell_bw


def test_lstm_matches_torch():
    x = RNG.standard_normal((B, T, I)).astype(np.float32)
    m = paddle.nn.LSTM(I, H)
    tm = torch.nn.LSTM(I, H, batch_first=True)
    _copy_cell_to_torch(m[0].cell, tm, 0)
    out, (h, c) = m(paddle.to_tensor(x))
    tout, (th, tc) = tm(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(h.numpy(), th.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(c.numpy(), tc.detach().numpy(), atol=1e-5)


def test_gru_matches_torch():
    x = RNG.standard_normal((B, T, I)).astype(np.float32)
    m = paddle.nn.GRU(I, H)
    tm = torch.nn.GRU(I, H, batch_first=True)
    _copy_cell_to_torch(m[0].cell, tm, 0)
    out, h = m(paddle.to_tensor(x))
    tout, th = tm(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(h.numpy(), th.detach().numpy(), atol=1e-5)


def test_simple_rnn_matches_torch():
    x = RNG.standard_normal((B, T, I)).astype(np.float32)
    m = paddle.nn.SimpleRNN(I, H, activation="relu")
    tm = torch.nn.RNN(I, H, nonlinearity="relu", batch_first=True)
    _copy_cell_to_torch(m[0].cell, tm, 0)
    out, h = m(paddle.to_tensor(x))
    tout, th = tm(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), atol=1e-5)


def test_bidirectional_two_layer_lstm_matches_torch():
    x = RNG.standard_normal((B, T, I)).astype(np.float32)
    m = paddle.nn.LSTM(I, H, num_layers=2, direction="bidirect")
    tm = torch.nn.LSTM(I, H, num_layers=2, bidirectional=True, batch_first=True)
    for layer in range(2):
        for d, suf in ((0, ""), (1, "_reverse")):
            _copy_cell_to_torch(_layer_cell(m[layer], d), tm, layer, suf)
    out, (h, c) = m(paddle.to_tensor(x))
    tout, (th, tc) = tm(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(h.numpy(), th.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(c.numpy(), tc.detach().numpy(), atol=1e-5)


def test_time_major():
    x = RNG.standard_normal((T, B, I)).astype(np.float32)
    m = paddle.nn.GRU(I, H, time_major=True)
    out, h = m(paddle.to_tensor(x))
    assert list(out.shape) == [T, B, H]
    # same weights run batch-first must agree
    m2 = paddle.nn.GRU(I, H)
    m2.set_state_dict(m.state_dict())
    out2, _ = m2(paddle.to_tensor(np.swapaxes(x, 0, 1)))
    np.testing.assert_allclose(out.numpy(), np.swapaxes(out2.numpy(), 0, 1), atol=1e-6)


def test_sequence_length_masking():
    x = RNG.standard_normal((B, T, I)).astype(np.float32)
    seq_len = np.array([T, 4, 2], dtype=np.int32)
    m = paddle.nn.LSTM(I, H)
    tm = torch.nn.LSTM(I, H, batch_first=True)
    _copy_cell_to_torch(m[0].cell, tm, 0)
    out, (h, c) = m(paddle.to_tensor(x), sequence_length=paddle.to_tensor(seq_len))
    packed = torch.nn.utils.rnn.pack_padded_sequence(
        torch.tensor(x), torch.tensor(seq_len, dtype=torch.int64), batch_first=True
    )
    tout_p, (th, tc) = tm(packed)
    tout, _ = torch.nn.utils.rnn.pad_packed_sequence(tout_p, batch_first=True, total_length=T)
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(h.numpy(), th.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(c.numpy(), tc.detach().numpy(), atol=1e-5)


def test_sequence_length_reverse_direction():
    x = RNG.standard_normal((B, T, I)).astype(np.float32)
    seq_len = np.array([T, 5, 3], dtype=np.int32)
    cell = paddle.nn.GRUCell(I, H)
    rnn_bw = paddle.nn.RNN(cell, is_reverse=True)
    out, h = rnn_bw(paddle.to_tensor(x), sequence_length=paddle.to_tensor(seq_len))
    # reverse scan with mask: final state equals processing x[:len] backwards
    for b in range(B):
        hb = np.zeros((1, H), np.float32)
        for t in reversed(range(seq_len[b])):
            _, hb_t = cell(paddle.to_tensor(x[b : b + 1, t]), paddle.to_tensor(hb))
            hb = hb_t.numpy()
        np.testing.assert_allclose(h.numpy()[b], hb[0], atol=1e-5)
        # outputs past the valid region are zeroed
        assert np.all(out.numpy()[b, seq_len[b] :] == 0)


def test_lstm_proj_size():
    P = 3
    x = RNG.standard_normal((B, T, I)).astype(np.float32)
    m = paddle.nn.LSTM(I, H, proj_size=P)
    tm = torch.nn.LSTM(I, H, proj_size=P, batch_first=True)
    cell = m[0].cell
    _copy_cell_to_torch(cell, tm, 0)
    with torch.no_grad():
        tm.weight_hr_l0.copy_(torch.tensor(cell.weight_ho.numpy().T))
    out, (h, c) = m(paddle.to_tensor(x))
    tout, (th, tc) = tm(torch.tensor(x))
    assert list(out.shape) == [B, T, P]
    np.testing.assert_allclose(out.numpy(), tout.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(c.numpy(), tc.detach().numpy(), atol=1e-5)


def test_cells_single_step():
    x = RNG.standard_normal((B, I)).astype(np.float32)
    lstm_cell = paddle.nn.LSTMCell(I, H)
    out, (h, c) = lstm_cell(paddle.to_tensor(x))
    assert list(out.shape) == [B, H] and list(c.shape) == [B, H]
    gru_cell = paddle.nn.GRUCell(I, H)
    out, h = gru_cell(paddle.to_tensor(x))
    assert list(out.shape) == [B, H]
    rnn_cell = paddle.nn.SimpleRNNCell(I, H)
    out, h = rnn_cell(paddle.to_tensor(x))
    assert list(out.shape) == [B, H]


def test_rnn_grads_flow_through_scan():
    x = paddle.to_tensor(RNG.standard_normal((B, T, I)).astype(np.float32))
    m = paddle.nn.LSTM(I, H, num_layers=2)
    out, _ = m(x)
    out.sum().backward()
    for p in m.parameters():
        assert p.grad is not None, p.name
        assert np.isfinite(p.grad.numpy()).all()


def test_rnn_grad_matches_torch():
    x = RNG.standard_normal((B, T, I)).astype(np.float32)
    m = paddle.nn.GRU(I, H)
    tm = torch.nn.GRU(I, H, batch_first=True)
    _copy_cell_to_torch(m[0].cell, tm, 0)
    out, _ = m(paddle.to_tensor(x))
    out.sum().backward()
    tx = torch.tensor(x)
    tout, _ = tm(tx)
    tout.sum().backward()
    np.testing.assert_allclose(
        m[0].cell.weight_ih.grad.numpy(), tm.weight_ih_l0.grad.numpy(), atol=1e-4
    )
    np.testing.assert_allclose(
        m[0].cell.weight_hh.grad.numpy(), tm.weight_hh_l0.grad.numpy(), atol=1e-4
    )


def test_rnn_under_jit():
    m = paddle.nn.LSTM(I, H)
    x = paddle.to_tensor(RNG.standard_normal((B, T, I)).astype(np.float32))
    eager, _ = m(x)

    stepped = paddle.jit.to_static(lambda inp: m(inp)[0])
    jitted = stepped(x)
    np.testing.assert_allclose(eager.numpy(), jitted.numpy(), atol=1e-6)


def test_rnn_dropout_between_layers():
    m = paddle.nn.GRU(I, H, num_layers=2, dropout=0.5)
    x = paddle.to_tensor(RNG.standard_normal((B, T, I)).astype(np.float32))
    m.eval()
    o1, _ = m(x)
    o2, _ = m(x)
    np.testing.assert_allclose(o1.numpy(), o2.numpy())  # eval: dropout off
    m.train()
    o3, _ = m(x)
    assert o3.shape == o1.shape


def test_custom_cell_generic_fallback():
    class WrappedGRU(paddle.nn.RNNCellBase):
        def __init__(self, input_size, hidden_size):
            super().__init__()
            self.inner = paddle.nn.GRUCell(input_size, hidden_size)

        @property
        def state_shape(self):
            return (self.inner.hidden_size,)

        def forward(self, inputs, states=None):
            return self.inner(inputs, states)

    x = RNG.standard_normal((B, T, I)).astype(np.float32)
    cell = WrappedGRU(I, H)
    out, h = paddle.nn.RNN(cell)(paddle.to_tensor(x))
    ref_out, ref_h = paddle.nn.RNN(cell.inner)(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref_out.numpy(), atol=1e-6)
    np.testing.assert_allclose(h.numpy(), ref_h.numpy(), atol=1e-6)


def test_custom_cell_generic_fallback_param_grads():
    """ADVICE r3 (high): the generic fallback must pass the cell's params
    through the op so they receive gradients — and backward() must work even
    when the sequence input has stop_gradient=True (the default)."""

    class WrappedGRU(paddle.nn.RNNCellBase):
        def __init__(self, input_size, hidden_size):
            super().__init__()
            self.inner = paddle.nn.GRUCell(input_size, hidden_size)

        @property
        def state_shape(self):
            return (self.inner.hidden_size,)

        def forward(self, inputs, states=None):
            return self.inner(inputs, states)

    x_np = RNG.standard_normal((B, T, I)).astype(np.float32)

    cell = WrappedGRU(I, H)
    x = paddle.to_tensor(x_np)  # stop_gradient=True: params alone drive the tape
    out, _ = paddle.nn.RNN(cell)(x)
    out.sum().backward()
    grads = {}
    for name, p in cell.named_parameters():
        assert p.grad is not None, f"generic-fallback cell param {name} got no grad"
        grads[name] = p.grad.numpy().copy()

    # parity vs the builtin GRU scan path on the same weights
    cell.inner.clear_gradients()
    out_ref, _ = paddle.nn.RNN(cell.inner)(paddle.to_tensor(x_np))
    out_ref.sum().backward()
    for name, p in cell.named_parameters():
        ref = p.grad.numpy()
        np.testing.assert_allclose(grads[name], ref, rtol=1e-4, atol=1e-5)
