"""fused_rope backward-path tests (ROADMAP: "fix the live fused_rope
backward fallback").

The r03 TPU bench log showed the rope kernel silently degrading to XLA in
training ("Linearization failed to produce known values for all output
primals") even though the kernel carries a custom VJP — the generic op
dispatch differentiates its forward with ``jax.vjp`` at record time, and on
the TPU host's jax that linearization-over-``custom_vjp`` is what failed.
The fix routes the rope op around jax AD entirely: an explicit tape
``GradNode`` whose backward calls the standalone adjoint kernel
(``rope_adjoint_pallas``) directly. These tests pin:

- forward/backward numerics of both Pallas kernels (interpret mode) against
  the pure-XLA composition, neox AND interleaved layouts;
- the tape node's gradients (q, k, and table cotangents) against
  ``jax.grad`` of the composition;
- ``paddle_tpu_kernel_fallbacks_total`` staying FLAT across a real train
  step with the Pallas fwd+bwd kernels forced on — the acceptance criterion
  that training no longer silently pays for an XLA fallback;
- double backward (``create_graph=True``) through the registered pure-XLA
  raw op.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.incubate.nn.functional import (
    _rope_adjoint_xla,
    _rope_apply_xla,
    fused_rotary_position_embedding,
)
from paddle_tpu.kernels.fused import fused_rope_pallas, rope_adjoint_pallas


def _tables(rng, s, d):
    cos = np.cos(rng.standard_normal((s, d))).astype(np.float32)
    sin = np.sin(rng.standard_normal((s, d))).astype(np.float32)
    return jnp.asarray(cos), jnp.asarray(sin)


class TestRopeKernels:
    def test_fused_rope_pallas_matches_composition(self):
        rng = np.random.default_rng(0)
        b, s, h, d = 2, 8, 2, 128
        x = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        cos, sin = _tables(rng, s, d)
        y = fused_rope_pallas(x, cos, sin, interpret=True)
        ref = _rope_apply_xla(x, sin, cos, True)
        assert jnp.allclose(y, ref, atol=1e-5)

    def test_rope_adjoint_pallas_matches_vjp(self):
        """The standalone backward kernel IS the composition's vjp."""
        rng = np.random.default_rng(1)
        b, s, h, d = 2, 8, 2, 128
        x = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        g = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        cos, sin = _tables(rng, s, d)
        _, vjp = jax.vjp(lambda t: _rope_apply_xla(t, sin, cos, True), x)
        dx_kernel = rope_adjoint_pallas(g, cos, sin, interpret=True)
        assert jnp.allclose(dx_kernel, vjp(g)[0], atol=1e-5)

    def test_rope_adjoint_asymmetric_tables(self):
        """The adjoint must be exact even when the two sin halves differ —
        no table-symmetry assumption."""
        rng = np.random.default_rng(2)
        b, s, h, d = 1, 4, 1, 128
        x = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        g = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        cos, sin = _tables(rng, s, d)
        sin = sin.at[:, : d // 2].mul(1.7)  # break half-symmetry
        _, vjp = jax.vjp(lambda t: _rope_apply_xla(t, sin, cos, True), x)
        assert jnp.allclose(
            rope_adjoint_pallas(g, cos, sin, interpret=True), vjp(g)[0], atol=1e-5
        )

    def test_adjoint_xla_interleaved_layout(self):
        rng = np.random.default_rng(3)
        b, s, h, d = 2, 4, 2, 8
        x = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        g = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        cos, sin = _tables(rng, s, d)
        _, vjp = jax.vjp(lambda t: _rope_apply_xla(t, sin, cos, False), x)
        assert jnp.allclose(_rope_adjoint_xla(g, sin, cos, False), vjp(g)[0], atol=1e-6)

    def test_jax_grad_through_kernel_custom_vjp(self):
        """Direct jax users (the bench preflight shape) still differentiate
        the kernel through its custom_vjp."""
        rng = np.random.default_rng(4)
        b, s, h, d = 1, 4, 2, 128
        x = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        cos, sin = _tables(rng, s, d)
        gk = jax.grad(
            lambda t: (fused_rope_pallas(t, cos, sin, interpret=True) ** 2).sum()
        )(x)
        gr = jax.grad(lambda t: (_rope_apply_xla(t, sin, cos, True) ** 2).sum())(x)
        assert jnp.allclose(gk, gr, atol=1e-4)


class TestRopeTapeNode:
    def test_tape_grads_match_composition_grad(self):
        rng = np.random.default_rng(5)
        b, s, h, d = 2, 8, 2, 128
        q = paddle.to_tensor(rng.standard_normal((b, s, h, d)).astype(np.float32))
        k = paddle.to_tensor(rng.standard_normal((b, s, h, d)).astype(np.float32))
        q.stop_gradient = False
        k.stop_gradient = False
        cos, sin = _tables(rng, s, d)
        qo, ko, vo = fused_rotary_position_embedding(
            q, k, None, sin=paddle.to_tensor(np.asarray(sin)),
            cos=paddle.to_tensor(np.asarray(cos)),
        )
        assert vo is None
        loss = (qo * qo).sum() + (ko * ko * 0.5).sum()
        loss.backward()
        gq_ref = jax.grad(
            lambda t: (_rope_apply_xla(t, sin, cos, True) ** 2).sum()
        )(q._data)
        gk_ref = jax.grad(
            lambda t: (0.5 * _rope_apply_xla(t, sin, cos, True) ** 2).sum()
        )(k._data)
        assert jnp.allclose(q.grad._data, gq_ref, atol=1e-4)
        assert jnp.allclose(k.grad._data, gk_ref, atol=1e-4)

    def test_tape_table_cotangents(self):
        """sin/cos marked differentiable get exact grads (reduced over the
        broadcast) — matches jax.grad of the composition."""
        rng = np.random.default_rng(6)
        b, s, h, d = 2, 4, 2, 8
        q = paddle.to_tensor(rng.standard_normal((b, s, h, d)).astype(np.float32))
        q.stop_gradient = False
        cos, sin = _tables(rng, s, d)
        sin_t = paddle.to_tensor(np.asarray(sin))
        cos_t = paddle.to_tensor(np.asarray(cos))
        sin_t.stop_gradient = False
        cos_t.stop_gradient = False
        qo, _, _ = fused_rotary_position_embedding(q, None, None, sin=sin_t, cos=cos_t)
        (qo * qo).sum().backward()
        gs_ref = jax.grad(
            lambda t: (_rope_apply_xla(q._data, t, cos, True) ** 2).sum()
        )(sin)
        gc_ref = jax.grad(
            lambda t: (_rope_apply_xla(q._data, sin, t, True) ** 2).sum()
        )(cos)
        assert jnp.allclose(sin_t.grad._data, gs_ref, atol=1e-4)
        assert jnp.allclose(cos_t.grad._data, gc_ref, atol=1e-4)

    def test_no_grad_path_records_nothing(self):
        rng = np.random.default_rng(7)
        q = paddle.to_tensor(rng.standard_normal((1, 4, 1, 8)).astype(np.float32))
        cos, sin = _tables(rng, 4, 8)
        with paddle.no_grad():
            qo, _, _ = fused_rotary_position_embedding(
                q, sin=paddle.to_tensor(np.asarray(sin)),
                cos=paddle.to_tensor(np.asarray(cos)),
            )
        assert qo.stop_gradient and qo.grad_node is None

    def test_double_backward_through_raw_op(self):
        """create_graph re-differentiation goes through the registered
        pure-XLA raw op (fwd_fn) — grad-of-grad works and never needs a
        Pallas rule."""
        rng = np.random.default_rng(8)
        q = paddle.to_tensor(rng.standard_normal((1, 4, 2, 8)).astype(np.float32))
        q.stop_gradient = False
        cos, sin = _tables(rng, 4, 8)
        qo, _, _ = fused_rotary_position_embedding(
            q, sin=paddle.to_tensor(np.asarray(sin)),
            cos=paddle.to_tensor(np.asarray(cos)),
        )
        (g1,) = paddle.grad([(qo ** 3).sum()], [q], create_graph=True)
        (g2,) = paddle.grad([(g1 ** 2).sum()], [q])
        ref = jax.grad(
            lambda t: (
                jax.grad(lambda u: (_rope_apply_xla(u, sin, cos, True) ** 3).sum())(t)
                ** 2
            ).sum()
        )(q._data)
        assert jnp.allclose(g2._data, ref, atol=1e-3)


class TestRopeTrainStepFallbackFlat:
    def test_train_step_pallas_rope_no_fallbacks(self, monkeypatch):
        """Force the Pallas fwd+bwd rope kernels (interpret mode) through a
        REAL recompute+to_static train step and assert:

        - both kernels actually ran (fwd on forward+recompute-replay, the
          adjoint on backward),
        - ``paddle_tpu_kernel_fallbacks_total`` stays flat for fused_rope /
          fused_rope_bwd (the r03 regression: training silently paying for
          an XLA fallback),
        - the loss still trains.
        """
        import paddle_tpu.kernels.fused as fused
        import paddle_tpu.kernels.select as sel
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.observability import get_registry

        orig_enabled = sel.pallas_enabled
        monkeypatch.setattr(
            sel, "pallas_enabled",
            lambda flag: flag == "use_pallas_fused" or orig_enabled(flag),
        )
        fwd_calls, bwd_calls = [0], [0]
        orig_rope = fused.fused_rope_pallas
        orig_adj = fused.rope_adjoint_pallas

        def counted_rope(*a, **kw):
            fwd_calls[0] += 1
            return orig_rope(*a, interpret=True, **kw)

        def counted_adj(*a, **kw):
            bwd_calls[0] += 1
            return orig_adj(*a, interpret=True, **kw)

        monkeypatch.setattr(fused, "fused_rope_pallas", counted_rope)
        monkeypatch.setattr(fused, "rope_adjoint_pallas", counted_adj)
        monkeypatch.setattr(
            fused, "fused_rms_norm_pallas",
            functools.partial(fused.fused_rms_norm_pallas, interpret=True),
        )

        def fallback_counts():
            snap = get_registry().snapshot()
            out = {}
            for key, val in snap.items():
                name = key[0] if isinstance(key, tuple) else str(key)
                if "fallbacks" in str(name):
                    out[str(key)] = val
            return out

        before = fallback_counts()
        cfg = LlamaConfig(
            hidden_size=256, intermediate_size=256, num_hidden_layers=1,
            num_attention_heads=2, num_key_value_heads=2, vocab_size=64,
            max_position_embeddings=32, recompute=True,
        )
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(
            learning_rate=1e-3, parameters=model.parameters(), multi_precision=True
        )

        @paddle.jit.to_static
        def train_step(model, opt, ids, labels):
            loss, _ = model(ids, labels=labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, 64, (2, 8)).astype(np.int32))
        labels = paddle.to_tensor(rng.integers(0, 64, (2, 8)).astype(np.int32))
        first = float(train_step(model, opt, ids, labels))
        last = float(train_step(model, opt, ids, labels))

        assert fwd_calls[0] > 0, "Pallas rope forward never ran"
        assert bwd_calls[0] > 0, "Pallas rope adjoint never ran in backward"
        assert last < first, f"loss did not decrease ({first} -> {last})"
        after = fallback_counts()
        rope_deltas = {
            k: after.get(k, 0) - before.get(k, 0)
            for k in set(before) | set(after)
            if "rope" in k
        }
        assert not any(rope_deltas.values()), (
            f"fused_rope fell back to XLA during the train step: {rope_deltas}"
        )


class TestFusedDecodeEpilogueFallbackFlat:
    """Satellite pin: the NEW fused decode-layer epilogues are counted in
    ``paddle_tpu_kernel_fallbacks_total`` per kernel label, and the CPU
    REFERENCE path (pallas ineligible by backend, so the XLA composition is
    the intended route, not a degradation) keeps every one of those counters
    flat — fwd AND tape backward."""

    LABELS = (
        "fused_rms_norm_residual",
        "fused_rms_norm_residual_bwd",
        "fused_layer_norm_residual",
        "fused_layer_norm_residual_bwd",
        "fused_embed_norm",
        "paged_flash_chunk_fused",
        "paged_flash_decode_fused",
    )

    @staticmethod
    def _fallback_counts():
        """Flatten ``paddle_tpu_kernel_fallbacks_total`` to
        ``{kernel_label: value}``."""
        from paddle_tpu.observability import get_registry

        out = {}
        for name, data in get_registry().snapshot().items():
            if "fallbacks" not in str(name) or not isinstance(data, dict):
                continue
            for row in data.get("values", []):
                labels = row.get("labels") or {}
                out[labels.get("kernel", str(labels))] = row.get("value", 0)
        return out

    def test_cpu_reference_path_counters_flat(self):
        from paddle_tpu.incubate.nn.functional import (
            fused_embed_rms_norm,
            fused_layer_norm_residual,
            fused_rms_norm_residual,
        )

        prior = paddle.get_flags(["FLAGS_enable_metrics"])["FLAGS_enable_metrics"]
        paddle.set_flags({"FLAGS_enable_metrics": True})
        try:
            before = self._fallback_counts()
            rng = np.random.default_rng(0)
            x = paddle.to_tensor(rng.standard_normal((2, 4, 64)).astype(np.float32))
            res = paddle.to_tensor(rng.standard_normal((2, 4, 64)).astype(np.float32))
            w = paddle.to_tensor(np.ones(64, np.float32))
            b = paddle.to_tensor(np.zeros(64, np.float32))
            for t in (x, res, w, b):
                t.stop_gradient = False

            y, r = fused_rms_norm_residual(x, w, res)
            (y.sum() + r.sum()).backward()
            y2, r2 = fused_layer_norm_residual(x, w, b, res)
            (y2.sum() + r2.sum()).backward()

            ids = paddle.to_tensor(rng.integers(0, 16, (2, 4)).astype(np.int32))
            table = paddle.to_tensor(rng.standard_normal((16, 64)).astype(np.float32))
            emb, normed = fused_embed_rms_norm(ids, table, w.detach())
            assert emb.shape == [2, 4, 64] and normed.shape == [2, 4, 64]

            after = self._fallback_counts()
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": prior})
        deltas = {
            k: after.get(k, 0) - before.get(k, 0)
            for k in set(before) | set(after)
            if k in self.LABELS
        }
        assert not any(deltas.values()), (
            f"CPU reference path incremented fused-epilogue fallback counters: {deltas}"
        )

    def test_enabled_but_failing_kernel_increments_counter(self, monkeypatch):
        """The counter is live, not vestigial: force-enable pallas for the
        fused epilogues on CPU — the kernel path raises off-TPU, warn_fallback
        fires, and the per-kernel label moves."""
        import paddle_tpu.kernels.fused as fused
        import paddle_tpu.kernels.select as sel
        from paddle_tpu.incubate.nn.functional import fused_rms_norm_residual

        orig_enabled = sel.pallas_enabled
        monkeypatch.setattr(
            sel, "pallas_enabled",
            lambda flag: flag == "use_pallas_fused" or orig_enabled(flag),
        )

        def boom(*a, **kw):
            raise RuntimeError("no TPU in this test")

        monkeypatch.setattr(fused, "fused_rms_norm_residual_pallas", boom)
        prior = paddle.get_flags(["FLAGS_enable_metrics"])["FLAGS_enable_metrics"]
        paddle.set_flags({"FLAGS_enable_metrics": True})
        try:
            before = self._fallback_counts()
            rng = np.random.default_rng(1)
            x = paddle.to_tensor(rng.standard_normal((2, 128)).astype(np.float32))
            res = paddle.to_tensor(rng.standard_normal((2, 128)).astype(np.float32))
            w = paddle.to_tensor(np.ones(128, np.float32))
            fused_rms_norm_residual(x, w, res)
            after = self._fallback_counts()
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": prior})
        assert after.get("fused_rms_norm_residual", 0) > before.get(
            "fused_rms_norm_residual", 0
        ), "warn_fallback never incremented the fused_rms_norm_residual label"
