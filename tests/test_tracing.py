"""Per-request distributed tracing + the always-on flight recorder (PR 8).

Pins the acceptance contract: a traced request through ``ServingFrontend``
yields a span tree whose queue → prefill → decode → stream phases are
properly nested under one root and sum to the observed end-to-end latency;
sampling is deterministic by seed; with ``FLAGS_trace_sample_rate=0`` the
per-request tracing surface is one cached-bool read and the recompile
watchdog still reports exactly 2 engine compiles; an injected permanent
engine failure produces a flight-recorder dump — redacted of prompt
content — readable by ``python -m paddle_tpu.observability.dump``.

Everything runs on CPU with the tiny Llama config, same as test_serving.py.
"""

import http.client
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import dump as dump_cli
from paddle_tpu.observability import flight_recorder as flightrec
from paddle_tpu.observability import tracing
from paddle_tpu.serving import (
    ServingConfig,
    ServingFrontend,
    start_serving_server,
    stop_serving_server,
)
from paddle_tpu.testing import faults


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _frontend(seed=0, max_queue=8, **engine_kw):
    m, cfg = _model(seed)
    engine_kw.setdefault("max_slots", 2)
    engine_kw.setdefault("block_size", 4)
    engine_kw.setdefault("prompt_bucket", 8)
    eng = ContinuousBatchingEngine(m, **engine_kw)
    fe = ServingFrontend(eng, ServingConfig(max_queue=max_queue))
    return fe, eng, cfg


def _prompt(rng, cfg, n=4):
    return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)


def _drain(fe, handles, max_iters=500):
    done = []
    for _ in range(max_iters):
        done += fe.pump()
        if all(h.finished for h in handles):
            return done
    raise AssertionError("requests did not reach a terminal state")


@pytest.fixture
def tracing_on():
    """Sample everything, deterministically, into a clean store."""
    prior = paddle.get_flags(["FLAGS_trace_sample_rate", "FLAGS_trace_seed"])
    paddle.set_flags({"FLAGS_trace_sample_rate": 1.0, "FLAGS_trace_seed": 1234})
    obs.GLOBAL_TRACER.clear()
    obs.GLOBAL_WATCHDOG.reset()
    yield obs.GLOBAL_TRACER
    paddle.set_flags(prior)
    obs.GLOBAL_TRACER.clear()


# -- traceparent + context ----------------------------------------------------

class TestTraceparent:
    def test_round_trip(self):
        ctx = tracing.TraceContext("ab" * 16, "cd" * 8, None, sampled=True)
        header = tracing.format_traceparent(ctx)
        assert header == f"00-{'ab' * 16}-{'cd' * 8}-01"
        back = tracing.parse_traceparent(header)
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id
        assert back.sampled is True

    def test_unsampled_flag(self):
        ctx = tracing.TraceContext("ab" * 16, "cd" * 8, None, sampled=False)
        assert tracing.format_traceparent(ctx).endswith("-00")
        assert tracing.parse_traceparent(
            tracing.format_traceparent(ctx)
        ).sampled is False

    @pytest.mark.parametrize(
        "bad",
        [
            None,
            "",
            "garbage",
            "00-zz-cd-01",
            "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",  # all-zero trace id
            "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # all-zero span id
            "00-" + "ab" * 16 + "-" + "cd" * 8,  # missing flags
        ],
    )
    def test_malformed_headers_ignored(self, bad):
        assert tracing.parse_traceparent(bad) is None

    def test_incoming_header_pins_trace_id_and_sampling(self):
        t = tracing.Tracer(capacity=16, seed=1)
        hdr = f"00-{'ab' * 16}-{'cd' * 8}-01"
        ctx = t.start_trace(hdr, sample_rate=0.0)  # header overrides the coin
        assert ctx.trace_id == "ab" * 16
        assert ctx.parent_id == "cd" * 8
        assert ctx.span_id != "cd" * 8  # fresh root span for this hop
        assert ctx.sampled is True
        off = t.start_trace(f"00-{'ab' * 16}-{'cd' * 8}-00", sample_rate=1.0)
        assert off.sampled is False  # upstream said no; respect it


class TestSampling:
    def test_deterministic_by_seed(self):
        a = tracing.Tracer(capacity=16, seed=7)
        b = tracing.Tracer(capacity=16, seed=7)
        da = [a.start_trace(sample_rate=0.5) for _ in range(64)]
        db = [b.start_trace(sample_rate=0.5) for _ in range(64)]
        assert [c.sampled for c in da] == [c.sampled for c in db]
        assert [c.trace_id for c in da] == [c.trace_id for c in db]
        assert 0 < sum(c.sampled for c in da) < 64  # actually a coin
        c = tracing.Tracer(capacity=16, seed=8)
        dc = [c.start_trace(sample_rate=0.5) for _ in range(64)]
        assert [x.trace_id for x in dc] != [x.trace_id for x in da]

    def test_rate_bounds(self):
        t = tracing.Tracer(capacity=16, seed=3)
        assert not any(
            t.start_trace(sample_rate=0.0).sampled for _ in range(32)
        )
        assert all(t.start_trace(sample_rate=1.0).sampled for _ in range(32))

    def test_flag_seed_reseeds_global_tracer(self):
        prior = paddle.get_flags(["FLAGS_trace_seed"])
        try:
            paddle.set_flags({"FLAGS_trace_seed": 99})
            a = obs.GLOBAL_TRACER.start_trace(sample_rate=1.0)
            paddle.set_flags({"FLAGS_trace_seed": 99})
            b = obs.GLOBAL_TRACER.start_trace(sample_rate=1.0)
            assert a.trace_id == b.trace_id  # same seed -> same id stream
        finally:
            paddle.set_flags(prior)

    def test_partial_rate_does_not_flood_with_contextless_spans(self):
        """Collective wrappers have no request context to sample against:
        at a partial rate they must stay silent (tracing_full gate), or the
        ring would fill with unattributable spans and evict the sampled
        request trees the rate was chosen to capture."""
        from paddle_tpu.distributed import collective as coll

        prior = paddle.get_flags(["FLAGS_trace_sample_rate"])
        try:
            paddle.set_flags({"FLAGS_trace_sample_rate": 0.01})
            assert tracing.tracing_enabled() and not tracing.tracing_full()
            obs.GLOBAL_TRACER.clear()
            coll.barrier()
            assert [
                s for s in obs.GLOBAL_TRACER.spans()
                if s["name"].startswith("collective.")
            ] == []
            paddle.set_flags({"FLAGS_trace_sample_rate": 1.0})
            assert tracing.tracing_full()
            coll.barrier()
            assert [
                s["name"] for s in obs.GLOBAL_TRACER.spans()
                if s["name"].startswith("collective.")
            ] == ["collective.barrier"]
        finally:
            paddle.set_flags(prior)
            obs.GLOBAL_TRACER.clear()

    def test_env_seeding(self):
        from paddle_tpu.flags import FlagRegistry

        reg = FlagRegistry()
        reg.define("trace_sample_rate", float, 0.0, "")
        os.environ["FLAGS_trace_sample_rate"] = "0.25"
        try:
            assert reg.get("trace_sample_rate") == 0.25
        finally:
            del os.environ["FLAGS_trace_sample_rate"]


# -- span store ---------------------------------------------------------------

class TestSpanStore:
    def test_bounded_store_drops_oldest(self):
        t = tracing.Tracer(capacity=4, seed=0)
        for i in range(10):
            t.add_span(f"s{i}", start_s=0.0, end_s=1.0)
        names = [s["name"] for s in t.spans()]
        assert names == ["s6", "s7", "s8", "s9"]
        assert t.dropped == 6

    def test_span_context_manager_records_error_status(self):
        t = tracing.Tracer(capacity=16, seed=0)
        with pytest.raises(ValueError):
            with t.span("boom"):
                raise ValueError("x")
        (rec,) = t.spans()
        assert rec["status"] == "error:ValueError"

    def test_unsampled_parent_records_nothing(self):
        t = tracing.Tracer(capacity=16, seed=0)
        ctx = tracing.TraceContext("ab" * 16, "cd" * 8, sampled=False)
        with t.span("child", parent=ctx) as sp:
            sp.set_attr("k", 1)
        assert t.spans() == []
        t.add_event("e", ctx=ctx)  # unsampled events are dropped too
        assert t.records() == []

    def test_jsonl_export_and_cli_chrome_conversion(self, tmp_path):
        t = tracing.Tracer(capacity=16, seed=0)
        with t.span("parent") as sp:
            with t.span("child", parent=sp):
                pass
        p = tmp_path / "spans.jsonl"
        assert t.export_jsonl(str(p)) == 2
        lines = [json.loads(x) for x in p.read_text().splitlines()]
        assert {x["name"] for x in lines} == {"parent", "child"}
        out = tmp_path / "chrome.json"
        assert dump_cli.main([str(p), "--to-chrome", str(out)]) == 0
        trace = json.loads(out.read_text())
        assert len(trace["traceEvents"]) == 2
        assert all(ev["ph"] == "X" for ev in trace["traceEvents"])

    def test_profiler_export_merges_tracer_spans(self, tmp_path):
        import paddle_tpu.profiler as profiler

        obs.GLOBAL_TRACER.clear()
        obs.GLOBAL_TRACER.add_span("traced_phase", start_s=1.0, end_s=2.0)
        prof = profiler.Profiler()
        prof.start()
        prof.stop()
        path = tmp_path / "trace.json"
        prof.export(str(path))
        events = json.loads(path.read_text())["traceEvents"]
        assert any(ev["name"] == "traced_phase" for ev in events)
        # drained: a second export does not duplicate the span
        prof.export(str(path))
        events = json.loads(path.read_text())["traceEvents"]
        assert not any(ev["name"] == "traced_phase" for ev in events)


# -- the acceptance span tree -------------------------------------------------

class TestRequestSpanTree:
    PHASES = ("request.queue_wait", "request.prefill", "request.decode",
              "request.stream_out")

    def test_phases_nest_and_sum_to_e2e(self, tracing_on):
        fe, eng, cfg = _frontend(seed=1)
        rng = np.random.default_rng(1)
        handles = [
            fe.submit(_prompt(rng, cfg), max_new_tokens=4) for _ in range(3)
        ]
        _drain(fe, handles)
        assert all(h.outcome == "ok" for h in handles)
        for h in handles:
            tid = h.trace_ctx.trace_id
            spans = tracing_on.spans(tid)
            by_name = {s["name"]: s for s in spans}
            assert set(by_name) == {"request", *self.PHASES}
            root = by_name["request"]
            # every phase parented to the root, nested inside its interval
            for name in self.PHASES:
                s = by_name[name]
                assert s["parent_id"] == root["span_id"]
                assert s["ts_us"] >= root["ts_us"] - 1.0
                assert (
                    s["ts_us"] + s["dur_us"]
                    <= root["ts_us"] + root["dur_us"] + 1.0
                )
            # phases tile the root: their durations sum to the observed E2E
            phase_sum = sum(by_name[n]["dur_us"] for n in self.PHASES)
            assert phase_sum == pytest.approx(root["dur_us"], rel=1e-6, abs=5.0)
            # terminal outcome annotated on the root
            assert root["attrs"]["outcome"] == "ok"
            assert root["attrs"]["n_generated"] == 4
            assert by_name["request.decode"]["attrs"]["decode_steps"] >= 1

    def test_decode_steps_annotated_with_slot_membership(self, tracing_on):
        fe, eng, cfg = _frontend(seed=2)
        rng = np.random.default_rng(2)
        handles = [
            fe.submit(_prompt(rng, cfg), max_new_tokens=4) for _ in range(2)
        ]
        _drain(fe, handles)
        steps = [s for s in tracing_on.spans() if s["name"] == "engine.decode_step"]
        assert steps, "no batch-step spans recorded"
        ids = {h.id for h in handles}
        seen = set()
        for s in steps:
            assert set(s["attrs"]["slot_req_ids"].values()) <= ids
            assert s["attrs"]["n_active"] >= 1
            seen |= set(s["attrs"]["slot_req_ids"].values())
        assert seen == ids  # every request rode at least one annotated step
        # the per-request share is derived from the steps it rode: the sum
        # of all even splits equals the summed step durations
        share_total = sum(h.inner.decode_share_s for h in handles)
        step_total = sum(s["dur_us"] for s in steps) / 1e6
        assert share_total == pytest.approx(step_total, rel=1e-6)

    def test_engine_compiles_stay_at_one_with_tracing_on(self, tracing_on):
        fe, eng, cfg = _frontend(seed=3)
        rng = np.random.default_rng(3)
        handles = [
            fe.submit(_prompt(rng, cfg, n=3 + (i % 3)), max_new_tokens=3)
            for i in range(5)
        ]
        _drain(fe, handles)
        counts = obs.GLOBAL_WATCHDOG.counts()
        assert counts.get("ContinuousBatchingEngine.step") == 1

    def test_intake_rejection_still_gets_a_terminal_root_span(self, tracing_on):
        from paddle_tpu.serving import Overloaded

        fe, eng, cfg = _frontend(seed=7, max_queue=1)
        rng = np.random.default_rng(7)
        fe.submit(_prompt(rng, cfg), max_new_tokens=4)  # fills the queue
        with pytest.raises(Overloaded):
            fe.submit(_prompt(rng, cfg), max_new_tokens=4)
        sheds = [
            s for s in tracing_on.spans()
            if s["name"] == "request" and s["status"] == "shed:queue_full"
        ]
        assert len(sheds) == 1
        assert sheds[0]["attrs"]["outcome"] == "queue_full"

    def test_shed_request_still_gets_a_terminal_span_tree(self, tracing_on):
        fe, eng, cfg = _frontend(seed=4)
        rng = np.random.default_rng(4)
        h = fe.submit(_prompt(rng, cfg), max_new_tokens=64)
        assert fe.cancel(h.id)
        spans = tracing_on.spans(h.trace_ctx.trace_id)
        by_name = {s["name"]: s for s in spans}
        root = by_name["request"]
        assert root["attrs"]["outcome"] == "cancelled"
        assert root["status"] == "shed:cancelled"
        # never admitted: queue_wait + stream_out only, still tiling E2E
        assert "request.prefill" not in by_name
        phase_sum = sum(
            s["dur_us"] for n, s in by_name.items() if n != "request"
        )
        assert phase_sum == pytest.approx(root["dur_us"], rel=1e-6, abs=5.0)


class TestTracingOffPath:
    def test_off_path_is_one_cached_bool_read(self):
        assert paddle.get_flags(["FLAGS_trace_sample_rate"])[
            "FLAGS_trace_sample_rate"
        ] == 0.0
        assert not tracing.tracing_enabled()
        obs.GLOBAL_TRACER.clear()
        rng_state_before = obs.GLOBAL_TRACER._rng.getstate()
        fe, eng, cfg = _frontend(seed=5)
        rng = np.random.default_rng(5)
        h = fe.submit(_prompt(rng, cfg), max_new_tokens=4)
        _drain(fe, [h])
        assert h.outcome == "ok"
        # no context, no ids drawn, no spans stored, no shares accumulated:
        # the entire tracing surface of the request was the cached-bool gate
        assert h.trace_ctx is None
        assert h.traceparent is None
        assert h.inner.trace is None
        assert h.inner.decode_steps == 0 and h.inner.decode_share_s == 0.0
        assert obs.GLOBAL_TRACER.records() == []
        assert obs.GLOBAL_TRACER._rng.getstate() == rng_state_before

    def test_watchdog_still_reports_one_compile_with_rate_zero(self):
        obs.GLOBAL_WATCHDOG.reset()
        fe, eng, cfg = _frontend(seed=6)
        rng = np.random.default_rng(6)
        hs = [fe.submit(_prompt(rng, cfg), max_new_tokens=3) for _ in range(3)]
        _drain(fe, hs)
        counts = obs.GLOBAL_WATCHDOG.counts()
        assert counts.get("ContinuousBatchingEngine.step") == 1


# -- HTTP propagation ---------------------------------------------------------

@pytest.fixture
def http_frontend():
    fe, eng, cfg = _frontend(seed=12, max_queue=4)
    srv = start_serving_server(fe, port=0)
    port = srv.server_address[1]
    yield fe, eng, cfg, port
    stop_serving_server(fe)


def _post(port, payload, headers=None, timeout=30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", "/v1/generate", json.dumps(payload), hdrs)
    resp = conn.getresponse()
    body = resp.read().decode()
    out_headers = dict(resp.getheaders())
    conn.close()
    return resp.status, body, out_headers


class TestHTTPTraceparent:
    def test_round_trip_through_the_endpoint(self, http_frontend, tracing_on):
        fe, eng, cfg, port = http_frontend
        rng = np.random.default_rng(0)
        upstream_trace = "ab" * 16
        upstream_span = "cd" * 8
        status, body, headers = _post(
            port,
            {"prompt": _prompt(rng, cfg).tolist(), "max_new_tokens": 3},
            headers={"traceparent": f"00-{upstream_trace}-{upstream_span}-01"},
        )
        assert status == 200
        # the response names the request's root span INSIDE the caller's trace
        tp = headers.get("traceparent")
        assert tp is not None
        ctx = tracing.parse_traceparent(tp)
        assert ctx.trace_id == upstream_trace
        assert ctx.span_id != upstream_span
        assert ctx.sampled is True
        # the recorded root span parents to the upstream hop's span
        spans = tracing_on.spans(upstream_trace)
        root = [s for s in spans if s["name"] == "request"][0]
        assert root["parent_id"] == upstream_span
        assert root["span_id"] == ctx.span_id
        assert {"request.queue_wait", "request.prefill", "request.decode",
                "request.stream_out"} <= {s["name"] for s in spans}

    def test_no_header_with_tracing_off_means_no_trace(self, http_frontend):
        fe, eng, cfg, port = http_frontend
        rng = np.random.default_rng(1)
        status, body, headers = _post(
            port, {"prompt": _prompt(rng, cfg).tolist(), "max_new_tokens": 2}
        )
        assert status == 200
        assert "traceparent" not in {k.lower() for k in headers}


# -- flight recorder ----------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded(self):
        fr = flightrec.FlightRecorder(capacity=8)
        for i in range(50):
            fr.record("tick", i=i)
        events = fr.snapshot()
        assert len(events) == 8
        assert [e["i"] for e in events] == list(range(42, 50))

    def test_dump_redacts_prompt_content(self, tmp_path):
        fr = flightrec.FlightRecorder(capacity=8)
        fr.record("admit", req_id=1, prompt=[5, 6, 7], prompt_len=3)
        fr.record("nested", payload={"tokens": [1, 2], "reason": "ok"})
        path = fr.dump("unit", path=str(tmp_path / "d.json"),
                       extra={"prompt": "secret text"})
        data = json.loads((tmp_path / "d.json").read_text())
        text = json.dumps(data)
        assert "secret text" not in text
        assert "[5, 6, 7]" not in text
        ev = data["events"][0]
        assert ev["prompt"] == "<redacted:3>"
        assert ev["prompt_len"] == 3  # sizes survive, content does not
        assert data["events"][1]["payload"]["tokens"] == "<redacted:2>"
        assert data["extra"]["prompt"].startswith("<redacted:")
        assert path == str(tmp_path / "d.json")

    def test_safe_dump_swallows_injected_export_fault(self, tmp_path):
        fr = flightrec.FlightRecorder(capacity=8)
        fr.record("tick")
        plan = faults.FaultPlan(
            [faults.FaultTrigger("tracing.export", 0),
             faults.FaultTrigger("tracing.export", 1)]
        )
        with faults.inject(plan):
            assert fr.safe_dump("unit", path=str(tmp_path / "x.json")) is None
            with pytest.raises(faults.InjectedFault):
                fr.dump("unit", path=str(tmp_path / "y.json"))
        assert not (tmp_path / "x.json").exists()

    def test_export_site_registered_and_zero_cost_when_empty(self):
        assert "tracing.export" in faults.KNOWN_SITES
        from paddle_tpu.testing.faults import _ACTIVE

        assert not _ACTIVE[0]
        flightrec.GLOBAL_FLIGHT_RECORDER.record("tick")
        # no plan installed: the site does not even count calls
        t = tracing.Tracer(capacity=4, seed=0)
        t.add_span("s", start_s=0.0, end_s=1.0)
        assert faults.site_call_count("tracing.export") == 0

    def test_cli_exit_codes(self, tmp_path):
        assert dump_cli.main([str(tmp_path / "missing.json")]) == 2
        empty = tmp_path / "empty.json"
        empty.write_text("")
        assert dump_cli.main([str(empty)]) == 2
        corrupt = tmp_path / "corrupt.json"
        corrupt.write_text("{not json")
        assert dump_cli.main([str(corrupt)]) == 2
        # a JSON file that is neither a flight dump nor span records
        wrong = tmp_path / "wrong.jsonl"
        wrong.write_text('{"foo": 1}\n')
        assert dump_cli.main([str(wrong)]) == 2

    def test_cli_survives_cyclic_parent_chain(self, tmp_path):
        # a corrupt/hand-edited JSONL with a self-parenting span must not
        # hang the postmortem script
        p = tmp_path / "cyclic.jsonl"
        p.write_text(
            json.dumps({"kind": "span", "name": "a", "trace_id": "t",
                        "span_id": "s1", "parent_id": "s2", "ts_us": 0.0,
                        "dur_us": 1.0, "attrs": {}}) + "\n"
            + json.dumps({"kind": "span", "name": "b", "trace_id": "t",
                          "span_id": "s2", "parent_id": "s1", "ts_us": 0.0,
                          "dur_us": 1.0, "attrs": {}}) + "\n"
        )
        assert dump_cli.main([str(p)]) == 0  # terminates

    def test_cli_module_entrypoint(self, tmp_path):
        import subprocess
        import sys

        fr = flightrec.FlightRecorder(capacity=4)
        fr.record("admit", req_id=7)
        path = str(tmp_path / "dump.json")
        fr.dump("unit", path=path)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability.dump", path],
            capture_output=True, text=True, env=env,
        )
        assert r.returncode == 0, r.stderr
        assert "reason: unit" in r.stdout
        assert "admit" in r.stdout


class TestBlackBoxOnPermanentFailure:
    def _tiny_engine(self, seed=0, **kw):
        m, cfg = _model(seed)
        kw.setdefault("max_slots", 2)
        kw.setdefault("block_size", 4)
        kw.setdefault("prompt_bucket", 16)
        return m, cfg, ContinuousBatchingEngine(m, **kw)

    def test_dump_emitted_with_failed_requests_timeline(self, tmp_path):
        prior = paddle.get_flags(["FLAGS_flight_recorder_dir"])
        paddle.set_flags({"FLAGS_flight_recorder_dir": str(tmp_path)})
        try:
            obs.GLOBAL_FLIGHT_RECORDER.clear()
            m, cfg, eng = self._tiny_engine(seed=23, max_recoveries=1)
            rng = np.random.default_rng(23)
            rid = eng.add_request(_prompt(rng, cfg), max_new_tokens=4)
            plan = faults.FaultPlan(
                [faults.FaultTrigger("engine.decode", i) for i in range(8)]
            )
            with faults.inject(plan):
                with pytest.raises(faults.InjectedFault):
                    eng.run()
            assert eng.broken
            dumps = sorted(tmp_path.glob("flightrec_*engine_permanent_failure*"))
            assert dumps, "permanent failure produced no flight-recorder dump"
            data = json.loads(dumps[-1].read_text())
            assert data["reason"] == "engine_permanent_failure"
            kinds = [e["kind"] for e in data["events"]]
            # the failed request's lifecycle is in the black box: its admit,
            # the injected faults, the recovery attempt, the death
            assert "admit" in kinds
            assert "fault_injected" in kinds
            assert "recovery" in kinds
            assert "engine_permanent_failure" in kinds
            admits = [e for e in data["events"] if e["kind"] == "admit"]
            assert any(e["req_id"] == rid for e in admits)
            # redaction: no prompt token content anywhere in the dump —
            # any denylisted key that made it in is a length-only marker
            for e in data["events"]:
                for key in ("prompt", "prompt_ids", "tokens", "generated"):
                    if key in e:
                        assert str(e[key]).startswith("<redacted"), e
            # the dump is readable by the CLI
            assert dump_cli.main([str(dumps[-1])]) == 0
        finally:
            paddle.set_flags(prior)

    def test_pump_death_dumps_and_survives_injected_export_fault(self, tmp_path):
        """The serving pump thread dying is the third dump seam — and an
        injected tracing.export fault during THAT dump must not change the
        failure handling (streams still fail explicitly)."""
        prior = paddle.get_flags(["FLAGS_flight_recorder_dir"])
        paddle.set_flags({"FLAGS_flight_recorder_dir": str(tmp_path)})
        try:
            obs.GLOBAL_FLIGHT_RECORDER.clear()
            fe, eng, cfg = _frontend(seed=30)
            rng = np.random.default_rng(30)
            h = fe.submit(_prompt(rng, cfg), max_new_tokens=32)
            fe.pump()  # admit
            # every dump attempt fails at the export site; the pump death
            # path must still fail all live streams explicitly
            plan = faults.FaultPlan(
                [faults.FaultTrigger("tracing.export", i) for i in range(4)]
            )
            with faults.inject(plan):
                fe._fail_all("unit: simulated pump death")
            assert h.finished and h.outcome == "engine_failure"
            assert not list(tmp_path.glob("flightrec_*"))  # dump failed, softly
            # without the fault the same seam produces a readable dump
            fe2, eng2, cfg2 = _frontend(seed=31)
            h2 = fe2.submit(_prompt(rng, cfg2), max_new_tokens=32)
            fe2.pump()
            fe2._fail_all("unit: simulated pump death")
            dumps = sorted(tmp_path.glob("flightrec_*serving_pump_death*"))
            assert dumps
            data = json.loads(dumps[-1].read_text())
            assert any(e["kind"] == "pump_death" for e in data["events"])
            assert dump_cli.main([str(dumps[-1])]) == 0
        finally:
            paddle.set_flags(prior)
