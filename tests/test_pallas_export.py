"""TPU-lowering regression tests that need NO hardware.

``jax.export.export(jax.jit(fn), platforms=['tpu'])`` runs the full Mosaic
kernel lowering on the CPU backend and raises the exact error a real chip
would (BENCH_r02 died on an illegal ``(1, 1, blk_q)`` LSE BlockSpec that this
file would have caught statically). Every gated Pallas kernel must export —
forward AND backward — for every configuration the framework routes to it.

Grads are taken wrt every differentiable input: the backward pass runs as
separate pallas_calls (dq vs dkv) and an unused cotangent lets DCE prune a
kernel out before Mosaic ever checks it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.flash_attention import flash_attention_pallas
from paddle_tpu.kernels.fused import fused_rms_norm_pallas, fused_rope_pallas

B, H, HK, D = 1, 4, 2, 64


def _qkv(sq, sk, h=H, hk=H, dtype=jnp.bfloat16):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, sq, h, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, sk, hk, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, sk, hk, D)), dtype)
    return q, k, v


def _export_grad(fn, *args):
    """Export fwd+bwd for TPU; grads wrt all float args."""
    argnums = tuple(
        i for i, a in enumerate(args) if jnp.issubdtype(a.dtype, jnp.floating)
    )

    def loss_and_grads(*a):
        loss = lambda *inner: fn(*inner).astype(jnp.float32).sum()  # noqa: E731
        return jax.grad(loss, argnums=argnums)(*a)

    jax.export.export(jax.jit(loss_and_grads), platforms=["tpu"])(*args)


class TestFlashAttentionExport:
    @pytest.mark.parametrize("causal", [False, True])
    def test_basic(self, causal):
        q, k, v = _qkv(256, 256)
        _export_grad(
            lambda q, k, v: flash_attention_pallas(q, k, v, causal=causal), q, k, v
        )

    def test_gqa(self):
        q, k, v = _qkv(256, 256, h=H, hk=HK)
        _export_grad(
            lambda q, k, v: flash_attention_pallas(q, k, v, causal=True), q, k, v
        )

    def test_unaligned_seq(self):
        # exercises the pad-to-block path (sq=200 -> blk_q=104? no: min(128, 200->208))
        q, k, v = _qkv(200, 200)
        _export_grad(
            lambda q, k, v: flash_attention_pallas(q, k, v, causal=True), q, k, v
        )

    def test_cross_attention(self):
        q, k, v = _qkv(128, 384)
        _export_grad(lambda q, k, v: flash_attention_pallas(q, k, v), q, k, v)

    @pytest.mark.parametrize("c", [1, 2, 4])
    def test_flashmask(self, c):
        sq = sk = 256
        q, k, v = _qkv(sq, sk)
        rng = np.random.default_rng(1)
        if c == 1:
            bounds = rng.integers(1, sq, (B, 1, sk, 1))
        elif c == 2:
            start = rng.integers(1, sq, (B, 1, sk, 1))
            end = np.minimum(start + rng.integers(0, 64, start.shape), sq)
            bounds = np.concatenate([start, end], axis=-1)
        else:
            lts = rng.integers(1, sq, (B, 1, sk, 1))
            lte = np.minimum(lts + 32, sq)
            uts = np.maximum(lts - 64, 0)
            ute = lts
            bounds = np.concatenate([lts, lte, uts, ute], axis=-1)
        idx = jnp.asarray(bounds, jnp.int32)
        _export_grad(
            lambda q, k, v: flash_attention_pallas(
                q, k, v, startend_row_indices=idx, causal=True
            ),
            q, k, v,
        )

    def test_flashmask_per_head(self):
        # Hm == H (per-head mask) exercises the non-broadcast index map
        sq = sk = 256
        q, k, v = _qkv(sq, sk)
        rng = np.random.default_rng(2)
        idx = jnp.asarray(rng.integers(1, sq, (B, H, sk, 1)), jnp.int32)
        _export_grad(
            lambda q, k, v: flash_attention_pallas(
                q, k, v, startend_row_indices=idx, causal=True
            ),
            q, k, v,
        )

    def test_bench_shape(self):
        """The exact shape class BENCH uses (12 heads, hd 128, seq 2048) —
        12 is not a multiple of 8, which is what broke the old LSE layout."""
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(1, 2048, 12, 128)), jnp.bfloat16)
        _export_grad(
            lambda q, k, v: flash_attention_pallas(q, k, v, causal=True), q, q, q
        )


class TestFusedKernelExport:
    @pytest.mark.parametrize("shape", [(2, 256, 512), (1, 2048, 1536)])
    def test_rms_norm(self, shape):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=shape[-1:]), jnp.bfloat16)
        _export_grad(lambda x, w: fused_rms_norm_pallas(x, w, 1e-6), x, w)

    def test_rope_grad(self):
        # custom VJP: fwd AND the Pallas bwd kernel must lower for TPU
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 256, 4, 64)), jnp.bfloat16)
        cs = jnp.asarray(rng.normal(size=(256, 64)), jnp.float32)
        _export_grad(lambda x: fused_rope_pallas(x, cs, cs), x)


class TestFusedMoeExport:
    def test_fused_moe_lowers_for_tpu(self):
        # ragged_dot is a Mosaic grouped matmul: statically verify fwd+bwd
        # TPU lowering like the Pallas kernels
        from paddle_tpu.incubate.nn.functional.fused_moe import _fused_moe_impl

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(16, 32)), jnp.float32)
        gw = jnp.asarray(rng.normal(size=(32, 4)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(4, 32, 64)), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(4, 32, 32)), jnp.float32)

        def loss(x, gw, w1, w2):
            return _fused_moe_impl(x, gw, w1, w2, 2, True, "swiglu").sum()

        jax.export.export(
            jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3))), platforms=["tpu"]
        )(x, gw, w1, w2)
