"""Decode-path tests: static-KV-cache attention op + compiled generate().

Reference parity targets: ``masked_multihead_attention_``
(``paddle/phi/ops/yaml/ops.yaml:3074``) and a PaddleNLP-style ``generate``.
The oracle is cache-free eager decoding (full forward over the growing
sequence, argmax each step) — if the static cache, RoPE offsets, or length
masking were wrong, token streams would diverge immediately.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def _tiny_model(seed=0, vocab=64):
    paddle.seed(seed)
    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model, cfg


class TestMaskedMultiheadAttention:
    def test_matches_dense_attention(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.incubate.nn.functional import masked_multihead_attention

        rng = np.random.default_rng(0)
        b, s_max, h, hk, d = 2, 16, 4, 2, 8
        ln = 5  # tokens already cached
        cache_k = jnp.asarray(rng.normal(size=(b, s_max, hk, d)), jnp.float32)
        cache_v = jnp.asarray(rng.normal(size=(b, s_max, hk, d)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
        k1 = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)
        v1 = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)

        out, ck, cv = masked_multihead_attention(q, k1, v1, cache_k, cache_v, ln)
        out, ck, cv = out._data, ck._data, cv._data

        # cache updated in place at index ln
        np.testing.assert_allclose(np.asarray(ck[:, ln]), np.asarray(k1[:, 0]))
        np.testing.assert_allclose(np.asarray(cv[:, ln]), np.asarray(v1[:, 0]))
        np.testing.assert_allclose(np.asarray(ck[:, :ln]), np.asarray(cache_k[:, :ln]))

        # dense reference over the first ln+1 positions, GQA-expanded
        group = h // hk
        keys = np.asarray(ck[:, : ln + 1])  # [b, L, hk, d]
        vals = np.asarray(cv[:, : ln + 1])
        qn = np.asarray(q)[:, 0]  # [b, h, d]
        expect = np.zeros((b, h, d), np.float32)
        for bi in range(b):
            for hi in range(h):
                kk = keys[bi, :, hi // group]  # [L, d]
                vv = vals[bi, :, hi // group]
                logit = kk @ qn[bi, hi] / np.sqrt(d)
                p = np.exp(logit - logit.max())
                p /= p.sum()
                expect[bi, hi] = p @ vv
        np.testing.assert_allclose(np.asarray(out[:, 0]), expect, rtol=2e-5, atol=2e-6)

    def test_per_batch_lengths(self):
        import jax.numpy as jnp

        from paddle_tpu.incubate.nn.functional import masked_multihead_attention

        rng = np.random.default_rng(1)
        b, s_max, hk, d = 2, 8, 2, 4
        cache = jnp.asarray(rng.normal(size=(b, s_max, hk, d)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)
        k1 = jnp.ones((b, 1, hk, d), jnp.float32)
        lens = jnp.asarray([2, 6], jnp.int32)
        _, ck, _ = masked_multihead_attention(q, k1, k1, cache, cache, lens)
        ck = np.asarray(ck._data)
        assert np.allclose(ck[0, 2], 1.0) and np.allclose(ck[1, 6], 1.0)
        assert not np.allclose(ck[0, 6], 1.0)


class TestGenerate:
    def test_greedy_matches_cache_free_decode(self):
        """Compiled static-cache generate == eager full-recompute argmax."""
        model, cfg = _tiny_model()
        rng = np.random.default_rng(3)
        ids = rng.integers(0, cfg.vocab_size, (2, 7)).astype(np.int32)
        T = 6

        out = model.generate(paddle.to_tensor(ids), max_new_tokens=T).numpy()

        # oracle: no cache at all — full forward each step
        seq = ids.copy()
        with paddle.no_grad():
            for _ in range(T):
                logits = model(paddle.to_tensor(seq)).numpy()
                nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
                seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, seq)

    def test_eos_padding(self):
        model, cfg = _tiny_model(seed=1)
        rng = np.random.default_rng(4)
        ids = rng.integers(0, cfg.vocab_size, (1, 5)).astype(np.int32)
        # find what greedy emits first, then declare THAT the eos token:
        # everything after it must be pad
        first = int(
            model.generate(paddle.to_tensor(ids), max_new_tokens=1).numpy()[0, -1]
        )
        out = model.generate(
            paddle.to_tensor(ids), max_new_tokens=5, eos_token_id=first, pad_token_id=0
        ).numpy()
        assert out[0, 5] == first
        assert (out[0, 6:] == 0).all()

    def test_sampling_modes_run(self):
        model, cfg = _tiny_model(seed=2)
        ids = paddle.to_tensor(np.zeros((2, 4), np.int32))
        for kw in (
            dict(do_sample=True, temperature=0.8),
            dict(do_sample=True, top_k=8),
            dict(do_sample=True, top_p=0.9),
        ):
            out = model.generate(ids, max_new_tokens=3, seed=7, **kw).numpy()
            assert out.shape == (2, 7)
            assert (out >= 0).all() and (out < cfg.vocab_size).all()

    def test_jit_cache_reused(self):
        model, cfg = _tiny_model(seed=5)
        ids = paddle.to_tensor(np.ones((1, 4), np.int32))
        model.generate(ids, max_new_tokens=2)
        assert len(model._generate_jit_cache) == 1
        model.generate(ids, max_new_tokens=2)  # same shapes -> same entry
        assert len(model._generate_jit_cache) == 1
        model.generate(ids, max_new_tokens=3)
        assert len(model._generate_jit_cache) == 2

    def test_sampling_distribution_respects_topk(self):
        """top_k=1 sampling must equal greedy."""
        model, cfg = _tiny_model(seed=6)
        rng = np.random.default_rng(8)
        ids = rng.integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)
        greedy = model.generate(paddle.to_tensor(ids), max_new_tokens=4).numpy()
        topk1 = model.generate(
            paddle.to_tensor(ids), max_new_tokens=4, do_sample=True, top_k=1, seed=9
        ).numpy()
        np.testing.assert_array_equal(greedy, topk1)


class TestPerBatchDecode:
    def test_ragged_positions_through_model(self):
        """cache_position as a [B] vector (left-padded batches at different
        lengths): positions get per-batch rope rows and per-batch cache
        writes. Oracle: run each sequence alone with its scalar position."""
        import jax.numpy as jnp

        model, cfg = _tiny_model(seed=7)
        layer = model.llama.layers[0].self_attn
        rng = np.random.default_rng(5)
        b, s_max = 2, 12
        h = paddle.to_tensor(rng.normal(size=(b, 1, cfg.hidden_size)).astype(np.float32))
        hk, d = cfg.num_key_value_heads, cfg.hidden_size // cfg.num_attention_heads
        ck = paddle.to_tensor(rng.normal(size=(b, s_max, hk, d)).astype(np.float32))
        cv = paddle.to_tensor(rng.normal(size=(b, s_max, hk, d)).astype(np.float32))
        lens = np.array([3, 7], np.int32)

        out_vec = layer(
            h, past_key_value=(ck, cv), use_cache=False,
            cache_position=paddle.to_tensor(lens),
        ).numpy()

        for bi in range(b):
            out_one = layer(
                h[bi : bi + 1],
                past_key_value=(ck[bi : bi + 1], cv[bi : bi + 1]),
                use_cache=False,
                cache_position=paddle.to_tensor(np.int32(lens[bi])),
            ).numpy()
            np.testing.assert_allclose(out_vec[bi], out_one[0], rtol=2e-5, atol=2e-6)


class TestPagedGeneration:
    """Paged-KV-cache decode (reference block_multihead_attention_): greedy
    parity with the dense static-cache generate()."""

    def test_paged_matches_dense_greedy(self):
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 7)).astype(np.int32))
        dense = m.generate(ids, max_new_tokens=9, do_sample=False).numpy()
        paged = m.generate_paged(ids, max_new_tokens=9, block_size=4).numpy()
        np.testing.assert_array_equal(np.asarray(paged), np.asarray(dense))

    def test_paged_crosses_block_boundaries_and_frees(self):
        paddle.seed(1)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(1)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 3)).astype(np.int32))
        # block_size 2 with 3+8 tokens: several boundary crossings per seq
        out = m.generate_paged(ids, max_new_tokens=8, block_size=2)
        assert list(out.shape) == [2, 11]
        dense = m.generate(ids, max_new_tokens=8, do_sample=False).numpy()
        np.testing.assert_array_equal(np.asarray(out.numpy()), np.asarray(dense))

    def test_paged_eos_padding(self):
        paddle.seed(2)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(2)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (1, 4)).astype(np.int32))
        dense = m.generate(ids, max_new_tokens=6, do_sample=False).numpy()
        eos = int(np.asarray(dense)[0, 5])  # force an early eos
        got = m.generate_paged(ids, max_new_tokens=6, eos_token_id=eos, pad_token_id=0).numpy()
        arr = np.asarray(got)[0]
        hit = np.where(arr[4:] == eos)[0]
        assert hit.size > 0
        first = 4 + hit[0]
        assert (arr[first + 1 :] == 0).all()


class TestBeamSearch:
    """generate_beam (reference beam_search op + BeamSearchScorer): one
    compiled scan, beams folded into the batch axis, gather_tree backtrace."""

    def _model(self):
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        return LlamaForCausalLM(cfg), cfg

    def _seq_logprob(self, model, seq, prompt_len):
        """Teacher-forced total log-prob of the generated suffix."""
        import jax
        import jax.numpy as jnp

        with paddle.no_grad():
            logits, _ = model(paddle.to_tensor(seq[None, :-1]), use_cache=True)
        lp = jax.nn.log_softmax(logits._data[0].astype(jnp.float32), axis=-1)
        tgt = seq[1:]
        tot = 0.0
        for t in range(prompt_len - 1, len(tgt)):
            tot += float(lp[t, tgt[t]])
        return tot

    def test_beam1_equals_greedy(self):
        model, cfg = self._model()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)
        greedy = model.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
        beam1 = model.generate_beam(paddle.to_tensor(ids), max_new_tokens=6, num_beams=1).numpy()
        np.testing.assert_array_equal(greedy, beam1)

    def test_beam_score_at_least_greedy(self):
        model, cfg = self._model()
        rng = np.random.default_rng(1)
        ids = rng.integers(0, cfg.vocab_size, (1, 4)).astype(np.int32)
        N = 6
        greedy = model.generate(paddle.to_tensor(ids), max_new_tokens=N).numpy()[0]
        beam = model.generate_beam(paddle.to_tensor(ids), max_new_tokens=N, num_beams=4).numpy()[0]
        g = self._seq_logprob(model, greedy, ids.shape[1])
        bm = self._seq_logprob(model, beam, ids.shape[1])
        assert bm >= g - 1e-4, f"beam {bm} < greedy {g}"

    def test_beam_shapes_and_batch(self):
        model, cfg = self._model()
        rng = np.random.default_rng(2)
        ids = rng.integers(0, cfg.vocab_size, (3, 4)).astype(np.int32)
        out = model.generate_beam(paddle.to_tensor(ids), max_new_tokens=5, num_beams=3).numpy()
        assert out.shape == (3, 9)
        np.testing.assert_array_equal(out[:, :4], ids)  # prompt preserved

    def test_eos_finishes_and_pads(self):
        model, cfg = self._model()
        rng = np.random.default_rng(3)
        ids = rng.integers(0, cfg.vocab_size, (2, 4)).astype(np.int32)
        # pick an eos the model will actually emit (batch 0's greedy token)
        eos = int(model.generate(paddle.to_tensor(ids), max_new_tokens=1).numpy()[0, -1])
        PAD = cfg.vocab_size - 1
        out = model.generate_beam(
            paddle.to_tensor(ids), max_new_tokens=6, num_beams=2,
            eos_token_id=eos, pad_token_id=PAD,
        ).numpy()
        assert out.shape == (2, 10)
        # after the first eos in a row, EVERY later token must be pad
        # (the pad_row freeze) — this is the finishing semantics, not shape
        for row in out:
            gen = row[4:]
            hits = np.where(gen == eos)[0]
            if hits.size:
                tail = gen[hits[0] + 1 :]
                assert (tail == PAD).all(), (gen, eos, PAD)

    def test_negative_max_new_tokens_raises_like_generate(self):
        model, cfg = self._model()
        ids = paddle.to_tensor(np.zeros((1, 3), np.int32))
        with pytest.raises(ValueError, match="max_new_tokens"):
            model.generate_beam(ids, max_new_tokens=-5)

    def test_rejects_bad_beams(self):
        model, cfg = self._model()
        with pytest.raises(ValueError, match="num_beams"):
            model.generate_beam(paddle.to_tensor(np.zeros((1, 3), np.int32)), num_beams=0)


class TestBeamLengthPenalty:
    """Reference BeamSearchScorer normalization: final score is
    sum_logprob / (((5 + full_len) / 6) ** alpha) over the FULL hypothesis
    length (prompt + generated). Verified against a hand-computed beam search
    over a scripted Markov-chain LM where every candidate score is exact."""

    V = 4  # vocabulary

    class _ToyLM(paddle.generation.GenerationMixin if hasattr(paddle, "generation") else object):
        """Logits depend only on the previous token: logits[t+1] = T[tok_t].
        Tiny, deterministic, and fully hand-computable."""

        def __init__(self, T):
            import jax.numpy as jnp

            self._T = jnp.asarray(T, jnp.float32)

        def named_parameters(self):
            return []

        def __call__(self, ids, past_key_values=None, use_cache=False,
                     cache_position=None):
            import jax.numpy as jnp

            from paddle_tpu.core.tensor import Tensor

            arr = ids._data if hasattr(ids, "_data") else ids
            logits = self._T[arr]  # [B, S, V]
            if not use_cache:
                return Tensor(logits)
            if past_key_values is not None:
                return Tensor(logits), past_key_values  # carry unchanged
            b, s = arr.shape
            zeros = jnp.zeros((b, s, 1, 1), jnp.float32)
            return Tensor(logits), [(Tensor(zeros), Tensor(zeros))]

    def _numpy_beam(self, T, prompt, max_new, K, alpha, eos):
        """Independent numpy implementation of the compiled beam scan +
        the reference length normalization."""
        import numpy as np

        def lsm(x):
            x = x - x.max()
            return x - np.log(np.exp(x).sum())

        V = T.shape[0]
        NEG, PAD = -1e9, 0
        logp0 = lsm(T[prompt[-1]].astype(np.float64))
        order = np.argsort(-logp0, kind="stable")[:K]
        scores, toks = logp0[order], order.astype(int)
        done = toks == eos
        lens = np.ones(K, int)
        hist_t, hist_p = [list(toks)], [[0] * K]
        pad_row = np.full(V, NEG); pad_row[PAD] = 0.0
        for _ in range(max_new - 1):
            cand = np.empty((K, V))
            for k in range(K):
                cand[k] = scores[k] + (pad_row if done[k] else lsm(T[toks[k]].astype(np.float64)))
            flat = cand.reshape(-1)
            idx = np.argsort(-flat, kind="stable")[:K]
            scores = flat[idx]
            parent, toks = idx // V, (idx % V).astype(int)
            done = done[parent] | (toks == eos)
            lens = lens[parent] + (1 - done[parent].astype(int))
            hist_t.append(list(toks)); hist_p.append(list(parent))
        # backtrace
        full_len = len(prompt) + lens
        norm = ((5.0 + full_len) / 6.0) ** alpha if alpha != 0.0 else np.ones(K)
        best = int(np.argmax(scores / norm))
        seq, k = [], best
        for t in range(len(hist_t) - 1, -1, -1):
            seq.append(hist_t[t][k]); k = hist_p[t][k]
        return np.asarray(seq[::-1], np.int32)

    def _run(self, alpha, seed=0):
        import numpy as np

        rng = np.random.default_rng(seed)
        T = rng.normal(size=(self.V, self.V)).astype(np.float32) * 2.0
        # make token `eos` reachable so beams finish at different lengths
        eos = 2
        model = self._ToyLM(T)
        prompt = np.asarray([1], np.int32)
        got = model.generate_beam(
            paddle.to_tensor(prompt[None]), max_new_tokens=5, num_beams=2,
            length_penalty=alpha, eos_token_id=eos, pad_token_id=0,
        ).numpy()[0][1:]
        want = self._numpy_beam(T, prompt, 5, 2, alpha, eos)
        # compare only up to the winner's eos (past it both emit pad 0)
        hits = np.where(want == eos)[0]
        n = (hits[0] + 1) if hits.size else len(want)
        np.testing.assert_array_equal(got[:n], want[:n])
        return got, want

    @pytest.mark.parametrize("alpha", [0.0, 1.0, 2.0, -1.0])
    def test_matches_reference_normalization(self, alpha):
        # several seeds: at least some produce length-divergent beams where
        # the normalization formula decides the winner
        for seed in range(4):
            self._run(alpha, seed=seed)

    def test_hand_computed_two_beam_case(self):
        """Fully hand-checkable: chain where beam A ends at eos early (short,
        high avg logprob) and beam B runs long (higher raw total). alpha
        picks the winner per the ((5+len)/6)**alpha rule."""
        import numpy as np

        NEG = -40.0
        # tokens: 0=pad, 1=start, 2=eos, 3=filler
        T = np.full((4, 4), NEG, np.float32)
        # from 1: eos with logp ~ log .6, filler ~ log .4
        T[1, 2], T[1, 3] = np.log(0.6), np.log(0.4)
        # filler keeps emitting filler with prob ~1 (logp ~ 0)
        T[3, 3] = 5.0
        T[3, 0], T[3, 1], T[3, 2] = NEG, NEG, NEG
        model = self._ToyLM(T)
        prompt = paddle.to_tensor(np.asarray([[1]], np.int32))
        # raw totals after 4 steps: beam-eos = log .6 (len 1, full 2);
        # beam-filler ~= log .4 (len 4, full 5).  log .6 > log .4 so with
        # alpha = 0 the eos beam wins outright...
        out0 = model.generate_beam(prompt, max_new_tokens=4, num_beams=2,
                                   length_penalty=0.0, eos_token_id=2,
                                   pad_token_id=0).numpy()[0]
        assert out0[1] == 2  # eos immediately
        # ...and a strongly positive alpha REWARDS length (GNMT-style): the
        # scores are negative, so dividing by the larger ((5+len)/6)**alpha
        # shrinks the long beam's penalty toward zero. By hand:
        #   eos:    log .6 / ((5+2)/6)**6 = -0.511 / 2.522 = -0.203
        #   filler: log .4 / ((5+5)/6)**6 = -0.916 / 21.43 = -0.043  (wins)
        out_pos = model.generate_beam(prompt, max_new_tokens=4, num_beams=2,
                                      length_penalty=6.0, eos_token_id=2,
                                      pad_token_id=0).numpy()[0]
        assert out_pos[1] == 3  # the long filler beam wins under +6
