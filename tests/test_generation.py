"""Decode-path tests: static-KV-cache attention op + compiled generate().

Reference parity targets: ``masked_multihead_attention_``
(``paddle/phi/ops/yaml/ops.yaml:3074``) and a PaddleNLP-style ``generate``.
The oracle is cache-free eager decoding (full forward over the growing
sequence, argmax each step) — if the static cache, RoPE offsets, or length
masking were wrong, token streams would diverge immediately.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def _tiny_model(seed=0, vocab=64):
    paddle.seed(seed)
    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model, cfg


class TestMaskedMultiheadAttention:
    def test_matches_dense_attention(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.incubate.nn.functional import masked_multihead_attention

        rng = np.random.default_rng(0)
        b, s_max, h, hk, d = 2, 16, 4, 2, 8
        ln = 5  # tokens already cached
        cache_k = jnp.asarray(rng.normal(size=(b, s_max, hk, d)), jnp.float32)
        cache_v = jnp.asarray(rng.normal(size=(b, s_max, hk, d)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
        k1 = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)
        v1 = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)

        out, ck, cv = masked_multihead_attention(q, k1, v1, cache_k, cache_v, ln)
        out, ck, cv = out._data, ck._data, cv._data

        # cache updated in place at index ln
        np.testing.assert_allclose(np.asarray(ck[:, ln]), np.asarray(k1[:, 0]))
        np.testing.assert_allclose(np.asarray(cv[:, ln]), np.asarray(v1[:, 0]))
        np.testing.assert_allclose(np.asarray(ck[:, :ln]), np.asarray(cache_k[:, :ln]))

        # dense reference over the first ln+1 positions, GQA-expanded
        group = h // hk
        keys = np.asarray(ck[:, : ln + 1])  # [b, L, hk, d]
        vals = np.asarray(cv[:, : ln + 1])
        qn = np.asarray(q)[:, 0]  # [b, h, d]
        expect = np.zeros((b, h, d), np.float32)
        for bi in range(b):
            for hi in range(h):
                kk = keys[bi, :, hi // group]  # [L, d]
                vv = vals[bi, :, hi // group]
                logit = kk @ qn[bi, hi] / np.sqrt(d)
                p = np.exp(logit - logit.max())
                p /= p.sum()
                expect[bi, hi] = p @ vv
        np.testing.assert_allclose(np.asarray(out[:, 0]), expect, rtol=2e-5, atol=2e-6)

    def test_per_batch_lengths(self):
        import jax.numpy as jnp

        from paddle_tpu.incubate.nn.functional import masked_multihead_attention

        rng = np.random.default_rng(1)
        b, s_max, hk, d = 2, 8, 2, 4
        cache = jnp.asarray(rng.normal(size=(b, s_max, hk, d)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)
        k1 = jnp.ones((b, 1, hk, d), jnp.float32)
        lens = jnp.asarray([2, 6], jnp.int32)
        _, ck, _ = masked_multihead_attention(q, k1, k1, cache, cache, lens)
        ck = np.asarray(ck._data)
        assert np.allclose(ck[0, 2], 1.0) and np.allclose(ck[1, 6], 1.0)
        assert not np.allclose(ck[0, 6], 1.0)


class TestGenerate:
    def test_greedy_matches_cache_free_decode(self):
        """Compiled static-cache generate == eager full-recompute argmax."""
        model, cfg = _tiny_model()
        rng = np.random.default_rng(3)
        ids = rng.integers(0, cfg.vocab_size, (2, 7)).astype(np.int32)
        T = 6

        out = model.generate(paddle.to_tensor(ids), max_new_tokens=T).numpy()

        # oracle: no cache at all — full forward each step
        seq = ids.copy()
        with paddle.no_grad():
            for _ in range(T):
                logits = model(paddle.to_tensor(seq)).numpy()
                nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
                seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, seq)

    def test_eos_padding(self):
        model, cfg = _tiny_model(seed=1)
        rng = np.random.default_rng(4)
        ids = rng.integers(0, cfg.vocab_size, (1, 5)).astype(np.int32)
        # find what greedy emits first, then declare THAT the eos token:
        # everything after it must be pad
        first = int(
            model.generate(paddle.to_tensor(ids), max_new_tokens=1).numpy()[0, -1]
        )
        out = model.generate(
            paddle.to_tensor(ids), max_new_tokens=5, eos_token_id=first, pad_token_id=0
        ).numpy()
        assert out[0, 5] == first
        assert (out[0, 6:] == 0).all()

    def test_sampling_modes_run(self):
        model, cfg = _tiny_model(seed=2)
        ids = paddle.to_tensor(np.zeros((2, 4), np.int32))
        for kw in (
            dict(do_sample=True, temperature=0.8),
            dict(do_sample=True, top_k=8),
            dict(do_sample=True, top_p=0.9),
        ):
            out = model.generate(ids, max_new_tokens=3, seed=7, **kw).numpy()
            assert out.shape == (2, 7)
            assert (out >= 0).all() and (out < cfg.vocab_size).all()

    def test_jit_cache_reused(self):
        model, cfg = _tiny_model(seed=5)
        ids = paddle.to_tensor(np.ones((1, 4), np.int32))
        model.generate(ids, max_new_tokens=2)
        assert len(model._generate_jit_cache) == 1
        model.generate(ids, max_new_tokens=2)  # same shapes -> same entry
        assert len(model._generate_jit_cache) == 1
        model.generate(ids, max_new_tokens=3)
        assert len(model._generate_jit_cache) == 2

    def test_sampling_distribution_respects_topk(self):
        """top_k=1 sampling must equal greedy."""
        model, cfg = _tiny_model(seed=6)
        rng = np.random.default_rng(8)
        ids = rng.integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)
        greedy = model.generate(paddle.to_tensor(ids), max_new_tokens=4).numpy()
        topk1 = model.generate(
            paddle.to_tensor(ids), max_new_tokens=4, do_sample=True, top_k=1, seed=9
        ).numpy()
        np.testing.assert_array_equal(greedy, topk1)


class TestPerBatchDecode:
    def test_ragged_positions_through_model(self):
        """cache_position as a [B] vector (left-padded batches at different
        lengths): positions get per-batch rope rows and per-batch cache
        writes. Oracle: run each sequence alone with its scalar position."""
        import jax.numpy as jnp

        model, cfg = _tiny_model(seed=7)
        layer = model.llama.layers[0].self_attn
        rng = np.random.default_rng(5)
        b, s_max = 2, 12
        h = paddle.to_tensor(rng.normal(size=(b, 1, cfg.hidden_size)).astype(np.float32))
        hk, d = cfg.num_key_value_heads, cfg.hidden_size // cfg.num_attention_heads
        ck = paddle.to_tensor(rng.normal(size=(b, s_max, hk, d)).astype(np.float32))
        cv = paddle.to_tensor(rng.normal(size=(b, s_max, hk, d)).astype(np.float32))
        lens = np.array([3, 7], np.int32)

        out_vec = layer(
            h, past_key_value=(ck, cv), use_cache=False,
            cache_position=paddle.to_tensor(lens),
        ).numpy()

        for bi in range(b):
            out_one = layer(
                h[bi : bi + 1],
                past_key_value=(ck[bi : bi + 1], cv[bi : bi + 1]),
                use_cache=False,
                cache_position=paddle.to_tensor(np.int32(lens[bi])),
            ).numpy()
            np.testing.assert_allclose(out_vec[bi], out_one[0], rtol=2e-5, atol=2e-6)


class TestPagedGeneration:
    """Paged-KV-cache decode (reference block_multihead_attention_): greedy
    parity with the dense static-cache generate()."""

    def test_paged_matches_dense_greedy(self):
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 7)).astype(np.int32))
        dense = m.generate(ids, max_new_tokens=9, do_sample=False).numpy()
        paged = m.generate_paged(ids, max_new_tokens=9, block_size=4).numpy()
        np.testing.assert_array_equal(np.asarray(paged), np.asarray(dense))

    def test_paged_crosses_block_boundaries_and_frees(self):
        paddle.seed(1)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(1)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 3)).astype(np.int32))
        # block_size 2 with 3+8 tokens: several boundary crossings per seq
        out = m.generate_paged(ids, max_new_tokens=8, block_size=2)
        assert list(out.shape) == [2, 11]
        dense = m.generate(ids, max_new_tokens=8, do_sample=False).numpy()
        np.testing.assert_array_equal(np.asarray(out.numpy()), np.asarray(dense))

    def test_paged_eos_padding(self):
        paddle.seed(2)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(2)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (1, 4)).astype(np.int32))
        dense = m.generate(ids, max_new_tokens=6, do_sample=False).numpy()
        eos = int(np.asarray(dense)[0, 5])  # force an early eos
        got = m.generate_paged(ids, max_new_tokens=6, eos_token_id=eos, pad_token_id=0).numpy()
        arr = np.asarray(got)[0]
        hit = np.where(arr[4:] == eos)[0]
        assert hit.size > 0
        first = 4 + hit[0]
        assert (arr[first + 1 :] == 0).all()


class TestBeamSearch:
    """generate_beam (reference beam_search op + BeamSearchScorer): one
    compiled scan, beams folded into the batch axis, gather_tree backtrace."""

    def _model(self):
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        return LlamaForCausalLM(cfg), cfg

    def _seq_logprob(self, model, seq, prompt_len):
        """Teacher-forced total log-prob of the generated suffix."""
        import jax
        import jax.numpy as jnp

        with paddle.no_grad():
            logits, _ = model(paddle.to_tensor(seq[None, :-1]), use_cache=True)
        lp = jax.nn.log_softmax(logits._data[0].astype(jnp.float32), axis=-1)
        tgt = seq[1:]
        tot = 0.0
        for t in range(prompt_len - 1, len(tgt)):
            tot += float(lp[t, tgt[t]])
        return tot

    def test_beam1_equals_greedy(self):
        model, cfg = self._model()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)
        greedy = model.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
        beam1 = model.generate_beam(paddle.to_tensor(ids), max_new_tokens=6, num_beams=1).numpy()
        np.testing.assert_array_equal(greedy, beam1)

    def test_beam_score_at_least_greedy(self):
        model, cfg = self._model()
        rng = np.random.default_rng(1)
        ids = rng.integers(0, cfg.vocab_size, (1, 4)).astype(np.int32)
        N = 6
        greedy = model.generate(paddle.to_tensor(ids), max_new_tokens=N).numpy()[0]
        beam = model.generate_beam(paddle.to_tensor(ids), max_new_tokens=N, num_beams=4).numpy()[0]
        g = self._seq_logprob(model, greedy, ids.shape[1])
        bm = self._seq_logprob(model, beam, ids.shape[1])
        assert bm >= g - 1e-4, f"beam {bm} < greedy {g}"

    def test_beam_shapes_and_batch(self):
        model, cfg = self._model()
        rng = np.random.default_rng(2)
        ids = rng.integers(0, cfg.vocab_size, (3, 4)).astype(np.int32)
        out = model.generate_beam(paddle.to_tensor(ids), max_new_tokens=5, num_beams=3).numpy()
        assert out.shape == (3, 9)
        np.testing.assert_array_equal(out[:, :4], ids)  # prompt preserved

    def test_eos_finishes_and_pads(self):
        model, cfg = self._model()
        rng = np.random.default_rng(3)
        ids = rng.integers(0, cfg.vocab_size, (2, 4)).astype(np.int32)
        # pick an eos the model will actually emit (batch 0's greedy token)
        eos = int(model.generate(paddle.to_tensor(ids), max_new_tokens=1).numpy()[0, -1])
        PAD = cfg.vocab_size - 1
        out = model.generate_beam(
            paddle.to_tensor(ids), max_new_tokens=6, num_beams=2,
            eos_token_id=eos, pad_token_id=PAD,
        ).numpy()
        assert out.shape == (2, 10)
        # after the first eos in a row, EVERY later token must be pad
        # (the pad_row freeze) — this is the finishing semantics, not shape
        for row in out:
            gen = row[4:]
            hits = np.where(gen == eos)[0]
            if hits.size:
                tail = gen[hits[0] + 1 :]
                assert (tail == PAD).all(), (gen, eos, PAD)

    def test_negative_max_new_tokens_raises_like_generate(self):
        model, cfg = self._model()
        ids = paddle.to_tensor(np.zeros((1, 3), np.int32))
        with pytest.raises(ValueError, match="max_new_tokens"):
            model.generate_beam(ids, max_new_tokens=-5)

    def test_rejects_bad_beams(self):
        model, cfg = self._model()
        with pytest.raises(ValueError, match="num_beams"):
            model.generate_beam(paddle.to_tensor(np.zeros((1, 3), np.int32)), num_beams=0)
