"""Multiprocess DataLoader workers (reference ``io/dataloader/worker.py``):
real forked processes, shared-memory handoff, ordering, errors, timeouts."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset, IterableDataset, get_worker_info


class PidDataset(Dataset):
    """Each sample carries the producing process's pid so the test can prove
    the work really happened in a forked worker."""

    def __init__(self, n=32, dim=4):
        self.n = n
        self.dim = dim

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        x = np.full((self.dim,), float(i), np.float32)
        return x, np.asarray([os.getpid()], np.int64)


def test_workers_actually_fork_and_order_is_preserved():
    ds = PidDataset(n=32)
    loader = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False)
    xs, pids = [], set()
    for xb, pidb in loader:
        xs.append(xb.numpy())
        pids.update(int(p) for p in pidb.numpy().ravel())
    got = np.concatenate(xs)[:, 0]
    np.testing.assert_array_equal(got, np.arange(32, dtype=np.float32))
    assert os.getpid() not in pids, "samples were produced in the parent, not workers"
    assert len(pids) >= 1


def test_shared_memory_and_pickle_paths_agree():
    ds = PidDataset(n=16)
    a = [x.numpy() for x, _ in DataLoader(ds, batch_size=4, num_workers=2, use_shared_memory=True)]
    b = [x.numpy() for x, _ in DataLoader(ds, batch_size=4, num_workers=2, use_shared_memory=False)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_worker_init_fn_and_worker_info():
    ds = PidDataset(n=8)
    seen = []

    def init_fn(worker_id):
        info = get_worker_info()
        assert info is not None and info.id == worker_id and info.num_workers == 2
        seen.append(worker_id)  # in the child; parent list stays empty

    loader = DataLoader(ds, batch_size=2, num_workers=2, worker_init_fn=init_fn)
    assert len(list(loader)) == 4
    assert seen == []  # init ran in children, not the parent
    assert get_worker_info() is None  # parent process


def test_worker_exception_propagates():
    class Bad(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom at 5")
            return np.zeros((2,), np.float32)

    loader = DataLoader(Bad(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 5"):
        list(loader)


def test_iterable_dataset_stride_split_no_duplicates():
    class Stream(IterableDataset):
        def __iter__(self):
            # no explicit worker sharding: the loader strides the stream
            return (np.asarray([i], np.int64) for i in range(20))

    loader = DataLoader(Stream(), batch_size=4, num_workers=2)
    vals = sorted(int(v) for b in loader for v in np.asarray(b.numpy()).ravel())
    assert vals == list(range(20))


def test_persistent_workers_reused_across_epochs():
    ds = PidDataset(n=8)
    loader = DataLoader(ds, batch_size=2, num_workers=2, persistent_workers=True)
    e1 = [x.numpy() for x, _ in loader]
    pool = loader._pool
    assert pool is not None and pool.alive()
    e2 = [x.numpy() for x, _ in loader]
    assert loader._pool is pool  # same pool served both epochs
    for x, y in zip(e1, e2):
        np.testing.assert_array_equal(x, y)
    pool.shutdown()


def test_break_mid_epoch_with_persistent_workers_stays_correct():
    """r4 review: breaking out of an epoch must not leak stale results into
    the next epoch (the pool is torn down and rebuilt)."""
    ds = PidDataset(n=16)
    loader = DataLoader(ds, batch_size=2, num_workers=2, persistent_workers=True)
    it = iter(loader)
    first = next(it)[0].numpy()
    del it  # abandon mid-epoch with results in flight
    # next epoch must start from batch 0 with correct ordering
    xs = [x.numpy() for x, _ in loader]
    got = np.concatenate(xs)[:, 0]
    np.testing.assert_array_equal(got, np.arange(16, dtype=np.float32))
    np.testing.assert_array_equal(xs[0], first)
    if loader._pool is not None:
        loader._pool.shutdown()


def test_custom_collate_fn_runs_in_parent():
    """User collate functions may build framework Tensors — they must never
    run in a forked child (PJRT-after-fork UB); the loader falls back to the
    parent-side prefetch thread."""
    import paddle_tpu as paddle

    seen_pids = []

    def my_collate(batch):
        seen_pids.append(os.getpid())
        return paddle.to_tensor(np.stack([b[0] for b in batch]))

    ds = PidDataset(n=8)
    loader = DataLoader(ds, batch_size=2, num_workers=2, collate_fn=my_collate)
    out = [b.numpy() for b in loader]
    assert len(out) == 4
    assert set(seen_pids) == {os.getpid()}  # collate ran in the parent
