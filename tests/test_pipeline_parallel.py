"""Pipeline parallelism tests: segmentation, shared embeddings, microbatch
grad-accumulation parity, and the SPMD circular-pipeline executor.

Mirrors the reference's PP coverage (SURVEY §4: hybrid_parallel_pp_* under
test/collective/fleet) run in-process on the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    SegmentLayers,
    SharedLayerDesc,
)
from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
    pipeline,
    stack_stage_params,
)

# 8-device CPU-mesh pipeline schedules cost minutes of XLA compile on the
# fast tier, so the executor/schedule classes below are marked slow; the
# host-side segmentation/layer classes stay tier-1, and the shard_map compat
# surface stays tier-1-covered by the cheaper test_sequence_parallel /
# test_collective
_mesh_heavy = pytest.mark.slow


class TestSegmentLayers:
    def test_uniform(self):
        descs = [LayerDesc(nn.Linear, 4, 4) for _ in range(8)]
        assert SegmentLayers(descs, 4, "uniform").do_segment() == [0, 2, 4, 6, 8]

    def test_uniform_uneven(self):
        descs = [LayerDesc(nn.Linear, 4, 4) for _ in range(7)]
        parts = SegmentLayers(descs, 4, "uniform").do_segment()
        assert parts[0] == 0 and parts[-1] == 7
        sizes = [parts[i + 1] - parts[i] for i in range(4)]
        assert sorted(sizes) == [1, 2, 2, 2]

    def test_layer_name_method(self):
        descs = [
            LayerDesc(nn.Embedding, 10, 4),
            LayerDesc(nn.Linear, 4, 4),
            LayerDesc(nn.Linear, 4, 4),
            LayerDesc(nn.Linear, 4, 4),
            LayerDesc(nn.Linear, 4, 4),
            LayerDesc(nn.LayerNorm, 4),
        ]
        parts = SegmentLayers(descs, 2, "layer:Linear").do_segment()
        # each stage gets 2 Linear blocks
        assert parts == [0, 3, 6]


class TestPipelineLayer:
    def test_forward_matches_sequential(self):
        paddle.seed(1)
        pipe = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 8) for _ in range(4)],
            num_stages=2,
        )
        x = paddle.randn([2, 8])
        out = pipe(x)
        h = x
        for layer in pipe._built:
            h = layer(h)
        np.testing.assert_allclose(out.numpy(), h.numpy(), rtol=1e-6)

    def test_stage_layers(self):
        pipe = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 8) for _ in range(6)],
            num_stages=3,
        )
        assert len(pipe.get_stage_layers(0)) == 2
        assert pipe.stage_of(0) == 0 and pipe.stage_of(5) == 2

    def test_shared_embedding_single_object(self):
        def head_fwd(layer, x):
            return paddle.matmul(x, layer.weight, transpose_y=True)

        pipe = PipelineLayer(
            layers=[
                SharedLayerDesc("embed", nn.Embedding, None, "weight", 16, 8),
                LayerDesc(nn.Linear, 8, 8),
                SharedLayerDesc("embed", nn.Embedding, head_fwd, "weight", 16, 8),
            ],
            num_stages=1,
        )
        # one shared module: 3 descs but embedding params counted once
        embeds = [l for l in pipe._built if isinstance(l, nn.Embedding)]
        assert embeds[0] is embeds[1]
        ids = paddle.to_tensor(np.array([[1, 2, 3]], dtype=np.int32))
        logits = pipe(ids)
        assert tuple(logits.shape) == (1, 3, 16)
        # tied gradient: backward accumulates from both uses
        loss = logits.sum()
        loss.backward()
        g = pipe.shared_layers["embed"].weight.grad
        assert g is not None and float(np.abs(g.numpy()).sum()) > 0

    def test_recompute_interval_same_numerics(self):
        paddle.seed(3)
        layers = [nn.Linear(8, 8) for _ in range(4)]  # concrete: shared params
        pipe = PipelineLayer(layers=layers, num_stages=2, recompute_interval=2)
        x = paddle.randn([2, 8])
        x.stop_gradient = False
        out = pipe(x)
        out.sum().backward()
        grads = [p.grad.numpy().copy() for p in pipe.parameters()]
        pipe.clear_gradients()

        pipe2 = PipelineLayer(layers=layers, num_stages=2, recompute_interval=0)
        # same underlying layers → same params
        out2 = pipe2(x)
        out2.sum().backward()
        grads2 = [p.grad.numpy().copy() for p in pipe2.parameters()]
        np.testing.assert_allclose(out.numpy(), out2.numpy(), rtol=1e-6)
        for g1, g2 in zip(grads, grads2):
            np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-7)


class TestPipelineParallelSchedule:
    def _mk(self, acc):
        class Strat:
            hybrid_configs = {"pp_configs": {"accumulate_steps": acc}}

        paddle.seed(7)
        pipe = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 4, 4) for _ in range(4)],
            num_stages=2,
            loss_fn=nn.MSELoss(),
        )
        return PipelineParallel(pipe, strategy=Strat()), pipe

    def test_microbatch_grad_accum_matches_full_batch(self):
        pp, pipe = self._mk(4)
        x = paddle.randn([8, 4])
        y = paddle.randn([8, 4])
        loss = pp.forward_backward_pipeline((x, y))
        grads_micro = [p.grad.numpy().copy() for p in pipe.parameters()]
        pipe.clear_gradients()

        out = pipe(x)
        full = nn.MSELoss()(out, y)
        full.backward()
        grads_full = [p.grad.numpy().copy() for p in pipe.parameters()]
        # mean-of-microbatch-means == full-batch mean for equal micro sizes
        for gm, gf in zip(grads_micro, grads_full):
            np.testing.assert_allclose(gm, gf, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(loss), float(full), rtol=1e-5)

    def test_train_batch_steps_optimizer(self):
        pp, pipe = self._mk(2)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=pipe.parameters())
        before = [p.numpy().copy() for p in pipe.parameters()]
        pp.train_batch((paddle.randn([4, 4]), paddle.randn([4, 4])), opt)
        after = [p.numpy().copy() for p in pipe.parameters()]
        assert any(not np.allclose(b, a) for b, a in zip(before, after))
        assert all(p.grad is None or np.allclose(p.grad.numpy(), 0) for p in pipe.parameters())


class TestFleetPipelineIntegration:
    def test_distributed_model_wraps_pipeline_layer(self):
        import paddle_tpu.distributed.fleet as fleet

        strat = fleet.DistributedStrategy()
        strat.hybrid_configs = {"dp_degree": 1, "pp_degree": 2, "sharding_degree": 1, "mp_degree": 1}
        strat.pipeline_configs = {"accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strat)
        pipe = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 4, 4) for _ in range(4)],
            num_stages=2,
            loss_fn=nn.MSELoss(),
        )
        wrapped = fleet.distributed_model(pipe)
        assert isinstance(wrapped, PipelineParallel)
        assert wrapped.accumulate_steps == 2
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=pipe.parameters())
        loss = wrapped.train_batch((paddle.randn([4, 4]), paddle.randn([4, 4])), opt)
        assert np.isfinite(float(loss))

    def test_split_micro_rejects_raw_arrays(self):
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import _split_micro

        with pytest.raises(TypeError):
            _split_micro(np.zeros((8, 4), np.float32), 4)
        assert _split_micro(None, 2) == [None, None]


class TestSpmdPipeline:
    """The true TPU path: stacked stage weights over the pp mesh axis."""

    def _stage_fn(self):
        def fn(params, x):
            w, b = params
            return jnp.tanh(x @ w + b)

        return fn

    def _params(self, S, H, key=0):
        ks = jax.random.split(jax.random.PRNGKey(key), S)
        return [
            (
                jax.random.normal(k, (H, H), jnp.float32) / np.sqrt(H),
                jnp.zeros((H,), jnp.float32),
            )
            for k in ks
        ]

    @_mesh_heavy
    def test_matches_sequential(self):
        import paddle_tpu.distributed as dist

        S, M, B, H = 4, 8, 2, 16
        mesh = dist.ProcessMesh(shape=[S, 2], dim_names=["pp", "dp"])
        stage_params = self._params(S, H)
        stacked = stack_stage_params(stage_params)
        mb = jax.random.normal(jax.random.PRNGKey(1), (M, B, H), jnp.float32)

        out = pipeline(self._stage_fn(), stacked, mb, mesh, axis_name="pp")

        expect = mb
        for p in stage_params:
            expect = jax.vmap(lambda x, p=p: self._stage_fn()(p, x))(expect)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=2e-5, atol=2e-6)

    @_mesh_heavy
    def test_grads_match_sequential(self):
        import paddle_tpu.distributed as dist

        S, M, B, H = 2, 4, 2, 8
        mesh = dist.ProcessMesh(shape=[S], dim_names=["pp"])
        stacked = stack_stage_params(self._params(S, H, key=2))
        mb = jax.random.normal(jax.random.PRNGKey(3), (M, B, H), jnp.float32)
        fn = self._stage_fn()

        def loss_pipe(params):
            return pipeline(fn, params, mb, mesh, axis_name="pp").sum()

        def loss_seq(params):
            x = mb
            for s in range(S):
                p = jax.tree.map(lambda a, s=s: a[s], params)
                x = jax.vmap(lambda xx, p=p: fn(p, xx))(x)
            return x.sum()

        g_pipe = jax.grad(loss_pipe)(stacked)
        g_seq = jax.grad(loss_seq)(stacked)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)

    @_mesh_heavy
    def test_jit_and_checkpoint(self):
        import paddle_tpu.distributed as dist

        S, M, B, H = 4, 4, 2, 8
        mesh = dist.ProcessMesh(shape=[S], dim_names=["pp"])
        stacked = stack_stage_params(self._params(S, H, key=4))
        mb = jax.random.normal(jax.random.PRNGKey(5), (M, B, H), jnp.float32)
        fn = self._stage_fn()

        out = jax.jit(
            lambda p, x: pipeline(fn, p, x, mesh, axis_name="pp", checkpoint_stages=True)
        )(stacked, mb)
        expect = pipeline(fn, stacked, mb, mesh, axis_name="pp")
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=1e-5, atol=1e-6)

    def test_single_stage_fallback(self):
        import paddle_tpu.distributed as dist

        mesh = dist.ProcessMesh(shape=[1], dim_names=["pp"])
        stacked = stack_stage_params(self._params(1, 8, key=6))
        mb = jax.random.normal(jax.random.PRNGKey(7), (2, 2, 8), jnp.float32)
        out = pipeline(self._stage_fn(), stacked, mb, mesh, axis_name="pp")
        assert out.shape == mb.shape


class TestSpmdPipelineExecutorGPT:
    """VERDICT r2 item #3: the circular executor wired into PipelineLayer/GPT —
    full train step through scan+ppermute with loss/grad parity vs the
    non-pipelined global view, on the 8-device CPU mesh."""

    def _cfg(self, num_layers=4):
        from paddle_tpu.models.gpt import GPTConfig

        return GPTConfig(
            vocab_size=64, hidden_size=16, num_layers=num_layers, num_heads=2,
            max_position=32,
        )

    def _build(self, num_layers=4, num_stages=2, **kw):
        from paddle_tpu.models.gpt import build_gpt_pipeline

        paddle.seed(11)
        return build_gpt_pipeline(self._cfg(num_layers), num_stages=num_stages, **kw)

    def _data(self, batch=8, seq=16):
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, 64, (batch, seq)).astype(np.int32))
        labels = paddle.to_tensor(rng.integers(0, 64, (batch, seq)).astype(np.int32))
        return ids, labels

    def test_plan_finds_decoder_region(self):
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            plan_pipeline_region,
        )

        pipe = self._build()
        start, end = plan_pipeline_region(pipe)
        # [embed, block x4, ln_f, tied head] -> region is exactly the blocks
        assert (start, end) == (1, 5)

    @_mesh_heavy
    def test_forward_matches_global_view(self):
        import paddle_tpu.distributed as dist

        mesh = dist.ProcessMesh(shape=[2, 2, 2], dim_names=["dp", "pp", "mp"])
        pipe = self._build()
        ex = pipe.build_spmd_executor(mesh, num_microbatches=4)
        ids, _ = self._data()
        out_pipe = ex(ids)
        out_seq = pipe(ids)
        np.testing.assert_allclose(
            out_pipe.numpy(), out_seq.numpy(), rtol=2e-5, atol=2e-5
        )

    @_mesh_heavy
    def test_train_step_grad_parity(self):
        """fwd+bwd through the executor == fwd+bwd through the plain stack,
        for every parameter including the tied embedding."""
        import paddle_tpu.distributed as dist
        import paddle_tpu.nn.functional as F

        mesh = dist.ProcessMesh(shape=[4], dim_names=["pp"])
        pipe = self._build(num_layers=4, num_stages=4)
        ex = pipe.build_spmd_executor(mesh, num_microbatches=4)
        ids, labels = self._data()

        def ce(logits):
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]).astype("float32"),
                labels.reshape([-1]),
                reduction="mean",
            )

        loss_pipe = ce(ex(ids))
        loss_pipe.backward()
        named = list(pipe.named_parameters())
        grads_pipe = {n: p.grad.numpy().copy() for n, p in named if p.grad is not None}
        pipe.clear_gradients()

        loss_seq = ce(pipe(ids))
        loss_seq.backward()
        grads_seq = {n: p.grad.numpy().copy() for n, p in named if p.grad is not None}

        np.testing.assert_allclose(float(loss_pipe), float(loss_seq), rtol=1e-5)
        assert set(grads_pipe) == set(grads_seq) and grads_pipe
        for n in grads_seq:
            np.testing.assert_allclose(
                grads_pipe[n], grads_seq[n], rtol=5e-4, atol=1e-5, err_msg=n
            )

    @_mesh_heavy
    def test_interleave_virtual_stages(self):
        """VPP: 8 blocks on 2 stages x 2 virtual chunks == plain stack."""
        import paddle_tpu.distributed as dist

        mesh = dist.ProcessMesh(shape=[2], dim_names=["pp"])
        pipe = self._build(num_layers=8, num_stages=2, num_virtual_pipeline_stages=2)
        ex = pipe.build_spmd_executor(mesh, num_microbatches=4)
        ids, _ = self._data()
        np.testing.assert_allclose(
            ex(ids).numpy(), pipe(ids).numpy(), rtol=2e-5, atol=2e-5
        )

    @_mesh_heavy
    def test_jitted_hybrid_train_step(self):
        """Full jitted train step (fwd+bwd+AdamW) over dp x pp x mp with TP
        placements — the shape the dryrun drives."""
        import paddle_tpu.distributed as dist
        import paddle_tpu.nn.functional as F
        from paddle_tpu.models.gpt import gpt_shard_fn

        mesh = dist.ProcessMesh(shape=[2, 2, 2], dim_names=["dp", "pp", "mp"])
        dist.set_mesh(mesh)
        pipe = self._build()
        for name, sub in pipe.named_sublayers(include_self=True):
            gpt_shard_fn(name, sub, mesh)
        ex = pipe.build_spmd_executor(mesh, num_microbatches=2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=pipe.parameters())

        @paddle.jit.to_static
        def step(model_ex, opt, ids, labels):
            logits = model_ex(ids)
            loss = F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]).astype("float32"),
                labels.reshape([-1]),
                reduction="mean",
            )
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        ids, labels = self._data(batch=4, seq=8)
        before = pipe._built[1].attn.qkv_proj.weight.numpy().copy()
        l0 = float(step(ex, opt, ids, labels))
        l1 = float(step(ex, opt, ids, labels))
        assert np.isfinite(l0) and np.isfinite(l1)
        after = pipe._built[1].attn.qkv_proj.weight.numpy()
        assert not np.allclose(before, after)

    def test_rejects_indivisible_region(self):
        import paddle_tpu.distributed as dist

        mesh = dist.ProcessMesh(shape=[4], dim_names=["pp"])
        pipe = self._build(num_layers=6, num_stages=4)
        with pytest.raises(ValueError, match="not divisible"):
            pipe.build_spmd_executor(mesh, num_microbatches=4)


class TestInterleavedPipeline:
    """Interleaved ring schedule: V laps overlap in one scan (reference
    PipelineParallelWithInterleave / zero-bubble scheduler bubble math)."""

    def _stage_fn(self):
        def fn(params, x):
            w, b = params
            return jnp.tanh(x @ w + b)

        return fn

    def _sv_params(self, S, V, H, key=0):
        ks = jax.random.split(jax.random.PRNGKey(key), S * V)
        flat = [
            (
                jax.random.normal(k, (H, H), jnp.float32) / np.sqrt(H),
                jnp.zeros((H,), jnp.float32),
            )
            for k in ks
        ]
        # virtual stage order: lap-major (v*S + s); device s holds laps v=0..V-1
        per_sv = [[flat[v * S + s] for v in range(V)] for s in range(S)]
        lap_stacked = [
            jax.tree.map(lambda *xs: jnp.stack(xs, 0), *per_sv[s]) for s in range(S)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs, 0), *lap_stacked)
        return flat, stacked

    def test_bubble_strictly_smaller_than_sequential_laps(self):
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            num_interleaved_ticks,
            num_pipeline_ticks,
        )

        for S, V, M in [(4, 2, 4), (4, 4, 8), (2, 3, 4), (8, 2, 8)]:
            seq = V * num_pipeline_ticks(M, S)
            inter = num_interleaved_ticks(M, S, V)
            assert inter < seq, (S, V, M, inter, seq)
            # bubble: interleaved pays S-1 once; sequential pays it V times
            assert inter - V * M == S - 1
            assert seq - V * M == V * (S - 1)

    @_mesh_heavy
    def test_matches_sequential_composition(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            pipeline_interleaved,
        )

        S, V, M, B, H = 4, 2, 4, 2, 16
        mesh = dist.ProcessMesh(shape=[S, 2], dim_names=["pp", "dp"])
        flat, stacked = self._sv_params(S, V, H, key=11)
        mb = jax.random.normal(jax.random.PRNGKey(12), (M, B, H), jnp.float32)
        fn = self._stage_fn()

        out = pipeline_interleaved(fn, stacked, mb, mesh, V, axis_name="pp")

        expect = mb
        for p in flat:  # virtual stages in order v*S + s
            expect = jax.vmap(lambda x, p=p: fn(p, x))(expect)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=2e-5, atol=2e-6)

    @_mesh_heavy
    def test_m_equals_s_edge(self):
        # wrap activation arrives exactly at its consume tick (S == M)
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            pipeline_interleaved,
        )

        S, V, M, B, H = 2, 3, 2, 2, 8
        mesh = dist.ProcessMesh(shape=[S], dim_names=["pp"])
        flat, stacked = self._sv_params(S, V, H, key=13)
        mb = jax.random.normal(jax.random.PRNGKey(14), (M, B, H), jnp.float32)
        fn = self._stage_fn()
        out = pipeline_interleaved(fn, stacked, mb, mesh, V, axis_name="pp")
        expect = mb
        for p in flat:
            expect = jax.vmap(lambda x, p=p: fn(p, x))(expect)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=2e-5, atol=2e-6)

    @_mesh_heavy
    def test_grads_flow(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            pipeline_interleaved,
        )

        S, V, M, B, H = 2, 2, 2, 2, 8
        mesh = dist.ProcessMesh(shape=[S], dim_names=["pp"])
        flat, stacked = self._sv_params(S, V, H, key=15)
        mb = jax.random.normal(jax.random.PRNGKey(16), (M, B, H), jnp.float32)
        fn = self._stage_fn()

        def loss_inter(params):
            return pipeline_interleaved(fn, params, mb, mesh, V, axis_name="pp").sum()

        def loss_seq(params):
            x = mb
            for v in range(V):
                for s in range(S):
                    p = jax.tree.map(lambda a, s=s, v=v: a[s, v], params)
                    x = jax.vmap(lambda xx, p=p: fn(p, xx))(x)
            return x.sum()

        g_i = jax.grad(loss_inter)(stacked)
        g_s = jax.grad(loss_seq)(stacked)
        for a, b in zip(jax.tree.leaves(g_i), jax.tree.leaves(g_s)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)

    @_mesh_heavy
    def test_executor_uses_interleaved_for_vpp(self):
        """PipelineLayer with num_virtual_pipeline_stages>1 runs the decoder
        region through the interleaved schedule with identical numerics to a
        plain sequential stack."""
        import paddle_tpu.distributed as dist
        import paddle_tpu.nn.functional as F
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline, gpt_shard_fn

        S = 2
        mesh = dist.ProcessMesh(shape=[1, S, 1], dim_names=["dp", "pp", "mp"])
        dist.set_mesh(mesh)
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4, num_heads=2, max_position=32)
        pipe = build_gpt_pipeline(cfg, num_stages=S, num_virtual_pipeline_stages=2)
        for name, sub in pipe.named_sublayers(include_self=True):
            gpt_shard_fn(name, sub, mesh)
        ex = pipe.build_spmd_executor(mesh, num_microbatches=2)

        rng = np.random.default_rng(8)
        ids = paddle.to_tensor(rng.integers(0, 64, (2, 8)).astype(np.int32))
        logits = ex(ids)

        # same weights, plain sequential execution
        h = ids
        for i, layer in enumerate(pipe._built):
            h = pipe._run_one(i, layer, h)
        np.testing.assert_allclose(
            np.asarray(logits.numpy(), np.float32),
            np.asarray(h.numpy(), np.float32),
            rtol=2e-4,
            atol=2e-5,
        )


class TestZeroBubble:
    """Zero-bubble schedule (reference pipeline_zero_bubble.py): dx-only
    reverse ring + off-ring batched weight grads, numerics-equal to the
    sequential executor, with strictly less bubble work than interleaved."""

    def _stage_fn(self):
        def fn(params, x):
            w, b = params
            return jnp.tanh(x @ w + b)

        return fn

    def _params(self, S, H, V=1, key=0):
        n = S * V
        ks = jax.random.split(jax.random.PRNGKey(key), n)
        flat = [
            (
                jax.random.normal(k, (H, H), jnp.float32) / np.sqrt(H),
                jnp.zeros((H,), jnp.float32),
            )
            for k in ks
        ]
        return flat  # virtual-stage order: v*S + s

    def _seq_loss(self, fn, flat_params, mb):
        x = mb
        for p in flat_params:
            x = jax.vmap(lambda xx, p=p: fn(p, xx))(x)
        return x

    @_mesh_heavy
    def test_forward_matches_sequential_v1(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            pipeline_zero_bubble,
        )

        S, M, B, H = 4, 8, 2, 16
        mesh = dist.ProcessMesh(shape=[S], dim_names=["pp"])
        flat = self._params(S, H, key=10)
        stacked = stack_stage_params(flat)
        mb = jax.random.normal(jax.random.PRNGKey(11), (M, B, H), jnp.float32)
        out = pipeline_zero_bubble(self._stage_fn(), stacked, mb, mesh, axis_name="pp")
        expect = self._seq_loss(self._stage_fn(), flat, mb)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=2e-5, atol=2e-6)

    @_mesh_heavy
    def test_grads_match_sequential_v1(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            pipeline_zero_bubble,
        )

        S, M, B, H = 2, 4, 2, 8
        mesh = dist.ProcessMesh(shape=[S], dim_names=["pp"])
        fn = self._stage_fn()
        flat = self._params(S, H, key=12)
        stacked = stack_stage_params(flat)
        mb = jax.random.normal(jax.random.PRNGKey(13), (M, B, H), jnp.float32)

        def loss_zb(params, x):
            return (pipeline_zero_bubble(fn, params, x, mesh, axis_name="pp") ** 2).sum()

        def loss_seq(params, x):
            for s in range(S):
                p = jax.tree.map(lambda a, s=s: a[s], params)
                x = jax.vmap(lambda xx, p=p: fn(p, xx))(x)
            return (x**2).sum()

        gp_zb, gx_zb = jax.grad(loss_zb, argnums=(0, 1))(stacked, mb)
        gp_seq, gx_seq = jax.grad(loss_seq, argnums=(0, 1))(stacked, mb)
        for a, b in zip(jax.tree.leaves(gp_zb), jax.tree.leaves(gp_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gx_zb), np.asarray(gx_seq), rtol=2e-4, atol=1e-5)

    @_mesh_heavy
    def test_grads_match_sequential_interleaved_v2(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            pipeline_zero_bubble,
        )

        S, V, M, B, H = 2, 2, 4, 2, 8
        mesh = dist.ProcessMesh(shape=[S], dim_names=["pp"])
        fn = self._stage_fn()
        flat = self._params(S, H, V=V, key=14)  # order v*S + s
        # leaves [S, V, ...]: stack stage-major then lap
        per_s = [
            jax.tree.map(lambda *xs: jnp.stack(xs, 0), *[flat[v * S + s] for v in range(V)])
            for s in range(S)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs, 0), *per_s)
        mb = jax.random.normal(jax.random.PRNGKey(15), (M, B, H), jnp.float32)

        def loss_zb(params, x):
            return (
                pipeline_zero_bubble(fn, params, x, mesh, num_virtual=V, axis_name="pp") ** 2
            ).sum()

        def loss_seq(params, x):
            for v in range(V):
                for s in range(S):
                    p = jax.tree.map(lambda a, s=s, v=v: a[s, v], params)
                    x = jax.vmap(lambda xx, p=p: fn(p, xx))(x)
            return (x**2).sum()

        gp_zb, gx_zb = jax.grad(loss_zb, argnums=(0, 1))(stacked, mb)
        gp_seq, gx_seq = jax.grad(loss_seq, argnums=(0, 1))(stacked, mb)
        for a, b in zip(jax.tree.leaves(gp_zb), jax.tree.leaves(gp_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gx_zb), np.asarray(gx_seq), rtol=2e-4, atol=1e-5)

    @_mesh_heavy
    def test_with_dp_axis(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            pipeline_zero_bubble,
        )

        S, M, B, H = 2, 4, 4, 8
        mesh = dist.ProcessMesh(shape=[S, 2], dim_names=["pp", "dp"])
        fn = self._stage_fn()
        flat = self._params(S, H, key=16)
        stacked = stack_stage_params(flat)
        mb = jax.random.normal(jax.random.PRNGKey(17), (M, B, H), jnp.float32)
        # dp stays an automatic (GSPMD) axis: only pp is manual in the pipeline
        out = pipeline_zero_bubble(fn, stacked, mb, mesh, axis_name="pp")
        expect = self._seq_loss(fn, flat, mb)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=2e-5, atol=2e-6)

    def test_work_model_strictly_beats_interleaved(self):
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            num_interleaved_ticks,
            num_zero_bubble_ticks,
            schedule_work_model,
        )

        for S, M, V in [(2, 4, 2), (4, 8, 2), (4, 16, 4), (8, 16, 2), (2, 2, 1)]:
            zb = schedule_work_model("zero_bubble", S, M, V)
            il = schedule_work_model("interleaved", S, M, V)
            ff = schedule_work_model("1f1b", S, M, V)
            # same ring length per direction as interleaved...
            assert num_zero_bubble_ticks(M, S, V) == num_interleaved_ticks(M, S, V)
            # ...but strictly less bubble (idle) work and shorter critical path
            assert zb["idle_work"] < il["idle_work"] <= ff["idle_work"]
            assert zb["critical_path"] < il["critical_path"] <= ff["critical_path"]
            # useful work: zb pays ONE extra remat per microbatch-lap (the dx
            # phase and the wgrad phase each recompute the forward once) —
            # that's the FLOPs-for-serialization trade zero-bubble makes
            zb_useful = (zb["critical_path"] - zb["idle_work"]) + zb["offring_work"]
            il_useful = il["critical_path"] - il["idle_work"]
            assert zb_useful == il_useful + V * M

    def test_single_stage_fallback(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            pipeline_zero_bubble,
        )

        mesh = dist.ProcessMesh(shape=[1], dim_names=["pp"])
        fn = self._stage_fn()
        flat = self._params(1, 8, key=18)
        stacked = stack_stage_params(flat)
        mb = jax.random.normal(jax.random.PRNGKey(19), (2, 2, 8), jnp.float32)
        out = pipeline_zero_bubble(fn, stacked, mb, mesh, axis_name="pp")
        expect = self._seq_loss(fn, flat, mb)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=1e-5, atol=1e-6)


class TestZeroBubbleExecutor:
    """schedule='zero_bubble' through the full GPT PipelineLayer executor."""

    def _build(self, num_layers, num_stages, **kw):
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline

        paddle.seed(0)
        cfg = GPTConfig(
            vocab_size=64, hidden_size=16, num_layers=num_layers, num_heads=2,
            max_position=32,
        )
        return build_gpt_pipeline(cfg, num_stages=num_stages, **kw)

    def _data(self):
        rng = np.random.default_rng(21)
        ids = paddle.to_tensor(rng.integers(0, 64, (4, 8)).astype(np.int32))
        labels = paddle.to_tensor(rng.integers(0, 64, (4, 8)).astype(np.int32))
        return ids, labels

    @_mesh_heavy
    @pytest.mark.parametrize("vpp", [1, 2])
    def test_grad_parity_vs_sequential(self, vpp):
        import paddle_tpu.distributed as dist
        import paddle_tpu.nn.functional as F

        S = 2
        mesh = dist.ProcessMesh(shape=[S], dim_names=["pp"])
        kw = {"num_virtual_pipeline_stages": vpp} if vpp > 1 else {}
        pipe = self._build(num_layers=4 * vpp, num_stages=S, **kw)
        ex = pipe.build_spmd_executor(mesh, num_microbatches=4, schedule="zero_bubble")
        ids, labels = self._data()

        def ce(logits):
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]).astype("float32"),
                labels.reshape([-1]),
                reduction="mean",
            )

        loss_zb = ce(ex(ids))
        loss_zb.backward()
        named = list(pipe.named_parameters())
        grads_zb = {n: p.grad.numpy().copy() for n, p in named if p.grad is not None}
        pipe.clear_gradients()

        loss_seq = ce(pipe(ids))
        loss_seq.backward()
        grads_seq = {n: p.grad.numpy().copy() for n, p in named if p.grad is not None}

        np.testing.assert_allclose(float(loss_zb), float(loss_seq), rtol=1e-5)
        assert set(grads_zb) == set(grads_seq) and grads_zb
        for n in grads_seq:
            np.testing.assert_allclose(
                grads_zb[n], grads_seq[n], rtol=5e-4, atol=1e-5, err_msg=n
            )

    def test_rejects_unknown_schedule(self):
        import paddle_tpu.distributed as dist

        mesh = dist.ProcessMesh(shape=[2], dim_names=["pp"])
        pipe = self._build(num_layers=4, num_stages=2)
        with pytest.raises(ValueError, match="schedule"):
            pipe.build_spmd_executor(mesh, num_microbatches=4, schedule="zb2pp")
