"""Vision dataset pipeline (reference ``python/paddle/vision/datasets``):
DatasetFolder/ImageFolder directory walking, MNIST idx parsing, Cifar batch
parsing, end-to-end with the multiprocess DataLoader."""

import gzip
import os
import pickle
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader
from paddle_tpu.vision import transforms
from paddle_tpu.vision.datasets import Cifar10, DatasetFolder, ImageFolder, MNIST

RNG = np.random.default_rng(0)


def _folder_tree(tmp_path, classes=("cat", "dog"), per_class=3):
    for c in classes:
        d = tmp_path / c
        d.mkdir(parents=True)
        for i in range(per_class):
            np.save(d / f"{i}.npy", RNG.integers(0, 255, (8, 8, 3)).astype(np.uint8))
    return str(tmp_path)


class TestFolders:
    def test_dataset_folder_classes_and_samples(self, tmp_path):
        root = _folder_tree(tmp_path)
        ds = DatasetFolder(root)
        assert ds.classes == ["cat", "dog"]
        assert len(ds) == 6
        img, label = ds[0]
        assert img.shape == (8, 8, 3) and label == 0
        img, label = ds[5]
        assert label == 1

    def test_dataset_folder_with_transform_and_loader(self, tmp_path):
        root = _folder_tree(tmp_path)
        tf = transforms.Compose([transforms.ToTensor()])
        ds = DatasetFolder(root, transform=tf)
        img, _ = ds[0]
        assert list(img.shape) == [3, 8, 8]  # CHW
        assert float(img.numpy().max()) <= 1.0

    def test_image_folder_flat(self, tmp_path):
        root = _folder_tree(tmp_path)
        ds = ImageFolder(root)
        assert len(ds) == 6
        (img,) = ds[0]
        assert img.shape == (8, 8, 3)

    def test_empty_raises(self, tmp_path):
        (tmp_path / "empty_cls").mkdir()
        with pytest.raises(RuntimeError):
            DatasetFolder(str(tmp_path))

    def test_end_to_end_multiprocess_loader(self, tmp_path):
        root = _folder_tree(tmp_path, per_class=8)
        ds = DatasetFolder(root)  # raw numpy samples: worker-safe
        loader = DataLoader(ds, batch_size=4, num_workers=2)
        batches = list(loader)
        assert len(batches) == 4
        xb, yb = batches[0]
        assert list(xb.shape) == [4, 8, 8, 3]
        assert list(yb.shape) == [4]


class TestMNIST:
    def _write_idx(self, path, arr, magic_dims):
        with gzip.open(path, "wb") as f:
            f.write(struct.pack(">I", magic_dims))
            for d in arr.shape:
                f.write(struct.pack(">I", d))
            f.write(arr.tobytes())

    def test_idx_roundtrip(self, tmp_path):
        imgs = RNG.integers(0, 255, (10, 28, 28)).astype(np.uint8)
        labels = RNG.integers(0, 10, (10,)).astype(np.uint8)
        ip = str(tmp_path / "img.gz")
        lp = str(tmp_path / "lbl.gz")
        self._write_idx(ip, imgs, 0x00000803)
        self._write_idx(lp, labels, 0x00000801)
        ds = MNIST(image_path=ip, label_path=lp)
        assert len(ds) == 10
        img, lab = ds[3]
        np.testing.assert_array_equal(img, imgs[3])
        assert int(lab) == int(labels[3])

    def test_download_refused(self):
        with pytest.raises(RuntimeError, match="egress"):
            MNIST(download=True)


class TestCifar:
    def test_batch_parsing(self, tmp_path):
        data = RNG.integers(0, 255, (20, 3 * 32 * 32)).astype(np.uint8)
        labels = RNG.integers(0, 10, (20,)).tolist()
        d = tmp_path / "cifar-10-batches-py"
        d.mkdir()
        for i in range(1, 6):
            with open(d / f"data_batch_{i}", "wb") as f:
                pickle.dump({b"data": data, b"labels": labels}, f)
        with open(d / "test_batch", "wb") as f:
            pickle.dump({b"data": data[:5], b"labels": labels[:5]}, f)
        train = Cifar10(data_file=str(d), mode="train")
        assert len(train) == 100  # 5 batches x 20
        img, lab = train[0]
        assert img.shape == (3, 32, 32)
        test = Cifar10(data_file=str(d), mode="test")
        assert len(test) == 5
