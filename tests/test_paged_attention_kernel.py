"""Pallas paged flash-decode kernel: interpret-mode numerics parity with the
XLA gather path, ragged lengths, GQA, and static TPU (Mosaic) lowering."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.paged_attention import paged_flash_decode

BS = 16  # tokens per physical block


def _setup(b=3, hq=4, hkv=4, d=64, mbs=4, nb=16, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, hq, d)), dtype)
    key_cache = jnp.asarray(rng.normal(size=(nb, hkv, BS, d)), dtype)
    value_cache = jnp.asarray(rng.normal(size=(nb, hkv, BS, d)), dtype)
    # disjoint random block tables
    perm = rng.permutation(nb)[: b * mbs].reshape(b, mbs)
    tables = jnp.asarray(perm, jnp.int32)
    lens = jnp.asarray(rng.integers(1, mbs * BS + 1, (b,)), jnp.int32)
    return q, key_cache, value_cache, tables, lens


def _reference(q, key_cache, value_cache, tables, lens):
    """Dense-gather reference (the XLA path's math)."""
    b, hq, d = q.shape
    hkv = key_cache.shape[1]
    gk = jnp.moveaxis(key_cache[tables], 2, 3).reshape(b, -1, hkv, d)
    gv = jnp.moveaxis(value_cache[tables], 2, 3).reshape(b, -1, hkv, d)
    if hkv != hq:
        gk = jnp.repeat(gk, hq // hkv, axis=2)
        gv = jnp.repeat(gv, hq // hkv, axis=2)
    qf = q.astype(jnp.float32) / np.sqrt(d)
    s = jnp.einsum("bhd,blhd->bhl", qf, gk.astype(jnp.float32))
    mask = jnp.arange(gk.shape[1])[None, None, :] < lens[:, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhl,blhd->bhd", p, gv.astype(jnp.float32)).astype(q.dtype)


class TestPagedFlashDecode:
    def test_matches_dense_gather(self):
        args = _setup()
        out = paged_flash_decode(*args, interpret=True)
        ref = _reference(*args)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_gqa(self):
        args = _setup(hq=8, hkv=2, seed=1)
        out = paged_flash_decode(*args, interpret=True)
        ref = _reference(*args)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_single_token_sequence(self):
        q, kc, vc, tables, _ = _setup(seed=2)
        lens = jnp.ones((q.shape[0],), jnp.int32)
        out = paged_flash_decode(q, kc, vc, tables, lens, interpret=True)
        ref = _reference(q, kc, vc, tables, lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_shared_physical_block_between_sequences(self):
        """Two sequences may map to the SAME physical block (prefix sharing)."""
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(2, 4, 64)), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(8, 4, BS, 64)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(8, 4, BS, 64)), jnp.float32)
        tables = jnp.asarray([[5, 1], [5, 2]], jnp.int32)  # shared block 5
        lens = jnp.asarray([20, 24], jnp.int32)
        out = paged_flash_decode(q, kc, vc, tables, lens, interpret=True)
        ref = _reference(q, kc, vc, tables, lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_bf16(self):
        args = _setup(seed=4, dtype=jnp.bfloat16)
        out = paged_flash_decode(*args, interpret=True)
        ref = _reference(*args)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2
        )

    def test_block_multihead_attention_uses_it_when_flagged(self, monkeypatch):
        """The serving entry routes to the kernel under the flag (fallback
        keeps numerics when the kernel import explodes)."""
        import paddle_tpu.incubate.nn.functional.block_attention as ba
        import paddle_tpu.kernels.select as sel

        monkeypatch.setattr(sel, "pallas_enabled", lambda flag: True)
        called = {}
        import paddle_tpu.kernels.paged_attention as pa

        real = pa.paged_flash_decode

        def spy(*a, **kw):
            called["yes"] = True
            return real(*a, interpret=True, **{k: v for k, v in kw.items() if k != "interpret"})

        monkeypatch.setattr(pa, "paged_flash_decode", spy)
        rng = np.random.default_rng(5)
        b, hq, d, nb, mbs = 2, 4, 64, 8, 2
        q = jnp.asarray(rng.normal(size=(b, 1, hq, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, 1, hq, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, 1, hq, d)), jnp.float32)
        kc = jnp.zeros((nb, hq, BS, d), jnp.float32)
        vc = jnp.zeros((nb, hq, BS, d), jnp.float32)
        tables = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
        lens = jnp.asarray([3, 7], jnp.int32)
        out, kc2, vc2 = ba.block_multihead_attention(q, k, v, kc, vc, tables, lens)
        assert called.get("yes")
        # parity vs the XLA path with the kernel disabled
        monkeypatch.setattr(sel, "pallas_enabled", lambda flag: False)
        out_xla, _, _ = ba.block_multihead_attention(q, k, v, kc, vc, tables, lens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(out_xla), rtol=2e-5, atol=2e-5
        )


class TestPagedDecodeExport:
    def test_lowers_for_tpu(self):
        args = _setup(b=2, hq=8, hkv=2, d=128, mbs=8, nb=32, dtype=jnp.bfloat16)

        def fn(q, kc, vc, tables, lens):
            return paged_flash_decode(q, kc, vc, tables, lens)

        jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)

    def test_lowers_for_tpu_serving_shape(self):
        # llama-7B-ish decode: 8 seqs, 32 q heads, 32 kv heads, d=128
        args = _setup(b=8, hq=32, hkv=32, d=128, mbs=16, nb=256, dtype=jnp.bfloat16)

        def fn(q, kc, vc, tables, lens):
            return paged_flash_decode(q, kc, vc, tables, lens)

        jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


def test_zero_length_sequence_yields_zeros():
    """A padded/inactive batch slot (len 0) must produce zeros, not a silent
    mean over physical block 0 (fully-masked softmax degeneracy)."""
    q, kc, vc, tables, _ = _setup(seed=7)
    lens = jnp.asarray([0, 5, 0], jnp.int32)
    out = np.asarray(paged_flash_decode(q, kc, vc, tables, lens, interpret=True))
    assert np.all(out[0] == 0.0) and np.all(out[2] == 0.0)
    assert np.abs(out[1]).sum() > 0


def test_lowering_supported_probe_caches():
    import time as _time

    from paddle_tpu.kernels.paged_attention import lowering_supported

    ok = lowering_supported(2, 8, 2, 128, 32, 16, 8, "bfloat16")
    assert ok is True
    t0 = _time.perf_counter()
    assert lowering_supported(2, 8, 2, 128, 32, 16, 8, "bfloat16") is True
    assert _time.perf_counter() - t0 < 0.05  # cached, no re-lowering
    # invalid geometry reports False instead of raising (hq % hkv != 0
    # fails inside the probed call)
    assert lowering_supported(2, 6, 4, 128, 32, 16, 8, "bfloat16") is False


class TestRaggedSkip:
    """The ragged decode path: unused block-table tails and fully-padded
    slots are never touched (no DMA via the clamped index map, no compute via
    the pl.when guard)."""

    def test_unused_tail_blocks_never_read(self):
        """Poison every block past each sequence's last in-use block with
        NaN: the clamped index map + predicated compute must keep the output
        bit-identical to clean caches (the old path multiplied masked
        probabilities into NaN values — 0 * NaN = NaN)."""
        rng = np.random.default_rng(11)
        b, hq, d, mbs, nb = 2, 4, 64, 4, 16
        q = jnp.asarray(rng.normal(size=(b, hq, d)), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(nb, hq, BS, d)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(nb, hq, BS, d)), jnp.float32)
        tables = jnp.asarray(rng.permutation(nb)[: b * mbs].reshape(b, mbs), jnp.int32)
        lens = jnp.asarray([BS + 3, 2 * BS], jnp.int32)  # tails: 2 blocks each
        clean = paged_flash_decode(q, kc, vc, tables, lens, interpret=True)
        # poison the tail blocks (logical blocks >= ceil(len/BS))
        kc_p, vc_p = np.array(kc), np.array(vc)
        for bi in range(b):
            used = -(-int(lens[bi]) // BS)
            for lb in range(used, mbs):
                kc_p[int(tables[bi, lb])] = np.nan
                vc_p[int(tables[bi, lb])] = np.nan
        out = paged_flash_decode(
            q, jnp.asarray(kc_p), jnp.asarray(vc_p), tables, lens, interpret=True
        )
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_array_equal(np.asarray(out), np.asarray(clean))

    def test_padded_slot_skips_even_poisoned_pool(self):
        """A len-0 slot's whole block-table row may point at junk; its output
        is exact zeros and no NaN leaks in."""
        rng = np.random.default_rng(12)
        q, kc, vc, tables, _ = _setup(seed=12)
        kc = jnp.asarray(np.full(kc.shape, np.nan, np.float32))
        vc = jnp.asarray(np.full(vc.shape, np.nan, np.float32))
        lens = jnp.zeros((q.shape[0],), jnp.int32)
        out = np.asarray(paged_flash_decode(q, kc, vc, tables, lens, interpret=True))
        assert (out == 0.0).all()


# -- ragged MIXED prefill/decode kernel (chunked prefill) ---------------------

from paddle_tpu.kernels.paged_attention import paged_flash_chunk  # noqa: E402


def _chunk_reference(q, key_cache, value_cache, tables, lens, q_lens):
    """Dense-gather reference for the mixed step (the XLA chunk path's
    math): query token j of sequence b sees cached positions < lens[b]+j+1;
    rows past q_lens emit zeros."""
    b, c, hq, d = q.shape
    hkv = key_cache.shape[1]
    gk = jnp.moveaxis(key_cache[tables], 2, 3).reshape(b, -1, hkv, d)
    gv = jnp.moveaxis(value_cache[tables], 2, 3).reshape(b, -1, hkv, d)
    if hkv != hq:
        gk = jnp.repeat(gk, hq // hkv, axis=2)
        gv = jnp.repeat(gv, hq // hkv, axis=2)
    qf = q.astype(jnp.float32) / np.sqrt(d)
    s = jnp.einsum("bchd,blhd->bchl", qf, gk.astype(jnp.float32))
    L = gk.shape[1]
    limit = lens[:, None] + jnp.arange(c)[None, :] + 1  # [B, C]
    mask = jnp.arange(L)[None, None, :] < limit[:, :, None]
    s = jnp.where(mask[:, :, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bchl,blhd->bchd", p, gv.astype(jnp.float32))
    row_valid = jnp.arange(c)[None, :] < q_lens[:, None]
    return jnp.where(row_valid[:, :, None, None], out, 0.0).astype(q.dtype)


def _chunk_setup(b=3, c=4, hq=4, hkv=4, d=64, mbs=4, nb=16, seed=0,
                 dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, c, hq, d)), dtype)
    kc = jnp.asarray(rng.normal(size=(nb, hkv, BS, d)), dtype)
    vc = jnp.asarray(rng.normal(size=(nb, hkv, BS, d)), dtype)
    tables = jnp.asarray(rng.permutation(nb)[: b * mbs].reshape(b, mbs), jnp.int32)
    # ragged mix: a decode row (1), a full prompt chunk (c), an inactive (0)
    q_lens = jnp.asarray([1, c, 0][:b] + [1] * max(0, b - 3), jnp.int32)
    lens = jnp.asarray(rng.integers(0, mbs * BS - c, (b,)), jnp.int32)
    return q, kc, vc, tables, lens, q_lens


class TestPagedFlashChunk:
    def test_mixed_rows_match_dense_gather(self):
        args = _chunk_setup()
        out = paged_flash_chunk(*args, interpret=True)
        ref = _chunk_reference(*args)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_gqa_chunk(self):
        args = _chunk_setup(hq=8, hkv=2, seed=1)
        out = paged_flash_chunk(*args, interpret=True)
        ref = _chunk_reference(*args)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_inactive_rows_exact_zero_even_poisoned_pool(self):
        """q_lens == 0 slots and rows past q_lens must emit EXACT zeros even
        when every pool value is NaN — the engine's padded slots."""
        q, kc, vc, tables, lens, _ = _chunk_setup(seed=2)
        kc = jnp.full_like(kc, jnp.nan)
        vc = jnp.full_like(vc, jnp.nan)
        q_lens = jnp.zeros((q.shape[0],), jnp.int32)
        out = paged_flash_chunk(q, kc, vc, tables, lens, q_lens, interpret=True)
        assert np.array_equal(np.asarray(out), np.zeros_like(np.asarray(out)))

    def test_decode_row_equals_decode_kernel(self):
        """A chunk with q_lens == 1 must reproduce the decode kernel's
        output for its first row — the two raggednesses agree."""
        q, kc, vc, tables, lens = _setup(seed=5)
        b, hq, d = q.shape
        c = 4
        qc = jnp.zeros((b, c, hq, d), q.dtype).at[:, 0].set(q)
        q_lens = jnp.ones((b,), jnp.int32)
        # decode semantics: the current token is ALREADY appended in the
        # pool, and `lens` EXCLUDES it — mirror that for the chunk call
        out_c = paged_flash_chunk(
            qc, kc, vc, tables, jnp.maximum(lens - 1, 0), q_lens, interpret=True
        )
        out_d = paged_flash_decode(q, kc, vc, tables, lens, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out_c[:, 0]), np.asarray(out_d), rtol=2e-5, atol=2e-5
        )

    def test_chunk_lowers_for_tpu_serving_shape(self):
        """The engine's unified mixed step lowers for TPU at a serving
        geometry (8 slots x 16-token chunks, llama-7B-ish heads)."""
        args = _chunk_setup(b=8, c=16, hq=32, hkv=32, d=128, mbs=16, nb=256,
                            dtype=jnp.bfloat16)

        def fn(q, kc, vc, tables, lens, q_lens):
            return paged_flash_chunk(q, kc, vc, tables, lens, q_lens)

        jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)

    def test_chunk_lowering_probe_matches_export(self):
        from paddle_tpu.kernels.paged_attention import chunk_lowering_supported

        assert chunk_lowering_supported(8, 16, 32, 32, 128, 256, 16, 16, "bfloat16")
