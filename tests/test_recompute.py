"""Activation checkpointing: grad parity vs plain backward, RNG replay,
jit-captured recompute."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet import recompute, recompute_sequential


def _t(a, **kw):
    return paddle.to_tensor(np.asarray(a), **kw)


def _make_mlp():
    paddle.seed(7)
    return nn.Sequential(
        nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 32), nn.GELU(), nn.Linear(32, 4)
    )


def test_recompute_grad_parity():
    x = np.random.RandomState(0).rand(16, 8).astype(np.float32)

    m1 = _make_mlp()
    a = _t(x)
    a.stop_gradient = False
    loss1 = m1(a).sum()
    loss1.backward()

    m2 = _make_mlp()
    b = _t(x)
    b.stop_gradient = False
    loss2 = recompute(m2, b).sum()
    loss2.backward()

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)
    np.testing.assert_allclose(a.grad.numpy(), b.grad.numpy(), rtol=1e-5)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.grad.numpy(), p2.grad.numpy(), rtol=1e-5)


def test_recompute_param_grads_compose_with_outside_use():
    """A param used both inside and outside the recompute segment gets the sum."""
    paddle.seed(0)
    lin = nn.Linear(4, 4)
    x = _t(np.random.rand(3, 4).astype(np.float32))

    loss_plain = (lin(x) + lin(x)).sum()
    loss_plain.backward()
    ref = lin.weight.grad.numpy().copy()
    lin.clear_gradients()

    loss_mix = (recompute(lin, x) + lin(x)).sum()
    loss_mix.backward()
    np.testing.assert_allclose(lin.weight.grad.numpy(), ref, rtol=1e-5)


def test_recompute_rng_replay_dropout():
    """Backward re-run must replay the SAME dropout mask as forward."""
    paddle.seed(123)
    drop = nn.Dropout(p=0.5)
    x = _t(np.ones((64, 64), np.float32))
    x.stop_gradient = False
    out = recompute(drop, x)
    mask = (out.numpy() != 0).astype(np.float32)
    out.sum().backward()
    # d(out)/dx = mask / keep_prob: same mask as forward iff RNG replayed
    np.testing.assert_allclose(x.grad.numpy(), mask * 2.0, rtol=1e-6)


def test_recompute_sequential_segments():
    x = np.random.RandomState(1).rand(8, 8).astype(np.float32)
    m1 = _make_mlp()
    a = _t(x)
    loss1 = m1(a).sum()
    loss1.backward()

    m2 = _make_mlp()
    b = _t(x)
    loss2 = recompute_sequential({"segments": 2}, m2, b).sum()
    loss2.backward()
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.grad.numpy(), p2.grad.numpy(), rtol=1e-5)


def test_recompute_under_jit():
    x = np.random.RandomState(2).rand(16, 8).astype(np.float32)
    y = np.random.RandomState(3).rand(16, 4).astype(np.float32)

    def make():
        m = _make_mlp()
        o = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        return m, o

    m1, o1 = make()
    for _ in range(5):
        loss = ((m1(_t(x)) - _t(y)) ** 2).mean()
        loss.backward()
        o1.step()
        o1.clear_grad()
    ref = float(((m1(_t(x)) - _t(y)) ** 2).mean())

    m2, o2 = make()

    @paddle.jit.to_static
    def step(model, opt, xx, yy):
        pred = recompute(model, xx)
        loss = ((pred - yy) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    for _ in range(5):
        step(m2, o2, _t(x), _t(y))
    got = float(((m2(_t(x)) - _t(y)) ** 2).mean())
    np.testing.assert_allclose(got, ref, rtol=1e-4)


def test_recompute_no_grad_passthrough():
    m = _make_mlp()
    x = _t(np.random.rand(2, 8).astype(np.float32))
    with paddle.no_grad():
        out = recompute(m, x)
    assert out.grad_node is None


def test_recompute_kwarg_tensor_gets_grad():
    """Tensors passed by keyword are segment inputs too."""
    paddle.seed(0)
    lin = nn.Linear(4, 4)

    def f(x, scale=None):
        return lin(x) * scale

    x = _t(np.random.rand(3, 4).astype(np.float32))
    base = _t(np.full((1,), 2.0, np.float32))
    base.stop_gradient = False
    scale = base * 3.0  # non-leaf: exercises routing into the outer tape
    x.stop_gradient = False
    out = recompute(f, x, scale=scale)
    out.sum().backward()
    assert x.grad is not None
    assert base.grad is not None
    np.testing.assert_allclose(
        base.grad.numpy(), [3.0 * float(lin(x).sum())], rtol=1e-5
    )


def test_recompute_replays_amp_state():
    """backward() outside the auto_cast context must re-run the segment
    with the forward's autocast config."""
    import paddle_tpu.amp as amp

    paddle.seed(0)
    lin = nn.Linear(16, 16)
    x = _t(np.random.rand(8, 16).astype(np.float32))

    with amp.auto_cast(level="O1"):
        out_plain = lin(x)
    with amp.auto_cast(level="O1"):
        out_rc = recompute(lin, x)
    loss_plain = out_plain.astype("float32").sum()
    loss_rc = out_rc.astype("float32").sum()
    lin.clear_gradients()
    loss_plain.backward()
    ref = lin.weight.grad.numpy().copy()
    lin.clear_gradients()
    loss_rc.backward()  # outside auto_cast: state must be replayed
    np.testing.assert_allclose(lin.weight.grad.numpy(), ref, rtol=1e-6)


def test_recompute_under_only_inputs_grad_no_param_side_effects():
    """autograd.grad() through a recompute segment must honor only-inputs
    semantics: input grads returned, param .grad left untouched (r4 review
    finding — the inner sweep used to side-effect params)."""
    paddle.seed(0)
    lin = nn.Linear(8, 8)
    x = _t(np.random.rand(4, 8).astype(np.float32))
    x.stop_gradient = False

    loss = recompute(lin, x).sum()
    (gx,) = paddle.autograd.grad([loss], [x])
    assert gx is not None
    assert lin.weight.grad is None, "grad() leaked param grads through recompute"
    assert lin.bias.grad is None

    # and asking grad() FOR the segment's params still works
    loss2 = recompute(lin, x).sum()
    gw, gb = paddle.autograd.grad([loss2], [lin.weight, lin.bias])
    assert gw is not None and gb is not None
    # parity vs non-recompute grad()
    loss3 = lin(x).sum()
    gw3, gb3 = paddle.autograd.grad([loss3], [lin.weight, lin.bias])
    np.testing.assert_allclose(gw.numpy(), gw3.numpy(), rtol=1e-5)
    np.testing.assert_allclose(gb.numpy(), gb3.numpy(), rtol=1e-5)
    assert lin.weight.grad is None
