"""Profiler tests: RecordEvent spans, scheduler state machine, chrome export,
summary, throughput timer."""

import json
import time

import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (
    Profiler,
    ProfilerState,
    RecordEvent,
    benchmark,
    make_scheduler,
)


class TestScheduler:
    def test_windows(self):
        sched = make_scheduler(closed=1, ready=1, record=2, repeat=1, skip_first=1)
        states = [sched(i) for i in range(6)]
        assert states[0] == ProfilerState.CLOSED  # skip_first
        assert states[1] == ProfilerState.CLOSED
        assert states[2] == ProfilerState.READY
        assert states[3] == ProfilerState.RECORD
        assert states[4] == ProfilerState.RECORD_AND_RETURN
        assert states[5] == ProfilerState.CLOSED  # repeat exhausted


class TestProfiler:
    def test_record_and_export(self, tmp_path):
        p = Profiler()
        p.start()
        with RecordEvent("forward"):
            time.sleep(0.002)
        with RecordEvent("backward"):
            time.sleep(0.001)
        p.step()
        p.stop()
        out = str(tmp_path / "trace.json")
        p.export(out)
        data = json.load(open(out))
        names = [e["name"] for e in data["traceEvents"]]
        assert "forward" in names and "backward" in names

    def test_summary_aggregates(self):
        p = Profiler()
        p.start()
        for _ in range(3):
            with RecordEvent("op_x"):
                pass
        p.stop()
        s = p.summary()
        assert "op_x" in s and " 3 " in s

    def test_events_outside_record_not_collected(self):
        p = Profiler(scheduler=make_scheduler(closed=1, ready=0, record=1, repeat=1))
        p.start()  # step 0: CLOSED
        with RecordEvent("hidden"):
            pass
        p.step()  # step 1 → RECORD
        with RecordEvent("visible"):
            pass
        p.stop()
        names = [e["name"] for e in p._events]
        assert "visible" in names and "hidden" not in names


class TestMetricsSnapshotLink:
    def test_chrome_export_roundtrips_metrics_snapshots(self, tmp_path):
        """Snapshots written via observability.write_snapshot_jsonl appear as
        instant events in the chrome trace, round-tripped through
        load_profiler_result alongside the RecordEvent spans."""
        import paddle_tpu as paddle
        from paddle_tpu import observability as obs

        prior = paddle.get_flags(["FLAGS_enable_metrics"])["FLAGS_enable_metrics"]
        paddle.set_flags({"FLAGS_enable_metrics": True})
        obs.drain_trace_events()  # leftovers from other tests
        try:
            obs.GLOBAL_METRICS.reset()
            obs.GLOBAL_METRICS.counter("roundtrip_probe_total").inc(2)
            snap_path = str(tmp_path / "metrics.jsonl")
            p = Profiler()
            p.start()
            with RecordEvent("span_a"):
                rec1 = obs.write_snapshot_jsonl(snap_path)
            rec2 = obs.write_snapshot_jsonl(snap_path)
            p.stop()
            out = str(tmp_path / "trace.json")
            p.export(out)

            data = profiler.load_profiler_result(out)
            names = [e["name"] for e in data["traceEvents"]]
            assert "span_a" in names
            snaps = [e for e in data["traceEvents"] if e["name"] == "metrics_snapshot"]
            assert [e["args"]["seq"] for e in snaps] == [rec1["seq"], rec2["seq"]]
            assert all(e["ph"] == "i" and e["args"]["path"] == snap_path for e in snaps)
            # the linked JSONL file carries the full registry snapshot
            lines = open(snap_path).read().splitlines()
            assert len(lines) == 2
            parsed = json.loads(lines[0])
            assert parsed["seq"] == rec1["seq"]
            probe = parsed["metrics"]["roundtrip_probe_total"]["values"][0]
            assert probe["value"] == 2.0
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": prior})


class TestBenchmarkTimer:
    def test_throughput(self):
        bm = benchmark()
        bm.begin()
        bm._warmup = 0
        for _ in range(3):
            bm.before_reader()
            bm.after_reader()
            bm.step(num_samples=32)
        info = bm.end()
        assert info["steps"] == 3
        assert info["ips"] > 0
