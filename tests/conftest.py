"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's "distributed tests without a cluster" strategy
(SURVEY §4): the reference spawns localhost NCCL subprocesses; on TPU/XLA the
CPU backend natively exposes N virtual devices, so multi-device SPMD tests run
in-process.
"""

import os
import sys

# Force the CPU backend: tests must not depend on the TPU tunnel being alive.
# The lab image's sitecustomize imports jax at interpreter startup, so env
# vars are too late — update jax.config directly (backends are still
# uninitialized at conftest time, so this takes effect).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The CPU backend's "default" matmul precision truncates to bf16-class
# accuracy; tests compare against numpy fp32 references.
jax.config.update("jax_default_matmul_precision", "highest")

if not hasattr(jax, "shard_map"):
    # jax < 0.5 has only the experimental shard_map (different kwarg surface);
    # tests use the modern `jax.shard_map` API — install the framework's
    # compat wrapper so they run against both jax generations.
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        shard_map as _shard_map_compat,
    )

    jax.shard_map = _shard_map_compat


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
    # isolate global mesh state between tests (set_mesh leaks otherwise)
    import paddle_tpu.distributed.mesh as _mesh

    _mesh._global_mesh = None
