"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's "distributed tests without a cluster" strategy
(SURVEY §4): the reference spawns localhost NCCL subprocesses; on TPU/XLA the
CPU backend natively exposes N virtual devices, so multi-device SPMD tests run
in-process.
"""

import os
import sys

# Force the CPU backend: tests must not depend on the TPU tunnel being alive.
# The lab image's sitecustomize imports jax at interpreter startup, so env
# vars are too late — update jax.config directly (backends are still
# uninitialized at conftest time, so this takes effect).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The CPU backend's "default" matmul precision truncates to bf16-class
# accuracy; tests compare against numpy fp32 references.
jax.config.update("jax_default_matmul_precision", "highest")

if not hasattr(jax, "shard_map"):
    # jax < 0.5 has only the experimental shard_map (different kwarg surface);
    # tests use the modern `jax.shard_map` API — install the framework's
    # compat wrapper so they run against both jax generations.
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        shard_map as _shard_map_compat,
    )

    jax.shard_map = _shard_map_compat


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
    # isolate global mesh state between tests (set_mesh leaks otherwise)
    import paddle_tpu.distributed.mesh as _mesh

    _mesh._global_mesh = None


def assert_engine_pool_exact(eng):
    """The engine pool-accounting churn invariant, shared by every engine
    suite (engine / spec-decode / prefix-cache / tp): refcount truth —
    every refcounted block's owner count equals its live mappings (slot
    tables + pending CoW pins) plus cache chain ownership — exact
    allocated+free accounting, no live table referencing a freed block,
    and the cached chain aligned as a prefix of each slot's block table."""
    s = eng.pool_stats()
    assert s["allocated"] + s["free"] == s["total"], s
    expect = {}
    for slot, req in enumerate(eng._slot_req):
        if req is not None:
            for b in eng._blocks[slot]:
                expect[b] = expect.get(b, 0) + 1
    for pending in eng._pending_cow:
        if pending is not None:
            expect[pending[0].block] = expect.get(pending[0].block, 0) + 1
    if eng._cache is not None:
        for node in eng._cache._nodes.values():
            expect[node.block] = expect.get(node.block, 0) + 1
    assert eng._mgr.refcounts() == expect
    free = set(eng._mgr._free)
    for slot, req in enumerate(eng._slot_req):
        if req is not None:
            assert not (set(eng._blocks[slot]) & free), (
                f"slot {slot} references freed blocks"
            )
            for i, node in enumerate(eng._nodes[slot]):
                assert eng._blocks[slot][i] == node.block


def assert_kv_tier_exact(eng):
    """The hierarchical-KV churn invariant, shared by the tier suites:
    host-tier bytes stay within budget (and equal blocks x block_nbytes),
    and no block is live in BOTH tiers under the same chain key with
    mismatched contents — a device-resident chain node whose key also
    lives in the host tier must hold byte-identical KV (content-addressed
    immutability is what makes dual residency safe)."""
    import numpy as np

    tier = eng._host_tier
    if tier is None:
        return
    s = tier.stats_snapshot()
    assert s["host_bytes"] <= s["budget_bytes"], s
    assert s["host_bytes"] == len(tier) * tier.block_nbytes, s
    if eng._cache is None:
        return
    for node in list(eng._cache._nodes.values()):
        host = tier._entries.get(node.key)
        if host is None:
            continue
        assert host.digest == node.digest
        dev = eng._capture_block_kv(node.block)
        assert np.array_equal(np.asarray(dev), np.asarray(host.kv)), (
            f"block {node.block} resident in both tiers with mismatched KV"
        )
