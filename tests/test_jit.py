"""jit capture: to_static tracing, caching, state threading, full train step."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _t(a, **kw):
    return paddle.to_tensor(np.asarray(a), **kw)


def test_to_static_function():
    calls = []

    @paddle.jit.to_static
    def f(x, y):
        calls.append(1)
        return x * 2 + y

    a = _t(np.ones(3, np.float32))
    b = _t(np.full(3, 5.0, np.float32))
    out = f(a, b)
    np.testing.assert_allclose(out.numpy(), [7, 7, 7])
    out2 = f(b, a)
    np.testing.assert_allclose(out2.numpy(), [11, 11, 11])
    # second call hit the compiled cache: python body traced once
    assert len(calls) == 1


def test_to_static_retraces_on_shape_change():
    calls = []

    @paddle.jit.to_static
    def f(x):
        calls.append(1)
        return x.sum()

    f(_t(np.ones(3, np.float32)))
    f(_t(np.ones(4, np.float32)))
    assert len(calls) == 2


def test_to_static_layer_forward():
    model = nn.Linear(4, 2)
    static_forward = paddle.jit.to_static(model.forward)
    x = _t(np.random.rand(3, 4).astype(np.float32))
    eager = model(x)
    static = static_forward(x)
    np.testing.assert_allclose(eager.numpy(), static.numpy(), rtol=1e-5)


def test_to_static_sees_param_updates():
    """Params are trace inputs, not baked constants."""
    model = nn.Linear(2, 2)
    static_forward = paddle.jit.to_static(model.forward)
    x = _t(np.ones((1, 2), np.float32))
    out1 = static_forward(x).numpy()
    with paddle.no_grad():
        model.weight.set_value(model.weight.numpy() * 2)
        model.bias.set_value(model.bias.numpy() + 1)
    out2 = static_forward(x).numpy()
    assert not np.allclose(out1, out2)


def test_jitted_full_train_step():
    """forward + backward + optimizer in ONE compiled program."""
    np.random.seed(0)
    model = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    mse = nn.MSELoss()

    @paddle.jit.to_static
    def train_step(model, opt, x, y):
        pred = model(x)
        loss = mse(pred, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = np.random.rand(16, 4).astype(np.float32)
    y = (x.sum(-1, keepdims=True) * 0.5).astype(np.float32)
    losses = []
    for _ in range(60):
        loss = train_step(model, opt, _t(x), _t(y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


def test_jitted_train_step_matches_eager():
    np.random.seed(1)
    x = np.random.rand(8, 3).astype(np.float32)
    y = np.random.rand(8, 1).astype(np.float32)

    def make():
        paddle.seed(3)
        m = nn.Linear(3, 1)
        o = paddle.optimizer.Adam(learning_rate=0.05, parameters=m.parameters())
        return m, o

    # eager
    m1, o1 = make()
    for _ in range(5):
        loss = ((m1(_t(x)) - _t(y)) ** 2).mean()
        loss.backward()
        o1.step()
        o1.clear_grad()

    # jitted
    m2, o2 = make()

    @paddle.jit.to_static
    def step(model, opt, xx, yy):
        loss = ((model(xx) - yy) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    for _ in range(5):
        step(m2, o2, _t(x), _t(y))

    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(m1.bias.numpy(), m2.bias.numpy(), rtol=1e-4, atol=1e-5)


def test_jit_save_load(tmp_path):
    model = nn.Linear(3, 2)
    path = str(tmp_path / "model")
    paddle.jit.save(model, path, input_spec=[paddle.static.InputSpec([1, 3])])
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(
        loaded.state_dict()["weight"].numpy(), model.weight.numpy()
    )
    assert loaded.program_text is not None and "stablehlo" in loaded.program_text or "module" in loaded.program_text


def test_rng_key_not_mesh_committed_after_sharded_step():
    """r4 drive regression: a jitted sharded step hands the global RNG key
    back replicated over the mesh; committing it that way silently placed
    every LATER tensor creation on the mesh (fresh layers inherited 8-device
    shardings, jit.save recorded an 8-device calling convention that broke
    single-device serving)."""
    import jax
    from jax.sharding import NamedSharding

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.placements import Replicate, Shard
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"])
    lin = nn.Linear(8, 8)
    for p in lin.parameters():
        from paddle_tpu.distributed.api import apply_placement

        apply_placement(p, mesh, [Replicate()])
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())

    @paddle.jit.to_static
    def step(lin, opt, x):
        y = nn.functional.dropout(lin(x), p=0.1, training=True)  # consumes RNG
        loss = (y * y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(np.random.rand(8, 8).astype(np.float32))
    x = dist.shard_tensor(x, mesh, [Shard(0)])
    float(step(lin, opt, x))

    # fresh params after the sharded step stay single-device
    fresh = nn.Linear(4, 4)
    for p in fresh.parameters():
        assert not isinstance(p._data.sharding, NamedSharding), (
            "fresh layer inherited a mesh sharding via the RNG key"
        )
    # and exports stay mesh-agnostic (1-device calling convention)
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        fresh.eval()
        paddle.jit.save(fresh, f"{d}/m", input_spec=[InputSpec([2, 4], "float32")])
        loaded = paddle.jit.load(f"{d}/m")
        assert loaded._exported.nr_devices == 1


class TestGraphBreakFallback:
    """to_static(full_graph=False) — the SOT analog (reference
    jit/sot/translate.py): untraceable data-dependent Python control flow
    falls back to eager with a per-signature guard cache."""

    def test_data_dependent_control_flow_runs(self):
        import warnings

        calls = {"n": 0}

        @paddle.jit.to_static(full_graph=False)
        def fn(x):
            calls["n"] += 1
            if float(x.sum()) > 0:  # data-dependent Python branch
                return x * 2
            return x - 1

        pos = paddle.to_tensor(np.ones((2, 2), np.float32))
        neg = paddle.to_tensor(-np.ones((2, 2), np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            np.testing.assert_allclose(fn(pos).numpy(), 2 * np.ones((2, 2)))
        assert any("graph break" in str(i.message) for i in w)
        # both branches work (pure-eager semantics)
        np.testing.assert_allclose(fn(neg).numpy(), -2 * np.ones((2, 2)))

    def test_guard_cache_skips_retrace(self):
        traces = {"n": 0}

        @paddle.jit.to_static(full_graph=False)
        def fn(x):
            traces["n"] += 1
            if float(x.max()) > 100:
                return x * 0
            return x + 1

        x = paddle.to_tensor(np.zeros((3,), np.float32))
        fn(x)
        n_after_first = traces["n"]  # trace attempt + eager run
        fn(x)
        fn(x)
        # guard cache: each later call is exactly ONE eager execution
        assert traces["n"] == n_after_first + 2

    def test_full_graph_still_raises(self):
        @paddle.jit.to_static  # default full_graph=True
        def fn(x):
            if float(x.sum()) > 0:
                return x * 2
            return x

        with pytest.raises(Exception):
            fn(paddle.to_tensor(np.ones((2,), np.float32)))

    def test_traceable_fn_still_compiles_under_partial_graph(self):
        """full_graph=False must not force eager for traceable functions."""
        traces = {"n": 0}

        @paddle.jit.to_static(full_graph=False)
        def fn(x):
            traces["n"] += 1
            return x * 3 + 1

        x = paddle.to_tensor(np.ones((4,), np.float32))
        fn(x)
        fn(x)
        fn(x)
        assert traces["n"] == 1  # traced once, compiled cache after

    def test_layer_mode_change_keeps_guard_per_signature(self):
        """A shape change is a NEW guard key: it gets its own trace attempt."""

        @paddle.jit.to_static(full_graph=False)
        def fn(x):
            if float(x.sum()) > 0:
                return x * 2
            return x

        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            a = fn(paddle.to_tensor(np.ones((2,), np.float32)))
            b = fn(paddle.to_tensor(np.ones((3, 3), np.float32)))
        np.testing.assert_allclose(a.numpy(), 2 * np.ones((2,)))
        np.testing.assert_allclose(b.numpy(), 2 * np.ones((3, 3)))


class TestGraphBreakGradRestore:
    """A trace that fails AFTER backward() must not leave tracer-valued
    grads on the live Parameters — the graph-break eager re-run (and any
    later grad accumulation) would silently operate on leaked tracers."""

    def test_backward_then_break_leaves_clean_grads(self):
        import warnings

        paddle.seed(0)
        layer = paddle.nn.Linear(4, 4)

        @paddle.jit.to_static(full_graph=False)
        def fn(layer, x):
            loss = (layer(x) ** 2).sum()
            loss.backward()  # grads written during the doomed trace
            if float(loss) > -1.0:  # concretization -> graph break
                return loss
            return loss * 0

        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = fn(layer, x)
        assert np.isfinite(float(out))
        g = layer.weight.grad
        assert g is not None  # the eager re-run produced real grads
        # a leaked tracer explodes on materialization / arithmetic
        gv = np.asarray(g.numpy())
        assert np.isfinite(gv).all() and np.abs(gv).sum() > 0
        # a SECOND call accumulates onto the eager grads without tracer mixing
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            fn(layer, x)
        np.testing.assert_allclose(layer.weight.grad.numpy(), 2 * gv, rtol=1e-6)

    def test_successful_trace_does_not_persist_tracer_grads(self):
        """Even WITHOUT a break: after a compiled call that ran backward()
        inside, params hold either None or concrete grads, never tracers."""
        paddle.seed(1)
        layer = paddle.nn.Linear(3, 3)

        @paddle.jit.to_static
        def step(layer, x):
            loss = layer(x).sum()
            loss.backward()
            grads = [p.grad for p in layer.parameters() if not p.stop_gradient]
            layer.clear_gradients()
            return loss, grads

        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        step(layer, x)
        for p in layer.parameters():
            if p.grad is not None:
                np.asarray(p.grad.numpy())  # must be concrete
