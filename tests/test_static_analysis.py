"""Static-analysis framework tests: per-checker fixtures (positive AND
negative per code), suppression semantics, reporters, CLI exit codes, and the
tier-1 gate — the whole-package self-run must come back with zero
unsuppressed violations."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from paddle_tpu.analysis import (
    all_checkers,
    all_codes,
    analyze_paths,
    analyze_source,
    render_json,
    render_text,
    summarize,
)

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "paddle_tpu"


def codes(src, **kw):
    return sorted(v.code for v in analyze_source(src, **kw) if not v.suppressed)


# -- TS: trace-safety --------------------------------------------------------

def test_ts101_print_in_jitted_function():
    assert "TS101" in codes(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    print(x)\n"
        "    return x\n"
    )


def test_ts101_negative_print_outside_trace():
    assert codes("def f(x):\n    print(x)\n    return x\n") == []


def test_ts101_function_passed_to_jax_jit():
    assert "TS101" in codes(
        "import jax\n"
        "def g(x):\n"
        "    print(x)\n"
        "    return x\n"
        "h = jax.jit(g, donate_argnums=(0,))\n"
    )


def test_ts101_method_passed_to_jax_jit_via_self():
    assert "TS101" in codes(
        "import jax\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self._fn = jax.jit(self._impl)\n"
        "    def _impl(self, x):\n"
        "        print(x)\n"
        "        return x\n"
    )


def test_ts_shard_map_body_is_traced():
    """The tensor-parallel collective seam: a function handed to shard_map
    (the per-shard kernel wrapper in the engine step path) is a traced body
    — flag reads / metrics / prints inside it fire per compile of the
    partitioned program, multiplied across the mesh."""
    assert "TS104" in codes(
        "from jax.experimental.shard_map import shard_map\n"
        "from paddle_tpu.observability import GLOBAL_METRICS\n"
        "def local_step(x):\n"
        "    GLOBAL_METRICS.counter('c').inc()\n"
        "    return x\n"
        "f = shard_map(local_step, mesh, in_specs=(), out_specs=())\n"
    )
    assert "TS101" in codes(
        "import jax\n"
        "def local_step(x):\n"
        "    print(x)\n"
        "    return x\n"
        "f = jax.experimental.shard_map.shard_map(local_step, mesh,\n"
        "                                         in_specs=(), out_specs=())\n"
    )
    # the modern spelling the repo itself prefers (conftest installs it)
    assert "TS101" in codes(
        "import jax\n"
        "def local_step(x):\n"
        "    print(x)\n"
        "    return x\n"
        "f = jax.shard_map(local_step, mesh=None, in_specs=(), out_specs=())\n"
    )


def test_ts_shard_map_negative_clean_body():
    # a clean per-shard body (the block_attention wrapper's shape) is fine,
    # and host code AROUND the shard_map call may do host things
    assert codes(
        "from jax.experimental.shard_map import shard_map\n"
        "def local_step(x):\n"
        "    return x * 2\n"
        "def dispatch(mesh, x):\n"
        "    print('host side is fine')\n"
        "    return shard_map(local_step, mesh, in_specs=(), out_specs=())(x)\n"
    ) == []


def test_ts_pjit_body_is_traced():
    assert "TS103" in codes(
        "import os\n"
        "from jax.experimental.pjit import pjit\n"
        "def step(x):\n"
        "    if os.environ.get('DEBUG'):\n"
        "        return x\n"
        "    return x + 1\n"
        "f = pjit(step)\n"
    )


def test_ts102_time_call():
    src = (
        "import time\n"
        "from paddle_tpu.jit import to_static\n"
        "@to_static\n"
        "def step(x):\n"
        "    t0 = time.perf_counter()\n"
        "    return x, t0\n"
    )
    assert "TS102" in codes(src)
    assert codes(src.replace("time.perf_counter()", "x + 1")) == []


def test_ts103_environ():
    assert "TS103" in codes(
        "import jax, os\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if os.environ.get('DEBUG'):\n"
        "        return x\n"
        "    return x + 1\n"
    )
    # reading the environment OUTSIDE the traced body is fine
    assert codes(
        "import jax, os\n"
        "dbg = os.environ.get('DEBUG')\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x\n"
    ) == []


def test_ts104_metrics_in_traced_body():
    assert "TS104" in codes(
        "import jax\n"
        "from paddle_tpu.observability import GLOBAL_METRICS\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    GLOBAL_METRICS.counter('c').inc()\n"
        "    return x\n"
    )
    assert "TS104" in codes(
        "import jax\n"
        "from paddle_tpu.observability import get_registry\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    get_registry().counter('c').inc()\n"
        "    return x\n"
    )


def test_ts104_negative_metrics_at_call_site():
    assert codes(
        "import jax\n"
        "from paddle_tpu.observability import GLOBAL_METRICS\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x\n"
        "def serve(x):\n"
        "    y = f(x)\n"
        "    GLOBAL_METRICS.counter('c').inc()\n"
        "    return y\n"
    ) == []


def test_ts105_param_materialization():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)\n"
    )
    assert "TS105" in codes(src)
    assert "TS105" in codes(src.replace("float(x)", "x.item()"))
    # float() of a non-parameter local is not flagged
    assert codes(src.replace("float(x)", "float(1.5) + x")) == []


def test_ts106_global_mutation():
    assert "TS106" in codes(
        "import jax\n"
        "_n = 0\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    global _n\n"
        "    _n += 1\n"
        "    return x\n"
    )
    assert codes(
        "_n = 0\n"
        "def f(x):\n"
        "    global _n\n"
        "    _n += 1\n"
        "    return x\n"
    ) == []


# -- PK: Pallas purity -------------------------------------------------------

def test_pk201_flag_read_in_kernel():
    assert "PK201" in codes(
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "def _add_kernel(x_ref, o_ref):\n"
        "    if GLOBAL_FLAGS.get('benchmark'):\n"
        "        o_ref[...] = x_ref[...]\n"
    )


def test_pk202_metrics_in_kernel():
    assert "PK202" in codes(
        "from paddle_tpu.observability import GLOBAL_METRICS\n"
        "def _add_kernel(x_ref, o_ref):\n"
        "    GLOBAL_METRICS.counter('c').inc()\n"
        "    o_ref[...] = x_ref[...]\n"
    )


def test_pk203_mutable_global_closure():
    src = (
        "_seen = {}\n"
        "NEG_INF = -1e30\n"
        "def _add_kernel(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...] + len(_seen) + NEG_INF\n"
    )
    got = codes(src)
    assert "PK203" in got
    # ALL_CAPS literal constants are allowed
    assert got.count("PK203") == 1


def test_pk203_negative_partial_bakes_state():
    assert codes(
        "import functools\n"
        "def _add_kernel(x_ref, o_ref, *, n):\n"
        "    o_ref[...] = x_ref[...] + n\n"
        "kernel = functools.partial(_add_kernel, n=3)\n"
    ) == []


def test_pk204_print_in_kernel_resolved_through_partial():
    # resolution path: pallas_call(k) where k = functools.partial(body, ...)
    assert "PK204" in codes(
        "import functools\n"
        "from jax.experimental import pallas as pl\n"
        "def body(x_ref, o_ref, *, n):\n"
        "    print('tracing')\n"
        "    o_ref[...] = x_ref[...]\n"
        "def run(x):\n"
        "    k = functools.partial(body, n=1)\n"
        "    return pl.pallas_call(k, out_shape=x)(x)\n"
    )


def test_pk204_index_map_lambda():
    assert "PK204" in codes(
        "import time\n"
        "from jax.experimental import pallas as pl\n"
        "spec = pl.BlockSpec((8, 8), lambda i, j: (i, int(time.time())))\n"
    )
    assert codes(
        "from jax.experimental import pallas as pl\n"
        "spec = pl.BlockSpec((8, 8), lambda i, j: (i, j))\n"
    ) == []


# -- FD: flag discipline -----------------------------------------------------

def test_fd301_undefined_flag():
    assert codes(
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "v = GLOBAL_FLAGS.get('definitely_not_a_flag')\n"
    ) == ["FD301"]
    # canonical flags.py names resolve
    assert codes(
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "v = GLOBAL_FLAGS.get('benchmark')\n"
    ) == []


def test_fd301_env_and_setters():
    assert codes("import os\nv = os.environ.get('FLAGS_nope')\n") == ["FD301"]
    assert codes("import os\nv = os.environ['FLAGS_benchmark']\n") == []
    assert codes("from paddle_tpu.flags import set_flags\nset_flags({'FLAGS_typo_flag': 1})\n") == ["FD301"]
    assert codes("from paddle_tpu.flags import get_flags\nget_flags(['benchmark', 'gone_flag'])\n") == ["FD301"]
    # the public attribute-qualified spellings resolve too
    assert codes("import paddle_tpu as paddle\npaddle.set_flags({'FLAGS_typo_flag': 1})\n") == ["FD301"]
    assert codes("import paddle_tpu as paddle\npaddle.set_flags({'FLAGS_benchmark': True})\n") == []


def test_fd301_define_in_same_run_resolves():
    assert codes(
        "from paddle_tpu.flags import GLOBAL_FLAGS, define_flag\n"
        "define_flag('my_new_flag', bool, False)\n"
        "v = GLOBAL_FLAGS.get('my_new_flag')\n"
    ) == []


def test_fd302_loop_read_in_hot_path():
    src = (
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "def scan(items):\n"
        "    for it in items:\n"
        "        if GLOBAL_FLAGS.get('benchmark'):\n"
        "            it.sync()\n"
    )
    assert codes(src, hot_path=True) == ["FD302"]
    assert codes(src, hot_path=False) == []
    hoisted = (
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "def scan(items):\n"
        "    bench = GLOBAL_FLAGS.get('benchmark')\n"
        "    for it in items:\n"
        "        if bench:\n"
        "            it.sync()\n"
    )
    assert codes(hoisted, hot_path=True) == []


# -- EH: exception hygiene ---------------------------------------------------

def test_eh401_bare_except():
    assert codes("try:\n    f()\nexcept:\n    g()\n") == ["EH401"]
    assert codes("try:\n    f()\nexcept ValueError:\n    g()\n") == []


def test_eh402_silent_swallow():
    assert "EH402" in codes("try:\n    f()\nexcept Exception:\n    pass\n")
    # logging the failure is not silent
    assert codes(
        "import logging\n"
        "try:\n"
        "    f()\n"
        "except Exception:  # tolerable: best-effort hook\n"
        "    logging.getLogger(__name__).warning('f failed')\n"
    ) == []


def test_eh403_lint_tags_are_not_reasons():
    # a bare noqa / type: ignore / pragma tag says nothing about WHY breadth
    # is correct — it must not satisfy EH403
    assert codes("try:\n    f()\nexcept Exception:  # noqa: BLE001\n    y = 0\n") == ["EH403"]
    assert codes("try:\n    f()\nexcept Exception:  # type: ignore[misc]\n    y = 0\n") == ["EH403"]
    # a tag FOLLOWED by prose is fine
    assert codes(
        "try:\n    f()\nexcept Exception:  # noqa: BLE001 - fallback covers it\n    y = 0\n"
    ) == []


def test_eh403_broad_except_needs_reason():
    assert codes("try:\n    f()\nexcept Exception as exc:\n    y = 0\n") == ["EH403"]
    assert codes("try:\n    f()\nexcept Exception as exc:  # fallback below\n    y = 0\n") == []
    # comment-only line opening the body also counts (repo idiom)
    assert codes(
        "try:\n"
        "    f()\n"
        "except Exception as exc:\n"
        "    # fallback: the retry path below re-raises on second failure\n"
        "    y = 0\n"
    ) == []


# -- RB: robustness ----------------------------------------------------------

def test_rb501_os_exit_flagged():
    assert codes("import os\ndef f():\n    os._exit(1)\n") == ["RB501"]


def test_rb501_through_import_alias():
    assert codes("import os as _os\ndef f():\n    _os._exit(7)\n") == ["RB501"]
    assert codes("from os import _exit\ndef f():\n    _exit(7)\n") == ["RB501"]
    assert codes("from os import _exit as bail\ndef f():\n    bail(7)\n") == ["RB501"]


def test_rb501_negative_sys_exit_and_other_exits():
    assert codes("import sys\ndef f():\n    sys.exit(1)\n") == []
    assert codes("import os\ndef f():\n    os.kill(1, 9)\n") == []


def test_rb501_allowed_in_watchdog_and_launch():
    src = "import os\ndef f():\n    os._exit(124)\n"
    assert codes(src, path="paddle_tpu/distributed/watchdog.py") == []
    assert codes(src, path="paddle_tpu/distributed/launch/main.py") == []
    assert codes(src, path="paddle_tpu/distributed/launch/sub/mod.py") == []
    # ... but NOT elsewhere under distributed/
    assert codes(src, path="paddle_tpu/distributed/collective.py") == ["RB501"]


def test_rb501_suppressible_with_reason():
    vs = analyze_source(
        "import os\n"
        "def f():\n"
        "    # analysis: disable=RB501 forked child owns no state to flush\n"
        "    os._exit(1)\n"
    )
    assert [v.code for v in vs] == ["RB501"]
    assert vs[0].suppressed and vs[0].reason


# -- RB502: un-timed blocking waits in request-serving paths ------------------

SERVING = "paddle_tpu/serving/worker.py"


def test_rb502_untimed_queue_get_flagged():
    src = "import queue\nq = queue.Queue()\nitem = q.get()\n"
    assert codes(src, path=SERVING) == ["RB502"]
    # from-import constructor form
    src = "from queue import Queue\nq = Queue()\nitem = q.get()\n"
    assert codes(src, path=SERVING) == ["RB502"]


def test_rb502_timed_queue_get_ok():
    assert codes(
        "import queue\nq = queue.Queue()\nitem = q.get(timeout=5)\n", path=SERVING
    ) == []
    # positional form get(block, timeout) and get_nowait are both fine
    assert codes(
        "import queue\nq = queue.Queue()\nitem = q.get(True, 5)\n", path=SERVING
    ) == []
    assert codes(
        "import queue\nq = queue.Queue()\nitem = q.get_nowait()\n", path=SERVING
    ) == []


def test_rb502_dict_get_and_str_join_not_confused_for_waits():
    # constructor tracking: untracked receivers never match
    assert codes("d = {}\nv = d.get('k')\n", path=SERVING) == []
    assert codes("s = ','.join(['a'])\n", path=SERVING) == []
    assert codes("import os\np = os.path.join('a', 'b')\n", path=SERVING) == []


def test_rb502_annotated_assignment_receivers_are_tracked():
    # `self._q: Queue = Queue()` is an AnnAssign — the exact construction
    # style the serving frontend uses; it must not be invisible
    src = (
        "from queue import Queue\n"
        "class H:\n"
        "    def __init__(self):\n"
        "        self._q: Queue = Queue()\n"
        "    def take(self):\n"
        "        return self._q.get()\n"
    )
    assert codes(src, path=SERVING) == ["RB502"]
    assert codes(src.replace(".get()", ".get(timeout=1)"), path=SERVING) == []


def test_rb502_event_wait_and_thread_join():
    src = (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._done = threading.Event()\n"
        "        self._t = threading.Thread(target=print)\n"
        "    def finish(self):\n"
        "        self._done.wait()\n"
        "        self._t.join()\n"
    )
    assert codes(src, path="paddle_tpu/inference/x.py") == ["RB502", "RB502"]
    timed = src.replace(".wait()", ".wait(timeout=2)").replace(".join()", ".join(5)")
    assert codes(timed, path="paddle_tpu/inference/x.py") == []


def test_rb502_socket_recv_needs_settimeout():
    src = "import socket\ns = socket.socket()\ndata = s.recv(1024)\n"
    assert codes(src, path="paddle_tpu/distributed/x.py") == ["RB502"]
    timed = "import socket\ns = socket.socket()\ns.settimeout(3)\ndata = s.recv(1024)\n"
    assert codes(timed, path="paddle_tpu/distributed/x.py") == []


def test_rb502_only_in_request_serving_dirs():
    src = "import queue\nq = queue.Queue()\nitem = q.get()\n"
    assert codes(src, path="paddle_tpu/models/x.py") == []
    assert codes(src, path="paddle_tpu/kernels/x.py") == []
    for gated in ("serving", "distributed", "inference"):
        assert codes(src, path=f"paddle_tpu/{gated}/x.py") == ["RB502"]


def test_rb502_suppressible_with_reason():
    vs = analyze_source(
        "import queue\n"
        "q = queue.Queue()\n"
        "# analysis: disable=RB502 shutdown path; producer provably alive\n"
        "item = q.get()\n",
        path=SERVING,
    )
    assert [v.code for v in vs] == ["RB502"]
    assert vs[0].suppressed and vs[0].reason


# -- RB503: unbounded retry loops in request-serving paths --------------------

def test_rb503_unbounded_retry_loop_flagged():
    # success-exit alone is NOT a bound: a permanently-dead dependency
    # never delivers success
    src = (
        "def pump(router):\n"
        "    while True:\n"
        "        ok = router.redispatch()\n"
        "        if ok:\n"
        "            break\n"
    )
    assert codes(src, path=SERVING) == ["RB503"]
    # recover()-shaped retries too
    src = "def f(engine):\n    while True:\n        engine.recover()\n"
    assert codes(src, path=SERVING) == ["RB503"]


def test_rb503_attempt_counter_bounds_the_loop():
    src = (
        "def f(x, max_attempts):\n"
        "    attempt = 0\n"
        "    while True:\n"
        "        attempt += 1\n"
        "        if attempt >= max_attempts:\n"
        "            raise RuntimeError('retries exhausted')\n"
        "        if retry_step(x):\n"
        "            return\n"
    )
    assert codes(src, path=SERVING) == []


def test_rb503_deadline_and_expired_checks_bound_the_loop():
    src = (
        "import time\n"
        "def f(req, deadline):\n"
        "    while True:\n"
        "        if time.perf_counter() >= deadline:\n"
        "            raise TimeoutError()\n"
        "        recover(req)\n"
    )
    assert codes(src, path=SERVING) == []
    src = (
        "def f(req):\n"
        "    while True:\n"
        "        if req.expired():\n"
        "            raise TimeoutError()\n"
        "        redispatch(req)\n"
    )
    assert codes(src, path=SERVING) == []


def test_rb503_conditioned_while_and_non_retry_loops_ok():
    # a conditioned while IS its own bound
    src = (
        "def f(r, n):\n"
        "    i = 0\n"
        "    while i < n:\n"
        "        r.redispatch()\n"
        "        i += 1\n"
    )
    assert codes(src, path=SERVING) == []
    # while True without a retry-shaped call is not this checker's business
    src = (
        "def f(q):\n"
        "    while True:\n"
        "        item = q.get_nowait()\n"
        "        if item is None:\n"
        "            break\n"
    )
    assert codes(src, path=SERVING) == []


def test_rb503_only_in_request_serving_dirs():
    src = "def f(r):\n    while True:\n        r.redispatch()\n"
    assert codes(src, path="paddle_tpu/models/x.py") == []
    for gated in ("serving", "distributed", "inference"):
        assert codes(src, path=f"paddle_tpu/{gated}/x.py") == ["RB503"]


def test_rb503_nested_function_retry_is_not_the_outer_loops_problem():
    # a closure's retry belongs to that function's own loop discipline
    src = (
        "def f(q):\n"
        "    while True:\n"
        "        def later():\n"
        "            retry_op()\n"
        "        item = q.get_nowait()\n"
        "        if item is None:\n"
        "            break\n"
    )
    assert codes(src, path=SERVING) == []


def test_rb503_suppressible_with_reason():
    vs = analyze_source(
        "def f(r):\n"
        "    # analysis: disable=RB503 bounded by the caller's watchdog\n"
        "    while True:\n"
        "        r.redispatch()\n",
        path=SERVING,
    )
    assert [v.code for v in vs] == ["RB503"]
    assert vs[0].suppressed and vs[0].reason


# -- OB: observability discipline --------------------------------------------

def test_ob601_span_opened_without_with_leaks():
    # armed Span assigned to a variable: __exit__ never runs, silent leak
    assert codes('sp = tracer.span("phase")\n') == ["OB601"]
    assert codes('x = self._tracer.span("phase")\n') == ["OB601"]
    assert codes('GLOBAL_TRACER.span("phase")\n') == ["OB601"]
    assert codes('s = get_tracer().span("phase")\n') == ["OB601"]


def test_ob601_with_statement_and_retroactive_forms_ok():
    assert codes('with tracer.span("phase") as sp:\n    sp.set_attr("k", 1)\n') == []
    # add_span/add_event take explicit timestamps: no with required
    assert codes('tracer.add_span("phase", start_s=0.0, end_s=1.0)\n') == []
    assert codes('tracer.add_event("mark")\n') == []


def test_ob601_unrelated_span_and_record_receivers_not_confused():
    # .span on a non-tracer receiver, .record on a non-recorder receiver
    assert codes('cell.span(3)\n') == []
    assert codes('db.record("row")\n') == []
    assert codes('wingspan = bird.span("wide")\n') == []


def test_ob601_emission_inside_jitted_body():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    with tracer.span('inner'):\n"
        "        return x\n"
    )
    assert codes(src) == ["OB601"]
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    record_event('admit', req_id=1)\n"
        "    return x\n"
    )
    assert codes(src) == ["OB601"]
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    GLOBAL_FLIGHT_RECORDER.record('admit', req_id=1)\n"
        "    return x\n"
    )
    assert codes(src) == ["OB601"]


def test_ob601_emission_inside_pallas_kernel():
    src = (
        "import jax.experimental.pallas as pl\n"
        "def my_kernel(x_ref, o_ref):\n"
        "    record_event('tile')\n"
        "    o_ref[...] = x_ref[...]\n"
        "def run(x):\n"
        "    return pl.pallas_call(my_kernel, out_shape=x)(x)\n"
    )
    assert codes(src) == ["OB601"]


def test_ob601_host_call_site_pattern_is_clean():
    # the sanctioned shape: dispatch inside jit, emission at the call site
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return x * 2\n"
        "def drive(x):\n"
        "    y = step(x)\n"
        "    record_event('stepped')\n"
        "    with tracer.span('post') as sp:\n"
        "        sp.set_attr('ok', True)\n"
        "    return y\n"
    )
    assert codes(src) == []


def test_ob601_suppressible_with_reason():
    vs = analyze_source(
        "# analysis: disable=OB601 span handed to a helper that closes it\n"
        "sp = tracer.span('phase')\n"
    )
    assert [v.code for v in vs] == ["OB601"]
    assert vs[0].suppressed and vs[0].reason


def test_ob602_typo_in_registry_read_fires():
    # .family() is the strict-read API: any receiver counts
    assert codes('fam = registry.family("bogus_family_name_total")\n') == ["OB602"]
    # .get() on a registry-shaped receiver
    assert codes('fam = GLOBAL_METRICS.get("bogus_family_name_total")\n') == ["OB602"]
    assert codes('fam = self._registry.get("bogus_family_name_total")\n') == ["OB602"]
    assert codes('fam = get_registry().get("bogus_family_name_total")\n') == ["OB602"]


def test_ob602_registered_names_resolve():
    # a name defined in the SAME snippet resolves
    src = (
        'c = reg.counter("snippet_family_total", "help")\n'
        'back = GLOBAL_METRICS.get("snippet_family_total")\n'
    )
    assert codes(src) == []
    # a real package family resolves through the canonical package scan
    assert codes(
        'fam = registry.family("engine_requests_admitted_total")\n'
    ) == []
    assert codes('fam = registry.family("serving_shed_total")\n') == []


def test_ob602_non_registry_receivers_not_confused():
    # dict/config .get with a literal is NOT a registry read
    assert codes('v = cfg.get("whatever_key")\n') == []
    assert codes('v = self._metrics.get("shed")\n') == []
    assert codes('v = os.environ.get("PATH")\n') == []
    # dynamic names are out of static scope (runtime family() raises)
    assert codes("fam = registry.family(name)\n") == []


def test_ob602_suppressible_with_reason():
    vs = analyze_source(
        "# analysis: disable=OB602 family registered by an optional plugin\n"
        'fam = registry.family("plugin_only_family_total")\n'
    )
    assert [v.code for v in vs] == ["OB602"]
    assert vs[0].suppressed and vs[0].reason


def test_ob602_fleet_family_list_resolves():
    # the aggregation module's whole literal list must resolve: the drift
    # this checker exists for is exactly a rename desynchronizing these
    from paddle_tpu.analysis.checkers.observability import (
        _package_family_universe,
    )
    from paddle_tpu.observability.aggregate import FLEET_COUNTER_FAMILIES

    universe = _package_family_universe()
    missing = [n for n in FLEET_COUNTER_FAMILIES if n not in universe]
    assert not missing, f"fleet families not registered anywhere: {missing}"


def test_ob603_timed_dispatch_without_sync_fires():
    # perf_counter pair brackets a jitted call with no device sync before
    # the stop timestamp: the "measured" time is dispatch, not execution
    assert codes(
        "import jax, time\n"
        "def g(x):\n"
        "    return x\n"
        "f = jax.jit(g)\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = f(x)\n"
        "    t1 = time.perf_counter()\n"
        "    return t1 - t0, y\n"
    ) == ["OB603"]


def test_ob603_self_attribute_jitted_callable():
    assert codes(
        "import jax, time\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self._fn = jax.jit(lambda x: x)\n"
        "    def step(self, x):\n"
        "        t0 = time.time()\n"
        "        y = self._fn(x)\n"
        "        t1 = time.time()\n"
        "        return t1 - t0, y\n"
    ) == ["OB603"]


def test_ob603_sync_before_stop_is_honest():
    assert codes(
        "import jax, time\n"
        "def g(x):\n"
        "    return x\n"
        "f = jax.jit(g)\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = f(x)\n"
        "    jax.block_until_ready(y)\n"
        "    t1 = time.perf_counter()\n"
        "    return t1 - t0, y\n"
    ) == []


def test_ob603_fused_dispatch_and_sync_in_one_statement():
    # np.asarray(f(x)) blocks on the result in the same statement: honest
    assert codes(
        "import jax, time\n"
        "import numpy as np\n"
        "def g(x):\n"
        "    return x\n"
        "f = jax.jit(g)\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = np.asarray(f(x))\n"
        "    t1 = time.perf_counter()\n"
        "    return t1 - t0, y\n"
    ) == []


def test_ob603_non_jitted_call_not_confused():
    assert codes(
        "import time\n"
        "def helper(x):\n"
        "    return x + 1\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = helper(x)\n"
        "    t1 = time.perf_counter()\n"
        "    return t1 - t0, y\n"
    ) == []


def test_ob603_dispatch_before_first_timestamp_not_flagged():
    # a jitted warmup call ahead of the timing window is fine
    assert codes(
        "import jax, time\n"
        "def g(x):\n"
        "    return x\n"
        "f = jax.jit(g)\n"
        "def bench(x):\n"
        "    y = f(x)\n"
        "    jax.block_until_ready(y)\n"
        "    t0 = time.perf_counter()\n"
        "    t1 = time.perf_counter()\n"
        "    return t1 - t0\n"
    ) == []


def test_ob603_suppressible_with_reason():
    vs = analyze_source(
        "import jax, time\n"
        "def g(x):\n"
        "    return x\n"
        "f = jax.jit(g)\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = f(x)\n"
        "    # analysis: disable=OB603 dispatch cost is the quantity under test\n"
        "    t1 = time.perf_counter()\n"
        "    return t1 - t0, y\n"
    )
    ob = [v for v in vs if v.code == "OB603"]
    assert len(ob) == 1
    assert ob[0].suppressed and ob[0].reason


# -- suppressions ------------------------------------------------------------

def test_suppression_with_reason():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "except:  # analysis: disable=EH401 exercised by fixture\n"
        "    g()\n"
    )
    assert len(vs) == 1 and vs[0].suppressed and vs[0].reason == "exercised by fixture"


def test_suppression_on_preceding_comment_line():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "# analysis: disable=EH401 fixture wants it suppressed\n"
        "except:\n"
        "    g()\n"
    )
    assert [v.suppressed for v in vs] == [True]


def test_suppression_without_reason_does_not_suppress():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "except:  # analysis: disable=EH401\n"
        "    g()\n"
    )
    assert len(vs) == 1 and not vs[0].suppressed
    assert "missing reason" in vs[0].message


def test_suppression_wrong_code_does_not_suppress():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "except:  # analysis: disable=TS101 not the right code\n"
        "    g()\n"
    )
    assert len(vs) == 1 and not vs[0].suppressed


def test_suppression_preceding_line_wins_over_unrelated_inline_disable():
    # an inline disable for a DIFFERENT code must not mask a valid
    # suppression sitting on the preceding comment line
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "# analysis: disable=EH401 fixture suppresses the bare except\n"
        "except:  # analysis: disable=TS101 unrelated code\n"
        "    g()\n"
    )
    assert [v.suppressed for v in vs] == [True]
    assert vs[0].reason == "fixture suppresses the bare except"


def test_suppression_multiple_codes():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "except:  # analysis: disable=TS101,EH401 fixture covers both\n"
        "    g()\n"
    )
    assert [v.suppressed for v in vs] == [True]


# -- reporters + registry ----------------------------------------------------

def test_reporters_and_summary():
    vs = analyze_source("try:\n    f()\nexcept:\n    pass\n")
    data = json.loads(render_json(vs))
    assert data["summary"]["unsuppressed"] == len(vs) >= 1
    assert {v["code"] for v in data["violations"]} >= {"EH401"}
    text = render_text(vs)
    assert "EH401" in text and "unsuppressed" in text


def test_checker_codes_unique_and_documented():
    table = all_codes()
    assert {"TS101", "PK201", "FD301", "EH401"} <= set(table)
    for checker in all_checkers():
        for code, desc in checker.codes.items():
            assert desc, code


# -- CLI ---------------------------------------------------------------------

def _run_cli(args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    f()\nexcept:\n    pass\n")
    good = tmp_path / "good.py"
    good.write_text("def f():\n    return 1\n")
    r = _run_cli([str(bad)])
    assert r.returncode == 1 and "EH401" in r.stdout
    r = _run_cli(["--format", "json", str(good)])
    assert r.returncode == 0
    assert json.loads(r.stdout)["summary"]["unsuppressed"] == 0


def test_cli_missing_path_is_a_usage_error(tmp_path):
    # a typo'd target must not become a vacuous zero-file clean pass
    r = _run_cli([str(tmp_path / "no_such_dir")])
    assert r.returncode == 2 and "no such file" in r.stderr
    # ... and neither must an existing directory holding no Python files
    empty = tmp_path / "empty"
    empty.mkdir()
    r = _run_cli([str(empty)])
    assert r.returncode == 2 and "no Python files" in r.stderr


def test_cli_select_unknown_code_is_a_usage_error(tmp_path):
    # the same never-vacuous rule: a typo'd --select used to filter every
    # finding and exit 0, so a CI invocation passed without checking anything
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    f()\nexcept:\n    pass\n")
    r = _run_cli(["--select", "EH999", str(bad)])
    assert r.returncode == 2
    assert "EH999" in r.stderr and "valid codes" in r.stderr
    assert "EH401" in r.stderr  # the list names what IS registered
    # a valid prefix mixed with a bogus one still errors (no partial pass)
    r = _run_cli(["--select", "EH,TYPO", str(bad)])
    assert r.returncode == 2 and "TYPO" in r.stderr
    # family prefixes and exact codes stay accepted
    r = _run_cli(["--select", "EH", str(bad)])
    assert r.returncode == 1 and "EH401" in r.stdout
    r = _run_cli(["--select", "EH401", str(bad)])
    assert r.returncode == 1


def _run_cli_in(cwd, args):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", *args],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=300,
    )


def test_cli_changed_only_scopes_to_git_diff(tmp_path):
    def git(*argv):
        subprocess.run(
            ["git", *argv], cwd=tmp_path, check=True, capture_output=True,
            env=dict(
                os.environ,
                GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t",
            ),
        )

    git("init", "-q")
    clean = tmp_path / "clean.py"
    clean.write_text("try:\n    f()\nexcept:\n    pass\n")  # committed finding
    git("add", "clean.py")
    git("commit", "-qm", "base")
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    g()\nexcept:\n    pass\n")  # untracked finding
    # only the changed file is analyzed: clean.py's finding does not gate
    r = _run_cli_in(tmp_path, ["--changed-only=HEAD", "."])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "bad.py" in r.stdout and "clean.py" not in r.stdout
    # everything committed: nothing changed -> clean exit, nothing analyzed
    git("add", "bad.py")
    git("commit", "-qm", "rest")
    r = _run_cli_in(tmp_path, ["--changed-only=HEAD", "."])
    assert r.returncode == 0 and "no Python files changed" in r.stdout


def test_cli_changed_only_falls_back_without_git(tmp_path):
    # outside any repo (or with a bad ref) the mode must degrade to a FULL
    # run with a warning — never a vacuous zero-file pass
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    f()\nexcept:\n    pass\n")
    r = _run_cli_in(tmp_path, ["--changed-only=not-a-real-ref", "."])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "falling back to a full run" in r.stderr
    assert "bad.py" in r.stdout


def test_autotune_verbose_handler_follows_the_flag():
    import logging

    import paddle_tpu as paddle
    from paddle_tpu.kernels.autotune import _logger, _verbose_state

    prior = _logger.level
    try:
        paddle.set_flags({"FLAGS_kernel_autotune_verbose": True})
        assert _verbose_state and _verbose_state[0] in _logger.handlers
        paddle.set_flags({"FLAGS_kernel_autotune_verbose": False})
        assert not _verbose_state
        assert not any(isinstance(h, logging.StreamHandler) for h in _logger.handlers)
        assert _logger.level == prior
    finally:
        paddle.set_flags({"FLAGS_kernel_autotune_verbose": False})
        _logger.setLevel(prior)


# -- dataflow layer: thread-entry discovery ----------------------------------

def _graph_of(src, path="<snippet>.py"):
    import ast as _ast

    from paddle_tpu.analysis.dataflow import PackageIndex

    idx = PackageIndex()
    return idx, idx.add_module(path, _ast.parse(src))


def test_thread_entry_thread_target_self_method():
    _, g = _graph_of(
        "import threading\n"
        "class S:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._run, daemon=True)\n"
        "    def _run(self):\n"
        "        pass\n"
    )
    assert ("S._run", "thread") in {(q, k) for q, k, _ in g.thread_entries}


def test_thread_entry_module_function_target():
    _, g = _graph_of(
        "import threading\n"
        "def worker():\n"
        "    pass\n"
        "t = threading.Thread(target=worker)\n"
    )
    assert ("worker", "thread") in {(q, k) for q, k, _ in g.thread_entries}


def test_thread_entry_http_handler_methods():
    _, g = _graph_of(
        "from http.server import BaseHTTPRequestHandler\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_GET(self):\n"
        "        pass\n"
        "    def _helper(self):\n"
        "        pass\n"
    )
    kinds = {(q, k) for q, k, _ in g.thread_entries}
    assert ("H.do_GET", "handler") in kinds and ("H._helper", "handler") in kinds


def test_thread_entry_flag_listener():
    _, g = _graph_of(
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "def _refresh(value):\n"
        "    pass\n"
        "GLOBAL_FLAGS.on_change('enable_metrics', _refresh)\n"
    )
    assert ("_refresh", "listener") in {(q, k) for q, k, _ in g.thread_entries}


def test_jit_wrapper_conditional_donate_argnums_resolves():
    """The engine's `(1,) if donate else ()` idiom yields position 1."""
    _, g = _graph_of(
        "import jax\n"
        "class E:\n"
        "    def __init__(self, impl, donate):\n"
        "        self._fn = jax.jit(impl, donate_argnums=(1,) if donate else ())\n"
    )
    w = g.jit_wrappers[("E", "self._fn")]
    assert w.donated == frozenset({1})


def test_package_index_memoizes_per_module_graphs():
    import ast as _ast

    from paddle_tpu.analysis.dataflow import PackageIndex

    idx = PackageIndex()
    tree = _ast.parse("def f():\n    pass\n")
    idx.add_module("a.py", tree)
    idx.add_module("a.py", tree)
    idx.add_module("a.py", tree)
    assert idx.build_count == 1


# -- CC: concurrency ---------------------------------------------------------

_CC_THREADED_CLASS = (
    "import threading\n"
    "class Server:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._jobs = {}\n"
    "        self._t = threading.Thread(target=self._run)\n"
    "    def _run(self):\n"
    "        while True:\n"
    "            with self._lock:\n"
    "                self._jobs['x'] = 1\n"
)


def test_cc701_unguarded_read_of_guarded_field():
    src = _CC_THREADED_CLASS + (
        "    def peek(self):\n"
        "        return self._jobs.get('x')\n"
    )
    assert "CC701" in codes(src)


def test_cc701_negative_all_accesses_locked():
    src = _CC_THREADED_CLASS + (
        "    def peek(self):\n"
        "        with self._lock:\n"
        "            return self._jobs.get('x')\n"
    )
    assert codes(src) == []


def test_cc701_negative_helper_inherits_lock_from_call_sites():
    """A helper whose every call site holds the lock is effectively locked
    (interprocedural fixpoint) — the frontend's submit->_tenant_label shape."""
    src = _CC_THREADED_CLASS + (
        "    def _peek_locked(self):\n"
        "        return self._jobs.get('x')\n"
        "    def peek(self):\n"
        "        with self._lock:\n"
        "            return self._peek_locked()\n"
    )
    assert codes(src) == []


def test_cc701_negative_no_thread_seam_means_silence():
    """A lock-owning class with no thread entry anywhere never fires —
    single-threaded code with a vestigial lock is not a race."""
    src = (
        "import threading\n"
        "class Quiet:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._jobs = {}\n"
        "    def put(self):\n"
        "        with self._lock:\n"
        "            self._jobs['x'] = 1\n"
        "    def peek(self):\n"
        "        return self._jobs.get('x')\n"
    )
    assert codes(src) == []


def test_cc701_negative_sync_primitive_fields_exempt():
    src = _CC_THREADED_CLASS + (
        "    def wait(self):\n"
        "        self._evt = threading.Event()\n"
        "        self._evt.wait(1.0)\n"
    )
    assert codes(src) == []


def test_cc702_inverted_lock_order():
    src = (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._la = threading.Lock()\n"
        "        self._lb = threading.Lock()\n"
        "        threading.Thread(target=self.f1).start()\n"
        "    def f1(self):\n"
        "        with self._la:\n"
        "            with self._lb:\n"
        "                pass\n"
        "    def f2(self):\n"
        "        with self._lb:\n"
        "            with self._la:\n"
        "                pass\n"
    )
    assert "CC702" in codes(src)


def test_cc702_negative_consistent_order():
    src = (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._la = threading.Lock()\n"
        "        self._lb = threading.Lock()\n"
        "        threading.Thread(target=self.f1).start()\n"
        "    def f1(self):\n"
        "        with self._la:\n"
        "            with self._lb:\n"
        "                pass\n"
        "    def f2(self):\n"
        "        with self._la:\n"
        "            with self._lb:\n"
        "                pass\n"
    )
    assert codes(src) == []


def test_cc702_interprocedural_through_call_edge():
    """f2 holds lb and calls g which takes la — inverted vs f1's la->lb."""
    src = (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._la = threading.Lock()\n"
        "        self._lb = threading.Lock()\n"
        "        threading.Thread(target=self.f1).start()\n"
        "    def f1(self):\n"
        "        with self._la:\n"
        "            with self._lb:\n"
        "                pass\n"
        "    def g(self):\n"
        "        with self._la:\n"
        "            pass\n"
        "    def f2(self):\n"
        "        with self._lb:\n"
        "            self.g()\n"
    )
    assert "CC702" in codes(src)


def test_cc703_iteration_outside_lock():
    src = _CC_THREADED_CLASS + (
        "    def snapshot(self):\n"
        "        return list(self._jobs)\n"
    )
    assert "CC703" in codes(src)


def test_cc703_negative_iteration_under_lock():
    src = _CC_THREADED_CLASS + (
        "    def snapshot(self):\n"
        "        with self._lock:\n"
        "            return list(self._jobs)\n"
    )
    assert codes(src) == []


_CC704_HOT_LOOP = (
    "from paddle_tpu.flags import GLOBAL_FLAGS\n"
    "def dispatch(x):\n"
    "    if GLOBAL_FLAGS.get('check_nan_inf'):\n"
    "        scan(x)\n"
    "    return x\n"
    "def run(xs):\n"
    "    out = []\n"
    "    for x in xs:\n"
    "        out.append(dispatch(x))\n"
    "    return out\n"
)


def test_cc704_reverted_nan_check_shape_is_flagged():
    """Regression fixture: the pre-PR3 core/dispatch.py shape — a registry
    read inside a function the call graph reaches from a loop. FD302 could
    not see this (no syntactic loop around the read); the interprocedural
    pass can."""
    assert "CC704" in codes(_CC704_HOT_LOOP, hot_path=True)


def test_cc704_negative_outside_hot_path_modules():
    assert codes(_CC704_HOT_LOOP, hot_path=False) == []


def test_cc704_negative_unreachable_from_any_loop():
    src = (
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "def configure():\n"
        "    return GLOBAL_FLAGS.get('check_nan_inf')\n"
    )
    assert codes(src, hot_path=True) == []


def test_cc704_current_dispatch_module_is_clean():
    """The fixed core/dispatch.py (_NAN_CHECK cached locals) stays clean."""
    vs = analyze_paths([str(PKG / "core" / "dispatch.py")], select=["CC704"])
    assert [v for v in vs if not v.suppressed] == []


# -- DN: donation / buffer lifetime ------------------------------------------

_DN_ENGINE_HEADER = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "import numpy as np\n"
    "class Eng:\n"
    "    def __init__(self, impl):\n"
    "        self._fn = jax.jit(impl, donate_argnums=(1,))\n"
    "        self._state = init()\n"
    "        self._ntok = np.zeros((4,), np.int32)\n"
    "        self._last_tok = np.zeros((4,), np.int32)\n"
)


def test_dn801_read_after_donate():
    src = _DN_ENGINE_HEADER + (
        "    def step(self, x):\n"
        "        out, new_state = self._fn(x, self._state)\n"
        "        y = self._state.sum()\n"
        "        self._state = new_state\n"
        "        return out, y\n"
    )
    assert "DN801" in codes(src)


def test_dn801_negative_donate_and_rebind_same_statement():
    src = _DN_ENGINE_HEADER + (
        "    def step(self, x):\n"
        "        out, self._state = self._fn(x, self._state)\n"
        "        return out\n"
    )
    assert codes(src) == []


def test_dn801_mutation_after_donate():
    src = _DN_ENGINE_HEADER + (
        "    def step(self, x):\n"
        "        out, new_state = self._fn(x, self._state)\n"
        "        self._state[0] = 0\n"
        "        return out\n"
    )
    assert "DN801" in codes(src)


def test_dn801_negative_read_in_untaken_branch_arm():
    """A donate in the `if` arm must not taint the sibling `else` arm."""
    src = _DN_ENGINE_HEADER + (
        "    def step(self, x, fast):\n"
        "        if fast:\n"
        "            out, self._state = self._fn(x, self._state)\n"
        "        else:\n"
        "            out = slow(x, self._state)\n"
        "        return out\n"
    )
    assert codes(src) == []


def test_dn802_replay_race_minimized_pr6_replica():
    """The PR 6 recovery-replay race, minimized: host vectors handed to the
    decode dispatch WITHOUT .copy(), then mutated in the same loop body —
    replay never syncs (the emitted tokens are discarded), so the async
    dispatch still aliases the numpy memory being mutated."""
    src = _DN_ENGINE_HEADER + (
        "    def replay(self, tables, depth):\n"
        "        for r in range(depth):\n"
        "            lens = jnp.asarray(self._ntok)\n"
        "            toks = jnp.asarray(self._last_tok)\n"
        "            _nxt, self._state = self._fn(toks, self._state, lens)\n"
        "            for i in range(4):\n"
        "                self._ntok[i] += 1\n"
        "                self._last_tok[i] = 7\n"
    )
    found = codes(src)
    assert "DN802" in found, found


def test_dn802_negative_snapshot_copy_is_the_fix():
    """jnp.asarray(buf.copy()) — the exact PR 6 fix shape — is clean."""
    src = _DN_ENGINE_HEADER + (
        "    def replay(self, tables, depth):\n"
        "        for r in range(depth):\n"
        "            lens = jnp.asarray(self._ntok.copy())\n"
        "            toks = jnp.asarray(self._last_tok.copy())\n"
        "            _nxt, self._state = self._fn(toks, self._state, lens)\n"
        "            for i in range(4):\n"
        "                self._ntok[i] += 1\n"
        "                self._last_tok[i] = 7\n"
    )
    assert codes(src) == []


def test_dn802_chunked_dispatch_block_table_mutation():
    """Chunked-prefill shape of the replay race: the per-slot block table
    (host numpy) is handed to the unified mixed prefill/decode dispatch,
    then mutated (a new block appended for the next chunk) before any sync
    point — the async dispatch still aliases the table memory."""
    src = _DN_ENGINE_HEADER + (
        "    def chunk_steps(self, depth):\n"
        "        for r in range(depth):\n"
        "            tables = jnp.asarray(self._ntok)\n"
        "            q_lens = jnp.asarray(self._last_tok)\n"
        "            _nxt, self._state = self._fn(tables, self._state, q_lens)\n"
        "            for i in range(4):\n"
        "                self._ntok[i] = 9\n"
        "                self._last_tok[i] += 1\n"
    )
    found = codes(src)
    assert "DN802" in found, found


def test_dn802_negative_chunked_dispatch_synced_then_mutated():
    """The engine's actual unified-step shape: np.asarray(nxt) syncs the
    dispatch before _ntok advances and the tables regrow — clean."""
    src = _DN_ENGINE_HEADER + (
        "    def chunk_steps(self, depth):\n"
        "        for r in range(depth):\n"
        "            tables = jnp.asarray(self._ntok)\n"
        "            q_lens = jnp.asarray(self._last_tok)\n"
        "            nxt, self._state = self._fn(tables, self._state, q_lens)\n"
        "            nxt = np.asarray(nxt)\n"
        "            for i in range(4):\n"
        "                self._ntok[i] = 9\n"
        "                self._last_tok[i] += 1\n"
    )
    assert codes(src) == []


def test_dn802_negative_sync_point_before_mutation():
    """The normal step path: np.asarray(result) syncs before the host-side
    vectors are mutated — exactly why step() is safe without copies."""
    src = _DN_ENGINE_HEADER + (
        "    def step(self):\n"
        "        lens = jnp.asarray(self._ntok)\n"
        "        nxt, self._state = self._fn(jnp.asarray(self._last_tok), self._state, lens)\n"
        "        nxt = np.asarray(nxt)\n"
        "        self._ntok[0] += 1\n"
        "        self._last_tok[0] = int(nxt[0])\n"
    )
    assert codes(src) == []


def test_dn803_record_between_dispatch_and_commit():
    src = (
        "import jax\n"
        "from paddle_tpu.observability.recompile import GLOBAL_WATCHDOG\n"
        "class SF:\n"
        "    def __init__(self, impl):\n"
        "        self._fn = jax.jit(impl, donate_argnums=(1,))\n"
        "        self._state = init()\n"
        "    def __call__(self, x):\n"
        "        out, new_state = self._fn(x, self._state)\n"
        "        GLOBAL_WATCHDOG.record_compile('sf', signature='x')\n"
        "        self._state = new_state\n"
        "        return out\n"
    )
    assert "DN803" in codes(src)


def test_dn_local_wrapper_name_does_not_leak_across_functions():
    """A bare-name jit wrapper bound INSIDE one function must not make a
    same-named local in another function look like a donating dispatch
    (review repro: `step` in build() vs a plain callable `step` elsewhere)."""
    src = (
        "import jax\n"
        "def build(impl):\n"
        "    step = jax.jit(impl, donate_argnums=(1,))\n"
        "    return step\n"
        "def other(x, state, make_plain):\n"
        "    step = make_plain()\n"
        "    out = step(x, state)\n"
        "    y = state.sum()\n"
        "    return out, y\n"
    )
    assert codes(src) == []


def test_dn_module_level_wrapper_applies_module_wide():
    src = (
        "import jax\n"
        "_step = jax.jit(impl, donate_argnums=(1,))\n"
        "def use(x, state):\n"
        "    out, new_state = _step(x, state)\n"
        "    y = state.sum()\n"
        "    return out, y\n"
    )
    assert "DN801" in codes(src)


def test_dn_rebound_wrapper_name_stops_donating():
    """Rebinding the wrapper name to a plain callable kills its donation
    semantics for the rest of the function."""
    src = (
        "import jax\n"
        "def use(x, state, plain):\n"
        "    step = jax.jit(impl, donate_argnums=(1,))\n"
        "    step = plain\n"
        "    out = step(x, state)\n"
        "    y = state.sum()\n"
        "    return out, y\n"
    )
    assert codes(src) == []


def test_dn803_negative_record_after_commit():
    src = (
        "import jax\n"
        "from paddle_tpu.observability.recompile import GLOBAL_WATCHDOG\n"
        "class SF:\n"
        "    def __init__(self, impl):\n"
        "        self._fn = jax.jit(impl, donate_argnums=(1,))\n"
        "        self._state = init()\n"
        "    def __call__(self, x):\n"
        "        out, new_state = self._fn(x, self._state)\n"
        "        self._state = new_state\n"
        "        GLOBAL_WATCHDOG.record_compile('sf', signature='x')\n"
        "        return out\n"
    )
    assert codes(src) == []


def test_dn_engine_module_is_clean():
    """inference/engine.py (donate-and-rebind + snapshot-copy replay + sync
    before mutation) passes the DN family as written."""
    vs = analyze_paths([str(PKG / "inference" / "engine.py")], select=["DN"])
    assert [v for v in vs if not v.suppressed] == []


# -- TB: tape backward discipline ---------------------------------------------

def test_tb901_grad_over_kernel_function():
    src = """
import jax
from jax.experimental import pallas as pl

def my_op(x):
    return pl.pallas_call(lambda r, o: None, out_shape=x)(x)

g = jax.grad(my_op)(1.0)
"""
    assert codes(src) == ["TB901"]


def test_tb901_vjp_over_one_hop_wrapper_and_lambda():
    src = """
import jax
from jax.experimental import pallas as pl

def my_op(x):
    return pl.pallas_call(lambda r, o: None, out_shape=x)(x)

def wrapper(x):
    return my_op(x) * 2.0

h = jax.vjp(wrapper, 1.0)
i = jax.value_and_grad(lambda x: my_op(x))(1.0)
"""
    assert codes(src) == ["TB901", "TB901"]


def test_tb901_from_jax_import_alias():
    src = """
from jax import grad
from jax.experimental import pallas as pl

def my_op(x):
    return pl.pallas_call(lambda r, o: None, out_shape=x)(x)

g = grad(my_op)(1.0)
"""
    assert codes(src) == ["TB901"]


def test_tb901_negative_custom_vjp_forms():
    """Decorator, assignment, and factory-shell wiring all define their own
    AD rule — none may fire."""
    src = """
import jax
from jax.experimental import pallas as pl

@jax.custom_vjp
def decorated(x):
    return pl.pallas_call(lambda r, o: None, out_shape=x)(x)

def assigned_raw(x):
    return pl.pallas_call(lambda r, o: None, out_shape=x)(x)

core = jax.custom_vjp(assigned_raw)

def shell(engine_fwd):
    @jax.custom_vjp
    def inner(x):
        return engine_fwd(x)
    return inner

def factory(x):
    def engine_fwd(x):
        return pl.pallas_call(lambda r, o: None, out_shape=x)(x)
    return shell(engine_fwd)

j = jax.grad(decorated)(1.0)
k = jax.grad(core)(1.0)
m = jax.vjp(factory, 1.0)
"""
    assert codes(src) == []


def test_tb901_negative_generic_dispatch_parameter():
    """The tape's own ``jax.vjp(fn, ...)`` over a caller-supplied function is
    unresolvable by design and stays clean."""
    src = """
import jax

def generic(fn, *arrays):
    out, vjp_fn = jax.vjp(fn, *arrays)
    return out, vjp_fn
"""
    assert codes(src) == []


def test_tb901_kernel_package_self_run_clean():
    """The fused-op modules differentiate through tape GradNodes or
    custom_vjp only — the kernels package passes TB as written."""
    vs = analyze_paths([str(PKG / "kernels")], select=["TB"])
    assert [v for v in vs if not v.suppressed] == []


# -- PG: Pallas kernel geometry ----------------------------------------------

_PG_PRELUDE = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "from jax.experimental import pallas as pl\n"
    "def k(x_ref, o_ref):\n"
    "    o_ref[...] = x_ref[...]\n"
)


def _pg_site(shape_in, shape_out, grid="(4,)", map_in="lambda i: (i, 0)"):
    return (
        _PG_PRELUDE
        + "def f():\n"
        "    x = jnp.zeros((256, 8), jnp.float32)\n"
        "    return pl.pallas_call(\n"
        "        k,\n"
        f"        grid={grid},\n"
        f"        in_specs=[pl.BlockSpec({shape_in}, {map_in})],\n"
        f"        out_specs=pl.BlockSpec({shape_out}, lambda i: (i, 0)),\n"
        "        out_shape=jax.ShapeDtypeStruct((256, 8), jnp.float32),\n"
        "    )(x)\n"
    )


def test_pg901_block_rank_vs_map_arity():
    # 3-dim block shape against a 2-tuple index map: Mosaic would reject it
    # at first lowering; here it fails at lint time
    assert "PG901" in codes(_pg_site("(64, 8, 1)", "(64, 8)"))


def test_pg901_negative_consistent_geometry():
    assert codes(_pg_site("(64, 8)", "(64, 8)")) == []


def test_pg901_block_rank_vs_operand_rank():
    src = (
        _PG_PRELUDE
        + "def f():\n"
        "    x = jnp.zeros((256, 8, 4), jnp.float32)\n"
        "    return pl.pallas_call(\n"
        "        k,\n"
        "        grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((64, 8), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((64, 8), lambda i: (i, 0)),\n"
        "        out_shape=jax.ShapeDtypeStruct((256, 8), jnp.float32),\n"
        "    )(x)\n"
    )
    assert "PG901" in codes(src)


def test_pg902_window_overrun_at_grid_corner():
    # 4 grid steps of a 96-row block over 256 rows: corner i=3 ends at 384
    found = codes(_pg_site("(96, 8)", "(96, 8)"))
    assert "PG902" in found


def test_pg902_negative_exact_tiling():
    # 4 x 64 == 256: the corner window ends exactly at the boundary
    assert codes(_pg_site("(64, 8)", "(64, 8)")) == []


def test_pg902_intentional_clamp_is_reason_suppressed():
    src = (
        _PG_PRELUDE
        + "def f():\n"
        "    x = jnp.zeros((256, 8), jnp.float32)\n"
        "    return pl.pallas_call(\n"
        "        k,\n"
        "        grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((96, 8), lambda i: (i, 0))],"
        "  # analysis: disable=PG902 index map clamps the tail block\n"
        "        out_specs=pl.BlockSpec((96, 8), lambda i: (i, 0)),"
        "  # analysis: disable=PG902 index map clamps the tail block\n"
        "        out_shape=jax.ShapeDtypeStruct((256, 8), jnp.float32),\n"
        "    )(x)\n"
    )
    vs = analyze_source(src)
    assert [v.code for v in vs if not v.suppressed] == []
    assert {v.code for v in vs if v.suppressed} == {"PG902"}
    assert all(v.reason for v in vs if v.suppressed)


def test_pg903_vmem_budget_exceeded():
    src = (
        _PG_PRELUDE
        + "def f():\n"
        "    x = jnp.zeros((8192, 8192), jnp.float32)\n"
        "    return pl.pallas_call(\n"
        "        k,\n"
        "        grid=(2,),\n"
        "        in_specs=[pl.BlockSpec((4096, 8192), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((4096, 8192), lambda i: (i, 0)),\n"
        "        out_shape=jax.ShapeDtypeStruct((8192, 8192), jnp.float32),\n"
        "    )(x)\n"
    )
    assert "PG903" in codes(src)


def test_pg903_negative_fits_budget():
    # 2 x 64 x 8 x 4B = 4 KiB per grid step: far under 16 MiB
    assert codes(_pg_site("(64, 8)", "(64, 8)")) == []


def test_pg903_budget_is_tunable():
    from paddle_tpu.analysis.checkers.pallas_geometry import PallasGeometryChecker

    chk = PallasGeometryChecker()
    chk.vmem_budget = 1024  # 2 x 64 x 8 x 4B = 4096 > 1 KiB
    vs = analyze_source(_pg_site("(64, 8)", "(64, 8)"), checkers=[chk])
    assert "PG903" in {v.code for v in vs}


def _pg903_dtype_site(dtype: str) -> str:
    # one (512, 8192) block in + out: 4 MiB each at 1 byte/elt, 16 MiB each
    # at 4 bytes/elt — the SAME geometry crosses the 16 MiB budget purely on
    # the element width, so the audit must price narrow dtypes truthfully
    return (
        _PG_PRELUDE
        + "def f():\n"
        f"    x = jnp.zeros((8192, 8192), {dtype})\n"
        "    return pl.pallas_call(\n"
        "        k,\n"
        "        grid=(16,),\n"
        "        in_specs=[pl.BlockSpec((512, 8192), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((512, 8192), lambda i: (i, 0)),\n"
        f"        out_shape=jax.ShapeDtypeStruct((8192, 8192), {dtype}),\n"
        "    )(x)\n"
    )


def test_pg903_int8_true_width_fits_budget():
    """The quantized-kernel case (kernels/quant.py): an int8 window the
    audit would flag at an assumed 4-byte width fits comfortably at its TRUE
    1-byte width — narrow dtypes must not produce false PG903 positives."""
    assert codes(_pg903_dtype_site("jnp.int8")) == []


def test_pg903_fp8_true_width_fits_budget():
    assert codes(_pg903_dtype_site("jnp.float8_e4m3fn")) == []


def test_pg903_fp32_same_geometry_exceeds_budget():
    """Negative control for the pair above: the identical block geometry at
    4 bytes/elt crosses the 16 MiB budget — the dtype is the only delta."""
    assert "PG903" in codes(_pg903_dtype_site("jnp.float32"))


def test_pg903_int8_width_not_assumed():
    """int8 is a KNOWN width (DTYPE_BYTES), not the assumed-1-byte fallback:
    the VMEM config must not carry the ``assumed_width`` caveat."""
    from paddle_tpu.analysis.kernel_geometry import DTYPE_BYTES, evaluate_module
    import ast

    assert DTYPE_BYTES["int8"] == 1
    assert DTYPE_BYTES["float8_e4m3fn"] == 1
    src = _pg903_dtype_site("jnp.int8")
    mod = evaluate_module("x.py", ast.parse(src))
    sites = mod.sites
    assert sites, "fixture must contain a pallas_call site"
    for site in sites:
        for vc in site.vmem_configs:
            assert not vc.assumed_width


def test_pg_sweep_quant_kernel_clean():
    """The weight-only int8 kernel ships PG-clean: a full checker sweep over
    kernels/quant.py (geometry, prefetch, dispatch discipline) reports zero
    unsuppressed violations."""
    vs = analyze_paths([str(PKG / "kernels" / "quant.py")])
    bad = [v for v in vs if not v.suppressed]
    assert bad == [], [f"{v.code}:{v.line}" for v in bad]


_PG_PREFETCH = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "from jax.experimental import pallas as pl\n"
    "from jax.experimental.pallas import tpu as pltpu\n"
    "def k(ids_ref, x_ref, o_ref):\n"
    "    o_ref[...] = x_ref[...]\n"
)


def _pg_prefetch_site(map_in):
    return (
        _PG_PREFETCH
        + "def f(x, ids):\n"
        "    return pl.pallas_call(\n"
        "        k,\n"
        "        grid_spec=pltpu.PrefetchScalarGridSpec(\n"
        "            num_scalar_prefetch=1,\n"
        "            grid=(4,),\n"
        f"            in_specs=[pl.BlockSpec((8, 8), {map_in})],\n"
        "            out_specs=pl.BlockSpec((8, 8), lambda i, ids: (i, 0)),\n"
        "        ),\n"
        "        out_shape=jax.ShapeDtypeStruct((32, 8), jnp.float32),\n"
        "    )(ids, x)\n"
    )


def test_pg904_prefetch_ref_indexed_by_non_grid_value():
    found = codes(_pg_prefetch_site("lambda i, ids: (ids[j], 0)"))
    assert "PG904" in found


def test_pg904_negative_grid_indexed_prefetch():
    assert codes(_pg_prefetch_site("lambda i, ids: (ids[i], 0)")) == []


def test_pg904_prefetch_arity_mismatch():
    # index maps take grid rank + num_scalar_prefetch args; one short fires
    found = codes(_pg_prefetch_site("lambda i: (i, 0)"))
    assert "PG904" in found


def test_pg905_gated_dispatch_without_fallback_counter():
    src = (
        "from paddle_tpu.kernels.select import pallas_enabled\n"
        "def dispatch(x):\n"
        "    if pallas_enabled('use_pallas_paged_attention'):\n"
        "        return fast_kernel(x)\n"
        "    return slow_path(x)\n"
    )
    assert "PG905" in codes(src)


def test_pg905_negative_warn_fallback_registered():
    src = (
        "from paddle_tpu.kernels.select import pallas_enabled, warn_fallback\n"
        "def dispatch(x):\n"
        "    if pallas_enabled('use_pallas_paged_attention'):\n"
        "        try:\n"
        "            return fast_kernel(x)\n"
        "        except Exception as exc:"
        "  # analysis: disable=EH403 fixture: XLA fallback below\n"
        "            warn_fallback('fast_kernel', exc)\n"
        "    return slow_path(x)\n"
    )
    assert codes(src) == []


def test_pg905_public_kernel_entry_needs_coverage():
    # a public pallas_call-lowering entry in kernels/ nobody fallback-wraps
    src = _pg_site("(64, 8)", "(64, 8)").replace("def f():", "def public_kernel():")
    found = codes(src, path="paddle_tpu/kernels/pg_snippet.py")
    assert "PG905" in found
    # the same module-private entry is some wrapper's implementation detail
    src_private = _pg_site("(64, 8)", "(64, 8)").replace("def f():", "def _impl():")
    assert codes(src_private, path="paddle_tpu/kernels/pg_snippet.py") == []


def test_pg905_self_wrapping_entry_is_covered():
    src = (
        _PG_PRELUDE
        + "from paddle_tpu.kernels.select import warn_fallback\n"
        "def public_kernel():\n"
        "    x = jnp.zeros((256, 8), jnp.float32)\n"
        "    try:\n"
        "        return pl.pallas_call(\n"
        "            k,\n"
        "            grid=(4,),\n"
        "            in_specs=[pl.BlockSpec((64, 8), lambda i: (i, 0))],\n"
        "            out_specs=pl.BlockSpec((64, 8), lambda i: (i, 0)),\n"
        "            out_shape=jax.ShapeDtypeStruct((256, 8), jnp.float32),\n"
        "        )(x)\n"
        "    except Exception as exc:"
        "  # analysis: disable=EH403 fixture: XLA fallback below\n"
        "        warn_fallback('public_kernel', exc)\n"
        "    return x\n"
    )
    assert codes(src, path="paddle_tpu/kernels/pg_snippet.py") == []


# -- kernel_geometry resolution edge cases -----------------------------------

def _geom(src, path="geom_snippet.py"):
    import ast as _ast

    from paddle_tpu.analysis.kernel_geometry import evaluate_module

    return evaluate_module(path, _ast.parse(src))


def test_geometry_autotune_candidates_and_cdiv_grid():
    """Block sizes flowing from autotune candidate tuples stay correlated
    per configuration (a ``pl.cdiv`` grid derived from the same candidate),
    so a bad candidate is named concretely instead of smearing every
    config to unproven."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "from paddle_tpu.kernels.autotune import autotune\n"
        "ROWS = 256\n"
        "def k(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...]\n"
        "def build(blk):\n"
        "    x = jnp.zeros((ROWS, 8), jnp.float32)\n"
        "    return pl.pallas_call(\n"
        "        k,\n"
        "        grid=(pl.cdiv(ROWS, blk),),\n"
        "        in_specs=[pl.BlockSpec((blk, 8), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((blk, 8), lambda i: (i, 0)),\n"
        "        out_shape=jax.ShapeDtypeStruct((ROWS, 8), jnp.float32),\n"
        "    )(x)\n"
        "impl = autotune('thing', 'key', (64, 96), build, default=64)\n"
    )
    site = _geom(src).sites[0]
    # cdiv folded per candidate: 256/64 -> 4 steps, 256/96 -> 3 steps
    assert site.grid[0].values == frozenset({3, 4})
    # the 96 candidate's last block ends at 288 > 256 — named, not smeared
    overruns = [p for p in site.axis_proofs if p.status == "overrun"]
    assert overruns and all("blk=96" in p.detail for p in overruns)
    # VMEM footprint tracked per candidate config (in + out, f32)
    per_cfg = {
        cfg.binding["blk"]: cfg.bytes_per_step.concrete()
        for cfg in site.vmem_configs
    }
    assert per_cfg == {64: 2 * 64 * 8 * 4, 96: 2 * 96 * 8 * 4}


def test_geometry_named_index_map_function():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "def _row_map(i):\n"
        "    return (i, 0)\n"
        "def k(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...]\n"
        "def f():\n"
        "    x = jnp.zeros((256, 8), jnp.float32)\n"
        "    return pl.pallas_call(\n"
        "        k,\n"
        "        grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((64, 8), _row_map)],\n"
        "        out_specs=pl.BlockSpec((64, 8), _row_map),\n"
        "        out_shape=jax.ShapeDtypeStruct((256, 8), jnp.float32),\n"
        "    )(x)\n"
    )
    site = _geom(src).sites[0]
    spec = site.in_specs[0]
    assert spec.map_params == ["i"] and spec.ret_arity == 2
    assert {p.status for p in site.axis_proofs} == {"proven"}


def test_geometry_symbolic_grid_axis_is_unproven_not_passed():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "def k(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...]\n"
        "def f(x, n):\n"
        "    return pl.pallas_call(\n"
        "        k,\n"
        "        grid=(n // 64,),\n"
        "        in_specs=[pl.BlockSpec((64, 8), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((64, 8), lambda i: (i, 0)),\n"
        "        out_shape=jax.ShapeDtypeStruct((256, 8), jnp.float32),\n"
        "    )(x)\n"
    )
    site = _geom(src).sites[0]
    assert not site.grid[0].known  # symbolic residue, honestly reported
    dim0 = [p for p in site.axis_proofs if p.dim == 0]
    assert dim0 and {p.status for p in dim0} == {"unproven"}
    # unproven is NOT a finding — but it is never silently "proven" either
    assert "PG902" not in codes(src)


def test_geometry_is_memoized_in_package_index():
    """The PG layer rides the PR 9 memoization contract: one evaluation per
    module per PackageIndex, however many checkers ask."""
    import ast as _ast

    from paddle_tpu.analysis import dataflow as _df

    idx = _df.PackageIndex()
    tree = _ast.parse(_pg_site("(64, 8)", "(64, 8)"))
    idx.add_module("geom_memo.py", tree)
    g1 = idx.kernel_geometry("geom_memo.py")
    g2 = idx.kernel_geometry("geom_memo.py")
    assert g1 is g2 and len(g1.sites) == 1


# -- CM: distributed protocol -------------------------------------------------

def test_cm1001_rank_divergent_collective():
    assert "CM1001" in codes(
        "import paddle_tpu.distributed as dist\n"
        "import jax\n"
        "def sync(x):\n"
        "    rank = jax.process_index()\n"
        "    if rank == 0:\n"
        "        dist.broadcast(x, src=0)\n",
        select=["CM"],
    )


def test_cm1001_negative_rejoin_after_branch():
    """The branch touches rank-local state but EVERY rank reaches the
    collective afterwards — the canonical checkpoint-then-sync shape."""
    assert codes(
        "import paddle_tpu.distributed as dist\n"
        "import jax\n"
        "def sync(x):\n"
        "    rank = jax.process_index()\n"
        "    if rank == 0:\n"
        "        x = x + 1\n"
        "    dist.broadcast(x, src=0)\n",
        select=["CM"],
    ) == []


def test_cm1001_negative_balanced_arms():
    """Both arms issue the same collective: every rank participates
    whichever way the rank test goes."""
    assert codes(
        "import paddle_tpu.distributed as dist\n"
        "import jax\n"
        "def sync(x, y):\n"
        "    rank = jax.process_index()\n"
        "    if rank == 0:\n"
        "        dist.broadcast(x, src=0)\n"
        "    else:\n"
        "        dist.broadcast(y, src=0)\n",
        select=["CM"],
    ) == []


def test_cm1002_collective_under_thread_shared_lock():
    assert "CM1002" in codes(
        "import threading\n"
        "import paddle_tpu.distributed as dist\n"
        "class Manager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._t = threading.Thread(target=self._probe_loop)\n"
        "    def _probe_loop(self):\n"
        "        with self._lock:\n"
        "            self._n = 1\n"
        "    def sync(self, x):\n"
        "        with self._lock:\n"
        "            dist.all_reduce(x)\n",
        select=["CM"],
    )


def test_cm1002_negative_lock_not_thread_shared():
    assert codes(
        "import threading\n"
        "import paddle_tpu.distributed as dist\n"
        "class Manager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def sync(self, x):\n"
        "        with self._lock:\n"
        "            dist.all_reduce(x)\n",
        select=["CM"],
    ) == []


def test_cm1003_counter_key_without_delete():
    """Minimized ``all_gather_object`` replica: a per-call counter namespaces
    the store key, so every call strands a fresh key forever unless a
    dominating delete reclaims it (the unbounded-store failure)."""
    assert "CM1003" in codes(
        "_calls = [0]\n"
        "def gather(client, rank, payload):\n"
        "    n = _calls[0]\n"
        "    _calls[0] += 1\n"
        "    prefix = f\"gather/{n}\"\n"
        "    client.key_value_set(f\"{prefix}/{rank}\", payload)\n",
        select=["CM"],
    )


def test_cm1003_negative_finally_deleted_counter_key():
    assert codes(
        "_calls = [0]\n"
        "def gather(client, rank, payload):\n"
        "    n = _calls[0]\n"
        "    _calls[0] += 1\n"
        "    prefix = f\"gather/{n}\"\n"
        "    try:\n"
        "        client.key_value_set(f\"{prefix}/{rank}\", payload)\n"
        "    finally:\n"
        "        client.key_value_delete(f\"{prefix}/{rank}\")\n",
        select=["CM"],
    ) == []


def test_cm1004_collective_in_except_arm():
    assert "CM1004" in codes(
        "import paddle_tpu.distributed as dist\n"
        "def step(x):\n"
        "    try:\n"
        "        y = x.compute()\n"
        "    except ValueError:\n"
        "        dist.barrier()\n",
        select=["CM"],
    )


def test_cm1004_negative_try_body_cannot_raise():
    assert codes(
        "import paddle_tpu.distributed as dist\n"
        "def step(x):\n"
        "    try:\n"
        "        y = 1\n"
        "    except ValueError:\n"
        "        dist.barrier()\n",
        select=["CM"],
    ) == []


def test_cm1005_partition_spec_axis_outside_mesh():
    assert "CM1005" in codes(
        "import numpy as np\n"
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "mesh = Mesh(np.array([]), (\"dp\", \"tp\"))\n"
        "def spec():\n"
        "    return P(\"model\")\n",
        select=["CM"],
    )


def test_cm1005_negative_axis_in_mesh_universe():
    assert codes(
        "import numpy as np\n"
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "mesh = Mesh(np.array([]), (\"dp\", \"tp\"))\n"
        "def spec():\n"
        "    return P(\"tp\", None)\n",
        select=["CM"],
    ) == []


def test_cm1005_donating_jit_without_out_shardings():
    assert "CM1005" in codes(
        "import jax\n"
        "def build(fn, shardings):\n"
        "    return jax.jit(fn, donate_argnums=(1,), in_shardings=shardings)\n",
        select=["CM"],
    )


def test_cm1005_negative_out_shardings_pinned():
    assert codes(
        "import jax\n"
        "def build(fn, shardings):\n"
        "    return jax.jit(fn, donate_argnums=(1,), in_shardings=shardings,\n"
        "                   out_shardings=shardings)\n",
        select=["CM"],
    ) == []


def test_cm_protocol_calls_memoized_in_package_index():
    """CM rides the PR 9 memoization contract like PG: the module graph (and
    its recorded protocol calls) is built once per PackageIndex, however
    many checkers ask for it."""
    import ast as _ast

    from paddle_tpu.analysis import dataflow as _df

    idx = _df.PackageIndex()
    tree = _ast.parse(
        "import paddle_tpu.distributed as dist\n"
        "def f(x):\n"
        "    dist.all_reduce(x)\n"
    )
    idx.add_module("cm_memo.py", tree)
    g1 = idx.module("cm_memo.py")
    g2 = idx.module("cm_memo.py")
    assert g1 is g2
    assert [p.op for p in g1.protocol_calls if p.kind == "collective"] == ["all_reduce"]
    # the thread-acquirer closure is memoized too (CM1002's partner set)
    a1 = idx.thread_lock_acquirers()
    a2 = idx.thread_lock_acquirers()
    assert a1 is a2


def test_cm_baseline_accepts_known_finding(tmp_path):
    """A baselined CM finding stops gating; a new one past the baseline
    gates again — same contract as every other family."""
    bad = tmp_path / "proto.py"
    bad.write_text(
        "import paddle_tpu.distributed as dist\n"
        "def step(x):\n"
        "    try:\n"
        "        y = x.compute()\n"
        "    except ValueError:\n"
        "        dist.barrier()\n"
    )
    r = _run_cli(["--select", "CM", str(bad)])
    assert r.returncode == 1 and "CM1004" in r.stdout
    base = tmp_path / "base.json"
    r = _run_cli(["--select", "CM", "--write-baseline", str(base), str(bad)])
    assert r.returncode == 0
    r = _run_cli(["--select", "CM", "--baseline", str(base), str(bad)])
    assert r.returncode == 0
    bad.write_text(
        bad.read_text()
        + "def step2(x):\n"
        "    try:\n"
        "        y = x.compute()\n"
        "    except ValueError:\n"
        "        dist.barrier()\n"
    )
    r = _run_cli(["--select", "CM", "--baseline", str(base), str(bad)])
    assert r.returncode == 1


def test_timings_flag_names_every_checker_and_phase(tmp_path):
    """--timings must attribute the 30s budget: one ``checker:`` line per
    registered checker (zero-cost ones included) and the index phases."""
    f = tmp_path / "ok.py"
    f.write_text("import paddle_tpu.distributed as dist\ndef f(x):\n    dist.all_reduce(x)\n")
    r = _run_cli(["--timings", str(f)])
    assert r.returncode == 0
    assert "timings:" in r.stderr
    for checker in all_checkers():
        assert f"checker {checker.name}" in " ".join(r.stderr.split()), (
            f"--timings output missing checker {checker.name!r}:\n{r.stderr}"
        )
    assert "phase" in r.stderr and "parse" in r.stderr


# -- SARIF + baseline ---------------------------------------------------------

def test_sarif_output_shape_and_rule_ids():
    from paddle_tpu.analysis import all_codes as _codes
    from paddle_tpu.analysis.reporters import render_sarif

    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "except:\n"
        "    pass\n"
        "try:\n"
        "    g()\n"
        "except:  # analysis: disable=EH401 fixture accepts this one\n"
        "    pass\n"
    )
    doc = json.loads(render_sarif(vs, _codes()))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "EH401" in rules and "CC701" in rules and "DN802" in rules
    # the PG family rides the same schema: rule ids only, no shape change
    assert {"PG901", "PG902", "PG903", "PG904", "PG905"} <= rules
    # the CM family too
    assert {"CM1001", "CM1002", "CM1003", "CM1004", "CM1005"} <= rules
    results = run["results"]
    live = [r for r in results if "suppressions" not in r]
    sup = [r for r in results if "suppressions" in r]
    assert len(live) >= 1 and len(sup) == 1
    assert sup[0]["suppressions"][0]["justification"] == "fixture accepts this one"
    loc = live[0]["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] >= 1 and loc["region"]["startColumn"] >= 1


def test_baseline_accepts_known_and_catches_new(tmp_path):
    from paddle_tpu.analysis.reporters import (
        load_baseline,
        new_violations,
        write_baseline,
    )

    one = analyze_source("try:\n    f()\nexcept:\n    pass\n")
    base = tmp_path / "base.json"
    write_baseline(str(base), one)
    known = load_baseline(str(base))
    # same findings: nothing new
    assert new_violations(one, known) == []
    # a second bare except in the same file is NEW (count-based fingerprints)
    two = analyze_source(
        "try:\n    f()\nexcept:\n    pass\n"
        "try:\n    g()\nexcept:\n    pass\n"
    )
    fresh = new_violations(two, known)
    assert len(fresh) == 1 and fresh[0].code in ("EH401",)


def test_baseline_rejects_wrong_shape(tmp_path):
    from paddle_tpu.analysis.reporters import load_baseline

    bad = tmp_path / "bad.json"
    bad.write_text('{"findings": {"a": 1}}')
    with pytest.raises(ValueError):
        load_baseline(str(bad))


def test_cli_sarif_and_baseline_gate(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    f()\nexcept:\n    pass\n")
    r = _run_cli(["--format", "sarif", str(bad)])
    assert r.returncode == 1
    assert json.loads(r.stdout)["version"] == "2.1.0"
    base = tmp_path / "base.json"
    r = _run_cli(["--write-baseline", str(base), str(bad)])
    assert r.returncode == 0 and base.exists()
    # baselined: the known finding no longer gates
    r = _run_cli(["--baseline", str(base), str(bad)])
    assert r.returncode == 0
    # a NEW finding past the baseline count gates again
    bad.write_text(
        "try:\n    f()\nexcept:\n    pass\n"
        "try:\n    g()\nexcept:\n    pass\n"
    )
    r = _run_cli(["--baseline", str(base), str(bad)])
    assert r.returncode == 1
    # a corrupt baseline must not turn the gate vacuous
    base.write_text("not json")
    r = _run_cli(["--baseline", str(base), str(bad)])
    assert r.returncode == 2


# -- CI perf gate: one memoized dataflow pass, bounded wall time --------------

def test_analyzer_wall_time_and_single_dataflow_pass():
    """The tier-1 gate runs every checker family over the whole package; the
    dataflow graphs must be built once per module (memoized in the
    PackageIndex) and the whole run must stay under 30 s — including the
    interprocedural CM family, which must ride the shared index rather
    than build its own."""
    import time as _time

    from paddle_tpu.analysis import dataflow as _df

    # the budget is only meaningful if the expensive families are actually in
    # the run — guard against the gate going vacuous via deregistration
    names = {c.name for c in all_checkers()}
    assert {"distributed_protocol", "pallas_geometry", "concurrency"} <= names

    builds = {"n": 0}
    orig = _df.ModuleGraph._build

    def counting_build(self):
        builds["n"] += 1
        return orig(self)

    _df.ModuleGraph._build = counting_build
    try:
        t0 = _time.perf_counter()
        vs = analyze_paths([str(PKG)])
        dt = _time.perf_counter() - t0
    finally:
        _df.ModuleGraph._build = orig
    n_modules = len(list(PKG.rglob("*.py")))
    assert builds["n"] <= n_modules, (
        f"dataflow graphs rebuilt: {builds['n']} builds for {n_modules} modules"
    )
    assert dt < 30.0, f"whole-package analysis took {dt:.1f}s (budget 30s)"
    assert isinstance(vs, list)


# -- the tier-1 gate: the package must analyze clean -------------------------

def test_whole_package_clean():
    vs = analyze_paths([str(PKG)])
    live = [v for v in vs if not v.suppressed]
    assert live == [], "unsuppressed violations:\n" + "\n".join(v.format() for v in live)
    # acceptance: every suppression carries a reason string
    for v in vs:
        if v.suppressed:
            assert v.reason, v.format()


def test_cli_whole_package_gate():
    r = _run_cli(["--format", "json", "paddle_tpu/"])
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert data["summary"]["unsuppressed"] == 0


# -- flags satellite: env-coercion failures name the flag --------------------

def test_env_coercion_error_names_flag_and_env_var(monkeypatch):
    from paddle_tpu.flags import FlagRegistry

    reg = FlagRegistry()
    reg.define("scan_depth", int, 4)
    monkeypatch.setenv("FLAGS_scan_depth", "not-an-int")
    with pytest.raises(ValueError) as ei:
        reg.get("scan_depth")
    msg = str(ei.value)
    assert "FLAGS_scan_depth" in msg and "scan_depth" in msg and "int" in msg
    # the error re-fires on every read — a first get() swallowed by someone's
    # broad except must not leave the flag silently serving its default
    with pytest.raises(ValueError):
        reg.get("scan_depth")


def test_set_coercion_error_names_flag():
    from paddle_tpu.flags import FlagRegistry

    reg = FlagRegistry()
    reg.define("scan_depth", int, 4)
    with pytest.raises(ValueError, match="scan_depth"):
        reg.set("scan_depth", "nope")
