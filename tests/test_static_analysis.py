"""Static-analysis framework tests: per-checker fixtures (positive AND
negative per code), suppression semantics, reporters, CLI exit codes, and the
tier-1 gate — the whole-package self-run must come back with zero
unsuppressed violations."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from paddle_tpu.analysis import (
    all_checkers,
    all_codes,
    analyze_paths,
    analyze_source,
    render_json,
    render_text,
    summarize,
)

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "paddle_tpu"


def codes(src, **kw):
    return sorted(v.code for v in analyze_source(src, **kw) if not v.suppressed)


# -- TS: trace-safety --------------------------------------------------------

def test_ts101_print_in_jitted_function():
    assert "TS101" in codes(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    print(x)\n"
        "    return x\n"
    )


def test_ts101_negative_print_outside_trace():
    assert codes("def f(x):\n    print(x)\n    return x\n") == []


def test_ts101_function_passed_to_jax_jit():
    assert "TS101" in codes(
        "import jax\n"
        "def g(x):\n"
        "    print(x)\n"
        "    return x\n"
        "h = jax.jit(g, donate_argnums=(0,))\n"
    )


def test_ts101_method_passed_to_jax_jit_via_self():
    assert "TS101" in codes(
        "import jax\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self._fn = jax.jit(self._impl)\n"
        "    def _impl(self, x):\n"
        "        print(x)\n"
        "        return x\n"
    )


def test_ts102_time_call():
    src = (
        "import time\n"
        "from paddle_tpu.jit import to_static\n"
        "@to_static\n"
        "def step(x):\n"
        "    t0 = time.perf_counter()\n"
        "    return x, t0\n"
    )
    assert "TS102" in codes(src)
    assert codes(src.replace("time.perf_counter()", "x + 1")) == []


def test_ts103_environ():
    assert "TS103" in codes(
        "import jax, os\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if os.environ.get('DEBUG'):\n"
        "        return x\n"
        "    return x + 1\n"
    )
    # reading the environment OUTSIDE the traced body is fine
    assert codes(
        "import jax, os\n"
        "dbg = os.environ.get('DEBUG')\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x\n"
    ) == []


def test_ts104_metrics_in_traced_body():
    assert "TS104" in codes(
        "import jax\n"
        "from paddle_tpu.observability import GLOBAL_METRICS\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    GLOBAL_METRICS.counter('c').inc()\n"
        "    return x\n"
    )
    assert "TS104" in codes(
        "import jax\n"
        "from paddle_tpu.observability import get_registry\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    get_registry().counter('c').inc()\n"
        "    return x\n"
    )


def test_ts104_negative_metrics_at_call_site():
    assert codes(
        "import jax\n"
        "from paddle_tpu.observability import GLOBAL_METRICS\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x\n"
        "def serve(x):\n"
        "    y = f(x)\n"
        "    GLOBAL_METRICS.counter('c').inc()\n"
        "    return y\n"
    ) == []


def test_ts105_param_materialization():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)\n"
    )
    assert "TS105" in codes(src)
    assert "TS105" in codes(src.replace("float(x)", "x.item()"))
    # float() of a non-parameter local is not flagged
    assert codes(src.replace("float(x)", "float(1.5) + x")) == []


def test_ts106_global_mutation():
    assert "TS106" in codes(
        "import jax\n"
        "_n = 0\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    global _n\n"
        "    _n += 1\n"
        "    return x\n"
    )
    assert codes(
        "_n = 0\n"
        "def f(x):\n"
        "    global _n\n"
        "    _n += 1\n"
        "    return x\n"
    ) == []


# -- PK: Pallas purity -------------------------------------------------------

def test_pk201_flag_read_in_kernel():
    assert "PK201" in codes(
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "def _add_kernel(x_ref, o_ref):\n"
        "    if GLOBAL_FLAGS.get('benchmark'):\n"
        "        o_ref[...] = x_ref[...]\n"
    )


def test_pk202_metrics_in_kernel():
    assert "PK202" in codes(
        "from paddle_tpu.observability import GLOBAL_METRICS\n"
        "def _add_kernel(x_ref, o_ref):\n"
        "    GLOBAL_METRICS.counter('c').inc()\n"
        "    o_ref[...] = x_ref[...]\n"
    )


def test_pk203_mutable_global_closure():
    src = (
        "_seen = {}\n"
        "NEG_INF = -1e30\n"
        "def _add_kernel(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...] + len(_seen) + NEG_INF\n"
    )
    got = codes(src)
    assert "PK203" in got
    # ALL_CAPS literal constants are allowed
    assert got.count("PK203") == 1


def test_pk203_negative_partial_bakes_state():
    assert codes(
        "import functools\n"
        "def _add_kernel(x_ref, o_ref, *, n):\n"
        "    o_ref[...] = x_ref[...] + n\n"
        "kernel = functools.partial(_add_kernel, n=3)\n"
    ) == []


def test_pk204_print_in_kernel_resolved_through_partial():
    # resolution path: pallas_call(k) where k = functools.partial(body, ...)
    assert "PK204" in codes(
        "import functools\n"
        "from jax.experimental import pallas as pl\n"
        "def body(x_ref, o_ref, *, n):\n"
        "    print('tracing')\n"
        "    o_ref[...] = x_ref[...]\n"
        "def run(x):\n"
        "    k = functools.partial(body, n=1)\n"
        "    return pl.pallas_call(k, out_shape=x)(x)\n"
    )


def test_pk204_index_map_lambda():
    assert "PK204" in codes(
        "import time\n"
        "from jax.experimental import pallas as pl\n"
        "spec = pl.BlockSpec((8, 8), lambda i, j: (i, int(time.time())))\n"
    )
    assert codes(
        "from jax.experimental import pallas as pl\n"
        "spec = pl.BlockSpec((8, 8), lambda i, j: (i, j))\n"
    ) == []


# -- FD: flag discipline -----------------------------------------------------

def test_fd301_undefined_flag():
    assert codes(
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "v = GLOBAL_FLAGS.get('definitely_not_a_flag')\n"
    ) == ["FD301"]
    # canonical flags.py names resolve
    assert codes(
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "v = GLOBAL_FLAGS.get('benchmark')\n"
    ) == []


def test_fd301_env_and_setters():
    assert codes("import os\nv = os.environ.get('FLAGS_nope')\n") == ["FD301"]
    assert codes("import os\nv = os.environ['FLAGS_benchmark']\n") == []
    assert codes("from paddle_tpu.flags import set_flags\nset_flags({'FLAGS_typo_flag': 1})\n") == ["FD301"]
    assert codes("from paddle_tpu.flags import get_flags\nget_flags(['benchmark', 'gone_flag'])\n") == ["FD301"]
    # the public attribute-qualified spellings resolve too
    assert codes("import paddle_tpu as paddle\npaddle.set_flags({'FLAGS_typo_flag': 1})\n") == ["FD301"]
    assert codes("import paddle_tpu as paddle\npaddle.set_flags({'FLAGS_benchmark': True})\n") == []


def test_fd301_define_in_same_run_resolves():
    assert codes(
        "from paddle_tpu.flags import GLOBAL_FLAGS, define_flag\n"
        "define_flag('my_new_flag', bool, False)\n"
        "v = GLOBAL_FLAGS.get('my_new_flag')\n"
    ) == []


def test_fd302_loop_read_in_hot_path():
    src = (
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "def scan(items):\n"
        "    for it in items:\n"
        "        if GLOBAL_FLAGS.get('benchmark'):\n"
        "            it.sync()\n"
    )
    assert codes(src, hot_path=True) == ["FD302"]
    assert codes(src, hot_path=False) == []
    hoisted = (
        "from paddle_tpu.flags import GLOBAL_FLAGS\n"
        "def scan(items):\n"
        "    bench = GLOBAL_FLAGS.get('benchmark')\n"
        "    for it in items:\n"
        "        if bench:\n"
        "            it.sync()\n"
    )
    assert codes(hoisted, hot_path=True) == []


# -- EH: exception hygiene ---------------------------------------------------

def test_eh401_bare_except():
    assert codes("try:\n    f()\nexcept:\n    g()\n") == ["EH401"]
    assert codes("try:\n    f()\nexcept ValueError:\n    g()\n") == []


def test_eh402_silent_swallow():
    assert "EH402" in codes("try:\n    f()\nexcept Exception:\n    pass\n")
    # logging the failure is not silent
    assert codes(
        "import logging\n"
        "try:\n"
        "    f()\n"
        "except Exception:  # tolerable: best-effort hook\n"
        "    logging.getLogger(__name__).warning('f failed')\n"
    ) == []


def test_eh403_lint_tags_are_not_reasons():
    # a bare noqa / type: ignore / pragma tag says nothing about WHY breadth
    # is correct — it must not satisfy EH403
    assert codes("try:\n    f()\nexcept Exception:  # noqa: BLE001\n    y = 0\n") == ["EH403"]
    assert codes("try:\n    f()\nexcept Exception:  # type: ignore[misc]\n    y = 0\n") == ["EH403"]
    # a tag FOLLOWED by prose is fine
    assert codes(
        "try:\n    f()\nexcept Exception:  # noqa: BLE001 - fallback covers it\n    y = 0\n"
    ) == []


def test_eh403_broad_except_needs_reason():
    assert codes("try:\n    f()\nexcept Exception as exc:\n    y = 0\n") == ["EH403"]
    assert codes("try:\n    f()\nexcept Exception as exc:  # fallback below\n    y = 0\n") == []
    # comment-only line opening the body also counts (repo idiom)
    assert codes(
        "try:\n"
        "    f()\n"
        "except Exception as exc:\n"
        "    # fallback: the retry path below re-raises on second failure\n"
        "    y = 0\n"
    ) == []


# -- RB: robustness ----------------------------------------------------------

def test_rb501_os_exit_flagged():
    assert codes("import os\ndef f():\n    os._exit(1)\n") == ["RB501"]


def test_rb501_through_import_alias():
    assert codes("import os as _os\ndef f():\n    _os._exit(7)\n") == ["RB501"]
    assert codes("from os import _exit\ndef f():\n    _exit(7)\n") == ["RB501"]
    assert codes("from os import _exit as bail\ndef f():\n    bail(7)\n") == ["RB501"]


def test_rb501_negative_sys_exit_and_other_exits():
    assert codes("import sys\ndef f():\n    sys.exit(1)\n") == []
    assert codes("import os\ndef f():\n    os.kill(1, 9)\n") == []


def test_rb501_allowed_in_watchdog_and_launch():
    src = "import os\ndef f():\n    os._exit(124)\n"
    assert codes(src, path="paddle_tpu/distributed/watchdog.py") == []
    assert codes(src, path="paddle_tpu/distributed/launch/main.py") == []
    assert codes(src, path="paddle_tpu/distributed/launch/sub/mod.py") == []
    # ... but NOT elsewhere under distributed/
    assert codes(src, path="paddle_tpu/distributed/collective.py") == ["RB501"]


def test_rb501_suppressible_with_reason():
    vs = analyze_source(
        "import os\n"
        "def f():\n"
        "    # analysis: disable=RB501 forked child owns no state to flush\n"
        "    os._exit(1)\n"
    )
    assert [v.code for v in vs] == ["RB501"]
    assert vs[0].suppressed and vs[0].reason


# -- RB502: un-timed blocking waits in request-serving paths ------------------

SERVING = "paddle_tpu/serving/worker.py"


def test_rb502_untimed_queue_get_flagged():
    src = "import queue\nq = queue.Queue()\nitem = q.get()\n"
    assert codes(src, path=SERVING) == ["RB502"]
    # from-import constructor form
    src = "from queue import Queue\nq = Queue()\nitem = q.get()\n"
    assert codes(src, path=SERVING) == ["RB502"]


def test_rb502_timed_queue_get_ok():
    assert codes(
        "import queue\nq = queue.Queue()\nitem = q.get(timeout=5)\n", path=SERVING
    ) == []
    # positional form get(block, timeout) and get_nowait are both fine
    assert codes(
        "import queue\nq = queue.Queue()\nitem = q.get(True, 5)\n", path=SERVING
    ) == []
    assert codes(
        "import queue\nq = queue.Queue()\nitem = q.get_nowait()\n", path=SERVING
    ) == []


def test_rb502_dict_get_and_str_join_not_confused_for_waits():
    # constructor tracking: untracked receivers never match
    assert codes("d = {}\nv = d.get('k')\n", path=SERVING) == []
    assert codes("s = ','.join(['a'])\n", path=SERVING) == []
    assert codes("import os\np = os.path.join('a', 'b')\n", path=SERVING) == []


def test_rb502_annotated_assignment_receivers_are_tracked():
    # `self._q: Queue = Queue()` is an AnnAssign — the exact construction
    # style the serving frontend uses; it must not be invisible
    src = (
        "from queue import Queue\n"
        "class H:\n"
        "    def __init__(self):\n"
        "        self._q: Queue = Queue()\n"
        "    def take(self):\n"
        "        return self._q.get()\n"
    )
    assert codes(src, path=SERVING) == ["RB502"]
    assert codes(src.replace(".get()", ".get(timeout=1)"), path=SERVING) == []


def test_rb502_event_wait_and_thread_join():
    src = (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._done = threading.Event()\n"
        "        self._t = threading.Thread(target=print)\n"
        "    def finish(self):\n"
        "        self._done.wait()\n"
        "        self._t.join()\n"
    )
    assert codes(src, path="paddle_tpu/inference/x.py") == ["RB502", "RB502"]
    timed = src.replace(".wait()", ".wait(timeout=2)").replace(".join()", ".join(5)")
    assert codes(timed, path="paddle_tpu/inference/x.py") == []


def test_rb502_socket_recv_needs_settimeout():
    src = "import socket\ns = socket.socket()\ndata = s.recv(1024)\n"
    assert codes(src, path="paddle_tpu/distributed/x.py") == ["RB502"]
    timed = "import socket\ns = socket.socket()\ns.settimeout(3)\ndata = s.recv(1024)\n"
    assert codes(timed, path="paddle_tpu/distributed/x.py") == []


def test_rb502_only_in_request_serving_dirs():
    src = "import queue\nq = queue.Queue()\nitem = q.get()\n"
    assert codes(src, path="paddle_tpu/models/x.py") == []
    assert codes(src, path="paddle_tpu/kernels/x.py") == []
    for gated in ("serving", "distributed", "inference"):
        assert codes(src, path=f"paddle_tpu/{gated}/x.py") == ["RB502"]


def test_rb502_suppressible_with_reason():
    vs = analyze_source(
        "import queue\n"
        "q = queue.Queue()\n"
        "# analysis: disable=RB502 shutdown path; producer provably alive\n"
        "item = q.get()\n",
        path=SERVING,
    )
    assert [v.code for v in vs] == ["RB502"]
    assert vs[0].suppressed and vs[0].reason


# -- OB: observability discipline --------------------------------------------

def test_ob601_span_opened_without_with_leaks():
    # armed Span assigned to a variable: __exit__ never runs, silent leak
    assert codes('sp = tracer.span("phase")\n') == ["OB601"]
    assert codes('x = self._tracer.span("phase")\n') == ["OB601"]
    assert codes('GLOBAL_TRACER.span("phase")\n') == ["OB601"]
    assert codes('s = get_tracer().span("phase")\n') == ["OB601"]


def test_ob601_with_statement_and_retroactive_forms_ok():
    assert codes('with tracer.span("phase") as sp:\n    sp.set_attr("k", 1)\n') == []
    # add_span/add_event take explicit timestamps: no with required
    assert codes('tracer.add_span("phase", start_s=0.0, end_s=1.0)\n') == []
    assert codes('tracer.add_event("mark")\n') == []


def test_ob601_unrelated_span_and_record_receivers_not_confused():
    # .span on a non-tracer receiver, .record on a non-recorder receiver
    assert codes('cell.span(3)\n') == []
    assert codes('db.record("row")\n') == []
    assert codes('wingspan = bird.span("wide")\n') == []


def test_ob601_emission_inside_jitted_body():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    with tracer.span('inner'):\n"
        "        return x\n"
    )
    assert codes(src) == ["OB601"]
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    record_event('admit', req_id=1)\n"
        "    return x\n"
    )
    assert codes(src) == ["OB601"]
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    GLOBAL_FLIGHT_RECORDER.record('admit', req_id=1)\n"
        "    return x\n"
    )
    assert codes(src) == ["OB601"]


def test_ob601_emission_inside_pallas_kernel():
    src = (
        "import jax.experimental.pallas as pl\n"
        "def my_kernel(x_ref, o_ref):\n"
        "    record_event('tile')\n"
        "    o_ref[...] = x_ref[...]\n"
        "def run(x):\n"
        "    return pl.pallas_call(my_kernel, out_shape=x)(x)\n"
    )
    assert codes(src) == ["OB601"]


def test_ob601_host_call_site_pattern_is_clean():
    # the sanctioned shape: dispatch inside jit, emission at the call site
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return x * 2\n"
        "def drive(x):\n"
        "    y = step(x)\n"
        "    record_event('stepped')\n"
        "    with tracer.span('post') as sp:\n"
        "        sp.set_attr('ok', True)\n"
        "    return y\n"
    )
    assert codes(src) == []


def test_ob601_suppressible_with_reason():
    vs = analyze_source(
        "# analysis: disable=OB601 span handed to a helper that closes it\n"
        "sp = tracer.span('phase')\n"
    )
    assert [v.code for v in vs] == ["OB601"]
    assert vs[0].suppressed and vs[0].reason


# -- suppressions ------------------------------------------------------------

def test_suppression_with_reason():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "except:  # analysis: disable=EH401 exercised by fixture\n"
        "    g()\n"
    )
    assert len(vs) == 1 and vs[0].suppressed and vs[0].reason == "exercised by fixture"


def test_suppression_on_preceding_comment_line():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "# analysis: disable=EH401 fixture wants it suppressed\n"
        "except:\n"
        "    g()\n"
    )
    assert [v.suppressed for v in vs] == [True]


def test_suppression_without_reason_does_not_suppress():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "except:  # analysis: disable=EH401\n"
        "    g()\n"
    )
    assert len(vs) == 1 and not vs[0].suppressed
    assert "missing reason" in vs[0].message


def test_suppression_wrong_code_does_not_suppress():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "except:  # analysis: disable=TS101 not the right code\n"
        "    g()\n"
    )
    assert len(vs) == 1 and not vs[0].suppressed


def test_suppression_preceding_line_wins_over_unrelated_inline_disable():
    # an inline disable for a DIFFERENT code must not mask a valid
    # suppression sitting on the preceding comment line
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "# analysis: disable=EH401 fixture suppresses the bare except\n"
        "except:  # analysis: disable=TS101 unrelated code\n"
        "    g()\n"
    )
    assert [v.suppressed for v in vs] == [True]
    assert vs[0].reason == "fixture suppresses the bare except"


def test_suppression_multiple_codes():
    vs = analyze_source(
        "try:\n"
        "    f()\n"
        "except:  # analysis: disable=TS101,EH401 fixture covers both\n"
        "    g()\n"
    )
    assert [v.suppressed for v in vs] == [True]


# -- reporters + registry ----------------------------------------------------

def test_reporters_and_summary():
    vs = analyze_source("try:\n    f()\nexcept:\n    pass\n")
    data = json.loads(render_json(vs))
    assert data["summary"]["unsuppressed"] == len(vs) >= 1
    assert {v["code"] for v in data["violations"]} >= {"EH401"}
    text = render_text(vs)
    assert "EH401" in text and "unsuppressed" in text


def test_checker_codes_unique_and_documented():
    table = all_codes()
    assert {"TS101", "PK201", "FD301", "EH401"} <= set(table)
    for checker in all_checkers():
        for code, desc in checker.codes.items():
            assert desc, code


# -- CLI ---------------------------------------------------------------------

def _run_cli(args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    f()\nexcept:\n    pass\n")
    good = tmp_path / "good.py"
    good.write_text("def f():\n    return 1\n")
    r = _run_cli([str(bad)])
    assert r.returncode == 1 and "EH401" in r.stdout
    r = _run_cli(["--format", "json", str(good)])
    assert r.returncode == 0
    assert json.loads(r.stdout)["summary"]["unsuppressed"] == 0


def test_cli_missing_path_is_a_usage_error(tmp_path):
    # a typo'd target must not become a vacuous zero-file clean pass
    r = _run_cli([str(tmp_path / "no_such_dir")])
    assert r.returncode == 2 and "no such file" in r.stderr
    # ... and neither must an existing directory holding no Python files
    empty = tmp_path / "empty"
    empty.mkdir()
    r = _run_cli([str(empty)])
    assert r.returncode == 2 and "no Python files" in r.stderr


def test_autotune_verbose_handler_follows_the_flag():
    import logging

    import paddle_tpu as paddle
    from paddle_tpu.kernels.autotune import _logger, _verbose_state

    prior = _logger.level
    try:
        paddle.set_flags({"FLAGS_kernel_autotune_verbose": True})
        assert _verbose_state and _verbose_state[0] in _logger.handlers
        paddle.set_flags({"FLAGS_kernel_autotune_verbose": False})
        assert not _verbose_state
        assert not any(isinstance(h, logging.StreamHandler) for h in _logger.handlers)
        assert _logger.level == prior
    finally:
        paddle.set_flags({"FLAGS_kernel_autotune_verbose": False})
        _logger.setLevel(prior)


# -- the tier-1 gate: the package must analyze clean -------------------------

def test_whole_package_clean():
    vs = analyze_paths([str(PKG)])
    live = [v for v in vs if not v.suppressed]
    assert live == [], "unsuppressed violations:\n" + "\n".join(v.format() for v in live)
    # acceptance: every suppression carries a reason string
    for v in vs:
        if v.suppressed:
            assert v.reason, v.format()


def test_cli_whole_package_gate():
    r = _run_cli(["--format", "json", "paddle_tpu/"])
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert data["summary"]["unsuppressed"] == 0


# -- flags satellite: env-coercion failures name the flag --------------------

def test_env_coercion_error_names_flag_and_env_var(monkeypatch):
    from paddle_tpu.flags import FlagRegistry

    reg = FlagRegistry()
    reg.define("scan_depth", int, 4)
    monkeypatch.setenv("FLAGS_scan_depth", "not-an-int")
    with pytest.raises(ValueError) as ei:
        reg.get("scan_depth")
    msg = str(ei.value)
    assert "FLAGS_scan_depth" in msg and "scan_depth" in msg and "int" in msg
    # the error re-fires on every read — a first get() swallowed by someone's
    # broad except must not leave the flag silently serving its default
    with pytest.raises(ValueError):
        reg.get("scan_depth")


def test_set_coercion_error_names_flag():
    from paddle_tpu.flags import FlagRegistry

    reg = FlagRegistry()
    reg.define("scan_depth", int, 4)
    with pytest.raises(ValueError, match="scan_depth"):
        reg.set("scan_depth", "nope")
