"""Collective API tests driven inside shard_map on the 8-device CPU mesh —
mirrors reference ``test/collective/`` cases (send/recv, subgroup
communicators, reduce-to-one) per SURVEY §4's no-cluster strategy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed import collective as C

AX = "x"
N = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), (AX,))


def _run(fn, *args, out_specs=P(AX)):
    mapped = jax.shard_map(
        fn, mesh=_mesh(), in_specs=P(AX), out_specs=out_specs, check_vma=False
    )
    return np.asarray(jax.jit(mapped)(*args))


def _axis_group():
    return C.new_group(list(range(N)), axis_name=AX)


def _subgroup(ranks):
    return C.new_group(ranks, axis_name=AX, axis_size=N)


X = np.arange(N, dtype=np.float32)


class TestSubgroups:
    def test_partition_construction(self):
        g = _subgroup([0, 2])
        assert g.ranks == [0, 2]
        assert g.axis_index_groups == [[0, 2], [1, 3], [4, 5], [6, 7]]

    def test_indivisible_remainder_rejected(self):
        with pytest.raises(ValueError, match="partition"):
            C.new_group([0, 1, 2], axis_name=AX, axis_size=N)

    def test_all_reduce_subgroup(self):
        g = _subgroup([0, 2])
        out = _run(lambda x: C.all_reduce(x, group=g), X)
        expect = np.array([2, 4, 2, 4, 9, 9, 13, 13], np.float32)
        np.testing.assert_allclose(out, expect)

    def test_all_reduce_whole_axis(self):
        g = _axis_group()
        out = _run(lambda x: C.all_reduce(x, group=g), X)
        np.testing.assert_allclose(out, np.full(N, X.sum()))

    def test_broadcast_subgroup(self):
        g = _subgroup([0, 2])
        out = _run(lambda x: C.broadcast(x, src=2, group=g), X)
        # each sibling group receives its own member at position 1
        expect = np.array([2, 3, 2, 3, 5, 5, 7, 7], np.float32)
        np.testing.assert_allclose(out, expect)

    def test_ppermute_subgroup_applies_per_sibling(self):
        g = _subgroup([0, 2])
        # group-local swap (0<->1) runs inside every sibling subgroup
        out = _run(lambda x: C.ppermute(x, [(0, 1), (1, 0)], group=g), X)
        expect = np.array([2, 3, 0, 1, 5, 4, 7, 6], np.float32)
        np.testing.assert_allclose(out, expect)


class TestReduceToOne:
    def test_reduce_keeps_value_only_at_dst(self):
        g = _axis_group()
        out = _run(lambda x: C.reduce(x, dst=3, group=g), X)
        expect = X.copy()
        expect[3] = X.sum()
        np.testing.assert_allclose(out, expect)

    def test_reduce_subgroup(self):
        g = _subgroup([0, 2])
        out = _run(lambda x: C.reduce(x, dst=2, group=g), X)
        # dst position 1 of each sibling group holds its group sum
        expect = np.array([0, 1, 2, 4, 4, 9, 6, 13], np.float32)
        np.testing.assert_allclose(out, expect)

    def test_reduce_max(self):
        g = _axis_group()
        out = _run(lambda x: C.reduce(x, dst=0, op=C.ReduceOp.MAX, group=g), X)
        expect = X.copy()
        expect[0] = X.max()
        np.testing.assert_allclose(out, expect)


class TestAllGatherAxis:
    def test_concat_along_requested_axis(self):
        g = _axis_group()
        x2 = np.arange(N * 2, dtype=np.float32).reshape(N, 2)
        out = _run(
            lambda x: C.all_gather(None, x, group=g, axis=1),
            x2,
            out_specs=P(AX),
        )
        # every member holds the concatenation along axis 1: [1, 16] locally
        assert out.shape == (N, 16)
        np.testing.assert_allclose(out[0], x2.reshape(-1))
        np.testing.assert_allclose(out[5], x2.reshape(-1))

    def test_gather_subgroup(self):
        g = _subgroup([0, 4])
        out = _run(
            lambda x: C.all_gather(None, x[:, None], group=g, axis=0), X
        )
        # local result per member is its subgroup's [2, 1] gather; member 0's
        # rows are [x0, x4]
        assert out.shape == (2 * N, 1)
        np.testing.assert_allclose(out[:2, 0], np.array([0.0, 4.0]))


class TestBatchIsendIrecv:
    def test_bidirectional_ring_two_buffers(self):
        g = _axis_group()

        def fn(x):
            y = x * 10  # ONE buffer object: ops sharing it fold into one ppermute
            nxt_pairs = [
                C.P2POp(C.isend, x, peer=(i + 1) % N, group=g, src=i) for i in range(N)
            ]
            prv_pairs = [
                C.P2POp(C.isend, y, peer=(i - 1) % N, group=g, src=i)
                for i in range(N)
            ]
            ops = nxt_pairs + prv_pairs
            res = C.batch_isend_irecv(ops)
            return jnp.stack([res[0], res[N]], axis=0)  # (from prev, from next)

        out = _run(fn, X, out_specs=P(None, AX))
        np.testing.assert_allclose(out[0], np.roll(X, 1))  # received from i-1
        np.testing.assert_allclose(out[1], np.roll(X * 10, -1))  # from i+1

    def test_send_recv_pairs_dedupe_to_one_edge(self):
        g = _axis_group()

        def fn(x):
            ops = [
                C.P2POp(C.isend, x, peer=1, group=g, src=0),
                C.P2POp(C.irecv, x, peer=0, group=g, src=1),  # same edge 0->1
            ]
            res = C.batch_isend_irecv(ops)
            assert len(res) == 2
            return res[1]

        out = _run(fn, X)
        assert out[1] == 0.0  # rank 1 received rank 0's value
        assert out[5] == 0.0  # everyone else got the ppermute fill

    def test_results_align_with_ops(self):
        g = _axis_group()

        def fn(x):
            ops = [
                C.P2POp(C.irecv, x * 2, peer=3, group=g, src=4),   # 3 -> 4
                C.P2POp(C.isend, x, peer=2, group=g, src=6),       # 6 -> 2
            ]
            r = C.batch_isend_irecv(ops)
            return jnp.stack(r, axis=0)

        out = _run(fn, X, out_specs=P(None, AX))
        assert out[0, 4] == 6.0  # x*2 from rank 3
        assert out[1, 2] == 6.0  # x from rank 6

    def test_missing_src_rejected(self):
        g = _axis_group()

        def fn(x):
            return C.batch_isend_irecv([C.P2POp(C.isend, x, peer=1, group=g)])[0]

        with pytest.raises(ValueError, match="both endpoints"):
            _run(fn, X)


class TestAlltoallSubgroup:
    def test_alltoall_single_subgroup(self):
        g = _subgroup([0, 2])

        def fn(x):
            return C.alltoall_single(None, x, group=g)

        # local [2, 2] per member: one row per subgroup peer
        x2 = np.arange(N * 4, dtype=np.float32).reshape(N * 2, 2)
        out = _run(fn, x2)
        assert out.shape == (N * 2, 2)
        # member 0 (subgroup [0, 2]): keeps its row 0, receives member 2's row 0
        np.testing.assert_allclose(out[0], x2[0])
        np.testing.assert_allclose(out[1], x2[4])  # member 2's first row


class TestInPlaceSemantics:
    """reduce_scatter/scatter write into the provided output tensor, matching
    the reference's in-place collectives (communication/reduce_scatter.py) —
    ported scripts read the buffer, not the return value."""

    def test_reduce_scatter_writes_output_tensor(self):
        g = _axis_group()
        X64 = np.arange(N * N, dtype=np.float32)  # local [N] per rank

        def fn(x):
            from paddle_tpu.core.tensor import Tensor

            t_in = Tensor(x)
            out = Tensor(jnp.zeros((x.shape[0] // N,), x.dtype))
            ret = C.reduce_scatter(out, t_in, group=g)
            assert ret is out  # same object returned
            return out.data

        out = _run(fn, X64)
        # tiled psum_scatter: rank r gets sum_s X64[N*s + r]
        expect = np.array([X64[r::N].sum() for r in range(N)], np.float32)
        np.testing.assert_allclose(out, expect)

    def test_scatter_writes_output_tensor(self):
        g = _axis_group()
        X64 = np.arange(N * N, dtype=np.float32)

        def fn(x):
            from paddle_tpu.core.tensor import Tensor

            t_in = Tensor(x)
            out = Tensor(jnp.zeros((), x.dtype))
            ret = C.scatter(out, t_in, src=0, group=g)
            assert ret is out
            return out.data.reshape(1)

        out = _run(fn, X64)
        # each rank receives its piece of rank 0's local buffer X64[:N]
        np.testing.assert_allclose(out, X64[:N])


class TestAllGatherObject:
    def test_single_process_appends(self):
        """Single-process SPMD: every 'rank' already holds the global value,
        so the gather is the one local object (the historical contract).
        The multi-process path exchanges through the jax.distributed
        coordination store and is exercised end-to-end by
        test_launch.py::TestTwoNodeHandshake."""
        got = []
        C.all_gather_object(got, {"rank": 0})
        assert got == [{"rank": 0}]
        # repeated calls append independently (no shared state between calls)
        C.all_gather_object(got, 7)
        assert got == [{"rank": 0}, 7]
