"""Pallas kernel parity tests (interpret mode on CPU): flash attention fwd/bwd
vs the XLA reference, FlashMask C∈{1,2,4} vs densified-bias reference, GQA,
fused rms_norm and rope.

Mirrors the reference's OpTest analytic-grad methodology (SURVEY §4) for the
kernels that replace flash_attn_kernel.cu / rms_norm / fused_rope.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.flash_attention import flash_attention_pallas
from paddle_tpu.kernels.flashmask import flashmask_attention_pallas, flashmask_maxmin
from paddle_tpu.kernels.fused import fused_rms_norm_pallas, fused_rope_pallas
from paddle_tpu.nn.functional.flash_attention import (
    _xla_attention,
    make_flashmask_bias,
)


def _qkv(b=2, sq=64, sk=64, h=4, hk=None, d=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    hk = hk or h
    q = jax.random.normal(ks[0], (b, sq, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, sk, hk, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, sk, hk, d), jnp.float32)
    return q, k, v


class TestFlashAttentionPallas:
    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_matches_xla(self, causal):
        q, k, v = _qkv()
        out = flash_attention_pallas(q, k, v, causal=causal, interpret=True)
        ref = _xla_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_unaligned_seqlen(self):
        q, k, v = _qkv(sq=50, sk=70)
        out = flash_attention_pallas(q, k, v, causal=False, interpret=True)
        ref = _xla_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_gqa(self):
        q, k, v = _qkv(h=8, hk=2)
        out = flash_attention_pallas(q, k, v, causal=True, interpret=True)
        ref = _xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_xla(self, causal):
        q, k, v = _qkv(b=1, sq=32, sk=32, h=2, d=16)

        def f_pallas(q, k, v):
            return flash_attention_pallas(q, k, v, causal=causal, interpret=True).sum()

        def f_ref(q, k, v):
            return _xla_attention(q, k, v, causal=causal).sum()

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=5e-4, atol=5e-4)

    def test_gqa_grads(self):
        q, k, v = _qkv(b=1, sq=32, sk=32, h=4, hk=2, d=16)

        def f_pallas(q, k, v):
            return (flash_attention_pallas(q, k, v, causal=True, interpret=True) ** 2).sum()

        def f_ref(q, k, v):
            return (_xla_attention(q, k, v, causal=True) ** 2).sum()

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=5e-4, atol=5e-4)

    def test_bf16(self):
        q, k, v = _qkv()
        out = flash_attention_pallas(
            q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
            causal=True, interpret=True,
        )
        ref = _xla_attention(q, k, v, causal=True)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), rtol=3e-2, atol=3e-2
        )


def _doc_mask_bounds(b, sk, doc_len):
    """C=1 causal document mask: tokens attend within their document."""
    starts = []
    for j in range(sk):
        doc_end = ((j // doc_len) + 1) * doc_len
        starts.append(min(doc_end, sk))
    idx = np.asarray(starts, np.int32).reshape(1, 1, sk, 1)
    return jnp.asarray(np.broadcast_to(idx, (b, 1, sk, 1)))


class TestFlashMaskPallas:
    def test_c1_document_mask(self):
        b, s = 2, 64
        q, k, v = _qkv(b=b, sq=s, sk=s)
        idx = _doc_mask_bounds(b, s, doc_len=16)
        out = flashmask_attention_pallas(q, k, v, idx, causal=True, interpret=True)
        bias = make_flashmask_bias(idx, s, s, True)
        ref = _xla_attention(q, k, v, bias=bias, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_c2_sliding_window(self):
        b, s, w = 1, 64, 16
        q, k, v = _qkv(b=b, sq=s, sk=s)
        # sliding window: for column j mask rows in [j + w, Sq)
        start = np.minimum(np.arange(s) + w, s).astype(np.int32)
        end = np.full(s, s, np.int32)
        idx = jnp.asarray(np.stack([start, end], -1).reshape(1, 1, s, 2))
        out = flashmask_attention_pallas(q, k, v, idx, causal=True, interpret=True)
        bias = make_flashmask_bias(idx, s, s, True)
        ref = _xla_attention(q, k, v, bias=bias, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_c4_bidirectional_bands(self):
        b, s = 1, 32
        q, k, v = _qkv(b=b, sq=s, sk=s, h=2, d=16)
        rng = np.random.default_rng(0)
        lts = rng.integers(0, s, s).astype(np.int32)
        lte = np.minimum(lts + rng.integers(0, 8, s), s).astype(np.int32)
        uts = rng.integers(0, s // 2, s).astype(np.int32)
        ute = np.minimum(uts + rng.integers(0, 4, s), s).astype(np.int32)
        idx = jnp.asarray(np.stack([lts, lte, uts, ute], -1).reshape(1, 1, s, 4))
        out = flashmask_attention_pallas(q, k, v, idx, causal=False, interpret=True)
        bias = make_flashmask_bias(idx, s, s, False)
        ref = _xla_attention(q, k, v, bias=bias, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_flashmask_grads(self):
        b, s = 1, 32
        q, k, v = _qkv(b=b, sq=s, sk=s, h=2, d=16)
        idx = _doc_mask_bounds(b, s, doc_len=8)

        def f_pallas(q, k, v):
            return flashmask_attention_pallas(q, k, v, idx, causal=True, interpret=True).sum()

        def f_ref(q, k, v):
            bias = make_flashmask_bias(idx, s, s, True)
            return _xla_attention(q, k, v, bias=bias, causal=True).sum()

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=5e-4, atol=5e-4)

    def test_per_head_mask(self):
        b, s, h = 1, 32, 2
        q, k, v = _qkv(b=b, sq=s, sk=s, h=h, d=16)
        idx1 = np.asarray(_doc_mask_bounds(1, s, 8))
        idx2 = np.asarray(_doc_mask_bounds(1, s, 16))
        idx = jnp.asarray(np.concatenate([idx1, idx2], axis=1))  # [1, 2, S, 1]
        out = flashmask_attention_pallas(q, k, v, idx, causal=True, interpret=True)
        bias = make_flashmask_bias(idx, s, s, True)
        ref = _xla_attention(q, k, v, bias=bias, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_maxmin_blocks(self):
        idx = _doc_mask_bounds(1, 64, 16)
        mn, mx = flashmask_maxmin(idx, block_size=16)
        assert mn.shape == (1, 1, 4, 1) and mx.shape == (1, 1, 4, 1)
        np.testing.assert_array_equal(np.asarray(mn)[0, 0, :, 0], [16, 32, 48, 64])


class TestFusedKernels:
    def test_rms_norm_fwd(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 17, 256), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (256,)) * 0.1 + 1.0
        y = fused_rms_norm_pallas(x, w, epsilon=1e-6, interpret=True)
        ref = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_rms_norm_grads(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 64), jnp.float32)
        w = jnp.ones((64,)) * 1.5

        def f_pallas(x, w):
            return (fused_rms_norm_pallas(x, w, interpret=True) ** 2).sum()

        def f_ref(x, w):
            y = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w
            return (y**2).sum()

        gp = jax.grad(f_pallas, argnums=(0, 1))(x, w)
        gr = jax.grad(f_ref, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(gr[0]), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gp[1]), np.asarray(gr[1]), rtol=1e-4, atol=1e-5)

    def test_rope(self):
        b, s, h, d = 2, 16, 4, 32
        x = jax.random.normal(jax.random.PRNGKey(3), (b, s, h, d), jnp.float32)
        inv = 1.0 / (10000 ** (jnp.arange(0, d, 2) / d))
        t = jnp.arange(s)[:, None] * inv[None, :]
        cos = jnp.concatenate([jnp.cos(t), jnp.cos(t)], -1)
        sin = jnp.concatenate([jnp.sin(t), jnp.sin(t)], -1)
        y = fused_rope_pallas(x, cos, sin, interpret=True)
        x1, x2 = x[..., : d // 2], x[..., d // 2 :]
        rot = jnp.concatenate([-x2, x1], -1)
        ref = x * cos[None, :, None, :] + rot * sin[None, :, None, :]
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_rope_grad(self):
        # custom VJP: Pallas bwd kernel must match autodiff of the reference
        # composition — including asymmetric sin/cos halves (no table symmetry)
        b, s, h, d = 2, 8, 2, 32
        key = jax.random.PRNGKey(7)
        k1, k2, k3 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (b, s, h, d), jnp.float32)
        cos = jax.random.normal(k2, (s, d), jnp.float32)
        sin = jax.random.normal(k3, (s, d), jnp.float32)

        def f_pallas(x, cos, sin):
            return (fused_rope_pallas(x, cos, sin, interpret=True) ** 2).sum()

        def f_ref(x, cos, sin):
            x1, x2 = x[..., : d // 2], x[..., d // 2 :]
            rot = jnp.concatenate([-x2, x1], -1)
            y = x * cos[None, :, None, :] + rot * sin[None, :, None, :]
            return (y**2).sum()

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(x, cos, sin)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(x, cos, sin)
        np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(gr[0]), rtol=1e-4, atol=1e-4)
        # table grads come back in the kernel's [1, S, D] layout
        np.testing.assert_allclose(
            np.asarray(gp[1]).reshape(s, d), np.asarray(gr[1]), rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(gp[2]).reshape(s, d), np.asarray(gr[2]), rtol=1e-4, atol=1e-4
        )
