"""Tensor-parallel layer tests on the virtual 8-device CPU mesh.

Mirrors the reference's hybrid_parallel_mp_layers.py strategy (SURVEY §4):
parallel layers must match their single-device counterparts numerically, both
forward and gradients.
"""

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


@pytest.fixture()
def mp_env():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    yield fleet.get_hybrid_communicate_group()


def _set_weight(layer_param, value):
    with paddle_tpu.no_grad():
        sharding = getattr(layer_param._data, "sharding", None)
        t = paddle_tpu.to_tensor(value)
        import jax

        layer_param._data = jax.device_put(t._data, sharding) if sharding is not None else t._data


def test_column_row_parallel_linear_matches_serial(mp_env):
    np.random.seed(0)
    B, H, FF = 8, 16, 32
    x_np = np.random.randn(B, H).astype(np.float32)
    w1_np = np.random.randn(H, FF).astype(np.float32) * 0.1
    w2_np = np.random.randn(FF, H).astype(np.float32) * 0.1

    col = fleet.ColumnParallelLinear(H, FF, has_bias=True, gather_output=False)
    row = fleet.RowParallelLinear(FF, H, has_bias=True, input_is_parallel=True)
    assert col.world_size == 4 and row.world_size == 4
    _set_weight(col.weight, w1_np)
    _set_weight(row.weight, w2_np)

    # weights must actually be placed sharded over the mp axis
    spec1 = col.weight._data.sharding.spec
    assert "mp" in str(spec1)

    lin1 = paddle_tpu.nn.Linear(H, FF)
    lin2 = paddle_tpu.nn.Linear(FF, H)
    _set_weight(lin1.weight, w1_np)
    _set_weight(lin2.weight, w2_np)

    x1 = paddle_tpu.to_tensor(x_np, stop_gradient=False)
    x2 = paddle_tpu.to_tensor(x_np, stop_gradient=False)
    y_par = row(col(x1))
    y_ser = lin2(lin1(x2))
    np.testing.assert_allclose(y_par.numpy(), y_ser.numpy(), rtol=1e-5, atol=1e-5)

    y_par.sum().backward()
    y_ser.sum().backward()
    np.testing.assert_allclose(x1.grad.numpy(), x2.grad.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(col.weight.grad.numpy(), lin1.weight.grad.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(row.weight.grad.numpy(), lin2.weight.grad.numpy(), rtol=1e-5, atol=1e-5)


def test_column_parallel_gather_output(mp_env):
    H, FF = 8, 16
    col = fleet.ColumnParallelLinear(H, FF, has_bias=False, gather_output=True)
    x = paddle_tpu.randn([4, H])
    y = col(x)
    assert y.shape == [4, FF]


def test_vocab_parallel_embedding_matches_serial(mp_env):
    V, D = 32, 16
    np.random.seed(1)
    w_np = np.random.randn(V, D).astype(np.float32)
    ids_np = np.random.randint(0, V, size=(4, 6))

    vp = fleet.VocabParallelEmbedding(V, D)
    _set_weight(vp.weight, w_np)
    emb = paddle_tpu.nn.Embedding(V, D)
    _set_weight(emb.weight, w_np)

    ids = paddle_tpu.to_tensor(ids_np)
    out_p = vp(ids)
    out_s = emb(ids)
    np.testing.assert_allclose(out_p.numpy(), out_s.numpy(), rtol=1e-6, atol=1e-6)

    out_p.sum().backward()
    out_s.sum().backward()
    np.testing.assert_allclose(vp.weight.grad.numpy(), emb.weight.grad.numpy(), rtol=1e-6, atol=1e-6)


def test_parallel_cross_entropy_matches_serial(mp_env):
    B, C = 8, 16
    np.random.seed(2)
    logits_np = np.random.randn(B, C).astype(np.float32)
    labels_np = np.random.randint(0, C, size=(B, 1))

    pce = fleet.ParallelCrossEntropy()
    logits_p = paddle_tpu.to_tensor(logits_np, stop_gradient=False)
    loss_p = pce(logits_p, paddle_tpu.to_tensor(labels_np))

    logits_s = paddle_tpu.to_tensor(logits_np, stop_gradient=False)
    loss_s = paddle_tpu.nn.functional.softmax_with_cross_entropy(
        logits_s, paddle_tpu.to_tensor(labels_np)
    )
    np.testing.assert_allclose(loss_p.numpy(), loss_s.numpy(), rtol=1e-5, atol=1e-5)

    loss_p.sum().backward()
    loss_s.sum().backward()
    np.testing.assert_allclose(logits_p.grad.numpy(), logits_s.grad.numpy(), rtol=1e-5, atol=1e-5)


def test_rng_tracker_decorrelates_dropout(mp_env):
    from paddle_tpu.distributed.fleet.layers.mpu.random import (
        get_rng_state_tracker,
        model_parallel_random_seed,
    )

    model_parallel_random_seed(1234)
    tracker = get_rng_state_tracker()
    x = paddle_tpu.ones([64, 64])
    with tracker.rng_state("global_seed"):
        a = paddle_tpu.nn.functional.dropout(x, p=0.5, training=True)
    with tracker.rng_state("local_seed"):
        b = paddle_tpu.nn.functional.dropout(x, p=0.5, training=True)
    assert not np.allclose(a.numpy(), b.numpy())
    # replaying the same named state reproduces the mask
    model_parallel_random_seed(1234)
    with tracker.rng_state("global_seed"):
        a2 = paddle_tpu.nn.functional.dropout(x, p=0.5, training=True)
    np.testing.assert_allclose(a.numpy(), a2.numpy())


def test_hybrid_dp_mp_preserves_batch_sharding(mp_env):
    """mark_replicated must only constrain the mp axis: a batch-dim-sharded
    activation keeps its dp sharding through a Column->Row block."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    col = fleet.ColumnParallelLinear(16, 32, has_bias=False, gather_output=False)
    row = fleet.RowParallelLinear(32, 16, has_bias=False, input_is_parallel=True)
    mesh = mp_env.get_parallel_mesh().jax_mesh()
    x = paddle_tpu.randn([8, 16])
    x_sharded = paddle_tpu.Tensor(
        jax.device_put(x._data, NamedSharding(mesh, PartitionSpec("dp", None)))
    )
    y = row(col(x_sharded))
    assert "dp" in str(y._data.sharding.spec), y._data.sharding
