"""Eager autograd tape: backward, accumulation, hooks, no_grad, PyLayer, grad."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_rule_multiple_uses():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x + x  # dy/dx = 2x + 1 = 5
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 5.0)


def test_grad_accumulates_across_backwards():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), 5.0)


def test_no_grad_blocks_tape():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.grad_node is None
    assert y.stop_gradient


def test_stop_gradient_leaf_gets_no_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    w = paddle.to_tensor([2.0], stop_gradient=True)
    y = (x * w).sum()
    y.backward()
    assert w.grad is None
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_backward_through_matmul_mlp():
    np.random.seed(0)
    w1 = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32), stop_gradient=False)
    w2 = paddle.to_tensor(np.random.randn(8, 2).astype(np.float32), stop_gradient=False)
    x = paddle.to_tensor(np.random.randn(3, 4).astype(np.float32))
    h = paddle.nn.functional.relu(x @ w1)
    out = (h @ w2).sum()
    out.backward()
    assert w1.grad.shape == [4, 8]
    assert w2.grad.shape == [8, 2]
    # closed-form check: dL/dW2 = h^T @ ones
    h_np = np.maximum(x.numpy() @ w1.numpy(), 0)
    expected_w2 = h_np.T @ np.ones((3, 2), np.float32)
    np.testing.assert_allclose(w2.grad.numpy(), expected_w2, rtol=1e-5)


def test_retain_graph():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 12.0)


def test_double_backward_without_retain_raises():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x
    y.backward()
    with pytest.raises(Exception):
        y.backward()


def test_backward_hook():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []
    x.register_hook(lambda g: seen.append(g.numpy().copy()))
    (x * 5).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [5.0])


def test_hook_modifies_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    x.register_hook(lambda g: g * 2)
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_multi_output_op_grad():
    x = paddle.to_tensor([3.0, 1.0, 2.0], stop_gradient=False)
    values, indices = paddle.topk(x, k=2)
    values.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])


def test_grad_api():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x * x
    (gx,) = paddle.grad([y], [x])
    np.testing.assert_allclose(gx.numpy(), 12.0)
    assert x.grad is None  # paddle.grad does not pollute .grad


def test_autograd_backward_api():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 4
    paddle.autograd.backward([y], [paddle.ones_like(y)])
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 4.0])


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            return dy * 2

    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [3.0])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_retain_grads_intermediate():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * 3
    y.retain_grads()
    z = y * 4
    z.backward()
    np.testing.assert_allclose(y.grad.numpy(), 4.0)
    np.testing.assert_allclose(x.grad.numpy(), 12.0)


def test_getitem_grad():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
    x[0].sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 1], [0, 0]])


# -- higher-order (create_graph) ------------------------------------------
# Reference: egr::Grad with create_graph=True, paddle/fluid/eager/backward.cc:450.


def test_create_graph_double_grad():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = x**3
    (gx,) = paddle.grad([y.sum()], [x], create_graph=True)
    np.testing.assert_allclose(gx.numpy(), [12.0, 27.0])
    assert not gx.stop_gradient
    (ggx,) = paddle.grad([gx.sum()], [x])
    np.testing.assert_allclose(ggx.numpy(), [12.0, 18.0])


def test_create_graph_third_order():
    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = x**4
    (g1,) = paddle.grad([y.sum()], [x], create_graph=True)
    (g2,) = paddle.grad([g1.sum()], [x], create_graph=True)
    (g3,) = paddle.grad([g2.sum()], [x])
    np.testing.assert_allclose(g3.numpy(), [36.0], rtol=1e-6)


def test_create_graph_mixed_partial():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = paddle.to_tensor(5.0, stop_gradient=False)
    z = x * y * y
    (gx,) = paddle.grad([z], [x], create_graph=True)
    np.testing.assert_allclose(gx.numpy(), 25.0)
    (gxy,) = paddle.grad([gx], [y])
    np.testing.assert_allclose(gxy.numpy(), 10.0)


def test_create_graph_backward_on_grad():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    (gx,) = paddle.grad([y], [x], create_graph=True)
    gx.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_create_graph_matmul_second_order():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((3, 4)).astype(np.float32)
    c = rng.standard_normal((3, 4)).astype(np.float32)
    A = paddle.to_tensor(a, stop_gradient=False)
    B = paddle.to_tensor(rng.standard_normal((4, 2)).astype(np.float32), stop_gradient=False)
    out = paddle.matmul(A, B).sum()
    (gA,) = paddle.grad([out], [A], create_graph=True)  # = ones(3,2) @ B.T
    (gB,) = paddle.grad([(gA * paddle.to_tensor(c)).sum()], [B], allow_unused=True)
    # d/dB sum(ones@B.T * C) = C.T @ ones(3,2)
    np.testing.assert_allclose(gB.numpy(), c.T @ np.ones((3, 2), np.float32), rtol=1e-5)


def test_create_graph_exp_hessian_vector():
    x = paddle.to_tensor([0.3, -0.7], stop_gradient=False)
    y = paddle.exp(x).sum()
    (gx,) = paddle.grad([y], [x], create_graph=True)
    v = paddle.to_tensor([1.0, 2.0])
    (hvp,) = paddle.grad([(gx * v).sum()], [x])
    np.testing.assert_allclose(hvp.numpy(), np.exp([0.3, -0.7]) * [1.0, 2.0], rtol=1e-6)


def test_grad_only_inputs_no_side_effects():
    a = paddle.to_tensor(2.0, stop_gradient=False)
    b = paddle.to_tensor(5.0, stop_gradient=False)
    z = a * b
    (ga,) = paddle.grad([z], [a])
    np.testing.assert_allclose(ga.numpy(), 5.0)
    assert b.grad is None  # egr::Grad only_inputs semantics


def test_mixed_accumulation_keeps_taped_grad():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = (x * x).sum()
    # taped grad via run_backward(create_graph), then a plain backward on top
    from paddle_tpu.core.autograd import run_backward

    run_backward([y], retain_graph=True, create_graph=True)
    assert x.grad.grad_node is not None
    y2 = (x * 3.0).sum()
    y2.backward()
    # 2x + 3 accumulated; the taped component must survive
    np.testing.assert_allclose(x.grad.numpy(), [9.0])
    assert x.grad.grad_node is not None


def test_create_graph_immune_to_inplace_mutation():
    """ADVICE r3: create_graph re-derives the vjp from buffers snapshotted at
    dispatch (reference TensorWrapper semantics), so an in-place mutation
    between forward and double-backward yields gradients w.r.t. the ORIGINAL
    values — not silently wrong ones from the mutated buffer."""
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32))
    x.stop_gradient = False
    y = (x * x).sum()
    x.set_value(np.array([9.0, 9.0], np.float32))  # mutate AFTER forward
    (gx,) = paddle.autograd.grad([y], [x], create_graph=True)
    np.testing.assert_allclose(gx.numpy(), [4.0, 6.0], rtol=1e-6)  # 2*orig


def test_create_graph_through_inplace_op():
    """The in-place op's own rebind must not break create_graph either: the
    node snapshots its input before _replace_ bumps the buffer."""
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32))
    x.stop_gradient = False
    y = x * x  # d/dx = 2x
    y.scale_(3.0)  # in-place on a non-leaf; total: 3*x^2
    (gx,) = paddle.autograd.grad([y.sum()], [x], create_graph=True)
    np.testing.assert_allclose(gx.numpy(), 6 * np.array([2.0, 3.0]), rtol=1e-5)
    (ggx,) = paddle.autograd.grad([gx.sum()], [x])
    np.testing.assert_allclose(ggx.numpy(), [6.0, 6.0], rtol=1e-5)


def test_create_graph_without_mutation_still_works():
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32))
    x.stop_gradient = False
    y = (x * x * x).sum()
    (gx,) = paddle.autograd.grad([y], [x], create_graph=True)
    (ggx,) = paddle.autograd.grad([gx.sum()], [x])
    np.testing.assert_allclose(ggx.numpy(), 6 * np.array([2.0, 3.0]), rtol=1e-5)


def test_first_order_backward_through_inplace_on_nonleaf():
    """Regression (r4 review chain): in-place on a non-leaf used to rewire the
    recording into a self-cycle, orphaning the producer's tape."""
    x = paddle.to_tensor(np.array([1.0, 4.0], np.float32))
    x.stop_gradient = False
    y = x * x
    y.scale_(2.0)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 4 * np.array([1.0, 4.0]), rtol=1e-6)


class TestFunctionalAutograd:
    """jacobian/hessian/jvp/vjp (reference autograd.py:461 +
    incubate.autograd): numpy oracles on small closed forms."""

    def test_jacobian(self):
        def f(x):
            return x * x * paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))

        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        J = paddle.autograd.jacobian(f, x)
        ref = np.diag(2 * np.array([1.0, 2.0, 3.0]) * np.array([1.0, 2.0, 3.0]))
        np.testing.assert_allclose(np.asarray(J.numpy()), ref, rtol=1e-5)
        Jf = paddle.autograd.jacobian(f, x, mode="fwd")
        np.testing.assert_allclose(np.asarray(Jf.numpy()), ref, rtol=1e-5)

    def test_hessian(self):
        def f(x):
            return (x * x * x).sum()

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        H = paddle.autograd.hessian(f, x)
        np.testing.assert_allclose(np.asarray(H.numpy()), np.diag([6.0, 12.0]), rtol=1e-5)
        with pytest.raises(ValueError, match="scalar"):
            paddle.autograd.hessian(lambda x: x * 2, x)

    def test_jvp_vjp(self):
        def f(x):
            return paddle.sin(x)

        x = paddle.to_tensor(np.array([0.5, 1.0], np.float32))
        v = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out, tang = paddle.autograd.jvp(f, x, v)
        np.testing.assert_allclose(np.asarray(out.numpy()), np.sin([0.5, 1.0]), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(tang.numpy()), np.cos([0.5, 1.0]) * [1.0, 2.0], rtol=1e-5
        )
        out2, grads = paddle.autograd.vjp(f, x, v)
        np.testing.assert_allclose(
            np.asarray(grads.numpy()), np.cos([0.5, 1.0]) * [1.0, 2.0], rtol=1e-5
        )

    def test_multi_input_jacobian(self):
        def f(a, b):
            return a * b

        a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        b = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
        Ja, Jb = paddle.autograd.jacobian(f, [a, b])
        np.testing.assert_allclose(np.asarray(Ja.numpy()), np.diag([3.0, 4.0]), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(Jb.numpy()), np.diag([1.0, 2.0]), rtol=1e-6)

    def test_batched_jacobian_and_hessian(self):
        def f(x):
            return (x * x).sum(-1)  # per-sample scalar

        xb = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        Jb = paddle.autograd.jacobian(f, xb, 0)
        np.testing.assert_allclose(
            np.asarray(Jb.numpy()), 2 * np.array([[1.0, 2.0], [3.0, 4.0]]), rtol=1e-5
        )
        Hb = paddle.autograd.hessian(lambda x: (x * x).sum(), xb, 0)
        np.testing.assert_allclose(
            np.asarray(Hb.numpy()), np.stack([2 * np.eye(2)] * 2), rtol=1e-5
        )
        with pytest.raises(NotImplementedError):
            paddle.autograd.jacobian(f, xb, 1)

    def test_vjp_multi_output_list_cotangent(self):
        def f(x):
            return x, 2 * x

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        v1 = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
        v2 = paddle.to_tensor(np.array([10.0, 10.0], np.float32))
        _, g = paddle.autograd.vjp(f, x, [v1, v2])  # list v onto tuple output
        np.testing.assert_allclose(np.asarray(g.numpy()), [21.0, 21.0], rtol=1e-6)
