"""Numerics tests for the long-tail parity ops (ops/parity.py, sparse
additions, int8 primitives, packed flash wrappers) against numpy/scipy
references."""

import numpy as np
import pytest

import paddle_tpu as paddle

t = paddle.to_tensor
rng = np.random.default_rng(0)


class TestSpecialFunctions:
    def test_gammaln_vs_scipy(self):
        import scipy.special as ss

        x = np.abs(rng.normal(size=(16,))).astype(np.float32) + 0.1
        np.testing.assert_allclose(
            paddle.gammaln(t(x)).numpy(), ss.gammaln(x), rtol=1e-5, atol=1e-5
        )

    def test_gammaincc_and_bessel(self):
        import scipy.special as ss

        a = np.abs(rng.normal(size=(8,))).astype(np.float32) + 0.5
        x = np.abs(rng.normal(size=(8,))).astype(np.float32) + 0.5
        np.testing.assert_allclose(
            paddle.gammaincc(t(a), t(x)).numpy(), ss.gammaincc(a, x), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(paddle.i0e(t(x)).numpy(), ss.i0e(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i1(t(x)).numpy(), ss.i1(x), rtol=1e-4)
        np.testing.assert_allclose(paddle.i1e(t(x)).numpy(), ss.i1e(x), rtol=1e-5)

    def test_polygamma(self):
        import scipy.special as ss

        x = np.abs(rng.normal(size=(8,))).astype(np.float32) + 0.5
        np.testing.assert_allclose(
            paddle.polygamma(t(x), 1).numpy(), ss.polygamma(1, x), rtol=1e-4
        )


class TestComplexViews:
    def test_roundtrip(self):
        x = rng.normal(size=(4, 3, 2)).astype(np.float32)
        c = paddle.as_complex(t(x))
        assert c.numpy().dtype == np.complex64
        np.testing.assert_allclose(paddle.as_real(c).numpy(), x, rtol=1e-6)

    def test_complex_build(self):
        r = rng.normal(size=(5,)).astype(np.float32)
        i = rng.normal(size=(5,)).astype(np.float32)
        np.testing.assert_allclose(paddle.complex(t(r), t(i)).numpy(), r + 1j * i)

    def test_complex_promotes_float64_to_complex128(self):
        import jax

        with jax.experimental.enable_x64():
            r = t(np.array([1.0, -2.0], np.float64))
            i = t(np.array([0.5, 3.0], np.float64))
            c = paddle.complex(r, i)
            assert c.numpy().dtype == np.complex128
            # mixed f32 x f64 promotes to the common (wider) type
            c2 = paddle.complex(t(np.float32([1.0])), t(np.float64([2.0])))
            assert c2.numpy().dtype == np.complex128

    def test_complex_half_inputs_take_float32_floor(self):
        # lax.complex only takes f32/f64 — halves must floor up, not raise
        c = paddle.complex(
            t(np.array([1.0], np.float16)), t(np.array([2.0], np.float16))
        )
        assert c.numpy().dtype == np.complex64
        np.testing.assert_allclose(c.numpy(), np.array([1 + 2j], np.complex64))

    def test_complex_integer_inputs_take_float32_floor(self):
        c = paddle.complex(t(np.array([1, 2], np.int32)), t(np.array([3, 4], np.int32)))
        assert c.numpy().dtype == np.complex64
        np.testing.assert_allclose(c.numpy(), np.array([1 + 3j, 2 + 4j], np.complex64))


class TestLinalgExtras:
    def test_lu_unpack_reconstructs(self):
        a = rng.normal(size=(5, 5)).astype(np.float32)
        lu, piv, _ = paddle.linalg.lu(t(a), get_infos=True)
        P, L, U = paddle.lu_unpack(lu, piv)
        rec = P.numpy() @ L.numpy() @ U.numpy()
        np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-5)

    def test_diag_embed_and_fill_diagonal(self):
        v = rng.normal(size=(3, 4)).astype(np.float32)
        d = paddle.diag_embed(t(v))
        assert list(d.shape) == [3, 4, 4]
        np.testing.assert_allclose(np.diagonal(d.numpy(), axis1=-2, axis2=-1), v)
        m = paddle.fill_diagonal(t(np.zeros((4, 4), np.float32)), 3.0)
        np.testing.assert_allclose(np.diag(m.numpy()), np.full(4, 3.0))
        # offset diagonal
        off = paddle.diag_embed(t(v), offset=1)
        assert list(off.shape) == [3, 5, 5]

    def test_tri_indices_match_numpy(self):
        np.testing.assert_array_equal(
            paddle.tril_indices(4, 4, 0).numpy(), np.stack(np.tril_indices(4, 0, 4))
        )
        np.testing.assert_array_equal(
            paddle.triu_indices(3, 5, 1).numpy(), np.stack(np.triu_indices(3, 1, 5))
        )

    def test_pdist_cdist_vs_scipy(self):
        from scipy.spatial.distance import cdist as sp_cdist
        from scipy.spatial.distance import pdist as sp_pdist

        x = rng.normal(size=(6, 4)).astype(np.float32)
        y = rng.normal(size=(5, 4)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.pdist(t(x)).numpy(), sp_pdist(x).astype(np.float32), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            paddle.cdist(t(x), t(y)).numpy(), sp_cdist(x, y).astype(np.float32),
            rtol=1e-3, atol=1e-4,
        )
        np.testing.assert_allclose(
            paddle.cdist(t(x), t(y), p=1.0).numpy(),
            sp_cdist(x, y, metric="minkowski", p=1).astype(np.float32),
            rtol=1e-4, atol=1e-5,
        )

    def test_reduce_as(self):
        x = rng.normal(size=(4, 3, 5)).astype(np.float32)
        target = np.zeros((3, 1), np.float32)
        out = paddle.reduce_as(t(x), t(target))
        np.testing.assert_allclose(out.numpy(), x.sum(0).sum(-1, keepdims=True), rtol=1e-5)

    def test_norms(self):
        x = rng.normal(size=(3, 4)).astype(np.float32)
        np.testing.assert_allclose(
            float(paddle.squared_l2_norm(t(x)).numpy()), float((x**2).sum()), rtol=1e-5
        )
        np.testing.assert_allclose(
            paddle.p_norm(t(x), porder=3.0, axis=1).numpy(),
            (np.abs(x) ** 3).sum(1) ** (1 / 3), rtol=1e-4,
        )
        np.testing.assert_allclose(
            float(paddle.frobenius_norm(t(x)).numpy()), np.linalg.norm(x), rtol=1e-5
        )


class TestManipulationExtras:
    def test_index_fill(self):
        x = rng.normal(size=(4, 5)).astype(np.float32)
        out = paddle.index_fill(t(x), t(np.array([1, 3])), 0, 9.0).numpy()
        assert (out[[1, 3]] == 9.0).all() and (out[[0, 2]] == x[[0, 2]]).all()
        # method + inplace forms
        y = t(x.copy())
        y.index_fill_(t(np.array([0])), 1, -5.0)
        assert (y.numpy()[:, 0] == -5.0).all()

    def test_tensor_unfold_windows(self):
        x = np.arange(10, dtype=np.float32)
        w = t(x).unfold(0, 4, 3).numpy()
        np.testing.assert_array_equal(w, np.stack([x[0:4], x[3:7], x[6:10]]))

    def test_view_dtype_bitcast(self):
        x = np.array([1.0], np.float32)
        assert paddle.view_dtype(t(x), "int32").numpy()[0] == np.array([1.0], np.float32).view(np.int32)[0]

    def test_shape_fill_isempty(self):
        x = t(np.zeros((2, 3), np.float32))
        np.testing.assert_array_equal(paddle.shape(x).numpy(), [2, 3])
        assert paddle.fill(x, 4.0).numpy().max() == 4.0
        assert not bool(paddle.is_empty(x).numpy())


class TestDecodeOps:
    def test_viterbi_matches_bruteforce(self):
        import itertools

        B, T, N = 2, 4, 3
        pot = rng.normal(size=(B, T, N)).astype(np.float32)
        trans = rng.normal(size=(N, N)).astype(np.float32)
        score, path = paddle.viterbi_decode(t(pot), t(trans), include_bos_eos_tag=False)
        for b in range(B):
            best, best_path = -1e9, None
            for tags in itertools.product(range(N), repeat=T):
                s = pot[b, 0, tags[0]] + sum(
                    trans[tags[i - 1], tags[i]] + pot[b, i, tags[i]] for i in range(1, T)
                )
                if s > best:
                    best, best_path = s, tags
            np.testing.assert_allclose(float(score.numpy()[b]), best, rtol=1e-5)
            assert tuple(path.numpy()[b]) == best_path

    def test_edit_distance(self):
        h = np.array([[1, 2, 3, 0]], np.int64)
        r = np.array([[1, 3, 3, 4]], np.int64)
        d, n = paddle.edit_distance(t(h), t(r), normalized=False)
        assert float(d.numpy()[0, 0]) == 2.0  # substitute 2->3... wait: 1,2,3,0 vs 1,3,3,4
        dn, _ = paddle.edit_distance(
            t(np.array([[1, 2, 3]], np.int64)), t(np.array([[1, 2, 3]], np.int64)),
            normalized=False,
        )
        assert float(dn.numpy()[0, 0]) == 0.0

    def test_top_p_restricts_support(self):
        probs = np.array([[0.6, 0.3, 0.08, 0.02]], np.float32)
        for seed in range(1, 6):
            _, ids = paddle.top_p_sampling(t(probs), t(np.array([0.5], np.float32)), seed=seed)
            assert ids.numpy()[0, 0] == 0  # only the top token survives p=0.5

    def test_gather_tree_backtrace(self):
        # T=3, batch=1, beam=2; parents chain beam1@t2 -> beam0@t1 -> beam0@t0
        ids = np.array([[[1, 5]], [[2, 6]], [[3, 7]]], np.int64)
        parents = np.array([[[0, 1]], [[0, 0]], [[0, 0]]], np.int64)
        out = paddle.gather_tree(t(ids), t(parents)).numpy()
        np.testing.assert_array_equal(out[:, 0, 1], [1, 2, 7])


class TestSegmentOps:
    def test_segment_pool_modes(self):
        x = np.array([[1.0], [2.0], [4.0], [8.0]], np.float32)
        ids = np.array([0, 0, 1, 1], np.int32)
        assert paddle.segment_pool(t(x), t(ids), "SUM").numpy().ravel().tolist() == [3.0, 12.0]
        assert paddle.segment_pool(t(x), t(ids), "MEAN").numpy().ravel().tolist() == [1.5, 6.0]
        assert paddle.segment_pool(t(x), t(ids), "MAX").numpy().ravel().tolist() == [2.0, 8.0]

    def test_send_ue_recv(self):
        x = np.eye(3, dtype=np.float32)
        src = np.array([0, 1], np.int32)
        dst = np.array([2, 2], np.int32)
        e = np.array([[2.0], [3.0]], np.float32)
        out = paddle.send_ue_recv(t(x), t(e), t(src), t(dst), "MUL", "SUM").numpy()
        np.testing.assert_allclose(out[2], [2.0, 3.0, 0.0])


class TestVisionOps:
    def test_grid_sample_identity(self):
        x = rng.normal(size=(2, 3, 5, 5)).astype(np.float32)
        theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], np.float32), (2, 1, 1))
        grid = paddle.affine_grid(t(theta), [2, 3, 5, 5])
        out = paddle.grid_sample(t(x), grid).numpy()
        np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-5)

    def test_grid_sample_nearest_and_zeros_padding(self):
        x = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
        grid = np.array([[[[-1, -1], [3.0, 3.0]]]], np.float32)  # corner + out of bounds
        out = paddle.grid_sample(t(x), t(grid), mode="nearest").numpy()
        assert out[0, 0, 0, 0] == 0.0 and out[0, 0, 0, 1] == 0.0

    def test_nms_suppresses_overlaps(self):
        boxes = np.array(
            [[0, 0, 10, 10], [1, 1, 10.5, 10.5], [20, 20, 30, 30], [21, 21, 29, 29]],
            np.float32,
        )
        keep = paddle.nms(t(boxes), 0.5).numpy()
        assert keep[0] == 0 and keep[1] == 2 and (keep[2:] == -1).all()

    def test_nms_scores_sorts_internally_and_maps_back(self):
        """Reference ``paddle.vision.ops.nms(boxes, iou_threshold, scores)``:
        unsorted boxes + scores — nms runs in descending-score order and the
        returned indices point into the ORIGINAL box order."""
        boxes = np.array(
            [[1, 1, 10.5, 10.5], [20, 20, 30, 30], [0, 0, 10, 10], [21, 21, 29, 29]],
            np.float32,
        )
        scores = np.array([0.6, 0.9, 0.8, 0.3], np.float32)
        keep = paddle.nms(t(boxes), 0.5, scores=t(scores)).numpy()
        # score order: box1 (.9), box2 (.8), box0 (.6, IoU>0.5 with box2 ->
        # suppressed), box3 (IoU>0.5 with box1 -> suppressed)
        assert keep[0] == 1 and keep[1] == 2 and (keep[2:] == -1).all()

    def test_nms_without_scores_unchanged(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 10.5, 10.5]], np.float32)
        keep = paddle.nms(t(boxes), 0.5).numpy()
        assert keep[0] == 0 and keep[1] == -1

    def test_matrix_nms_decays_overlaps(self):
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10], [50, 50, 60, 60]], np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        out, order = paddle.matrix_nms(t(boxes), t(scores))
        o = out.numpy()
        assert o[0] == pytest.approx(0.9)  # top box undamped
        assert o[1] < 0.1  # duplicate heavily decayed
        assert o[2] == pytest.approx(0.7, abs=1e-5)  # disjoint box untouched

    def test_roi_align_constant_region(self):
        x = np.full((1, 2, 8, 8), 3.0, np.float32)
        out = paddle.roi_align(t(x), t(np.array([[1, 1, 5, 5]], np.float32)), output_size=2)
        np.testing.assert_allclose(out.numpy(), np.full((1, 2, 2, 2), 3.0), rtol=1e-5)

    def test_roi_pool_picks_max(self):
        x = np.zeros((1, 1, 8, 8), np.float32)
        x[0, 0, 2, 2] = 5.0
        out = paddle.roi_pool(t(x), t(np.array([[0, 0, 7, 7]], np.float32)), output_size=1)
        assert float(out.numpy().max()) == 5.0

    def test_box_coder_roundtrip(self):
        priors = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], np.float32)
        targets = np.array([[1, 1, 9, 9], [4, 6, 16, 14]], np.float32)
        enc = paddle.box_coder(t(priors), None, t(targets), "encode_center_size")
        dec = paddle.box_coder(t(priors), None, enc, "decode_center_size", axis=0)
        np.testing.assert_allclose(
            dec.numpy()[np.arange(2), np.arange(2)], targets, rtol=1e-4, atol=1e-4
        )

    def test_unpool_inverts_maxpool_positions(self):
        x = np.zeros((1, 1, 2, 2), np.float32)
        x[0, 0] = [[5.0, 1.0], [2.0, 3.0]]
        idx = np.array([[[[0, 3], [10, 15]]]], np.int64)  # flat positions in 4x4
        out = paddle.unpool(t(x), t(idx), kernel_size=2, stride=2).numpy()
        assert out[0, 0, 0, 0] == 5.0 and out[0, 0, 0, 3] == 1.0
        assert out[0, 0, 2, 2] == 2.0 and out[0, 0, 3, 3] == 3.0

    def test_temporal_shift_moves_channels(self):
        x = rng.normal(size=(4, 8, 2, 2)).astype(np.float32)
        out = paddle.temporal_shift(t(x), seg_num=2, shift_ratio=0.25).numpy()
        x5 = x.reshape(2, 2, 8, 2, 2)
        o5 = out.reshape(2, 2, 8, 2, 2)
        np.testing.assert_allclose(o5[:, 0, :2], x5[:, 1, :2])  # shifted back
        np.testing.assert_allclose(o5[:, 1, 2:4], x5[:, 0, 2:4])  # shifted forward
        np.testing.assert_allclose(o5[:, :, 4:], x5[:, :, 4:])  # untouched

    def test_prior_box_shapes(self):
        feat = t(np.zeros((1, 8, 4, 4), np.float32))
        img = t(np.zeros((1, 3, 32, 32), np.float32))
        boxes, var = paddle.prior_box(feat, img, min_sizes=[8.0], aspect_ratios=[2.0], clip=True)
        assert list(boxes.shape) == [4, 4, 2, 4]
        b = boxes.numpy()
        assert (b >= 0).all() and (b <= 1).all()


class TestMiscParity:
    def test_clip_by_norm(self):
        x = np.full((4,), 3.0, np.float32)  # norm 6
        out = paddle.clip_by_norm(t(x), 3.0).numpy()
        np.testing.assert_allclose(np.linalg.norm(out), 3.0, rtol=1e-5)
        same = paddle.clip_by_norm(t(x), 100.0).numpy()
        np.testing.assert_allclose(same, x)

    def test_add_position_encoding(self):
        x = np.zeros((1, 4, 8), np.float32)
        out = paddle.add_position_encoding(t(x), alpha=1.0, beta=1.0).numpy()
        np.testing.assert_allclose(out[0, 0, 4], 1.0, rtol=1e-5)  # cos(0)

    def test_spectral_norm_unit_sigma(self):
        w = rng.normal(size=(6, 4)).astype(np.float32)
        wn = paddle.spectral_norm(t(w), n_power_iterations=30).numpy()
        assert abs(np.linalg.svd(wn)[1][0] - 1.0) < 1e-3

    def test_random_families(self):
        d = paddle.dirichlet(t(np.full((4, 3), 2.0, np.float32))).numpy()
        np.testing.assert_allclose(d.sum(-1), np.ones(4), rtol=1e-5)
        g = paddle.standard_gamma(t(np.full((1000,), 2.0, np.float32))).numpy()
        assert abs(g.mean() - 2.0) < 0.3
        tr = paddle.truncated_gaussian_random((500,), a=-1.0, b=1.0).numpy()
        assert tr.min() >= -1.0 and tr.max() <= 1.0
        b = paddle.binomial(t(np.full((200,), 20.0, np.float32)), t(np.full((200,), 0.25, np.float32))).numpy()
        assert abs(b.mean() - 5.0) < 1.0


class TestNewOptimizers:
    @pytest.mark.parametrize("name", ["Ftrl", "DecayedAdagrad", "Dpsgd"])
    def test_decreases_loss(self, name):
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt

        paddle.seed(0)
        lin = paddle.nn.Linear(4, 1)
        kwargs = {"sigma": 0.0} if name == "Dpsgd" else {}
        o = getattr(opt, name)(learning_rate=0.05, parameters=lin.parameters(), **kwargs)
        x = t(rng.normal(size=(16, 4)).astype(np.float32))
        y = t(rng.normal(size=(16, 1)).astype(np.float32))
        losses = []
        for _ in range(12):
            loss = F.mse_loss(lin(x), y)
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], f"{name} did not reduce loss: {losses}"


class TestInt8Primitives:
    def test_weight_quantize_roundtrip(self):
        import paddle_tpu.quantization as q

        w = rng.normal(size=(32, 16)).astype(np.float32)
        qw, sc = q.weight_quantize(t(w))
        assert qw.numpy().dtype == np.int8
        wd = q.weight_dequantize(qw, sc).numpy()
        assert np.abs(wd - w).max() < np.abs(w).max() / 100

    def test_weight_only_and_llm_int8_linear(self):
        import paddle_tpu.quantization as q

        w = rng.normal(size=(32, 16)).astype(np.float32)
        x = rng.normal(size=(4, 32)).astype(np.float32)
        b = rng.normal(size=(16,)).astype(np.float32)
        qw, sc = q.weight_quantize(t(w))
        ref = x @ w + b
        wol = q.weight_only_linear(t(x), qw, t(b), sc).numpy()
        i8 = q.llm_int8_linear(t(x), qw, t(b), sc).numpy()
        scale = np.abs(ref).max()
        assert np.abs(wol - ref).max() / scale < 0.02
        assert np.abs(i8 - ref).max() / scale < 0.03

    def test_llm_int8_uses_int32_accumulation(self):
        """The int8 path must contract in int8 (dot_general with int32
        accumulator), not silently upcast — check the jaxpr."""
        import jax
        import jax.numpy as jnp
        import paddle_tpu.quantization as q

        w = rng.normal(size=(8, 4)).astype(np.float32)
        qw, sc = q.weight_quantize(t(w))

        def f(xa):
            return q.llm_int8_linear(paddle.to_tensor(xa), qw, weight_scale=sc)._data

        jaxpr = str(jax.make_jaxpr(f)(jnp.ones((2, 8), jnp.float32)))
        assert "preferred_element_type=int32" in jaxpr


class TestSparseAdditions:
    def _coo(self):
        d = np.array([[1.0, 0, 2], [0, 3, 0]], np.float32)
        return d, paddle.to_tensor(d).to_sparse_coo()

    def test_unary_and_scale(self):
        import paddle_tpu.sparse as sp

        d, x = self._coo()
        np.testing.assert_allclose(sp.scale(x, 2.0).to_dense().numpy(), d * 2)
        np.testing.assert_allclose(sp.divide_scalar(x, 2.0).to_dense().numpy(), d / 2)
        assert sp.relu6(sp.scale(x, 5.0)).to_dense().numpy().max() == 6.0
        assert not sp.isnan(x).to_dense().numpy().any()

    def test_matvec_and_addmm(self):
        import paddle_tpu.sparse as sp

        d, x = self._coo()
        v = rng.normal(size=(3,)).astype(np.float32)
        np.testing.assert_allclose(sp.mv(x, t(v)).numpy(), d @ v, rtol=1e-5)
        dense = rng.normal(size=(3, 2)).astype(np.float32)
        inp = rng.normal(size=(2, 2)).astype(np.float32)
        np.testing.assert_allclose(
            sp.addmm(t(inp), x, t(dense), beta=0.5, alpha=2.0).numpy(),
            0.5 * inp + 2.0 * (d @ dense), rtol=1e-5,
        )

    def test_structure_ops(self):
        import paddle_tpu.sparse as sp

        d, x = self._coo()
        np.testing.assert_allclose(sp.reshape(x, [3, 2]).to_dense().numpy(), d.reshape(3, 2))
        np.testing.assert_allclose(
            sp.slice(x, [1], [1], [3]).to_dense().numpy(), d[:, 1:3]
        )
        np.testing.assert_allclose(
            sp.mask_as(t(np.full((2, 3), 7.0, np.float32)), x).to_dense().numpy(),
            7.0 * (d != 0),
        )

    def test_softmax_rows(self):
        import paddle_tpu.sparse as sp

        d, x = self._coo()
        sm = sp.softmax(x).to_dense().numpy()
        np.testing.assert_allclose(sm.sum(-1), np.ones(2), rtol=1e-5)
        assert sm[0, 1] == 0.0  # zeros stay zero


class TestPackedFlashWrappers:
    def test_qkvpacked_matches_unpacked(self):
        import paddle_tpu.nn.functional as F

        qkv = rng.normal(size=(2, 8, 3, 2, 4)).astype(np.float32)
        out_p, _ = F.flash_attn_qkvpacked(t(qkv), causal=True)
        out_u, _ = F.flash_attention(
            t(qkv[:, :, 0]), t(qkv[:, :, 1]), t(qkv[:, :, 2]), causal=True
        )
        np.testing.assert_allclose(out_p.numpy(), out_u.numpy(), rtol=1e-5, atol=1e-6)

    def test_fused_softmax_masks(self):
        import paddle_tpu.incubate.nn.functional as IF

        x = rng.normal(size=(1, 2, 4, 4)).astype(np.float32)
        up = IF.fused_softmax_mask_upper_triangle(t(x)).numpy()
        assert up[0, 0, 0, 1] == 0.0 and abs(up[0, 0, 0, 0] - 1.0) < 1e-6
        mask = np.zeros((1, 1, 4, 4), np.float32)
        sm = IF.fused_softmax_mask(t(x), t(mask)).numpy()
        np.testing.assert_allclose(sm.sum(-1), np.ones((1, 2, 4)), rtol=1e-5)


class TestReviewFixesR5:
    def test_fill_diagonal_non_square(self):
        out = paddle.fill_diagonal(t(np.zeros((2, 5), np.float32)), 1.0, offset=2).numpy()
        assert out[0, 2] == 1.0 and out[1, 3] == 1.0 and out.sum() == 2.0
        out = paddle.fill_diagonal(t(np.zeros((5, 2), np.float32)), 1.0, offset=-2).numpy()
        assert out[2, 0] == 1.0 and out[3, 1] == 1.0 and out.sum() == 2.0

    def test_viterbi_honors_lengths(self):
        B, T, N = 2, 6, 3
        pot = rng.normal(size=(B, T, N)).astype(np.float32)
        lens = np.array([3, 6], np.int32)
        s_pad, p_pad = paddle.viterbi_decode(
            t(pot), t(np.zeros((N, N), np.float32)), lengths=t(lens),
            include_bos_eos_tag=False,
        )
        # sequence 0 truncated at 3 must match decoding its 3-step slice alone
        s_short, p_short = paddle.viterbi_decode(
            t(pot[:1, :3]), t(np.zeros((N, N), np.float32)),
            include_bos_eos_tag=False,
        )
        np.testing.assert_allclose(float(s_pad.numpy()[0]), float(s_short.numpy()[0]), rtol=1e-5)
        np.testing.assert_array_equal(p_pad.numpy()[0, :3], p_short.numpy()[0])

    def test_zero_bubble_executor_rejects_small_M(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4, num_heads=2, max_position=32)
        pipe = build_gpt_pipeline(cfg, num_stages=4)
        mesh = dist.ProcessMesh(shape=[4], dim_names=["pp"])
        with pytest.raises(ValueError, match="zero_bubble"):
            pipe.build_spmd_executor(mesh, num_microbatches=2, schedule="zero_bubble")
