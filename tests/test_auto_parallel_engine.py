"""Static auto-parallel Engine (reference ``auto_parallel/static/engine.py:96``):
Strategy config tree, fit/evaluate/predict on GPT over the 8-device CPU mesh,
strategy-driven amp/recompute/sharding/gradient-merge, save/load."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.auto_parallel import Engine, Strategy
from paddle_tpu.io import Dataset
from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining, gpt_shard_fn

VOCAB = 64


class LMDataset(Dataset):
    def __init__(self, n=16, seq=16):
        rng = np.random.default_rng(0)
        self.ids = rng.integers(0, VOCAB, (n, seq)).astype(np.int32)

    def __len__(self):
        return len(self.ids)

    def __getitem__(self, i):
        return self.ids[i], self.ids[i].astype(np.int64)


def lm_loss(logits, labels):
    return F.cross_entropy(
        logits.astype("float32").reshape([-1, VOCAB]), labels.reshape([-1])
    )


def _engine(strategy=None, lr=1e-3):
    paddle.seed(0)
    cfg = GPTConfig.tiny(vocab=VOCAB)
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=lr, parameters=model.parameters())
    return Engine(model, loss=lm_loss, optimizer=opt, strategy=strategy), model


def test_strategy_defaults_and_overrides():
    s = Strategy()
    assert s.sharding.enable is False and s.sharding.stage == 1
    assert s.amp.dtype == "bfloat16"
    s2 = Strategy({"sharding": {"enable": True, "stage": 2}, "amp": {"enable": True}})
    assert s2.sharding.enable and s2.sharding.stage == 2 and s2.amp.enable
    d = s2.to_dict()
    assert d["sharding"]["stage"] == 2


def test_fit_evaluate_predict_on_mesh():
    n = 8
    mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"], process_ids=list(range(n)))
    engine, model = _engine()
    engine.prepare(mesh=mesh, shard_fn=gpt_shard_fn)
    history = engine.fit(LMDataset(), batch_size=4, epochs=2)
    losses = history["loss"]
    assert len(losses) == 8  # 16/4 per epoch, 2 epochs, drop_last
    assert all(np.isfinite(l) for l in losses)
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), "Engine.fit did not learn"
    # params keep their mesh shardings after training
    w = model.gpt.embeddings.word_embeddings.weight
    assert getattr(w._data, "sharding", None) is not None

    result = engine.evaluate(LMDataset(), batch_size=4)
    assert np.isfinite(result["eval_loss"])
    outs = engine.predict(LMDataset(), batch_size=4, steps=2)
    assert len(outs) == 2


def test_fit_with_strategy_amp_recompute_sharding():
    strategy = Strategy(
        {
            "amp": {"enable": True, "level": "o2", "dtype": "bfloat16"},
            "recompute": {"enable": True},
            "sharding": {"enable": True, "stage": 2},
        }
    )
    engine, model = _engine(strategy=strategy)
    mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"], process_ids=list(range(8)))
    engine.prepare(mesh=mesh)
    history = engine.fit(LMDataset(), batch_size=8, epochs=2)
    assert all(np.isfinite(l) for l in history["loss"])
    # O2: params were cast to bf16, optimizer keeps fp32 masters
    assert str(model.gpt.embeddings.word_embeddings.weight.dtype) in ("bfloat16", "jax.numpy.bfloat16")


def test_gradient_merge_accumulates():
    strategy = Strategy({"gradient_merge": {"enable": True, "k_steps": 2}})
    engine, model = _engine(strategy=strategy)
    engine.prepare()
    history = engine.fit(LMDataset(), batch_size=4, epochs=1)
    assert len(history["loss"]) == 4
    assert all(np.isfinite(l) for l in history["loss"])


def test_save_load_roundtrip(tmp_path):
    engine, model = _engine()
    engine.prepare()
    engine.fit(LMDataset(), batch_size=8, epochs=1)
    path = str(tmp_path / "ckpt")
    engine.save(path)

    engine2, model2 = _engine()
    engine2.prepare()
    engine2.load(path)
    w1 = model.gpt.embeddings.word_embeddings.weight.numpy()
    w2 = model2.gpt.embeddings.word_embeddings.weight.numpy()
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))


def test_predict_honors_test_sample_split():
    """(features, label) datasets: predict must feed only sample[:split] to
    the model (ADVICE r4 — the label used to ride along as an extra arg)."""
    engine, _ = _engine()
    engine.prepare()
    outs = engine.predict(LMDataset(), test_sample_split=1, batch_size=4, steps=2)
    assert len(outs) == 2
    assert list(outs[0].shape) == [4, 16, VOCAB]


def test_gradient_merge_partial_tail_applies_update():
    """Total steps not a multiple of k: the tail window is applied (with a
    warning) at end of fit, and the accumulation state is left clean."""
    strategy = Strategy({"gradient_merge": {"enable": True, "k_steps": 2}})
    engine, model = _engine(strategy=strategy)
    engine.prepare()
    w0 = np.asarray(model.gpt.embeddings.word_embeddings.weight.numpy()).copy()
    with pytest.warns(UserWarning, match="partial window"):
        engine.fit(LMDataset(), batch_size=4, epochs=1, steps_per_epoch=1)
    w1 = np.asarray(model.gpt.embeddings.word_embeddings.weight.numpy())
    assert np.abs(w1 - w0).max() > 0, "tail micro-batch grads were dropped"
    assert engine._merge_bufs is None and engine._merge_count == 0


class TestShardDataloader:
    """dist.shard_dataloader (reference auto_parallel/api.py:2952): global
    batches come out as DistTensors sharded over the dp axis."""

    def _loader(self):
        from paddle_tpu.io import DataLoader

        return DataLoader(LMDataset(), batch_size=8, shuffle=False, drop_last=True)

    def test_batches_are_dp_sharded(self):
        mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"], process_ids=list(range(8)))
        sharded = dist.shard_dataloader(self._loader(), mesh, shard_dims="dp")
        assert len(sharded) == 2
        ids, labels = next(iter(sharded))
        assert dist.get_placements(ids) is not None
        # batch dim sharded over dp, replicated over mp
        from paddle_tpu.distributed.placements import Replicate, Shard

        p = dist.get_placements(ids)
        assert isinstance(p[0], Shard) and p[0].dim == 0
        assert isinstance(p[1], Replicate)
        assert list(ids.shape) == [8, 16]  # global view preserved

    def test_trains_through_engine_style_step(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining, gpt_shard_fn

        paddle.seed(0)
        mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"], process_ids=list(range(8)))
        cfg = GPTConfig.tiny(vocab=VOCAB)
        model = GPTForPretraining(cfg)
        dist.shard_layer(model, mesh, gpt_shard_fn)
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
        sharded = dist.shard_dataloader(self._loader(), mesh, shard_dims="dp")

        @paddle.jit.to_static
        def step(model, opt, ids, labels):
            loss = lm_loss(model(ids), labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        losses = [float(step(model, opt, ids, labels)) for ids, labels in sharded]
        assert all(np.isfinite(l) for l in losses)

    def test_dict_batches_and_presplit_rejected(self):
        mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"], process_ids=list(range(8)))

        class DictLoader:
            def __iter__(self):
                yield {"x": np.zeros((8, 4), np.float32), "y": np.zeros((8,), np.int64)}

            def __len__(self):
                return 1

        out = next(iter(dist.shard_dataloader(DictLoader(), mesh, shard_dims=0)))
        assert set(out) == {"x", "y"}
        with pytest.raises(NotImplementedError, match="single-controller"):
            dist.shard_dataloader(DictLoader(), mesh, is_dataset_splitted=True)
        with pytest.raises(NotImplementedError, match="ONE mesh"):
            dist.shard_dataloader(DictLoader(), [mesh, mesh])
        with pytest.raises(NotImplementedError, match="input_keys"):
            dist.shard_dataloader(DictLoader(), mesh, input_keys=["x", "y"])

    def test_namedtuple_batches(self):
        import collections

        Batch = collections.namedtuple("Batch", ["ids", "labels"])
        mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"], process_ids=list(range(8)))

        class NTLoader:
            def __iter__(self):
                yield Batch(np.zeros((8, 4), np.float32), np.zeros((8,), np.int64))

            def __len__(self):
                return 1

        out = next(iter(dist.shard_dataloader(NTLoader(), mesh, shard_dims="dp")))
        assert isinstance(out, Batch) and list(out.ids.shape) == [8, 4]
