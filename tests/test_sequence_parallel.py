"""Sequence-parallel op/layer tests (8-device CPU mesh).

Strategy follows the reference's hybrid_parallel SP tests: SP layers must be
numerically identical to their serial counterparts.
"""

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (
    AllGatherOp,
    ColumnSequenceParallelLinear,
    GatherOp,
    ReduceScatterOp,
    RowSequenceParallelLinear,
    ScatterOp,
)


@pytest.fixture()
def mp_env():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    yield fleet.get_hybrid_communicate_group()


def _set_weight(p, value):
    import jax

    with paddle_tpu.no_grad():
        sharding = getattr(p._data, "sharding", None)
        t = paddle_tpu.to_tensor(value)
        p._data = jax.device_put(t._data, sharding) if sharding is not None else t._data


def test_scatter_gather_roundtrip(mp_env):
    x = paddle_tpu.randn([8, 4, 16])  # [s, b, h]
    s = ScatterOp.apply(x)
    g = GatherOp.apply(s)
    np.testing.assert_allclose(g.numpy(), x.numpy(), rtol=1e-6)


def test_sp_column_row_matches_serial(mp_env):
    np.random.seed(3)
    S, B, H, FF = 8, 2, 16, 32
    x_np = np.random.randn(S, B, H).astype(np.float32)
    w1 = (np.random.randn(H, FF) * 0.1).astype(np.float32)
    w2 = (np.random.randn(FF, H) * 0.1).astype(np.float32)

    col = ColumnSequenceParallelLinear(H, FF, has_bias=False)
    row = RowSequenceParallelLinear(FF, H, has_bias=False)
    _set_weight(col.weight, w1)
    _set_weight(row.weight, w2)

    lin1 = paddle_tpu.nn.Linear(H, FF)
    lin2 = paddle_tpu.nn.Linear(FF, H)
    _set_weight(lin1.weight, w1)
    _set_weight(lin2.weight, w2)
    lin1.bias = None
    lin2.bias = None

    x1 = paddle_tpu.to_tensor(x_np, stop_gradient=False)
    x2 = paddle_tpu.to_tensor(x_np, stop_gradient=False)

    # SP region: input sequence-sharded
    xs = ScatterOp.apply(x1)
    y_par = GatherOp.apply(row(col(xs)))
    y_ser = lin2(lin1(x2))
    np.testing.assert_allclose(y_par.numpy(), y_ser.numpy(), rtol=1e-5, atol=1e-5)

    y_par.sum().backward()
    y_ser.sum().backward()
    np.testing.assert_allclose(col.weight.grad.numpy(), lin1.weight.grad.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(row.weight.grad.numpy(), lin2.weight.grad.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(x1.grad.numpy(), x2.grad.numpy(), rtol=1e-5, atol=1e-5)


def test_sp_ops_in_shard_map_region(mp_env):
    """Explicit-collective path: run the SP scatter→gather pipeline inside a
    shard_map region over the mp axis and check the roundtrip + grad dual."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.sharding import NamedSharding

    mesh = mp_env.get_parallel_mesh().jax_mesh()
    x = np.random.randn(8, 2, 16).astype(np.float32)

    from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (
        _all_gather_op,
        _scatter_op,
    )

    def body(v):
        s = _scatter_op.raw_fn(v, axis="mp")
        return _all_gather_op.raw_fn(s, axis="mp")

    f = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
        )
    )
    out = f(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-6)


def test_segment_parallel_wrapper():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1, "pp_degree": 1, "sep_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    from paddle_tpu.distributed.fleet.meta_parallel import SegmentParallel

    model = paddle_tpu.nn.Linear(16, 16)
    sp_model = SegmentParallel(model, seq_axis=1)
    x = paddle_tpu.randn([2, 8, 16])
    y = sp_model(x)
    assert y.shape == [2, 8, 16]
    # input got seq-sharded over 'sep'
