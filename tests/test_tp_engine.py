"""Tensor-parallel serving: the sharded-engine invariants on a CPU ``tp=2``
mesh (the 8-device virtual CPU split from conftest).

The contract under test (``distributed/tp.py`` + engine ``tp=``):

- ``tp=2`` greedy outputs are BYTE-IDENTICAL to ``tp=1`` across a mixed
  staggered workload — with the prefix cache and speculative decoding riding
  along unchanged (host-side state is replicated-by-construction);
- exactly ONE compile per engine under the mesh (sharding lives in input
  placements, never in shapes);
- the KV pool partition is balanced per shard — every device holds the same
  logical blocks over an equal head slice — and the host-side refcount /
  accounting churn property holds at every step boundary;
- recovery under the mesh reallocates SHARDED pools and replays to identical
  streams through the same compiled program.
"""

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.testing import faults

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="tp tests need >= 2 devices"
)


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _mixed_workload(cfg, seed=0):
    rng = np.random.default_rng(seed)
    specs = [(5, 6), (7, 4), (3, 9), (6, 2), (2, 7), (8, 5), (4, 3)]
    prompts = [
        rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32) for n, _ in specs
    ]
    return prompts, [t for _, t in specs]


def _run_engine(prompts, budgets, seed=0, **kw):
    m, _ = _model(seed)
    eng = ContinuousBatchingEngine(
        m, max_slots=3, block_size=4, prompt_bucket=16, **kw
    )
    rids = [
        eng.add_request(p, max_new_tokens=t) for p, t in zip(prompts, budgets)
    ]
    out = eng.run()
    return eng, [out[r].tokens() for r in rids]


# the shared engine-wide accounting invariant: one HOST-side allocator
# steers every shard, so this holding under the mesh is exactly the 'host
# state replicated-by-construction' claim
from conftest import assert_engine_pool_exact as _assert_pool_exact


def _assert_shards_balanced(eng, tp):
    """Device truth of the pool partition: every mesh device holds one equal
    head slice of every layer's caches — same logical blocks, same block
    size, KVH/tp heads."""
    nb, kvh, bs, hd = eng._cache_shape
    for kc, vc in eng._caches:
        for arr in (kc, vc):
            shards = {s.device.id: s.data.shape for s in arr.addressable_shards}
            assert len(shards) == tp, shards
            for shape in shards.values():
                assert tuple(shape) == (nb, kvh // tp, bs, hd), shards
    st = eng.tp_stats()
    assert st["tp_degree"] == tp and st["balanced"], st
    assert st["per_shard_cache_shape"] == [nb, kvh // tp, bs, hd], st


class TestTpValidation:
    def test_tp_must_divide_kv_heads(self):
        m, _ = _model()
        with pytest.raises(ValueError, match="KV heads"):
            # tiny config has 2 KV heads; 3 cannot shard them
            ContinuousBatchingEngine(m, max_slots=2, block_size=4, tp=3)

    def test_tp_below_one_rejected(self):
        # 0/negative must not silently take the single-chip path: tp_degree
        # feeds capacity weighting in health snapshots and bench records
        m, _ = _model()
        with pytest.raises(ValueError, match=">= 1"):
            ContinuousBatchingEngine(m, max_slots=2, block_size=4, tp=0)

    def test_tp_needs_devices(self):
        from paddle_tpu.distributed.tp import build_tp_mesh

        with pytest.raises(ValueError, match="devices"):
            build_tp_mesh(len(jax.devices()) + 2)

    def test_tp1_is_the_unsharded_engine(self):
        m, _ = _model()
        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4)
        assert eng.tp_degree == 1
        assert eng._tp_mesh is None
        assert eng.tp_stats() == {"tp_degree": 1}

    def test_flag_default_reaches_engine(self):
        flags = paddle.get_flags(["FLAGS_engine_tp_degree"])
        assert flags["FLAGS_engine_tp_degree"] == 1


class TestTpByteIdentical:
    def test_mixed_workload_byte_identical_one_compile(self):
        """The acceptance test: staggered admits through 3 slots, varied
        prompt lengths and budgets — tp=2 tokens byte-equal tp=1, each
        engine compiling its step exactly once."""
        _, cfg = _model()
        prompts, budgets = _mixed_workload(cfg)
        e1, toks1 = _run_engine(prompts, budgets)
        e2, toks2 = _run_engine(prompts, budgets, tp=2)
        assert e1.stats["step_traces"] == 1, e1.stats
        assert e2.stats["step_traces"] == 1, e2.stats
        if hasattr(e2._step_fn, "_cache_size"):
            assert e2._step_fn._cache_size() == 1
        for a, b in zip(toks1, toks2):
            np.testing.assert_array_equal(a, b)
        _assert_shards_balanced(e2, 2)

    def test_spec_decode_rides_the_sharded_step(self):
        """Speculation is host-side draft + in-dispatch verification — pure
        data to the sharded program: byte-identical on the mesh, still one
        compile, same acceptance bookkeeping."""
        _, cfg = _model()
        prompts, budgets = _mixed_workload(cfg, seed=5)
        e1, toks1 = _run_engine(prompts, budgets, spec_decode=True)
        e2, toks2 = _run_engine(prompts, budgets, tp=2, spec_decode=True)
        for a, b in zip(toks1, toks2):
            np.testing.assert_array_equal(a, b)
        assert e2.stats["step_traces"] == 1
        assert e1.spec_decode_stats() == e2.spec_decode_stats()

    def test_prefix_cache_shared_by_all_shards(self):
        """One logical block id maps the shared prefix in EVERY shard's pool
        partition, so the prefix cache needs no per-shard state: warm hits
        on the mesh, byte-identical to tp=1."""
        _, cfg = _model()
        rng = np.random.default_rng(7)
        shared = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
        tails = [
            rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)
            for _ in range(3)
        ]
        prompts = [np.concatenate([shared, t]) for t in tails]

        def run_warm(tp):
            # cold request first so the shared prefix is REGISTERED before
            # the warm pair matches it (same-boundary admits are all cold)
            m, _ = _model()
            eng = ContinuousBatchingEngine(
                m, max_slots=3, block_size=4, prompt_bucket=16, tp=tp
            )
            r0 = eng.add_request(prompts[0], max_new_tokens=5)
            out = dict(eng.run())
            r1 = eng.add_request(prompts[1], max_new_tokens=5)
            r2 = eng.add_request(prompts[2], max_new_tokens=5)
            out.update(eng.run())
            return eng, [out[r].tokens() for r in (r0, r1, r2)]

        e1, toks1 = run_warm(1)
        e2, toks2 = run_warm(2)
        for a, b in zip(toks1, toks2):
            np.testing.assert_array_equal(a, b)
        stats = e2.prefix_cache_stats()
        assert stats["enabled"] and stats["hits"] > 0, stats
        assert e2.stats["prompt_tokens_reused"] > 0
        assert e2.stats["step_traces"] == 1


class TestTpShardAccounting:
    def test_churn_property_per_step(self):
        """Step the sharded engine manually through a staggered workload:
        after EVERY boundary the host accounting is exact AND the device
        shards stay balanced (the pool partition never skews)."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(3)
        eng = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, prompt_bucket=16, tp=2
        )
        pending = [
            (rng.integers(0, cfg.vocab_size, (int(rng.integers(2, 9)),)).astype(np.int32),
             int(rng.integers(2, 7)))
            for _ in range(6)
        ]
        for p, t in pending[:3]:
            eng.add_request(p, max_new_tokens=t)
        steps = 0
        while eng.has_work():
            eng.step()
            steps += 1
            if steps == 2:
                for p, t in pending[3:]:
                    eng.add_request(p, max_new_tokens=t)
            _assert_pool_exact(eng)
            _assert_shards_balanced(eng, 2)
            assert steps < 200
        assert eng.stats["step_traces"] == 1


class TestTpRecovery:
    def test_recovery_reallocates_sharded_pools_and_replays(self):
        """An injected dispatch loss mid-workload: recover() rebuilds the
        pools COMMITTED on the same mesh partition, replays from host truth,
        and the streams come out byte-identical to the unfaulted sharded run
        — with zero extra compiles."""
        _, cfg = _model()
        prompts, budgets = _mixed_workload(cfg, seed=11)
        e_ok, toks_ok = _run_engine(prompts, budgets, seed=2, tp=2)
        m, _ = _model(seed=2)
        eng = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, prompt_bucket=16, tp=2
        )
        rids = [
            eng.add_request(p, max_new_tokens=t)
            for p, t in zip(prompts, budgets)
        ]
        with faults.inject(faults.FaultPlan.parse("engine.decode:3:InjectedFault")):
            out = eng.run()
        assert eng.stats["recoveries"] == 1
        assert eng.stats["step_traces"] == 1, eng.stats
        for rid, ref in zip(rids, toks_ok):
            np.testing.assert_array_equal(out[rid].tokens(), ref)
        _assert_shards_balanced(eng, 2)
        _assert_pool_exact(eng)


class TestTpServingHealth:
    def test_health_unit_is_the_shard_group(self):
        """The replica's health unit becomes the shard group: tp_degree in
        the router-facing health snapshot, the /healthz payload, and on the
        Replica itself."""
        from paddle_tpu.serving import ServingConfig, ServingFrontend
        from paddle_tpu.serving.cluster import Replica

        m, _ = _model()
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, prompt_bucket=16, tp=2
        )
        fe = ServingFrontend(eng, ServingConfig(max_queue=4))
        health = fe.health_snapshot()
        assert health["tp_degree"] == 2
        snap = fe.snapshot()
        assert snap["tensor_parallel"]["tp_degree"] == 2
        assert snap["tensor_parallel"]["balanced"]
        assert Replica("r0", fe).tp_degree == 2

    def test_tp_stats_survives_lost_buffers(self):
        """On a donating backend a failed dispatch consumes the pools; the
        /healthz path must report the lost buffers, never raise (probing a
        broken replica is exactly when observability matters)."""
        m, _ = _model()
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, prompt_bucket=16, tp=2
        )
        for kc, vc in eng._caches:
            kc.delete()
            vc.delete()
        st = eng.tp_stats()
        assert st["buffers"] == "lost" and st["tp_degree"] == 2, st
        assert st["balanced"] is None


class TestTpShardMapWrapper:
    def test_sharded_kernel_matches_gather_reference(self):
        """The shard_map wrapping of the Pallas mixed ragged kernel (the TPU
        path), pinned off-TPU via interpret mode: per-shard head slices over
        per-shard pool partitions reassemble to the XLA gather reference."""
        import jax.numpy as jnp

        from paddle_tpu.distributed.tp import build_tp_mesh
        from paddle_tpu.incubate.nn.functional.block_attention import (
            _gather_chunk_attend,
            _tp_sharded_flash_chunk,
        )

        rng = np.random.default_rng(13)
        B, C, HQ, HKV, D, NB, BS, MBS = 3, 4, 4, 2, 16, 24, 4, 8
        q = jnp.asarray(rng.normal(size=(B, C, HQ, D)).astype(np.float32))
        kc = jnp.asarray(rng.normal(size=(NB, HKV, BS, D)).astype(np.float32))
        vc = jnp.asarray(rng.normal(size=(NB, HKV, BS, D)).astype(np.float32))
        tables = jnp.asarray(
            rng.permutation(NB)[: B * MBS].reshape(B, MBS).astype(np.int32)
        )
        lens = jnp.asarray(np.array([5, 0, 9], np.int32))
        qlens = jnp.asarray(np.array([1, 0, 4], np.int32))  # decode + idle + chunk
        mesh = build_tp_mesh(2)
        out_tp = _tp_sharded_flash_chunk(
            q, kc, vc, tables, lens, qlens, 0.25, mesh, interpret=True
        )
        out_ref = _gather_chunk_attend(q, kc, vc, tables, lens, qlens, 0.25)
        np.testing.assert_allclose(
            np.asarray(out_tp), np.asarray(out_ref), rtol=1e-5, atol=1e-5
        )
        # rows past q_lens are exact zeros on both paths
        assert not np.any(np.asarray(out_tp)[1])


def test_bench_tp_decode_cpu_smoke():
    """Tier-1 smoke of the guarded bench: the machinery runs on the virtual
    CPU mesh, the honesty fields hold (byte-identical streams, one compile
    per engine), and the schema carries tp_degree + per-chip/aggregate
    numbers. No throughput assertion: on CPU the all-reduce is a memcpy tax
    with no parallel compute behind it — the speedup claim is a TPU
    measurement."""
    import bench

    rec = bench._bench_tp_decode(paddle, "cpu")
    assert "error" not in rec, rec
    assert "skipped" not in rec, rec
    assert rec["tp_degree"] == 2
    assert rec["byte_identical_vs_tp1"] is True
    assert rec["compiles_tp1_engine"] == 1
    assert rec["compiles_tp_engine"] == 1
    assert rec["watchdog_step_compiles"] == 2
    # both fields are independently rounded to 2 decimals in the record
    assert rec["per_chip_tokens_per_sec"] == pytest.approx(
        rec["value"] / rec["tp_degree"], abs=0.02
    )
    # analytic vs measured comm share, each labeled with its provenance
    assert rec["comm_share_analytic"]["method"] == "analytic_estimate"
    assert 0.0 <= rec["comm_share_analytic"]["value"] <= 1.0
    assert rec["comm_share_measured"]["status"] == "measured"
    assert 0.0 <= rec["comm_share_measured"]["value"] <= 1.0
    assert rec["host_bubble_fraction"]["status"] == "measured"
