"""Device-time attribution (PR 17): cost profiles, host-bubble analysis,
measured comm share.

Pins the acceptance contract: with ``FLAGS_devprof_sample_rate=0`` the
profiling surface is one cached-bool read (no timeline entries, no flight
events, no extra compiles, seeded streams untouched); with rate 1 every
engine step yields a profile whose host-prep / dispatch-gap / device
segments tile the device-sync-honest step wall, whose per-category shares
sum to 1, and the engine still compiles exactly ONE step signature; the
cost-regression ledger fires when a re-trace moves flops/bytes past
tolerance; a tp=2 engine reports a measured comm share; and the dump CLI's
``--devprof`` view renders the story or exits 2, never a vacuous pass.

Everything runs on CPU with the tiny Llama config (conftest provides the
8-device virtual mesh for the tp case).
"""

import json

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import devprof
from paddle_tpu.observability import dump as dump_cli
from paddle_tpu.observability import flight_recorder as flightrec


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _engine(seed=0, **kw):
    m, cfg = _model(seed)
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 8)
    return ContinuousBatchingEngine(m, **kw), cfg


def _run(eng, cfg, seed=0, n=3):
    rng = np.random.default_rng(seed)
    rids = [
        eng.add_request(
            rng.integers(0, cfg.vocab_size, (4 + i,)).astype(np.int32),
            max_new_tokens=3 + i,
        )
        for i in range(n)
    ]
    out = eng.run()
    return {r: out[r].tokens().tolist() for r in rids}


@pytest.fixture
def devprof_on():
    """Sample every step into clean global state; restore on teardown."""
    prior = paddle.get_flags(["FLAGS_devprof_sample_rate"])
    paddle.set_flags({"FLAGS_devprof_sample_rate": 1.0})
    obs.GLOBAL_WATCHDOG.reset()
    devprof.GLOBAL_COST_LEDGER.reset()
    devprof.drain_chrome_events()
    yield
    paddle.set_flags(prior)
    devprof.GLOBAL_COST_LEDGER.reset()
    devprof.drain_chrome_events()


# -- cost_analysis shims ------------------------------------------------------

class TestNormalizeCostAnalysis:
    def test_dict_form(self):
        p = devprof.normalize_cost_analysis(
            {"flops": 100.0, "bytes accessed": 40.0, "transcendentals": 2.0}
        )
        assert p == {
            "flops": 100.0, "bytes_accessed": 40.0, "transcendentals": 2.0,
            "cost_model": "xla",
        }

    def test_list_of_dicts_sums(self):
        p = devprof.normalize_cost_analysis(
            [{"flops": 60.0, "bytes accessed": 10.0}, {"flops": 40.0}]
        )
        assert p["flops"] == 100.0
        assert p["bytes_accessed"] == 10.0
        assert p["cost_model"] == "xla"

    @pytest.mark.parametrize("raw", [None, "nope", [], [1, 2], {"foo": "bar"}])
    def test_missing_or_garbage_records_unavailable_with_zeros(self, raw):
        p = devprof.normalize_cost_analysis(raw)
        assert p["cost_model"] == "unavailable"
        assert p["flops"] == 0.0 and p["bytes_accessed"] == 0.0


# -- sampling gate ------------------------------------------------------------

class TestSampleGate:
    def test_off_is_one_cached_bool_read_and_no_counter_churn(self):
        assert paddle.get_flags(["FLAGS_devprof_sample_rate"])[
            "FLAGS_devprof_sample_rate"
        ] == 0.0
        assert not devprof.devprof_enabled()
        gate = devprof.SampleGate()
        assert [gate.should_sample() for _ in range(10)] == [False] * 10
        # the disabled gate never advances its stride counter, so flipping
        # the flag later starts a deterministic stride from scratch
        assert gate._n == 0

    def test_deterministic_stride(self, devprof_on):
        paddle.set_flags({"FLAGS_devprof_sample_rate": 0.25})
        gate = devprof.SampleGate()
        got = [gate.should_sample() for _ in range(8)]
        assert got == [True, False, False, False, True, False, False, False]

    def test_rate_one_samples_every_call(self, devprof_on):
        gate = devprof.SampleGate()
        assert all(gate.should_sample() for _ in range(5))


# -- off-path honesty ---------------------------------------------------------

class TestOffPath:
    def test_rate_zero_records_nothing_and_leaves_the_run_untouched(self):
        assert not devprof.devprof_enabled()
        obs.GLOBAL_WATCHDOG.reset()
        devprof.GLOBAL_COST_LEDGER.reset()
        eng, cfg = _engine(seed=7)
        flight_before = len(eng._flight.snapshot())
        toks = _run(eng, cfg, seed=7)
        assert all(len(t) > 0 for t in toks.values())
        # nothing sampled: no timeline entries, no devprof flight events,
        # no cost profiles captured, summary reports disabled
        assert len(eng._devprof_timeline) == 0
        devs = [
            e for e in eng._flight.snapshot()[flight_before:]
            if e.get("kind") in ("devprof_step", "cost_regression")
        ]
        assert devs == []
        assert devprof.GLOBAL_COST_LEDGER.snapshot()["profiles"] == {}
        assert eng.devprof_stats() == {"enabled": False, "sampled_steps": 0}
        # and the engine still compiled exactly one step signature
        assert obs.GLOBAL_WATCHDOG.counts().get(
            "ContinuousBatchingEngine.step"
        ) == 1

    def test_profiling_never_perturbs_seeded_generation(self, devprof_on):
        eng_on, cfg = _engine(seed=11)
        toks_on = _run(eng_on, cfg, seed=11)
        paddle.set_flags({"FLAGS_devprof_sample_rate": 0.0})
        eng_off, cfg = _engine(seed=11)
        toks_off = _run(eng_off, cfg, seed=11)
        assert toks_on == toks_off


# -- sampled steps ------------------------------------------------------------

class TestSampledSteps:
    def test_segments_tile_the_wall_and_shares_sum_to_one(self, devprof_on):
        eng, cfg = _engine(seed=3)
        _run(eng, cfg, seed=3)
        entries = eng._devprof_timeline.entries()
        assert len(entries) >= 3
        for e in entries:
            # device-sync-honest: consecutive perf_counter differences, so
            # the three segments tile the step wall exactly
            assert e["host_prep_s"] + e["dispatch_s"] + e["device_s"] == \
                pytest.approx(e["wall_s"], rel=1e-9, abs=1e-9)
            assert sum(e["categories"].values()) == pytest.approx(1.0, abs=1e-4)
            assert set(e["categories"]) == set(devprof.CATEGORIES)
            assert 0.0 <= e["host_bubble_fraction"] <= 1.0
            assert e["signature"].startswith("toks[")

    def test_cost_profile_captured_and_one_compile(self, devprof_on):
        eng, cfg = _engine(seed=4)
        _run(eng, cfg, seed=4)
        # exactly ONE compiled step signature even with profiling on — the
        # introspective AOT lowering must not add a trace of its own
        assert eng.stats["step_traces"] == 1
        assert obs.GLOBAL_WATCHDOG.counts().get(
            "ContinuousBatchingEngine.step"
        ) == 1
        snap = devprof.GLOBAL_COST_LEDGER.snapshot()
        profs = snap["profiles"].get("ContinuousBatchingEngine.step")
        assert profs, snap
        prof = next(iter(profs.values()))
        assert prof["cost_model"] in ("xla", "unavailable")
        if prof["cost_model"] == "xla":
            assert prof["flops"] > 0
        assert sum(prof["categories"].values()) == pytest.approx(1.0, abs=1e-6)

    def test_summary_and_flight_and_chrome_events(self, devprof_on):
        eng, cfg = _engine(seed=5)
        flight_before = len(eng._flight.snapshot())
        _run(eng, cfg, seed=5)
        st = eng.devprof_stats()
        assert st["enabled"] and st["sampled_steps"] == len(eng._devprof_timeline)
        assert sum(st["mean_category_shares"].values()) == pytest.approx(
            1.0, abs=1e-3
        )
        assert 0.0 <= st["comm_share_measured"] <= 1.0
        assert st["last"]["comm_source"] in ("wrapper", "cost_model", "none")
        devs = [
            e for e in eng._flight.snapshot()[flight_before:]
            if e.get("kind") == "devprof_step"
        ]
        assert len(devs) == st["sampled_steps"]
        assert all("categories" in e and "wall_ms" in e for e in devs)
        chrome = devprof.drain_chrome_events()
        names = {e["name"] for e in chrome}
        assert names == {
            "devprof.device_ms_by_category", "devprof.step_segments_ms"
        }
        assert all(e["ph"] == "C" for e in chrome)
        # drained means drained
        assert devprof.drain_chrome_events() == []

    def test_healthz_snapshot_carries_devprof(self, devprof_on):
        from paddle_tpu.serving import ServingConfig, ServingFrontend

        eng, cfg = _engine(seed=6)
        fe = ServingFrontend(eng, ServingConfig(max_queue=4))
        rng = np.random.default_rng(6)
        h = fe.submit(
            rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
            max_new_tokens=3,
        )
        for _ in range(200):
            fe.pump()
            if h.finished:
                break
        assert h.finished
        snap = fe.snapshot()
        assert snap["devprof"]["enabled"] is True
        assert snap["devprof"]["sampled_steps"] >= 1


# -- wrapper-measured comm override -------------------------------------------

class TestCommAttribution:
    def test_wrapper_time_overrides_the_prior(self, devprof_on):
        devprof.GLOBAL_COST_LEDGER.record(
            "f", "sig",
            {"flops": 100.0, "bytes_accessed": 10.0, "cost_model": "xla",
             "categories": {"attention": 0.3, "matmul": 0.5,
                            "collective": 0.1, "other": 0.1}},
        )
        e = devprof.record_step_profile(
            "f", "sig", t0=0.0, call_s=0.001, ret_s=0.002, sync_s=0.012,
            comm_ops={"all_reduce": 0.004},
        )
        assert e["comm_source"] == "wrapper"
        # 4ms of measured collective inside a 10ms device segment
        assert e["categories"]["collective"] == pytest.approx(0.4, abs=1e-6)
        # non-collective categories split the remainder by prior ratio
        assert e["categories"]["matmul"] == pytest.approx(
            0.6 * (0.5 / 0.9), abs=1e-6
        )
        assert sum(e["categories"].values()) == pytest.approx(1.0, abs=1e-9)

    def test_cost_model_fallback_when_window_caught_nothing(self, devprof_on):
        devprof.GLOBAL_COST_LEDGER.record(
            "g", "sig",
            {"flops": 100.0, "bytes_accessed": 10.0, "cost_model": "xla",
             "categories": {"attention": 0.2, "matmul": 0.5,
                            "collective": 0.2, "other": 0.1}},
        )
        e = devprof.record_step_profile(
            "g", "sig", t0=0.0, call_s=0.001, ret_s=0.002, sync_s=0.012,
            comm_ops={},
        )
        assert e["comm_source"] == "cost_model"
        assert e["categories"]["collective"] == pytest.approx(0.2, abs=1e-6)

    def test_no_prior_no_window_is_honestly_unattributed(self, devprof_on):
        e = devprof.record_step_profile(
            "h", "sig", t0=0.0, call_s=0.001, ret_s=0.002, sync_s=0.012,
        )
        assert e["comm_source"] == "none"
        assert e["cost_model"] == "missing"
        assert e["categories"] == {
            "attention": 0.0, "matmul": 0.0, "collective": 0.0, "other": 1.0
        }

    def test_comm_window_is_thread_local_and_disarms(self):
        assert not devprof.comm_window_armed()
        devprof.record_comm("all_reduce", 1.0)  # unarmed: dropped
        devprof.begin_comm_window()
        assert devprof.comm_window_armed()
        devprof.record_comm("all_reduce", 0.5)
        devprof.record_comm("all_reduce", 0.25)
        ops = devprof.end_comm_window()
        assert ops == {"all_reduce": 0.75}
        assert not devprof.comm_window_armed()
        assert devprof.end_comm_window() == {}


# -- cost-regression ledger ---------------------------------------------------

class TestCostLedger:
    def test_retrace_drift_past_tolerance_fires(self, devprof_on):
        led = devprof.CostLedger(drift_tolerance=0.01)
        base = {"flops": 1000.0, "bytes_accessed": 500.0, "cost_model": "xla"}
        led.record("fn", "sig-a", base)
        led.record("fn", "sig-b", {**base, "flops": 1100.0})
        assert len(led.regressions) == 1
        r = led.regressions[0]
        assert r["prev_signature"] == "sig-a" and r["signature"] == "sig-b"
        assert r["drift_flops"] == pytest.approx(0.1, abs=1e-9)

    def test_same_cost_retrace_is_quiet(self, devprof_on):
        led = devprof.CostLedger(drift_tolerance=0.01)
        base = {"flops": 1000.0, "bytes_accessed": 500.0, "cost_model": "xla"}
        led.record("fn", "sig-a", base)
        led.record("fn", "sig-b", {**base, "flops": 1005.0})
        led.record("fn", "sig-a", base)  # same-signature re-record: no drift
        assert led.regressions == []

    def test_unavailable_side_skips_drift(self, devprof_on):
        led = devprof.CostLedger(drift_tolerance=0.01)
        led.record(
            "fn", "sig-a",
            {"flops": 0.0, "bytes_accessed": 0.0, "cost_model": "unavailable"},
        )
        led.record(
            "fn", "sig-b",
            {"flops": 999.0, "bytes_accessed": 1.0, "cost_model": "xla"},
        )
        assert led.regressions == []

    def test_forced_engine_retrace_lands_in_the_global_ledger(self, devprof_on):
        """Two engines with different shape buckets are two signatures of
        the same step fn: the integration path the drift check watches."""
        eng_a, cfg = _engine(seed=8, prompt_bucket=8)
        _run(eng_a, cfg, seed=8, n=1)
        eng_b, cfg = _engine(seed=8, prompt_bucket=16, max_slots=4)
        _run(eng_b, cfg, seed=8, n=1)
        snap = devprof.GLOBAL_COST_LEDGER.snapshot()
        profs = snap["profiles"].get("ContinuousBatchingEngine.step", {})
        assert len(profs) == 2, profs
        if all(p["cost_model"] == "xla" for p in profs.values()):
            # a 2x-wider batch moved flops far past the 1% tolerance
            assert snap["regressions"], snap
            assert snap["regressions"][0]["fn"] == "ContinuousBatchingEngine.step"

    def test_unknown_signature_falls_back_to_latest(self, devprof_on):
        led = devprof.CostLedger()
        led.record("fn", "sig-a", {"flops": 1.0, "cost_model": "xla"})
        assert led.profile_for("fn", "sig-zzz")["flops"] == 1.0
        assert led.profile_for("other-fn", "sig") is None


# -- tensor-parallel measured comm share --------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs >= 2 devices")
class TestTensorParallel:
    def test_tp2_reports_a_measured_comm_share(self, devprof_on):
        eng, cfg = _engine(seed=9, tp=2, max_slots=3)
        toks = _run(eng, cfg, seed=9)
        assert all(len(t) > 0 for t in toks.values())
        assert eng.stats["step_traces"] == 1
        st = eng.devprof_stats()
        assert st["sampled_steps"] >= 3
        assert 0.0 <= st["comm_share_measured"] <= 1.0
        # every sampled step names its comm provenance; GSPMD-inserted
        # all-reduces are invisible to the host wrapper, so cost_model (or
        # wrapper, if the program used explicit collectives) — never a
        # silent zero with no source
        assert st["comm_sources"]
        assert set(st["comm_sources"]) <= {"wrapper", "cost_model", "none"}
        assert st["last"]["signature"].endswith("|tp2")


# -- dump CLI -----------------------------------------------------------------

class TestDumpCLI:
    def _flight_dump_with_steps(self, tmp_path, n=3):
        rec = flightrec.FlightRecorder(capacity=64)
        for i in range(n):
            devprof.record_step_profile(
                "f", "sig", t0=float(i), call_s=i + 0.001, ret_s=i + 0.002,
                sync_s=i + 0.010, step=i, flight=rec,
            )
        return rec.dump("devprof-test", path=str(tmp_path / "flight.json"))

    def test_devprof_view_renders_steps(self, tmp_path, capsys):
        path = self._flight_dump_with_steps(tmp_path)
        assert dump_cli.main([path, "--devprof"]) == 0
        out = capsys.readouterr().out
        assert "device-time attribution — 3 sampled steps" in out
        assert "top category:" in out
        assert "mean host-bubble fraction:" in out

    def test_no_profiles_exits_2(self, tmp_path, capsys):
        rec = flightrec.FlightRecorder(capacity=8)
        rec.record("admit", rid="r1")
        path = rec.dump("no-devprof", path=str(tmp_path / "flight.json"))
        assert dump_cli.main([path, "--devprof"]) == 2
        assert "no devprof_step profiles" in capsys.readouterr().err

    def test_corrupt_profile_row_exits_2(self, tmp_path, capsys):
        path = self._flight_dump_with_steps(tmp_path, n=1)
        with open(path) as f:
            payload = json.load(f)
        del payload["events"][0]["categories"]
        with open(path, "w") as f:
            json.dump(payload, f)
        assert dump_cli.main([path, "--devprof"]) == 2
        assert "corrupt devprof_step" in capsys.readouterr().err

    def test_span_jsonl_exits_2(self, tmp_path, capsys):
        p = tmp_path / "spans.jsonl"
        p.write_text(json.dumps({"name": "s", "ts_us": 1.0}) + "\n")
        assert dump_cli.main([str(p), "--devprof"]) == 2
        assert "flight dump or incident dir" in capsys.readouterr().err

    def test_plain_view_still_prints_devprof_events(self, tmp_path, capsys):
        path = self._flight_dump_with_steps(tmp_path, n=1)
        assert dump_cli.main([path]) == 0
        assert "devprof_step" in capsys.readouterr().out


# -- profiler export merge ----------------------------------------------------

class TestProfilerExport:
    def test_export_merges_devprof_counter_tracks(self, tmp_path, devprof_on):
        from paddle_tpu import profiler

        devprof.record_step_profile(
            "f", "sig", t0=0.0, call_s=0.001, ret_s=0.002, sync_s=0.010,
        )
        prof = profiler.Profiler()
        prof.start()
        prof.stop()
        out = tmp_path / "trace.json"
        prof.export(str(out))
        events = json.loads(out.read_text())["traceEvents"]
        names = {e.get("name") for e in events}
        assert "devprof.device_ms_by_category" in names
        assert "devprof.step_segments_ms" in names
