"""Optimizers: convergence, parity vs hand-rolled updates, schedulers, clip, amp."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _quadratic_setup(opt_cls, **kw):
    """min ||w - target||^2 via the optimizer."""
    w = paddle.Parameter(np.zeros(4, np.float32))
    target = paddle.to_tensor(np.array([1.0, -2.0, 3.0, 0.5], np.float32))
    opt = opt_cls(parameters=[w], **kw)
    for _ in range(200):
        loss = ((w - target) * (w - target)).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return w.numpy(), target.numpy()


def test_sgd_converges():
    w, t = _quadratic_setup(paddle.optimizer.SGD, learning_rate=0.1)
    np.testing.assert_allclose(w, t, atol=1e-3)


def test_momentum_converges():
    w, t = _quadratic_setup(paddle.optimizer.Momentum, learning_rate=0.05, momentum=0.9)
    np.testing.assert_allclose(w, t, atol=1e-3)


def test_adam_converges():
    w, t = _quadratic_setup(paddle.optimizer.Adam, learning_rate=0.1)
    np.testing.assert_allclose(w, t, atol=1e-2)


def test_adamw_converges():
    w, t = _quadratic_setup(paddle.optimizer.AdamW, learning_rate=0.1, weight_decay=0.0)
    np.testing.assert_allclose(w, t, atol=1e-2)


def test_adam_matches_reference_update():
    """One Adam step vs hand-computed numpy update."""
    g = np.array([0.5, -1.0], np.float32)
    w0 = np.array([1.0, 2.0], np.float32)
    w = paddle.Parameter(w0.copy())
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[w])
    loss = (w * paddle.to_tensor(g)).sum()
    loss.backward()
    opt.step()
    m = 0.1 * g
    v = 0.001 * g * g
    m_hat = m / (1 - 0.9)
    v_hat = v / (1 - 0.999)
    expected = w0 - 0.01 * m_hat / (np.sqrt(v_hat) + 1e-8)
    np.testing.assert_allclose(w.numpy(), expected, rtol=1e-5)


def test_adamw_decoupled_decay():
    w = paddle.Parameter(np.array([10.0], np.float32))
    opt = paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[w])
    loss = (w * 0.0).sum()  # zero gradient: only decay applies
    loss.backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [10.0 - 0.1 * 0.5 * 10.0], rtol=1e-5)


def test_multi_precision_master_weights():
    w = paddle.Parameter(np.ones(4, np.float32))
    w._data = w._data.astype(paddle.bfloat16)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=[w], multi_precision=True)
    loss = (w.astype("float32") * 1.0).sum()
    loss.backward()
    opt.step()
    st = opt._accumulators[id(w)]
    assert "master_weight" in st
    assert str(st["master_weight"].dtype) == "float32"


def test_grad_clip_global_norm():
    w = paddle.Parameter(np.ones(2, np.float32))
    clip = nn.ClipGradByGlobalNorm(1.0)
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[w], grad_clip=clip)
    (w * paddle.to_tensor(np.array([30.0, 40.0], np.float32))).sum().backward()
    opt.step()
    # grad (30,40) has norm 50 -> clipped to (0.6, 0.8)
    np.testing.assert_allclose(w.numpy(), [1 - 0.6, 1 - 0.8], rtol=1e-4)


def test_lr_scheduler_step_decay():
    sched = paddle.optimizer.lr.StepDecay(learning_rate=1.0, step_size=2, gamma=0.1)
    w = paddle.Parameter(np.ones(1, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[w])
    assert opt.get_lr() == pytest.approx(1.0)
    sched.step()
    sched.step()
    assert opt.get_lr() == pytest.approx(0.1)


def test_cosine_annealing():
    sched = paddle.optimizer.lr.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
    sched.step(5)
    assert sched() == pytest.approx(0.5, abs=1e-6)
    sched.step(10)
    assert sched() == pytest.approx(0.0, abs=1e-6)


def test_linear_warmup():
    sched = paddle.optimizer.lr.LinearWarmup(
        learning_rate=0.8, warmup_steps=4, start_lr=0.0, end_lr=0.8
    )
    assert sched() == pytest.approx(0.0)
    sched.step()
    assert sched() == pytest.approx(0.2)
    for _ in range(5):
        sched.step()
    assert sched() == pytest.approx(0.8)


def test_optimizer_state_dict_roundtrip():
    w = paddle.Parameter(np.ones(3, np.float32), name="w0")
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
    (w * 2).sum().backward()
    opt.step()
    sd = opt.state_dict()
    w2 = paddle.Parameter(np.ones(3, np.float32), name="w0")
    opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w2])
    opt2.set_state_dict(sd)
    st1 = opt._accumulators[id(w)]
    st2 = opt2._accumulators[id(w2)]
    np.testing.assert_allclose(np.asarray(st1["moment1"]), np.asarray(st2["moment1"]))


def test_training_loop_linear_regression():
    """End-to-end slice: Layer + loss + optimizer learns y = 2x + 1."""
    np.random.seed(0)
    x = np.random.rand(64, 1).astype(np.float32)
    y = 2 * x + 1 + 0.01 * np.random.randn(64, 1).astype(np.float32)
    model = nn.Linear(1, 1)
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=model.parameters())
    loss_fn = nn.MSELoss()
    for _ in range(150):
        pred = model(paddle.to_tensor(x))
        loss = loss_fn(pred, paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert model.weight.numpy()[0, 0] == pytest.approx(2.0, abs=0.1)
    assert model.bias.numpy()[0] == pytest.approx(1.0, abs=0.1)


class TestAmp:
    def test_autocast_casts_matmul(self):
        a = paddle.ones([2, 2])
        b = paddle.ones([2, 2])
        with paddle.amp.auto_cast(dtype="bfloat16"):
            out = paddle.matmul(a, b)
        assert out.dtype == paddle.bfloat16

    def test_autocast_keeps_blacklist_fp32(self):
        x = paddle.ones([4], dtype="bfloat16")
        with paddle.amp.auto_cast(dtype="bfloat16"):
            out = paddle.nn.functional.softmax(x)
        assert str(np.dtype(out.dtype)) == "float32"

    def test_amp_training_step(self):
        model = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
        x = paddle.ones([2, 4])
        with paddle.amp.auto_cast(dtype="bfloat16"):
            loss = model(x).sum()
        loss.backward()
        # grads accumulate back in fp32 (param dtype)
        assert str(np.dtype(model.weight.grad.dtype)) == "float32"
        opt.step()

    def test_o2_decorate(self):
        model = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, level="O2", dtype="bfloat16")
        assert model[0].weight.dtype == paddle.bfloat16
        assert str(np.dtype(model[1].weight.dtype)) == "float32"  # norms excluded
        assert opt._multi_precision

    def test_grad_scaler_passthrough(self):
        scaler = paddle.amp.GradScaler(enable=False)
        w = paddle.Parameter(np.ones(1, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        loss = (w * 3).sum()
        scaler.scale(loss).backward()
        scaler.step(opt)
        np.testing.assert_allclose(w.numpy(), [0.7], rtol=1e-5)
