"""Inference stack: jit.save serialized-program round trip + Predictor API.

Reference: AnalysisPredictor (``paddle/fluid/inference/api/analysis_predictor.h:105``)
and the offline mixed-precision convert
(``paddle/fluid/inference/analysis/passes/convert_to_mixed_precision.cc``).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, nn
from paddle_tpu.static import InputSpec


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


@pytest.fixture()
def bundle(tmp_path):
    paddle.seed(0)
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "m" / "inference")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 8], "float32", name="x")])
    x = np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32)
    ref = net(paddle.to_tensor(x)).numpy()
    return path, x, ref


def test_save_load_roundtrip_executes(bundle):
    path, x, ref = bundle
    loaded = paddle.jit.load(path)
    out = loaded(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)
    # signature travels with the bundle
    assert loaded.input_spec[0]["name"] == "x"
    assert loaded.input_spec[0]["shape"] == [2, 8]
    assert loaded.output_spec[0]["shape"] == [2, 4]
    assert "stablehlo" in (loaded.program_text or "") or "func" in (loaded.program_text or "")


def test_predictor_handle_style(bundle):
    path, x, ref = bundle
    config = inference.Config(path + ".pdmodel")
    config.enable_memory_optim(False)
    predictor = inference.create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(x)
    predictor.run()
    out_h = predictor.get_output_handle(predictor.get_output_names()[0])
    np.testing.assert_allclose(out_h.copy_to_cpu(), ref, rtol=1e-5, atol=1e-6)
    assert out_h.shape() == [2, 4]


def test_predictor_direct_run_and_model_dir(bundle, tmp_path):
    path, x, ref = bundle
    # model_dir form: directory containing inference.pdmodel
    import os

    config = inference.Config(os.path.dirname(path))
    predictor = inference.create_predictor(config)
    outs = predictor.run([x])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)
    # second run reuses the compiled program (weights resident)
    outs2 = predictor.run([x])
    np.testing.assert_allclose(outs2[0], ref, rtol=1e-5, atol=1e-6)


def test_predictor_from_layer_bf16():
    paddle.seed(1)
    net = SmallNet()
    net.eval()
    config = inference.Config.from_layer(net, [InputSpec([2, 8], "float32", name="x")])
    config.enable_mixed_precision(inference.PrecisionType.Bfloat16)
    config.enable_memory_optim(False)
    predictor = inference.create_predictor(config)
    x = np.random.default_rng(1).normal(size=(2, 8)).astype(np.float32)
    outs = predictor.run([x])
    ref = net(paddle.to_tensor(x)).numpy()
    # bf16 weights: loose tolerance, but must be the same function
    np.testing.assert_allclose(outs[0].astype(np.float32), ref, rtol=0.1, atol=0.1)
    assert "bfloat16" in predictor._inputs[0]._dtype


def test_convert_to_mixed_precision_offline(tmp_path):
    paddle.seed(2)
    net = SmallNet()
    net.eval()
    x = np.random.default_rng(2).normal(size=(2, 8)).astype(np.float32)
    ref = net(paddle.to_tensor(x)).numpy()
    path = str(tmp_path / "bf16" / "inference")
    inference.convert_to_mixed_precision(
        net, path, input_spec=[InputSpec([2, 8], "float32", name="x")]
    )
    config = inference.Config(path)
    config.enable_memory_optim(False)
    predictor = inference.create_predictor(config)
    outs = predictor.run([x.astype("float32")])
    np.testing.assert_allclose(np.asarray(outs[0], np.float32), ref, rtol=0.1, atol=0.1)
    # params on disk really are bf16
    loaded = paddle.jit.load(path)
    assert any("bfloat16" in str(t.dtype) for t in loaded.state_dict().values())


def test_static_load_inference_model(bundle):
    path, x, ref = bundle
    loaded = paddle.static.load_inference_model(path)
    out = loaded(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)


def test_predictor_direct_run_validates_input_count(bundle):
    path, x, _ref = bundle
    config = inference.Config(path + ".pdmodel")
    config.enable_memory_optim(False)
    predictor = inference.create_predictor(config)
    with pytest.raises(ValueError, match="expects 1 inputs"):
        predictor.run([x, x])
    with pytest.raises(ValueError, match="expects 1 inputs"):
        predictor.run([])


class TestInt8Serving:
    """Weight-only int8 serving (VERDICT r4 #4: stop silently serving bf16)."""

    def _net_and_data(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 8))
        net.eval()
        x = np.random.default_rng(0).normal(size=(4, 16)).astype(np.float32)
        return net, x, net(paddle.to_tensor(x)).numpy()

    def test_from_layer_int8_parity_and_residency(self):
        net, x, ref = self._net_and_data()
        cfg = inference.Config.from_layer(net, [InputSpec([4, 16], "float32", name="x")])
        cfg.enable_mixed_precision(inference.PrecisionType.Int8)
        cfg.enable_memory_optim(False)
        pred = inference.create_predictor(cfg)
        out = pred.run([x])[0].astype(np.float32)
        assert np.abs(out - ref).max() / np.abs(ref).max() < 0.01  # <=1% drop
        # the served weights are genuinely int8 in memory
        int8_keys = [k for k, v in pred._params.items()
                     if k.endswith("@int8") and np.asarray(v).dtype == np.int8]
        assert len(int8_keys) == 2

    def test_offline_int8_convert_roundtrip(self, tmp_path):
        import pickle

        net, x, ref = self._net_and_data()
        p = str(tmp_path / "int8" / "inference")
        inference.convert_to_mixed_precision(
            net, p, [InputSpec([4, 16], "float32", name="x")], inference.PrecisionType.Int8
        )
        state = pickle.load(open(p + ".pdiparams", "rb"))
        assert sum(1 for k in state if k.endswith("@int8")) == 2
        pred = inference.create_predictor(inference.Config(p + ".pdmodel"))
        out = pred.run([x])[0].astype(np.float32)
        assert np.abs(out - ref).max() / np.abs(ref).max() < 0.01

    def test_bundle_precision_request_warns(self, bundle):
        import warnings

        path, x, _ref = bundle
        config = inference.Config(path + ".pdmodel")
        config.enable_mixed_precision(inference.PrecisionType.Int8)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            inference.create_predictor(config)
        assert any("ignored for a serialized bundle" in str(i.message) for i in w)
