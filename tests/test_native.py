"""Native C++ component tests: TCPStore (in-process + true multi-process over
localhost sockets, the reference's TestDistBase pattern) and the host tracer.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.core.native import load_native
from paddle_tpu.distributed.store import TCPStore

native_available = load_native() is not None


@pytest.mark.skipif(not native_available, reason="native lib not built")
class TestTCPStoreNative:
    def test_set_get_add(self):
        store = TCPStore("127.0.0.1", 29617, is_master=True, world_size=1)
        store.set("alpha", b"hello")
        assert store.get("alpha") == b"hello"
        assert store.add("cnt", 5) == 5
        assert store.add("cnt", 3) == 8
        store.wait("alpha")

    def test_two_clients_same_master(self):
        master = TCPStore("127.0.0.1", 29618, is_master=True, world_size=2)
        client = TCPStore("127.0.0.1", 29618, is_master=False, world_size=2)
        client.set("from_client", b"x1")
        assert master.get("from_client") == b"x1"
        master.set("from_master", b"y2")
        assert client.get("from_master") == b"y2"
        assert master.add("ranks", 1) + client.add("ranks", 1) == 3  # 1 then 2

    def test_multiprocess_rendezvous(self, tmp_path):
        """The reference pattern (test_collective_api_base.py:228): spawn real
        subprocesses rendezvousing over loopback. Rank 0 binds an EPHEMERAL
        port (no fixed-port collisions with stale runs) and publishes it via
        a file rank 1 polls."""
        port_file = str(tmp_path / "port")
        worker = textwrap.dedent(
            f"""
            import os, sys, time
            sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
            # rendezvous must never require the ML runtime: the stdlib-only
            # package is the canonical import for bootstrap-side processes
            from paddle_tpu_native.store import TCPStore
            assert "paddle_tpu" not in sys.modules, "store import pulled in the framework"
            rank = int(sys.argv[1])
            port_file = {port_file!r}
            if rank == 0:
                store = TCPStore("127.0.0.1", 0, is_master=True, world_size=2, timeout=50)
                with open(port_file + ".tmp", "w") as f:
                    f.write(str(store.port))
                os.rename(port_file + ".tmp", port_file)
            else:
                for _ in range(500):
                    if os.path.exists(port_file):
                        break
                    time.sleep(0.1)
                port = int(open(port_file).read())
                store = TCPStore("127.0.0.1", port, is_master=False, world_size=2, timeout=50)
            store.set(f"rank{{rank}}", f"payload-{{rank}}".encode())
            # each rank waits for the OTHER rank's key (cross-process block)
            other = store.get(f"rank{{1 - rank}}")
            assert other == f"payload-{{1 - rank}}".encode(), other
            store.wait("rank0")
            # the arrival barrier is each rank's LAST store op, and the master
            # (rank 0) exits only after seeing both arrivals: otherwise rank 0
            # can finish and take the server down while rank 1's final request
            # is still in flight (flaked under full-suite load)
            n = store.add("arrived", 1)
            if rank == 0:
                for _ in range(500):
                    if store.add("arrived", 0) >= 2:
                        break
                    time.sleep(0.05)
            print(f"rank {{rank}} ok n={{n}}")
            """
        )
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", worker, str(r)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            for r in (0, 1)
        ]
        try:
            outs = [p.communicate(timeout=60)[0].decode() for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert "rank 0 ok" in outs[0] and "rank 1 ok" in outs[1]


@pytest.mark.skipif(not native_available, reason="native lib not built")
class TestTCPStoreEdgeCases:
    def test_ephemeral_port(self):
        store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
        assert store.port > 0  # kernel-assigned, reflected back
        store.set("k", b"v")
        client = TCPStore("127.0.0.1", store.port, is_master=False)
        assert client.get("k") == b"v"

    def test_get_timeout_raises(self):
        store = TCPStore("127.0.0.1", 0, is_master=True, timeout=0.3)
        import time

        t0 = time.time()
        with pytest.raises(TimeoutError):
            store.get("never-set")
        assert time.time() - t0 < 5

    def test_check_probe_is_nonblocking_for_missing_keys(self):
        """``check`` answers immediately for absent keys — unlike ``get``,
        which has rendezvous semantics and blocks the full store timeout.
        This is what keeps the elastic manager's liveness scans O(ms) per
        dead rank instead of O(store timeout)."""
        import time

        store = TCPStore("127.0.0.1", 0, is_master=True, timeout=30.0)
        t0 = time.time()
        assert store.check("never-set") is False
        assert time.time() - t0 < 2  # probe, not a 30s rendezvous wait
        store.set("present", b"1")
        assert store.check("present") is True
        client = TCPStore("127.0.0.1", store.port, is_master=False, timeout=30.0)
        assert client.check("present") is True
        assert client.check("never-set") is False

    def test_delete_removes_key_and_reports_existence(self):
        """The GC primitive for counter/generation-namespaced keys (elastic
        beat/fault leases): delete over the wire, True iff the key existed,
        and counters restart from zero once reclaimed."""
        store = TCPStore("127.0.0.1", 0, is_master=True, timeout=5.0)
        store.set("k", b"v")
        assert store.delete("k") is True
        assert store.check("k") is False
        assert store.delete("k") is False  # already gone
        # counter keys are reclaimed too: add restarts from the base
        assert store.add("cnt", 5) == 5
        assert store.delete("cnt") is True
        assert store.add("cnt", 2) == 2
        # a second client sees the deletion (server-side, not a local cache)
        client = TCPStore("127.0.0.1", store.port, is_master=False, timeout=5.0)
        store.set("shared", b"1")
        assert client.delete("shared") is True
        assert store.check("shared") is False

    def test_hostname_resolution(self):
        store = TCPStore("127.0.0.1", 0, is_master=True)
        store.set("h", b"1")
        client = TCPStore("localhost", store.port, is_master=False)
        assert client.get("h") == b"1"

    def test_add_stores_decimal_ascii(self):
        # torch/paddle convention AND identical to the python fallback
        store = TCPStore("127.0.0.1", 0, is_master=True)
        store.add("n", 7)
        assert store.get("n") == b"7"
        store.add("n", 3)
        assert int(store.get("n")) == 10

    def test_client_port_zero_rejected(self):
        with pytest.raises(ValueError):
            TCPStore("127.0.0.1", 0, is_master=False)

    def test_tracer_escapes_names(self):
        import ctypes
        import json

        lib = load_native()
        lib.het_enable(1)
        lib.het_record('bad "name"\nwith\tctrl\\'.encode(), 1.0, 2.0, 3)
        buf = ctypes.create_string_buffer(1 << 16)
        n = lib.het_drain_json(buf, 1 << 16, 1)
        assert n > 0
        events = json.loads(buf.value.decode())  # must be valid JSON
        assert events[0]["name"] == 'bad "name"\nwith\tctrl\\'
        lib.het_enable(0)


class TestTCPStoreFallback:
    def test_python_fallback_api(self, monkeypatch):
        import paddle_tpu_native.store as store_mod

        monkeypatch.setattr(store_mod, "load_native", lambda: None)
        s = store_mod.TCPStore("127.0.0.1", 0, is_master=True)
        s.set("k", b"v")
        assert s.get("k") == b"v"
        assert s.add("c", 2) == 2
        assert s.delete("k") is True and s.delete("k") is False
        assert s.check("k") is False
        assert s.delete("c") is True and s.add("c", 1) == 1


class TestStoreRuntimeDecoupling:
    def test_store_importable_without_framework(self):
        """Importing the rendezvous store must not import paddle_tpu (and with
        it the jax runtime) — a child process must be able to rendezvous while
        the accelerator plugin is unhealthy (round-1 regression: a 60s hang)."""
        code = (
            "import sys\n"
            "import paddle_tpu_native.store as s\n"
            "assert 'paddle_tpu' not in sys.modules, sorted(m for m in sys.modules if 'paddle' in m)\n"
            "assert hasattr(s, 'TCPStore')\n"
            "print('decoupled ok')\n"
        )
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=60,
            env={**os.environ, "PYTHONPATH": repo},
            cwd=repo,
        )
        assert out.returncode == 0, out.stdout.decode() + out.stderr.decode()
        assert b"decoupled ok" in out.stdout


@pytest.mark.skipif(not native_available, reason="native lib not built")
class TestNativeHostTracer:
    def test_record_and_drain(self):
        import ctypes

        lib = load_native()
        lib.het_enable(1)
        lib.het_record(b"span_a", 100.0, 5.0, 1)
        lib.het_record(b"span_b", 200.0, 7.5, 2)
        assert lib.het_count() == 2
        buf = ctypes.create_string_buffer(1 << 16)
        n = lib.het_drain_json(buf, 1 << 16, 42)
        assert n > 0
        import json

        events = json.loads(buf.value.decode())
        assert [e["name"] for e in events] == ["span_a", "span_b"]
        assert events[0]["dur"] == 5.0 and events[1]["pid"] == 42
        assert lib.het_count() == 0
        lib.het_enable(0)

    def test_profiler_uses_native(self, tmp_path):
        import json

        import paddle_tpu.profiler as prof

        p = prof.Profiler()
        p.start()
        with prof.RecordEvent("native_span"):
            pass
        p.stop()
        out = str(tmp_path / "t.json")
        p.export(out)
        names = [e["name"] for e in json.load(open(out))["traceEvents"]]
        assert "native_span" in names


class TestShmRing:
    """Native shared-memory ring arena (cpp/shm_ring.cpp): slot reuse,
    commit-order delivery, cross-process transport, DataLoader integration."""

    def test_available_and_roundtrip(self):
        from paddle_tpu_native.shm_ring import ShmRing, available

        assert available(), "native lib must build in this environment"
        with ShmRing("/pt_test_ring_a", nslots=4, slot_bytes=1 << 16) as ring:
            assert ring.put(b"hello", tag=7)
            data, tag = ring.get(timeout=5.0)
            assert data == b"hello" and tag == 7

    def test_commit_order_and_slot_reuse(self):
        from paddle_tpu_native.shm_ring import ShmRing

        with ShmRing("/pt_test_ring_b", nslots=2, slot_bytes=1 << 12) as ring:
            # more payloads than slots: reuse forces the full state cycle
            for i in range(6):
                assert ring.put(f"m{i}".encode(), tag=i, timeout=5.0)
                data, tag = ring.get(timeout=5.0)
                assert data == f"m{i}".encode() and tag == i
            # burst of nslots, drained in commit order
            ring.put(b"x0", tag=0)
            ring.put(b"x1", tag=1)
            assert ring.get(timeout=5.0)[1] == 0
            assert ring.get(timeout=5.0)[1] == 1

    def test_oversized_payload_rejected(self):
        from paddle_tpu_native.shm_ring import ShmRing

        with ShmRing("/pt_test_ring_c", nslots=2, slot_bytes=64) as ring:
            with pytest.raises(ValueError):
                ring.put(b"x" * 100)

    def test_cross_process_transport(self):
        from paddle_tpu_native.shm_ring import ShmRing

        name = "/pt_test_ring_d"
        with ShmRing(name, nslots=4, slot_bytes=1 << 16) as ring:
            code = textwrap.dedent(
                f"""
                import sys
                sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
                from paddle_tpu_native.shm_ring import ShmRing
                r = ShmRing({name!r}, create=False)
                for i in range(3):
                    assert r.put(("payload%d" % i).encode(), tag=i, timeout=10.0)
                """
            )
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, timeout=60,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            assert proc.returncode == 0, proc.stderr.decode()
            for i in range(3):
                data, tag = ring.get(timeout=10.0)
                assert data == f"payload{i}".encode() and tag == i

    def test_dataloader_uses_the_ring(self):
        """The worker pool routes batches through the native ring when the
        lib is present (fork start method)."""
        import numpy as np

        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return np.full((4,), float(i), np.float32)

        loader = DataLoader(DS(), batch_size=2, num_workers=2, persistent_workers=True)
        out = [b.numpy() for b in loader]
        assert len(out) == 4
        np.testing.assert_array_equal(np.concatenate(out)[:, 0], np.arange(8))
        pool = loader._pool
        assert pool is not None and pool._ring is not None, "ring transport not active"
        pool.shutdown()
