"""Model-family tests: GPT (incl. pipeline form + TP sharding), ERNIE
finetune, SD UNet inference — the BASELINE.json workloads at tiny scale.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.models.ernie import ErnieConfig, ErnieForSequenceClassification, ErnieModel
from paddle_tpu.models.gpt import (
    GPTConfig,
    GPTForPretraining,
    build_gpt_pipeline,
    gpt_shard_fn,
)
from paddle_tpu.models.sd_unet import UNetConfig, UNet2DConditionModel


def _ids(b, s, vocab=128, seed=0):
    rng = np.random.default_rng(seed)
    return paddle.to_tensor(rng.integers(0, vocab, (b, s)).astype(np.int32))


class TestGPT:
    def test_forward_and_train(self):
        paddle.seed(0)
        model = GPTForPretraining(GPTConfig.tiny())
        opt = paddle.optimizer.AdamW(learning_rate=5e-3, parameters=model.parameters())
        ids = _ids(4, 16)
        losses = []
        for _ in range(8):
            logits = model(ids)
            loss = F.cross_entropy(logits.astype("float32"), ids, reduction="mean")
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_tied_embedding_head(self):
        paddle.seed(1)
        model = GPTForPretraining(GPTConfig.tiny())
        ids = _ids(2, 8)
        logits = model(ids)
        loss = logits.sum()
        loss.backward()
        # gradient flows into the tied embedding from BOTH uses
        g = model.gpt.embeddings.word_embeddings.weight.grad
        assert g is not None and float(np.abs(g.numpy()).sum()) > 0

    def test_pipeline_form_matches_plain(self):
        paddle.seed(2)
        cfg = GPTConfig.tiny()
        pipe = build_gpt_pipeline(cfg, num_stages=2)
        ids = _ids(2, 8)
        out = pipe(ids)
        assert tuple(out.shape) == (2, 8, cfg.vocab_size)
        # shared embedding object used for input embed + head
        embeds = [l for l in pipe._built if type(l).__name__ == "GPTEmbeddings"]
        assert embeds[0] is embeds[1]
        # pipeline stages split on GPTBlock boundaries
        assert len(pipe.get_stage_layers(0)) + len(pipe.get_stage_layers(1)) == len(pipe._built)

        # NUMERICAL parity vs the plain model with the pipeline's weights
        plain = GPTForPretraining(cfg)
        plain.gpt.embeddings.set_state_dict(pipe._built[0].state_dict())
        for i, blk in enumerate(plain.gpt.layers):
            blk.set_state_dict(pipe._built[1 + i].state_dict())
        plain.gpt.ln_f.set_state_dict(pipe._built[1 + cfg.num_layers].state_dict())
        ref = plain(ids)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5, atol=1e-6)

    def test_tp_sharding(self):
        mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
        dist.set_mesh(mesh)
        paddle.seed(3)
        model = GPTForPretraining(GPTConfig.tiny())
        for name, sub in model.named_sublayers(include_self=True):
            gpt_shard_fn(name, sub, mesh)
        from paddle_tpu.distributed.placements import Shard

        blk = model.gpt.layers[0]
        assert isinstance(blk.attn.qkv_proj.weight.placements[1], Shard)
        out = model(_ids(2, 8))
        assert np.isfinite(out.numpy()).all()


class TestErnie:
    def test_finetune_step(self):
        paddle.seed(0)
        model = ErnieForSequenceClassification(ErnieConfig.tiny(), num_classes=2)
        opt = paddle.optimizer.AdamW(learning_rate=5e-4, parameters=model.parameters())
        ids = _ids(4, 16)
        labels = paddle.to_tensor(np.array([0, 1, 0, 1], np.int32))
        losses = []
        for _ in range(6):
            logits = model(ids)
            loss = F.cross_entropy(logits, labels, reduction="mean")
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_attention_mask(self):
        paddle.seed(1)
        model = ErnieModel(ErnieConfig.tiny())
        ids = _ids(2, 8)
        mask = paddle.to_tensor(np.array([[1] * 8, [1] * 4 + [0] * 4], np.float32))
        h_masked, _ = model(ids, attention_mask=mask)
        h_full, _ = model(ids)
        # masking changes outputs for the padded row but both finite
        assert np.isfinite(h_masked.numpy()).all()
        assert not np.allclose(h_masked.numpy()[1], h_full.numpy()[1])

    def test_token_types_and_pooler(self):
        paddle.seed(2)
        model = ErnieModel(ErnieConfig.tiny())
        ids = _ids(2, 8)
        tt = paddle.to_tensor(np.zeros((2, 8), np.int32))
        seq, pooled = model(ids, token_type_ids=tt)
        assert tuple(seq.shape) == (2, 8, 64)
        assert tuple(pooled.shape) == (2, 64)


class TestSDUNet:
    def test_inference_shapes(self):
        paddle.seed(0)
        unet = UNet2DConditionModel(UNetConfig.tiny())
        lat = paddle.randn([2, 4, 16, 16])
        t = paddle.to_tensor(np.array([10, 500], np.int32))
        ctx = paddle.randn([2, 8, 32])
        with paddle.no_grad():
            out = unet(lat, t, ctx)
        assert tuple(out.shape) == (2, 4, 16, 16)
        assert np.isfinite(out.numpy()).all()

    def test_jitted_denoise_step(self):
        paddle.seed(1)
        unet = UNet2DConditionModel(UNetConfig.tiny())
        unet.eval()

        @paddle.jit.to_static
        def denoise(unet, lat, t, ctx):
            with paddle.no_grad():
                eps = unet(lat, t, ctx)
            return lat - 0.1 * eps

        lat = paddle.randn([1, 4, 16, 16])
        ctx = paddle.randn([1, 8, 32])
        for step in [999, 500]:
            t = paddle.to_tensor(np.array([step], np.int32))
            lat = denoise(unet, lat, t, ctx)
        assert np.isfinite(lat.numpy()).all()

    def test_cross_attention_uses_context(self):
        paddle.seed(2)
        unet = UNet2DConditionModel(UNetConfig.tiny())
        lat = paddle.randn([1, 4, 16, 16])
        t = paddle.to_tensor(np.array([100], np.int32))
        with paddle.no_grad():
            out1 = unet(lat, t, paddle.randn([1, 8, 32]))
            out2 = unet(lat, t, paddle.randn([1, 8, 32]))
        assert not np.allclose(out1.numpy(), out2.numpy())

    def test_sd15_config_structure(self):
        cfg = UNetConfig.sd15()
        assert cfg.block_out_channels == (320, 640, 1280, 1280)
        assert cfg.cross_attention_dim == 768
