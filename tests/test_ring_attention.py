"""Ring attention (context parallelism) tests: parity with full attention,
grads, causal + non-causal, GQA, Tensor-level API, jit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.kernels.ring_attention import ring_flash_attention as ring_jax
from paddle_tpu.nn.functional.flash_attention import _xla_attention

# multi-device CPU emulation of the sep x dp mesh costs minutes of XLA
# compile on the fast tier, so the mesh-heavy cases below are marked slow;
# the shard_map compat surface stays tier-1-covered by the cheaper
# test_sequence_parallel / test_collective
_mesh_heavy = pytest.mark.slow


def _qkv(b=2, s=64, h=4, hk=None, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    hk = hk or h
    return (
        jax.random.normal(ks[0], (b, s, h, d), jnp.float32),
        jax.random.normal(ks[1], (b, s, hk, d), jnp.float32),
        jax.random.normal(ks[2], (b, s, hk, d), jnp.float32),
    )


class TestRingAttention:
    @_mesh_heavy
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        mesh = dist.ProcessMesh(shape=[4, 2], dim_names=["sep", "dp"])
        q, k, v = _qkv()
        out = ring_jax(q, k, v, mesh, axis_name="sep", causal=causal)
        ref = _xla_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    @_mesh_heavy
    def test_gqa(self):
        mesh = dist.ProcessMesh(shape=[4], dim_names=["sep"])
        q, k, v = _qkv(h=8, hk=2)
        out = ring_jax(q, k, v, mesh, axis_name="sep", causal=True)
        ref = _xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    @_mesh_heavy
    def test_grads_match(self):
        mesh = dist.ProcessMesh(shape=[4], dim_names=["sep"])
        q, k, v = _qkv(b=1, s=32, h=2, d=8)

        def f_ring(q, k, v):
            return (ring_jax(q, k, v, mesh, axis_name="sep", causal=True) ** 2).sum()

        def f_ref(q, k, v):
            return (_xla_attention(q, k, v, causal=True) ** 2).sum()

        gr = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4)

    @_mesh_heavy
    def test_under_jit_with_sharded_inputs(self):
        mesh = dist.ProcessMesh(shape=[8], dim_names=["sep"])
        q, k, v = _qkv(s=128)
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(mesh.jax_mesh(), P(None, "sep", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        out = jax.jit(lambda a, b, c: ring_jax(a, b, c, mesh, axis_name="sep"))(qs, ks, vs)
        ref = _xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
        # output stays sequence-sharded over the ring
        assert len(out.sharding.device_set) == 8

    def test_single_device_axis_fallback(self):
        mesh = dist.ProcessMesh(shape=[1], dim_names=["sep"])
        q, k, v = _qkv(s=16)
        out = ring_jax(q, k, v, mesh, axis_name="sep", causal=True)
        ref = _xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_indivisible_seq_raises(self):
        mesh = dist.ProcessMesh(shape=[4], dim_names=["sep"])
        q, k, v = _qkv(s=30)
        with pytest.raises(ValueError):
            ring_jax(q, k, v, mesh, axis_name="sep")


class TestAttentionDropout:
    def test_flash_attention_dropout_applied(self):
        import paddle_tpu.nn.functional as F

        paddle.seed(0)
        q = paddle.randn([1, 16, 2, 8])
        k = paddle.randn([1, 16, 2, 8])
        v = paddle.randn([1, 16, 2, 8])
        out_nodrop, _ = F.flash_attention(q, k, v, dropout=0.0, training=True)
        out_drop, _ = F.flash_attention(q, k, v, dropout=0.5, training=True)
        # dropout must change the output (was silently ignored before)
        assert not np.allclose(out_nodrop.numpy(), out_drop.numpy())
        out_eval, _ = F.flash_attention(q, k, v, dropout=0.5, training=False)
        np.testing.assert_allclose(out_nodrop.numpy(), out_eval.numpy(), rtol=1e-6)

    def test_sdpa_dropout_applied(self):
        import paddle_tpu.nn.functional as F

        paddle.seed(0)
        q = paddle.randn([1, 16, 2, 8])
        out1 = F.scaled_dot_product_attention(q, q, q, dropout_p=0.0)
        out2 = F.scaled_dot_product_attention(q, q, q, dropout_p=0.5, training=True)
        assert not np.allclose(out1.numpy(), out2.numpy())


@_mesh_heavy
class TestRingAttentionTensorAPI:
    def test_functional_fwd_bwd(self):
        import paddle_tpu.nn.functional as F

        mesh = dist.ProcessMesh(shape=[4], dim_names=["sep"])
        dist.set_mesh(mesh)
        paddle.seed(0)
        q = paddle.randn([2, 32, 2, 8])
        k = paddle.randn([2, 32, 2, 8])
        v = paddle.randn([2, 32, 2, 8])
        q.stop_gradient = False
        out = F.ring_flash_attention(q, k, v, causal=True)
        ref = _xla_attention(q._data, k._data, v._data, causal=True)
        np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=2e-5, atol=2e-5)
        out.sum().backward()
        assert q.grad is not None and np.isfinite(q.grad.numpy()).all()


@_mesh_heavy
def test_llama_context_parallel_matches_dense():
    """config.context_parallel routes attention through the ring over the
    mesh's 'sep' axis with identical numerics to the dense path."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    mesh = dist.ProcessMesh(shape=[1, 4], dim_names=["dp", "sep"])
    prev = dist.get_mesh()
    dist.set_mesh(mesh)
    try:
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        cfg.context_parallel = True
        m_cp = LlamaForCausalLM(cfg)
        paddle.seed(0)
        cfg2 = LlamaConfig.tiny()
        m_ref = LlamaForCausalLM(cfg2)

        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32))
        loss_cp, _ = m_cp(ids, labels=ids)
        loss_ref, _ = m_ref(ids, labels=ids)
        np.testing.assert_allclose(float(loss_cp), float(loss_ref), rtol=2e-4)

        # gradients flow through the ring
        loss_cp.backward()
        assert all(
            p.grad is not None for p in m_cp.parameters() if not p.stop_gradient
        )
    finally:
        if prev is not None:
            dist.set_mesh(prev)
