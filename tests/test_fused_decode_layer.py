"""Decode-step megakernel tests (``FLAGS_use_fused_decode_layer``).

Pins the PR's acceptance invariants:

- the NEW fused-epilogue kernels (residual+norm, embed+norm, rope-fused
  paged attention) match their unfused compositions — bitwise where the
  backend contract promises it (same-jit, same op order), allclose for the
  adjoints vs ``jax.grad`` of the composition;
- the engine emits BYTE-IDENTICAL token streams fused on vs off across
  chunked prefill, decode, prefix-cache CoW forks, and spec-decode rewinds;
- both flag settings keep the one-signature invariant (``step_traces == 1``
  each — the flag is read at trace time, so each setting gets its own
  engine);
- the trace-time dispatch probe shows the fused layer loop issuing FEWER
  dispatch sites per layer than the unfused one — the perf claim's CPU-
  checkable proxy;
- GPT / ERNIE flag-gated epilogue fusion is byte-identical with matching
  grads, and the tp overlap matmul is byte-identical to the plain matmul.
"""

import contextlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.kernels.fused import (
    arm_dispatch_probe,
    disarm_dispatch_probe,
    fused_embed_rms_norm_pallas,
    fused_layer_norm_residual_pallas,
    fused_rms_norm_pallas,
    fused_rms_norm_residual_pallas,
    layer_norm_residual_adjoint_pallas,
    rms_norm_residual_adjoint_pallas,
)
from paddle_tpu.kernels.paged_attention import (
    paged_flash_chunk,
    paged_flash_chunk_fused,
    paged_flash_decode,
    paged_flash_decode_fused,
)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

BS = 16  # tokens per physical block (the kernel tile)


@contextlib.contextmanager
def _fused_flag(value):
    prior = paddle.get_flags(["FLAGS_use_fused_decode_layer"])[
        "FLAGS_use_fused_decode_layer"
    ]
    paddle.set_flags({"FLAGS_use_fused_decode_layer": value})
    try:
        yield
    finally:
        paddle.set_flags({"FLAGS_use_fused_decode_layer": prior})


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


# -- kernel numerics (interpret mode) ----------------------------------------

class TestResidualNormKernels:
    def test_rms_residual_fwd_matches_unfused_kernel_bitwise(self):
        """The fused kernel's op order is the EXISTING ``_rms_fwd_kernel``'s
        (f32 weight multiply before downcast) applied to ``x + residual`` —
        the on-TPU unfused composition, bitwise."""
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.float32)
        res = jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal(128), jnp.float32)
        y, r = fused_rms_norm_residual_pallas(x, res, w, interpret=True)
        ref_y = fused_rms_norm_pallas(x + res, w, interpret=True)
        np.testing.assert_array_equal(np.asarray(r), np.asarray(x + res))
        np.testing.assert_array_equal(np.asarray(y), np.asarray(ref_y))

    def test_rms_residual_adjoint_matches_jax_grad(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((2, 4, 128)), jnp.float32)
        res = jnp.asarray(rng.standard_normal((2, 4, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal(128), jnp.float32)
        g = jnp.asarray(rng.standard_normal((2, 4, 128)), jnp.float32)
        r = x + res

        def comp(r_, w_):
            xf = r_.astype(jnp.float32)
            rstd = jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-6)
            return jnp.sum((xf * rstd * w_) * g)

        dr_ref = jax.grad(comp, argnums=0)(r, w)
        dw_ref = jax.grad(comp, argnums=1)(r, w)
        dx, dw = rms_norm_residual_adjoint_pallas(g, r, w, 1e-6, interpret=True)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(dr_ref), atol=1e-5)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref), atol=1e-4)

    def test_ln_residual_fwd_and_adjoint(self):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((3, 128)), jnp.float32)
        res = jnp.asarray(rng.standard_normal((3, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal(128), jnp.float32)
        b = jnp.asarray(rng.standard_normal(128), jnp.float32)
        g = jnp.asarray(rng.standard_normal((3, 128)), jnp.float32)
        y, r = fused_layer_norm_residual_pallas(x, res, w, b, interpret=True)
        np.testing.assert_array_equal(np.asarray(r), np.asarray(x + res))

        def comp(r_, w_, b_):
            mu = jnp.mean(r_, -1, keepdims=True)
            var = jnp.mean((r_ - mu) ** 2, -1, keepdims=True)
            return (r_ - mu) * jax.lax.rsqrt(var + 1e-5) * w_ + b_

        np.testing.assert_allclose(
            np.asarray(y), np.asarray(comp(r, w, b)), atol=1e-5
        )
        dr_ref, dw_ref, db_ref = jax.grad(
            lambda r_, w_, b_: jnp.sum(comp(r_, w_, b_) * g), argnums=(0, 1, 2)
        )(r, w, b)
        dx, dw, db = layer_norm_residual_adjoint_pallas(g, r, w, interpret=True)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(dr_ref), atol=1e-5)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref), atol=1e-4)
        np.testing.assert_allclose(np.asarray(db), np.asarray(db_ref), atol=1e-4)

    def test_embed_rms_gather_exact(self):
        rng = np.random.default_rng(3)
        table = jnp.asarray(rng.standard_normal((32, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal(128), jnp.float32)
        ids = jnp.asarray(rng.integers(0, 32, (2, 5)), jnp.int32)
        emb, y = fused_embed_rms_norm_pallas(ids, table, w, interpret=True)
        np.testing.assert_array_equal(np.asarray(emb), np.asarray(table[ids]))
        ref_y = fused_rms_norm_pallas(table[ids], w, interpret=True)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(ref_y))


def _neox_rope(x, cos, sin):
    """cos/sin broadcast against x's head dim; x.dtype arithmetic — the
    kernel's in-block op order."""
    c = cos.astype(x.dtype)
    s = sin.astype(x.dtype)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return x * c + jnp.concatenate([-x2, x1], axis=-1) * s


class TestRopeFusedPagedAttention:
    """Fused in-kernel q-rope vs XLA-rope-then-unfused-kernel, compared
    INSIDE one jit — the real engine's one-jit step — where the two are
    bitwise identical (an eager boundary would reintroduce FMA-contraction
    diffs)."""

    def _chunk_args(self, seed=0, b=3, c=4, hq=4, hkv=4, d=64, mbs=4, nb=16):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(b, c, hq, d)), jnp.float32)
        cos = jnp.asarray(np.cos(rng.normal(size=(b, c, d))), jnp.float32)
        sin = jnp.asarray(np.sin(rng.normal(size=(b, c, d))), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(nb, hkv, BS, d)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(nb, hkv, BS, d)), jnp.float32)
        tables = jnp.asarray(
            rng.permutation(nb)[: b * mbs].reshape(b, mbs), jnp.int32
        )
        lens = jnp.asarray(rng.integers(c, mbs * BS - c, (b,)), jnp.int32)
        q_lens = jnp.asarray([1, c, 0][:b], jnp.int32)
        return q, cos, sin, kc, vc, tables, lens, q_lens

    def test_chunk_fused_bitwise_same_jit(self):
        q, cos, sin, kc, vc, tables, lens, q_lens = self._chunk_args()

        @jax.jit
        def fused(q, cos, sin):
            return paged_flash_chunk_fused(
                q, cos, sin, kc, vc, tables, lens, q_lens, interpret=True
            )

        @jax.jit
        def unfused(q, cos, sin):
            qr = _neox_rope(q, cos[:, :, None, :], sin[:, :, None, :])
            return paged_flash_chunk(qr, kc, vc, tables, lens, q_lens, interpret=True)

        np.testing.assert_array_equal(
            np.asarray(fused(q, cos, sin)), np.asarray(unfused(q, cos, sin))
        )

    def test_chunk_fused_gqa(self):
        q, cos, sin, kc, vc, tables, lens, q_lens = self._chunk_args(
            seed=1, hq=8, hkv=2
        )

        @jax.jit
        def fused(q, cos, sin):
            return paged_flash_chunk_fused(
                q, cos, sin, kc, vc, tables, lens, q_lens, interpret=True
            )

        @jax.jit
        def unfused(q, cos, sin):
            qr = _neox_rope(q, cos[:, :, None, :], sin[:, :, None, :])
            return paged_flash_chunk(qr, kc, vc, tables, lens, q_lens, interpret=True)

        np.testing.assert_array_equal(
            np.asarray(fused(q, cos, sin)), np.asarray(unfused(q, cos, sin))
        )

    def _decode_pair(self, hq, hkv, seed=2, b=3, d=64, mbs=4, nb=16):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(b, hq, d)), jnp.float32)
        cos = jnp.asarray(np.cos(rng.normal(size=(b, 1, d))), jnp.float32)
        sin = jnp.asarray(np.sin(rng.normal(size=(b, 1, d))), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(nb, hkv, BS, d)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(nb, hkv, BS, d)), jnp.float32)
        tables = jnp.asarray(
            rng.permutation(nb)[: b * mbs].reshape(b, mbs), jnp.int32
        )
        lens = jnp.asarray(rng.integers(1, mbs * BS + 1, (b,)), jnp.int32)

        @jax.jit
        def fused(q, cos, sin):
            return paged_flash_decode_fused(
                q, cos, sin, kc, vc, tables, lens, interpret=True
            )

        @jax.jit
        def unfused(q, cos, sin):
            qr = _neox_rope(q, cos, sin)
            return paged_flash_decode(qr, kc, vc, tables, lens, interpret=True)

        return np.asarray(fused(q, cos, sin)), np.asarray(unfused(q, cos, sin))

    def test_decode_fused_gqa_bitwise_same_jit(self):
        a, b = self._decode_pair(hq=8, hkv=2)
        np.testing.assert_array_equal(a, b)

    def test_decode_fused_mha_single_row_allclose(self):
        """g=1 puts a [1, D] row through the in-kernel rope; XLA's FMA
        selection is shape-dependent for single-row elementwise chains, so
        MHA decode is exact math but not bitwise vs the outer-rope lowering
        (~1 ulp). The engine's one-signature step uses the CHUNK kernel
        (bitwise above); this kernel serves generate_paged/bench."""
        a, b = self._decode_pair(hq=4, hkv=4)
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)


# -- engine byte-identity + one signature ------------------------------------

class TestEngineFusedParity:
    def _run(self, m, cfg, prompts, budgets, fused, **eng_kw):
        with _fused_flag(fused):
            eng = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=32,
                prefill_chunk=8, max_model_len=128, **eng_kw
            )
            rids = [
                eng.add_request(p, max_new_tokens=t)
                for p, t in zip(prompts, budgets)
            ]
            out = eng.run()
        return eng, [out[r].tokens() for r in rids]

    def test_mixed_workload_byte_identical_and_one_signature_each(self):
        """Chunked prefill + decode, staggered budgets, more requests than
        slots: same stream fused on/off, ONE compiled signature each."""
        m, cfg = _model(seed=3)
        rng = np.random.default_rng(7)
        prompts = [
            rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
            for n in (5, 12, 3, 9)
        ]
        budgets = [6, 4, 8, 5]
        eng_off, toks_off = self._run(m, cfg, prompts, budgets, fused=False)
        eng_on, toks_on = self._run(m, cfg, prompts, budgets, fused=True)
        for a, b in zip(toks_off, toks_on):
            np.testing.assert_array_equal(a, b)
        assert eng_off.stats["step_traces"] == 1
        assert eng_on.stats["step_traces"] == 1
        if hasattr(eng_on._step_fn, "_cache_size"):
            assert eng_on._step_fn._cache_size() == 1

    def test_cow_fork_warm_hit_byte_identical(self):
        """Prefix-cache CoW fork (cold, then warm with a forked partial
        block) under the fused layer loop matches the unfused stream."""
        m, cfg = _model(seed=42)
        rng = np.random.default_rng(42)
        prompt = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)

        with _fused_flag(True):
            eng = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=16
            )
            r_cold = eng.add_request(prompt, max_new_tokens=6)
            out_cold = eng.run()
            r_warm = eng.add_request(prompt, max_new_tokens=6)
            out_warm = eng.run()
            assert out_warm[r_warm].cached_tokens > 0
            assert eng.prefix_cache_stats()["cow_forks"] >= 1
            np.testing.assert_array_equal(
                out_cold[r_cold].tokens(), out_warm[r_warm].tokens()
            )
        with _fused_flag(False):
            eng_off = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=16
            )
            r_off = eng_off.add_request(prompt, max_new_tokens=6)
            out_off = eng_off.run()
        np.testing.assert_array_equal(
            out_cold[r_cold].tokens(), out_off[r_off].tokens()
        )

    def test_spec_decode_rewinds_byte_identical(self):
        """Speculative drafts + rewinds ride the fused layer loop: fused+spec
        matches unfused+spec token-for-token and still speculates."""
        m, cfg = _model(seed=5)
        rng = np.random.default_rng(5)
        template = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
        fill = rng.integers(0, cfg.vocab_size, (2,)).astype(np.int32)
        rep = np.concatenate([template, fill, template, fill])[:16]
        prompts = [rep, rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)]
        budgets = [20, 8]
        eng_on, toks_on = self._run(
            m, cfg, prompts, budgets, fused=True, spec_decode=True
        )
        eng_off, toks_off = self._run(
            m, cfg, prompts, budgets, fused=False, spec_decode=True
        )
        for a, b in zip(toks_off, toks_on):
            np.testing.assert_array_equal(a, b)
        assert eng_on.stats["spec_drafted"] > 0
        assert eng_on.stats["step_traces"] == 1


class TestDispatchReduction:
    """The perf claim's CPU-checkable proxy: the fused layer loop issues
    fewer epilogue dispatch sites per layer per traced step."""

    def _probe(self, fused):
        m, cfg = _model(seed=9)
        rng = np.random.default_rng(9)
        prompt = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
        with _fused_flag(fused):
            eng = ContinuousBatchingEngine(
                m, max_slots=2, block_size=4, prompt_bucket=16
            )
            eng.add_request(prompt, max_new_tokens=3)
            arm_dispatch_probe()
            try:
                eng.run()
            finally:
                sites = disarm_dispatch_probe()
        return sites, cfg.num_hidden_layers

    def test_fused_layer_issues_fewer_sites(self):
        fused_sites, n_layers = self._probe(True)
        unfused_sites, _ = self._probe(False)
        assert fused_sites and all(k.startswith("fused:") for k in fused_sites)
        assert unfused_sites and all(
            k.startswith("unfused:") for k in unfused_sites
        )
        # the probe fires once per site per TRACE (python runs at trace only)
        per_layer_fused = sum(
            v for k, v in fused_sites.items()
            if k not in ("fused:embed_norm", "fused:rope_gather")
        ) / n_layers
        per_layer_unfused = sum(
            v for k, v in unfused_sites.items()
            if k not in ("unfused:embed", "unfused:final_norm")
        ) / n_layers
        assert per_layer_fused < per_layer_unfused, (
            fused_sites, unfused_sites
        )
        # rope tables gather once per STEP fused, once per LAYER unfused
        assert fused_sites["fused:rope_gather"] == 1
        assert unfused_sites["unfused:rope_gather"] >= n_layers


# -- GPT / ERNIE epilogue fusion ---------------------------------------------

class TestGptErnieFusion:
    def test_gpt_forward_byte_identical_and_grads_close(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTModel

        paddle.seed(0)
        g = GPTModel(GPTConfig.tiny())
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, 128, (2, 16)).astype(np.int64)
        )

        def loss_and_grads():
            for _, p in g.named_parameters():
                p.clear_grad()
            loss = (g(ids) ** 2).sum()
            loss.backward()
            return float(loss), {
                n: np.asarray(p.grad._data).copy()
                for n, p in g.named_parameters()
                if p.grad is not None
            }

        with _fused_flag(True):
            y_on = np.asarray(g(ids)._data)
            l_on, g_on = loss_and_grads()
        with _fused_flag(False):
            y_off = np.asarray(g(ids)._data)
            l_off, g_off = loss_and_grads()
        np.testing.assert_array_equal(y_on, y_off)
        assert l_on == l_off
        assert set(g_on) == set(g_off)
        for k in g_off:
            np.testing.assert_allclose(g_on[k], g_off[k], atol=1e-5)

    def test_ernie_forward_byte_identical(self):
        from paddle_tpu.models.ernie import ErnieConfig, ErnieModel

        paddle.seed(1)
        e = ErnieModel(ErnieConfig.tiny())
        e.eval()
        ids = paddle.to_tensor(
            np.random.default_rng(1).integers(0, 128, (2, 12)).astype(np.int64)
        )
        with _fused_flag(True):
            s_on, p_on = e(ids)
        with _fused_flag(False):
            s_off, p_off = e(ids)
        np.testing.assert_array_equal(
            np.asarray(s_on._data), np.asarray(s_off._data)
        )
        np.testing.assert_array_equal(
            np.asarray(p_on._data), np.asarray(p_off._data)
        )


# -- tp overlap matmul --------------------------------------------------------

class TestRowParallelOverlapMatmul:
    def test_tiled_byte_identical_to_plain(self):
        from paddle_tpu.distributed.tp import row_parallel_overlap_matmul

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 6, 32)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
        ref = np.asarray(jnp.matmul(x.reshape(24, 32), w).reshape(4, 6, 16))
        for tiles in (1, 2, 3, 4):
            out = row_parallel_overlap_matmul(x, w, tiles=tiles)
            assert out.shape == (4, 6, 16)
            np.testing.assert_array_equal(np.asarray(out), ref)

    def test_uneven_rows_fall_back_to_one_tile(self):
        from paddle_tpu.distributed.tp import row_parallel_overlap_matmul

        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((5, 8)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
        out = row_parallel_overlap_matmul(x, w, tiles=2)  # 5 % 2 != 0
        np.testing.assert_array_equal(np.asarray(out), np.asarray(jnp.matmul(x, w)))
