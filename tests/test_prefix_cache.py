"""Prefix-cache KV subsystem: content-hash block dedup with copy-on-write.

The acceptance surface of ``inference/prefix_cache.py``:

- the 200-op seeded churn property test — after EVERY admit/decode/finish/
  evict op, every refcounted block's owner count equals its live mappings
  (slot tables + pending CoW pins) plus cache chain ownership,
  ``allocated + free == total``, and no live request's table references a
  freed block;
- byte-exact token parity between cached-hit and cold-path decoding of the
  same prompt (and against a cache-disabled engine);
- copy-on-write on the first divergent block;
- LRU eviction over zero-ref chains only, under real pool pressure;
- the ``prefix_cache.match`` / ``prefix_cache.cow`` fault sites degrading to
  recompute, never to a failed request.

Everything runs on CPU with the tiny Llama config, same as test_engine.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn.functional import BlockKVCache
from paddle_tpu.inference import ContinuousBatchingEngine
from paddle_tpu.inference.prefix_cache import PrefixCache
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.testing import faults


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


# the churn contract: refcount truth, exact accounting, no dangling table
# entries, node/table alignment — the shared engine invariant
from conftest import assert_engine_pool_exact as _assert_invariants


class TestChurnProperty:
    def test_200_op_seeded_churn_holds_invariants_after_every_op(self):
        """Seeded admit/decode/finish/evict churn with heavy prefix sharing
        (three prompt families over a small pool) — the invariants hold
        after EVERY operation, and every request completes exactly once."""
        m, cfg = _model(seed=40)
        rng = np.random.default_rng(40)
        eng = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, num_blocks=24, prompt_bucket=16,
            max_model_len=32,
        )
        families = [
            rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
            for n in (9, 6, 12)
        ]

        def make_prompt():
            fam = families[int(rng.integers(0, len(families)))]
            tail_n = int(rng.integers(0, 4))
            tail = rng.integers(0, cfg.vocab_size, (tail_n,)).astype(np.int32)
            return np.concatenate([fam, tail])[:16]

        submitted = {}
        done = {}
        cancelled = 0
        for _op in range(200):
            r = rng.random()
            if r < 0.40 and len(eng._waiting) < 6:
                rid = eng.add_request(
                    make_prompt(), max_new_tokens=int(rng.integers(1, 6))
                )
                submitted[rid] = True
            elif r < 0.85:
                if eng.has_work():
                    for req in eng.step():
                        assert req.req_id not in done, "delivered twice"
                        done[req.req_id] = req
            elif r < 0.93:
                live = [q.req_id for q in eng.live_requests()] + [
                    q.req_id for q in eng._waiting
                ]
                if live:
                    rid = int(rng.choice(live))
                    req = eng.cancel_request(rid)
                    assert req is not None and req.finished
                    done[rid] = req
                    cancelled += 1
            else:
                if eng._cache is not None:
                    eng._cache.evict_blocks(1)  # external pressure
            _assert_invariants(eng)
        while eng.has_work():
            for req in eng.step():
                assert req.req_id not in done
                done[req.req_id] = req
            _assert_invariants(eng)
        assert set(done) == set(submitted)  # exactly once, nobody lost
        assert cancelled > 0  # the churn actually exercised targeted evict
        s = eng.pool_stats()
        assert s["free"] + s["cached_blocks"] == s["total"]

    def test_200_op_churn_with_host_tier_spill_prefetch_drop(self):
        """The churn property test extended with the hierarchical-KV ops:
        submit (with multi-turn re-submissions that land on spilled chains),
        step, cancel, device-evict (which now SPILLS), and host-tier drop.
        After EVERY op: pool refcounts exact (the shared engine invariant),
        host-tier bytes <= budget, and no block live in both tiers under the
        same digest with mismatched contents."""
        from conftest import assert_kv_tier_exact

        m, cfg = _model(seed=52)
        rng = np.random.default_rng(52)
        bpb = 2 * cfg.num_hidden_layers * cfg.num_key_value_heads * \
            (cfg.hidden_size // cfg.num_attention_heads) * 4 * 4  # f32, bs=4
        eng = ContinuousBatchingEngine(
            m, max_slots=3, block_size=4, num_blocks=20, prompt_bucket=24,
            max_model_len=40, kv_host_tier_bytes=6 * bpb,
        )
        families = [
            rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
            for n in (9, 12)
        ]
        finished_streams = []

        def make_prompt():
            # half the prompts replay a finished request's stream (the
            # multi-turn shape that matches spilled generated-token chains)
            if finished_streams and rng.random() < 0.5:
                base = finished_streams[int(rng.integers(0, len(finished_streams)))]
            else:
                base = families[int(rng.integers(0, len(families)))]
            tail_n = int(rng.integers(0, 4))
            tail = rng.integers(0, cfg.vocab_size, (tail_n,)).astype(np.int32)
            return np.concatenate([base, tail])[:20]

        submitted, done = {}, {}
        for _op in range(200):
            r = rng.random()
            if r < 0.35 and len(eng._waiting) < 6:
                rid = eng.add_request(
                    make_prompt(), max_new_tokens=int(rng.integers(1, 6))
                )
                submitted[rid] = True
            elif r < 0.80:
                if eng.has_work():
                    for req in eng.step():
                        assert req.req_id not in done, "delivered twice"
                        done[req.req_id] = req
                        if len(finished_streams) < 6:
                            finished_streams.append(req.tokens())
            elif r < 0.88:
                live = [q.req_id for q in eng.live_requests()] + [
                    q.req_id for q in eng._waiting
                ]
                if live:
                    rid = int(rng.choice(live))
                    req = eng.cancel_request(rid)
                    assert req is not None and req.finished
                    done[rid] = req
            elif r < 0.96:
                eng._cache.evict_blocks(1)  # device pressure -> SPILL
            else:
                eng._host_tier.drop_lru(1)  # host pressure -> DROP
            _assert_invariants(eng)
            assert_kv_tier_exact(eng)
        while eng.has_work():
            for req in eng.step():
                assert req.req_id not in done
                done[req.req_id] = req
            _assert_invariants(eng)
            assert_kv_tier_exact(eng)
        assert set(done) == set(submitted)  # exactly once, nobody lost
        t = eng.kv_tier_stats()
        assert t["spilled_blocks"] > 0, t  # the churn actually spilled
        assert t["prefetched_blocks"] > 0, t  # ... and prefetched
        assert t["dropped_blocks"] > 0, t  # ... and dropped

    def test_churn_with_cache_disabled_matches_invariants_too(self):
        """The same machinery with FLAGS_enable_prefix_cache off: pure
        refcounted private blocks, zero cache state."""
        m, cfg = _model(seed=41)
        rng = np.random.default_rng(41)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, num_blocks=12, prompt_bucket=8,
            max_model_len=16, enable_prefix_cache=False,
        )
        assert eng.prefix_cache_stats() == {"enabled": False}
        for _ in range(4):
            eng.add_request(
                rng.integers(0, cfg.vocab_size, (int(rng.integers(2, 8)),))
                .astype(np.int32),
                max_new_tokens=int(rng.integers(1, 5)),
            )
        while eng.has_work():
            eng.step()
            _assert_invariants(eng)
        assert eng.pool_stats()["free"] == eng.num_blocks  # nothing retained
        assert eng.pool_stats()["cached_blocks"] == 0


class TestHitParity:
    def test_cached_hit_decode_is_byte_identical_to_cold(self):
        """The same prompt served cold, then from the cache (full-block hits
        + CoW partial), then by a cache-disabled engine — every path emits
        byte-identical tokens."""
        m, cfg = _model(seed=42)
        rng = np.random.default_rng(42)
        prompt = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)

        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=16)
        r_cold = eng.add_request(prompt, max_new_tokens=6)
        out_cold = eng.run()
        assert out_cold[r_cold].cached_tokens == 0
        stats = eng.prefix_cache_stats()
        assert stats["misses"] >= 1 and stats["nodes"] >= 3

        r_warm = eng.add_request(prompt, max_new_tokens=6)
        out_warm = eng.run()
        # 12-token prompt over 4-token blocks: blocks 0/1 full-match (the
        # cap holds back the 12th token, so block 2 cannot full-match); the
        # 3-token remainder rides a CoW fork of cached block 2
        assert out_warm[r_warm].cached_tokens == 11
        assert eng.prefix_cache_stats()["cow_forks"] == 1
        np.testing.assert_array_equal(
            out_cold[r_cold].tokens(), out_warm[r_warm].tokens()
        )

        eng_off = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, prompt_bucket=16,
            enable_prefix_cache=False,
        )
        r_off = eng_off.add_request(prompt, max_new_tokens=6)
        out_off = eng_off.run()
        np.testing.assert_array_equal(
            out_cold[r_cold].tokens(), out_off[r_off].tokens()
        )

    def test_shared_prefix_computed_once_across_requests(self):
        """N staggered requests sharing a system prompt: the shared full
        blocks are computed exactly once; warm admissions compute only their
        tails (the honesty counter the bench records)."""
        m, cfg = _model(seed=43)
        rng = np.random.default_rng(43)
        shared = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=16)

        def submit():
            tail = rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)
            return eng.add_request(
                np.concatenate([shared, tail]), max_new_tokens=3
            )

        submit()
        eng.run()
        cold_computed = eng.stats["prompt_tokens_computed"]
        assert cold_computed == 11  # the whole first prompt

        before = eng.stats["prompt_tokens_computed"]
        rids = [submit() for _ in range(3)]
        out = eng.run()
        warm_computed = eng.stats["prompt_tokens_computed"] - before
        # each warm request computes only its 3-token tail (the 8 shared
        # tokens = 2 full blocks are mapped, never recomputed)
        assert warm_computed == 3 * 3
        assert all(out[r].cached_tokens == 8 for r in rids)
        assert eng.stats["prompt_tokens_reused"] == 3 * 8
        assert eng.prefix_cache_stats()["hit_rate"] == pytest.approx(3 / 4)

    def test_in_flight_insertion_shares_with_staggered_admissions(self):
        """A request admitted while the first is still mid-flight (but past
        the shared blocks) hits the in-flight-inserted chain nodes — sharing
        does not wait for the first request to finish."""
        m, cfg = _model(seed=44)
        rng = np.random.default_rng(44)
        shared = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=16)
        ra = eng.add_request(np.concatenate(
            [shared, rng.integers(0, cfg.vocab_size, (2,)).astype(np.int32)]
        ), max_new_tokens=8)
        # drive a few steps: prefill completes, blocks inserted in-flight
        for _ in range(4):
            eng.step()
        assert any(r is not None and r.req_id == ra for r in eng._slot_req)
        rb = eng.add_request(np.concatenate(
            [shared, rng.integers(0, cfg.vocab_size, (2,)).astype(np.int32)]
        ), max_new_tokens=2)
        out = eng.run()
        assert out[rb].cached_tokens == 8  # matched A's in-flight chain
        _assert_invariants(eng)


class TestCopyOnWrite:
    def test_divergent_tail_forks_and_never_writes_the_shared_block(self):
        """X cached; Y shares X's first block then diverges inside the
        second: Y must fork (CoW) and X's re-run must still be byte-exact —
        the shared block was never written by Y."""
        m, cfg = _model(seed=45)
        rng = np.random.default_rng(45)
        x = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
        y = x.copy()[:11]
        y[6:] = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)  # diverge in block 1

        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=16)
        rx = eng.add_request(x, max_new_tokens=5)
        out_x = eng.run()
        forks_before = eng.prefix_cache_stats()["cow_forks"]
        ry = eng.add_request(y, max_new_tokens=5)
        out_y = eng.run()
        assert eng.prefix_cache_stats()["cow_forks"] == forks_before + 1
        assert out_y[ry].cached_tokens == 4 + 2  # block 0 + 2-token partial

        # oracle runs in a FRESH cache-off engine
        eng_off = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, prompt_bucket=16,
            enable_prefix_cache=False,
        )
        r1 = eng_off.add_request(y, max_new_tokens=5)
        out_off = eng_off.run()
        np.testing.assert_array_equal(out_y[ry].tokens(), out_off[r1].tokens())

        # X again through the shared (possibly forked-from) chain: byte-exact
        rx2 = eng.add_request(x, max_new_tokens=5)
        out_x2 = eng.run()
        np.testing.assert_array_equal(
            out_x[rx].tokens(), out_x2[rx2].tokens()
        )
        _assert_invariants(eng)


class TestEviction:
    def test_lru_evicts_zero_ref_chains_only_under_pressure(self):
        """Distinct prompts through a pool too small to retain them all:
        evictions must happen, live requests never lose blocks, and every
        request completes."""
        m, cfg = _model(seed=46)
        rng = np.random.default_rng(46)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, num_blocks=8, prompt_bucket=8,
            max_model_len=16,
        )
        outs = {}
        for i in range(6):
            rid = eng.add_request(
                rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32),
                max_new_tokens=3,
            )
            while eng.has_work():
                for req in eng.step():
                    outs[req.req_id] = req
                _assert_invariants(eng)
            assert rid in outs
        assert eng.prefix_cache_stats()["evictions"] > 0
        s = eng.pool_stats()
        assert s["free"] + s["cached_blocks"] == s["total"]

    def test_evict_blocks_never_touches_referenced_nodes(self):
        """Direct pool-level check: a node mapped by a live chain ref is not
        evictable even under explicit eviction pressure."""
        pool = BlockKVCache(8, 4, 2, 8, 4, dtype=np.float32)
        cache = PrefixCache(pool, 4, bytes_per_token=1)
        t1 = np.arange(4, dtype=np.int32)
        t2 = np.arange(4, 8, dtype=np.int32)
        b1 = pool.acquire_block()
        n1 = cache.insert(None, t1, b1)
        b2 = pool.acquire_block()
        n2 = cache.insert(n1, t2, b2)
        assert n1 is not None and n2 is not None
        # release both request refs (this also drops the request's pool
        # ref): BOTH nodes are now dead and count as reclaimable headroom,
        # though the eviction walk order is leaf-first (parent pinned by
        # child until the cascade reaches it)
        cache.release([n1, n2])
        assert cache.evictable_blocks == 2
        # eviction walks leaf-first; the parent cascades into the LRU the
        # moment its last child leaves, so one pressured call drains both
        assert cache.evict_blocks(5) == 2
        assert cache.node_count == 0
        assert pool.free_blocks == 8

    def test_match_is_capped_at_prompt_len_minus_one(self):
        """A fully-cached prompt must still compute its last token — the
        first generated token comes from that position's logits."""
        pool = BlockKVCache(8, 4, 2, 8, 4, dtype=np.float32)
        cache = PrefixCache(pool, 4, bytes_per_token=1)
        toks = np.arange(8, dtype=np.int32)
        b1 = pool.acquire_block()
        n1 = cache.insert(None, toks[:4], b1)
        b2 = pool.acquire_block()
        cache.insert(n1, toks[4:], b2)
        res = cache.match(toks)  # prompt == the cached chain exactly
        # block 1 may only be reused via CoW partial (3 of its 4 tokens)
        assert len(res.nodes) == 1
        assert res.cow is not None and res.cow[2] == 3
        assert res.cached_tokens == 7  # never prompt_len

    def test_insert_dedup_returns_none_for_existing_key(self):
        pool = BlockKVCache(8, 4, 2, 8, 4, dtype=np.float32)
        cache = PrefixCache(pool, 4, bytes_per_token=1)
        toks = np.arange(4, dtype=np.int32)
        b1 = pool.acquire_block()
        assert cache.insert(None, toks, b1) is not None
        b2 = pool.acquire_block()
        assert cache.insert(None, toks, b2) is None  # caller keeps b2 private
        assert pool.refcount(b1) == 2  # owner + cache
        assert pool.refcount(b2) == 1  # owner only


class TestPartialBlockSuffixReuse:
    """The match-length contract (PR 10 follow-on): a prompt diverging
    mid-chain maps EVERY full cached block before the first divergent block
    — even when the divergent block itself is partial (a ragged prompt
    tail) — plus the divergent block's leading run via copy-on-write. The
    same lengths must hold when the chain's tail has been spilled to the
    host tier (prefetch instead of CoW). The oracle for every case:
    ``cached == min(lcp, prompt_len - 1)`` and
    ``full_blocks_mapped == cached // block_size``."""

    def _cached_chain(self, seed, n_tokens=16):
        m, cfg = _model(seed=seed)
        rng = np.random.default_rng(seed)
        x = rng.integers(0, cfg.vocab_size, (n_tokens,)).astype(np.int32)
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, num_blocks=64, prompt_bucket=32,
            max_model_len=48, kv_host_tier_bytes=1 << 20,
        )
        r = eng.add_request(x, max_new_tokens=2)
        out = eng.run()
        return eng, cfg, rng, x, out[r].tokens()

    def test_mid_chain_divergence_with_partial_divergent_block(self):
        """x cached (4 full blocks); y = x[:13] diverging at position 10 —
        inside y's PARTIAL third block. Full blocks 0 and 1 must both map
        (8 tokens) plus the 2-token leading run of the divergent block."""
        eng, cfg, rng, x, _ = self._cached_chain(seed=53)
        y = x[:13].copy()
        y[10:] = (y[10:] + 1) % cfg.vocab_size
        res = eng._cache.match(y)
        assert len(res.nodes) == 2  # every full block before the divergence
        assert res.cow is not None and res.cow[2] == 2
        assert res.cached_tokens == 10  # == lcp, the oracle maximum
        eng._cache.release(res.nodes)
        eng._cache.release_cow_source(res.cow[0])
        eng._mgr.decref(res.cow[1])

    def test_divergence_at_partial_block_start_maps_all_preceding(self):
        eng, cfg, rng, x, _ = self._cached_chain(seed=54)
        y = x[:11].copy()
        y[8:] = (y[8:] + 1) % cfg.vocab_size  # diverges at its block's row 0
        res = eng._cache.match(y)
        assert len(res.nodes) == 2 and res.cow is None
        assert res.cached_tokens == 8
        eng._cache.release(res.nodes)

    def test_exact_prefix_ending_mid_block_maps_all_full_blocks(self):
        """y is an exact 14-token prefix of the cached stream: all 3 full
        blocks map and the partial fourth reuses 1 token via CoW — the
        held-back final token is the only one recomputed."""
        eng, cfg, rng, x, _ = self._cached_chain(seed=55)
        y = x[:14]
        res = eng._cache.match(y)
        assert len(res.nodes) == 3
        assert res.cow is not None and res.cow[2] == 1
        assert res.cached_tokens == 13  # min(lcp, plen-1)
        eng._cache.release(res.nodes)
        eng._cache.release_cow_source(res.cow[0])
        eng._mgr.decref(res.cow[1])

    def test_same_lengths_when_the_chain_tail_is_spilled(self):
        """The cross-tier half of the contract: spill the whole chain, then
        the SAME divergent-partial prompt must reuse the same token count —
        full blocks via H2D prefetch, the divergent block's leading run via
        prefetch-on-write — and decode byte-identically to a cold engine."""
        eng, cfg, rng, x, _ = self._cached_chain(seed=56)
        y = x[:13].copy()
        y[10:] = (y[10:] + 1) % cfg.vocab_size
        eng._cache.evict_blocks(16)
        assert eng._cache.node_count == 0
        ry = eng.add_request(y, max_new_tokens=3)
        out = eng.run()
        assert out[ry].cached_tokens == 10  # same oracle across tiers
        assert eng.kv_tier_stats()["prefetched_blocks"] == 3  # 2 full + partial
        eng_off = ContinuousBatchingEngine(
            eng.model, max_slots=2, block_size=4, prompt_bucket=32,
            max_model_len=48, enable_prefix_cache=False,
        )
        r_off = eng_off.add_request(y, max_new_tokens=3)
        out_off = eng_off.run()
        np.testing.assert_array_equal(out[ry].tokens(), out_off[r_off].tokens())
        _assert_invariants(eng)

    def test_multi_turn_divergence_inside_generated_chain(self):
        """Turn-2 prompt = turn-1 stream + new text: the divergence (where
        the new text begins) is mid-block, and every full block of the
        registered prompt+generated chain before it must map."""
        eng, cfg, rng, x, stream = self._cached_chain(seed=57)
        tail = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
        y = np.concatenate([stream, tail])
        # the final generated token is emitted, never appended to KV, so the
        # chain registers full blocks of the first stream.size - 1 tokens
        registered = ((stream.size - 1) // 4) * 4
        res = eng._cache.match(y)
        got = len(res.nodes) * 4 + (res.cow[2] if res.cow else 0)
        assert len(res.nodes) == registered // 4
        assert res.cached_tokens == got
        eng._cache.release(res.nodes)
        if res.cow is not None:
            eng._cache.release_cow_source(res.cow[0])
            eng._mgr.decref(res.cow[1])


class TestFaultSites:
    def test_sites_are_pinned_in_known_sites(self):
        assert "prefix_cache.match" in faults.KNOWN_SITES
        assert "prefix_cache.cow" in faults.KNOWN_SITES

    def test_match_fault_degrades_to_cold_miss(self):
        """An injected prefix_cache.match fault must cost a recompute, never
        a failed request — and tokens stay byte-identical."""
        m, cfg = _model(seed=47)
        rng = np.random.default_rng(47)
        prompt = rng.integers(0, cfg.vocab_size, (9,)).astype(np.int32)
        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=16)
        r1 = eng.add_request(prompt, max_new_tokens=4)
        out1 = eng.run()
        with faults.inject(faults.FaultPlan.single("prefix_cache.match", 0)):
            r2 = eng.add_request(prompt, max_new_tokens=4)
            out2 = eng.run()
        assert out2[r2].cached_tokens == 0  # lookup failed -> cold path
        np.testing.assert_array_equal(out1[r1].tokens(), out2[r2].tokens())
        _assert_invariants(eng)

    def test_cow_fault_degrades_to_recompute_of_the_partial(self):
        """An injected prefix_cache.cow fault skips the fork: full-block
        hits still apply, the ragged tail is recomputed, tokens identical."""
        m, cfg = _model(seed=48)
        rng = np.random.default_rng(48)
        prompt = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
        eng = ContinuousBatchingEngine(m, max_slots=2, block_size=4, prompt_bucket=16)
        r1 = eng.add_request(prompt, max_new_tokens=4)
        out1 = eng.run()
        with faults.inject(faults.FaultPlan.single("prefix_cache.cow", 0)):
            r2 = eng.add_request(prompt, max_new_tokens=4)
            out2 = eng.run()
        # full blocks 0/1 still hit; the 2-token partial was recomputed
        assert out2[r2].cached_tokens == 8
        assert eng.prefix_cache_stats()["cow_forks"] == 0
        np.testing.assert_array_equal(out1[r1].tokens(), out2[r2].tokens())
        _assert_invariants(eng)


def test_one_compile_with_cache_on_and_off():
    """The unified signature is independent of cache hits, misses, CoW and
    the flag itself — ONE compiled program per engine either way."""
    m, cfg = _model(seed=49)
    rng = np.random.default_rng(49)
    prompt = rng.integers(0, cfg.vocab_size, (10,)).astype(np.int32)
    for flag in (True, False):
        eng = ContinuousBatchingEngine(
            m, max_slots=2, block_size=4, prompt_bucket=16,
            enable_prefix_cache=flag,
        )
        for _ in range(2):
            eng.add_request(prompt, max_new_tokens=3)
            eng.run()
        assert eng.stats["step_traces"] == 1, (flag, eng.stats)


def test_rope_vector_offset_near_table_end_is_exact():
    """Chunked rows slice C rope positions starting at each slot's length; a
    width-C dynamic_slice CLAMPS its start near the table end and silently
    rotates the last tokens of a near-max context with wrong positions. The
    gather path must return exact per-position rows (clipping only the
    beyond-table tail, which is always a masked row)."""
    from paddle_tpu.models.llama import LlamaRotaryEmbedding

    emb = LlamaRotaryEmbedding(8, 32, 10000.0)
    cos, sin = emb.forward(4, paddle.to_tensor(np.asarray([29], np.int32)))
    ref_c = np.asarray(emb.cos_cached.numpy())
    ref_s = np.asarray(emb.sin_cached.numpy())
    got_c = np.asarray(cos.numpy())[0, :, 0, :]
    got_s = np.asarray(sin.numpy())[0, :, 0, :]
    # positions 29, 30, 31, then 32 clipped to 31 — a clamped slice would
    # have started at 28 and shifted EVERY row off by one
    for j, p in enumerate((29, 30, 31, 31)):
        np.testing.assert_array_equal(got_c[j], ref_c[p])
        np.testing.assert_array_equal(got_s[j], ref_s[p])


def test_admission_counts_whole_dead_chains_as_reclaimable():
    """A finished request's warm chain is ALL reclaimable headroom (interior
    nodes included, reached by the eviction cascade) — a request whose need
    equals free + the whole dead chain must admit, not queue forever."""
    m, cfg = _model(seed=50)
    rng = np.random.default_rng(50)
    eng = ContinuousBatchingEngine(
        m, max_slots=1, block_size=4, num_blocks=6, prompt_bucket=16,
        max_model_len=24,
    )
    ra = eng.add_request(
        rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32), max_new_tokens=1
    )
    out = eng.run()
    assert ra in out
    s = eng.pool_stats()
    assert s["cached_blocks"] == 2 and s["cached_reusable"] == 2, s
    # B needs all 6 blocks: only free(4) + the WHOLE dead chain(2) covers it
    rb = eng.add_request(
        rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32),
        max_new_tokens=8,
    )
    for _ in range(64):  # bounded: a headroom undercount would loop forever
        done = eng.step()
        if any(r.req_id == rb for r in done):
            break
    else:
        raise AssertionError("request B never admitted/finished: "
                             f"{eng.pool_stats()} {eng.prefix_cache_stats()}")
    _assert_invariants(eng)
