"""paddle.fft module (reference ``python/paddle/fft.py``): numpy parity,
norm conventions, gradients through transforms, bincount."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft

RNG = np.random.default_rng(0)
X = RNG.normal(size=(4, 8)).astype(np.float32)
XC = (RNG.normal(size=(4, 8)) + 1j * RNG.normal(size=(4, 8))).astype(np.complex64)


@pytest.mark.parametrize("name", ["fft", "ifft", "rfft", "ihfft"])
@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_1d_real_input_matches_numpy(name, norm):
    ours = getattr(fft, name)(paddle.to_tensor(X), norm=norm).numpy()
    ref = getattr(np.fft, name)(X, norm=None if norm == "backward" else norm)
    np.testing.assert_allclose(np.asarray(ours), ref.astype(ours.dtype), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", ["fft", "ifft", "irfft", "hfft"])
def test_1d_complex_input_matches_numpy(name):
    ours = getattr(fft, name)(paddle.to_tensor(XC)).numpy()
    ref = getattr(np.fft, name)(XC)
    np.testing.assert_allclose(np.asarray(ours), ref.astype(ours.dtype), rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("name", ["fft2", "ifft2", "rfft2"])
def test_2d_matches_numpy(name):
    ours = getattr(fft, name)(paddle.to_tensor(X)).numpy()
    ref = getattr(np.fft, name)(X)
    np.testing.assert_allclose(np.asarray(ours), ref.astype(ours.dtype), rtol=1e-4, atol=1e-4)


def test_fftn_axes_and_s():
    ours = fft.fftn(paddle.to_tensor(X), s=(4, 4), axes=(0, 1)).numpy()
    ref = np.fft.fftn(X, s=(4, 4), axes=(0, 1))
    np.testing.assert_allclose(np.asarray(ours), ref.astype(ours.dtype), rtol=1e-4, atol=1e-4)


def test_rfft_irfft_roundtrip():
    r = fft.irfft(fft.rfft(paddle.to_tensor(X)), n=8).numpy()
    np.testing.assert_allclose(np.asarray(r), X, rtol=1e-4, atol=1e-5)


def test_freq_and_shift():
    np.testing.assert_allclose(
        np.asarray(fft.fftfreq(8, d=0.5).numpy()), np.fft.fftfreq(8, d=0.5), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(fft.rfftfreq(8).numpy()), np.fft.rfftfreq(8), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(fft.fftshift(paddle.to_tensor(X)).numpy()), np.fft.fftshift(X)
    )
    np.testing.assert_allclose(
        np.asarray(fft.ifftshift(paddle.to_tensor(X)).numpy()), np.fft.ifftshift(X)
    )


def test_rfft_gradient_parseval():
    """Transforms ride the eager tape: d/dx ||rfft(x)||^2 = 2*n*x (Parseval)."""
    x = paddle.to_tensor(X.copy())
    x.stop_gradient = False
    y = fft.rfft(x)
    # |Y|^2 with the hermitian double-count correction: full fft energy = n*|x|^2
    yf = fft.fft(x)
    energy = (yf.abs() ** 2).sum()
    energy.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * 8 * X, rtol=1e-3, atol=1e-3)


def test_invalid_norm_rejected():
    with pytest.raises(ValueError, match="norm"):
        fft.fft(paddle.to_tensor(X), norm="bogus")


def test_bincount():
    v = np.array([1, 1, 3, 0, 3, 3], np.int32)
    t = paddle.to_tensor(v)
    np.testing.assert_array_equal(np.asarray(paddle.bincount(t).numpy()), np.bincount(v))
    np.testing.assert_array_equal(
        np.asarray(paddle.bincount(t, minlength=8).numpy()), np.bincount(v, minlength=8)
    )
    w = np.array([0.5, 0.5, 1.0, 2.0, 1.0, 1.0], np.float32)
    np.testing.assert_allclose(
        np.asarray(paddle.bincount(t, weights=paddle.to_tensor(w)).numpy()),
        np.bincount(v, weights=w),
        rtol=1e-6,
    )
    np.testing.assert_array_equal(np.asarray(t.bincount().numpy()), np.bincount(v))


class TestSignal:
    """paddle.signal (reference python/paddle/signal.py): frame/overlap_add
    and stft/istft round trip + scipy-free numpy oracle."""

    def test_frame_overlap_add_paddle_layout(self):
        from paddle_tpu import signal

        x = RNG.normal(size=(120,)).astype(np.float32)
        f = signal.frame(paddle.to_tensor(x), frame_length=16, hop_length=16)
        # paddle layout: [..., frame_length, num_frames] — frames as COLUMNS
        assert list(f.shape) == [16, 120 // 16]
        np.testing.assert_allclose(np.asarray(f.numpy())[:, 2], x[32:48], rtol=1e-6)
        back = signal.overlap_add(f, hop_length=16)
        np.testing.assert_allclose(np.asarray(back.numpy()), x[: 16 * (120 // 16)], rtol=1e-6)

    def test_frame_overlap(self):
        from paddle_tpu import signal

        x = np.arange(8, dtype=np.float32)
        f = signal.frame(paddle.to_tensor(x), frame_length=4, hop_length=2)
        assert list(f.shape) == [4, 3]
        np.testing.assert_array_equal(np.asarray(f.numpy()).T, [[0, 1, 2, 3], [2, 3, 4, 5], [4, 5, 6, 7]])
        # overlap_add sums overlapping regions
        back = signal.overlap_add(f, hop_length=2).numpy()
        ref = np.zeros(8, np.float32)
        for i in range(3):
            ref[i * 2 : i * 2 + 4] += x[i * 2 : i * 2 + 4]
        np.testing.assert_allclose(np.asarray(back), ref, rtol=1e-6)

    def test_stft_matches_numpy_oracle(self):
        from paddle_tpu import signal

        n_fft, hop = 16, 4
        x = RNG.normal(size=(2, 64)).astype(np.float32)
        w = np.hanning(n_fft).astype(np.float32)
        out = signal.stft(
            paddle.to_tensor(x), n_fft, hop_length=hop,
            window=paddle.to_tensor(w), center=False,
        ).numpy()
        # manual oracle
        num = 1 + (64 - n_fft) // hop
        ref = np.stack(
            [np.fft.rfft(x[:, i * hop : i * hop + n_fft] * w) for i in range(num)],
            axis=-1,
        )  # [2, freq, num] after transpose of stack axis
        ref = np.transpose(ref, (0, 2, 1)).transpose(0, 2, 1)  # keep [2, freq, num]
        np.testing.assert_allclose(np.asarray(out), ref.astype(out.dtype), rtol=1e-4, atol=1e-4)

    def test_stft_istft_roundtrip(self):
        from paddle_tpu import signal

        n_fft, hop = 32, 8
        x = RNG.normal(size=(3, 160)).astype(np.float32)
        w = np.hanning(n_fft).astype(np.float32)
        spec = signal.stft(paddle.to_tensor(x), n_fft, hop_length=hop, window=paddle.to_tensor(w))
        back = signal.istft(
            spec, n_fft, hop_length=hop, window=paddle.to_tensor(w), length=160
        ).numpy()
        np.testing.assert_allclose(np.asarray(back), x, rtol=1e-3, atol=1e-3)

    def test_istft_return_complex(self):
        from paddle_tpu import signal

        n_fft, hop = 16, 4
        xc = (RNG.normal(size=(64,)) + 1j * RNG.normal(size=(64,))).astype(np.complex64)
        w = np.hanning(n_fft).astype(np.float32)
        spec = signal.stft(
            paddle.to_tensor(xc), n_fft, hop_length=hop,
            window=paddle.to_tensor(w), onesided=False,
        )
        back = signal.istft(
            spec, n_fft, hop_length=hop, window=paddle.to_tensor(w),
            onesided=False, return_complex=True, length=64,
        ).numpy()
        assert np.iscomplexobj(np.asarray(back))
        np.testing.assert_allclose(np.asarray(back), xc, rtol=1e-3, atol=1e-3)
        with pytest.raises(ValueError, match="onesided"):
            signal.istft(spec, n_fft, onesided=True, return_complex=True)

    def test_save_inference_model_bridge(self, tmp_path):
        from paddle_tpu import nn
        from paddle_tpu.static import InputSpec, load_inference_model, save_inference_model

        paddle.seed(0)
        net = nn.Linear(4, 2)
        net.eval()
        path = str(tmp_path / "static_im")
        save_inference_model(path, [InputSpec([2, 4], "float32", name="x")], net)
        loaded = load_inference_model(path)
        x = RNG.normal(size=(2, 4)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(loaded(paddle.to_tensor(x)).numpy()),
            np.asarray(net(paddle.to_tensor(x)).numpy()),
            rtol=1e-5, atol=1e-6,
        )
