// Host event tracer: low-overhead RAII span recording.
//
// Native counterpart of the reference's HostEventRecorder
// (paddle/phi/api/profiler/host_event_recorder.h) + chrome-trace export
// (chrometracing_logger.cc): spans go into per-thread lock-free segments,
// drained as chrome://tracing JSON. The Python profiler uses this when the
// native lib is built (falling back to its pure-python recorder otherwise);
// recording a span is an append to a preallocated vector, no allocation in
// the common case and no GIL involvement from C++.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Event {
  std::string name;
  double ts_us;
  double dur_us;
  uint64_t tid;
};

std::mutex g_mu;
std::vector<Event> g_events;
bool g_enabled = false;

}  // namespace

extern "C" {

void het_enable(int on) {
  std::lock_guard<std::mutex> lk(g_mu);
  g_enabled = on != 0;
  if (on) g_events.reserve(1 << 16);
}

int het_enabled() { return g_enabled ? 1 : 0; }

void het_record(const char* name, double ts_us, double dur_us, uint64_t tid) {
  if (!g_enabled) return;
  std::lock_guard<std::mutex> lk(g_mu);
  g_events.push_back(Event{name, ts_us, dur_us, tid});
}

namespace {

// proper JSON string escaping: quotes, backslashes, and control chars
void append_escaped(std::string* buf, const std::string& s) {
  for (unsigned char c : s) {
    switch (c) {
      case '"': *buf += "\\\""; break;
      case '\\': *buf += "\\\\"; break;
      case '\n': *buf += "\\n"; break;
      case '\t': *buf += "\\t"; break;
      case '\r': *buf += "\\r"; break;
      default:
        if (c < 0x20) {
          char esc[8];
          snprintf(esc, sizeof(esc), "\\u%04x", c);
          *buf += esc;
        } else {
          *buf += static_cast<char>(c);
        }
    }
  }
}

}  // namespace

// Drain all events as a chrome-trace JSON array (without the enclosing
// {"traceEvents": ...}). Returns bytes written, or -(needed) if cap is too
// small (events are retained in that case so the caller can retry).
int het_drain_json(char* out, int cap, int pid) {
  std::lock_guard<std::mutex> lk(g_mu);
  std::string buf = "[";
  char nums[160];
  for (size_t i = 0; i < g_events.size(); ++i) {
    const Event& e = g_events[i];
    if (i) buf += ",";
    buf += "{\"name\":\"";
    append_escaped(&buf, e.name);
    snprintf(nums, sizeof(nums),
             "\",\"ph\":\"X\",\"ts\":%.3f,\"dur\":%.3f,\"pid\":%d,\"tid\":%llu}",
             e.ts_us, e.dur_us, pid, static_cast<unsigned long long>(e.tid));
    buf += nums;
  }
  buf += "]";
  if (static_cast<int>(buf.size()) + 1 > cap) return -static_cast<int>(buf.size() + 1);
  memcpy(out, buf.data(), buf.size() + 1);
  g_events.clear();
  return static_cast<int>(buf.size());
}

int het_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  return static_cast<int>(g_events.size());
}

}  // extern "C"
