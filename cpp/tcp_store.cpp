// TCPStore: rendezvous key-value store for distributed bootstrap.
//
// Native C++ counterpart of the reference's TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h:121, socket impl
// socket.cpp): one master process listens; every rank connects as a client
// and uses SET / blocking GET / atomic ADD / WAIT to exchange bootstrap
// blobs (coordinator addresses, per-rank endpoints) before any collective
// backend exists. Thread-per-connection with a shared map + condition
// variable (the reference uses a callback-driven event loop; at rendezvous
// scale the simpler threading model has identical behavior).
//
// Exposed as a C API consumed via ctypes from
// paddle_tpu/distributed/store.py.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdlib>

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Cmd : uint8_t { kSet = 1, kGet = 2, kAdd = 3, kWait = 4, kPing = 5, kDelete = 6 };

struct Master {
  int listen_fd = -1;
  int port = 0;
  std::thread accept_thread;
  std::vector<std::thread> workers;
  std::vector<int> client_fds;
  std::map<std::string, std::string> kv;
  std::mutex mu;
  std::condition_variable cv;
  bool stopping = false;
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_blob(int fd, std::string* out) {
  uint32_t len = 0;
  if (!read_full(fd, &len, 4)) return false;
  out->resize(len);
  return len == 0 || read_full(fd, &(*out)[0], len);
}

bool write_blob(int fd, const std::string& s) {
  uint32_t len = static_cast<uint32_t>(s.size());
  if (!write_full(fd, &len, 4)) return false;
  return s.empty() || write_full(fd, s.data(), s.size());
}

void serve_conn(Master* m, int fd) {
  for (;;) {
    uint8_t cmd = 0;
    if (!read_full(fd, &cmd, 1)) break;
    std::string key;
    if (!read_blob(fd, &key)) break;
    if (cmd == kSet) {
      std::string val;
      if (!read_blob(fd, &val)) break;
      {
        std::lock_guard<std::mutex> lk(m->mu);
        m->kv[key] = std::move(val);
      }
      m->cv.notify_all();
      uint8_t ok = 1;
      if (!write_full(fd, &ok, 1)) break;
    } else if (cmd == kGet || cmd == kWait) {
      uint32_t timeout_ms = 0;  // 0 = wait forever
      if (!read_full(fd, &timeout_ms, 4)) break;
      bool found;
      {
        std::unique_lock<std::mutex> lk(m->mu);
        auto pred = [&] { return m->stopping || m->kv.count(key) > 0; };
        if (timeout_ms == 0) {
          m->cv.wait(lk, pred);
        } else {
          m->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
        }
        if (m->stopping) break;
        found = m->kv.count(key) > 0;
      }
      uint8_t status = found ? 0 : 1;  // 1 = timed out
      if (!write_full(fd, &status, 1)) break;
      if (cmd == kGet && found) {
        std::string val;
        {
          std::lock_guard<std::mutex> lk(m->mu);
          val = m->kv[key];
        }
        if (!write_blob(fd, val)) break;
      }
    } else if (cmd == kAdd) {
      int64_t delta = 0;
      if (!read_full(fd, &delta, 8)) break;
      int64_t now = 0;
      {
        std::lock_guard<std::mutex> lk(m->mu);
        std::string& cur = m->kv[key];
        // counters stored as decimal ASCII — the torch/paddle TCPStore
        // convention, and identical to the python fallback's behavior
        int64_t v = cur.empty() ? 0 : strtoll(cur.c_str(), nullptr, 10);
        v += delta;
        cur = std::to_string(v);
        now = v;
      }
      m->cv.notify_all();
      if (!write_full(fd, &now, 8)) break;
    } else if (cmd == kDelete) {
      // GC primitive for generation-namespaced keys (elastic manager): the
      // waiters' predicate only tests presence, so erasing never wakes a
      // kGet/kWait spuriously — no notify needed
      uint8_t existed;
      {
        std::lock_guard<std::mutex> lk(m->mu);
        existed = m->kv.erase(key) > 0 ? 1 : 0;
      }
      if (!write_full(fd, &existed, 1)) break;
    } else if (cmd == kPing) {
      uint8_t ok = 1;
      if (!write_full(fd, &ok, 1)) break;
    } else {
      break;
    }
  }
  {
    // unregister before closing so master_stop never shuts down a reused fd
    std::lock_guard<std::mutex> lk(m->mu);
    for (auto it = m->client_fds.begin(); it != m->client_fds.end(); ++it) {
      if (*it == fd) {
        m->client_fds.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

}  // namespace

extern "C" {

void* tcpstore_master_start(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  auto* m = new Master();
  m->listen_fd = fd;
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  if (::getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen) == 0) {
    m->port = ntohs(bound.sin_port);  // actual port (ephemeral when port==0)
  }
  m->accept_thread = std::thread([m] {
    for (;;) {
      int cfd = ::accept(m->listen_fd, nullptr, nullptr);
      if (cfd < 0) break;  // listen fd closed → shutdown
      int one = 1;
      ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(m->mu);
      if (m->stopping) {
        ::close(cfd);
        break;
      }
      m->client_fds.push_back(cfd);
      m->workers.emplace_back(serve_conn, m, cfd);
    }
  });
  return m;
}

int tcpstore_master_port(void* handle) {
  auto* m = static_cast<Master*>(handle);
  return m ? m->port : -1;
}

void tcpstore_master_stop(void* handle) {
  auto* m = static_cast<Master*>(handle);
  if (!m) return;
  {
    std::lock_guard<std::mutex> lk(m->mu);
    m->stopping = true;
    // unblock workers parked in read(): shut their sockets down
    for (int cfd : m->client_fds) ::shutdown(cfd, SHUT_RDWR);
  }
  m->cv.notify_all();
  ::shutdown(m->listen_fd, SHUT_RDWR);
  ::close(m->listen_fd);
  if (m->accept_thread.joinable()) m->accept_thread.join();
  for (auto& t : m->workers)
    if (t.joinable()) t.join();  // safe: all blocking points were released
  delete m;
}

int tcpstore_connect(const char* host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      // not a dotted-quad literal: DNS-resolve (hostnames, "localhost")
      addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      if (::getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr) {
        ::close(fd);
        return -1;
      }
      addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      ::freeaddrinfo(res);
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    if (std::chrono::steady_clock::now() >= deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

int tcpstore_set(int fd, const char* key, const char* val, int len) {
  uint8_t cmd = kSet;
  if (!write_full(fd, &cmd, 1)) return -1;
  if (!write_blob(fd, key)) return -1;
  if (!write_blob(fd, std::string(val, static_cast<size_t>(len)))) return -1;
  uint8_t ok = 0;
  return read_full(fd, &ok, 1) && ok == 1 ? 0 : -1;
}

// returns value length; -1 on error, -2 buffer too small (caller retries
// with a larger cap), -3 timed out. timeout_ms == 0 waits forever.
int tcpstore_get(int fd, const char* key, char* out, int cap, int timeout_ms) {
  uint8_t cmd = kGet;
  if (!write_full(fd, &cmd, 1)) return -1;
  if (!write_blob(fd, key)) return -1;
  uint32_t t = static_cast<uint32_t>(timeout_ms < 0 ? 0 : timeout_ms);
  if (!write_full(fd, &t, 4)) return -1;
  uint8_t status = 0;
  if (!read_full(fd, &status, 1)) return -1;
  if (status != 0) return -3;
  uint32_t len = 0;
  if (!read_full(fd, &len, 4)) return -1;
  if (static_cast<int>(len) > cap) {
    // drain and report needed size as negative-2 (caller retries with cap)
    std::vector<char> tmp(len);
    read_full(fd, tmp.data(), len);
    return -2;
  }
  if (len > 0 && !read_full(fd, out, len)) return -1;
  return static_cast<int>(len);
}

int64_t tcpstore_add(int fd, const char* key, int64_t delta) {
  uint8_t cmd = kAdd;
  if (!write_full(fd, &cmd, 1)) return -1;
  if (!write_blob(fd, key)) return -1;
  if (!write_full(fd, &delta, 8)) return -1;
  int64_t now = 0;
  return read_full(fd, &now, 8) ? now : -1;
}

// 1 key existed, 0 key absent, -1 error
int tcpstore_delete(int fd, const char* key) {
  uint8_t cmd = kDelete;
  if (!write_full(fd, &cmd, 1)) return -1;
  if (!write_blob(fd, key)) return -1;
  uint8_t existed = 0;
  return read_full(fd, &existed, 1) ? existed : -1;
}

// 0 ok, -1 error, -3 timed out
int tcpstore_wait(int fd, const char* key, int timeout_ms) {
  uint8_t cmd = kWait;
  if (!write_full(fd, &cmd, 1)) return -1;
  if (!write_blob(fd, key)) return -1;
  uint32_t t = static_cast<uint32_t>(timeout_ms < 0 ? 0 : timeout_ms);
  if (!write_full(fd, &t, 4)) return -1;
  uint8_t status = 0;
  if (!read_full(fd, &status, 1)) return -1;
  return status == 0 ? 0 : -3;
}

void tcpstore_close(int fd) { ::close(fd); }

}  // extern "C"
