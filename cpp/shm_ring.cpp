// Shared-memory ring arena for DataLoader worker→parent batch handoff.
//
// Reference: the multiprocess DataLoader's shared-memory tensor transport
// (python/paddle/io/dataloader/worker.py + the C++ shared-memory allocator
// under paddle/fluid/memory/allocation/mmap_allocator.cc): batches cross the
// process boundary through mapped memory, not pickled pipe bytes.
//
// Design: one POSIX shm segment = header + N fixed-size slots. Slot states
// advance EMPTY -> WRITING -> READY -> READING -> EMPTY via C11 atomics in
// the mapped header (process-shared, lock-free); waiting sides back off with
// short sleeps (batch-granularity handoff; microsecond latency is
// irrelevant next to a training step). Producers claim any EMPTY slot;
// consumers drain READY slots in commit order via a monotone ticket so batch
// ordering survives multi-producer races.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x50525247;  // "PRRG"

enum SlotState : uint32_t {
  kEmpty = 0,
  kWriting = 1,
  kReady = 2,
  kReading = 3,
};

struct SlotHeader {
  std::atomic<uint32_t> state;
  std::atomic<uint64_t> ticket;  // commit order
  uint64_t size;                 // payload bytes
  int64_t tag;                   // caller-defined (e.g. batch index)
};

struct RingHeader {
  uint32_t magic;
  uint32_t nslots;
  uint64_t slot_bytes;
  std::atomic<uint64_t> next_ticket;   // producer commit counter
  std::atomic<uint64_t> read_ticket;   // next ticket the consumer wants
  SlotHeader slots[];                  // nslots entries, then payload area
};

struct Ring {
  RingHeader* hdr;
  uint8_t* payload;
  size_t map_bytes;
  char name[256];
  bool owner;
};

size_t total_bytes(uint32_t nslots, uint64_t slot_bytes) {
  return sizeof(RingHeader) + nslots * sizeof(SlotHeader) +
         static_cast<size_t>(nslots) * slot_bytes;
}

void sleep_us(long us) {
  struct timespec ts {0, us * 1000L};
  nanosleep(&ts, nullptr);
}

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

}  // namespace

extern "C" {

// Create (owner=1) or attach (owner=0). Returns nullptr on failure.
void* shm_ring_open(const char* name, uint32_t nslots, uint64_t slot_bytes,
                    int create) {
  size_t bytes = 0;
  int fd = -1;
  if (create) {
    shm_unlink(name);  // stale segment from a dead run
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return nullptr;
    bytes = total_bytes(nslots, slot_bytes);
    if (ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
    struct stat st;
    if (fstat(fd, &st) != 0) {
      close(fd);
      return nullptr;
    }
    bytes = static_cast<size_t>(st.st_size);
  }
  void* mem = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;

  Ring* r = new Ring();
  r->hdr = static_cast<RingHeader*>(mem);
  r->map_bytes = bytes;
  r->owner = create != 0;
  snprintf(r->name, sizeof(r->name), "%s", name);
  if (create) {
    r->hdr->magic = kMagic;
    r->hdr->nslots = nslots;
    r->hdr->slot_bytes = slot_bytes;
    r->hdr->next_ticket.store(0);
    r->hdr->read_ticket.store(0);
    for (uint32_t i = 0; i < nslots; ++i) {
      r->hdr->slots[i].state.store(kEmpty);
      r->hdr->slots[i].ticket.store(0);
      r->hdr->slots[i].size = 0;
      r->hdr->slots[i].tag = 0;
    }
  } else if (r->hdr->magic != kMagic) {
    munmap(mem, bytes);
    delete r;
    return nullptr;
  }
  r->payload = reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader) +
               r->hdr->nslots * sizeof(SlotHeader);
  return r;
}

uint64_t shm_ring_slot_bytes(void* ring) {
  return static_cast<Ring*>(ring)->hdr->slot_bytes;
}

uint32_t shm_ring_nslots(void* ring) {
  return static_cast<Ring*>(ring)->hdr->nslots;
}

// Claim an EMPTY slot for writing; returns slot index or -1 on timeout.
int shm_ring_acquire_write(void* ring, double timeout_s) {
  Ring* r = static_cast<Ring*>(ring);
  const double deadline = now_s() + timeout_s;
  long backoff = 1;
  for (;;) {
    const uint32_t n = r->hdr->nslots;
    for (uint32_t i = 0; i < n; ++i) {
      uint32_t expect = kEmpty;
      if (r->hdr->slots[i].state.compare_exchange_strong(expect, kWriting)) {
        return static_cast<int>(i);
      }
    }
    if (timeout_s >= 0 && now_s() > deadline) return -1;
    sleep_us(backoff);
    if (backoff < 200) backoff *= 2;
  }
}

// Payload pointer for a claimed slot.
void* shm_ring_slot_ptr(void* ring, int slot) {
  Ring* r = static_cast<Ring*>(ring);
  return r->payload + static_cast<size_t>(slot) * r->hdr->slot_bytes;
}

// Publish a written slot (assigns the next commit ticket).
int shm_ring_commit_write(void* ring, int slot, uint64_t size, int64_t tag) {
  Ring* r = static_cast<Ring*>(ring);
  if (size > r->hdr->slot_bytes) return -1;
  SlotHeader& s = r->hdr->slots[slot];
  if (s.state.load() != kWriting) return -2;
  s.size = size;
  s.tag = tag;
  s.ticket.store(r->hdr->next_ticket.fetch_add(1));
  s.state.store(kReady);
  return 0;
}

// Abort a claimed write (slot returns to the pool).
void shm_ring_abort_write(void* ring, int slot) {
  static_cast<Ring*>(ring)->hdr->slots[slot].state.store(kEmpty);
}

// Take the next READY slot in commit order. Returns slot index or -1 on
// timeout; fills size/tag.
int shm_ring_acquire_read(void* ring, double timeout_s, uint64_t* size,
                          int64_t* tag) {
  Ring* r = static_cast<Ring*>(ring);
  const double deadline = now_s() + timeout_s;
  long backoff = 1;
  const uint64_t want = r->hdr->read_ticket.load();
  for (;;) {
    const uint32_t n = r->hdr->nslots;
    for (uint32_t i = 0; i < n; ++i) {
      SlotHeader& s = r->hdr->slots[i];
      if (s.state.load() == kReady && s.ticket.load() == want) {
        uint32_t expect = kReady;
        if (s.state.compare_exchange_strong(expect, kReading)) {
          r->hdr->read_ticket.fetch_add(1);
          *size = s.size;
          *tag = s.tag;
          return static_cast<int>(i);
        }
      }
    }
    if (timeout_s >= 0 && now_s() > deadline) return -1;
    sleep_us(backoff);
    if (backoff < 200) backoff *= 2;
  }
}

// Return a read slot to the pool.
void shm_ring_release_read(void* ring, int slot) {
  static_cast<Ring*>(ring)->hdr->slots[slot].state.store(kEmpty);
}

void shm_ring_close(void* ring) {
  Ring* r = static_cast<Ring*>(ring);
  const bool owner = r->owner;
  char name[256];
  snprintf(name, sizeof(name), "%s", r->name);
  munmap(r->hdr, r->map_bytes);
  if (owner) shm_unlink(name);
  delete r;
}

}  // extern "C"
